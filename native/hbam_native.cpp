// hbam_native: host-side native kernels for hadoop-bam-tpu.
//
// The reference's native layer is zlib behind java.util.zip JNI (SURVEY.md
// section 2.8).  Ours is explicit: a small C++ library doing the two serial,
// branchy jobs that belong on the host —
//   1. batched multithreaded BGZF DEFLATE inflate (feeding device batches),
//   2. BAM record-boundary walking (the block_size chain),
// leaving vectorizable decode to the TPU.  Exposed via plain C ABI for ctypes.
//
// Build: g++ -O3 -march=native -shared -fPIC -pthread hbam_native.cpp -lz
#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <mutex>
#include <thread>
#include <vector>

#include <zlib.h>

// libdeflate inflates raw DEFLATE ~2x faster than zlib; the build probes for
// it (utils/native.py) and falls back to plain zlib when absent.
#if defined(HBAM_USE_LIBDEFLATE)
#include <libdeflate.h>
#endif

extern "C" {

// Inflate n_blocks independent raw-DEFLATE streams concurrently.
// src: the whole compressed span; cdata_off/cdata_len: per-block payload
// location; dst: output buffer; dst_off: per-block output position;
// expected_isize: per-block expected inflated size (from BGZF footers).
// Returns 0 on success, or (1000 + first failing block index).
int hbam_inflate_batch(const uint8_t* src,
                       const int64_t* cdata_off, const int32_t* cdata_len,
                       int32_t n_blocks,
                       uint8_t* dst, const int64_t* dst_off,
                       const int32_t* expected_isize,
                       int32_t n_threads) {
  if (n_threads < 1) n_threads = 1;
  std::atomic<int32_t> next(0);
  std::atomic<int32_t> fail(-1);
#if defined(HBAM_USE_LIBDEFLATE)
  auto worker = [&]() {
    libdeflate_decompressor* d = libdeflate_alloc_decompressor();
    if (!d) { fail.store(0); return; }
    for (;;) {
      int32_t i = next.fetch_add(1);
      if (i >= n_blocks || fail.load(std::memory_order_relaxed) >= 0) break;
      size_t out_n = 0;
      libdeflate_result rc = libdeflate_deflate_decompress(
          d, src + cdata_off[i], static_cast<size_t>(cdata_len[i]),
          dst + dst_off[i], static_cast<size_t>(expected_isize[i]), &out_n);
      if (rc != LIBDEFLATE_SUCCESS ||
          static_cast<int32_t>(out_n) != expected_isize[i]) {
        int32_t expect = -1;
        fail.compare_exchange_strong(expect, i);
        break;
      }
    }
    libdeflate_free_decompressor(d);
  };
#else
  auto worker = [&]() {
    z_stream zs;
    std::memset(&zs, 0, sizeof(zs));
    bool live = false;
    for (;;) {
      int32_t i = next.fetch_add(1);
      if (i >= n_blocks || fail.load(std::memory_order_relaxed) >= 0) break;
      if (!live) {
        if (inflateInit2(&zs, -15) != Z_OK) { fail.store(i); break; }
        live = true;
      } else {
        inflateReset(&zs);
      }
      zs.next_in = const_cast<Bytef*>(src + cdata_off[i]);
      zs.avail_in = static_cast<uInt>(cdata_len[i]);
      zs.next_out = dst + dst_off[i];
      zs.avail_out = static_cast<uInt>(expected_isize[i]);
      int rc = inflate(&zs, Z_FINISH);
      if (rc != Z_STREAM_END ||
          static_cast<int32_t>(zs.total_out) != expected_isize[i]) {
        int32_t expect = -1;
        fail.compare_exchange_strong(expect, i);
        break;
      }
    }
    if (live) inflateEnd(&zs);
  };
#endif
  if (n_threads == 1) {
    worker();
  } else {
    std::vector<std::thread> pool;
    pool.reserve(n_threads);
    for (int t = 0; t < n_threads; ++t) pool.emplace_back(worker);
    for (auto& th : pool) th.join();
  }
  int32_t f = fail.load();
  return f >= 0 ? 1000 + f : 0;
}

// Walk BAM record boundaries: offsets of each record's block_size field.
// buf/n: inflated bytes; start: first record offset; out/cap: output array.
// Writes record-start offsets; returns count (may be < actual if cap hit),
// or -1 on a malformed block_size.  *tail_off receives the offset of the
// first incomplete record (== n when the walk consumed everything).
int64_t hbam_walk_bam_records(const uint8_t* buf, int64_t n, int64_t start,
                              int64_t* out, int64_t cap, int64_t* tail_off) {
  int64_t p = start, count = 0;
  while (p + 4 <= n) {
    int32_t bs;
    std::memcpy(&bs, buf + p, 4);  // BAM is little-endian; so are our hosts
    if (bs < 32) return -1;
    if (p + 4 + bs > n) break;
    if (count < cap) out[count] = p;
    ++count;
    p += 4 + static_cast<int64_t>(bs);
  }
  if (tail_off) *tail_off = p;
  return count;
}

// Walk BAM record boundaries AND pack selected per-record byte ranges into a
// dense row tile in the same pass (the columnar host->device transfer layout:
// only projected columns cross the link).  sel_off/sel_len give n_sel source
// ranges within each record (all must lie inside the fixed 36-byte prefix,
// which every valid record has since block_size >= 32); they are packed
// back-to-back into rows of row_stride bytes.  The walk stops at the first
// record starting at or past ``stop`` (records there are owned by the next
// span — pass n to disable).  Callers must size cap for the worst case
// ((stop - start) / 36 + 1 records); the Python wrapper rejects overflow.
// Returns the record count, -1 on malformed input.
int64_t hbam_walk_bam_packed(const uint8_t* buf, int64_t n, int64_t start,
                             int64_t stop,
                             const int32_t* sel_off, const int32_t* sel_len,
                             int32_t n_sel, int32_t row_stride,
                             uint8_t* out_rows, int64_t* out_off, int64_t cap,
                             int64_t* tail_off) {
  int64_t p = start, count = 0;
  while (p + 4 <= n && p < stop) {
    int32_t bs;
    std::memcpy(&bs, buf + p, 4);
    if (bs < 32) return -1;
    if (p + 4 + bs > n) break;
    if (count < cap) {
      out_off[count] = p;
      uint8_t* row = out_rows + count * row_stride;
      const uint8_t* rec = buf + p;
      for (int32_t s = 0; s < n_sel; ++s) {
        std::memcpy(row, rec + sel_off[s], static_cast<size_t>(sel_len[s]));
        row += sel_len[s];
      }
    }
    ++count;
    p += 4 + static_cast<int64_t>(bs);
  }
  if (tail_off) *tail_off = p;
  return count;
}

// Walk BAM records and pack fixed prefix + sequence + quality payloads into
// dense tiles in one pass — the host side of the tensor-batch feed (bases
// and quals as fixed-stride device tiles).  Sequence bytes stay 4-bit
// packed (2 bases/byte [SPEC]); reads longer than max_len are truncated
// (full l_seq remains available in the prefix).  Output rows beyond the
// copied payload are NOT cleared — callers pass zeroed buffers.  Walk stops
// at ``stop`` as in hbam_walk_bam_packed.  Returns record count, or -1 on a
// malformed record.
int64_t hbam_walk_bam_payload(const uint8_t* buf, int64_t n, int64_t start,
                              int64_t stop, int32_t max_len,
                              int32_t seq_stride, int32_t qual_stride,
                              uint8_t* out_prefix, uint8_t* out_seq,
                              uint8_t* out_qual, int64_t* out_off,
                              int64_t cap, int64_t* tail_off) {
  int64_t p = start, count = 0;
  while (p + 4 <= n && p < stop) {
    int32_t bs;
    std::memcpy(&bs, buf + p, 4);
    if (bs < 32) return -1;
    if (p + 4 + bs > n) break;
    if (count < cap) {
      const uint8_t* rec = buf + p;
      std::memcpy(out_prefix + count * 36, rec, 36);
      uint8_t l_read_name = rec[12];
      uint16_t n_cigar;
      std::memcpy(&n_cigar, rec + 16, 2);
      int32_t l_seq;
      std::memcpy(&l_seq, rec + 20, 4);
      int64_t seq_off = 36 + static_cast<int64_t>(l_read_name) +
                        4 * static_cast<int64_t>(n_cigar);
      int64_t nb = (static_cast<int64_t>(l_seq) + 1) / 2;
      if (l_seq < 0 || seq_off + nb + l_seq > 4 + static_cast<int64_t>(bs))
        return -1;
      int32_t use = l_seq < max_len ? l_seq : max_len;
      std::memcpy(out_seq + count * seq_stride, rec + seq_off, (use + 1) / 2);
      std::memcpy(out_qual + count * qual_stride, rec + seq_off + nb, use);
      out_off[count] = p;
    }
    ++count;
    p += 4 + static_cast<int64_t>(bs);
  }
  if (tail_off) *tail_off = p;
  return count;
}

// CRC32 of a batch of byte ranges (BGZF block payload validation), threaded.
// Returns 0; crcs[i] receives the zlib CRC32 of data[off[i] .. off[i]+len[i]).
int hbam_crc32_batch(const uint8_t* data, const int64_t* off,
                     const int32_t* len, int32_t n, uint32_t* crcs,
                     int32_t n_threads) {
  if (n_threads < 1) n_threads = 1;
  std::atomic<int32_t> next(0);
  auto worker = [&]() {
    for (;;) {
      int32_t i = next.fetch_add(1);
      if (i >= n) break;
#if defined(HBAM_USE_LIBDEFLATE)
      crcs[i] = libdeflate_crc32(0, data + off[i],
                                 static_cast<size_t>(len[i]));
#else
      crcs[i] = static_cast<uint32_t>(
          crc32(0L, data + off[i], static_cast<uInt>(len[i])));
#endif
    }
  };
  std::vector<std::thread> pool;
  for (int t = 0; t < n_threads; ++t) pool.emplace_back(worker);
  for (auto& th : pool) th.join();
  return 0;
}

// Batched BGZF block deflate (writer path): compress n independent payloads.
// levels: zlib level; dst must have 64 KiB capacity per block at dst_off[i];
// out_len[i] receives each compressed size (header+cdata+footer are NOT
// added here — this is the raw DEFLATE payload only).
int hbam_deflate_batch(const uint8_t* src, const int64_t* src_off,
                       const int32_t* src_len, int32_t n_blocks,
                       uint8_t* dst, const int64_t* dst_off,
                       const int32_t* dst_cap, int32_t* out_len,
                       int32_t level, int32_t n_threads) {
  if (n_threads < 1) n_threads = 1;
  std::atomic<int32_t> next(0);
  std::atomic<int32_t> fail(-1);
#if defined(HBAM_USE_LIBDEFLATE)
  // libdeflate compresses ~3x faster than zlib at comparable ratios.
  // out_len[i] = 0 signals "did not fit in dst_cap" (incompressible) —
  // callers fall back to a stored block, matching the zlib-path contract
  // where oversized output is also a caller-handled condition.
  auto worker = [&]() {
    libdeflate_compressor* c = libdeflate_alloc_compressor(level);
    if (!c) { fail.store(0); return; }
    for (;;) {
      int32_t i = next.fetch_add(1);
      if (i >= n_blocks || fail.load(std::memory_order_relaxed) >= 0) break;
      size_t n = libdeflate_deflate_compress(
          c, src + src_off[i], static_cast<size_t>(src_len[i]),
          dst + dst_off[i], static_cast<size_t>(dst_cap[i]));
      out_len[i] = static_cast<int32_t>(n);
    }
    libdeflate_free_compressor(c);
  };
#else
  auto worker = [&]() {
    for (;;) {
      int32_t i = next.fetch_add(1);
      if (i >= n_blocks || fail.load(std::memory_order_relaxed) >= 0) break;
      z_stream zs;
      std::memset(&zs, 0, sizeof(zs));
      if (deflateInit2(&zs, level, Z_DEFLATED, -15, 8,
                       Z_DEFAULT_STRATEGY) != Z_OK) {
        fail.store(i);
        break;
      }
      zs.next_in = const_cast<Bytef*>(src + src_off[i]);
      zs.avail_in = static_cast<uInt>(src_len[i]);
      zs.next_out = dst + dst_off[i];
      zs.avail_out = static_cast<uInt>(dst_cap[i]);
      int rc = deflate(&zs, Z_FINISH);
      if (rc != Z_STREAM_END) {
        int32_t expect = -1;
        fail.compare_exchange_strong(expect, i);
      } else {
        out_len[i] = static_cast<int32_t>(zs.total_out);
      }
      deflateEnd(&zs);
    }
  };
#endif
  std::vector<std::thread> pool;
  for (int t = 0; t < n_threads; ++t) pool.emplace_back(worker);
  for (auto& th : pool) th.join();
  int32_t f = fail.load();
  return f >= 0 ? 1000 + f : 0;
}

// ---------------------------------------------------------------------------
// rANS 4x8 decode (CRAM 3.0 entropy codec [SPEC CRAMv3 section 13]).
// Frequency tables are parsed Python-side (once per stream); these run the
// per-symbol loops, which dominate CRAM decode time in pure Python.
// Semantics mirror formats/cram_codecs.py exactly, including byte-
// consumption order during renormalization.
// ---------------------------------------------------------------------------

static const uint32_t kRansLow = 1u << 23;
static const int kTfShift = 12;
static const uint32_t kTotMask = (1u << kTfShift) - 1;

// Order-0: 4 interleaved states over the whole output.
// buf[ptr..ptr+16) holds the 4 little-endian initial states.
int hbam_rans0_decode(const uint8_t* buf, int64_t buf_len, int64_t ptr,
                      const uint32_t* freqs, const uint32_t* cum,
                      const uint8_t* slot2sym,
                      uint8_t* out, int64_t out_size) {
  if (ptr + 16 > buf_len) return -1;
  uint64_t states[4];
  for (int j = 0; j < 4; ++j) {
    uint32_t s;
    std::memcpy(&s, buf + ptr + 4 * j, 4);
    states[j] = s;
  }
  ptr += 16;
  int64_t i = 0;
  for (; i + 4 <= out_size; i += 4) {
    for (int j = 0; j < 4; ++j) {
      uint64_t x = states[j];
      uint32_t m = static_cast<uint32_t>(x) & kTotMask;
      uint8_t s = slot2sym[m];
      out[i + j] = s;
      x = static_cast<uint64_t>(freqs[s]) * (x >> kTfShift) + m - cum[s];
      while (x < kRansLow) {
        if (ptr >= buf_len) return -1;
        x = (x << 8) | buf[ptr++];
      }
      states[j] = x;
    }
  }
  for (int j = 0; i + j < out_size; ++j) {
    uint64_t x = states[j];
    uint32_t m = static_cast<uint32_t>(x) & kTotMask;
    uint8_t s = slot2sym[m];
    out[i + j] = s;
    x = static_cast<uint64_t>(freqs[s]) * (x >> kTfShift) + m - cum[s];
    while (x < kRansLow) {
      if (ptr >= buf_len) return -1;
      x = (x << 8) | buf[ptr++];
    }
    states[j] = x;
  }
  // a well-formed stream decodes every state back to the encoder's
  // initial value; anything else is corruption (or a lying out_size)
  for (int j = 0; j < 4; ++j)
    if (states[j] != kRansLow) return -2;
  return 0;
}

// Order-1: per-context tables (freqs/cum [256*256], slot2sym [256*4096]);
// 4 states own the output quarters, stepped together in j order (the byte
// consumption order of the Python reference loop).
int hbam_rans1_decode(const uint8_t* buf, int64_t buf_len, int64_t ptr,
                      const uint32_t* freqs, const uint32_t* cum,
                      const uint8_t* slot2sym,
                      uint8_t* out, int64_t out_size) {
  if (ptr + 16 > buf_len) return -1;
  uint64_t states[4];
  for (int j = 0; j < 4; ++j) {
    uint32_t s;
    std::memcpy(&s, buf + ptr + 4 * j, 4);
    states[j] = s;
  }
  ptr += 16;
  const int64_t q = out_size >> 2;
  int64_t idx[4] = {0, q, 2 * q, 3 * q};
  const int64_t ends[4] = {q, 2 * q, 3 * q, out_size};
  int ctxs[4] = {0, 0, 0, 0};
  bool done_all = false;
  while (!done_all) {
    done_all = true;
    for (int j = 0; j < 4; ++j) {
      if (idx[j] >= ends[j]) continue;
      uint64_t x = states[j];
      uint32_t m = static_cast<uint32_t>(x) & kTotMask;
      int ctx = ctxs[j];
      uint8_t s = slot2sym[static_cast<int64_t>(ctx) * 4096 + m];
      out[idx[j]] = s;
      const int64_t t = static_cast<int64_t>(ctx) * 256 + s;
      x = static_cast<uint64_t>(freqs[t]) * (x >> kTfShift) + m - cum[t];
      while (x < kRansLow) {
        if (ptr >= buf_len) return -1;
        x = (x << 8) | buf[ptr++];
      }
      states[j] = x;
      ctxs[j] = s;
      if (++idx[j] < ends[j]) done_all = false;
    }
  }
  for (int j = 0; j < 4; ++j)
    if (states[j] != kRansLow) return -2;
  return 0;
}

// Decode n ITF8 varints (CRAM spec 2.3: leading-ones byte count; the
// 5-byte form keeps only the low 4 bits of its final byte) from buf into
// out.  Returns bytes consumed, or -1 if the stream ends mid-value.
// One C pass replaces the per-value Python loop in CRAM series decode.
long long hbam_itf8_decode_batch(const unsigned char* buf,
                                 long long buf_len, long long n,
                                 int32_t* out) {
  long long p = 0;
  for (long long i = 0; i < n; ++i) {
    if (p >= buf_len) return -1;
    unsigned b0 = buf[p];
    uint32_t v;
    int extra;
    if (b0 < 0x80)      { v = b0;        extra = 0; }
    else if (b0 < 0xC0) { v = b0 & 0x3F; extra = 1; }
    else if (b0 < 0xE0) { v = b0 & 0x1F; extra = 2; }
    else if (b0 < 0xF0) { v = b0 & 0x0F; extra = 3; }
    else                { v = b0 & 0x0F; extra = 4; }
    if (p + 1 + extra > buf_len) return -1;
    if (extra == 4) {
      v = (v << 28) | ((uint32_t)buf[p + 1] << 20)
        | ((uint32_t)buf[p + 2] << 12) | ((uint32_t)buf[p + 3] << 4)
        | (buf[p + 4] & 0x0F);
    } else {
      for (int j = 1; j <= extra; ++j) v = (v << 8) | buf[p + j];
    }
    out[i] = (int32_t)v;
    p += 1 + extra;
  }
  return p;
}

}  // extern "C"

// ---------------------------------------------------------------------------
// DEFLATE tokenizer: Huffman-decode a raw DEFLATE stream into LZ77 tokens
// WITHOUT resolving back-references — the host half of the two-stage device
// inflate experiment (ops/inflate_device.py).  The bit-serial, branchy
// Huffman stage is unvectorizable and stays on the host (threaded across
// blocks); the embarrassingly parallel copy resolution runs on the device.
//
// Token u32 layout:
//   bit 31 set   -> copy: bits 16-24 = length (3..258), bits 0-15 = dist-1
//   bit 31 clear -> literal: bits 0-7 = byte value
// [SPEC] RFC 1951 (DEFLATE): block types, code-length code order, canonical
// Huffman construction, length/distance base+extra-bit tables.

namespace {

// 64-bit bit reservoir, LSB-first; refilled with zero padding past EOF
// (consumption past the real end is caught by the ``consumed`` counter).
struct HbamBits64 {
  const uint8_t* p;
  int64_t n;
  int64_t pos;       // next unread byte
  uint64_t acc;
  int cnt;           // bits in acc (may include zero padding)
  int64_t consumed;  // bits taken so far (pad bits included)
};

inline void hbam_refill(HbamBits64* b) {
  while (b->cnt <= 56) {
    const uint64_t byte = b->pos < b->n ? b->p[b->pos++] : 0;
    b->acc |= byte << b->cnt;
    b->cnt += 8;
  }
}

inline uint32_t hbam_take(HbamBits64* b, int k) {
  const uint32_t v = static_cast<uint32_t>(b->acc) & ((1u << k) - 1u);
  b->acc >>= k;
  b->cnt -= k;
  b->consumed += k;
  return v;
}

inline uint32_t hbam_getbits(HbamBits64* b, int k) {
  hbam_refill(b);
  return hbam_take(b, k);
}

struct HbamHuff {
  uint16_t count[16];   // codes per bit length
  uint16_t sym[288];    // symbols ordered by (length, symbol)
  bool empty;
};

int hbam_build_huff(const uint8_t* lens, int n, HbamHuff* h) {
  for (int i = 0; i < 16; ++i) h->count[i] = 0;
  for (int i = 0; i < n; ++i) h->count[lens[i]]++;
  h->empty = (h->count[0] == n);
  h->count[0] = 0;
  if (h->empty) return 0;   // legal: e.g. HDIST table with no codes
  int left = 1;             // over-subscription check
  for (int l = 1; l < 16; ++l) {
    left <<= 1;
    left -= h->count[l];
    if (left < 0) return -1;
  }
  uint16_t offs[16];
  offs[1] = 0;
  for (int l = 1; l < 15; ++l)
    offs[l + 1] = static_cast<uint16_t>(offs[l] + h->count[l]);
  for (int i = 0; i < n; ++i)
    if (lens[i]) h->sym[offs[lens[i]]++] = static_cast<uint16_t>(i);
  return 0;
}

// canonical code decode, one bit at a time (fallback for codes > 10 bits
// and for the tiny code-length table); caller must hbam_refill first
inline int hbam_decode_slow(HbamBits64* b, const HbamHuff* h) {
  int code = 0, first = 0, index = 0;
  for (int l = 1; l < 16; ++l) {
    code |= static_cast<int>(hbam_take(b, 1));
    const int cnt = h->count[l];
    if (code - first < cnt) return h->sym[index + (code - first)];
    index += cnt;
    first = (first + cnt) << 1;
    code <<= 1;
  }
  return -1;
}

// one-level lookup table over the low ROOT_BITS reservoir bits (DEFLATE
// packs codes MSB-first, so table indices are bit-reversed codes); codes
// longer than ROOT_BITS leave zero entries and fall back to slow decode.
constexpr int kRootBits = 10;

struct HbamFastTable {
  uint16_t root[1 << kRootBits];  // bit15 valid, bits 9-12 len, 0-8 sym
  HbamHuff slow;
};

int hbam_build_fast(const uint8_t* lens, int n, HbamFastTable* t) {
  if (hbam_build_huff(lens, n, &t->slow)) return -1;
  std::memset(t->root, 0, sizeof(t->root));
  if (t->slow.empty) return 0;
  uint32_t next_code[16];
  uint32_t code = 0;
  for (int l = 1; l < 16; ++l) {
    code = (code + t->slow.count[l - 1]) << 1;
    next_code[l] = code;
  }
  for (int i = 0; i < n; ++i) {
    const int l = lens[i];
    if (!l) continue;
    const uint32_t c = next_code[l]++;
    if (l > kRootBits) continue;
    uint32_t r = 0;                 // reverse the l code bits
    for (int bb = 0; bb < l; ++bb) r |= ((c >> bb) & 1u) << (l - 1 - bb);
    const uint16_t e = static_cast<uint16_t>(
        0x8000u | (static_cast<uint32_t>(l) << 9) | i);
    for (uint32_t j = r; j < (1u << kRootBits); j += (1u << l))
      t->root[j] = e;
  }
  return 0;
}

inline int hbam_fast_sym(HbamBits64* b, const HbamFastTable* t) {
  const uint16_t e = t->root[b->acc & ((1u << kRootBits) - 1u)];
  if (e & 0x8000) {
    const int l = (e >> 9) & 0xF;
    b->acc >>= l;
    b->cnt -= l;
    b->consumed += l;
    return e & 0x1FF;
  }
  return hbam_decode_slow(b, &t->slow);
}

const uint16_t kLenBase[29] = {
    3,  4,  5,  6,  7,  8,  9,  10, 11,  13,  15,  17,  19,  23, 27,
    31, 35, 43, 51, 59, 67, 83, 99, 115, 131, 163, 195, 227, 258};
const uint8_t kLenExtra[29] = {0, 0, 0, 0, 0, 0, 0, 0, 1, 1, 1, 1, 2, 2, 2,
                               2, 3, 3, 3, 3, 4, 4, 4, 4, 5, 5, 5, 5, 0};
const uint16_t kDistBase[30] = {
    1,    2,    3,    4,    5,    7,    9,    13,   17,   25,
    33,   49,   65,   97,   129,  193,  257,  385,  513,  769,
    1025, 1537, 2049, 3073, 4097, 6145, 8193, 12289, 16385, 24577};
const uint8_t kDistExtra[30] = {0, 0, 0,  0,  1,  1,  2,  2,  3,  3,
                                4, 4, 5,  5,  6,  6,  7,  7,  8,  8,
                                9, 9, 10, 10, 11, 11, 12, 12, 13, 13};
const uint8_t kClPerm[19] = {16, 17, 18, 0, 8,  7, 9,  6, 10, 5,
                             11, 4,  12, 3, 13, 2, 14, 1, 15};

}  // namespace

extern "C" {

// Tokenize one raw DEFLATE stream.  tokens/cap: output token array and its
// capacity; n_tokens/out_len: tokens written and total inflated length.
// Returns 0, or <0: -1 truncated input, -2 malformed stream, -3 token
// capacity exceeded, -4 distance reaches before stream start.
int hbam_deflate_tokenize(const uint8_t* comp, int64_t comp_len,
                          uint32_t* tokens, int64_t cap,
                          int64_t* n_tokens, int64_t* out_len) {
  HbamBits64 b{comp, comp_len, 0, 0, 0, 0};
  const int64_t limit = comp_len * 8;
  int64_t nt = 0, opos = 0;
  uint32_t bfinal = 0;
  do {
    hbam_refill(&b);
    bfinal = hbam_take(&b, 1);
    const uint32_t btype = hbam_take(&b, 2);
    if (btype == 0) {             // stored: byte-align, LEN/NLEN, raw copy
      hbam_take(&b, b.cnt & 7);
      const uint32_t len = hbam_getbits(&b, 16);
      const uint32_t nlen = hbam_getbits(&b, 16);
      if (b.consumed > limit) return -1;
      if ((len ^ 0xFFFFu) != nlen) return -2;
      if (nt + len > cap) return -3;
      uint32_t remaining = len;
      while (remaining && b.cnt >= 8) {   // drain reservoir bytes first
        tokens[nt++] = hbam_take(&b, 8);
        --remaining;
      }
      if (b.consumed > limit) return -1;
      if (b.pos + remaining > b.n) return -1;
      for (uint32_t i = 0; i < remaining; ++i)
        tokens[nt++] = comp[b.pos + i];
      b.pos += remaining;
      b.consumed += 8 * static_cast<int64_t>(remaining);
      opos += len;
      continue;
    }
    static thread_local HbamFastTable lit_t, dist_t;
    if (btype == 1) {             // fixed tables [SPEC RFC1951 3.2.6]
      uint8_t lens[288];
      for (int i = 0; i < 144; ++i) lens[i] = 8;
      for (int i = 144; i < 256; ++i) lens[i] = 9;
      for (int i = 256; i < 280; ++i) lens[i] = 7;
      for (int i = 280; i < 288; ++i) lens[i] = 8;
      hbam_build_fast(lens, 288, &lit_t);
      uint8_t dlens[30];
      for (int i = 0; i < 30; ++i) dlens[i] = 5;
      hbam_build_fast(dlens, 30, &dist_t);
    } else if (btype == 2) {      // dynamic tables [SPEC RFC1951 3.2.7]
      uint32_t hlit = hbam_getbits(&b, 5) + 257;
      uint32_t hdist = hbam_getbits(&b, 5) + 1;
      uint32_t hclen = hbam_getbits(&b, 4) + 4;
      if (hlit > 286 || hdist > 30) return -2;
      uint8_t cl[19] = {0};
      for (uint32_t i = 0; i < hclen; ++i)
        cl[kClPerm[i]] = static_cast<uint8_t>(hbam_getbits(&b, 3));
      if (b.consumed > limit) return -1;
      HbamHuff clh;
      if (hbam_build_huff(cl, 19, &clh) || clh.empty) return -2;
      uint8_t lens[288 + 30] = {0};
      uint32_t idx = 0;
      while (idx < hlit + hdist) {
        hbam_refill(&b);
        if (b.consumed > limit) return -1;
        const int s = hbam_decode_slow(&b, &clh);
        if (s < 0) return -2;
        if (s < 16) {
          lens[idx++] = static_cast<uint8_t>(s);
        } else {
          uint32_t rep;
          uint8_t val = 0;
          if (s == 16) {
            if (idx == 0) return -2;
            val = lens[idx - 1];
            rep = hbam_take(&b, 2) + 3;
          } else if (s == 17) {
            rep = hbam_take(&b, 3) + 3;
          } else {
            rep = hbam_take(&b, 7) + 11;
          }
          if (idx + rep > hlit + hdist) return -2;
          while (rep--) lens[idx++] = val;
        }
      }
      if (lens[256] == 0) return -2;   // end-of-block code must exist
      if (hbam_build_fast(lens, static_cast<int>(hlit), &lit_t) ||
          lit_t.slow.empty)
        return -2;
      if (hbam_build_fast(lens + hlit, static_cast<int>(hdist), &dist_t))
        return -2;
    } else {
      return -2;                  // btype 3 is reserved
    }
    for (;;) {                    // symbol loop: one refill covers the
      hbam_refill(&b);            // worst case 15+5+15+13 = 48 bits
      if (b.consumed > limit) return -1;
      int s = hbam_fast_sym(&b, &lit_t);
      if (s < 0) return -2;
      if (s < 256) {
        if (nt >= cap) return -3;
        tokens[nt++] = static_cast<uint32_t>(s);
        ++opos;
      } else if (s == 256) {
        break;
      } else {
        s -= 257;
        if (s >= 29 || dist_t.slow.empty) return -2;
        const uint32_t length = kLenBase[s] + hbam_take(&b, kLenExtra[s]);
        const int ds = hbam_fast_sym(&b, &dist_t);
        if (ds < 0 || ds >= 30) return -2;
        const uint32_t d = kDistBase[ds] + hbam_take(&b, kDistExtra[ds]);
        if (static_cast<int64_t>(d) > opos) return -4;
        if (nt >= cap) return -3;
        tokens[nt++] = 0x80000000u | (length << 16) | (d - 1);
        opos += length;
      }
    }
  } while (!bfinal);
  if (b.consumed > limit) return -1;
  *n_tokens = nt;
  *out_len = opos;
  return 0;
}

}  // extern "C"

// ---------------------------------------------------------------------------
// Fused single-pass span decode: inflate + record walk + projection pack +
// CRC fold in ONE streamed pass over the span, chunk-granular.
//
// The two-pass hot path (hbam_inflate_batch -> DRAM, then a separate
// hbam_walk_bam_* full re-read, plus an optional third hbam_crc32_batch
// sweep) touches every inflated byte two-to-three times from DRAM.  Here a
// worker inflates a run of ``chunk_blocks`` BGZF blocks and the record walk
// consumes those bytes while they are still cache-resident; the CRC32
// check folds into the same visit.  Record boundaries chain serially
// (offset[i+1] = offset[i] + 4 + block_size[i]), so the walk advances
// behind the CONTIGUOUS inflated frontier: whichever worker extends the
// frontier drains the walk (one walker at a time; inflation of later
// chunks keeps running concurrently).  Completed walk increments are
// published as [row_lo, row_hi) ranges that hbam_fused_next hands to the
// caller as they land — the chunk-streamed handoff that lets the Python
// side start packing staging tiles before the span's tail is inflated
// (rapidgzip's chunk-pipelined consumption shape, applied host-side).
//
// Pack modes share one walk:
//   0: offsets only (callers that pack variable-length series themselves)
//   1: selected fixed-prefix ranges -> dense rows (hbam_walk_bam_packed)
//   2: prefix + 4-bit seq + qual tiles   (hbam_walk_bam_payload)
// ---------------------------------------------------------------------------

namespace {

struct HbamFusedChunk { int64_t row_lo, row_hi; };

struct HbamFusedJob {
  // borrowed inputs — the Python wrapper keeps every array alive
  const uint8_t* src;
  const int64_t* cdata_off;
  const int32_t* cdata_len;
  const int32_t* isize;
  const uint32_t* expect_crc;    // null: no CRC fold
  int32_t n_blocks;
  uint8_t* dst;                  // inflated span buffer [total]
  const int64_t* ubase;          // per-block inflated start offsets
  int64_t total;
  int64_t start_u, stop;         // walk start / ownership limit
  // pack configuration
  int32_t mode;
  const int32_t* sel_off;
  const int32_t* sel_len;
  int32_t n_sel, row_stride;
  uint8_t* out_rows;             // mode 1 rows / mode 2 prefix tile
  uint8_t* out_seq;
  uint8_t* out_qual;
  int32_t max_len, seq_stride, qual_stride;
  int64_t* out_off;
  int64_t cap;
  // chunk bookkeeping (mu guards everything below except the atomics)
  int32_t chunk_blocks, n_chunks;
  std::vector<uint8_t> chunk_done;
  int32_t frontier = 0;          // count of contiguously inflated chunks
  bool walk_active = false;
  int64_t walk_pos = 0;
  int64_t walk_limit_done = 0;   // bytes the walk has already swept
  int64_t rows = 0;
  bool finished = false;
  int32_t err_kind = 0;          // 1 inflate, 2 isize, 3 crc, 4 chain, 5 cap
  int64_t err_index = -1;        // failing block (1-3) or offset (4-5)
  std::atomic<bool> cancel{false};
  std::atomic<int32_t> next{0};
  std::mutex mu;
  std::condition_variable cv;
  std::deque<HbamFusedChunk> ready;
  std::vector<std::thread> pool;
};

// Walk newly contiguous bytes and pack rows.  Called with ``lk`` held;
// the walk body runs unlocked (walk_active excludes other walkers while
// inflation of later chunks proceeds in parallel).
void hbam_fused_drain(HbamFusedJob* j, std::unique_lock<std::mutex>& lk) {
  if (j->walk_active || j->err_kind) return;
  for (;;) {
    const bool final_pass = j->frontier >= j->n_chunks;
    const int64_t limit = final_pass
        ? j->total
        : j->ubase[static_cast<int64_t>(j->frontier) * j->chunk_blocks];
    if (j->finished) return;
    if (!final_pass && limit <= j->walk_limit_done) return;
    j->walk_active = true;
    int64_t p = j->walk_pos;
    int64_t r = j->rows;
    lk.unlock();
    int ekind = 0;
    while (p + 4 <= limit && p < j->stop) {
      int32_t bs;
      std::memcpy(&bs, j->dst + p, 4);
      if (bs < 32) { ekind = 4; break; }
      if (p + 4 + bs > limit) break;   // record cut at the frontier: resume
      if (r >= j->cap) { ekind = 5; break; }
      const uint8_t* rec = j->dst + p;
      if (j->mode == 1) {
        uint8_t* row = j->out_rows + r * j->row_stride;
        for (int32_t s = 0; s < j->n_sel; ++s) {
          std::memcpy(row, rec + j->sel_off[s],
                      static_cast<size_t>(j->sel_len[s]));
          row += j->sel_len[s];
        }
      } else if (j->mode == 2) {
        std::memcpy(j->out_rows + r * 36, rec, 36);
        uint8_t l_read_name = rec[12];
        uint16_t n_cigar;
        std::memcpy(&n_cigar, rec + 16, 2);
        int32_t l_seq;
        std::memcpy(&l_seq, rec + 20, 4);
        int64_t seq_off = 36 + static_cast<int64_t>(l_read_name) +
                          4 * static_cast<int64_t>(n_cigar);
        int64_t nb = (static_cast<int64_t>(l_seq) + 1) / 2;
        if (l_seq < 0 || seq_off + nb + l_seq > 4 + static_cast<int64_t>(bs)) {
          ekind = 4;
          break;
        }
        int32_t use = l_seq < j->max_len ? l_seq : j->max_len;
        std::memcpy(j->out_seq + r * j->seq_stride, rec + seq_off,
                    (use + 1) / 2);
        std::memcpy(j->out_qual + r * j->qual_stride, rec + seq_off + nb,
                    use);
      }
      j->out_off[r] = p;
      ++r;
      p += 4 + static_cast<int64_t>(bs);
    }
    lk.lock();
    const int64_t lo = j->rows;
    j->rows = r;
    j->walk_pos = p;
    j->walk_limit_done = limit;
    j->walk_active = false;
    if (ekind) {
      if (!j->err_kind) { j->err_kind = ekind; j->err_index = p; }
      j->cancel.store(true);
      j->cv.notify_all();
      return;
    }
    if (r > lo) {
      j->ready.push_back({lo, r});
      j->cv.notify_all();
    }
    if (final_pass) {
      j->finished = true;
      j->cv.notify_all();
      return;
    }
    // loop: the frontier may have advanced while this pass walked
  }
}

void hbam_fused_worker(HbamFusedJob* j) {
#if defined(HBAM_USE_LIBDEFLATE)
  libdeflate_decompressor* d = libdeflate_alloc_decompressor();
  if (!d) {
    std::lock_guard<std::mutex> lk(j->mu);
    if (!j->err_kind) { j->err_kind = 1; j->err_index = 0; }
    j->cancel.store(true);
    j->cv.notify_all();
    return;
  }
#else
  z_stream zs;
  std::memset(&zs, 0, sizeof(zs));
  bool live = false;
#endif
  for (;;) {
    const int32_t c = j->next.fetch_add(1);
    if (c >= j->n_chunks || j->cancel.load(std::memory_order_relaxed)) break;
    const int32_t b0 = c * j->chunk_blocks;
    const int32_t b1 = b0 + j->chunk_blocks < j->n_blocks
                           ? b0 + j->chunk_blocks : j->n_blocks;
    int ekind = 0;
    int64_t eidx = -1;
    for (int32_t b = b0; b < b1 && !ekind; ++b) {
#if defined(HBAM_USE_LIBDEFLATE)
      size_t out_n = 0;
      libdeflate_result rc = libdeflate_deflate_decompress(
          d, j->src + j->cdata_off[b], static_cast<size_t>(j->cdata_len[b]),
          j->dst + j->ubase[b], static_cast<size_t>(j->isize[b]), &out_n);
      if (rc != LIBDEFLATE_SUCCESS) { ekind = 1; eidx = b; }
      else if (static_cast<int32_t>(out_n) != j->isize[b]) {
        ekind = 2; eidx = b;
      }
#else
      if (!live) {
        if (inflateInit2(&zs, -15) != Z_OK) { ekind = 1; eidx = b; break; }
        live = true;
      } else {
        inflateReset(&zs);
      }
      zs.next_in = const_cast<Bytef*>(j->src + j->cdata_off[b]);
      zs.avail_in = static_cast<uInt>(j->cdata_len[b]);
      zs.next_out = j->dst + j->ubase[b];
      zs.avail_out = static_cast<uInt>(j->isize[b]);
      int rc = inflate(&zs, Z_FINISH);
      if (rc != Z_STREAM_END) { ekind = 1; eidx = b; }
      else if (static_cast<int32_t>(zs.total_out) != j->isize[b]) {
        ekind = 2; eidx = b;
      }
#endif
      if (!ekind && j->expect_crc) {
        // fold the footer check in while the block is cache-hot — this
        // is what makes check_crc nearly free on the fused path
#if defined(HBAM_USE_LIBDEFLATE)
        uint32_t got = libdeflate_crc32(0, j->dst + j->ubase[b],
                                        static_cast<size_t>(j->isize[b]));
#else
        uint32_t got = static_cast<uint32_t>(
            crc32(0L, j->dst + j->ubase[b],
                  static_cast<uInt>(j->isize[b])));
#endif
        if (got != j->expect_crc[b]) { ekind = 3; eidx = b; }
      }
    }
    std::unique_lock<std::mutex> lk(j->mu);
    if (ekind) {
      if (!j->err_kind) { j->err_kind = ekind; j->err_index = eidx; }
      j->cancel.store(true);
      j->cv.notify_all();
      break;
    }
    j->chunk_done[c] = 1;
    while (j->frontier < j->n_chunks && j->chunk_done[j->frontier])
      ++j->frontier;
    hbam_fused_drain(j, lk);
  }
#if defined(HBAM_USE_LIBDEFLATE)
  libdeflate_free_decompressor(d);
#else
  if (live) inflateEnd(&zs);
#endif
}

}  // namespace

extern "C" {

// Start a fused span decode; returns an opaque handle (null on bad args).
// All arrays are borrowed until hbam_fused_finish returns.  expect_crc may
// be null (no CRC fold); out_seq/out_qual are only read in mode 2 and
// sel_off/sel_len only in mode 1.
void* hbam_fused_start(const uint8_t* src, const int64_t* cdata_off,
                       const int32_t* cdata_len, const int32_t* isize,
                       const uint32_t* expect_crc, int32_t n_blocks,
                       uint8_t* dst, const int64_t* ubase, int64_t total,
                       int64_t start_u, int64_t stop, int32_t mode,
                       const int32_t* sel_off, const int32_t* sel_len,
                       int32_t n_sel, int32_t row_stride,
                       uint8_t* out_rows, uint8_t* out_seq,
                       uint8_t* out_qual, int32_t max_len,
                       int32_t seq_stride, int32_t qual_stride,
                       int64_t* out_off, int64_t cap,
                       int32_t chunk_blocks, int32_t n_threads) {
  if (n_blocks <= 0 || mode < 0 || mode > 2) return nullptr;
  if (chunk_blocks < 1) chunk_blocks = 1;
  if (n_threads < 1) n_threads = 1;
  HbamFusedJob* j = new HbamFusedJob();
  j->src = src;
  j->cdata_off = cdata_off;
  j->cdata_len = cdata_len;
  j->isize = isize;
  j->expect_crc = expect_crc;
  j->n_blocks = n_blocks;
  j->dst = dst;
  j->ubase = ubase;
  j->total = total;
  j->start_u = start_u;
  j->stop = stop;
  j->mode = mode;
  j->sel_off = sel_off;
  j->sel_len = sel_len;
  j->n_sel = n_sel;
  j->row_stride = row_stride;
  j->out_rows = out_rows;
  j->out_seq = out_seq;
  j->out_qual = out_qual;
  j->max_len = max_len;
  j->seq_stride = seq_stride;
  j->qual_stride = qual_stride;
  j->out_off = out_off;
  j->cap = cap;
  j->chunk_blocks = chunk_blocks;
  j->n_chunks = (n_blocks + chunk_blocks - 1) / chunk_blocks;
  j->chunk_done.assign(j->n_chunks, 0);
  j->walk_pos = start_u;
  if (n_threads > j->n_chunks) n_threads = j->n_chunks;
  j->pool.reserve(n_threads);
  for (int t = 0; t < n_threads; ++t)
    j->pool.emplace_back(hbam_fused_worker, j);
  return j;
}

// Block until the next walked row range is ready.  Returns 1 and fills
// [*row_lo, *row_hi); 0 when the decode completed (all chunks inflated,
// walk drained); -kind on error (kind per HbamFusedJob::err_kind).
int hbam_fused_next(void* h, int64_t* row_lo, int64_t* row_hi) {
  HbamFusedJob* j = static_cast<HbamFusedJob*>(h);
  std::unique_lock<std::mutex> lk(j->mu);
  j->cv.wait(lk, [&] {
    return j->err_kind || !j->ready.empty() || j->finished;
  });
  if (j->err_kind) return -j->err_kind;
  if (!j->ready.empty()) {
    HbamFusedChunk c = j->ready.front();
    j->ready.pop_front();
    *row_lo = c.row_lo;
    *row_hi = c.row_hi;
    return 1;
  }
  return 0;
}

// Join workers and free the job.  Returns 0 or -kind; *tail receives the
// first incomplete record's offset (== stop-trimmed walk end), *n_rows
// the packed row count, *err_index the failing block/offset on error.
// Safe to call while workers are still running (cancels outstanding
// chunks) — but then dst/out arrays are only partially written.
int hbam_fused_finish(void* h, int64_t* tail, int64_t* n_rows,
                      int64_t* err_index) {
  HbamFusedJob* j = static_cast<HbamFusedJob*>(h);
  {
    std::lock_guard<std::mutex> lk(j->mu);
    j->cancel.store(true);
    j->cv.notify_all();
  }
  for (auto& th : j->pool) th.join();
  int rc = j->err_kind ? -j->err_kind : 0;
  if (tail) *tail = j->walk_pos;
  if (n_rows) *n_rows = j->rows;
  if (err_index) *err_index = j->err_index;
  delete j;
  return rc;
}

// Resolve a block's LZ77 tokens into ``scratch`` (grown as needed) and
// return the CRC32 of the inflated bytes — the tokenize-time CRC fold for
// the device decode plane.  The resolved bytes are a thread-local
// throwaway: the device resolves its own copy, this exists only so
// check_crc can be verified against the BGZF footer WITHOUT a host
// inflate pass materializing in the pipeline (the resolve here is
// cache-resident and far cheaper than the Huffman stage just paid).
static uint32_t hbam_tokens_crc32(const uint32_t* toks, int64_t nt,
                                  int64_t out_len,
                                  std::vector<uint8_t>* scratch) {
  if (static_cast<int64_t>(scratch->size()) < out_len)
    scratch->resize(static_cast<size_t>(out_len));
  uint8_t* out = scratch->data();
  int64_t p = 0;
  for (int64_t t = 0; t < nt; ++t) {
    const uint32_t tok = toks[t];
    if (tok & 0x80000000u) {
      const int64_t length = (tok >> 16) & 0x1FF;
      const int64_t dist = (tok & 0xFFFFu) + 1;
      // overlapping copies (dist < length) must run byte-serial
      const uint8_t* s = out + p - dist;
      for (int64_t k = 0; k < length; ++k) out[p + k] = s[k];
      p += length;
    } else {
      out[p++] = static_cast<uint8_t>(tok & 0xFF);
    }
  }
  return static_cast<uint32_t>(
      crc32(0L, out, static_cast<uInt>(out_len)));
}

// Threaded batch tokenize over independent blocks (same pool shape as
// hbam_inflate_batch).  tokens is [n_blocks, tok_stride] row-major.
// out_crcs (nullable): per-block CRC32 of the inflated bytes, folded in
// at tokenize time from a thread-local resolve scratch.
// Returns 0, or (1000 + first failing block index + 1000000 * -rc) so the
// caller can recover both which block failed and why (rc per
// hbam_deflate_tokenize: -1 truncated, -2 malformed, -3 token capacity,
// -4 bad distance).
int hbam_deflate_tokenize_batch(const uint8_t* src, const int64_t* off,
                                const int32_t* len, int32_t n_blocks,
                                uint32_t* tokens, int64_t tok_stride,
                                int32_t* n_tokens, int32_t* out_lens,
                                uint32_t* out_crcs, int32_t n_threads) {
  if (n_threads < 1) n_threads = 1;
  std::atomic<int32_t> next(0);
  std::atomic<int32_t> fail(-1);
  auto worker = [&]() {
    std::vector<uint8_t> scratch;
    for (;;) {
      const int32_t i = next.fetch_add(1);
      if (i >= n_blocks || fail.load(std::memory_order_relaxed) >= 0) break;
      int64_t nt = 0, ol = 0;
      const int rc = hbam_deflate_tokenize(
          src + off[i], len[i],
          tokens + static_cast<int64_t>(i) * tok_stride, tok_stride, &nt,
          &ol);
      if (rc) {
        int32_t e = -1;
        fail.compare_exchange_strong(e, i + 1000000 * -rc);
        break;
      }
      n_tokens[i] = static_cast<int32_t>(nt);
      out_lens[i] = static_cast<int32_t>(ol);
      if (out_crcs)
        out_crcs[i] = hbam_tokens_crc32(
            tokens + static_cast<int64_t>(i) * tok_stride, nt, ol, &scratch);
    }
  };
  if (n_threads == 1) {
    worker();
  } else {
    std::vector<std::thread> pool;
    pool.reserve(n_threads);
    for (int t = 0; t < n_threads; ++t) pool.emplace_back(worker);
    for (auto& th : pool) th.join();
  }
  const int32_t f = fail.load();
  return f >= 0 ? 1000 + f : 0;
}

}  // extern "C"
