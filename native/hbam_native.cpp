// hbam_native: host-side native kernels for hadoop-bam-tpu.
//
// The reference's native layer is zlib behind java.util.zip JNI (SURVEY.md
// section 2.8).  Ours is explicit: a small C++ library doing the two serial,
// branchy jobs that belong on the host —
//   1. batched multithreaded BGZF DEFLATE inflate (feeding device batches),
//   2. BAM record-boundary walking (the block_size chain),
// leaving vectorizable decode to the TPU.  Exposed via plain C ABI for ctypes.
//
// Build: g++ -O3 -march=native -shared -fPIC -pthread hbam_native.cpp -lz
#include <atomic>
#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

#include <zlib.h>

// libdeflate inflates raw DEFLATE ~2x faster than zlib; the build probes for
// it (utils/native.py) and falls back to plain zlib when absent.
#if defined(HBAM_USE_LIBDEFLATE)
#include <libdeflate.h>
#endif

extern "C" {

// Inflate n_blocks independent raw-DEFLATE streams concurrently.
// src: the whole compressed span; cdata_off/cdata_len: per-block payload
// location; dst: output buffer; dst_off: per-block output position;
// expected_isize: per-block expected inflated size (from BGZF footers).
// Returns 0 on success, or (1000 + first failing block index).
int hbam_inflate_batch(const uint8_t* src,
                       const int64_t* cdata_off, const int32_t* cdata_len,
                       int32_t n_blocks,
                       uint8_t* dst, const int64_t* dst_off,
                       const int32_t* expected_isize,
                       int32_t n_threads) {
  if (n_threads < 1) n_threads = 1;
  std::atomic<int32_t> next(0);
  std::atomic<int32_t> fail(-1);
#if defined(HBAM_USE_LIBDEFLATE)
  auto worker = [&]() {
    libdeflate_decompressor* d = libdeflate_alloc_decompressor();
    if (!d) { fail.store(0); return; }
    for (;;) {
      int32_t i = next.fetch_add(1);
      if (i >= n_blocks || fail.load(std::memory_order_relaxed) >= 0) break;
      size_t out_n = 0;
      libdeflate_result rc = libdeflate_deflate_decompress(
          d, src + cdata_off[i], static_cast<size_t>(cdata_len[i]),
          dst + dst_off[i], static_cast<size_t>(expected_isize[i]), &out_n);
      if (rc != LIBDEFLATE_SUCCESS ||
          static_cast<int32_t>(out_n) != expected_isize[i]) {
        int32_t expect = -1;
        fail.compare_exchange_strong(expect, i);
        break;
      }
    }
    libdeflate_free_decompressor(d);
  };
#else
  auto worker = [&]() {
    z_stream zs;
    std::memset(&zs, 0, sizeof(zs));
    bool live = false;
    for (;;) {
      int32_t i = next.fetch_add(1);
      if (i >= n_blocks || fail.load(std::memory_order_relaxed) >= 0) break;
      if (!live) {
        if (inflateInit2(&zs, -15) != Z_OK) { fail.store(i); break; }
        live = true;
      } else {
        inflateReset(&zs);
      }
      zs.next_in = const_cast<Bytef*>(src + cdata_off[i]);
      zs.avail_in = static_cast<uInt>(cdata_len[i]);
      zs.next_out = dst + dst_off[i];
      zs.avail_out = static_cast<uInt>(expected_isize[i]);
      int rc = inflate(&zs, Z_FINISH);
      if (rc != Z_STREAM_END ||
          static_cast<int32_t>(zs.total_out) != expected_isize[i]) {
        int32_t expect = -1;
        fail.compare_exchange_strong(expect, i);
        break;
      }
    }
    if (live) inflateEnd(&zs);
  };
#endif
  if (n_threads == 1) {
    worker();
  } else {
    std::vector<std::thread> pool;
    pool.reserve(n_threads);
    for (int t = 0; t < n_threads; ++t) pool.emplace_back(worker);
    for (auto& th : pool) th.join();
  }
  int32_t f = fail.load();
  return f >= 0 ? 1000 + f : 0;
}

// Walk BAM record boundaries: offsets of each record's block_size field.
// buf/n: inflated bytes; start: first record offset; out/cap: output array.
// Writes record-start offsets; returns count (may be < actual if cap hit),
// or -1 on a malformed block_size.  *tail_off receives the offset of the
// first incomplete record (== n when the walk consumed everything).
int64_t hbam_walk_bam_records(const uint8_t* buf, int64_t n, int64_t start,
                              int64_t* out, int64_t cap, int64_t* tail_off) {
  int64_t p = start, count = 0;
  while (p + 4 <= n) {
    int32_t bs;
    std::memcpy(&bs, buf + p, 4);  // BAM is little-endian; so are our hosts
    if (bs < 32) return -1;
    if (p + 4 + bs > n) break;
    if (count < cap) out[count] = p;
    ++count;
    p += 4 + static_cast<int64_t>(bs);
  }
  if (tail_off) *tail_off = p;
  return count;
}

// Walk BAM record boundaries AND pack selected per-record byte ranges into a
// dense row tile in the same pass (the columnar host->device transfer layout:
// only projected columns cross the link).  sel_off/sel_len give n_sel source
// ranges within each record (all must lie inside the fixed 36-byte prefix,
// which every valid record has since block_size >= 32); they are packed
// back-to-back into rows of row_stride bytes.  The walk stops at the first
// record starting at or past ``stop`` (records there are owned by the next
// span — pass n to disable).  Callers must size cap for the worst case
// ((stop - start) / 36 + 1 records); the Python wrapper rejects overflow.
// Returns the record count, -1 on malformed input.
int64_t hbam_walk_bam_packed(const uint8_t* buf, int64_t n, int64_t start,
                             int64_t stop,
                             const int32_t* sel_off, const int32_t* sel_len,
                             int32_t n_sel, int32_t row_stride,
                             uint8_t* out_rows, int64_t* out_off, int64_t cap,
                             int64_t* tail_off) {
  int64_t p = start, count = 0;
  while (p + 4 <= n && p < stop) {
    int32_t bs;
    std::memcpy(&bs, buf + p, 4);
    if (bs < 32) return -1;
    if (p + 4 + bs > n) break;
    if (count < cap) {
      out_off[count] = p;
      uint8_t* row = out_rows + count * row_stride;
      const uint8_t* rec = buf + p;
      for (int32_t s = 0; s < n_sel; ++s) {
        std::memcpy(row, rec + sel_off[s], static_cast<size_t>(sel_len[s]));
        row += sel_len[s];
      }
    }
    ++count;
    p += 4 + static_cast<int64_t>(bs);
  }
  if (tail_off) *tail_off = p;
  return count;
}

// Walk BAM records and pack fixed prefix + sequence + quality payloads into
// dense tiles in one pass — the host side of the tensor-batch feed (bases
// and quals as fixed-stride device tiles).  Sequence bytes stay 4-bit
// packed (2 bases/byte [SPEC]); reads longer than max_len are truncated
// (full l_seq remains available in the prefix).  Output rows beyond the
// copied payload are NOT cleared — callers pass zeroed buffers.  Walk stops
// at ``stop`` as in hbam_walk_bam_packed.  Returns record count, or -1 on a
// malformed record.
int64_t hbam_walk_bam_payload(const uint8_t* buf, int64_t n, int64_t start,
                              int64_t stop, int32_t max_len,
                              int32_t seq_stride, int32_t qual_stride,
                              uint8_t* out_prefix, uint8_t* out_seq,
                              uint8_t* out_qual, int64_t* out_off,
                              int64_t cap, int64_t* tail_off) {
  int64_t p = start, count = 0;
  while (p + 4 <= n && p < stop) {
    int32_t bs;
    std::memcpy(&bs, buf + p, 4);
    if (bs < 32) return -1;
    if (p + 4 + bs > n) break;
    if (count < cap) {
      const uint8_t* rec = buf + p;
      std::memcpy(out_prefix + count * 36, rec, 36);
      uint8_t l_read_name = rec[12];
      uint16_t n_cigar;
      std::memcpy(&n_cigar, rec + 16, 2);
      int32_t l_seq;
      std::memcpy(&l_seq, rec + 20, 4);
      int64_t seq_off = 36 + static_cast<int64_t>(l_read_name) +
                        4 * static_cast<int64_t>(n_cigar);
      int64_t nb = (static_cast<int64_t>(l_seq) + 1) / 2;
      if (l_seq < 0 || seq_off + nb + l_seq > 4 + static_cast<int64_t>(bs))
        return -1;
      int32_t use = l_seq < max_len ? l_seq : max_len;
      std::memcpy(out_seq + count * seq_stride, rec + seq_off, (use + 1) / 2);
      std::memcpy(out_qual + count * qual_stride, rec + seq_off + nb, use);
      out_off[count] = p;
    }
    ++count;
    p += 4 + static_cast<int64_t>(bs);
  }
  if (tail_off) *tail_off = p;
  return count;
}

// CRC32 of a batch of byte ranges (BGZF block payload validation), threaded.
// Returns 0; crcs[i] receives the zlib CRC32 of data[off[i] .. off[i]+len[i]).
int hbam_crc32_batch(const uint8_t* data, const int64_t* off,
                     const int32_t* len, int32_t n, uint32_t* crcs,
                     int32_t n_threads) {
  if (n_threads < 1) n_threads = 1;
  std::atomic<int32_t> next(0);
  auto worker = [&]() {
    for (;;) {
      int32_t i = next.fetch_add(1);
      if (i >= n) break;
#if defined(HBAM_USE_LIBDEFLATE)
      crcs[i] = libdeflate_crc32(0, data + off[i],
                                 static_cast<size_t>(len[i]));
#else
      crcs[i] = static_cast<uint32_t>(
          crc32(0L, data + off[i], static_cast<uInt>(len[i])));
#endif
    }
  };
  std::vector<std::thread> pool;
  for (int t = 0; t < n_threads; ++t) pool.emplace_back(worker);
  for (auto& th : pool) th.join();
  return 0;
}

// Batched BGZF block deflate (writer path): compress n independent payloads.
// levels: zlib level; dst must have 64 KiB capacity per block at dst_off[i];
// out_len[i] receives each compressed size (header+cdata+footer are NOT
// added here — this is the raw DEFLATE payload only).
int hbam_deflate_batch(const uint8_t* src, const int64_t* src_off,
                       const int32_t* src_len, int32_t n_blocks,
                       uint8_t* dst, const int64_t* dst_off,
                       const int32_t* dst_cap, int32_t* out_len,
                       int32_t level, int32_t n_threads) {
  if (n_threads < 1) n_threads = 1;
  std::atomic<int32_t> next(0);
  std::atomic<int32_t> fail(-1);
#if defined(HBAM_USE_LIBDEFLATE)
  // libdeflate compresses ~3x faster than zlib at comparable ratios.
  // out_len[i] = 0 signals "did not fit in dst_cap" (incompressible) —
  // callers fall back to a stored block, matching the zlib-path contract
  // where oversized output is also a caller-handled condition.
  auto worker = [&]() {
    libdeflate_compressor* c = libdeflate_alloc_compressor(level);
    if (!c) { fail.store(0); return; }
    for (;;) {
      int32_t i = next.fetch_add(1);
      if (i >= n_blocks || fail.load(std::memory_order_relaxed) >= 0) break;
      size_t n = libdeflate_deflate_compress(
          c, src + src_off[i], static_cast<size_t>(src_len[i]),
          dst + dst_off[i], static_cast<size_t>(dst_cap[i]));
      out_len[i] = static_cast<int32_t>(n);
    }
    libdeflate_free_compressor(c);
  };
#else
  auto worker = [&]() {
    for (;;) {
      int32_t i = next.fetch_add(1);
      if (i >= n_blocks || fail.load(std::memory_order_relaxed) >= 0) break;
      z_stream zs;
      std::memset(&zs, 0, sizeof(zs));
      if (deflateInit2(&zs, level, Z_DEFLATED, -15, 8,
                       Z_DEFAULT_STRATEGY) != Z_OK) {
        fail.store(i);
        break;
      }
      zs.next_in = const_cast<Bytef*>(src + src_off[i]);
      zs.avail_in = static_cast<uInt>(src_len[i]);
      zs.next_out = dst + dst_off[i];
      zs.avail_out = static_cast<uInt>(dst_cap[i]);
      int rc = deflate(&zs, Z_FINISH);
      if (rc != Z_STREAM_END) {
        int32_t expect = -1;
        fail.compare_exchange_strong(expect, i);
      } else {
        out_len[i] = static_cast<int32_t>(zs.total_out);
      }
      deflateEnd(&zs);
    }
  };
#endif
  std::vector<std::thread> pool;
  for (int t = 0; t < n_threads; ++t) pool.emplace_back(worker);
  for (auto& th : pool) th.join();
  int32_t f = fail.load();
  return f >= 0 ? 1000 + f : 0;
}

// ---------------------------------------------------------------------------
// rANS 4x8 decode (CRAM 3.0 entropy codec [SPEC CRAMv3 section 13]).
// Frequency tables are parsed Python-side (once per stream); these run the
// per-symbol loops, which dominate CRAM decode time in pure Python.
// Semantics mirror formats/cram_codecs.py exactly, including byte-
// consumption order during renormalization.
// ---------------------------------------------------------------------------

static const uint32_t kRansLow = 1u << 23;
static const int kTfShift = 12;
static const uint32_t kTotMask = (1u << kTfShift) - 1;

// Order-0: 4 interleaved states over the whole output.
// buf[ptr..ptr+16) holds the 4 little-endian initial states.
int hbam_rans0_decode(const uint8_t* buf, int64_t buf_len, int64_t ptr,
                      const uint32_t* freqs, const uint32_t* cum,
                      const uint8_t* slot2sym,
                      uint8_t* out, int64_t out_size) {
  if (ptr + 16 > buf_len) return -1;
  uint64_t states[4];
  for (int j = 0; j < 4; ++j) {
    uint32_t s;
    std::memcpy(&s, buf + ptr + 4 * j, 4);
    states[j] = s;
  }
  ptr += 16;
  int64_t i = 0;
  for (; i + 4 <= out_size; i += 4) {
    for (int j = 0; j < 4; ++j) {
      uint64_t x = states[j];
      uint32_t m = static_cast<uint32_t>(x) & kTotMask;
      uint8_t s = slot2sym[m];
      out[i + j] = s;
      x = static_cast<uint64_t>(freqs[s]) * (x >> kTfShift) + m - cum[s];
      while (x < kRansLow) {
        if (ptr >= buf_len) return -1;
        x = (x << 8) | buf[ptr++];
      }
      states[j] = x;
    }
  }
  for (int j = 0; i + j < out_size; ++j) {
    uint64_t x = states[j];
    uint32_t m = static_cast<uint32_t>(x) & kTotMask;
    uint8_t s = slot2sym[m];
    out[i + j] = s;
    x = static_cast<uint64_t>(freqs[s]) * (x >> kTfShift) + m - cum[s];
    while (x < kRansLow) {
      if (ptr >= buf_len) return -1;
      x = (x << 8) | buf[ptr++];
    }
    states[j] = x;
  }
  // a well-formed stream decodes every state back to the encoder's
  // initial value; anything else is corruption (or a lying out_size)
  for (int j = 0; j < 4; ++j)
    if (states[j] != kRansLow) return -2;
  return 0;
}

// Order-1: per-context tables (freqs/cum [256*256], slot2sym [256*4096]);
// 4 states own the output quarters, stepped together in j order (the byte
// consumption order of the Python reference loop).
int hbam_rans1_decode(const uint8_t* buf, int64_t buf_len, int64_t ptr,
                      const uint32_t* freqs, const uint32_t* cum,
                      const uint8_t* slot2sym,
                      uint8_t* out, int64_t out_size) {
  if (ptr + 16 > buf_len) return -1;
  uint64_t states[4];
  for (int j = 0; j < 4; ++j) {
    uint32_t s;
    std::memcpy(&s, buf + ptr + 4 * j, 4);
    states[j] = s;
  }
  ptr += 16;
  const int64_t q = out_size >> 2;
  int64_t idx[4] = {0, q, 2 * q, 3 * q};
  const int64_t ends[4] = {q, 2 * q, 3 * q, out_size};
  int ctxs[4] = {0, 0, 0, 0};
  bool done_all = false;
  while (!done_all) {
    done_all = true;
    for (int j = 0; j < 4; ++j) {
      if (idx[j] >= ends[j]) continue;
      uint64_t x = states[j];
      uint32_t m = static_cast<uint32_t>(x) & kTotMask;
      int ctx = ctxs[j];
      uint8_t s = slot2sym[static_cast<int64_t>(ctx) * 4096 + m];
      out[idx[j]] = s;
      const int64_t t = static_cast<int64_t>(ctx) * 256 + s;
      x = static_cast<uint64_t>(freqs[t]) * (x >> kTfShift) + m - cum[t];
      while (x < kRansLow) {
        if (ptr >= buf_len) return -1;
        x = (x << 8) | buf[ptr++];
      }
      states[j] = x;
      ctxs[j] = s;
      if (++idx[j] < ends[j]) done_all = false;
    }
  }
  for (int j = 0; j < 4; ++j)
    if (states[j] != kRansLow) return -2;
  return 0;
}

}  // extern "C"
