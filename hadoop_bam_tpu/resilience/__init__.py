"""Degrade-and-heal resilience supervisor.

Turns the PR-1 static fault policies into an adaptive loop:

- ``breaker``: the closed/open/half-open ``CircuitBreaker`` state
  machine with decayed failure windows (injectable clock);
- ``domains``: ``FaultDomain`` tracking keyed (subsystem, backend,
  file identity), the ``DemotionLadder`` that demotes decode planes
  device -> native -> zlib mid-run (byte-identical results) and heals
  back via half-open probes, and the upgraded quarantine circuit;
- ``chaos``: named fault points past the byte-source layer (pool
  submission, the device shard_map step, deflate workers, transport
  disconnects) with seed-derived deterministic schedules.

Everything here is host-local policy — no jax, no collectives — so it
is safe to consult from pool workers, the serve dispatcher, and client
threads alike.
"""
from hadoop_bam_tpu.resilience.breaker import (       # noqa: F401
    CLOSED, HALF_OPEN, OPEN, CircuitBreaker, DecayingWindow,
)
from hadoop_bam_tpu.resilience.domains import (       # noqa: F401
    PLANES, DemotionLadder, FaultDomain, FaultDomainRegistry,
    check_quarantine_gate, decode_ladder, file_ident, quarantine_breaker,
    quarantine_run_ok, registry, reset,
)
from hadoop_bam_tpu.resilience import chaos           # noqa: F401
