"""Half-open circuit breakers with decayed failure-rate windows.

PR 1's fault policies were one-way: ``CircuitBreakerError`` tripped
terminally, quarantine never healed, the decode-plane probe ran once per
process and never revisited its answer.  This module is the reusable
state machine every adaptive policy in ``resilience/`` shares — the
classic three states:

- CLOSED: traffic flows; failures accumulate in a DECAYED window (an
  old burst of faults ages out instead of counting forever), and the
  breaker OPENS once the windowed failure count crosses the threshold;
- OPEN: traffic is refused (``allow() == False``) until ``cooldown_s``
  elapses, at which point the breaker turns HALF_OPEN;
- HALF_OPEN: a bounded number of PROBE calls are allowed through; one
  recorded success closes the breaker (and clears the window), one
  recorded failure re-opens it and re-arms the cooldown.

Clock is injectable (the ``RetryPolicy`` convention from
``utils/resilient.py``) so tests drive transitions without real time.
All methods are thread-safe: decode pool workers, the serve dispatcher
and client threads all consult the same breakers.

Metric taxonomy: ``resilience.breaker_opened`` /
``resilience.breaker_half_open`` / ``resilience.breaker_closed``
counters tick on transitions, and each transition emits a zero-width
``resilience.breaker_state`` span so state flips land on the trace
timeline next to the request that caused them.
"""
from __future__ import annotations

import threading
import time
from typing import Callable, Optional

from hadoop_bam_tpu.obs import flight
from hadoop_bam_tpu.utils.metrics import METRICS

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half_open"


class DecayingWindow:
    """Exponentially-decayed event counter: ``add()`` records an event
    NOW, ``value()`` reads the count with events older than ``window_s``
    contributing e^-1 or less.  O(1) state (a single decayed
    accumulator), so a registry can hold one per fault domain without
    SV801-style growth."""

    def __init__(self, window_s: float = 30.0,
                 clock: Callable[[], float] = time.monotonic):
        self.window_s = max(1e-6, float(window_s))
        self._clock = clock
        self._value = 0.0
        self._t_last = clock()

    def _decay(self) -> None:
        now = self._clock()
        dt = max(0.0, now - self._t_last)
        if dt:
            import math
            self._value *= math.exp(-dt / self.window_s)
            self._t_last = now

    def add(self, n: float = 1.0) -> float:
        self._decay()
        self._value += n
        return self._value

    def value(self) -> float:
        self._decay()
        return self._value

    def reset(self) -> None:
        self._value = 0.0
        self._t_last = self._clock()


class CircuitBreaker:
    """The closed/open/half-open state machine (module docstring).

    ``allow()`` is the gate call sites make BEFORE doing work; in
    HALF_OPEN it consumes one of the ``half_open_probes`` probe slots,
    so the caller that gets ``True`` is expected to report the outcome
    with ``record_success`` / ``record_failure``."""

    def __init__(self, failure_threshold: float = 3.0,
                 window_s: float = 30.0, cooldown_s: float = 5.0,
                 half_open_probes: int = 1,
                 clock: Callable[[], float] = time.monotonic,
                 name: str = ""):
        self.failure_threshold = float(failure_threshold)
        self.cooldown_s = float(cooldown_s)
        self.half_open_probes = max(1, int(half_open_probes))
        self.name = name
        self._clock = clock
        self._lock = threading.Lock()
        self._state = CLOSED
        self._window = DecayingWindow(window_s, clock)
        self._opened_at = 0.0
        self._half_open_at = 0.0
        self._probes = 0
        self.opened_total = 0      # times this breaker tripped (tests/health)
        self.healed_total = 0      # half-open probes that closed it

    # -- internals (lock held) ----------------------------------------------

    def _transition(self, state: str) -> None:
        self._state = state
        METRICS.count(f"resilience.breaker_{state}")
        # zero-width span: a state flip on the trace timeline
        with METRICS.span("resilience.breaker_state",
                          breaker=self.name, state=state):
            pass
        # the flight recorder sees every flip; an OPEN additionally
        # snapshots the ring to disk (when a dump dir is configured) —
        # the trip, the tripping request's trace id, and the prior span
        # completions land in one incident document
        rec = flight.recorder()
        rec.record_transition("breaker", self.name, state)
        if state == OPEN:
            rec.dump(f"breaker_open:{self.name or 'unnamed'}")

    def _maybe_half_open(self) -> None:
        if self._state == OPEN and \
                self._clock() - self._opened_at >= self.cooldown_s:
            self._probes = 0
            self._half_open_at = self._clock()
            self._transition(HALF_OPEN)
        elif self._state == HALF_OPEN and \
                self._probes >= self.half_open_probes and \
                self._clock() - self._half_open_at >= self.cooldown_s:
            # an exhausted probe budget whose outcomes were never
            # reported (a probe-taker that died mid-flight) re-arms
            # after another cooldown — the breaker must never wedge in
            # HALF_OPEN with no way forward
            self._probes = 0
            self._half_open_at = self._clock()

    # -- public surface ------------------------------------------------------

    @property
    def state(self) -> str:
        with self._lock:
            self._maybe_half_open()
            return self._state

    def allow(self) -> bool:
        """May the caller do the protected work right now?  (Consumes a
        probe slot in HALF_OPEN.)"""
        with self._lock:
            self._maybe_half_open()
            if self._state == CLOSED:
                return True
            if self._state == HALF_OPEN and \
                    self._probes < self.half_open_probes:
                self._probes += 1
                return True
            return False

    def retry_after_s(self) -> float:
        """How long until the next state change could let work through —
        the ``retry_after_s`` hint shed responses carry."""
        with self._lock:
            if self._state != OPEN:
                return 0.0
            return max(0.0, self.cooldown_s
                       - (self._clock() - self._opened_at))

    def record_success(self) -> None:
        # successes in CLOSED do not actively drain the window (decay
        # does); in HALF_OPEN — including an OPEN breaker whose cooldown
        # just elapsed — one success IS the passed probe and closes
        with self._lock:
            self._maybe_half_open()
            if self._state == HALF_OPEN:
                self.healed_total += 1
                self._window.reset()
                self._transition(CLOSED)

    def record_failure(self, weight: float = 1.0) -> None:
        with self._lock:
            self._maybe_half_open()
            if self._state == HALF_OPEN:
                self._opened_at = self._clock()
                self._transition(OPEN)
                self.opened_total += 1
                return
            rate = self._window.add(weight)
            # half-event tolerance: N failures spread over a fraction of
            # the window decay to just under N (2.97 for "3 quick
            # failures"), and a strict >= would quietly turn threshold 3
            # into threshold 4 — windowed mass within half an event of
            # the threshold counts as reaching it
            if self._state == CLOSED and \
                    rate >= self.failure_threshold - 0.5:
                self._opened_at = self._clock()
                self._transition(OPEN)
                self.opened_total += 1

    def force_open(self) -> None:
        """Trip immediately (the quarantine circuit uses this: one
        tripped run IS the threshold)."""
        with self._lock:
            if self._state != OPEN:
                self._opened_at = self._clock()
                self._transition(OPEN)
                self.opened_total += 1
            else:
                self._opened_at = self._clock()

    def failure_rate(self) -> float:
        with self._lock:
            return self._window.value()

    def snapshot(self) -> dict:
        with self._lock:
            self._maybe_half_open()
            return {"state": self._state,
                    "failure_rate": round(self._window.value(), 4),
                    "opened_total": self.opened_total,
                    "healed_total": self.healed_total,
                    "retry_after_s": round(
                        max(0.0, self.cooldown_s
                            - (self._clock() - self._opened_at))
                        if self._state == OPEN else 0.0, 4)}
