"""Chaos fault points past the byte-source layer + seeded schedules.

PR 1's ``install_chaos`` covers exactly one seam: path-opened byte
sources.  The device plane, the serve tier's transports, the shared
pool, and the parallel writer all fault in production for reasons a
``pread`` wrapper can never exercise.  This module adds *named fault
points* — instrumented call sites that consult a registry and raise /
delay deterministically when a schedule is installed, and cost one
dict-get of a module global when nothing is (the ``_SOURCE_WRAPPER``
discipline from ``utils/seekable.py``):

========================  =================================================
point                     instrumented at
========================  =================================================
``pool.submit``           ``utils/pools.submit`` (task submission)
``pool.task``             ``utils/pools._timed_task`` (the WORKER
                          thread, before the task body — a "delay"
                          fault here wedges a worker mid-task)
``decode.native``         the ladder-aware span decode closures
                          (``parallel/pipeline.py``), native rung only
``device.step``           ``_flagstat_device_plane`` dispatch (the
                          shard_map step boundary)
``write.deflate``         ``ParallelBGZFWriter._deflate`` pool workers
``serve.transport``       ``serve/transport.handle_stream`` per line
                          (an injected disconnect)
``serve.peer``            ``serve/fleet.Fleet._peer_call`` before the
                          socket is opened (delay/drop/disconnect on
                          every fleet heartbeat and peer-fetch)
========================  =================================================

Faults raise the PR-1 taxonomy (``TransientIOError`` for "transient",
``CorruptDataError`` for "corrupt", ``ConnectionResetError`` for
"disconnect") so every policy boundary treats injected faults exactly
like real ones.

Determinism: a ``PointFault`` fires by 0-based call index (``at_call``)
with a firing ``count`` budget, and ``seeded_point_faults`` derives the
indices from a single integer seed — the same seed always reproduces
the same fault timeline, which is what makes a chaos soak's failure
bisectable (the satellite contract; byte sources get the same treatment
in ``utils/resilient.SeededFaultSchedule``).
"""
from __future__ import annotations

import dataclasses
import random
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence

from hadoop_bam_tpu.utils.errors import CorruptDataError, TransientIOError
from hadoop_bam_tpu.utils.metrics import METRICS

KNOWN_POINTS = ("pool.submit", "pool.task", "decode.native",
                "device.step", "write.deflate", "serve.transport",
                "serve.peer")

FAULT_KINDS = ("transient", "corrupt", "disconnect", "delay")


@dataclasses.dataclass
class PointFault:
    """One scheduled fault at a named point.  ``at_call`` matches the
    point's 0-based call index (None = every call); ``count`` is the
    firing budget, shared across threads hitting the point."""

    kind: str                       # transient|corrupt|disconnect|delay
    at_call: Optional[int] = None
    count: int = 1
    delay_s: float = 0.005


class _PointState:
    def __init__(self, faults: Sequence[PointFault],
                 sleep: Callable[[float], None]):
        self.faults = list(faults)
        self.sleep = sleep
        self.calls = 0
        self.fired: Dict[str, int] = {}


_LOCK = threading.Lock()
_POINTS: Dict[str, _PointState] = {}
# fast path: None unless at least one point is installed, so `fire`
# costs a single global load on production paths
_ACTIVE: Optional[Dict[str, _PointState]] = None


def install_fault_points(point: str, faults: Sequence[PointFault],
                         sleep: Callable[[float], None] = time.sleep
                         ) -> None:
    """Arm ``point`` with a fault schedule (replacing any existing one).
    Unknown point names are accepted — a test may instrument its own —
    but the production sites are ``KNOWN_POINTS``."""
    global _ACTIVE
    with _LOCK:
        _POINTS[str(point)] = _PointState(faults, sleep)
        _ACTIVE = _POINTS


def clear_fault_points(point: Optional[str] = None) -> None:
    global _ACTIVE
    with _LOCK:
        if point is None:
            _POINTS.clear()
        else:
            _POINTS.pop(str(point), None)
        if not _POINTS:
            _ACTIVE = None


def injected_counts(point: str) -> Dict[str, int]:
    """Faults fired so far at ``point``, by kind (test assertions)."""
    with _LOCK:
        st = _POINTS.get(point)
        return dict(st.fired) if st is not None else {}


class fault_points_on:
    """``with fault_points_on(point, faults):`` — scoped install."""

    def __init__(self, point: str, faults: Sequence[PointFault],
                 sleep: Callable[[float], None] = time.sleep):
        self._point = point
        install_fault_points(point, faults, sleep)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        clear_fault_points(self._point)


def fire(point: str, **ctx) -> None:
    """The instrumented-site call: no-op (one global load) when no
    chaos is installed; otherwise consult ``point``'s schedule and
    raise/delay per the matching fault."""
    active = _ACTIVE
    if active is None:
        return
    with _LOCK:
        st = active.get(point)
        if st is None:
            return
        idx = st.calls
        st.calls += 1
        hits: List[PointFault] = []
        for f in st.faults:
            if f.count <= 0:
                continue
            if f.at_call is not None and idx != f.at_call:
                continue
            f.count -= 1
            st.fired[f.kind] = st.fired.get(f.kind, 0) + 1
            METRICS.count("chaos.point_faults")
            METRICS.count(f"chaos.{point}.{f.kind}")
            hits.append(f)
        sleep = st.sleep
    for f in hits:
        if f.kind == "delay":
            sleep(f.delay_s)
    for f in hits:
        if f.kind == "transient":
            raise TransientIOError(
                f"injected transient fault at {point} (call {idx})")
        if f.kind == "corrupt":
            raise CorruptDataError(
                f"injected corrupt fault at {point} (call {idx})")
        if f.kind == "disconnect":
            raise ConnectionResetError(
                f"injected disconnect at {point} (call {idx})")


def seeded_point_faults(seed: int, point: str, kinds: Sequence[str],
                        n_faults: int, max_call: int = 64,
                        delay_s: float = 0.005) -> List[PointFault]:
    """A deterministic fault schedule for ``point`` derived from
    ``seed``: ``n_faults`` single-shot faults at distinct call indices
    in ``[0, max_call)``, kinds cycled from the seeded shuffle.  Same
    (seed, point, args) -> same schedule, every run, every host."""
    rng = random.Random(f"{int(seed)}:{point}")
    n = min(int(n_faults), int(max_call))
    calls = rng.sample(range(int(max_call)), n)
    ks = list(kinds)
    rng.shuffle(ks)
    return [PointFault(kind=ks[i % len(ks)], at_call=c, count=1,
                       delay_s=delay_s)
            for i, c in enumerate(sorted(calls))]
