"""Fault domains + the decode-backend demotion ladder.

A *fault domain* is the unit adaptive policy reasons about: one
``(subsystem, backend, file_identity)`` triple — e.g. ``("decode",
"native", <ident of f.bam>)`` — holding a decayed failure window and a
half-open ``CircuitBreaker``.  Faults in one file's native decode never
demote another file's plane; a burst of faults last minute ages out of
the window instead of counting forever.

``DemotionLadder`` layers the multi-backend decode lineage (Rapidgzip /
Compressed-Resident Genomics, PAPERS.md) on top: every decode plane in
``device -> native -> zlib`` produces byte-identical results, so when
one plane's domain breaker opens, the run *demotes* to the next plane
mid-flight and keeps producing correct answers — and after the
breaker's cooldown a half-open probe re-tries the faster plane and
heals back.  Blame is only ever **confirmed on the oracle**: a span
that fails on plane P counts against P's domain only when a lower plane
decodes the same bytes successfully (if every plane fails, the data —
not the plane — is bad, and no domain is charged).

The process-global ``registry()`` is what drivers and the serve tier
consult; ``reset()`` restores pristine state (tests).  Domain count is
bounded (LRU) so arbitrary file churn cannot grow it without bound —
the SV801 discipline.
"""
from __future__ import annotations

import os
import threading
import time
from collections import OrderedDict
from typing import Callable, Dict, Optional, Tuple

from hadoop_bam_tpu.obs import flight
from hadoop_bam_tpu.resilience.breaker import CircuitBreaker, OPEN
from hadoop_bam_tpu.utils.errors import (
    CircuitBreakerError, PLAN, classify_error,
)
from hadoop_bam_tpu.utils.metrics import METRICS

# fast -> safe; every rung is byte-identical, each one slower and more
# battle-tested than the one above it
PLANES = ("device", "native", "zlib")

_MAX_DOMAINS = 256          # LRU bound on tracked domains


def file_ident(path) -> str:
    """Domain key component for a path-ish input: the absolute path.
    (Identity by abspath, not (size, mtime): a fault domain should
    survive the file being atomically republished — the environment
    around the path is what faults, and a healed republish closes the
    breaker through the normal half-open probe.)"""
    if isinstance(path, (str, os.PathLike)):
        return os.path.abspath(os.fspath(path))
    return repr(path)


class FaultDomain:
    """One (subsystem, backend, ident) tracker: breaker + counters."""

    def __init__(self, key: Tuple[str, str, str], config=None,
                 clock: Callable[[], float] = time.monotonic):
        self.key = key
        self.breaker = CircuitBreaker(
            failure_threshold=float(getattr(
                config, "breaker_failure_threshold", 3.0)),
            window_s=float(getattr(config, "breaker_window_s", 30.0)),
            cooldown_s=float(getattr(config, "breaker_cooldown_s", 5.0)),
            half_open_probes=int(getattr(
                config, "breaker_half_open_probes", 1)),
            clock=clock, name="/".join(key[:2]))
        self.failures_total = 0
        self.successes_total = 0

    def record_failure(self, exc: Optional[BaseException] = None,
                       weight: float = 1.0) -> None:
        self.failures_total += 1
        METRICS.count("resilience.domain_failures")
        self.breaker.record_failure(weight)

    def record_success(self) -> None:
        self.successes_total += 1
        self.breaker.record_success()

    def snapshot(self) -> dict:
        d = self.breaker.snapshot()
        d.update(subsystem=self.key[0], backend=self.key[1],
                 failures_total=self.failures_total,
                 successes_total=self.successes_total)
        return d


class FaultDomainRegistry:
    """Process-wide domain table (module docstring)."""

    def __init__(self, clock: Callable[[], float] = time.monotonic):
        self._clock = clock
        self._lock = threading.Lock()
        self._domains: "OrderedDict[Tuple, FaultDomain]" = OrderedDict()

    @property
    def clock(self) -> Callable[[], float]:
        return self._clock

    def domain(self, subsystem: str, backend: str, ident: str,
               config=None) -> FaultDomain:
        key = (str(subsystem), str(backend), str(ident))
        with self._lock:
            d = self._domains.get(key)
            if d is not None:
                self._domains.move_to_end(key)
                return d
            while len(self._domains) >= _MAX_DOMAINS:
                self._domains.popitem(last=False)
            d = FaultDomain(key, config=config, clock=self._clock)
            self._domains[key] = d
            return d

    def fault_pressure(self) -> float:
        """Registry-wide decayed failure count — the serve prefetcher's
        auto-pause signal: high pressure means speculative work is the
        wrong way to spend decode capacity right now."""
        with self._lock:
            domains = list(self._domains.values())
        return sum(d.breaker.failure_rate() for d in domains)

    def open_breakers(self) -> int:
        with self._lock:
            domains = list(self._domains.values())
        return sum(1 for d in domains if d.breaker.state == OPEN)

    def states(self) -> Dict[str, dict]:
        """Health-surface snapshot: domain key string -> breaker state
        (only NON-TRIVIAL domains: something recorded or non-closed)."""
        with self._lock:
            items = list(self._domains.items())
        out: Dict[str, dict] = {}
        for key, d in items:
            snap = d.snapshot()
            if d.failures_total or snap["state"] != "closed":
                out["/".join(key)] = snap
        return out

    def reset(self, clock: Optional[Callable[[], float]] = None) -> None:
        with self._lock:
            self._domains.clear()
            if clock is not None:
                self._clock = clock


_REGISTRY = FaultDomainRegistry()


def registry() -> FaultDomainRegistry:
    return _REGISTRY


def reset(clock: Optional[Callable[[], float]] = None) -> None:
    """Restore pristine process state (tests): domains, breakers, and —
    when given — the registry clock for fake-time transition tests."""
    _REGISTRY.reset(clock=clock if clock is not None else time.monotonic)


# ---------------------------------------------------------------------------
# Decode-backend demotion ladder
# ---------------------------------------------------------------------------

class DemotionLadder:
    """Adaptive plane selection for ONE file's decode (module
    docstring).  Thread-safe: pool workers decoding spans concurrently
    share one ladder per driver call.

    - ``plane()``: the best currently-allowed rung (may consume a
      half-open probe slot — the caller that gets the healed plane is
      the probe).
    - ``next_lower(p)``: the rung below ``p``, or None at the bottom.
    - ``confirm_failure(p, exc)``: charge plane ``p``'s domain — call
      ONLY after a lower rung succeeded on the same bytes (oracle-
      confirmed plane-local fault).
    - ``record_success(p)``: ticks the domain; a success on a HALF_OPEN
      rung heals it (closed again for everyone).
    """

    def __init__(self, ident: str, start_plane: str,
                 config=None, subsystem: str = "decode",
                 reg: Optional[FaultDomainRegistry] = None):
        if start_plane not in PLANES:
            # a plane outside the ladder (a future backend) gets a
            # one-rung ladder: nothing to demote to, nothing breaks
            self.planes: Tuple[str, ...] = (start_plane,)
        else:
            self.planes = PLANES[PLANES.index(start_plane):]
        self.ident = ident
        self.subsystem = subsystem
        self.config = config
        self._reg = reg if reg is not None else registry()

    def _domain(self, plane: str) -> FaultDomain:
        return self._reg.domain(self.subsystem, plane, self.ident,
                                config=self.config)

    def plane(self) -> str:
        """Best allowed rung right now.  The terminal rung is always
        allowed — a fully-open ladder still serves, just slowly."""
        for p in self.planes[:-1]:
            if self._domain(p).breaker.allow():
                return p
        return self.planes[-1]

    def host_plane(self) -> str:
        """Like ``plane()`` but never 'device' — what the span-level
        host decode closures consult."""
        for p in self.planes[:-1]:
            if p == "device":
                continue
            if self._domain(p).breaker.allow():
                return p
        return self.planes[-1]

    def allow_plane(self, plane: str) -> bool:
        """Gate ONE plane's breaker (consumes a half-open probe slot —
        call only when the caller will actually attempt the plane and
        report the outcome; use ``states()`` for display)."""
        if plane not in self.planes:
            return False
        return self._domain(plane).breaker.allow()

    def next_lower(self, plane: str) -> Optional[str]:
        try:
            i = self.planes.index(plane)
        except ValueError:
            return None
        return self.planes[i + 1] if i + 1 < len(self.planes) else None

    def demotable(self, plane: str, exc: BaseException) -> bool:
        """May a fault of this class on this rung demote?  PLAN-class
        (misconfiguration) and breaker errors never demote — they are
        not the plane's fault."""
        if isinstance(exc, CircuitBreakerError):
            return False
        if classify_error(exc) == PLAN:
            return False
        return self.next_lower(plane) is not None

    def confirm_failure(self, plane: str, exc: BaseException) -> None:
        METRICS.count("resilience.demotions")
        METRICS.count(f"resilience.demoted_from_{plane}")
        # a demotion is an incident-grade event even before the plane's
        # breaker opens: record + dump so the first oracle-confirmed
        # plane fault already leaves a flight snapshot behind
        rec = flight.recorder()
        rec.record_transition("demotion", f"{self.subsystem}/{plane}",
                              "demoted")
        rec.dump(f"plane_demotion:{plane}", error=str(exc))
        self._domain(plane).record_failure(exc)

    def record_success(self, plane: str) -> None:
        d = self._domain(plane)
        healed_before = d.breaker.healed_total
        d.record_success()
        if d.breaker.healed_total > healed_before:
            METRICS.count("resilience.heals")

    def states(self) -> Dict[str, dict]:
        return {p: self._domain(p).snapshot() for p in self.planes}


def decode_ladder(path, start_plane: str, config=None) -> DemotionLadder:
    """The decode-plane ladder for one file (drivers' entry point)."""
    return DemotionLadder(file_ident(path), start_plane, config=config)


# ---------------------------------------------------------------------------
# Quarantine circuit (the PR-1 one-way breaker, upgraded)
# ---------------------------------------------------------------------------

def quarantine_breaker(path, config=None) -> CircuitBreaker:
    """The per-file quarantine circuit: ``QuarantineManifest``'s
    fraction trip force-opens it, runs that finish clean record success
    (closing a HALF_OPEN probe).  Threshold 1 — the fraction check IS
    the threshold; the breaker adds the open/half-open/heal lifecycle
    the old one-way trip lacked."""
    d = _REGISTRY.domain("quarantine", "spans", file_ident(path),
                         config=config)
    return d.breaker


def check_quarantine_gate(path, config=None) -> None:
    """Fast-fail gate drivers call before planning a run: while the
    path's quarantine circuit is OPEN the run is refused immediately
    (``CircuitBreakerError`` with a retry-after hint) instead of
    re-decoding a file that just quarantined past the threshold; after
    the cooldown, HALF_OPEN lets one probe run through — a clean finish
    heals the circuit."""
    br = quarantine_breaker(path, config=config)
    if not br.allow():
        METRICS.count("resilience.quarantine_gate_shed")
        raise CircuitBreakerError(
            f"quarantine circuit for {file_ident(path)} is open "
            f"(tripped {br.opened_total}x) — retry in "
            f"{br.retry_after_s():.3g}s",
            retry_after_s=br.retry_after_s())


def quarantine_run_ok(path, config=None) -> None:
    """A run over ``path`` finished without tripping the fraction
    breaker: heal a half-open quarantine circuit."""
    quarantine_breaker(path, config=config).record_success()
