"""Admission control + per-request deadlines for the query engine.

A serving path that accepts unbounded concurrent work degrades for
everyone at once; this module bounds it the way the PR-1 resilience
layer expects failures to surface:

- shed load (in-flight limit hit with a full wait queue) and blown
  deadlines raise ``TransientIOError`` — the class the retry /
  circuit-breaker machinery already treats as "back off and try again",
  which is exactly what a loaded server wants clients to do;
- misconfiguration (non-positive limits, negative deadlines) raises
  ``PlanError`` — never retried, never quarantined.

Clock and sleep are injectable so tests assert exact behavior without
real time passing (the RetryPolicy convention from utils/resilient.py).
"""
from __future__ import annotations

import contextlib
import threading
import time
from typing import Callable, Iterator, Optional

from hadoop_bam_tpu.utils.errors import PlanError, TransientIOError
from hadoop_bam_tpu.utils.metrics import METRICS


class Deadline:
    """A per-request wall budget.  ``check()`` raises ``TransientIOError``
    once the budget is spent — transient on purpose: the data is fine,
    the request may simply be retried when the system is less loaded.

    The budget is anchored at ``start`` — ENQUEUE time, by default the
    moment the Deadline is built inside ``QueryScheduler.admit`` —
    so admission wait counts against it, matching what the
    ``query.latency_s`` histogram measures end to end.  ``rebudget``
    derives a per-request override Deadline that KEEPS the anchor: a
    request that waited 0.3s for admission has 0.3s less of its own
    budget left, never a fresh one."""

    def __init__(self, seconds: Optional[float],
                 clock: Callable[[], float] = time.monotonic,
                 start: Optional[float] = None):
        if seconds is not None and seconds < 0:
            raise PlanError(f"query deadline must be >= 0, got {seconds}")
        self.seconds = seconds
        self._clock = clock
        self.t_start = clock() if start is None else start
        self._t_end = None if seconds is None else self.t_start + seconds
        self.missed = False      # set once by book_miss()

    def rebudget(self, seconds: Optional[float]) -> "Deadline":
        """A new Deadline with ``seconds`` of budget anchored at THIS
        deadline's enqueue instant (per-request overrides inside an
        admitted batch)."""
        return Deadline(seconds, clock=self._clock, start=self.t_start)

    def remaining(self) -> Optional[float]:
        if self._t_end is None:
            return None
        return self._t_end - self._clock()

    @property
    def expired(self) -> bool:
        r = self.remaining()
        return r is not None and r <= 0

    def book_miss(self) -> bool:
        """Tick ``query.deadline_misses`` ONCE for this deadline —
        idempotent, so a hard abort (``check`` raising) and the serving
        path's finally-block soft-miss accounting never double-count
        one request."""
        if self.missed:
            return False
        self.missed = True
        METRICS.count("query.deadline_misses")
        # incident-grade: a missed deadline snapshots the flight ring
        # (transition always; disk only when a dump dir is configured)
        from hadoop_bam_tpu.obs import flight
        rec = flight.recorder()
        rec.record_transition("deadline", "query.deadline", "missed")
        rec.dump("deadline_miss")
        return True

    def check(self, what: str = "query") -> None:
        if self.expired:
            METRICS.count("query.deadline_exceeded")
            self.book_miss()
            raise TransientIOError(
                f"{what} exceeded its {self.seconds:g}s deadline — "
                f"retry later or raise the deadline "
                f"(config.query_deadline_s)")


class QueryScheduler:
    """Bounded in-flight admission with a bounded wait queue.

    ``admit()`` yields a ``Deadline`` for the admitted request.  When
    ``max_in_flight`` requests are already running and ``queue_depth``
    more are already waiting, admission is REJECTED immediately with
    ``TransientIOError`` (load shedding beats unbounded queueing: a
    queue that grows without bound converts overload into latency for
    every later request).  A waiter whose deadline expires before a slot
    frees also raises ``TransientIOError``."""

    def __init__(self, max_in_flight: int = 8, queue_depth: int = 32,
                 default_deadline_s: Optional[float] = None,
                 clock: Callable[[], float] = time.monotonic,
                 shed_retry_after_s: float = 0.1):
        if max_in_flight < 1:
            raise PlanError(
                f"query_max_in_flight must be >= 1, got {max_in_flight}")
        if queue_depth < 0:
            raise PlanError(
                f"query_queue_depth must be >= 0, got {queue_depth}")
        if default_deadline_s is not None and default_deadline_s < 0:
            raise PlanError(
                f"query_deadline_s must be >= 0, got {default_deadline_s}")
        self.max_in_flight = int(max_in_flight)
        self.queue_depth = int(queue_depth)
        self.default_deadline_s = default_deadline_s
        self.shed_retry_after_s = float(shed_retry_after_s)
        self._clock = clock
        self._cond = threading.Condition()
        self._in_flight = 0
        self._waiting = 0

    @property
    def in_flight(self) -> int:
        with self._cond:
            return self._in_flight

    def deadline(self, seconds: Optional[float] = None) -> Deadline:
        return Deadline(self.default_deadline_s if seconds is None
                        else seconds, clock=self._clock)

    @contextlib.contextmanager
    def admit(self, deadline_s: Optional[float] = None) -> Iterator[Deadline]:
        deadline = self.deadline(deadline_s)
        t0 = time.perf_counter()
        with self._cond:
            if self._in_flight >= self.max_in_flight \
                    and self._waiting >= self.queue_depth:
                METRICS.count("query.rejected")
                # the retry_after hint rides the shed so transports can
                # put a concrete backoff on the wire (never a hang, and
                # never a client guessing)
                raise TransientIOError(
                    f"query admission rejected: {self._in_flight} in "
                    f"flight (limit {self.max_in_flight}) and "
                    f"{self._waiting} queued (limit {self.queue_depth}) "
                    f"— retry with backoff",
                    retry_after_s=self.shed_retry_after_s)
            self._waiting += 1
            try:
                while self._in_flight >= self.max_in_flight:
                    rem = deadline.remaining()
                    if rem is not None and rem <= 0:
                        deadline.check("query admission wait")
                    # bounded waits so an injected clock can expire the
                    # deadline without a real notification arriving
                    self._cond.wait(0.05 if rem is None
                                    else min(0.05, max(rem, 0.001)))
            finally:
                self._waiting -= 1
            self._in_flight += 1
        METRICS.count("query.admitted")
        # admission-wait distribution: a deep p95 here means the limit,
        # not the decode path, is what clients are waiting on
        METRICS.observe("query.admit_wait_s", time.perf_counter() - t0)
        try:
            yield deadline
        finally:
            with self._cond:
                self._in_flight -= 1
                self._cond.notify()
