"""Batched random-access region serving: the query subsystem.

The scan drivers (parallel/pipeline.py) answer "process the whole
file"; this package answers the serving shape the north star actually
describes — many concurrent small *region* queries against the same
files, where warm-path throughput comes from reusing decoded chunks
rather than from scan parallelism:

- ``engine.py``  QueryEngine: a batch of (path, region) requests is
  resolved through the genomic indexes (BAI/CSI for BAM, tabix for
  BGZF VCF and BCF, the container table for CRAM) to a minimal list of
  virtual-offset chunks, coalesced and deduplicated ACROSS requests,
  decoded once each, then filtered on the device mesh by an
  interval-overlap predicate fed through parallel/staging.FeedPipeline.
- ``cache.py``   ChunkCache: byte-budgeted LRU over decoded chunks,
  keyed by file identity (path + mtime + size) and virtual-offset
  range, with hit/miss/eviction counters in utils/metrics.py.
- ``scheduler.py``  QueryScheduler: admission control (bounded
  in-flight queries + a bounded wait queue) and per-request deadlines,
  raising through the PR-1 error taxonomy (``TransientIOError`` for
  shed load / blown deadlines, ``PlanError`` for misconfiguration) so
  the existing retry / circuit-breaker layers apply unchanged.

CLI: ``hbam query``.  API: ``api.query_regions``.
"""
from hadoop_bam_tpu.query.cache import ChunkCache, file_identity  # noqa: F401
from hadoop_bam_tpu.query.scheduler import (  # noqa: F401
    Deadline, QueryScheduler,
)
from hadoop_bam_tpu.query.engine import (  # noqa: F401
    QueryEngine, QueryRequest, QueryResult,
)
