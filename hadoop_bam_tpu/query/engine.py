"""QueryEngine: batched random-access region serving over indexed files.

Request shape: a BATCH of ``(path, region)`` pairs (the serving analog of
Hadoop-BAM's BAMInputFormat interval support, which only ever trimmed
scan plans).  The engine:

1. resolves every region through the file's genomic index — BAI/CSI for
   BAM (``split/bai.py``), tabix for BGZF VCF *and* BCF
   (``split/tabix.py``), the container coordinate table for CRAM
   (``split/cram_planner.py``) — into virtual-offset chunk ranges;
2. COALESCES and deduplicates the ranges across all requests touching
   the same file (overlapping hot regions share chunks; small compressed
   gaps merge so one pread+inflate serves neighbours) and decodes each
   chunk exactly once, through the ``ChunkCache`` so repeated queries
   reuse decoded chunks across batches;
3. routes the candidate record columns through the shared
   ``parallel/staging.FeedPipeline`` and filters them with a jitted
   interval-overlap predicate ON THE MESH (``make_overlap_step``) — the
   exactness filter runs as one sharded vector compare per tile group,
   not per-record host Python;
4. materializes per-request results (or yields the device tensor
   batches directly — ``api.query_regions``).

Failure policy rides the PR-1 taxonomy unchanged: chunk decode goes
through ``decode_with_retry`` (transient retries, corrupt fails fast),
admission/deadline pressure raises ``TransientIOError``, and bad
requests (missing index, unknown contig, unsupported container) raise
``PlanError``.
"""
from __future__ import annotations

import dataclasses
import struct
import threading
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from hadoop_bam_tpu.config import DEFAULT_CONFIG, HBamConfig
from hadoop_bam_tpu.query.cache import ChunkCache, file_identity
from hadoop_bam_tpu.query.scheduler import Deadline, QueryScheduler
from hadoop_bam_tpu.split.intervals import Interval, resolve_interval
from hadoop_bam_tpu.split.spans import FileVirtualSpan
from hadoop_bam_tpu.utils.errors import PlanError
from hadoop_bam_tpu.utils.metrics import METRICS
from hadoop_bam_tpu.utils.stepcache import BoundedStepCache

_I32_MAX = np.int32(np.iinfo(np.int32).max)
# compressed gap below which neighbouring index ranges coalesce into one
# chunk: one pread+inflate then serves both (htslib merges chunks the
# same way); the decoded-but-unrequested rows in the gap are filtered by
# the exact device predicate like any other non-overlapping candidate
_COALESCE_GAP_C = 1 << 14


@dataclasses.dataclass(frozen=True)
class QueryRequest:
    path: str
    region: str
    # per-request deadline override (seconds); None = the batch deadline
    deadline_s: Optional[float] = None


@dataclasses.dataclass
class QueryResult:
    request: QueryRequest
    records: List[object]          # SamRecord (BAM/CRAM) or VcfRecord
    n_candidates: int = 0          # rows the index surfaced pre-predicate


# ---------------------------------------------------------------------------
# device predicate
# ---------------------------------------------------------------------------

# bounded (SV801): one entry per (mesh, axis) actually used — a process
# cycling through many meshes must not grow this forever
_STEP_CACHE = BoundedStepCache(cap=8)

# tile column order fed through the FeedPipeline (all [] int32 series)
TILE_COLUMNS = ("rid", "pos1", "end1", "iv_rid", "iv_beg", "iv_end", "req")


def make_overlap_step(mesh, axis: str = "data"):
    """Jitted sharded predicate: per-row 1-based inclusive interval
    overlap — ``rid == iv_rid and pos1 <= iv_end and end1 >= iv_beg`` —
    over ``[n_dev, cap]`` int32 column tiles, returning the sharded
    boolean keep mask.  The interval bounds ride the tile as per-row
    columns, so one step serves rows belonging to DIFFERENT requests in
    the same dispatch (the whole point of batching the queries)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from hadoop_bam_tpu.parallel.mesh import shard_map

    key = ("query_overlap", tuple(mesh.devices.flat), mesh.axis_names, axis)

    def build():
        def per_device(rid, pos1, end1, iv_rid, iv_beg, iv_end, req,
                       count):
            rid, pos1, end1 = rid[0], pos1[0], end1[0]
            iv_rid, iv_beg, iv_end = iv_rid[0], iv_beg[0], iv_end[0]
            count = count[0]
            valid = jnp.arange(rid.shape[0], dtype=jnp.int32) < count
            keep = valid & (rid == iv_rid) & (pos1 <= iv_end) \
                & (end1 >= iv_beg)
            del req
            return keep[None]

        fn = shard_map(per_device, mesh=mesh, in_specs=(P(axis),) * 8,
                       out_specs=P(axis))
        return jax.jit(fn)

    return _STEP_CACHE.get_or_build(key, build)


# ---------------------------------------------------------------------------
# per-format metadata + chunk decode
# ---------------------------------------------------------------------------

def _sniff_kind(path: str) -> str:
    lower = path.lower()
    if lower.endswith(".bam"):
        return "bam"
    if lower.endswith(".cram"):
        return "cram"
    if lower.endswith(".bcf"):
        return "bcf"
    if lower.endswith((".vcf.gz", ".vcf.bgz")):
        return "vcf"
    raise PlanError(
        f"cannot region-query {path!r}: supported containers are .bam "
        f"(.bai/.csi sidecar), .vcf.gz (.tbi), .bcf (.tbi), .cram")


def _ref_span_of_cigar(cigar: str, seq: str) -> int:
    """Reference span of a SAM CIGAR string (M/D/N/=/X) — host fallback
    for record formats without columnar CIGAR access (CRAM)."""
    import re
    if cigar in ("*", ""):
        return len(seq) if seq != "*" else 0
    return sum(int(n) for n, op in re.findall(r"(\d+)([MIDNSHP=X])", cigar)
               if op in "MDN=X")


class _FileMeta:
    """Header + index of one file identity, resolved once per engine."""

    __slots__ = ("path", "ident", "kind", "header", "ref_names", "index")

    def __init__(self, path: str, ident, kind: str, header, ref_names,
                 index):
        self.path = path
        self.ident = ident
        self.kind = kind
        self.header = header
        self.ref_names = list(ref_names)
        self.index = index


class QueryEngine:
    """Batched random-access query serving (module docstring)."""

    def __init__(self, config: HBamConfig = DEFAULT_CONFIG,
                 cache: Optional[ChunkCache] = None,
                 scheduler: Optional[QueryScheduler] = None,
                 mesh=None):
        self.config = config
        self.cache = cache if cache is not None else ChunkCache(
            int(getattr(config, "query_cache_bytes", 256 << 20)))
        self.scheduler = scheduler if scheduler is not None else \
            QueryScheduler(
                int(getattr(config, "query_max_in_flight", 8)),
                int(getattr(config, "query_queue_depth", 32)),
                getattr(config, "query_deadline_s", None))
        self._mesh = mesh
        # bounded metadata LRU + its lock: `hbam serve` drives one engine
        # from many client/dispatcher threads, so lookup/insert/evict of
        # the header+index table must be atomic
        import collections
        self._meta: "collections.OrderedDict[Tuple, _FileMeta]" = \
            collections.OrderedDict()
        self._meta_lock = threading.Lock()

    # -- metadata ------------------------------------------------------------

    def _mesh_or_make(self):
        with self._meta_lock:
            if self._mesh is None:
                from hadoop_bam_tpu.parallel.mesh import make_mesh
                self._mesh = make_mesh()
            return self._mesh

    def _file_meta(self, path: str) -> _FileMeta:
        ident = file_identity(path)
        with self._meta_lock:
            meta = self._meta.get(ident)
            if meta is not None:
                # true LRU: a hot file's header+index must never be the
                # one evicted at the 65th distinct file
                self._meta.move_to_end(ident)
                return meta
        kind = _sniff_kind(path)
        if kind == "bam":
            from hadoop_bam_tpu.formats.bamio import read_bam_header
            from hadoop_bam_tpu.split.bai import load_bai_for
            header, _ = read_bam_header(path)
            index = load_bai_for(path)
            if index is None:
                raise PlanError(
                    f"{path} has no .bai/.csi sidecar — region queries "
                    f"need a genomic index; build one with "
                    f"`hbam index --flavor bai {path}`")
            meta = _FileMeta(path, ident, kind, header, header.ref_names,
                             index)
        elif kind in ("vcf", "bcf"):
            from hadoop_bam_tpu.split.tabix import load_tabix_for
            header = self._variant_header(path, kind)
            index = load_tabix_for(path)
            if index is None:
                raise PlanError(
                    f"{path} has no .tbi sidecar — region queries need a "
                    f"tabix index; build one with "
                    f"`hbam index --flavor tbi {path}`")
            meta = _FileMeta(path, ident, kind, header, header.contigs,
                             index)
        else:  # cram
            from hadoop_bam_tpu.formats.cramio import read_cram_header
            header, _ = read_cram_header(path)
            index = self._cram_container_table(path, ident)
            meta = _FileMeta(path, ident, kind, header, header.ref_names,
                             index)
        with self._meta_lock:
            # two threads may have built the same meta concurrently; the
            # first insert wins so every caller shares one instance
            existing = self._meta.get(ident)
            if existing is not None:
                return existing
            if len(self._meta) >= 64:
                self._meta.pop(next(iter(self._meta)))
            self._meta[ident] = meta
        return meta

    def _variant_header(self, path: str, kind: str):
        from hadoop_bam_tpu.formats import bgzf
        from hadoop_bam_tpu.utils.seekable import scoped_byte_source
        with scoped_byte_source(path) as src:
            if kind == "bcf":
                from hadoop_bam_tpu.formats.bcfio import read_bcf_header
                header, _first, is_bgzf = read_bcf_header(src)
                if not is_bgzf:
                    raise PlanError(
                        f"{path} is a raw (non-BGZF) BCF — virtual-offset "
                        f"random access needs the BGZF container")
                return header
            from hadoop_bam_tpu.formats.vcf import read_vcf_header_text
            r = bgzf.BGZFReader(src)

            def read_chunk(off: int, size: int) -> bytes:
                r.seek_voffset(0)
                r.read(off)           # header-sized positions only
                return r.read(size)
            header, _ = read_vcf_header_text(read_chunk)
            return header

    def _cram_container_table(self, path: str, ident):
        """[(offset, end, ref_seq_id, start, span)] for every data
        container — the CRAM 'index': container headers carry their
        alignment coordinates, so one header walk (cached by file
        identity) answers region -> containers."""
        key = (ident, "cram-toc")
        hit = self.cache.get(key)
        if hit is not None:
            return hit
        from hadoop_bam_tpu.formats.cram import (
            ContainerHeader, FileDefinition,
        )
        from hadoop_bam_tpu.utils.seekable import scoped_byte_source
        table: List[Tuple[int, int, int, int, int]] = []
        # through as_byte_source, not a bare open(): the TOC walk reads
        # like any other engine read, so io_read_retries wraps it and
        # the install_chaos registry observes it (audited seam)
        with scoped_byte_source(path) as src:
            FileDefinition.from_bytes(src.pread(0, FileDefinition.SIZE))
            fsize = src.size
            pos = FileDefinition.SIZE
            while pos < fsize:
                chunk = src.pread(pos, 1 << 16)
                hdr, after = ContainerHeader.from_buffer(chunk, 0)
                if hdr.is_eof:
                    break
                end = pos + after + hdr.length
                table.append((pos, end, hdr.ref_seq_id, hdr.start,
                              hdr.span))
                pos = end
        table = table[1:]     # the first container is the SAM header
        self.cache.put(key, table, nbytes=48 * len(table))
        return table

    # -- resolution ----------------------------------------------------------

    def _resolve(self, meta: _FileMeta, region: str
                 ) -> Tuple[Interval, List[Tuple[int, int]]]:
        iv = resolve_interval(region, meta.ref_names)
        if iv.rname not in meta.ref_names:
            raise PlanError(
                f"region contig {iv.rname!r} is not in {meta.path}'s "
                f"reference dictionary")
        rid = meta.ref_names.index(iv.rname)
        beg0, end0 = iv.start - 1, iv.end
        if meta.kind == "bam":
            ranges = meta.index.query(rid, beg0, end0)
        elif meta.kind in ("vcf", "bcf"):
            ranges = meta.index.query(iv.rname, beg0, end0)
        else:  # cram: container coordinate overlap (multi-ref containers
            #    are always candidates; the predicate is exact)
            ranges = []
            for off, end, ref, start, span in meta.index:
                if ref == -2 or (ref == rid and start <= iv.end
                                 and start + max(span, 1) - 1 >= iv.start):
                    ranges.append((off, end))
        return iv, ranges

    def _coalesce(self, ranges: Sequence[Tuple[int, int]], kind: str
                  ) -> List[Tuple[int, int]]:
        """Merge overlapping/near-adjacent (start, end) ranges, bounded by
        ``query_chunk_bytes`` compressed per chunk (a single oversized
        range stays one chunk — splitting it would need record-aligned
        interior offsets the index does not provide).

        Gap/size arithmetic is in COMPRESSED bytes: BAM/VCF/BCF ranges
        are packed virtual offsets (compressed offset = value >> 16)
        while CRAM container ranges are already raw byte offsets — the
        shift must differ or CRAM gaps would read 65536x too small and
        whole-file stretches of unrelated containers would coalesce."""
        shift = 0 if kind == "cram" else 16
        cap_c = max(1 << 16,
                    int(getattr(self.config, "query_chunk_bytes", 1 << 20)))
        out: List[Tuple[int, int]] = []
        for s, e in sorted(set(ranges)):
            if out:
                ps, pe = out[-1]
                gap_c = (s >> shift) - (pe >> shift)
                size_c = (e >> shift) - (ps >> shift)
                if s <= pe or (gap_c <= _COALESCE_GAP_C
                               and size_c <= cap_c):
                    if e > pe:
                        out[-1] = (ps, e)
                    continue
            out.append((s, e))
        return out

    # -- chunk decode (cache + retry) ---------------------------------------

    def chunk_key(self, meta: _FileMeta, s: int, e: int) -> Tuple:
        return (meta.ident, meta.kind, s, e)

    def _chunk(self, meta: _FileMeta, s: int, e: int) -> Dict[str, object]:
        """Decoded chunk columns: {'rid','pos1','end1' int32 arrays,
        'records' materializer state} — cached by (identity, range)
        through the SINGLE-FLIGHT cache path, so many serve clients
        landing on the same cold chunk share one decode."""
        return self.cache.get_or_compute(
            self.chunk_key(meta, s, e),
            lambda: self._compute_chunk(meta, s, e))

    def _compute_chunk(self, meta: _FileMeta, s: int, e: int):
        """One cold chunk decode, compiled to a plan: the executor owns
        ``decode_with_retry`` and the query decode metrics taxonomy;
        this engine owns only the per-format column decoders and the
        cache tiering above."""
        from hadoop_bam_tpu.plan import builders
        from hadoop_bam_tpu.plan import executor as plan_executor

        plan = builders.query_chunk_plan(meta.path, meta.kind, s, e)
        return plan_executor.execute(
            plan, config=self.config,
            decode_fn=lambda sp: self._decode_chunk(meta, sp))

    def _decode_chunk(self, meta: _FileMeta,
                      span: FileVirtualSpan) -> Dict[str, object]:
        if meta.kind == "bam":
            return self._decode_bam_chunk(meta, span)
        if meta.kind == "vcf":
            return self._decode_vcf_chunk(meta, span)
        if meta.kind == "bcf":
            return self._decode_bcf_chunk(meta, span)
        return self._decode_cram_chunk(meta, span)

    def _decode_bam_chunk(self, meta, span) -> Dict[str, object]:
        from hadoop_bam_tpu.split.planners import read_bam_span
        batch = read_bam_span(meta.path, span, header=meta.header)
        n = len(batch)
        pos1 = batch.pos.astype(np.int64) + 1
        end1 = pos1 + np.maximum(batch.reference_span(), 1) - 1
        return {
            "rid": batch.refid.astype(np.int32),
            "pos1": np.minimum(pos1, _I32_MAX).astype(np.int32),
            "end1": np.minimum(end1, _I32_MAX).astype(np.int32),
            "batch": batch,
            "n": n,
            "nbytes": int(batch.data.nbytes) + 16 * n + 64,
        }

    def _variant_columns(self, meta, records) -> Dict[str, object]:
        rid_of = {c: i for i, c in enumerate(meta.ref_names)}
        n = len(records)
        rid = np.fromiter((rid_of.get(r.chrom, -1) for r in records),
                          np.int32, n)
        pos1 = np.fromiter((r.pos for r in records), np.int64, n)
        end1 = pos1 + np.fromiter((max(r.rlen, 1) for r in records),
                                  np.int64, n) - 1
        return {
            "rid": rid,
            "pos1": np.minimum(pos1, _I32_MAX).astype(np.int32),
            "end1": np.minimum(end1, _I32_MAX).astype(np.int32),
            "records": records,
            "n": n,
        }

    def _decode_vcf_chunk(self, meta, span) -> Dict[str, object]:
        from hadoop_bam_tpu.config import ValidationStringency
        from hadoop_bam_tpu.formats import bgzf
        from hadoop_bam_tpu.formats.vcf import VcfRecord
        from hadoop_bam_tpu.utils.seekable import scoped_byte_source
        records: List[VcfRecord] = []
        nbytes = 0
        with scoped_byte_source(meta.path) as src:
            r = bgzf.BGZFReader(src)
            r.seek_voffset(span.start_voffset)
            text = r.read_to_voffset(span.end_voffset)
            nbytes = len(text)
            for line in text.split(b"\n"):
                if not line or line[:1] == b"#":
                    continue
                try:
                    records.append(VcfRecord.from_line(line.decode()))
                except Exception:
                    if (self.config.validation_stringency
                            is ValidationStringency.STRICT):
                        raise
        out = self._variant_columns(meta, records)
        out["nbytes"] = 2 * nbytes + 64
        return out

    def _decode_bcf_chunk(self, meta, span) -> Dict[str, object]:
        from hadoop_bam_tpu.formats import bgzf
        from hadoop_bam_tpu.formats.bcf import BCFRecordCodec
        from hadoop_bam_tpu.utils.seekable import scoped_byte_source
        codec = BCFRecordCodec(meta.header)
        records = []
        nbytes = 0
        with scoped_byte_source(meta.path) as src:
            r = bgzf.BGZFReader(src)
            r.seek_voffset(span.start_voffset)
            while r.voffset() < span.end_voffset:
                head = r.read(8)
                if len(head) < 8:
                    break
                l_shared, l_indiv = struct.unpack("<II", head)
                body = r.read(l_shared + l_indiv)
                rec, _ = codec.decode(head + body, 0)
                records.append(rec)
                nbytes += 8 + l_shared + l_indiv
        out = self._variant_columns(meta, records)
        out["nbytes"] = 3 * nbytes + 64
        return out

    def _decode_cram_chunk(self, meta, span) -> Dict[str, object]:
        from hadoop_bam_tpu.split.cram_planner import read_cram_span
        from hadoop_bam_tpu.split.spans import FileByteSpan
        ref_source = None
        if self.config.cram_reference_source_path:
            from hadoop_bam_tpu.formats.cram_decode import (
                FastaReferenceSource,
            )
            ref_source = FastaReferenceSource(
                self.config.cram_reference_source_path)
        bspan = FileByteSpan(meta.path, span.start_voffset,
                             span.end_voffset)
        records = read_cram_span(meta.path, bspan, header=meta.header,
                                 ref_source=ref_source)
        rid_of = {c: i for i, c in enumerate(meta.ref_names)}
        n = len(records)
        rid = np.fromiter((rid_of.get(r.rname, -1) for r in records),
                          np.int32, n)
        pos1 = np.fromiter((r.pos for r in records), np.int64, n)
        spans = np.fromiter(
            (max(_ref_span_of_cigar(r.cigar, r.seq), 1) for r in records),
            np.int64, n)
        return {
            "rid": rid,
            "pos1": np.minimum(pos1, _I32_MAX).astype(np.int32),
            "end1": np.minimum(pos1 + spans - 1, _I32_MAX).astype(np.int32),
            "records": records,
            "n": n,
            "nbytes": sum(len(r.seq) + len(r.qual) + 64 for r in records)
            + 64,
        }

    @staticmethod
    def _materialize(meta: _FileMeta, value: Dict[str, object], row: int):
        if meta.kind == "bam":
            from hadoop_bam_tpu.formats.sam import SamRecord
            return SamRecord.from_line(value["batch"].to_sam_line(row))
        return value["records"][row]

    # -- serving -------------------------------------------------------------

    def _prepare(self, requests: Sequence[QueryRequest], deadline: Deadline):
        """Resolve + decode: returns (stream tuples, host refs,
        per-request candidate counts, interval list)."""
        tuples: List[Tuple[np.ndarray, ...]] = []
        refs: List[Tuple[int, _FileMeta, Dict[str, object]]] = []
        cand_counts = [0] * len(requests)
        ivs: List[Interval] = [None] * len(requests)
        # per-request deadline overrides ride alongside the batch one,
        # ANCHORED at the batch's enqueue instant (rebudget): admission
        # wait counts against them, matching query.latency_s
        req_deadlines = [
            None if r.deadline_s is None
            else deadline.rebudget(r.deadline_s)
            for r in requests]

        def check(i: int, what: str) -> None:
            deadline.check(what)
            if req_deadlines[i] is not None:
                req_deadlines[i].check(f"{what} (request {i})")

        # group by path, preserving first-appearance order
        by_path: Dict[str, List[int]] = {}
        for i, req in enumerate(requests):
            by_path.setdefault(req.path, []).append(i)

        with METRICS.span("query.resolve_wall", requests=len(requests)):
            plans = []           # (req_idx, meta, iv, ranges)
            # ranges accumulate BY FILE IDENTITY, not by path string —
            # two spellings of the same file (relative vs absolute)
            # resolve to one identity, and a per-path assignment here
            # would overwrite the earlier spelling's ranges
            ranges_by_ident: Dict[Tuple, List[Tuple[int, int]]] = {}
            kind_of_ident: Dict[Tuple, str] = {}
            for path, req_idxs in by_path.items():
                deadline.check("query resolve")
                meta = self._file_meta(path)
                acc = ranges_by_ident.setdefault(meta.ident, [])
                kind_of_ident[meta.ident] = meta.kind
                for i in req_idxs:
                    METRICS.count("query.requests")
                    check(i, "query resolve")
                    iv, ranges = self._resolve(meta, requests[i].region)
                    ivs[i] = iv
                    plans.append((i, meta, iv, ranges))
                    acc.extend(ranges)
            chunk_sets = {
                ident: self._coalesce(rs, kind_of_ident[ident])
                for ident, rs in ranges_by_ident.items()}

        for i, meta, iv, ranges in plans:
            check(i, "query decode")
            if not ranges:
                continue
            rid = np.int32(meta.ref_names.index(iv.rname))
            iv_beg = np.int32(min(iv.start, int(_I32_MAX)))
            iv_end = np.int32(min(iv.end, int(_I32_MAX)))
            lo = min(s for s, _ in ranges)
            hi = max(e for _, e in ranges)
            for s, e in chunk_sets[meta.ident]:
                if e <= lo or s >= hi:
                    continue             # chunk serves other requests only
                check(i, "query decode")
                value = self._chunk(meta, s, e)
                n = int(value["n"])
                if not n:
                    continue
                cand_counts[i] += n
                METRICS.count("query.rows_scanned", n)
                tuples.append((
                    value["rid"], value["pos1"], value["end1"],
                    np.full(n, rid, np.int32),
                    np.full(n, iv_beg, np.int32),
                    np.full(n, iv_end, np.int32),
                    np.full(n, i, np.int32),
                ))
                refs.append((i, meta, value))
        return tuples, refs, cand_counts, ivs

    def _stream_groups(self, tuples, deadline: Deadline) -> Iterator[Dict]:
        """Feed the candidate tuples through the shared FeedPipeline and
        yield device batches {rid,pos,end,req,keep,n_records}."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        from hadoop_bam_tpu.parallel.staging import FeedPipeline, TileSpec

        if not tuples:
            return
        mesh = self._mesh_or_make()
        n_dev = int(np.prod(mesh.devices.shape))
        cap = int(getattr(self.config, "query_tile_records", 8192))
        sharding = NamedSharding(mesh, P("data"))
        step = make_overlap_step(mesh)
        fp = FeedPipeline(n_dev, cap,
                          [TileSpec((), np.int32)] * len(TILE_COLUMNS),
                          block_n=64, config=self.config,
                          name="query")

        def emit(arrays, counts) -> Dict:
            deadline.check("query filter")
            # ONE batched device_put for all eight leaves: per-leaf puts
            # were ~60% of the measured warm-path wall (8 python
            # dispatches per group), and the serving path lives on
            # per-query latency
            dev = jax.device_put((*arrays, counts), sharding)
            keep = step(*dev)
            # the dict doubles as the ring slot's in-flight handle
            return {"rid": dev[0], "pos": dev[1], "end": dev[2],
                    "req": dev[6], "keep": keep, "n_records": dev[7]}

        with METRICS.span("query.filter_wall"):
            yield from fp.stream(iter(tuples), emit)

    def tensor_batches(self, requests: Sequence[QueryRequest],
                       deadline_s: Optional[float] = None) -> Iterator[Dict]:
        """Device-batch surface (api.query_regions): yields sharded
        ``{rid,pos,end,req,keep,n_records}`` groups where ``keep`` is the
        mesh-computed interval-overlap mask and ``req`` maps each row back
        to its request index."""
        requests = [r if isinstance(r, QueryRequest) else QueryRequest(*r)
                    for r in requests]
        import time

        from hadoop_bam_tpu.obs.context import ensure_trace
        t0 = time.perf_counter()
        deadline = None
        try:
            # one trace per query batch (joined when the CLI / serve
            # tier already minted one): every span below — resolve,
            # pool-side chunk decode, staging dispatch — shares its id
            with ensure_trace(op="query.batch", deadline_s=deadline_s), \
                    self.scheduler.admit(deadline_s) as deadline:
                tuples, _refs, _counts, _ivs = self._prepare(requests,
                                                             deadline)
                yield from self._stream_groups(tuples, deadline)
        finally:
            # end-to-end batch latency (admission wait included): on a
            # single-request batch this IS the per-query latency the
            # bench's p50/p99 columns report
            METRICS.observe("query.latency_s", time.perf_counter() - t0)
            # one tick per batch whose deadline was missed — whether it
            # aborted mid-serve (check() already booked it) or merely
            # finished late (booked here)
            if deadline is not None and deadline.expired:
                deadline.book_miss()

    def query_records(self, requests: Sequence[QueryRequest],
                      deadline_s: Optional[float] = None
                      ) -> List[QueryResult]:
        """Exact per-request record lists, index-pruned + mesh-filtered.
        Results keep file order within each request and request order
        across the batch."""
        requests = [r if isinstance(r, QueryRequest) else QueryRequest(*r)
                    for r in requests]
        import time

        from hadoop_bam_tpu.obs.context import ensure_trace
        t_start = time.perf_counter()
        batch_deadline = None
        try:
            with ensure_trace(op="query.batch", deadline_s=deadline_s), \
                    self.scheduler.admit(deadline_s) as deadline:
                batch_deadline = deadline
                tuples, refs, cand_counts, _ivs = self._prepare(requests,
                                                                deadline)
                mesh = self._mesh_or_make()
                n_dev = int(np.prod(mesh.devices.shape))
                flat_keep: List[np.ndarray] = []
                for out in self._stream_groups(tuples, deadline):
                    counts = np.asarray(out["n_records"])
                    keep = np.asarray(out["keep"])
                    for dev in range(n_dev):
                        flat_keep.append(keep[dev, :int(counts[dev])])
        finally:
            if batch_deadline is not None and batch_deadline.expired:
                batch_deadline.book_miss()
        mask = (np.concatenate(flat_keep) if flat_keep
                else np.zeros(0, bool))
        results = [QueryResult(req, [], cand_counts[i])
                   for i, req in enumerate(requests)]
        base = 0
        for req_idx, meta, value in refs:
            n = int(value["n"])
            rows = np.flatnonzero(mask[base:base + n])
            base += n
            recs = results[req_idx].records
            for row in rows:
                recs.append(self._materialize(meta, value, int(row)))
        METRICS.count("query.rows_matched",
                      sum(len(r.records) for r in results))
        METRICS.observe("query.latency_s", time.perf_counter() - t_start)
        return results

    def stats(self) -> Dict[str, float]:
        return self.cache.stats()
