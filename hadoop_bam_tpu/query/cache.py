"""Bounded LRU chunk cache keyed by file identity + virtual-offset range.

The warm path of the query engine is this cache: a zipf-skewed region
workload hits the same hot BGZF chunks over and over, and re-inflating
them per request would make every query pay the cold-path decode.  Keys
ALWAYS include the file's identity — (absolute path, size, mtime_ns) —
so replacing a file on disk can never serve stale decoded chunks (the
lint rule QE501 flags raw-path-only keys in this package).  Eviction is
by byte budget, strict LRU; counters ride utils/metrics.py
(``query.cache_hits`` / ``query.cache_misses`` / ``query.cache_evictions``)
so the bench can report hit rates without private hooks.
"""
from __future__ import annotations

import concurrent.futures as cf
import os
import threading
from collections import OrderedDict
from typing import Callable, Dict, Hashable, Optional, Tuple

from hadoop_bam_tpu.utils.metrics import METRICS

FileIdentity = Tuple[str, int, int]          # (abspath, size, mtime_ns)


def file_identity(path: "str | os.PathLike") -> FileIdentity:
    """(abspath, size, mtime_ns) of a file — the cache-key component that
    makes chunk entries self-invalidating: rewrite the file and every key
    derived from the old identity simply never matches again.

    A missing path raises ``FileNotFoundError`` (PLAN class in the error
    taxonomy: a bad path is configuration, never retried or skipped)."""
    p = os.path.abspath(os.fspath(path))
    st = os.stat(p)
    return (p, int(st.st_size), int(st.st_mtime_ns))


class ChunkCache:
    """Thread-safe byte-budgeted LRU of decoded chunks.

    Values are opaque to the cache; the caller supplies ``nbytes`` (the
    decoded footprint) on ``put``.  An entry larger than the whole budget
    is not admitted at all — counting it would immediately evict
    everything else for a value that can never be re-used before it is
    evicted itself."""

    def __init__(self, byte_budget: int = 256 << 20):
        if byte_budget <= 0:
            from hadoop_bam_tpu.utils.errors import PlanError
            raise PlanError(
                f"query cache byte budget must be positive, got "
                f"{byte_budget}")
        self.byte_budget = int(byte_budget)
        self._lock = threading.Lock()
        self._entries: "OrderedDict[Hashable, Tuple[object, int]]" = \
            OrderedDict()
        self._bytes = 0
        # per-INSTANCE counters (stats() must describe THIS cache even
        # with several engines alive); the METRICS ticks below are the
        # process-wide view for dashboards
        self._hits = 0
        self._misses = 0
        self._evictions = 0
        self._coalesced = 0
        # single-flight table: key -> Future of the one in-progress
        # compute; entries are ALWAYS removed in the leader's finally
        self._inflight: Dict[Hashable, cf.Future] = {}

    def get(self, key: Hashable):
        """Cached value or None; ticks query.cache_hits / cache_misses."""
        with self._lock:
            hit = self._entries.get(key)
            if hit is None:
                self._misses += 1
                METRICS.count("query.cache_misses")
                return None
            self._entries.move_to_end(key)
            self._hits += 1
            METRICS.count("query.cache_hits")
            return hit[0]

    def put(self, key: Hashable, value, nbytes: int) -> None:
        nbytes = max(0, int(nbytes))
        if nbytes > self.byte_budget:
            METRICS.count("query.cache_oversize")
            return
        with self._lock:
            old = self._entries.pop(key, None)
            if old is not None:
                self._bytes -= old[1]
            self._entries[key] = (value, nbytes)
            self._bytes += nbytes
            while self._bytes > self.byte_budget and len(self._entries) > 1:
                _k, (_v, nb) = self._entries.popitem(last=False)
                self._bytes -= nb
                self._evictions += 1
                METRICS.count("query.cache_evictions")
            # a single entry can never exceed the budget (guard above),
            # so the loop always terminates with _bytes <= byte_budget

    def contains(self, key: Hashable) -> bool:
        """Counter-free membership probe (cached OR currently being
        computed) — the prefetcher's dedup check, which must not distort
        hit/miss stats with its speculative lookups."""
        with self._lock:
            return key in self._entries or key in self._inflight

    def get_or_compute(self, key: Hashable,
                       compute: Callable[[], Tuple[object, Optional[int]]]):
        """Single-flight lookup: a hit returns immediately; on a miss
        exactly ONE caller (the leader) runs ``compute`` while concurrent
        callers for the same key block on its result instead of
        duplicating the decode (the thundering-herd shape of a zipf-hot
        region arriving from many serve clients at once).

        ``compute`` returns ``(value, nbytes)``; ``nbytes=None`` means
        serve-but-don't-cache (the quarantined-chunk healing path).  A
        leader exception propagates to every waiter — the waiters asked
        for the same bytes and would have failed identically."""
        with self._lock:
            hit = self._entries.get(key)
            if hit is not None:
                self._entries.move_to_end(key)
                self._hits += 1
                METRICS.count("query.cache_hits")
                return hit[0]
            fut = self._inflight.get(key)
            if fut is None:
                fut = self._inflight[key] = cf.Future()
                leader = True
                self._misses += 1
                METRICS.count("query.cache_misses")
            else:
                leader = False
                self._coalesced += 1
                METRICS.count("query.cache_coalesced")
        if not leader:
            return fut.result()
        try:
            value, nbytes = compute()
            if nbytes is not None:
                self.put(key, value, nbytes)
        except BaseException as e:
            fut.set_exception(e)
            raise
        else:
            fut.set_result(value)
            return value
        finally:
            # the flight entry ALWAYS clears and the future ALWAYS
            # resolves, whatever failed above — a leaked entry would
            # park every future caller for this key on a dead future
            with self._lock:
                self._inflight.pop(key, None)

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            self._bytes = 0

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    @property
    def bytes_used(self) -> int:
        with self._lock:
            return self._bytes

    def stats(self) -> Dict[str, float]:
        """THIS cache's hit/miss/eviction counters and occupancy — what
        ``bench.py`` reports as the region query row's hit rate.  (The
        process-wide ``query.cache_*`` METRICS counters aggregate over
        every cache; a multi-engine server must not have one engine's
        traffic distort another's stats.)"""
        with self._lock:
            total = self._hits + self._misses
            return {
                "entries": len(self._entries),
                "bytes": self._bytes,
                "byte_budget": self.byte_budget,
                "hits": self._hits,
                "misses": self._misses,
                "evictions": self._evictions,
                "coalesced": self._coalesced,
                "hit_rate": (self._hits / total) if total else 0.0,
            }
