from hadoop_bam_tpu.tools.cli import main
import sys

sys.exit(main())
