import sys

from hadoop_bam_tpu.tools.cli import main

if __name__ == "__main__":
    sys.exit(main())
