"""Cohort manifests: the named set of single-sample inputs a join runs over.

A manifest is a JSON document::

    {"samples": [{"id": "NA00001", "path": "calls/NA00001.bcf"},
                 {"id": "NA00002", "path": "calls/NA00002.vcf.gz"}]}

or, minimally, a bare list of paths (sample ids default to the file
stem).  Relative paths resolve against the manifest file's directory,
so a manifest can travel with its call set.

The manifest's **identity** is what the serve tier keys device-resident
dosage tiles on: the manifest path plus every input's
``(abspath, size, mtime_ns)`` file identity, digested — rewrite any
sample file (or the manifest) and every cached cohort tile derived from
the old identity simply never matches again, the same self-invalidation
contract as ``query.cache.file_identity``.

This is a policy boundary module (ET3xx lint scope): a malformed or
missing manifest is run CONFIGURATION — ``PlanError``, never retried,
never quarantined.  Quarantine is reserved for sample files whose
*bytes* fault mid-join (cohort/join.py).
"""
from __future__ import annotations

import dataclasses
import hashlib
import json
import os
from typing import Dict, List, Optional, Sequence, Tuple, Union

from hadoop_bam_tpu.utils.errors import PlanError


@dataclasses.dataclass(frozen=True)
class CohortSample:
    """One input of the cohort: a sample id and its single-sample
    VCF/BCF path (any container ``api.dispatch`` recognises)."""
    sample_id: str
    path: str


def _default_id(path: str) -> str:
    base = os.path.basename(path)
    for suffix in (".vcf.gz", ".vcf.bgz", ".vcf", ".bcf"):
        if base.lower().endswith(suffix):
            return base[:-len(suffix)]
    return os.path.splitext(base)[0]


@dataclasses.dataclass
class CohortManifest:
    """The resolved sample set plus (after a build) quarantine records."""

    samples: List[CohortSample]
    path: Optional[str] = None          # manifest file, when loaded from one
    # sample_id -> reason string, recorded by the join when an input
    # quarantines (sentinel-filled column); merged, never reset, so a
    # caller holding the manifest sees every build's casualties
    quarantined: Dict[str, str] = dataclasses.field(default_factory=dict)

    @property
    def n_samples(self) -> int:
        return len(self.samples)

    @property
    def sample_ids(self) -> List[str]:
        return [s.sample_id for s in self.samples]

    def identity(self) -> Tuple[str, int, str]:
        """(anchor path, n_samples, digest of every input's file
        identity) — the device-tile cache key component.  Raises
        ``FileNotFoundError`` (PLAN class) for a missing input: a bad
        path is configuration."""
        h = hashlib.sha256()
        for s in self.samples:
            p = os.path.abspath(s.path)
            st = os.stat(p)
            h.update(f"{s.sample_id}\0{p}\0{st.st_size}\0"
                     f"{st.st_mtime_ns}\n".encode())
        anchor = (os.path.abspath(self.path) if self.path
                  else "<inline-manifest>")
        return (anchor, len(self.samples), h.hexdigest()[:32])

    def record_quarantine(self, sample_id: str, reason: str) -> None:
        self.quarantined.setdefault(sample_id, reason)

    def to_dict(self) -> Dict:
        out: Dict = {"samples": [{"id": s.sample_id, "path": s.path}
                                 for s in self.samples]}
        if self.quarantined:
            out["quarantined"] = dict(self.quarantined)
        return out

    @classmethod
    def from_doc(cls, doc: Union[Dict, Sequence],
                 base_dir: Optional[str] = None,
                 path: Optional[str] = None) -> "CohortManifest":
        """Build from a parsed JSON document (dict with "samples", or a
        bare list of path strings / sample dicts)."""
        if isinstance(doc, dict):
            entries = doc.get("samples")
            if entries is None:
                raise PlanError(
                    'cohort manifest object needs a "samples" list')
        else:
            entries = doc
        if not isinstance(entries, (list, tuple)) or not entries:
            raise PlanError("cohort manifest needs a non-empty sample list")
        samples: List[CohortSample] = []
        seen = set()
        for i, e in enumerate(entries):
            if isinstance(e, str):
                spath, sid = e, None
            elif isinstance(e, dict) and "path" in e:
                spath = str(e["path"])
                sid = e.get("id")
            else:
                raise PlanError(
                    f"cohort manifest sample #{i} must be a path string or "
                    f'an object with "path" (and optional "id"), got '
                    f"{type(e).__name__}")
            if base_dir is not None and not os.path.isabs(spath):
                spath = os.path.join(base_dir, spath)
            sid = str(sid) if sid is not None else _default_id(spath)
            if sid in seen:
                raise PlanError(
                    f"cohort manifest sample id {sid!r} appears twice — "
                    f"ids key the [variants, samples] columns and must be "
                    f"unique")
            seen.add(sid)
            samples.append(CohortSample(sample_id=sid, path=spath))
        return cls(samples=samples, path=path)


def load_manifest(path: str) -> CohortManifest:
    """Read and resolve a manifest JSON file (PLAN class on anything
    malformed — a bad manifest is configuration, not data)."""
    try:
        with open(path, "r", encoding="utf-8") as f:
            doc = json.load(f)
    except FileNotFoundError:
        raise            # already PLAN-classified by the taxonomy
    except (json.JSONDecodeError, UnicodeDecodeError) as e:
        raise PlanError(f"cohort manifest {path!r} is not valid JSON: {e}")
    return CohortManifest.from_doc(doc, base_dir=os.path.dirname(
        os.path.abspath(path)), path=path)


def as_manifest(source: Union[str, CohortManifest, Sequence[str]]
                ) -> CohortManifest:
    """Accept a manifest object, a manifest JSON path, or a bare list of
    sample file paths — every cohort entry point's first line."""
    if isinstance(source, CohortManifest):
        return source
    if isinstance(source, (str, os.PathLike)):
        return load_manifest(os.fspath(source))
    return CohortManifest.from_doc(list(source))
