"""CohortDataset: the [variants, samples] tensor surface over a manifest.

The cohort twin of ``api.vcf_dataset.VcfDataset``: where that class
tiles ONE file's variants, this one streams k single-sample files
through the position join (cohort/join.py) and tiles the JOINED columns
onto the mesh through the same shared ``variant_feed``/``FeedPipeline``
machinery — so sentinel padding (-1 dosage / NaN qual), ring-slot
reuse, and the in-flight transfer discipline are all inherited, not
re-implemented.
"""
from __future__ import annotations

from typing import Dict, Iterator, List, Optional, Union

import numpy as np

from hadoop_bam_tpu.config import DEFAULT_CONFIG, HBamConfig
from hadoop_bam_tpu.cohort.join import (
    _JoinState, build_contig_space, guarded_sites, iter_joined_chunks,
    iter_sample_sites,
)
from hadoop_bam_tpu.cohort.manifest import CohortManifest, as_manifest


class CohortDataset:
    """Mesh-tiled access to a cohort of single-sample VCF/BCF files.

    ``tensor_batches`` yields device-resident dicts sharded over the
    mesh's data axis::

        chrom    int32  [n_dev, cap]
        pos      int32  [n_dev, cap]
        n_allele int16  [n_dev, cap]
        dosage   int8   [n_dev, cap, samples_pad]   (-1 missing)
        qual     float32[n_dev, cap, samples_pad]   (NaN missing)
        n_records int32 [n_dev]

    Rows beyond a shard's ``n_records`` carry the missing-value
    sentinels uniformly (dosage -1, qual NaN, 0 elsewhere) — the PR-4
    convention, enforced by the shared TileSpec pads.  Column ``j`` is
    ``manifest.samples[j]``; a sample whose input quarantined mid-join
    is sentinel-filled from the fault onward and listed in
    ``self.manifest.quarantined``.
    """

    def __init__(self, source: Union[str, CohortManifest, List[str]],
                 config: HBamConfig = DEFAULT_CONFIG,
                 journal_path: Optional[str] = None):
        from hadoop_bam_tpu.api.vcf_dataset import VcfDataset
        from hadoop_bam_tpu.parallel.variant_pipeline import VariantGeometry
        from hadoop_bam_tpu.resilience import file_ident, registry
        from hadoop_bam_tpu.utils.errors import (
            CorruptDataError, PLAN, classify_error,
        )
        from hadoop_bam_tpu.utils.metrics import METRICS

        self.config = config
        self.journal_path = journal_path
        self._journal_live = False     # one journaled join at a time
        self.manifest = as_manifest(source)
        quarantine = bool(getattr(config, "cohort_quarantine_inputs",
                                  True))
        # header reads: a MISSING path is configuration (PLAN, raises);
        # a file whose header bytes are corrupt is data — under the
        # quarantine policy its column goes sentinel before the join
        # even starts (the slot is kept as None so sample indices stay
        # stable)
        self._datasets: List = []
        for s in self.manifest.samples:
            try:
                self._datasets.append(VcfDataset(s.path, config))
            except Exception as e:  # noqa: BLE001 — classified below
                if classify_error(e) == PLAN or not quarantine:
                    raise
                registry().domain("cohort", "input", file_ident(s.path),
                                  config=config).record_failure(e)
                self.manifest.record_quarantine(
                    s.sample_id, f"{type(e).__name__}: {e}")
                METRICS.count("cohort.samples_quarantined")
                self._datasets.append(None)
        n_dead = sum(1 for d in self._datasets if d is None)
        max_frac = float(getattr(config, "cohort_max_quarantine_fraction",
                                 0.5))
        if n_dead / max(1, self.manifest.n_samples) > max_frac:
            raise CorruptDataError(
                f"cohort build: {n_dead}/{self.manifest.n_samples} "
                f"sample inputs quarantined at header read — over the "
                f"cohort_max_quarantine_fraction={max_frac} circuit")
        self.contigs = build_contig_space(
            [ds.header for ds in self._datasets if ds is not None])
        self._cmap = {c: i for i, c in enumerate(self.contigs)}
        self.geometry = VariantGeometry(n_samples=self.manifest.n_samples)

    @property
    def n_samples(self) -> int:
        return self.manifest.n_samples

    @property
    def sample_ids(self) -> List[str]:
        return self.manifest.sample_ids

    def contig_index(self, name: str) -> int:
        return self._cmap.get(name, -1)

    # -- host-side joined columns (the serve tier + oracle surface) ----------

    def site_chunks(self) -> Iterator[Dict[str, np.ndarray]]:
        """Stream the joined cohort as host column chunks (up to
        ``config.cohort_chunk_sites`` rows each) — the input of both the
        mesh feed below and the serve tier's tile builder.

        With a ``journal_path`` the join is CRASH-SAFE (jobs/): every
        produced chunk persists to ``<journal>.chunks/chunk-NNNNN.npz``
        and commits a journaled unit (size+CRC+last site key); a
        resumed join replays the verified chunks from disk — identical
        bytes, zero re-join/re-harmonize work — then continues the live
        merge from the last committed key.  Input records are still
        re-streamed for the continuation (a k-way merge needs its
        cursors), so the savings are the join/harmonize/pack work and,
        on a finished job, the entire decode.  A quarantine that was
        caused by a TRANSIENT fault may heal on resume: the journaled
        chunks keep their sentinel columns, the live suffix carries
        real data — recorded as ``quarantine`` events either way."""
        if self.journal_path is not None and self._journal_live:
            # the guard must fire BEFORE stream construction: merely
            # building streams resets every sample's span cursor, which
            # would corrupt the live iteration's reads even if the
            # journal itself were protected further down
            from hadoop_bam_tpu.utils.errors import PlanError
            raise PlanError(
                f"a journaled join over {self.journal_path} is already "
                f"in progress on this dataset — close (exhaust) the "
                f"prior site_chunks() iterator before starting another")
        state = _JoinState(
            self.manifest.n_samples,
            float(getattr(self.config, "cohort_max_quarantine_fraction",
                          0.5)))
        # header-time casualties count toward the fraction circuit
        state.quarantined = sum(1 for d in self._datasets if d is None)
        streams = []
        for ds, sample in zip(self._datasets, self.manifest.samples):
            if ds is None:
                streams.append(iter(()))   # quarantined at header read
                continue
            # every join starts from the file's FIRST span: records()
            # only auto-resets after a fully-exhausted iteration, and a
            # join abandoned mid-stream (early tensor_batches break, a
            # fraction-circuit trip) would otherwise silently RESUME
            # mid-file on the next call and serve a truncated cohort
            ds._next_span = 0
            sites = iter_sample_sites(ds.records(), self._cmap)
            streams.append(guarded_sites(
                sites, sample.sample_id, sample.path, self.manifest,
                state, self.config))
        if self.journal_path is None:
            return iter_joined_chunks(self.manifest, streams,
                                      self.geometry.samples_pad,
                                      self.config)
        return self._journaled_chunks(streams)

    def _journaled_chunks(self, streams) -> Iterator[Dict[str,
                                                          np.ndarray]]:
        """The journal-aware wrapper around ``iter_joined_chunks``
        (``site_chunks`` docstring): replay verified chunks, sweep the
        in-flight chunk's debris, continue past the last committed
        key, commit each fresh chunk before handing it downstream."""
        import os

        from hadoop_bam_tpu.jobs import journal as jj
        from hadoop_bam_tpu.jobs.runner import (
            COHORT_FINGERPRINT_FIELDS, plan_journal_params,
        )
        from hadoop_bam_tpu.utils.metrics import METRICS

        # reentrancy is refused at the top of site_chunks (two live
        # journaled iterations = two writers on one journal, the exact
        # shape replay classifies as corruption; and the second
        # resume's sweep could unlink chunks the first just committed)
        chunks_dir = os.path.abspath(self.journal_path) + ".chunks"

        def load(u):
            with np.load(u["path"]) as z:
                return {kk: z[kk] for kk in ("chrom", "pos", "n_allele",
                                             "dosage", "qual")}

        def gen():
            # EVERYTHING — journal open, lock, replay — happens lazily
            # at first next(): a generator that is created but never
            # started runs no body, so eager setup would leave the
            # dataset permanently locked with an open journal fd
            if self._journal_live:
                from hadoop_bam_tpu.utils.errors import PlanError
                raise PlanError(
                    f"a journaled join over {self.journal_path} is "
                    f"already in progress on this dataset")
            self._journal_live = True
            jr = None
            try:
                anchor, _k, digest = self.manifest.identity()
                jr, state = jj.JobJournal.resume(
                    self.journal_path, kind="cohort_join",
                    inputs=[(anchor or "<inline-manifest>", digest)],
                    output=None,
                    fingerprint=jj.config_fingerprint(
                        self.config, COHORT_FINGERPRINT_FIELDS),
                    config_values=jj.fingerprint_values(
                        self.config, COHORT_FINGERPRINT_FIELDS),
                    # the plan digest rides the params (the IR-level
                    # twin of the spill sort's span plan_digest): a
                    # resume whose compiled plan differs — changed
                    # manifest identity, changed unit-partitioning
                    # knobs — refuses instead of mis-stitching chunks
                    params=plan_journal_params(self.plan(), {
                        "manifest":
                            (os.path.abspath(self.manifest.path)
                             if self.manifest.path else None)}),
                    fsync=bool(getattr(self.config, "journal_fsync",
                                       True)))
                replayed = []
                if state is not None:
                    while True:
                        u = state.unit("chunk", len(replayed))
                        if u is None or not jj.verify_artifact(
                                u.get("path", ""), u.get("size", -1),
                                u.get("crc", "")):
                            break
                        replayed.append(u)
                    jj.sweep_unrecorded(
                        chunks_dir, [u["path"] for u in replayed],
                        counter="jobs.stale_chunks_swept")
                # finished job with every chunk intact: pure replay,
                # the input streams are never touched (zero decode)
                replay_only = (state is not None
                               and state.done is not None
                               and int(state.done.get("chunks", -1))
                               == len(replayed))
                last_key = None
                for u in replayed:
                    METRICS.count("jobs.chunks_replayed")
                    last_key = (int(u.get("key_hi", 0)),
                                int(u.get("key_lo", 0)))
                    yield load(u)
                if replay_only:
                    METRICS.count("jobs.jobs_skipped")
                    return
                if replayed:
                    METRICS.count("jobs.cohort_resumes")
                os.makedirs(chunks_dir, exist_ok=True)
                seen_q = set(self.manifest.quarantined)
                i = len(replayed)
                for chunk in iter_joined_chunks(
                        self.manifest, streams,
                        self.geometry.samples_pad, self.config,
                        skip_through_key=last_key):
                    for sid in sorted(set(self.manifest.quarantined)
                                      - seen_q):
                        # observability, not replayed state: a
                        # deterministic fault re-fires on resume, a
                        # transient one heals (docstring)
                        jr.event("quarantine", sample=sid)
                        seen_q.add(sid)
                    # abspath (chunks_dir is absolute): the unit record
                    # must verify from any cwd `hbam resume` runs in
                    path = os.path.join(chunks_dir,
                                        f"chunk-{i:05d}.npz")
                    np.savez(path, **chunk)
                    size, crc = jj.file_digest(path)
                    jr.unit_done(
                        "chunk", i, path=path, size=size, crc=crc,
                        sites=int(chunk["pos"].shape[0]),
                        # the continuation key: group keys strictly
                        # increase, so the last row's (chrom, pos) IS
                        # the chunk's high-water mark
                        key_hi=int(chunk["chrom"][-1]),
                        key_lo=int(chunk["pos"][-1]))
                    i += 1
                    yield chunk
                jr.job_done(chunks=i)
            finally:
                self._journal_live = False
                if jr is not None:
                    jr.close()

        return gen()

    # -- mesh feed -----------------------------------------------------------

    def plan(self):
        """This cohort's compiled PlanIR (plan/builders.cohort_plan):
        the identity the journal seam records and ``hbam explain
        cohort`` prints."""
        from hadoop_bam_tpu.plan import builders
        return builders.cohort_plan(self.manifest, self.config,
                                    geometry=self.geometry)

    def tensor_batches(self, mesh=None, geometry=None) -> Iterator[Dict]:
        """Yield device-resident joined tensor batches (class
        docstring).  Compiles to a plan and runs through the one
        executor, which owns the feed discipline shared with
        ``VcfDataset.tensor_batches``: ring-slot groups, async
        device_put with in-flight handles, fixed-shape tiles.  Lazy:
        no join work (and no journal open) until first iteration.

        The compiled plan is ALWAYS ``self.plan()`` — the join identity
        the journal seam records: ``site_chunks`` joins with
        ``self.geometry`` regardless of a feed-geometry override here
        (``geometry`` only re-tiles the mesh feed), so the executing
        plan and the journaled plan_digest can never diverge."""
        from hadoop_bam_tpu.plan import executor as plan_executor

        return plan_executor.execute(self.plan(), config=self.config,
                                     mesh=mesh, geometry=geometry,
                                     dataset=self)

    # -- drivers -------------------------------------------------------------

    def gwas(self, phenotype=None, mesh=None) -> Dict[str, np.ndarray]:
        """Per-variant GWAS columns (cohort/gwas.py): allele frequency,
        call rate, HWE chi-square, and — with a phenotype vector — the
        score-test association chi-square."""
        from hadoop_bam_tpu.cohort.gwas import cohort_gwas
        return cohort_gwas(self, phenotype=phenotype, mesh=mesh,
                           config=self.config)


def open_cohort(source: Union[str, CohortManifest, List[str]],
                config: HBamConfig = DEFAULT_CONFIG,
                journal_path: Optional[str] = None) -> CohortDataset:
    """Resolve a manifest (path / object / bare path list) into the
    cohort dataset — the cohort analog of ``api.open_vcf``.
    ``journal_path`` makes the join crash-safe (``site_chunks``)."""
    return CohortDataset(source, config, journal_path=journal_path)
