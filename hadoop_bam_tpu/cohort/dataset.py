"""CohortDataset: the [variants, samples] tensor surface over a manifest.

The cohort twin of ``api.vcf_dataset.VcfDataset``: where that class
tiles ONE file's variants, this one streams k single-sample files
through the position join (cohort/join.py) and tiles the JOINED columns
onto the mesh through the same shared ``variant_feed``/``FeedPipeline``
machinery — so sentinel padding (-1 dosage / NaN qual), ring-slot
reuse, and the in-flight transfer discipline are all inherited, not
re-implemented.
"""
from __future__ import annotations

from typing import Dict, Iterator, List, Optional, Union

import numpy as np

from hadoop_bam_tpu.config import DEFAULT_CONFIG, HBamConfig
from hadoop_bam_tpu.cohort.join import (
    _JoinState, build_contig_space, guarded_sites, iter_joined_chunks,
    iter_sample_sites,
)
from hadoop_bam_tpu.cohort.manifest import CohortManifest, as_manifest


class CohortDataset:
    """Mesh-tiled access to a cohort of single-sample VCF/BCF files.

    ``tensor_batches`` yields device-resident dicts sharded over the
    mesh's data axis::

        chrom    int32  [n_dev, cap]
        pos      int32  [n_dev, cap]
        n_allele int16  [n_dev, cap]
        dosage   int8   [n_dev, cap, samples_pad]   (-1 missing)
        qual     float32[n_dev, cap, samples_pad]   (NaN missing)
        n_records int32 [n_dev]

    Rows beyond a shard's ``n_records`` carry the missing-value
    sentinels uniformly (dosage -1, qual NaN, 0 elsewhere) — the PR-4
    convention, enforced by the shared TileSpec pads.  Column ``j`` is
    ``manifest.samples[j]``; a sample whose input quarantined mid-join
    is sentinel-filled from the fault onward and listed in
    ``self.manifest.quarantined``.
    """

    def __init__(self, source: Union[str, CohortManifest, List[str]],
                 config: HBamConfig = DEFAULT_CONFIG):
        from hadoop_bam_tpu.api.vcf_dataset import VcfDataset
        from hadoop_bam_tpu.parallel.variant_pipeline import VariantGeometry
        from hadoop_bam_tpu.resilience import file_ident, registry
        from hadoop_bam_tpu.utils.errors import (
            CorruptDataError, PLAN, classify_error,
        )
        from hadoop_bam_tpu.utils.metrics import METRICS

        self.config = config
        self.manifest = as_manifest(source)
        quarantine = bool(getattr(config, "cohort_quarantine_inputs",
                                  True))
        # header reads: a MISSING path is configuration (PLAN, raises);
        # a file whose header bytes are corrupt is data — under the
        # quarantine policy its column goes sentinel before the join
        # even starts (the slot is kept as None so sample indices stay
        # stable)
        self._datasets: List = []
        for s in self.manifest.samples:
            try:
                self._datasets.append(VcfDataset(s.path, config))
            except Exception as e:  # noqa: BLE001 — classified below
                if classify_error(e) == PLAN or not quarantine:
                    raise
                registry().domain("cohort", "input", file_ident(s.path),
                                  config=config).record_failure(e)
                self.manifest.record_quarantine(
                    s.sample_id, f"{type(e).__name__}: {e}")
                METRICS.count("cohort.samples_quarantined")
                self._datasets.append(None)
        n_dead = sum(1 for d in self._datasets if d is None)
        max_frac = float(getattr(config, "cohort_max_quarantine_fraction",
                                 0.5))
        if n_dead / max(1, self.manifest.n_samples) > max_frac:
            raise CorruptDataError(
                f"cohort build: {n_dead}/{self.manifest.n_samples} "
                f"sample inputs quarantined at header read — over the "
                f"cohort_max_quarantine_fraction={max_frac} circuit")
        self.contigs = build_contig_space(
            [ds.header for ds in self._datasets if ds is not None])
        self._cmap = {c: i for i, c in enumerate(self.contigs)}
        self.geometry = VariantGeometry(n_samples=self.manifest.n_samples)

    @property
    def n_samples(self) -> int:
        return self.manifest.n_samples

    @property
    def sample_ids(self) -> List[str]:
        return self.manifest.sample_ids

    def contig_index(self, name: str) -> int:
        return self._cmap.get(name, -1)

    # -- host-side joined columns (the serve tier + oracle surface) ----------

    def site_chunks(self) -> Iterator[Dict[str, np.ndarray]]:
        """Stream the joined cohort as host column chunks (up to
        ``config.cohort_chunk_sites`` rows each) — the input of both the
        mesh feed below and the serve tier's tile builder."""
        state = _JoinState(
            self.manifest.n_samples,
            float(getattr(self.config, "cohort_max_quarantine_fraction",
                          0.5)))
        # header-time casualties count toward the fraction circuit
        state.quarantined = sum(1 for d in self._datasets if d is None)
        streams = []
        for ds, sample in zip(self._datasets, self.manifest.samples):
            if ds is None:
                streams.append(iter(()))   # quarantined at header read
                continue
            # every join starts from the file's FIRST span: records()
            # only auto-resets after a fully-exhausted iteration, and a
            # join abandoned mid-stream (early tensor_batches break, a
            # fraction-circuit trip) would otherwise silently RESUME
            # mid-file on the next call and serve a truncated cohort
            ds._next_span = 0
            sites = iter_sample_sites(ds.records(), self._cmap)
            streams.append(guarded_sites(
                sites, sample.sample_id, sample.path, self.manifest,
                state, self.config))
        return iter_joined_chunks(self.manifest, streams,
                                  self.geometry.samples_pad, self.config)

    # -- mesh feed -----------------------------------------------------------

    def tensor_batches(self, mesh=None, geometry=None) -> Iterator[Dict]:
        """Yield device-resident joined tensor batches (class
        docstring).  Same feed discipline as
        ``VcfDataset.tensor_batches``: ring-slot groups, async
        device_put with in-flight handles, fixed-shape tiles."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        from hadoop_bam_tpu.parallel.mesh import make_mesh
        from hadoop_bam_tpu.parallel.variant_pipeline import variant_feed

        if mesh is None:
            mesh = make_mesh()
        if geometry is None:
            geometry = self.geometry
        n_dev = int(np.prod(mesh.devices.shape))
        sharding = NamedSharding(mesh, P("data"))

        keys, fp, tuples = variant_feed(self.site_chunks(), n_dev,
                                        geometry.tile_records, self.config,
                                        fixed_shape=True, fmt="cohort")
        if fp is None:
            return

        def emit(arrays, counts) -> Dict:
            # the device dict doubles as the slot's in-flight handle
            out = {k: jax.device_put(a, sharding)
                   for k, a in zip(keys, arrays)}
            out["n_records"] = jax.device_put(counts, sharding)
            return out

        yield from fp.stream(tuples, emit)

    # -- drivers -------------------------------------------------------------

    def gwas(self, phenotype=None, mesh=None) -> Dict[str, np.ndarray]:
        """Per-variant GWAS columns (cohort/gwas.py): allele frequency,
        call rate, HWE chi-square, and — with a phenotype vector — the
        score-test association chi-square."""
        from hadoop_bam_tpu.cohort.gwas import cohort_gwas
        return cohort_gwas(self, phenotype=phenotype, mesh=mesh,
                           config=self.config)


def open_cohort(source: Union[str, CohortManifest, List[str]],
                config: HBamConfig = DEFAULT_CONFIG) -> CohortDataset:
    """Resolve a manifest (path / object / bare path list) into the
    cohort dataset — the cohort analog of ``api.open_vcf``."""
    return CohortDataset(source, config)
