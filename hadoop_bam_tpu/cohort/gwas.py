"""GWAS-style mesh drivers over the joined [variants, samples] tensor.

One ``shard_map`` step per tile group computes, per variant row:

- **allele frequency** ``af = alt_allele_sum / (2 * n_called)`` —
  diploid ALT frequency over called samples (NaN when nothing called);
- **call rate** ``n_called / n_samples``;
- **HWE chi-square**: observed diploid genotype counts (hom-ref / het /
  hom-alt among called samples with dosage <= 2) against
  Hardy-Weinberg expectation at the observed allele frequency, 1 d.f.
  (NaN when no classed genotypes);
- **score-test association** against a phenotype vector ``y`` [SPEC:
  the standard 1-d.f. score test of H0: beta_g = 0 in
  ``y = mu + beta_g * g``]::

      U  = sum_i (y_i - ybar)(g_i - gbar)      over called, phenotyped i
      Vg = sum_i (g_i - gbar)^2
      Vy = sum_i (y_i - ybar)^2 / n            (MLE variance under H0)
      chi2 = U^2 / (Vy * Vg)                   (NaN when Vy*Vg == 0)

Every formula has a NumPy twin in tests/test_cohort.py pinned to
float32 tolerance — the drivers are reductions along the SAMPLE axis,
so rows shard cleanly over the mesh's data axis with no collective at
all; only the phenotype is replicated.
"""
from __future__ import annotations

from typing import Dict, Optional

import numpy as np

from hadoop_bam_tpu.config import DEFAULT_CONFIG, HBamConfig
from hadoop_bam_tpu.utils.errors import PlanError
from hadoop_bam_tpu.utils.metrics import METRICS

# columns of the per-variant stats tensor the step returns, in order
GWAS_COLUMNS = ("af", "call_rate", "hwe_chi2", "score_chi2")


def make_cohort_gwas_step(mesh, geometry, with_pheno: bool,
                          axis: str = "data"):
    """Jitted sharded step: one joined tile group -> per-variant stats
    ``[n_dev, cap, 4]`` float32 (NaN where a stat is undefined).  The
    phenotype rides as a replicated runtime argument, so one compiled
    step serves every batch and every phenotype."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from hadoop_bam_tpu.parallel.mesh import shard_map
    from hadoop_bam_tpu.parallel.pipeline import _STEP_CACHE

    key = ("cohort_gwas", tuple(mesh.devices.flat), mesh.axis_names,
           axis, geometry, bool(with_pheno))
    if key in _STEP_CACHE:
        return _STEP_CACHE[key]

    S = int(geometry.n_samples)
    nan = jnp.float32(jnp.nan)

    def per_device(dosage, count, pheno):
        dosage, count = dosage[0], count[0]
        cap = dosage.shape[0]
        valid = jnp.arange(cap, dtype=jnp.int32) < count
        samp = jnp.arange(dosage.shape[1], dtype=jnp.int32) < S
        d = dosage.astype(jnp.int32)
        called = (d >= 0) & samp[None, :]
        cf = called.astype(jnp.float32)
        n_called = called.sum(axis=1)                       # [cap] i32
        ncf = n_called.astype(jnp.float32)
        alt = jnp.where(called, d, 0).sum(axis=1).astype(jnp.float32)
        has = n_called > 0
        af = jnp.where(has, alt / (2.0 * jnp.maximum(ncf, 1.0)), nan)
        call_rate = ncf / jnp.float32(max(S, 1))

        # HWE: diploid-classed genotypes only (dosage 0/1/2); polyploid
        # dosage > 2 counts as called but is excluded from the table
        n0 = ((d == 0) & called).sum(axis=1).astype(jnp.float32)
        n1 = ((d == 1) & called).sum(axis=1).astype(jnp.float32)
        n2 = ((d == 2) & called).sum(axis=1).astype(jnp.float32)
        m = n0 + n1 + n2
        msafe = jnp.maximum(m, 1.0)
        p = (2.0 * n2 + n1) / (2.0 * msafe)
        e0 = (1.0 - p) ** 2 * m
        e1 = 2.0 * p * (1.0 - p) * m
        e2 = p ** 2 * m

        def term(obs, exp):
            return jnp.where(exp > 0, (obs - exp) ** 2
                             / jnp.maximum(exp, 1e-12), 0.0)

        hwe = jnp.where(m > 0, term(n0, e0) + term(n1, e1) + term(n2, e2),
                        nan)

        if with_pheno:
            yok = jnp.isfinite(pheno) & samp
            use = called & yok[None, :]
            uf = use.astype(jnp.float32)
            n = uf.sum(axis=1)
            nsafe = jnp.maximum(n, 1.0)
            y = jnp.where(yok, pheno, 0.0)[None, :]
            g = jnp.where(use, d, 0).astype(jnp.float32)
            sy = (y * uf).sum(axis=1)
            sg = g.sum(axis=1)
            sgy = (g * y).sum(axis=1)
            sgg = (g * g).sum(axis=1)
            syy = (y * y * uf).sum(axis=1)
            u_stat = sgy - sy * sg / nsafe
            vg = sgg - sg * sg / nsafe
            vy = (syy - sy * sy / nsafe) / nsafe
            denom = vy * vg
            score = jnp.where((n > 1) & (denom > 1e-12),
                              u_stat * u_stat / jnp.maximum(denom, 1e-12),
                              nan)
        else:
            score = jnp.full((cap,), nan, jnp.float32)

        stats = jnp.stack([af, call_rate, hwe, score], axis=1)
        # padding rows report NaN across the board, never a fake 0 stat
        return jnp.where(valid[:, None], stats, nan)[None]

    fn = shard_map(per_device, mesh=mesh,
                   in_specs=(P(axis), P(axis), P()),
                   out_specs=P(axis))
    step = jax.jit(fn)
    _STEP_CACHE[key] = step
    return step


def cohort_gwas(source, phenotype=None, mesh=None,
                config: HBamConfig = DEFAULT_CONFIG,
                geometry=None) -> Dict[str, np.ndarray]:
    """Drive the joined cohort through the GWAS step: returns
    per-variant arrays ``chrom``/``pos``/``n_allele`` plus the
    ``GWAS_COLUMNS`` float32 stats (and ``n_variants``,
    ``sample_ids``, ``quarantined``).

    ``phenotype`` is one float per manifest sample (NaN = missing
    phenotype; that sample drops out of the score test only).
    """
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from hadoop_bam_tpu.cohort.dataset import CohortDataset
    from hadoop_bam_tpu.parallel.mesh import make_mesh

    ds = source if isinstance(source, CohortDataset) \
        else CohortDataset(source, config)
    if mesh is None:
        mesh = make_mesh()
    if geometry is None:
        geometry = ds.geometry

    pheno_dev = None
    if phenotype is not None:
        y = np.asarray(phenotype, dtype=np.float32)
        if y.shape != (ds.n_samples,):
            raise PlanError(
                f"phenotype must be one value per manifest sample "
                f"({ds.n_samples}), got shape {tuple(y.shape)}")
        ypad = np.full(geometry.samples_pad, np.nan, np.float32)
        ypad[:ds.n_samples] = y
        pheno_dev = jax.device_put(ypad, NamedSharding(mesh, P()))
    else:
        pheno_dev = jax.device_put(
            np.full(geometry.samples_pad, np.nan, np.float32),
            NamedSharding(mesh, P()))

    step = make_cohort_gwas_step(mesh, geometry, phenotype is not None)
    chroms, poss, nalls, stats_parts = [], [], [], []
    for out in ds.tensor_batches(mesh, geometry):
        with METRICS.span("cohort.kernel_wall"):
            stats = step(out["dosage"], out["n_records"], pheno_dev)
        counts = np.asarray(out["n_records"])
        host = np.asarray(stats)
        hchrom = np.asarray(out["chrom"])
        hpos = np.asarray(out["pos"])
        hnall = np.asarray(out["n_allele"])
        for dev in range(counts.shape[0]):
            c = int(counts[dev])
            if c:
                chroms.append(hchrom[dev, :c])
                poss.append(hpos[dev, :c])
                nalls.append(hnall[dev, :c])
                stats_parts.append(host[dev, :c])
    if stats_parts:
        stats_all = np.concatenate(stats_parts, axis=0)
        chrom = np.concatenate(chroms)
        pos = np.concatenate(poss)
        nall = np.concatenate(nalls)
    else:
        stats_all = np.empty((0, len(GWAS_COLUMNS)), np.float32)
        chrom = np.empty(0, np.int32)
        pos = np.empty(0, np.int32)
        nall = np.empty(0, np.int16)
    out = {
        "n_variants": int(stats_all.shape[0]),
        "chrom": chrom, "pos": pos, "n_allele": nall,
        "sample_ids": list(ds.sample_ids),
        "quarantined": dict(ds.manifest.quarantined),
    }
    for j, name in enumerate(GWAS_COLUMNS):
        out[name] = stats_all[:, j]
    return out
