"""Cohort variant plane: mesh-joined multi-sample dosage tensors.

The million-user workload (ROADMAP item 3): thousands of single-sample
VCF/BCF files joined on position into one ``[variants, samples]``
dosage/genotype tensor, built as a mesh program —

- ``manifest``: the named input set + its cache-keying identity;
- ``harmonize``: per-site allele harmonization (multi-allelic
  split/merge, REF/ALT swaps, duplicate positions);
- ``join``: the k-way streaming position merge (split/kmerge.py core)
  with per-input-file fault domains;
- ``dataset``: ``CohortDataset.tensor_batches`` — joined tiles through
  the shared FeedPipeline with the PR-4 missing-value sentinels;
- ``gwas``: allele frequency / call rate / HWE / score-test mesh
  drivers;
- ``serving``: cohort-slice requests from device-resident dosage tiles
  (``hbam serve`` integration).
"""
from hadoop_bam_tpu.cohort.manifest import (      # noqa: F401
    CohortManifest, CohortSample, as_manifest, load_manifest,
)
from hadoop_bam_tpu.cohort.dataset import (       # noqa: F401
    CohortDataset, open_cohort,
)
from hadoop_bam_tpu.cohort.gwas import GWAS_COLUMNS, cohort_gwas  # noqa: F401
