"""The k-way position join: sample site streams -> joined column chunks.

One stream per manifest sample (``VcfDataset.records`` in any container
the dispatcher recognises, reduced to ``SampleSite``), merged on
``(contig, pos)`` by the shared ``split/kmerge.py`` heap core, each
group harmonized (cohort/harmonize.py) and packed into
``cohort_chunk_sites``-row column chunks:

    chrom i32 [n], pos i32 [n], n_allele i16 [n],
    dosage i8 [n, samples_pad] (-1 missing),
    qual f32 [n, samples_pad] (NaN missing)

— exactly the schema the shared ``variant_feed``/``FeedPipeline``
machinery tiles onto the mesh (the PR-4 sentinel convention rides the
TileSpec pads).

**Per-input-file fault domains** (this is a policy boundary module,
ET3xx scope): each sample stream runs inside a guard keyed
``("cohort", "input", <abspath>)`` in the resilience registry.  A data
fault mid-stream (corrupt bytes, a container error, out-of-order
records) QUARANTINES that sample — its column carries the missing
sentinels from the fault onward, the manifest records the casualty,
the domain's breaker is fed — and the join keeps going.  PLAN-class
errors (bad paths, bad parameters) always raise: configuration is
never quarantined.  ``cohort_max_quarantine_fraction`` bounds the
damage — losing most of the cohort's columns is not a result.
"""
from __future__ import annotations

import math
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from hadoop_bam_tpu.config import DEFAULT_CONFIG, HBamConfig
from hadoop_bam_tpu.cohort.harmonize import SampleSite, harmonize_site
from hadoop_bam_tpu.cohort.manifest import CohortManifest
from hadoop_bam_tpu.split.kmerge import kmerge_grouped
from hadoop_bam_tpu.utils.errors import (
    CorruptDataError, PLAN, classify_error,
)
from hadoop_bam_tpu.utils.metrics import METRICS


def build_contig_space(headers: Sequence) -> List[str]:
    """The shared cohort contig namespace: the union of every sample
    header's contigs, in manifest order then header order.  Every
    sample's positions key into ONE index space, so the k-way merge key
    ``(contig_index, pos)`` is comparable across streams."""
    contigs: List[str] = []
    seen = set()
    for h in headers:
        for c in h.contigs:
            if c not in seen:
                seen.add(c)
                contigs.append(c)
    return contigs


def _parse_alleles(genotype: str) -> Tuple[Optional[int], ...]:
    """GT string -> allele index tuple (None for '.'); '' -> ()."""
    gt = genotype.split(":", 1)[0]
    if not gt:
        return ()
    out: List[Optional[int]] = []
    for a in gt.replace("|", "/").split("/"):
        out.append(int(a) if a.isdigit() else None)
    return tuple(out)


def iter_sample_sites(records, cmap: Dict[str, int]) -> Iterator[SampleSite]:
    """Reduce one sample's ``VcfRecord`` stream to ``SampleSite``s keyed
    into the shared contig space.  A record on a contig absent from
    every header, or a record that breaks (contig, pos) order, is a
    DATA fault (``CorruptDataError``) — the guard above decides whether
    it quarantines or raises."""
    last: Optional[Tuple[int, int]] = None
    for rec in records:
        ci = cmap.get(rec.chrom)
        if ci is None:
            raise CorruptDataError(
                f"cohort join: contig {rec.chrom!r} appears in records "
                f"but in no sample header — the shared contig space "
                f"cannot order it")
        site = SampleSite(
            chrom=ci, pos=int(rec.pos), ref=rec.ref, alts=tuple(rec.alts),
            alleles=(_parse_alleles(rec.genotypes[0])
                     if rec.fmt and rec.fmt[0] == "GT" and rec.genotypes
                     else ()),
            qual=float(rec.qual) if rec.qual is not None else math.nan)
        if last is not None and site.key < last:
            raise CorruptDataError(
                f"cohort join: records out of (contig, pos) order at "
                f"{rec.chrom}:{rec.pos} — the streaming merge needs "
                f"position-sorted inputs")
        last = site.key
        yield site


class _JoinState:
    """Shared mutable accounting across the guarded streams."""

    def __init__(self, n_samples: int, max_fraction: float):
        self.n_samples = n_samples
        self.max_fraction = float(max_fraction)
        self.quarantined = 0


def guarded_sites(site_iter: Iterator[SampleSite], sample_id: str,
                  path: str, manifest: CohortManifest, state: _JoinState,
                  config: HBamConfig) -> Iterator[SampleSite]:
    """The per-input fault domain: stream ``site_iter`` through,
    classifying any fault.  PLAN raises; data faults feed the input's
    breaker and (under ``cohort_quarantine_inputs``) end THIS stream —
    the sample's column stays sentinel-filled — unless the quarantined
    fraction trips the build-wide circuit."""
    from hadoop_bam_tpu.resilience import file_ident, registry

    domain = registry().domain("cohort", "input", file_ident(path),
                               config=config)
    try:
        yield from site_iter
    except BaseException as e:  # noqa: BLE001 — classified below
        if not isinstance(e, Exception) or classify_error(e) == PLAN:
            raise              # configuration / KeyboardInterrupt etc.
        domain.record_failure(e)
        if not bool(getattr(config, "cohort_quarantine_inputs", True)):
            raise
        manifest.record_quarantine(
            sample_id, f"{type(e).__name__}: {e}")
        state.quarantined += 1
        METRICS.count("cohort.samples_quarantined")
        frac = state.quarantined / max(1, state.n_samples)
        if frac > state.max_fraction:
            raise CorruptDataError(
                f"cohort join: {state.quarantined}/{state.n_samples} "
                f"sample inputs quarantined ({frac:.0%}) — over the "
                f"cohort_max_quarantine_fraction="
                f"{state.max_fraction} circuit; the joined tensor "
                f"would be mostly sentinel") from e
        return                 # stream ends; the join keeps going
    else:
        domain.record_success()


def iter_joined_chunks(manifest: CohortManifest,
                       streams: Sequence[Iterator[SampleSite]],
                       samples_pad: int,
                       config: HBamConfig = DEFAULT_CONFIG,
                       skip_through_key: Optional[Tuple[int, int]] = None
                       ) -> Iterator[Dict[str, np.ndarray]]:
    """Merge + harmonize + pack: yields column-chunk dicts of up to
    ``config.cohort_chunk_sites`` joined sites.  ``streams`` are the
    (already guarded) per-sample ``SampleSite`` iterators, in manifest
    order — their index IS the sample column index.

    ``skip_through_key`` is the journal-resume continuation point
    (jobs/): merged site GROUPS with key <= it are dropped before
    harmonize/pack — they are already inside replayed chunks.  Group
    keys strictly increase and every record of a key lands in one
    group, so a chunk boundary is always a clean key boundary and the
    continuation reproduces the uninterrupted chunk sequence exactly
    (the streams are still consumed — record decode is not skipped,
    only the join/harmonize work and the chunk assembly are)."""
    k = manifest.n_samples
    chunk_sites = max(1, int(getattr(config, "cohort_chunk_sites", 1024)))

    def empty_chunk():
        return {
            "chrom": np.empty(chunk_sites, np.int32),
            "pos": np.empty(chunk_sites, np.int32),
            "n_allele": np.empty(chunk_sites, np.int16),
            "dosage": np.full((chunk_sites, samples_pad), -1, np.int8),
            "qual": np.full((chunk_sites, samples_pad), np.nan,
                            np.float32),
        }

    cols = empty_chunk()
    n = 0
    groups = kmerge_grouped(streams, key=lambda s: s.key)
    while True:
        # the span covers merge + harmonize + pack work for one chunk;
        # the generator suspends OUTSIDE it, so consumer time (device
        # dispatch) never pollutes the join wall
        with METRICS.span("cohort.join_wall"), \
                METRICS.wall_timer("pipeline.host_decode_wall"):
            # counters accumulate locally and emit ONCE per chunk: a
            # per-site METRICS.count would take the metrics lock per
            # joined variant inside the merge hot loop
            dupes = dropped = 0
            while n < chunk_sites:
                nxt = next(groups, None)
                if nxt is None:
                    break
                _key, group = nxt
                if skip_through_key is not None \
                        and tuple(_key) <= tuple(skip_through_key):
                    continue       # already inside a replayed chunk
                h = harmonize_site(group, k)
                cols["chrom"][n] = h.chrom
                cols["pos"][n] = min(h.pos, np.iinfo(np.int32).max)
                cols["n_allele"][n] = min(h.n_allele,
                                          np.iinfo(np.int16).max)
                cols["dosage"][n, :k] = h.dosage
                cols["qual"][n, :k] = h.qual
                n += 1
                dupes += h.duplicates
                dropped += h.dropped
            if n:
                METRICS.count("cohort.sites", n)
            if dupes:
                METRICS.count("cohort.duplicate_sites", dupes)
            if dropped:
                METRICS.count("cohort.harmonize_dropped", dropped)
        if n == 0:
            return
        out = {kk: v[:n] for kk, v in cols.items()}
        yield out
        if n < chunk_sites:       # stream exhausted mid-chunk
            return
        cols = empty_chunk()
        n = 0
