"""Cohort-slice serving: "this gene across N samples" from resident tiles.

The serve tier's third projection (after interval tiles and host
chunks): the joined cohort's ``chrom``/``pos``/``n_allele``/``dosage``
columns live as sharded device tiles in the SAME ``DeviceTileCache``
as region tiles, keyed by the **cohort manifest identity** (every
input's ``(abspath, size, mtime_ns)`` digested — rewrite one sample
file and every cached cohort tile self-invalidates).

Request shape on the wire (serve/transport.py)::

    {"id": 7, "cohort": true, "path": "cohort.json",
     "regions": ["chr20:1000000-2000000"], "records": false}

The COLD path runs the full position join (host work, spanned as
``cohort.join_wall`` + ``pipeline.host_decode_wall``) and parks the
joined tiles on the devices; every WARM slice goes straight to the
jitted interval filter — no host decode at all, the same bypass
contract as region serving (pinned by tests: host_decode share ~0 on
repeat slices).
"""
from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

import numpy as np

from hadoop_bam_tpu.config import DEFAULT_CONFIG, HBamConfig
from hadoop_bam_tpu.cohort.manifest import CohortManifest, load_manifest
from hadoop_bam_tpu.utils.errors import PlanError
from hadoop_bam_tpu.utils.metrics import METRICS
from hadoop_bam_tpu.utils.stepcache import BoundedStepCache

COHORT_PROJECTION = "cohort_dosage"

_I32_MAX = int(np.iinfo(np.int32).max)


class _CohortMeta:
    """Resident per-manifest state: ONE ``CohortDataset`` (so the serve
    path shares the exact quarantine policy AND contig space the
    CLI/API build uses — a header-corrupt sample quarantines here too,
    and tile chrom indices can never diverge from the cmap the slice
    resolves against), plus — once built — the tile group row counts
    (so warm lookups know every key to fetch)."""

    __slots__ = ("path", "dataset", "ident", "group_rows", "n_variants")

    def __init__(self, path: str, dataset, ident):
        self.path = path
        self.dataset = dataset
        self.ident = ident
        self.group_rows: Optional[List[int]] = None
        self.n_variants = 0

    @property
    def manifest(self) -> CohortManifest:
        return self.dataset.manifest

    @property
    def contigs(self) -> List[str]:
        return self.dataset.contigs

    @property
    def cmap(self):
        return self.dataset._cmap

    @property
    def n_samples(self) -> int:
        return self.dataset.n_samples

    @property
    def samples_pad(self) -> int:
        return self.dataset.geometry.samples_pad


def make_cohort_slice_step(mesh, axis: str = "data", *,
                           _cache=BoundedStepCache(cap=8)):
    """Jitted sharded slice predicate over a resident cohort tile:
    rows overlapping ONE interval ``iv = [contig, beg, end]``
    (replicated int32[3]).  Returns ``(keep, hits, af, af_sum, af_n)``
    — count-only serving reads just the per-device scalars; ``af`` is
    the per-row diploid ALT allele frequency (records mode)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from hadoop_bam_tpu.parallel.mesh import shard_map

    key = ("cohort_slice", tuple(mesh.devices.flat), mesh.axis_names,
           axis)

    def build():
        def per_device(chrom, pos, dosage, count, iv):
            chrom, pos = chrom[0], pos[0]
            dosage, count = dosage[0], count[0]
            cap = chrom.shape[0]
            valid = jnp.arange(cap, dtype=jnp.int32) < count
            keep = valid & (chrom == iv[0]) & (pos >= iv[1]) \
                & (pos <= iv[2])
            hits = keep.sum(dtype=jnp.int32)
            d = dosage.astype(jnp.int32)
            called = d >= 0
            ncf = called.sum(axis=1).astype(jnp.float32)
            alt = jnp.where(called, d, 0).sum(axis=1).astype(jnp.float32)
            has = ncf > 0
            af = jnp.where(has, alt / (2.0 * jnp.maximum(ncf, 1.0)),
                           jnp.float32(jnp.nan))
            in_mean = keep & has
            af_sum = jnp.where(in_mean, af, 0.0).sum()
            af_n = in_mean.sum(dtype=jnp.int32)
            return (keep[None], hits[None], af[None], af_sum[None],
                    af_n[None])

        fn = shard_map(per_device, mesh=mesh,
                       in_specs=(P(axis),) * 4 + (P(),),
                       out_specs=(P(axis),) * 5)
        return jax.jit(fn)

    return _cache.get_or_build(key, build)


class CohortServer:
    """The serve tier's cohort plane: owns manifest metadata (bounded
    LRU), builds joined dosage tiles into the shared DeviceTileCache,
    and answers slice requests.  All methods run on the ONE serve
    dispatcher thread — the FeedPipeline jax discipline — so no lock
    guards the device work, only the meta map (stats readers poll)."""

    def __init__(self, mesh, config: HBamConfig = DEFAULT_CONFIG):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        self.mesh = mesh
        self.config = config
        self.n_dev = int(np.prod(mesh.devices.shape))
        self.cap = int(getattr(config, "serve_tile_records", 4096))
        self.sharding = NamedSharding(mesh, P("data"))
        self.replicated = NamedSharding(mesh, P())
        self._lock = threading.Lock()
        self._meta: "OrderedDict[str, _CohortMeta]" = OrderedDict()
        self._meta_cap = max(1, int(getattr(config,
                                            "serve_cohort_manifests", 8)))
        self._jax = jax

    # -- metadata ------------------------------------------------------------

    def _meta_for(self, path: str) -> _CohortMeta:
        import os

        apath = os.path.abspath(path)
        manifest = load_manifest(apath)
        ident = manifest.identity()
        with self._lock:
            meta = self._meta.get(apath)
            if meta is not None and meta.ident == ident:
                self._meta.move_to_end(apath)
                return meta
        # cold or identity-changed: ONE CohortDataset carries the
        # contig space, geometry, and quarantine policy for both the
        # slice resolution below and the tile build — the same code
        # path the CLI/API build runs, so a header-corrupt sample
        # quarantines instead of failing the request, and tile chrom
        # indices always match the cmap slices resolve against
        from hadoop_bam_tpu.cohort.dataset import CohortDataset

        meta = _CohortMeta(apath, CohortDataset(manifest, self.config),
                           ident)
        with self._lock:
            self._meta[apath] = meta
            self._meta.move_to_end(apath)
            while len(self._meta) > self._meta_cap:
                self._meta.popitem(last=False)
        return meta

    # -- tiles ---------------------------------------------------------------

    def _key(self, meta: _CohortMeta, g: int) -> Tuple:
        from hadoop_bam_tpu.serve.tiles import tile_key
        return tile_key(meta.ident, "cohort", g, 0, self.n_dev, self.cap,
                        projection=COHORT_PROJECTION)

    def _build_tiles(self, meta: _CohortMeta) -> List:
        """Run the join and park the cohort on the devices: one sharded
        TileSet per ``n_dev * cap``-row group.  Host arrays here are
        FRESH per build (never ring-recycled), so the CPU backend's
        zero-copy ``device_put`` aliasing is safe by construction.

        Chunks STREAM into the group buffers: the slice path never
        uses the qual column (the largest one — dropped on arrival),
        and at most one group plus one chunk of dosage is held on the
        host at a time, never a second full-cohort copy."""
        from hadoop_bam_tpu.serve.tiles import TileGroup, TileSet

        ds = meta.dataset
        per_group = self.n_dev * self.cap
        sets: List[TileSet] = []
        group = None                # (chrom, pos, nall, dosage) buffers
        fill = 0                    # rows filled in the open group

        def fresh_group():
            return (np.full((per_group,), -1, np.int32),
                    np.zeros((per_group,), np.int32),
                    np.zeros((per_group,), np.int16),
                    np.full((per_group, meta.samples_pad), -1, np.int8))

        def close_group(bufs, rows: int) -> None:
            counts = np.minimum(
                np.maximum(rows - np.arange(self.n_dev) * self.cap, 0),
                self.cap).astype(np.int32)
            shaped = (bufs[0].reshape(self.n_dev, self.cap),
                      bufs[1].reshape(self.n_dev, self.cap),
                      bufs[2].reshape(self.n_dev, self.cap),
                      bufs[3].reshape(self.n_dev, self.cap,
                                      meta.samples_pad))
            dev_arrays = self._jax.device_put(shaped + (counts,),
                                              self.sharding)
            nbytes = sum(int(a.nbytes) for a in dev_arrays)
            sets.append(TileSet(
                groups=[TileGroup(cols=dev_arrays[:4],
                                  counts=dev_arrays[4], n=rows)],
                n=rows, nbytes=nbytes + 64, ident=meta.ident))

        n = 0
        with METRICS.span("cohort.tile_build_wall"):
            for chunk in ds.site_chunks():
                chunk.pop("qual", None)      # slicing never reads it
                m = int(chunk["chrom"].shape[0])
                taken = 0
                while taken < m:
                    if group is None:
                        group, fill = fresh_group(), 0
                    k = min(per_group - fill, m - taken)
                    group[0][fill:fill + k] = chunk["chrom"][taken:taken + k]
                    group[1][fill:fill + k] = chunk["pos"][taken:taken + k]
                    group[2][fill:fill + k] = \
                        chunk["n_allele"][taken:taken + k]
                    group[3][fill:fill + k] = \
                        chunk["dosage"][taken:taken + k]
                    fill += k
                    taken += k
                    n += k
                    if fill == per_group:
                        close_group(group, fill)
                        group = None
            if group is not None and fill:
                close_group(group, fill)
            elif n == 0:
                # empty cohort: one all-padding group so warm lookups
                # and the filter loop have a well-formed (empty) tile
                close_group(fresh_group(), 0)
        meta.n_variants = n
        return sets

    def _tiles(self, meta: _CohortMeta, tiles_cache
               ) -> Tuple[List, int, int]:
        """(tile sets, tile_hits, tile_misses) — warm fetch from the
        shared device cache, or one cold build that parks every group."""
        if meta.group_rows is not None:
            sets = []
            for g in range(len(meta.group_rows)):
                t = tiles_cache.get(self._key(meta, g))
                if t is None:
                    sets = None
                    break
                sets.append(t)
            if sets is not None:
                return sets, len(sets), 0
        built = self._build_tiles(meta)
        for g, t in enumerate(built):
            tiles_cache.put(self._key(meta, g), t)
        meta.group_rows = [t.n for t in built]
        METRICS.count("cohort.tile_builds")
        return built, 0, max(1, len(built))

    # -- the slice -----------------------------------------------------------

    def serve(self, path: str, region: str, tiles_cache, *,
              want_records: bool = False, deadline=None):
        """Answer one cohort-slice request; returns a
        ``serve.loop.ServeResult`` (count = variants in the slice,
        ``extra`` carries the cohort aggregates)."""
        from hadoop_bam_tpu.serve.loop import ServeResult
        from hadoop_bam_tpu.split.intervals import parse_interval

        if deadline is not None:
            deadline.check("cohort resolve")
        meta = self._meta_for(path)
        iv = parse_interval(region)
        rid = meta.cmap.get(iv.rname)
        if rid is None:
            raise PlanError(
                f"cohort slice: contig {iv.rname!r} is in no sample "
                f"header of {path!r}")
        sets, tile_hits, tile_misses = self._tiles(meta, tiles_cache)
        step = make_cohort_slice_step(self.mesh)
        iv_dev = self._jax.device_put(
            np.asarray([rid, min(iv.start, _I32_MAX),
                        min(iv.end, _I32_MAX)], np.int32),
            self.replicated)
        count = 0
        af_sum = 0.0
        af_n = 0
        recs: Optional[List[Dict]] = [] if want_records else None
        with METRICS.span("cohort.slice_wall", region=region):
            # dispatch EVERY group first, drain once: per-group host
            # syncs inside the loop would serialize a device round-trip
            # every n_dev*cap rows (the DV901 discipline, applied here)
            pending = []
            for t in sets:
                if deadline is not None:
                    deadline.check("cohort slice group")
                for g in t.groups:
                    pending.append(
                        (g, step(*g.cols[:2], g.cols[3], g.counts,
                                 iv_dev)))
            for g, (keep, hits, af, asum, an) in pending:
                count += int(np.asarray(hits).sum())
                af_sum += float(np.asarray(asum).sum())
                af_n += int(np.asarray(an).sum())
                if recs is not None:
                    km = np.asarray(keep)
                    hchrom = np.asarray(g.cols[0])
                    hpos = np.asarray(g.cols[1])
                    hnall = np.asarray(g.cols[2])
                    haf = np.asarray(af)
                    for dev in range(km.shape[0]):
                        for row in np.flatnonzero(km[dev]):
                            a = float(haf[dev, row])
                            recs.append({
                                "chrom": meta.contigs[
                                    int(hchrom[dev, row])],
                                "pos": int(hpos[dev, row]),
                                "n_allele": int(hnall[dev, row]),
                                "af": None if np.isnan(a)
                                else round(a, 6)})
        METRICS.count("cohort.slice_requests")
        extra = {
            "n_samples": meta.n_samples,
            "mean_af": (round(af_sum / af_n, 6) if af_n else None),
        }
        if meta.manifest.quarantined:
            extra["quarantined"] = sorted(meta.manifest.quarantined)
        if recs is not None:
            recs.sort(key=lambda r: (r["chrom"], r["pos"]))
        return ServeResult(region=region, count=count,
                           n_candidates=meta.n_variants,
                           tile_hits=tile_hits, tile_misses=tile_misses,
                           records=recs, extra=extra)

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {"manifests": len(self._meta)}
