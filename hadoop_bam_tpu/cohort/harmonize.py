"""Per-site allele harmonization: k samples' records -> one joined row.

Single-sample callers emit the SAME variant in different shapes: ALT
lists in different orders, multi-allelic sites split across calls,
even REF/ALT swapped (the caller normalized against the other allele).
Joining on position alone would average apples with oranges, so every
joined site runs through one harmonization pass:

- the **canonical REF** is the majority REF string among the site's
  records (ties break toward the earliest sample — deterministic
  because the k-way merge groups in stream order);
- **canonical ALTs** are the union, in sample order, of the ALT strings
  of records whose REF matches the canonical REF (the multi-allelic
  split/merge case: sample A's ``A->G`` and sample B's ``A->T`` join as
  ``A -> [G, T]``);
- a record whose REF does NOT match canonical is admitted only when
  its REF string is ITSELF in the canonical allele set (a true REF/ALT
  swap); its alleles then map **by string** into the canonical set, so
  a swapped caller's hom-ref ``0/0`` becomes dosage 2.  A genuinely
  inconsistent record (e.g. an indel REF overlapping a SNP site) is
  rejected wholesale — that sample's call becomes the missing sentinel
  (-1), counted as ``dropped`` — even when one of its ALT strings
  happens to collide with a canonical allele (an ``AT->A`` deletion's
  ALT "A" is NOT the SNP site's reference allele).  Mismatched-REF
  records never mint NEW canonical alleles: appending an unmapped
  indel REF as an ALT would fabricate an allele no consistent caller
  saw.
- **duplicate positions within one input** (same sample, same site,
  two records): the FIRST record wins, the rest are counted as
  ``duplicates`` and ignored — re-blocked gVCF spills do this.

Dosage is diploid-and-beyond ALT-allele count against the canonical
set: number of called alleles whose canonical index is non-zero;
any missing/unmappable allele makes the whole call -1 (matching the
PR-4 sentinel convention; qual's sentinel is NaN).
"""
from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np


@dataclasses.dataclass(frozen=True)
class SampleSite:
    """One sample's record at one position, reduced to what the join
    needs (parsed once, in the sample's stream thread)."""
    chrom: int                        # shared cohort contig index
    pos: int                          # 1-based
    ref: str
    alts: Tuple[str, ...]
    alleles: Tuple[Optional[int], ...]  # GT allele indices; None = '.'
    qual: float                       # NaN when missing

    @property
    def key(self) -> Tuple[int, int]:
        return (self.chrom, self.pos)


@dataclasses.dataclass
class HarmonizedSite:
    """One joined [variants, samples] row plus its accounting."""
    chrom: int
    pos: int
    n_allele: int                     # 1 + canonical ALT count
    dosage: np.ndarray                # [n_samples] int8, -1 missing
    qual: np.ndarray                  # [n_samples] float32, NaN missing
    duplicates: int                   # extra same-sample records dropped
    dropped: int                      # calls lost to REF inconsistency


def harmonize_site(entries: Sequence[Tuple[int, SampleSite]],
                   n_samples: int) -> HarmonizedSite:
    """``entries`` is one k-merge group: ``(sample_index, site)`` pairs
    at a single (chrom, pos), in sample order.  Returns the joined row;
    samples absent from the group keep the missing sentinels."""
    # duplicate positions within one input: first record per sample wins
    first: Dict[int, SampleSite] = {}
    duplicates = 0
    for si, site in entries:
        if si in first:
            duplicates += 1
        else:
            first[si] = site

    sites = list(first.items())
    # canonical REF: majority, ties toward the earliest sample
    counts: Dict[str, int] = {}
    order: Dict[str, int] = {}
    for rank, (_si, s) in enumerate(sites):
        counts[s.ref] = counts.get(s.ref, 0) + 1
        order.setdefault(s.ref, rank)
    ref = min(counts, key=lambda r: (-counts[r], order[r]))

    # canonical ALTs: union in sample order from REF-consistent records
    alts: List[str] = []
    index: Dict[str, int] = {ref: 0}
    for _si, s in sites:
        if s.ref != ref:
            continue
        for a in s.alts:
            if a not in index:
                alts.append(a)
                index[a] = len(alts)

    dosage = np.full(n_samples, -1, dtype=np.int8)
    qual = np.full(n_samples, np.nan, dtype=np.float32)
    dropped = 0
    for si, s in sites:
        qual[si] = np.float32(s.qual)
        if not s.alleles:
            continue                   # no GT block: call stays missing
        if s.ref != ref and s.ref not in index:
            # not a swap — an incompatible variant shape at this
            # position: reject the whole record (string-level ALT
            # collisions must not smuggle it in)
            dropped += 1
            continue
        local = (s.ref,) + s.alts      # this record's allele strings
        dose = 0
        ok = True
        for a in s.alleles:
            if a is None or not (0 <= a < len(local)):
                ok = False             # '.' or out-of-range index
                break
            canon = index.get(local[a])
            if canon is None:
                # a swap record calling an allele the canonical set
                # never saw: unusable — sentinel, counted
                ok = False
                dropped += 1
                break
            dose += 1 if canon != 0 else 0
        if ok:
            dosage[si] = min(dose, 127)
    return HarmonizedSite(
        chrom=sites[0][1].chrom, pos=sites[0][1].pos,
        n_allele=1 + len(alts), dosage=dosage, qual=qual,
        duplicates=duplicates, dropped=dropped)
