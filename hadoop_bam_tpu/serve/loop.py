"""ServeLoop: the long-running multi-tenant region-query server.

The PR-5 ``QueryEngine`` answers one batch and exits; production shape
is a RESIDENT server.  ``ServeLoop`` owns:

- one long-lived ``QueryEngine`` (host chunk LRU + metadata stay warm
  across requests, many client threads feed it safely);
- the device-resident ``DeviceTileCache`` tier above it — a warm query
  whose tiles are resident never touches fetch/inflate/host_decode and
  goes straight to the jitted interval-filter step;
- the ``Prefetcher`` (adjacent-window decode at background pool
  priority) and ``TenantQuotas`` (per-tenant admission + priority
  classes).

Threading model: clients call ``submit()`` from any thread and get a
``concurrent.futures.Future``; tenant admission blocks (bounded) on the
CLIENT's thread, then the job enters one priority heap.  A single
DISPATCHER thread drains the heap and does every jax call — device
dispatch stays single-threaded, exactly the FeedPipeline discipline —
while decode parallelism lives in the shared pool.  Each job runs under
the SUBMITTER's contextvars snapshot, so a client inside a
``MetricsContext`` gets its own isolated numbers even though the
serving and pool threads are shared (pinned by tests).

Span/metric taxonomy (PR-6 obs layer; all Prometheus-exportable):
``serve.request_wall`` / ``serve.tile_build_wall`` /
``serve.filter_wall`` spans, ``serve.latency_s`` end-to-end histogram
(enqueue -> result, admission wait included), ``serve.queue_wait_s``,
``serve.tile_hits/misses/evictions``, ``serve.prefetch_issued/useful``,
and ``query.deadline_misses`` for jobs that finish past their budget.
"""
from __future__ import annotations

import concurrent.futures as cf
import contextvars
import dataclasses
import heapq
import itertools
import threading
import time
from collections import OrderedDict
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from hadoop_bam_tpu.config import DEFAULT_CONFIG, HBamConfig
from hadoop_bam_tpu.obs import flight
from hadoop_bam_tpu.obs.context import ensure_trace
from hadoop_bam_tpu.obs.slo import SloEngine
from hadoop_bam_tpu.query.engine import QueryEngine, _I32_MAX
from hadoop_bam_tpu.serve.prefetch import Prefetcher
from hadoop_bam_tpu.serve.tenancy import TenantQuotas, priority_rank
from hadoop_bam_tpu.plan.executor import (
    SERVE_TILE_DAG, SourceIR, select_chunk_source, select_plane,
)
from hadoop_bam_tpu.serve.tiles import (
    INTERVAL_PROJECTION, DeviceTileCache, TileBuilder,
    device_build_chunk, make_tile_filter_step, tile_key,
)
from hadoop_bam_tpu.utils.errors import (
    PLAN, CorruptDataError, PlanError, TransientIOError, classify_error,
)
from hadoop_bam_tpu.utils.metrics import (
    METRICS, base_metrics, current_metrics,
)


@dataclasses.dataclass
class ServeResult:
    """One served region: the match count is always computed (tile
    path); ``records`` materialize only when asked for.  ``extra``
    carries projection-specific aggregates (the cohort plane reports
    ``n_samples`` / ``mean_af`` / ``quarantined`` through it) and
    rides the wire doc verbatim."""
    region: str
    count: int
    n_candidates: int
    tile_hits: int               # chunks served from resident tiles
    tile_misses: int             # chunks that needed a tile build
    records: Optional[List[object]] = None
    extra: Optional[Dict[str, object]] = None


@dataclasses.dataclass(order=True)
class _Job:
    rank: int                    # priority class (lower first)
    seq: int                     # FIFO within a class
    tenant: str = dataclasses.field(compare=False)
    path: str = dataclasses.field(compare=False)
    regions: Sequence[str] = dataclasses.field(compare=False)
    want_records: bool = dataclasses.field(compare=False)
    deadline: object = dataclasses.field(compare=False)
    admission: object = dataclasses.field(compare=False)   # entered CM
    future: cf.Future = dataclasses.field(compare=False)
    ctx: contextvars.Context = dataclasses.field(compare=False)
    t_enqueue: float = dataclasses.field(compare=False)
    # cohort-slice request: ``path`` is a cohort manifest JSON and the
    # regions slice the joined [variants, samples] tensor
    cohort: bool = dataclasses.field(compare=False, default=False)


class ServeLoop:
    """The resident server (module docstring).  Use as a context
    manager, or ``start()``/``stop()`` explicitly; ``submit()``
    auto-starts."""

    def __init__(self, config: HBamConfig = DEFAULT_CONFIG,
                 engine: Optional[QueryEngine] = None, mesh=None,
                 fleet=None):
        self.config = config
        self.engine = engine if engine is not None else QueryEngine(
            config=config, mesh=mesh)
        # the serving fleet (serve/fleet.py): explicit injection wins
        # (tests drive injectable clocks); otherwise auto-built when the
        # config names a replica id AND a peer roster.  None = the
        # single-replica serving every prior PR shipped, untouched.
        if fleet is None and getattr(config, "serve_replica_id", None) \
                and getattr(config, "serve_peers", ""):
            from hadoop_bam_tpu.serve.fleet import Fleet
            fleet = Fleet(config)
        self.fleet = fleet
        self.tiles = DeviceTileCache(
            int(getattr(config, "serve_tile_cache_bytes", 512 << 20)))
        self.tenants = TenantQuotas(config)
        self.prefetcher = Prefetcher(self.engine, config)
        # SLO burn accounting (obs/slo.py): per-tenant latency
        # objectives over the server's PROCESS-GLOBAL metrics — client
        # MetricsContexts isolate per-request numbers, so the serving
        # path mirrors its latency observations into base_metrics()
        # where the engine (and the metrics transport op) read them
        self.slo = SloEngine(
            tick_s=float(getattr(config, "slo_tick_s", 10.0)),
            min_events=int(getattr(config, "slo_min_events", 64)))
        self.slo_metrics = base_metrics()
        self.slo_latency_s = float(getattr(config, "slo_latency_s", 1.0))
        self.slo_target = float(getattr(config, "slo_target", 0.99))
        self.slo.ensure_latency("latency/_all", "serve.latency_s",
                                self.slo_latency_s, self.slo_target)
        self.tenants.slo_engine = self.slo
        # tenants with mirrored per-tenant series, LRU-bounded: tenant
        # strings are CLIENT input, and without eviction every distinct
        # string would grow the process-global metrics forever (the
        # SV801 discipline; the quota LRU bounds gates, not metric keys)
        self._slo_tenants: "OrderedDict[str, bool]" = OrderedDict()
        # flight-recorder disk dumps: configured from this loop's config
        # when set (unset leaves the process-wide recorder as-is, so a
        # directory installed by the CLI or a test is not clobbered)
        fdir = getattr(config, "flight_dump_dir", None)
        if fdir:
            flight.recorder().configure(
                dump_dir=fdir,
                dump_cap=int(getattr(config, "flight_dump_cap", 16)))
        self.tile_cap = int(getattr(config, "serve_tile_records", 4096))
        self._builder: Optional[TileBuilder] = None
        self._cohort = None          # lazy cohort/serving.CohortServer
        self._cond = threading.Condition()
        self._heap: List[_Job] = []
        self._seq = itertools.count()
        self._thread: Optional[threading.Thread] = None
        self._stopping = False

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "ServeLoop":
        with self._cond:
            if self._thread is None or not self._thread.is_alive():
                self._stopping = False
                self._thread = threading.Thread(
                    target=self._dispatch_loop, name="hbam-serve",
                    daemon=True)
                self._thread.start()
        if self.fleet is not None:
            self.fleet.start()
        return self

    def stop(self) -> None:
        with self._cond:
            self._stopping = True
            self._cond.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=30.0)
        if self.fleet is not None:
            self.fleet.stop()
        self.prefetcher.stop()
        # anything still queued will never run: fail it loudly as
        # retryable (a restarting server is a transient condition)
        with self._cond:
            leftovers, self._heap = self._heap, []
        for job in leftovers:
            self._finish_admission(job)
            job.future.set_exception(
                TransientIOError("serve loop stopped before this "
                                 "request was dispatched — retry",
                                 retry_after_s=1.0))
        if self._builder is not None:
            self._builder.close()

    def __enter__(self) -> "ServeLoop":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- client surface ------------------------------------------------------

    def submit(self, path: str, regions: Sequence[str], *,
               tenant: str = "default", priority: str = "interactive",
               deadline_s: Optional[float] = None,
               want_records: bool = False,
               cohort: bool = False) -> cf.Future:
        """Enqueue one request (a path + its regions) for serving.

        Blocks (bounded) on THIS thread for tenant admission — the
        backpressure lands on the flooding client — then returns a
        Future of ``[ServeResult, ...]``.  Over-quota tenants shed with
        ``TransientIOError``; bad parameters raise ``PlanError``.

        With ``cohort=True``, ``path`` names a cohort manifest JSON and
        each region is answered from the device-resident joined dosage
        tiles (cohort/serving.py) instead of the per-file index path."""
        if not regions:
            raise PlanError("submit() needs at least one region")
        rank = priority_rank(priority)
        with self._cond:
            if self._stopping:
                # a stopped loop sheds instead of silently resurrecting:
                # restart is an explicit start() by whoever owns the loop
                raise TransientIOError("serve loop is stopped — retry "
                                       "after it restarts",
                                       retry_after_s=1.0)
        if self._thread is None:
            self.start()
        # request identity: join the transport/CLI trace when one is
        # active, mint one for direct library callers — the contextvars
        # snapshot below carries it to the dispatcher, the decode pool
        # and the staging packer, so every span of this request shares
        # one trace_id end to end
        with ensure_trace(op="serve.submit", tenant=tenant,
                          deadline_s=deadline_s):
            # entered HERE (client thread: admission wait + shed happen
            # to the submitter); exited by the dispatcher when the job
            # finishes
            admission = self.tenants.admit(tenant, deadline_s,
                                           priority=priority)
            deadline = admission.__enter__()
            job = _Job(rank=rank, seq=next(self._seq), tenant=tenant,
                       path=path, regions=list(regions),
                       want_records=bool(want_records), deadline=deadline,
                       admission=admission, future=cf.Future(),
                       ctx=contextvars.copy_context(),
                       t_enqueue=time.perf_counter(), cohort=bool(cohort))
        with self._cond:
            if self._stopping:
                self._finish_admission(job)
                raise TransientIOError("serve loop is stopping — retry",
                                       retry_after_s=1.0)
            heapq.heappush(self._heap, job)
            self._cond.notify()
        return job.future

    def query(self, path: str, regions: Sequence[str],
              **kwargs) -> List[ServeResult]:
        """Blocking convenience: ``submit(...).result()``."""
        return self.submit(path, regions, **kwargs).result()

    def stats(self) -> Dict[str, object]:
        out = {"tiles": self.tiles.stats(),
               "chunks": self.engine.cache.stats(),
               "prefetch": self.prefetcher.stats(),
               "tenants": self.tenants.stats()}
        if self._cohort is not None:
            out["cohort"] = self._cohort.stats()
        return out

    def health(self) -> Dict[str, object]:
        """The degraded-mode diagnosis surface (``{"op": "health"}`` on
        the wire, and the CLI's shutdown report): loop liveness plus
        every adaptive-policy state — tenant breakers, the resilience
        registry's fault domains (decode-ladder + quarantine circuits),
        registry fault pressure, and whether prefetch auto-paused."""
        from hadoop_bam_tpu import resilience
        from hadoop_bam_tpu.plan.executor import plane_report

        reg = resilience.registry()
        with self._cond:
            stopping = self._stopping
            queued = len(self._heap)
        from hadoop_bam_tpu.utils import pools

        return {
            "status": "stopping" if stopping else "serving",
            "queued": queued,
            # the routing this process would decide right now, per
            # driver family (plan/executor.select_plane — display only,
            # consumes no breaker probes): what `hbam top` shows when
            # an operator asks "which plane is this server actually on"
            "planes": plane_report(self.config),
            "fault_pressure": round(reg.fault_pressure(), 4),
            "open_breakers": reg.open_breakers(),
            "domains": reg.states(),
            "tenant_breakers": self.tenants.breaker_states(),
            "prefetch": self.prefetcher.stats(),
            "tiles": self.tiles.stats(),
            # the live-ops additions: recent flight-recorder state (the
            # ring a breaker trip would dump), SLO burn rates, and pool
            # occupancy — the surfaces `hbam top` renders
            "flight": flight.recorder().stats(),
            "slo": self.slo.summary(self.slo_metrics),
            "pool": pools.pool_stats(),
            # fleet view: membership/ownership, per-peer breakers,
            # degraded flag, peer-fetch + hedge counters (None when
            # this process serves single-replica)
            "fleet": (self.fleet.states()
                      if self.fleet is not None else None),
        }

    # -- dispatcher ----------------------------------------------------------

    @staticmethod
    def _finish_admission(job: _Job) -> None:
        try:
            job.admission.__exit__(None, None, None)
        except Exception:  # noqa: BLE001 — release must never mask results
            pass

    def _dispatch_loop(self) -> None:
        while True:
            with self._cond:
                while not self._heap and not self._stopping:
                    self._cond.wait(0.1)
                if self._stopping:
                    return
                job = heapq.heappop(self._heap)
            try:
                # run under the SUBMITTER's contextvars snapshot: the
                # client's MetricsContext (and anything the decode pool
                # inherits from here) stays isolated per client
                job.ctx.run(self._run_job, job)
            except BaseException as e:  # noqa: BLE001 — keep serving
                if not job.future.done():
                    job.future.set_exception(e)

    def _run_job(self, job: _Job) -> None:
        t_run = time.perf_counter()
        METRICS.observe("serve.queue_wait_s", t_run - job.t_enqueue)
        try:
            with METRICS.span("serve.request_wall", tenant=job.tenant,
                              regions=len(job.regions)):
                results = [self._serve_region(job, region)
                           for region in job.regions]
            # outcome is recorded BEFORE the future resolves: a client
            # that saw its request fail and immediately retries must
            # find the breaker already fed (recording after set_result
            # races the next submit)
            self.tenants.record_outcome(job.tenant, None)
            job.future.set_result(results)
        except BaseException as e:  # noqa: BLE001 — crosses to the client
            # feed the tenant's half-open breaker: repeated serving
            # failures open it and the tenant sheds at admission until
            # a cooled-down probe succeeds (PLAN-class rejections are
            # the client's problem and never count)
            self.tenants.record_outcome(job.tenant, e)
            # an unhandled (non-PLAN) serving error is incident-grade:
            # snapshot the flight ring while the request's trace is
            # still the active context
            if classify_error(e) != PLAN:
                flight.recorder().dump("serve_error", error=str(e))
            job.future.set_exception(e)
        finally:
            lat = time.perf_counter() - job.t_enqueue
            METRICS.observe("serve.latency_s", lat)
            # mirror into the process-global metrics the SLO engine and
            # the metrics transport op read (a client MetricsContext
            # isolates the per-request view; the server still needs its
            # own aggregate), plus the per-tenant series hbam top and
            # the per-tenant SLO objectives consume.  Tenant cardinality
            # is bounded by the TenantQuotas LRU upstream of here.
            m = self.slo_metrics
            if current_metrics() is not m:
                # not already recorded there by the METRICS proxy above
                m.observe("serve.latency_s", lat)
            self._note_slo_tenant(job.tenant)
            m.observe(f"serve.latency_s.{job.tenant}", lat)
            m.count(f"serve.requests.{job.tenant}")
            self.slo.ensure_latency(
                f"latency/{job.tenant}",
                f"serve.latency_s.{job.tenant}",
                self.slo_latency_s, self.slo_target)
            self.slo.tick(m)
            if job.deadline is not None and job.deadline.expired:
                job.deadline.book_miss()
            self._finish_admission(job)

    def _note_slo_tenant(self, tenant: str) -> None:
        """Track (and LRU-bound) the tenants with mirrored per-tenant
        series; evicting one discards its metric keys so arbitrary
        client tenant strings cannot grow the process-global Metrics
        without bound.  Dispatcher-thread only."""
        lru = self._slo_tenants
        if tenant in lru:
            lru.move_to_end(tenant)
            return
        lru[tenant] = True
        cap = max(1, int(getattr(self.config, "serve_max_tenants", 64)))
        while len(lru) > cap:
            old, _ = lru.popitem(last=False)
            self.slo_metrics.discard_series(
                f"serve.latency_s.{old}", f"serve.requests.{old}")

    def _builder_or_make(self) -> TileBuilder:
        if self._builder is None:
            mesh = self.engine._mesh_or_make()
            self._builder = TileBuilder(
                mesh, self.tile_cap,
                int(getattr(self.config, "serve_ring_slots", 3)))
        return self._builder

    def _cohort_or_make(self):
        if self._cohort is None:
            from hadoop_bam_tpu.cohort.serving import CohortServer
            self._cohort = CohortServer(self.engine._mesh_or_make(),
                                        self.config)
        return self._cohort

    def _serve_region(self, job: _Job, region: str) -> ServeResult:
        if job.cohort:
            # the cohort plane: joined [variants, samples] tiles in the
            # SAME device cache, keyed by the manifest identity
            return self._cohort_or_make().serve(
                job.path, region, self.tiles,
                want_records=job.want_records, deadline=job.deadline)
        engine = self.engine
        job.deadline.check("serve resolve")
        meta = engine._file_meta(job.path)
        iv, ranges = engine._resolve(meta, region)
        chunks = engine._coalesce(ranges, meta.kind)
        builder = self._builder_or_make()
        step = make_tile_filter_step(builder.mesh)
        rid = meta.ref_names.index(iv.rname)
        iv_dev = builder.put_interval([
            rid, min(iv.start, int(_I32_MAX)), min(iv.end, int(_I32_MAX))])

        fleet = self.fleet
        degraded = fleet.degraded() if fleet is not None else False
        if degraded:
            fleet.note_degraded()
        # cold-tile plane routing, decided ONCE per request: the same
        # select_plane discipline the batch drivers use, over the
        # serve-tile DAG.  Records mode always builds from the host
        # chunk (the materializer needs its columns anyway — a device
        # build would just decode the chunk twice).
        ladder = None
        device_plane = False
        if not job.want_records:
            if self.config.adaptive_planes:
                from hadoop_bam_tpu.config import resolve_inflate_backend
                from hadoop_bam_tpu.resilience.domains import decode_ladder
                ladder = decode_ladder(
                    meta.path, resolve_inflate_backend(self.config),
                    self.config)
            decision = select_plane(SourceIR(meta.path, meta.kind),
                                    SERVE_TILE_DAG, self.config,
                                    ladder=ladder)
            device_plane = decision.plane == "device"
        count = 0
        n_candidates = 0
        tile_hits = 0
        tile_misses = 0
        peer_chunks = 0
        rows_per_chunk: List[Tuple[Tuple, np.ndarray, int]] = []
        for s, e in chunks:
            job.deadline.check("serve chunk")
            key = tile_key(meta.ident, meta.kind, s, e,
                           builder.n_dev, builder.cap)
            tiles = self.tiles.get(key)
            if tiles is None:
                tile_misses += 1
                value = None
                if fleet is not None:
                    # chunk-source routing is the executor's decision
                    # (plan/executor.select_chunk_source — the
                    # select_plane discipline applied to the fleet), the
                    # loop only consumes it
                    okey = (meta.ident, (s, e), INTERVAL_PROJECTION)
                    owner_ids = fleet.membership.owners_for(
                        okey, fleet.replication)
                    source, _why = select_chunk_source(
                        tile_cached=False,
                        fleet_owned=fleet.replica_id in owner_ids,
                        degraded=degraded,
                        want_records=job.want_records,
                        peer_ready=any(pid in fleet.peers
                                       for pid in owner_ids))
                    if source == "peer":
                        try:
                            value = fleet.fetch_chunk(
                                job.path, okey, s, e,
                                deadline=job.deadline)
                            peer_chunks += 1
                        except (TransientIOError, CorruptDataError,
                                RuntimeError, OSError, ValueError):
                            # every owner failed/hedged out: decode
                            # locally — sick peers never fail a request
                            # this replica can answer itself (the
                            # deadline still binds the fallback)
                            METRICS.count("fleet.peer_fallback_local")
                            value = None
                device_blame = None
                if value is None and device_plane:
                    # cold miss on the device plane: tokens resolve and
                    # the (rid, pos1, end1) columns unpack entirely
                    # on-mesh — no host inflate, no host record decode.
                    # None = the chunk declined (over-wide/over-cap/
                    # cut record) and takes the host oracle, which is
                    # not a device fault; an EXCEPTION is, and demotes
                    # through the ladder to the host build below
                    try:
                        tiles = device_build_chunk(
                            builder, meta.ident, meta.path, s, e,
                            self.config)
                    except Exception as exc:  # noqa: BLE001 — demotion
                        if ladder is None or not ladder.demotable(
                                "device", exc):
                            raise
                        device_blame = exc
                        tiles = None
                    if tiles is not None and ladder is not None:
                        ladder.record_success("device")
                if tiles is None:
                    if value is None:
                        value = engine._chunk(meta, s, e)
                        # ticks serve.prefetch_useful when the host
                        # chunk was decoded ahead of need
                        self.prefetcher.was_prefetched(
                            engine.chunk_key(meta, s, e))
                        if fleet is not None:
                            fleet.note_local_decode()
                    tiles = builder.build(meta.ident, value)
                    if ladder is not None and device_blame is not None:
                        # host plane decoded the same chunk fine: the
                        # device failure was plane-local — charge it
                        ladder.confirm_failure("device", device_blame)
                    quarantined = (int(value["n"]) == 0
                                   and int(value["nbytes"]) == 0)
                else:
                    # device builds can't be quarantined spans: the
                    # skip_bad_spans knob gates the device plane off
                    # entirely (select_plane), and bad bytes raise
                    quarantined = False
                if not quarantined:
                    self.tiles.put(key, tiles)
                else:
                    # a QUARANTINED chunk (skip_bad_spans healing path:
                    # n=0 AND nbytes=0 — a genuinely empty chunk always
                    # accounts >= 64 bytes) serves as empty but is NOT
                    # cached at either tier, so a healed transient fault
                    # re-decodes instead of returning empty forever
                    METRICS.count("serve.tiles_uncached_quarantine")
            else:
                tile_hits += 1
            n_candidates += tiles.n
            masks: List[np.ndarray] = []
            with METRICS.span("serve.filter_wall"):
                for g in tiles.groups:
                    keep, hits = step(*g.cols, g.counts, iv_dev)
                    # count-only serving reads just the [n_dev] match
                    # counts — a few bytes off the mesh; the full mask
                    # materializes only for records mode
                    count += int(np.asarray(hits).sum())
                    if job.want_records:
                        masks.append(np.asarray(keep))
            if job.want_records and masks:
                rows_per_chunk.append((
                    (s, e), self._flat_rows(masks, builder), tiles.n))
        records = None
        if job.want_records:
            records = self._materialize(meta, rows_per_chunk)
        METRICS.count("serve.requests")
        self.prefetcher.note(meta, iv)
        extra = None
        if fleet is not None:
            # fleet provenance rides the wire doc verbatim: which
            # replica answered, whether it was partitioned (degraded
            # mode serves owned data instead of erroring), and how many
            # chunks arrived pre-decoded from peers
            extra = {"replica": fleet.replica_id}
            if degraded:
                extra["degraded"] = True
            if peer_chunks:
                extra["peer_chunks"] = peer_chunks
        return ServeResult(region=region, count=count,
                           n_candidates=n_candidates,
                           tile_hits=tile_hits, tile_misses=tile_misses,
                           records=records, extra=extra)

    @staticmethod
    def _flat_rows(masks: List[np.ndarray], builder: TileBuilder
                   ) -> np.ndarray:
        """Chunk-local row indices of kept rows, undoing the serial
        group/device packing of ``TileBuilder.build``."""
        rows: List[int] = []
        per_group = builder.n_dev * builder.cap
        for g_idx, k in enumerate(masks):
            for dev in range(builder.n_dev):
                hit = np.flatnonzero(k[dev])
                rows.extend(g_idx * per_group + dev * builder.cap + hit)
        return np.asarray(sorted(rows), dtype=np.int64)

    def _materialize(self, meta, rows_per_chunk) -> List[object]:
        """Host record objects for kept rows: the host chunk tier has
        (or re-decodes, byte-identically) the materializer state."""
        out: List[object] = []
        for (s, e), rows, _n in rows_per_chunk:
            value = self.engine._chunk(meta, s, e)
            for row in rows:
                out.append(QueryEngine._materialize(meta, value, int(row)))
        return out
