"""Per-tenant admission quotas + priority classes for ``hbam serve``.

One tenant flooding the server must degrade THAT tenant, not its
neighbours.  This module layers multi-tenancy onto the PR-5
``QueryScheduler`` — reused unchanged, one instance per tenant:

- each tenant gets its own bounded admission gate
  (``serve_tenant_max_in_flight`` running + ``serve_tenant_queue_depth``
  waiting); a tenant past both sheds ITS OWN load with
  ``TransientIOError`` while every other tenant admits normally;
- admission happens on the SUBMITTING client's thread (backpressure
  lands on the flooder), and the admitted slot is held until the
  dispatcher finishes the request;
- priority classes order the dispatcher's queue: ``interactive``
  requests jump ahead of ``batch`` backfill, so a batch tenant
  saturating its quota cannot push an interactive tenant's p99 past its
  deadline (the isolation contract, pinned in tests/test_serve.py);
- idle tenant gates are LRU-evicted past ``serve_max_tenants`` — a
  long-running server accepting arbitrary tenant strings must not grow
  a scheduler per string forever (the SV801 bound).
"""
from __future__ import annotations

import contextlib
import threading
import time
from collections import OrderedDict
from typing import Callable, Dict, Optional

from hadoop_bam_tpu.config import DEFAULT_CONFIG, HBamConfig
from hadoop_bam_tpu.query.scheduler import QueryScheduler
from hadoop_bam_tpu.utils.errors import PlanError

# lower sorts first in the dispatch heap
PRIORITIES: Dict[str, int] = {"interactive": 0, "batch": 1}


def priority_rank(priority: str) -> int:
    try:
        return PRIORITIES[priority]
    except KeyError:
        raise PlanError(
            f"unknown priority class {priority!r}; choose from "
            f"{sorted(PRIORITIES)}") from None


class TenantQuotas:
    """The per-tenant gate registry (module docstring)."""

    def __init__(self, config: HBamConfig = DEFAULT_CONFIG,
                 clock: Callable[[], float] = time.monotonic):
        self.max_in_flight = int(
            getattr(config, "serve_tenant_max_in_flight", 4))
        self.queue_depth = int(
            getattr(config, "serve_tenant_queue_depth", 16))
        self.max_tenants = int(getattr(config, "serve_max_tenants", 64))
        self.default_deadline_s: Optional[float] = getattr(
            config, "query_deadline_s", None)
        self._clock = clock
        self._lock = threading.Lock()
        self._tenants: "OrderedDict[str, QueryScheduler]" = OrderedDict()

    def scheduler(self, tenant: str) -> QueryScheduler:
        """This tenant's admission gate (created on first use; idle gates
        LRU-evict past ``max_tenants``)."""
        if not isinstance(tenant, str) or not tenant:
            raise PlanError(f"tenant must be a non-empty string, "
                            f"got {tenant!r}")
        with self._lock:
            sched = self._tenants.get(tenant)
            if sched is not None:
                self._tenants.move_to_end(tenant)
                return sched
            if len(self._tenants) >= self.max_tenants:
                # evict the least-recently-used IDLE gate; busy gates
                # (admitted work outstanding) are skipped — evicting one
                # would orphan its in-flight accounting
                for name in list(self._tenants):
                    if self._tenants[name].in_flight == 0:
                        self._tenants.pop(name)
                        break
            sched = QueryScheduler(self.max_in_flight, self.queue_depth,
                                   self.default_deadline_s,
                                   clock=self._clock)
            self._tenants[tenant] = sched
            return sched

    @contextlib.contextmanager
    def admit(self, tenant: str, deadline_s: Optional[float] = None):
        """The tenant's ``QueryScheduler.admit`` — blocking bounded
        admission on the CALLER's thread, yielding the enqueue-anchored
        ``Deadline``.  Guards the handout window: if the idle-LRU
        eviction dropped this tenant's gate between lookup and
        admission, the admitted slot would live on an orphaned
        scheduler (splitting the tenant's quota across instances), so
        after admitting we re-validate membership — reinstalling the
        gate if it was evicted, or retrying on the replacement a racing
        creator installed."""
        while True:
            sched = self.scheduler(tenant)
            with sched.admit(deadline_s) as deadline:
                with self._lock:
                    live = self._tenants.get(tenant)
                    if live is None:
                        # evicted while idle in the handout window; we
                        # now hold an admitted slot, so it is not idle:
                        # reinstall it as the tenant's one true gate
                        self._tenants[tenant] = sched
                        live = sched
                if live is sched:
                    yield deadline
                    return
            # a racing creator installed a different gate: the slot we
            # took on the orphan is released by the with-exit above;
            # re-admit on the live gate

    def stats(self) -> Dict[str, Dict[str, float]]:
        with self._lock:
            return {name: {"in_flight": sched.in_flight}
                    for name, sched in self._tenants.items()}
