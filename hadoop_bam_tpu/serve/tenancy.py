"""Per-tenant admission quotas + priority classes for ``hbam serve``.

One tenant flooding the server must degrade THAT tenant, not its
neighbours.  This module layers multi-tenancy onto the PR-5
``QueryScheduler`` — reused unchanged, one instance per tenant:

- each tenant gets its own bounded admission gate
  (``serve_tenant_max_in_flight`` running + ``serve_tenant_queue_depth``
  waiting); a tenant past both sheds ITS OWN load with
  ``TransientIOError`` while every other tenant admits normally;
- admission happens on the SUBMITTING client's thread (backpressure
  lands on the flooder), and the admitted slot is held until the
  dispatcher finishes the request;
- priority classes order the dispatcher's queue: ``interactive``
  requests jump ahead of ``batch`` backfill, so a batch tenant
  saturating its quota cannot push an interactive tenant's p99 past its
  deadline (the isolation contract, pinned in tests/test_serve.py);
- idle tenant gates are LRU-evicted past ``serve_max_tenants`` — a
  long-running server accepting arbitrary tenant strings must not grow
  a scheduler per string forever (the SV801 bound);
- each tenant also carries a half-open ``CircuitBreaker``
  (``resilience/breaker.py``): repeated serving failures for one tenant
  (its files corrupt, its requests chronically deadline-missing) OPEN
  its breaker and the tenant sheds instantly with a ``retry_after_s``
  hint — no decode work spent — while every other tenant serves
  normally; after the cooldown one half-open probe request re-tests,
  and a success heals the tenant.  ``ServeLoop`` records the outcomes.
"""
from __future__ import annotations

import contextlib
import threading
import time
from collections import OrderedDict
from typing import Callable, Dict, Optional

from hadoop_bam_tpu.config import DEFAULT_CONFIG, HBamConfig
from hadoop_bam_tpu.query.scheduler import QueryScheduler
from hadoop_bam_tpu.resilience.breaker import CircuitBreaker
from hadoop_bam_tpu.utils.errors import (
    PlanError, TransientIOError, classify_error, PLAN,
)
from hadoop_bam_tpu.utils.metrics import METRICS

# lower sorts first in the dispatch heap
PRIORITIES: Dict[str, int] = {"interactive": 0, "batch": 1}


def priority_rank(priority: str) -> int:
    try:
        return PRIORITIES[priority]
    except KeyError:
        raise PlanError(
            f"unknown priority class {priority!r}; choose from "
            f"{sorted(PRIORITIES)}") from None


class TenantQuotas:
    """The per-tenant gate registry (module docstring)."""

    def __init__(self, config: HBamConfig = DEFAULT_CONFIG,
                 clock: Callable[[], float] = time.monotonic):
        self.max_in_flight = int(
            getattr(config, "serve_tenant_max_in_flight", 4))
        self.queue_depth = int(
            getattr(config, "serve_tenant_queue_depth", 16))
        self.max_tenants = int(getattr(config, "serve_max_tenants", 64))
        self.default_deadline_s: Optional[float] = getattr(
            config, "query_deadline_s", None)
        # SLO shed pressure (obs/slo.py): when ServeLoop installs its
        # engine here, a tenant whose FAST burn window is alight sheds
        # its batch-priority admissions — backfill is the load that can
        # wait while the budget recovers; interactive traffic still
        # admits (and still feeds the breaker on real failures)
        self.slo_engine = None
        self.slo_shed_batch = bool(getattr(config, "slo_shed_batch",
                                           True))
        self._clock = clock
        self._config = config
        self._lock = threading.Lock()
        self._tenants: "OrderedDict[str, QueryScheduler]" = OrderedDict()
        # tenant -> half-open breaker; same LRU life as the scheduler
        # gates (evicting an idle tenant forgets its failure history —
        # acceptable: a returning tenant starts CLOSED)
        self._breakers: "OrderedDict[str, CircuitBreaker]" = OrderedDict()

    def scheduler(self, tenant: str) -> QueryScheduler:
        """This tenant's admission gate (created on first use; idle gates
        LRU-evict past ``max_tenants``)."""
        if not isinstance(tenant, str) or not tenant:
            raise PlanError(f"tenant must be a non-empty string, "
                            f"got {tenant!r}")
        with self._lock:
            sched = self._tenants.get(tenant)
            if sched is not None:
                self._tenants.move_to_end(tenant)
                return sched
            if len(self._tenants) >= self.max_tenants:
                # evict the least-recently-used IDLE gate; busy gates
                # (admitted work outstanding) are skipped — evicting one
                # would orphan its in-flight accounting
                for name in list(self._tenants):
                    if self._tenants[name].in_flight == 0:
                        self._tenants.pop(name)
                        self._breakers.pop(name, None)
                        break
            sched = QueryScheduler(
                self.max_in_flight, self.queue_depth,
                self.default_deadline_s, clock=self._clock,
                shed_retry_after_s=float(getattr(
                    self._config, "serve_shed_retry_after_s", 0.1)))
            self._tenants[tenant] = sched
            return sched

    def breaker(self, tenant: str) -> CircuitBreaker:
        """This tenant's half-open failure breaker (created CLOSED on
        first use, bounded by the same tenant LRU)."""
        with self._lock:
            br = self._breakers.get(tenant)
            if br is None:
                cfg = self._config
                br = CircuitBreaker(
                    failure_threshold=float(getattr(
                        cfg, "breaker_failure_threshold", 3.0)),
                    window_s=float(getattr(cfg, "breaker_window_s", 30.0)),
                    cooldown_s=float(getattr(
                        cfg, "breaker_cooldown_s", 5.0)),
                    half_open_probes=int(getattr(
                        cfg, "breaker_half_open_probes", 1)),
                    clock=self._clock, name=f"tenant/{tenant}")
                while len(self._breakers) >= self.max_tenants:
                    self._breakers.popitem(last=False)
                self._breakers[tenant] = br
            else:
                self._breakers.move_to_end(tenant)
            return br

    def record_outcome(self, tenant: str,
                       exc: Optional[BaseException]) -> None:
        """Feed one finished request's outcome into the tenant breaker.
        PLAN-class failures (the client's malformed request) and
        admission sheds don't count — they prove nothing about whether
        serving this tenant's data works; everything else (corrupt
        files, deadline misses surfacing as TransientIOError from the
        serve path, unknown errors) does."""
        br = self.breaker(tenant)
        if exc is None:
            br.record_success()
            return
        if classify_error(exc) == PLAN:
            return
        br.record_failure()

    def slo_shed_check(self, tenant: str, priority: str) -> None:
        """Shed batch-priority work for a tenant whose fast SLO burn
        window is alight (``obs/slo.py``); interactive work admits."""
        if (self.slo_engine is None or not self.slo_shed_batch
                or priority != "batch"):
            return
        window = self.slo_engine.burning(f"latency/{tenant}")
        if window != "fast":
            return
        METRICS.count("slo.batch_shed")
        retry = float(getattr(self._config, "serve_shed_retry_after_s",
                              0.1))
        raise TransientIOError(
            f"tenant {tenant!r} is burning its latency SLO budget "
            f"({window} window) — batch work shed so interactive "
            f"traffic recovers; retry in {retry:g}s",
            retry_after_s=retry)

    @contextlib.contextmanager
    def admit(self, tenant: str, deadline_s: Optional[float] = None,
              priority: str = "interactive"):
        """The tenant's ``QueryScheduler.admit`` — blocking bounded
        admission on the CALLER's thread, yielding the enqueue-anchored
        ``Deadline``.  Guards the handout window: if the idle-LRU
        eviction dropped this tenant's gate between lookup and
        admission, the admitted slot would live on an orphaned
        scheduler (splitting the tenant's quota across instances), so
        after admitting we re-validate membership — reinstalling the
        gate if it was evicted, or retrying on the replacement a racing
        creator installed.

        The tenant's breaker gates FIRST: an OPEN tenant sheds here —
        before any queueing — with the cooldown remainder as the
        ``retry_after_s`` hint; a HALF_OPEN tenant admits exactly its
        probe budget (the probes' outcomes decide heal vs re-open)."""
        br = self.breaker(tenant)
        if not br.allow():
            METRICS.count("resilience.tenant_shed")
            raise TransientIOError(
                f"tenant {tenant!r} circuit is {br.state} after repeated "
                f"serving failures — retry in {br.retry_after_s():.3g}s",
                retry_after_s=br.retry_after_s() or None)
        self.slo_shed_check(tenant, priority)
        while True:
            sched = self.scheduler(tenant)
            with sched.admit(deadline_s) as deadline:
                with self._lock:
                    live = self._tenants.get(tenant)
                    if live is None:
                        # evicted while idle in the handout window; we
                        # now hold an admitted slot, so it is not idle:
                        # reinstall it as the tenant's one true gate
                        self._tenants[tenant] = sched
                        live = sched
                if live is sched:
                    yield deadline
                    return
            # a racing creator installed a different gate: the slot we
            # took on the orphan is released by the with-exit above;
            # re-admit on the live gate

    def stats(self) -> Dict[str, Dict[str, float]]:
        with self._lock:
            names = list(self._tenants)
            scheds = dict(self._tenants)
            breakers = dict(self._breakers)
        out: Dict[str, Dict[str, float]] = {}
        for name in names:
            row: Dict[str, float] = {"in_flight": scheds[name].in_flight}
            br = breakers.get(name)
            if br is not None:
                row["breaker"] = br.state
            out[name] = row
        return out

    def breaker_states(self) -> Dict[str, dict]:
        """Health-surface snapshot of every tracked tenant breaker."""
        with self._lock:
            breakers = dict(self._breakers)
        return {name: br.snapshot() for name, br in breakers.items()}
