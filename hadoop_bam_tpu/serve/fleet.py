"""The serving fleet: replicated tile ownership, failover, hedged fetch.

``hbam serve`` stays one process; a FLEET is N of them, each running
this module against a static peer roster (``--peers``/``--replica-id``).
Tile ownership is assigned by rendezvous hashing over
``(file_identity, chunk_range, projection)`` with
``fleet_replication``-way replication (``serve/membership.py``), so a
chunk's decoded tile lives device-resident on R replicas and everyone
else peer-fetches the decoded columns instead of re-paying
fetch + inflate + host_decode — the Compressed-Resident idea at fleet
scale, and the reason a replica loss does not cold-start the tile tier.

The robustness stack around every peer call:

- ``chaos.fire("serve.peer")`` first — the injectable seam the chaos
  soak drives (delay / transient / disconnect, like the five other
  points);
- a per-peer circuit breaker, ``("serve","peer",replica_id)`` in the
  PROCESS resilience registry: a dead peer stops being dialed after
  ``breaker_failure_threshold`` decayed failures, and REJOINS only
  through half-open probes (the heartbeat doubles as the probe);
- the originating request's enqueue-anchored deadline rides the wire
  (``deadline_s`` + ``enqueue_age_s``), so a peer re-anchors to the
  budget the CLIENT started with — admission wait and every prior hop
  already count against it (PR 8's anchor, fleet-wide);
- a hedge to the next-ranked replica when the call overruns the
  decaying-p95 soft deadline (``jobs/speculate.UnitLatency``; first
  result wins, the loser is abandoned to its socket timeout);
- total peer failure falls back to LOCAL decode — peers being sick
  never fails a request that this replica can answer itself.

Membership is heartbeat-driven (one daemon thread, injectable clock);
a replica that lost quorum keeps serving what it owns with
``extra.degraded=true`` instead of erroring.  Forwarded work adopts the
originating trace id and every span is stamped with this process's
``replica_id`` (``obs/context.set_replica_id``), so one fleet request
exports as ONE Chrome-trace tree across processes.
"""
from __future__ import annotations

import base64
import concurrent.futures as cf
import json
import socket as _socket
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from hadoop_bam_tpu.config import DEFAULT_CONFIG, HBamConfig
from hadoop_bam_tpu.jobs.speculate import UnitLatency
from hadoop_bam_tpu.obs import flight
from hadoop_bam_tpu.obs.context import current_trace_id, set_replica_id
from hadoop_bam_tpu.resilience import chaos, registry
from hadoop_bam_tpu.serve.membership import Membership
from hadoop_bam_tpu.utils.errors import (
    CorruptDataError, PLAN, PlanError, TransientIOError, classify_error,
)
from hadoop_bam_tpu.utils.metrics import METRICS

# sanity cap on the wire-carried enqueue age: a peer must re-anchor to
# the originating budget, not to a corrupted/hostile timestamp
_MAX_ENQUEUE_AGE_S = 3600.0
_HEDGE_WORKERS = 4


def parse_peers(spec: str) -> "Dict[str, Tuple[str, int]]":
    """``"a=127.0.0.1:7001,b=127.0.0.1:7002"`` -> id -> (host, port).
    A bare ``host:port`` entry uses the address itself as the id."""
    out: Dict[str, Tuple[str, int]] = {}
    for entry in (spec or "").split(","):
        entry = entry.strip()
        if not entry:
            continue
        if "=" in entry:
            pid, addr = entry.split("=", 1)
        else:
            pid, addr = entry, entry
        host, _, port = addr.rpartition(":")
        if not host or not port.isdigit():
            raise PlanError(
                f"bad peer spec {entry!r} — want id=host:port "
                f"(or host:port)")
        out[pid.strip()] = (host.strip(), int(port))
    return out


def effective_deadline_s(deadline_s, enqueue_age_s) -> Optional[float]:
    """The budget a peer request has LEFT, re-anchored to the
    originating request's enqueue instant: the original ``deadline_s``
    minus the elapsed age carried on the wire.  Returns None when the
    request is unbudgeted; clamps at 0.0 (an exhausted budget must
    surface as an immediate deadline miss, never a fresh budget)."""
    if deadline_s is None:
        return None
    d = float(deadline_s)
    try:
        age = float(enqueue_age_s) if enqueue_age_s is not None else 0.0
    except (TypeError, ValueError):
        age = 0.0
    if not (0.0 <= age <= _MAX_ENQUEUE_AGE_S):
        age = 0.0
    return max(0.0, d - age)


def _peer_error(resp: Dict) -> BaseException:
    """Rehydrate a peer's wire error into the PR-1 taxonomy class the
    local policy boundaries expect (breakers, retry, quarantine)."""
    msg = f"peer error: {resp.get('error')}"
    kind = resp.get("kind")
    if kind == "transient":
        return TransientIOError(msg,
                                retry_after_s=resp.get("retry_after_s"))
    if kind == "plan":
        return PlanError(msg)
    if kind == "corrupt":
        return CorruptDataError(msg)
    return RuntimeError(msg)


def encode_chunk_doc(value: Dict) -> Dict:
    """The ``{"op": "chunk"}`` response payload: the decoded interval
    columns of ``QueryEngine._chunk`` as base64 little-endian int32 —
    everything a peer's TileBuilder needs, records excluded (record
    materialization is always local)."""
    def b64(col) -> str:
        a = np.ascontiguousarray(np.asarray(col, np.int32))
        return base64.b64encode(a.tobytes()).decode("ascii")

    return {"n": int(value["n"]), "nbytes": int(value["nbytes"]),
            "cols": {k: b64(value[k]) for k in ("rid", "pos1", "end1")}}


def decode_chunk_doc(doc: Dict) -> Dict:
    """Inverse of ``encode_chunk_doc``: a ``_chunk``-shaped value dict
    (empty ``records`` — peer-fetched tiles serve counts; records mode
    routes local).  Shape-checked: a short/oversized column is CORRUPT
    (the taxonomy quarantine understands), not an index error later."""
    n = int(doc["n"])
    cols = doc["cols"]
    out: Dict[str, object] = {"n": n, "nbytes": int(doc["nbytes"]),
                              "records": []}
    for k in ("rid", "pos1", "end1"):
        a = np.frombuffer(base64.b64decode(cols[k]), dtype=np.int32)
        if a.shape[0] != n:
            raise CorruptDataError(
                f"peer chunk column {k!r} has {a.shape[0]} rows, "
                f"expected {n}")
        out[k] = a
    return out


class Fleet:
    """One replica's view of the serving fleet (module docstring).

    Owns the heartbeat thread and a small hedge executor; attached to a
    ``ServeLoop`` (``loop.fleet``) which consults
    ``plan.executor.select_chunk_source`` per chunk and calls
    ``fetch_chunk`` for peer-owned tiles."""

    def __init__(self, config: HBamConfig = DEFAULT_CONFIG, *,
                 replica_id: Optional[str] = None,
                 peers: Optional[Dict[str, Tuple[str, int]]] = None,
                 clock: Callable[[], float] = time.monotonic):
        self.config = config
        rid = replica_id if replica_id is not None else \
            getattr(config, "serve_replica_id", None)
        if not rid:
            raise PlanError("a fleet replica needs a replica id "
                            "(--replica-id / config.serve_replica_id)")
        self.replica_id = str(rid)
        self.peers = dict(peers) if peers is not None else \
            parse_peers(getattr(config, "serve_peers", ""))
        self.peers.pop(self.replica_id, None)   # never dial ourselves
        self.replication = max(1, int(
            getattr(config, "fleet_replication", 2)))
        self.heartbeat_s = float(getattr(config, "fleet_heartbeat_s", 0.25))
        self.peer_timeout_s = float(
            getattr(config, "fleet_peer_timeout_s", 2.0))
        self.membership = Membership(
            self.replica_id, list(self.peers),
            suspicion_s=float(getattr(config, "fleet_suspicion_s", 1.5)),
            eviction_s=float(getattr(config, "fleet_eviction_s", 5.0)),
            clock=clock)
        # hedged peer-fetch soft deadline: the fleet's OWN decaying
        # latency distribution (jobs/speculate.py), floored well below
        # the straggler default — peer RTTs are milliseconds, not span
        # decodes
        self.latency = UnitLatency.for_peer_fetch(config)
        self._clock = clock
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._executor: Optional[cf.ThreadPoolExecutor] = None
        self._lock = threading.Lock()
        # provenance counters (states() + the bench's fleet arm)
        self.peer_fetch_ok = 0
        self.peer_fetch_failed = 0
        self.local_decodes = 0       # chunks this replica host-decoded
        self.chunks_served = 0       # inbound {"op":"chunk"} answered
        self.hedges = 0
        self.hedge_wins = 0
        self.degraded_serves = 0
        # every span this process emits carries the replica id from now
        # on — the trace-hop contract (one fleet request, one tree)
        set_replica_id(self.replica_id)

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "Fleet":
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._heartbeat_loop, name="hbam-fleet-hb",
                daemon=True)
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
        with self._lock:
            ex, self._executor = self._executor, None
        if ex is not None:
            ex.shutdown(wait=False)

    def _executor_or_make(self) -> cf.ThreadPoolExecutor:
        with self._lock:
            if self._executor is None:
                self._executor = cf.ThreadPoolExecutor(
                    max_workers=_HEDGE_WORKERS,
                    thread_name_prefix="hbam-fleet")
            return self._executor

    # -- membership / heartbeats ---------------------------------------------

    def degraded(self) -> bool:
        return not self.membership.has_quorum()

    def _domain(self, peer_id: str):
        return registry().domain("serve", "peer", peer_id,
                                 config=self.config)

    def _heartbeat_loop(self) -> None:
        while not self._stop.wait(self.heartbeat_s):
            try:
                self.heartbeat_round()
            except Exception:  # noqa: BLE001 — liveness loop never dies
                METRICS.count("fleet.heartbeat_errors")

    def heartbeat_round(self) -> None:
        """One heartbeat pass: dial every peer whose breaker allows it
        (in HALF_OPEN the heartbeat IS the probe — success heals the
        breaker before any query traffic flows), then age membership.
        Public so tests drive rounds deterministically."""
        for pid in list(self.peers):
            dom = self._domain(pid)
            if not dom.breaker.allow():
                continue
            try:
                self._peer_call(
                    pid, {"op": "heartbeat", "from": self.replica_id},
                    timeout_s=min(self.peer_timeout_s,
                                  max(self.heartbeat_s, 0.05)))
            except (OSError, ValueError, TransientIOError,
                    CorruptDataError, RuntimeError) as e:
                dom.record_failure(e)
                continue
            dom.record_success()
            if self.membership.observe(pid):
                flight.recorder().record_transition(
                    "fleet", f"peer.{pid}", "rejoined")
        for pid, state in self.membership.sweep():
            rec = flight.recorder()
            rec.record_transition("fleet", f"peer.{pid}", state)
            if state == "evicted":
                # a member leaving the fleet is incident-grade: keep
                # the ring around the moment ownership re-ranked
                rec.dump("fleet_eviction",
                         error=f"peer {pid} evicted from membership")

    def note_local_decode(self) -> None:
        """ServeLoop accounting: a chunk this replica host-decoded
        (the denominator of the bench's cross-replica tile hit rate)."""
        with self._lock:
            self.local_decodes += 1

    def note_degraded(self) -> None:
        with self._lock:
            self.degraded_serves += 1
        METRICS.count("fleet.degraded_serves")

    def observe_peer(self, peer_id) -> None:
        """An INBOUND heartbeat (transport ``{"op":"heartbeat"}``) is as
        good an observation as our own round trip."""
        if isinstance(peer_id, str) and peer_id in self.peers:
            if self.membership.observe(peer_id):
                flight.recorder().record_transition(
                    "fleet", f"peer.{peer_id}", "rejoined")

    # -- the peer wire -------------------------------------------------------

    def _peer_call(self, peer_id: str, doc: Dict,
                   timeout_s: float) -> Dict:
        """One JSONL round trip to a peer over the existing TCP
        transport.  The ``serve.peer`` chaos point fires first, so an
        injected delay/transient/disconnect exercises exactly the
        breaker/hedge/fallback stack a real peer fault would."""
        chaos.fire("serve.peer")
        host, port = self.peers[peer_id]
        timeout = max(0.02, float(timeout_s))
        with _socket.create_connection((host, port),
                                       timeout=timeout) as s:
            s.settimeout(timeout)
            f = s.makefile("rw", encoding="utf-8", newline="\n")
            f.write(json.dumps(doc) + "\n")
            f.flush()
            line = f.readline()
        if not line:
            raise TransientIOError(
                f"fleet peer {peer_id} closed the connection "
                f"without answering")
        resp = json.loads(line)
        if not isinstance(resp, dict):
            raise CorruptDataError(
                f"fleet peer {peer_id} answered a non-object line")
        if "error" in resp:
            raise _peer_error(resp)
        return resp

    def _timed_call(self, peer_id: str, doc: Dict,
                    timeout_s: float) -> Dict:
        """A breaker-fed, latency-observed peer call (hedge executor
        body).  PLAN-class answers are the REQUEST's fault and never
        feed the peer's breaker — the tenancy discipline, applied to
        peers."""
        dom = self._domain(peer_id)
        t0 = time.perf_counter()
        try:
            resp = self._peer_call(peer_id, doc, timeout_s)
        except BaseException as e:  # noqa: BLE001 — classified below
            if classify_error(e) != PLAN:
                dom.record_failure(e)
            METRICS.count("fleet.peer_call_errors")
            raise
        dom.record_success()
        self.latency.observe(time.perf_counter() - t0)
        return resp

    # -- hedged peer-fetch ---------------------------------------------------

    def fetch_chunk(self, path: str, key: Tuple, s: int, e: int,
                    deadline=None) -> Dict:
        """Peer-fetch one decoded chunk from its rendezvous owners:
        breaker-gated, deadline-budgeted (re-anchored on the wire),
        hedged to the next-ranked replica past the decaying-p95 soft
        deadline — first result wins.  Raises ``TransientIOError`` when
        no owner could answer (the caller's cue to decode locally)."""
        cands = [pid for pid in
                 self.membership.owners_for(key, self.replication + 1)
                 if pid != self.replica_id and pid in self.peers]
        if not cands:
            raise TransientIOError("no fleet peer owns this chunk")
        doc = {"op": "chunk", "path": path, "s": int(s), "e": int(e),
               "from": self.replica_id}
        tid = current_trace_id()
        if tid is not None:
            doc["trace"] = tid
        if deadline is not None and deadline.seconds is not None:
            rem = deadline.remaining()
            if rem is not None and rem <= 0:
                deadline.check("fleet peer fetch")
            # the ORIGINATING enqueue anchor, carried as elapsed age:
            # the peer rebuilds the same remaining budget in its own
            # clock domain (monotonic anchors never cross processes raw)
            doc["deadline_s"] = deadline.seconds
            doc["enqueue_age_s"] = round(
                max(0.0, deadline.seconds - (rem or 0.0)), 6)
        try:
            resp = self._fetch_hedged(cands, doc, deadline)
            value = decode_chunk_doc(resp)
        except BaseException:
            with self._lock:
                self.peer_fetch_failed += 1
            METRICS.count("fleet.peer_fetch_failed")
            raise
        with self._lock:
            self.peer_fetch_ok += 1
        METRICS.count("fleet.peer_fetch_ok")
        return value

    def _fetch_hedged(self, cands: Sequence[str], doc: Dict,
                      deadline=None) -> Dict:
        ex = self._executor_or_make()
        timeout_s = self.peer_timeout_s
        if deadline is not None:
            rem = deadline.remaining()
            if rem is not None:
                timeout_s = min(timeout_s, max(rem, 0.02))
        futs: List[Tuple[cf.Future, bool]] = []   # (future, is_hedge)
        errors: List[str] = []
        idx = 0

        def launch(is_hedge: bool) -> bool:
            nonlocal idx
            while idx < len(cands):
                pid = cands[idx]
                idx += 1
                if not self._domain(pid).breaker.allow():
                    errors.append(f"{pid}: breaker open")
                    continue
                futs.append((ex.submit(self._timed_call, pid, dict(doc),
                                       timeout_s), is_hedge))
                return True
            return False

        if not launch(False):
            raise TransientIOError(
                "all fleet owners unavailable: " + "; ".join(errors))
        soft = self.latency.soft_deadline_s()
        hedged = False
        while futs:
            if deadline is not None:
                deadline.check("fleet peer fetch")
            wait = (soft if (soft is not None and not hedged)
                    else 0.05)
            if deadline is not None:
                rem = deadline.remaining()
                if rem is not None:
                    wait = min(wait, max(rem, 0.001))
            done, _ = cf.wait([f for f, _ in futs], timeout=wait,
                              return_when=cf.FIRST_COMPLETED)
            for f, is_hedge in list(futs):
                if f not in done:
                    continue
                futs.remove((f, is_hedge))
                try:
                    resp = f.result()
                except BaseException as e:  # noqa: BLE001 — next owner
                    errors.append(str(e))
                    continue
                if is_hedge:
                    with self._lock:
                        self.hedge_wins += 1
                    METRICS.count("fleet.hedge_wins")
                return resp
            if futs and not done and not hedged and soft is not None:
                # primary overran its decaying-p95 soft deadline: race
                # the next-ranked replica, first result wins (the loser
                # is abandoned to its socket timeout)
                hedged = True
                if launch(True):
                    with self._lock:
                        self.hedges += 1
                    METRICS.count("fleet.hedges")
            if not futs and not launch(hedged):
                break
        raise TransientIOError(
            "fleet peer fetch failed on every owner: "
            + ("; ".join(errors) or "no candidates"))

    # -- inbound peer-op serving (transport side) ----------------------------

    def serve_chunk(self, engine, doc: Dict) -> Dict:
        """Answer a peer's ``{"op": "chunk"}``: the host-decoded chunk
        columns from the warm ``ChunkCache`` (single-flight; safe on
        the transport reader thread — the prefetcher already decodes
        there-adjacent from pool threads).  The peer's re-anchored
        deadline binds the decode."""
        from hadoop_bam_tpu.query.scheduler import Deadline

        path = doc.get("path")
        if not isinstance(path, str) or "s" not in doc or "e" not in doc:
            raise PlanError('peer chunk request needs "path", "s", "e"')
        eff = effective_deadline_s(doc.get("deadline_s"),
                                   doc.get("enqueue_age_s"))
        dl = Deadline(eff, clock=self._clock)
        dl.check("peer chunk")
        meta = engine._file_meta(path)
        value = engine._chunk(meta, int(doc["s"]), int(doc["e"]))
        dl.check("peer chunk decode")
        with self._lock:
            self.chunks_served += 1
        METRICS.count("fleet.chunks_served")
        return encode_chunk_doc(value)

    # -- health surface ------------------------------------------------------

    def states(self) -> Dict[str, object]:
        reg = registry()
        breakers = {}
        for pid in sorted(self.peers):
            d = reg.domain("serve", "peer", pid, config=self.config)
            breakers[pid] = d.snapshot()
        with self._lock:
            counters = {
                "peer_fetch_ok": self.peer_fetch_ok,
                "peer_fetch_failed": self.peer_fetch_failed,
                "local_decodes": self.local_decodes,
                "chunks_served": self.chunks_served,
                "hedges": self.hedges,
                "hedge_wins": self.hedge_wins,
                "degraded_serves": self.degraded_serves,
            }
        soft = self.latency.soft_deadline_s()
        return {"replica_id": self.replica_id,
                "replication": self.replication,
                "degraded": self.degraded(),
                "membership": self.membership.states(),
                "peer_breakers": breakers,
                "hedge_soft_deadline_s": (round(soft, 6)
                                          if soft is not None else None),
                **counters}
