"""Predictive prefetch: decode the chunks a client is ABOUT to ask for.

Rapidgzip's access-layer insight (PAPERS.md) applied to region serving:
a zipf-skewed workload walks hot neighbourhoods, so after serving
``chr20:a-b`` the adjacent windows are disproportionately likely next.
After every served query the dispatcher calls ``note()``, which

1. predicts the next ``serve_prefetch_depth`` same-width windows past
   the served interval (and dedups against the per-file recency ring —
   a window served or predicted moments ago is already warm);
2. resolves the predictions through the in-memory index (cheap, on the
   dispatcher thread) to coalesced chunk ranges;
3. submits the EXPENSIVE part — fetch + inflate + host_decode into the
   host ``ChunkCache`` — to the shared decode pool at BACKGROUND
   priority (``utils.pools.submit(priority="bg")``), so prefetch soaks
   idle decode capacity but can never starve foreground admission.

Device-tile assembly stays on the dispatcher thread (all jax calls stay
single-threaded): a later query for a prefetched window finds its chunk
host-decoded and only pays the tile build + transfer — the cheap tail.

Usefulness accounting: ``serve.prefetch_issued`` counts submitted chunk
decodes, ``serve.prefetch_useful`` ticks when a later foreground query
consumes a prefetched chunk; their ratio is the bench row's
``prefetch_hit_rate``.
"""
from __future__ import annotations

import threading
from collections import OrderedDict, deque
from typing import Dict, Optional, Tuple

from hadoop_bam_tpu.config import DEFAULT_CONFIG, HBamConfig
from hadoop_bam_tpu.utils.errors import PlanError, TransientIOError
from hadoop_bam_tpu.utils.metrics import METRICS

_MAX_FILES = 64          # per-file recency rings kept (LRU)
_MAX_TRACKED = 1024      # prefetched-chunk provenance entries kept


class Prefetcher:
    """Recency+adjacency predictive prefetch (module docstring).

    ``note()`` runs on the dispatcher thread only; the submitted decode
    closures run on pool threads but touch only the thread-safe
    single-flight ``ChunkCache`` path."""

    def __init__(self, engine, config: HBamConfig = DEFAULT_CONFIG):
        self.engine = engine
        self.enabled = bool(getattr(config, "serve_prefetch", True))
        self.depth = max(0, int(getattr(config, "serve_prefetch_depth", 2)))
        self.recent_window = max(1, int(
            getattr(config, "serve_recent_regions", 16)))
        self.pause_pressure = float(getattr(
            config, "serve_prefetch_pause_pressure", 3.0))
        self.paused_total = 0
        self._config = config
        self._lock = threading.Lock()
        # per-file recency rings: ident -> deque of (rid, beg, end)
        self._recent: "OrderedDict[Tuple, deque]" = OrderedDict()
        # provenance of chunks decoded ahead of need: chunk key ->
        # False while the background decode is queued/running, True once
        # it COMPLETED (bounded LRU).  Only completed prefetches count
        # as useful — a mark consumed while still queued saved nothing
        self._prefetched: "OrderedDict[Tuple, bool]" = OrderedDict()
        self._outstanding: list = []      # live bg futures (drained)
        self.issued = 0
        self.useful = 0

    # -- dispatcher-side hooks ----------------------------------------------

    def was_prefetched(self, chunk_key: Tuple) -> bool:
        """Consume the provenance mark for a chunk a foreground query is
        now using; ticks ``serve.prefetch_useful`` once per chunk — and
        only when the background decode actually COMPLETED first (a
        prefetch the foreground overtook did no useful work and must
        not inflate the bench's prefetch_hit_rate)."""
        with self._lock:
            done = self._prefetched.pop(chunk_key, None)
            if not done:
                return False
            self.useful += 1
        METRICS.count("serve.prefetch_useful")
        return True

    def fault_paused(self) -> bool:
        """Auto-pause under fault pressure: when the resilience
        registry's decayed failure count crosses the config threshold,
        speculative decode is exactly the wrong way to spend pool
        capacity (every prefetched chunk competes with the retries and
        demoted-plane re-decodes that are healing the system) — so
        prediction pauses and resumes by itself as the pressure decays."""
        from hadoop_bam_tpu import resilience

        if self.pause_pressure <= 0:
            return False
        if resilience.registry().fault_pressure() < self.pause_pressure:
            return False
        self.paused_total += 1
        METRICS.count("serve.prefetch_paused")
        return True

    def note(self, meta, iv) -> None:
        """Record a served interval and issue adjacent-window prefetch."""
        if not self.enabled or self.depth == 0:
            return
        if self.fault_paused():
            return
        rid = meta.ref_names.index(iv.rname)
        width = max(1, iv.end - iv.start + 1)
        with self._lock:
            ring = self._recent.get(meta.ident)
            if ring is None:
                while len(self._recent) >= _MAX_FILES:
                    self._recent.popitem(last=False)
                ring = self._recent[meta.ident] = deque(
                    maxlen=self.recent_window)
            else:
                self._recent.move_to_end(meta.ident)
            ring.append((rid, iv.start, iv.end))
            seen = list(ring)
        for d in range(1, self.depth + 1):
            beg = iv.end + 1 + (d - 1) * width
            end = beg + width - 1
            if any(r == rid and b <= beg and e >= end for r, b, e in seen):
                continue          # recently served/predicted: warm already
            with self._lock:
                ring.append((rid, beg, end))
            self._prefetch_window(meta, iv.rname, beg, end)

    def _prefetch_window(self, meta, rname: str, beg: int, end: int) -> None:
        from hadoop_bam_tpu.utils import pools

        try:
            iv, ranges = self.engine._resolve(meta, f"{rname}:{beg}-{end}")
        except PlanError:
            return                # off the contig end / unindexable: skip
        chunks = self.engine._coalesce(ranges, meta.kind)
        pool = pools.decode_pool(self._config)
        for s, e in chunks:
            key = self.engine.chunk_key(meta, s, e)
            if self.engine.cache.contains(key):
                continue          # already decoded (or being decoded)
            with self._lock:
                if key in self._prefetched:
                    continue
                while len(self._prefetched) >= _MAX_TRACKED:
                    self._prefetched.popitem(last=False)
                self._prefetched[key] = False   # completion flips it
                self.issued += 1
            METRICS.count("serve.prefetch_issued")
            try:
                fut = pools.submit(pool, self._decode_quietly, meta, s, e,
                                   priority="bg")
            except Exception:  # noqa: BLE001 — speculative work only
                # a failed SUBMISSION (pool shutting down, injected
                # pool.submit chaos) must never surface through the
                # foreground serve path — the prediction just stays cold
                METRICS.count("serve.prefetch_errors")
                with self._lock:
                    self._prefetched.pop(key, None)
                continue
            with self._lock:
                self._outstanding.append(fut)
                self._outstanding = [f for f in self._outstanding
                                     if not f.done()]

    def _decode_quietly(self, meta, s: int, e: int) -> None:
        """Pool-side chunk decode into the host cache; speculative work
        never raises into the server (a transient fault just means the
        prediction stays cold)."""
        key = self.engine.chunk_key(meta, s, e)
        try:
            self.engine._chunk(meta, s, e)
        except (TransientIOError, PlanError, OSError, ValueError):
            METRICS.count("serve.prefetch_errors")
            with self._lock:
                self._prefetched.pop(key, None)
        else:
            with self._lock:
                if key in self._prefetched:
                    self._prefetched[key] = True

    # -- lifecycle -----------------------------------------------------------

    def drain(self, timeout: Optional[float] = 10.0) -> None:
        """Wait for every outstanding prefetch decode (tests + shutdown)."""
        import concurrent.futures as cf
        with self._lock:
            pending = list(self._outstanding)
            self._outstanding = []
        if pending:
            cf.wait(pending, timeout=timeout)

    def stop(self) -> None:
        from hadoop_bam_tpu.utils.pools import cancel_background
        cancel_background()
        self.drain(timeout=5.0)

    def stats(self) -> Dict[str, float]:
        with self._lock:
            issued, useful = self.issued, self.useful
        return {"issued": issued, "useful": useful,
                "hit_rate": (useful / issued) if issued else 0.0,
                "paused_total": self.paused_total}
