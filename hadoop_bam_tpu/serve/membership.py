"""Fleet membership: rendezvous tile ownership + heartbeat liveness.

Two small, separately-testable pieces of the serving fleet
(``serve/fleet.py`` composes them with the transport and breakers):

**Rendezvous (highest-random-weight) ownership.**  Every cacheable tile
key ``(file_identity, chunk_range, projection)`` hashes against every
member id; the R highest weights own the tile.  The properties the
fleet leans on, both pinned by tests:

- *deterministic across processes*: the weight is a keyed BLAKE2b
  digest, never Python's salted ``hash()``, so every replica computes
  the same owner ranking from the same member set with no coordination;
- *minimal disruption*: removing a member only re-ranks the keys that
  member owned (each surviving member's weight for a key never
  changes), so a replica death moves exactly the dead replica's share —
  no ring to rebuild, no bulk ownership churn.

**Heartbeat membership.**  Liveness is observation-driven: the fleet's
heartbeat loop calls ``observe(peer)`` on every successful round trip
and ``sweep()`` on every tick.  A peer silent past
``fleet_suspicion_s`` turns SUSPECT (still ranked — a hiccup must not
thrash ownership); silent past ``fleet_eviction_s`` it is EVICTED and
drops out of the owner ranking entirely.  A heartbeat from an evicted
peer re-admits it to the member set immediately — but the fleet's
per-peer circuit breaker (``("serve","peer",id)``) still gates actual
traffic, so a healed replica takes requests only after its half-open
probes succeed (the rejoin contract the failover test pins).

The clock is injectable (the ``resilience/breaker.py`` convention) so
suspicion/eviction transitions are tested without real time passing.
Quorum is majority of the CONFIGURED member set (self + static peer
list): a replica that can see fewer than half its fleet serves what it
owns in degraded mode instead of erroring (``extra.degraded`` on the
wire) — partition behavior, not an outage.
"""
from __future__ import annotations

import hashlib
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from hadoop_bam_tpu.utils.errors import PlanError
from hadoop_bam_tpu.utils.metrics import METRICS

ALIVE = "alive"
SUSPECT = "suspect"
EVICTED = "evicted"


def rendezvous_weight(key: Tuple, member: str) -> int:
    """The HRW weight of ``member`` for ``key``: a keyed 8-byte BLAKE2b
    digest, deterministic across processes and Python runs (``hash()``
    is salted per process and can never be used here)."""
    h = hashlib.blake2b(repr(key).encode("utf-8"), digest_size=8,
                        key=member.encode("utf-8")[:64])
    return int.from_bytes(h.digest(), "big")


def rank_members(key: Tuple, members: Sequence[str]) -> List[str]:
    """Members ranked by descending rendezvous weight for ``key``
    (owner first).  Ties break on the id so the order is total."""
    return sorted(members,
                  key=lambda m: (rendezvous_weight(key, m), m),
                  reverse=True)


def owners(key: Tuple, members: Sequence[str], r: int) -> List[str]:
    """The R-way owner set: the ``r`` highest-ranked members."""
    return rank_members(key, members)[:max(1, int(r))]


class Membership:
    """Heartbeat-observed fleet membership (module docstring).

    Thread-safe: ``observe`` runs on the heartbeat thread AND the
    transport reader threads (an inbound heartbeat is also an
    observation), ``alive_members``/``owners_for`` on the dispatcher.
    """

    def __init__(self, self_id: str, peer_ids: Sequence[str],
                 *, suspicion_s: float = 1.5, eviction_s: float = 5.0,
                 clock: Callable[[], float] = time.monotonic):
        if not self_id:
            raise PlanError("membership needs a non-empty replica id")
        self.self_id = str(self_id)
        self.suspicion_s = float(suspicion_s)
        self.eviction_s = max(float(eviction_s), self.suspicion_s)
        self._clock = clock
        self._lock = threading.Lock()
        now = clock()
        # peer id -> (state, last_observed).  Peers start ALIVE with a
        # fresh timestamp: a booting fleet must not evict everyone
        # before the first heartbeat round completes.
        self._peers: Dict[str, Tuple[str, float]] = {
            str(p): (ALIVE, now) for p in peer_ids if str(p) != self_id}
        self.evictions_total = 0
        self.rejoins_total = 0

    # -- observation ---------------------------------------------------------

    def observe(self, peer_id: str) -> bool:
        """Record a successful heartbeat round trip (either direction).
        Returns True when this observation RE-ADMITTED an evicted peer
        (the fleet logs it as a rejoin; the peer's breaker still gates
        traffic until its half-open probes pass)."""
        pid = str(peer_id)
        with self._lock:
            cur = self._peers.get(pid)
            if cur is None:
                return False          # not in this fleet's static roster
            state, _ = cur
            self._peers[pid] = (ALIVE, self._clock())
            if state == EVICTED:
                self.rejoins_total += 1
                METRICS.count("fleet.rejoins")
                return True
        return False

    def sweep(self) -> List[Tuple[str, str]]:
        """Age observations into SUSPECT/EVICTED transitions; returns
        the ``(peer_id, new_state)`` transitions this sweep made (the
        fleet records them on the flight ring)."""
        out: List[Tuple[str, str]] = []
        now = self._clock()
        with self._lock:
            for pid, (state, seen) in list(self._peers.items()):
                age = now - seen
                if state != EVICTED and age >= self.eviction_s:
                    self._peers[pid] = (EVICTED, seen)
                    self.evictions_total += 1
                    out.append((pid, EVICTED))
                elif state == ALIVE and age >= self.suspicion_s:
                    self._peers[pid] = (SUSPECT, seen)
                    out.append((pid, SUSPECT))
        for pid, state in out:
            METRICS.count(f"fleet.peer_{state}")
        return out

    # -- ownership views -----------------------------------------------------

    def members(self) -> List[str]:
        """Every NON-EVICTED member (self included), sorted — the set
        ownership ranks over.  SUSPECT peers stay ranked: a heartbeat
        hiccup must not move tile ownership; only eviction does."""
        with self._lock:
            ids = [pid for pid, (state, _) in self._peers.items()
                   if state != EVICTED]
        return sorted(ids + [self.self_id])

    def owners_for(self, key: Tuple, r: int) -> List[str]:
        return owners(key, self.members(), r)

    def has_quorum(self) -> bool:
        """Majority of the CONFIGURED fleet visible (self counts)."""
        with self._lock:
            total = len(self._peers) + 1
            visible = 1 + sum(1 for state, _ in self._peers.values()
                              if state != EVICTED)
        return visible * 2 > total

    def states(self) -> Dict[str, object]:
        """Health-surface snapshot."""
        now = self._clock()
        with self._lock:
            peers = {pid: {"state": state,
                           "age_s": round(now - seen, 3)}
                     for pid, (state, seen) in sorted(self._peers.items())}
        return {"self": self.self_id, "peers": peers,
                "quorum": self.has_quorum(),
                "evictions_total": self.evictions_total,
                "rejoins_total": self.rejoins_total}
