"""The second cache tier: DEVICE-RESIDENT decoded interval tiles.

The PR-5 ``ChunkCache`` only avoids re-*reading* — a warm query still
pays host_decode-to-columns plus a fresh ``device_put`` every time.
This module keeps the decoded, sharded ``[n_dev, cap]`` interval
columns (``rid``/``pos1``/``end1`` + per-device counts) resident on the
devices, keyed by ``(file_identity, chunk range, projection)``:

- a TILE HIT skips fetch + inflate + host_decode + transfer entirely
  and goes straight to the jitted interval-filter step — the warm
  serving path touches no host decode work at all;
- the budget is in DEVICE bytes, strict LRU, with proactive
  invalidation: putting a tile for a path whose ``file_identity``
  changed purges every tile of the old identity (the identity is also
  in the key, so even un-purged stale entries can never be served);
- tiles are assembled through a small pinned ``StagingRing``
  (``TileBuilder``): slot buffers are PINNED out of ring circulation
  from ``device_put`` until the transfer is committed, so a cached
  device tile can never be backed by host memory the ring re-leases
  and overwrites (the slot-pinning invariant, proof-tested in
  tests/test_serve.py).

Counters: ``serve.tile_hits`` / ``serve.tile_misses`` /
``serve.tile_evictions`` process-wide, plus per-instance ``stats()``
(the bench's hit-rate source, same convention as ``ChunkCache``).
"""
from __future__ import annotations

import dataclasses
import threading
from collections import OrderedDict
from typing import Dict, Hashable, List, Optional, Tuple

import numpy as np

from hadoop_bam_tpu.resilience import chaos
from hadoop_bam_tpu.utils.metrics import METRICS
from hadoop_bam_tpu.utils.stepcache import BoundedStepCache

# the one projection served today: interval-overlap columns.  Payload
# projections (seq/qual tiles for query-then-analyze fusion) slot in as
# new names without touching the cache.
INTERVAL_PROJECTION = "intervals"


@dataclasses.dataclass
class TileGroup:
    """One sharded device group of a tile set: ``cols`` is the
    (rid, pos1, end1) triple of ``[n_dev, cap]`` int32 device arrays,
    ``counts`` the ``[n_dev]`` int32 per-device row counts (device
    array), ``n`` the live rows in this group."""
    cols: Tuple
    counts: object
    n: int


@dataclasses.dataclass
class TileSet:
    """Every device group of one decoded chunk, plus accounting.
    (Prefetch provenance lives on the HOST chunk in
    ``serve/prefetch.py`` — tiles are always built by the dispatcher.)"""
    groups: List[TileGroup]
    n: int                       # total candidate rows
    nbytes: int                  # device-resident footprint
    ident: Tuple                 # file_identity the tiles decode


def tile_key(ident: Tuple, kind: str, s: int, e: int,
             n_dev: int, cap: int,
             projection: str = INTERVAL_PROJECTION) -> Tuple:
    """(file_identity, region bucket, projection) — plus the mesh/tile
    geometry, because tiles sharded for one mesh shape cannot be served
    to another."""
    return (ident, kind, s, e, projection, n_dev, cap)


class DeviceTileCache:
    """Byte-budgeted LRU of device-resident ``TileSet`` values.

    Thread-safe (serve hits it from the dispatcher thread while stats
    readers poll from transport threads); values are built and consumed
    only on the dispatcher thread, so the lock guards the map, not the
    device arrays."""

    def __init__(self, byte_budget: int = 512 << 20):
        if byte_budget <= 0:
            from hadoop_bam_tpu.utils.errors import PlanError
            raise PlanError(
                f"serve tile cache byte budget must be positive, got "
                f"{byte_budget}")
        self.byte_budget = int(byte_budget)
        self._lock = threading.Lock()
        self._entries: "OrderedDict[Hashable, TileSet]" = OrderedDict()
        self._by_path: Dict[str, set] = {}   # abspath -> live keys
        self._ident_of: Dict[str, Tuple] = {}  # abspath -> newest identity
        self._bytes = 0
        self._hits = 0
        self._misses = 0
        self._evictions = 0
        self._invalidated = 0

    @staticmethod
    def _abspath(key: Hashable) -> str:
        return key[0][0]          # tile_key ident = (abspath, size, mtime)

    def get(self, key: Hashable) -> Optional[TileSet]:
        with self._lock:
            hit = self._entries.get(key)
            if hit is None:
                self._misses += 1
                METRICS.count("serve.tile_misses")
                return None
            self._entries.move_to_end(key)
            self._hits += 1
            METRICS.count("serve.tile_hits")
            return hit

    def put(self, key: Hashable, tiles: TileSet) -> None:
        nbytes = max(0, int(tiles.nbytes))
        path = self._abspath(key)
        with self._lock:
            prev_ident = self._ident_of.get(path)
            if prev_ident is not None and prev_ident != tiles.ident:
                # the file changed on disk: purge every tile of the old
                # identity NOW rather than waiting for LRU pressure —
                # they can never hit again and would squat on the
                # budget.  This runs even when the NEW tile is rejected
                # as oversize below: the stale tiles are dead either way
                self._purge_path_locked(path)
            if nbytes > self.byte_budget:
                METRICS.count("serve.tile_oversize")
                return
            self._ident_of[path] = tiles.ident
            old = self._entries.pop(key, None)
            if old is not None:
                self._bytes -= old.nbytes
            self._entries[key] = tiles
            self._by_path.setdefault(path, set()).add(key)
            self._bytes += nbytes
            while self._bytes > self.byte_budget and len(self._entries) > 1:
                k, v = self._entries.popitem(last=False)
                self._drop_locked(k, v)
                self._evictions += 1
                METRICS.count("serve.tile_evictions")

    def _drop_locked(self, key: Hashable, tiles: TileSet) -> None:
        self._bytes -= tiles.nbytes
        path = self._abspath(key)
        keys = self._by_path.get(path)
        if keys is not None:
            keys.discard(key)
            if not keys:
                self._by_path.pop(path, None)
                self._ident_of.pop(path, None)

    def _purge_path_locked(self, path: str) -> None:
        for k in list(self._by_path.get(path, ())):
            v = self._entries.pop(k, None)
            if v is not None:
                self._drop_locked(k, v)
                self._invalidated += 1
                METRICS.count("serve.tile_invalidations")

    def invalidate_path(self, path: str) -> None:
        """Drop every tile of ``path`` (any identity) — the explicit
        variant of the identity-change purge."""
        import os
        with self._lock:
            self._purge_path_locked(os.path.abspath(path))

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            self._by_path.clear()
            self._ident_of.clear()
            self._bytes = 0

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    @property
    def bytes_used(self) -> int:
        with self._lock:
            return self._bytes

    def stats(self) -> Dict[str, float]:
        with self._lock:
            total = self._hits + self._misses
            return {
                "entries": len(self._entries),
                "bytes": self._bytes,
                "byte_budget": self.byte_budget,
                "hits": self._hits,
                "misses": self._misses,
                "evictions": self._evictions,
                "invalidated": self._invalidated,
                "hit_rate": (self._hits / total) if total else 0.0,
            }


# ---------------------------------------------------------------------------
# device filter step: cached tiles x one query interval
# ---------------------------------------------------------------------------

# bounded (SV801): one entry per (mesh, axis) in live use
_STEP_CACHE = BoundedStepCache(cap=8)


def make_tile_filter_step(mesh, axis: str = "data"):
    """Jitted sharded predicate over a CACHED tile: per-row 1-based
    inclusive overlap of the tile's (rid, pos1, end1) columns against
    ONE query interval ``iv = [rid, beg, end]`` (replicated int32[3]).
    Returns ``(keep, hits)``: the sharded boolean mask and the
    per-device match COUNTS — count-only serving reads just the [n_dev]
    counts (a few bytes off the mesh) and never materializes the mask.

    Unlike ``query.engine.make_overlap_step`` — which bakes the interval
    into per-row columns at pack time — the interval here is a runtime
    argument, so one resident tile serves every query that lands on its
    chunk without repacking or retransferring anything."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from hadoop_bam_tpu.parallel.mesh import shard_map

    key = ("serve_tile_filter", tuple(mesh.devices.flat),
           mesh.axis_names, axis)

    def build():
        def per_device(rid, pos1, end1, count, iv):
            rid, pos1, end1, count = rid[0], pos1[0], end1[0], count[0]
            valid = jnp.arange(rid.shape[0], dtype=jnp.int32) < count
            keep = valid & (rid == iv[0]) & (pos1 <= iv[2]) \
                & (end1 >= iv[1])
            hits = keep.sum(dtype=jnp.int32)
            return keep[None], hits[None]

        fn = shard_map(per_device, mesh=mesh,
                       in_specs=(P(axis), P(axis), P(axis), P(axis), P()),
                       out_specs=(P(axis), P(axis)))
        return jax.jit(fn)

    return _STEP_CACHE.get_or_build(key, build)


# ---------------------------------------------------------------------------
# tile assembly through a pinned staging ring
# ---------------------------------------------------------------------------

class TileBuilder:
    """Assembles decoded chunk columns into sharded device ``TileSet``s
    through a ``StagingRing`` with SLOT PINNING: each group's slot is
    pinned before release, which transfers its buffers OUT of ring
    circulation for the lifetime of the device arrays (the ring mints a
    replacement).  That ownership transfer is what makes device-tile
    caching safe at all — on the CPU backend ``jax.device_put`` may
    zero-copy ALIAS the host buffers, so a recycled slot would silently
    rewrite a cached tile (the churn proof in tests/test_serve.py
    catches exactly this).  All methods run on ONE thread (the serve
    dispatcher); jax never gets called from two threads here."""

    def __init__(self, mesh, cap: int, ring_slots: int = 3):
        import jax  # noqa: F401 — fail early if jax is absent
        from jax.sharding import NamedSharding, PartitionSpec as P

        from hadoop_bam_tpu.parallel.staging import StagingRing, TileSpec

        self.mesh = mesh
        self.n_dev = int(np.prod(mesh.devices.shape))
        self.cap = int(cap)
        self.sharding = NamedSharding(mesh, P("data"))
        self.replicated = NamedSharding(mesh, P())
        # rid pads with -1 so a padding row can never match a real
        # reference id even if a bug ever ignored the count mask
        specs = [TileSpec((), np.int32, -1),
                 TileSpec((), np.int32, 0),
                 TileSpec((), np.int32, 0)]
        self._ring = StagingRing(self.n_dev, self.cap, specs,
                                 max(3, int(ring_slots)))
        self._cancel = threading.Event()
        # replicated-interval LRU (SV801-bounded): zipf-hot regions
        # repeat, so the warm path skips even the tiny iv device_put
        self._iv_cache: "OrderedDict[Tuple[int, int, int], object]" = \
            OrderedDict()

    def put_interval(self, iv_arr) -> object:
        """Replicate a ``[rid, beg, end]`` int32 interval across the
        mesh for the filter step (LRU-cached: repeated hot regions pay
        zero transfers)."""
        import jax
        key = (int(iv_arr[0]), int(iv_arr[1]), int(iv_arr[2]))
        hit = self._iv_cache.get(key)
        if hit is not None:
            self._iv_cache.move_to_end(key)
            return hit
        dev = jax.device_put(np.asarray(iv_arr, np.int32),
                             self.replicated)
        while len(self._iv_cache) >= 256:
            self._iv_cache.popitem(last=False)
        self._iv_cache[key] = dev
        return dev

    def build(self, ident: Tuple, cols: Dict[str, object]) -> TileSet:
        """Sharded device tiles from one decoded chunk's host columns
        (the ``rid``/``pos1``/``end1`` arrays of ``QueryEngine._chunk``).
        Rows pack serially: group g, device d holds rows
        ``[g*n_dev*cap + d*cap, ...+cap)`` of the chunk."""
        import jax

        n = int(cols["n"])
        host = (np.asarray(cols["rid"], np.int32),
                np.asarray(cols["pos1"], np.int32),
                np.asarray(cols["end1"], np.int32))
        groups: List[TileGroup] = []
        nbytes = 0
        if n == 0:
            # empty chunks cache as an empty TileSet: the lookup still
            # hits (no re-decode), the filter loop has nothing to do
            return TileSet(groups=[], n=0, nbytes=64, ident=ident)
        with METRICS.span("serve.tile_build_wall", rows=n):
            per_group = self.n_dev * self.cap
            for base in range(0, n, per_group):
                slot = self._ring.lease(self._cancel)
                counts = slot.counts
                counts[:] = 0
                for dev in range(self.n_dev):
                    lo = base + dev * self.cap
                    k = max(0, min(self.cap, n - lo))
                    for dst, src in zip(slot.arrays, host):
                        if k:
                            dst[dev, :k] = src[lo:lo + k]
                    counts[dev] = k
                # pad rows past each device's count (fresh ring slots
                # arrive pre-padded, but a slot that recirculated from
                # an unpinned use may carry stale rows)
                for spec, dst in zip(self._ring.specs, slot.arrays):
                    for dev in range(self.n_dev):
                        c = int(counts[dev])
                        if c < self.cap:
                            dst[dev, c:] = spec.pad
                dev_arrays = jax.device_put(
                    (slot.arrays[0], slot.arrays[1], slot.arrays[2],
                     counts.copy()), self.sharding)
                # ownership transfer: these buffers now belong to the
                # cached tile; the ring replaces the slot and can never
                # hand this memory out again
                slot.pin()
                slot.release()
                g_rows = int(min(n - base, per_group))
                groups.append(TileGroup(cols=dev_arrays[:3],
                                        counts=dev_arrays[3], n=g_rows))
                nbytes += sum(int(a.nbytes) for a in dev_arrays)
        return TileSet(groups=groups, n=n, nbytes=nbytes + 64,
                       ident=ident)

    def close(self) -> None:
        self._cancel.set()


def device_build_chunk(builder: TileBuilder, ident: Tuple, path: str,
                       s: int, e: int, config) -> Optional[TileSet]:
    """Cold serve-tile build through the token-feed device decode plane:
    host tokenize (native Huffman) -> on-mesh LZ77 resolve + record walk
    + interval unpack (``ops/inflate_device.resolve_walk_intervals``) ->
    sharded device tiles.  The (rid, pos1, end1) columns never exist as
    host arrays — a cold miss on this route does no host inflate and no
    host record decode at all (``pipeline.host_decode_wall`` stays 0).

    Returns None whenever the chunk needs the host oracle instead: an
    over-wide span (> DEVICE_PLANE_MAX_BLOCKS), a CIGAR past the
    device-walk cap, a record-capacity overflow, a record cut at the
    buffer edge, or a malformed record chain — the host path then
    decodes it (and raises the canonical error class if the bytes
    really are bad).  Declining is not a device FAULT, so the caller
    charges no ladder blame for it; BGZF-level corruption raises here
    (inside ``_tokenize_span_tokens``), which IS ladder-demotable."""
    import jax
    import jax.numpy as jnp

    from hadoop_bam_tpu.ops.inflate_device import resolve_walk_intervals
    from hadoop_bam_tpu.ops.rans import _round_pow2
    from hadoop_bam_tpu.parallel.pipeline import _tokenize_span_tokens
    from hadoop_bam_tpu.split.spans import FileVirtualSpan
    from hadoop_bam_tpu.utils import native
    from hadoop_bam_tpu.utils.errors import PlanError

    if not native.available():
        raise PlanError(
            "inflate_backend='device' needs the native tokenizer "
            "(hbam_deflate_tokenize_batch); native library unavailable")
    chunk = _tokenize_span_tokens(path, FileVirtualSpan(path, s, e),
                                  bool(config.check_crc))
    if chunk is None:
        return TileSet(groups=[], n=0, nbytes=64, ident=ident)
    if chunk.used < chunk.n_blocks:
        return None
    # chaos point at the plane's dispatch boundary — the serve loop's
    # ladder demotes an injected fault here to the host tile build
    chaos.fire("device.step", blocks=int(chunk.used))
    B = _round_pow2(max(chunk.used, 8), 8)
    tokens, nt, isz = chunk.tokens, chunk.n_tokens, chunk.isize
    if B != chunk.used:
        tokens = np.vstack(
            [tokens, np.zeros((B - chunk.used, chunk.P), np.uint32)])
        nt = np.concatenate([nt, np.zeros(B - chunk.used, np.int32)])
        isz = np.concatenate([isz, np.zeros(B - chunk.used, np.int32)])
    with METRICS.span("serve.device_resolve_wall", blocks=chunk.used):
        rid, pos1, end1, n_all, tail, bad, over = resolve_walk_intervals(
            jnp.asarray(tokens), jnp.asarray(nt), jnp.asarray(isz),
            jnp.int32(chunk.start), jnp.int32(chunk.stop))
        # ONE bulk fetch of the four verdict scalars per chunk
        n_i, tail_i, bad_i, over_i = [
            int(v) for v in jax.device_get((n_all, tail, bad, over))]
    R = int(rid.shape[0])
    if bad_i or over_i or n_i > R or tail_i < chunk.stop:
        return None
    if n_i == 0:
        return TileSet(groups=[], n=0, nbytes=64, ident=ident)
    per_group = builder.n_dev * builder.cap
    n_groups = -(-n_i // per_group)
    padded = n_groups * per_group
    with METRICS.span("serve.tile_build_wall", rows=n_i):
        def shard(col, fill):
            # kernel outputs already pad (rid=-1, pos1=end1=0) past the
            # walked records; extend with the same fills to the group
            # grid — identical to the TileSpec pads of the host builder
            colp = jnp.pad(col, (0, max(0, padded - R)),
                           constant_values=fill)[:padded]
            return colp.reshape(n_groups, builder.n_dev, builder.cap)

        rid_g = shard(rid, -1)
        pos_g = shard(pos1, 0)
        end_g = shard(end1, 0)
        counts = np.zeros((n_groups, builder.n_dev), np.int32)
        for g in range(n_groups):
            for dev in range(builder.n_dev):
                lo = g * per_group + dev * builder.cap
                counts[g, dev] = max(0, min(builder.cap, n_i - lo))
        groups: List[TileGroup] = []
        nbytes = 0
        for g in range(n_groups):
            dev_arrays = jax.device_put(
                (rid_g[g], pos_g[g], end_g[g], counts[g]),
                builder.sharding)
            g_rows = int(min(n_i - g * per_group, per_group))
            groups.append(TileGroup(cols=dev_arrays[:3],
                                    counts=dev_arrays[3], n=g_rows))
            nbytes += sum(int(a.nbytes) for a in dev_arrays)
    METRICS.count("serve.device_tile_builds")
    return TileSet(groups=groups, n=n_i, nbytes=nbytes + 64, ident=ident)
