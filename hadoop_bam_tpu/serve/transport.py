"""Wire transports for ``hbam serve``: JSONL over stdin/stdout or TCP.

One request per line::

    {"id": 1, "path": "a.bam", "regions": ["chr20:1-5000"],
     "tenant": "web", "priority": "interactive", "deadline_s": 0.5,
     "records": false}

(``region`` singular is accepted too.)  One response line per request,
keyed by ``id`` — responses stream back AS THEY COMPLETE, which with
priority classes is not submission order::

    {"id": 1, "tenant": "web", "latency_ms": 3.1,
     "results": [{"region": "chr20:1-5000", "count": 17,
                  "candidates": 94, "tile_hits": 1, "tile_misses": 0}]}

Failures answer on the same line protocol with the PR-1 taxonomy class
spelled out, so clients can implement retry policy without parsing
message strings — sheds (admission overflow, open tenant breakers, a
stopping loop) additionally carry the server's backoff hint::

    {"id": 2, "error": "...", "kind": "transient", "retry_after_s": 0.1}
    {"id": 3, "error": "...", "kind": "plan"}        # fix the request

``"cohort": true`` marks a cohort-slice request: ``path`` names a
cohort manifest JSON and each region slices the joined
[variants, samples] tensor from device-resident dosage tiles
(cohort/serving.py); results additionally carry ``n_samples`` /
``mean_af`` / ``quarantined``.

``{"op": "health"}`` answers out of band with the loop's breaker and
demotion-ladder state (``ServeLoop.health``) — the liveness/diagnosis
surface a degraded server keeps serving even while it sheds queries.

Fleet ops (serve/fleet.py; answered inline on the reader thread, like
health/metrics, so they work while every tenant sheds):

- ``{"op": "heartbeat", "from": ID}`` — liveness ping; the sender is
  observed into membership (an inbound heartbeat is as good as our own
  round trip) and the reply names this replica.
- ``{"op": "chunk", "path": P, "s": S, "e": E}`` — peer-fetch of one
  host-decoded chunk's interval columns (base64 int32), served from the
  warm ChunkCache so a peer skips fetch+inflate+host_decode.
- ``{"op": "fleet"}`` — the fleet view of health (membership, per-peer
  breakers, degraded flag, hedge counters).

Fleet requests re-anchor deadlines to the ORIGINATING request's enqueue
instant: ``deadline_s`` is the original budget and ``enqueue_age_s``
the elapsed age at send time, so a hop never gets a fresh budget
(PR 8's enqueue anchor, fleet-wide).  Forwarded requests carry the
originating ``trace`` id, which is adopted (validated) instead of
minting a fresh one — one fleet request exports as ONE trace tree, each
span stamped with the replica that did the work.

The TCP flavor is a thread-per-connection ``socketserver`` veneer over
the same per-line handler; every connection funnels into the ONE
``ServeLoop`` dispatcher, so device work stays single-threaded no
matter how many sockets are open.  A dropped connection (real, or a
``serve.transport`` chaos fault) ends THAT stream only: in-flight
responses for it are abandoned at the socket, the dispatcher and every
other connection keep serving (pinned by tests).
"""
from __future__ import annotations

import concurrent.futures as cf
import json
import threading
import time
from typing import Dict, List

from hadoop_bam_tpu.obs.context import trace_context
from hadoop_bam_tpu.resilience import chaos
from hadoop_bam_tpu.serve.fleet import effective_deadline_s
from hadoop_bam_tpu.utils.errors import (
    CircuitBreakerError, CorruptDataError, HBamError, PlanError,
    TransientIOError,
)
from hadoop_bam_tpu.utils.metrics import METRICS


def error_kind(exc: BaseException) -> str:
    """The taxonomy class a failed request reports on the wire."""
    if isinstance(exc, TransientIOError):
        return "transient"
    if isinstance(exc, (PlanError, FileNotFoundError)):
        # a bad path is configuration (file_identity's contract): never
        # retried, never quarantined
        return "plan"
    if isinstance(exc, CorruptDataError):
        return "corrupt"
    return "error"


def error_doc(req_id, exc: BaseException, kind: "str | None" = None,
              trace: "str | None" = None) -> Dict:
    """The wire shape of one failed request: taxonomy kind + the
    server's ``retry_after_s`` backoff hint when the shed carries one.
    ``trace`` echoes the request's trace_id so a client can hand the
    operator the exact id a flight dump / Chrome trace will show."""
    doc = {"id": req_id, "error": str(exc),
           "kind": kind if kind is not None else error_kind(exc)}
    if trace is not None:
        doc["trace"] = trace
    ra = getattr(exc, "retry_after_s", None)
    if ra is not None:
        doc["retry_after_s"] = round(float(ra), 4)
    return doc


def _result_doc(req_id, tenant: str, results, t_enqueue: float,
                trace: "str | None" = None,
                replica: "str | None" = None) -> Dict:
    return {
        "id": req_id,
        "tenant": tenant,
        **({"trace": trace} if trace is not None else {}),
        **({"replica": replica} if replica is not None else {}),
        "latency_ms": round((time.perf_counter() - t_enqueue) * 1e3, 3),
        "results": [
            {"region": r.region, "count": r.count,
             "candidates": r.n_candidates, "tile_hits": r.tile_hits,
             "tile_misses": r.tile_misses,
             # cohort-plane aggregates (n_samples/mean_af/quarantined)
             # ride the result doc verbatim
             **(r.extra if getattr(r, "extra", None) else {}),
             # region records carry to_line(); cohort slice records are
             # already wire-shaped dicts
             **({"records": [rec.to_line() if hasattr(rec, "to_line")
                             else rec for rec in r.records]}
                if r.records is not None else {})}
            for r in results],
    }


def _client_trace(v) -> "str | None":
    """A client-supplied trace id, adopted only when it is sane: a
    short token of [alnum_-] characters.  Anything else (wrong type,
    oversized, control characters) is ignored and a fresh id is minted
    — the id is stamped on every ring entry and incident dump, so an
    attacker-sized string must not ride it."""
    if isinstance(v, str) and 0 < len(v) <= 64 \
            and all(c.isalnum() or c in "-_" for c in v):
        return v
    return None


def _metrics_doc(loop, req: Dict) -> Dict:
    """The ``{"op": "metrics"}`` answer: the server's process-global
    metrics snapshot (mergeable ``to_dict`` form) plus SLO burn rates;
    ``"format": "prometheus"`` returns the text exposition with the
    ``hbam_slo_burn_rate`` gauge series appended instead."""
    from hadoop_bam_tpu.obs.export import prometheus_text
    from hadoop_bam_tpu.utils.metrics import base_metrics

    metrics = getattr(loop, "slo_metrics", None) or base_metrics()
    slo = getattr(loop, "slo", None)
    d = metrics.to_dict()
    if str(req.get("format", "")) == "prometheus":
        text = prometheus_text(d)
        if slo is not None:
            lines = slo.prometheus_lines(d)
            if lines:
                text += "\n".join(lines) + "\n"
        return {"prometheus": text}
    out: Dict = {"metrics": d}
    if slo is not None:
        out["slo"] = slo.burn_rates(d)
    return out


def handle_stream(loop, rfile, wfile) -> int:
    """Drive one JSONL request stream against ``loop`` until EOF;
    returns the number of requests handled.  Writes are serialized by a
    lock because responses complete out of order on the dispatcher
    thread while this thread keeps reading."""
    wlock = threading.Lock()
    # response-WRITTEN events, not bare futures: a future resolves
    # before its done-callback runs, and returning on future completion
    # would let a TCP handler close the socket under the in-flight
    # response write
    written: List[threading.Event] = []

    def write(doc: Dict) -> None:
        line = json.dumps(doc)
        with wlock:
            try:
                wfile.write(line + "\n")
                wfile.flush()
            except (OSError, ValueError):
                pass              # client went away mid-response

    n = 0
    try:
        for raw in rfile:
            # injectable disconnect (chaos point serve.transport): raises
            # ConnectionResetError exactly where a real peer reset
            # surfaces — the handler below ends THIS stream cleanly
            chaos.fire("serve.transport")
            line = raw.strip()
            if not line:
                continue
            n += 1
            req_id: object = n
            t_enqueue = time.perf_counter()
            trace_id: "str | None" = None
            try:
                doc = json.loads(line)
                if not isinstance(doc, dict):
                    raise PlanError("request must be a JSON object")
                req_id = doc.get("id", n)
                if doc.get("op") == "health":
                    # degraded-mode diagnosis surface: answered inline
                    # on the reader thread (never enters the dispatch
                    # heap, so it works even when every tenant sheds)
                    write({"id": req_id, "health": loop.health()})
                    continue
                if doc.get("op") == "metrics":
                    # live metrics surface (`hbam top`'s poll target):
                    # the server's process-global snapshot + SLO burn
                    # rates, also answered inline on the reader thread
                    write({"id": req_id, **_metrics_doc(loop, doc)})
                    continue
                fleet = getattr(loop, "fleet", None)
                if doc.get("op") == "heartbeat":
                    if fleet is not None:
                        fleet.observe_peer(doc.get("from"))
                    write({"id": req_id, "ok": True,
                           "replica": (fleet.replica_id
                                       if fleet is not None else None)})
                    continue
                if doc.get("op") == "fleet":
                    write({"id": req_id,
                           "fleet": (fleet.states()
                                     if fleet is not None else None)})
                    continue
                if doc.get("op") == "chunk":
                    if fleet is None:
                        raise PlanError(
                            "peer chunk op on a non-fleet server")
                    # a peer's fetch adopts the ORIGINATING trace id:
                    # the spans below join the peer request's tree
                    with trace_context(
                            op="serve.peer_chunk",
                            trace_id=_client_trace(doc.get("trace"))
                            ) as tctx:
                        with METRICS.span("serve.peer_chunk_wall"):
                            payload = fleet.serve_chunk(loop.engine, doc)
                        write({"id": req_id, "trace": tctx.trace_id,
                               "replica": fleet.replica_id, **payload})
                    continue
                regions = doc.get("regions")
                if regions is None:
                    regions = [doc["region"]] if "region" in doc else None
                if not regions or "path" not in doc:
                    raise PlanError(
                        'request needs "path" and "regions" (or "region")')
                # fleet hop: the deadline re-anchors to the ORIGINATING
                # request's enqueue instant — the original budget minus
                # the age it already spent upstream, never a fresh one
                deadline_s = effective_deadline_s(
                    doc.get("deadline_s"), doc.get("enqueue_age_s"))
                # ONE trace per request line, minted here at the wire —
                # loop.submit's contextvars snapshot carries it through
                # the dispatcher, the decode pool and the staging
                # packer, and the response line echoes it back; a
                # client- or peer-supplied "trace" is adopted (validated)
                # so a forwarded fleet request keeps its originating id
                with trace_context(
                        op="serve.request",
                        tenant=str(doc.get("tenant", "default")),
                        deadline_s=deadline_s,
                        trace_id=_client_trace(doc.get("trace"))) as tctx:
                    trace_id = tctx.trace_id
                    fut = loop.submit(
                        doc["path"], regions,
                        tenant=str(doc.get("tenant", "default")),
                        priority=str(doc.get("priority", "interactive")),
                        deadline_s=deadline_s,
                        want_records=bool(doc.get("records", False)),
                        cohort=bool(doc.get("cohort", False)))
            except (ValueError, KeyError, TypeError) as e:
                # malformed line / PlanError-class rejection: answer,
                # keep serving the stream (one bad client line must not
                # kill the connection)
                write(error_doc(req_id, e,
                                kind=None if isinstance(e, HBamError)
                                else "plan", trace=trace_id))
                continue
            except (TransientIOError, CircuitBreakerError, OSError) as e:
                # admission / tenant-breaker / quarantine-circuit shed:
                # a classified answer with the backoff hint, never a
                # hang and never a dropped connection (a bare
                # RuntimeError is a bug and must propagate, not serve)
                write(error_doc(req_id, e, trace=trace_id))
                continue

            ev = threading.Event()

            def _done(f: cf.Future, req_id=req_id,
                      tenant=str(doc.get("tenant", "default")),
                      t_enqueue=t_enqueue, ev=ev,
                      trace_id=trace_id) -> None:
                replica = (fleet.replica_id if fleet is not None
                           else None)
                try:
                    exc = f.exception()
                    if exc is not None:
                        write(error_doc(req_id, exc, trace=trace_id))
                    else:
                        # the response write runs on the dispatcher
                        # thread inside the job's context — this span
                        # is the tail of the request's causal tree
                        with METRICS.span("serve.response_wall"):
                            write(_result_doc(req_id, tenant,
                                              f.result(), t_enqueue,
                                              trace=trace_id,
                                              replica=replica))
                finally:
                    ev.set()

            fut.add_done_callback(_done)
            written.append(ev)
            # prune responses already on the wire: a connection held
            # open for millions of requests must not grow this list
            # without bound (the SV802 discipline, applied to a local)
            if len(written) > 64:
                written[:] = [e for e in written if not e.is_set()]
    except OSError:
        # the connection died mid-read (peer reset / injected
        # disconnect): stop reading THIS stream; queued work still
        # completes below and the server keeps serving other streams
        METRICS.count("serve.transport_disconnects")
    for ev in written:
        ev.wait(timeout=60.0)
    return n


def serve_stdio(loop, rfile=None, wfile=None) -> int:
    """The ``hbam serve`` default transport: JSONL on stdin/stdout."""
    import sys
    return handle_stream(loop, rfile if rfile is not None else sys.stdin,
                         wfile if wfile is not None else sys.stdout)


def make_tcp_server(loop, host: str = "127.0.0.1", port: int = 0):
    """A ``ThreadingTCPServer`` speaking the JSONL protocol per
    connection; caller owns ``serve_forever()`` / ``shutdown()``.  The
    bound address is ``server.server_address`` (pass ``port=0`` for an
    ephemeral port — how the tests run it)."""
    import socketserver

    class Handler(socketserver.StreamRequestHandler):
        def handle(self) -> None:
            rfile = (line.decode("utf-8", "replace")
                     for line in self.rfile)
            import io

            class _W(io.TextIOBase):
                def write(inner, s: str) -> int:  # noqa: N805
                    self.wfile.write(s.encode())
                    return len(s)

                def flush(inner) -> None:  # noqa: N805
                    pass

            handle_stream(loop, rfile, _W())

    class Server(socketserver.ThreadingTCPServer):
        allow_reuse_address = True
        daemon_threads = True

    return Server((host, int(port)), Handler)
