"""``hbam serve`` — persistent multi-tenant region serving.

The serving tier the ROADMAP's open item 2 describes, built on the
PR-5 query engine without changing it:

- ``tiles.py``     DeviceTileCache: a SECOND cache tier of decoded,
  device-resident interval tiles above the host byte LRU, keyed by
  ``(file_identity, chunk range, projection)`` — a hit skips
  fetch + inflate + host_decode entirely and goes straight to the
  jitted interval-filter step.  TileBuilder assembles tiles through a
  PINNED staging ring (cached device tiles can never be aliased by
  ring reuse).
- ``prefetch.py``  Prefetcher: recency+adjacency prediction of the next
  windows, decoded into the host cache at BACKGROUND decode-pool
  priority (rapidgzip's cache-prefetching idea at the serving layer).
- ``tenancy.py``   TenantQuotas: per-tenant admission quotas (one PR-5
  ``QueryScheduler`` each) and ``interactive``/``batch`` priority
  classes.
- ``loop.py``      ServeLoop: the resident server — client futures, a
  single-threaded device dispatcher, per-client MetricsContext
  isolation, ``serve.*`` spans/histograms through the PR-6 obs layer.
- ``transport.py`` JSONL over stdin/stdout or TCP (``hbam serve``).
- ``membership.py`` rendezvous (HRW) tile ownership + heartbeat-observed
  fleet membership with suspicion/eviction (injectable clock).
- ``fleet.py``     the replicated serving fleet: R-way tile ownership,
  per-peer circuit breakers, enqueue-anchored deadline re-budgeting on
  the wire, hedged peer-fetch of decoded tiles, degraded partition
  mode, seamless failover (``hbam serve --peers --replica-id``).
"""
from hadoop_bam_tpu.serve.fleet import (  # noqa: F401
    Fleet, effective_deadline_s, parse_peers,
)
from hadoop_bam_tpu.serve.loop import ServeLoop, ServeResult  # noqa: F401
from hadoop_bam_tpu.serve.membership import (  # noqa: F401
    Membership, owners, rank_members, rendezvous_weight,
)
from hadoop_bam_tpu.serve.prefetch import Prefetcher  # noqa: F401
from hadoop_bam_tpu.serve.tenancy import (  # noqa: F401
    PRIORITIES, TenantQuotas,
)
from hadoop_bam_tpu.serve.tiles import (  # noqa: F401
    DeviceTileCache, TileBuilder, TileSet, make_tile_filter_step, tile_key,
)
from hadoop_bam_tpu.serve.transport import (  # noqa: F401
    handle_stream, make_tcp_server, serve_stdio,
)
