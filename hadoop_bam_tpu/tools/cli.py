"""``python -m hadoop_bam_tpu <verb>`` — the CLI frontend.

Verb parity with the reference CLI (SURVEY.md section 2.7):

- ``view``      print records as SAM/VCF text (optionally header-only/count)
- ``index``     build a .splitting-bai / .sbi sidecar (SplittingBAMIndexer)
- ``cat``       concatenate same-header BAMs into one
- ``summarize`` distributed flagstat over the mesh pipeline
- ``sort``      coordinate- (or name-) sort a BAM
- ``fixmate``   fill mate fields on name-grouped records
- ``vcf-sort``  sort a VCF/BCF by (contig, position)

Each verb works on local paths and prints to stdout; exit code != 0 on error.
"""
from __future__ import annotations

import argparse
import os
import sys
from typing import List, Optional, Tuple


def _parse_region(region: str) -> Tuple[str, int, int]:
    """'chr20:1,000-2,000' -> (chr20, 1000, 2000); open ends allowed."""
    if ":" not in region:
        return region, 1, 1 << 60
    name, rng = region.rsplit(":", 1)
    rng = rng.replace(",", "")
    if "-" in rng:
        lo, hi = rng.split("-", 1)
        return name, int(lo or 1), int(hi or 1 << 60)
    return name, int(rng), 1 << 60


# ---------------------------------------------------------------------------
# view
# ---------------------------------------------------------------------------

def cmd_view(args) -> int:
    from hadoop_bam_tpu.api.dispatch import sniff_sam_container, SAMContainer
    path = args.path
    if path.endswith((".vcf", ".vcf.gz", ".bcf")):
        return _view_vcf(args)
    fmt = sniff_sam_container(path)
    return _view_sam(args, fmt)


def _overlaps_region(rec, region) -> bool:
    """True iff the alignment's reference span intersects [start, end]."""
    if rec.rname != region[0]:
        return False
    return rec.pos <= region[2] and rec.pos + max(1, _alen(rec)) - 1 >= region[1]


def _view_sam(args, fmt) -> int:
    from hadoop_bam_tpu.api.dataset import open_any_sam
    ds = open_any_sam(args.path)
    header = ds.header
    if args.header_only:
        sys.stdout.write(header.to_sam_text())
        return 0
    region = None
    if args.region:
        from hadoop_bam_tpu.split.intervals import resolve_interval
        iv = resolve_interval(args.region, header.ref_names)
        region = (iv.rname, iv.start, iv.end)
    rid = header.ref_id(region[0]) if region else -2
    if region and rid < 0:
        print(f"unknown reference {region[0]!r}", file=sys.stderr)
        return 1
    n = 0
    if not args.count and not args.no_header:
        sys.stdout.write(header.to_sam_text())
    from hadoop_bam_tpu.api.dataset import BamDataset
    from hadoop_bam_tpu.formats.sam import SamRecord
    if isinstance(ds, BamDataset) and region and args.region:
        from hadoop_bam_tpu.split.bai import load_bai_for
        if load_bai_for(args.path) is not None:
            # genomic index present: read only the indexed chunk ranges
            for rec in ds.query(args.region):
                if args.count:
                    n += 1
                else:
                    print(rec.to_line())
            if args.count:
                print(n)
            return 0
    from hadoop_bam_tpu.api.cram_dataset import CramDataset
    if isinstance(ds, CramDataset) and args.count and not region:
        # container headers carry record counts: whole-file -c needs a
        # header scan, zero block decompression (samtools-style fast
        # count)
        from hadoop_bam_tpu.split.cram_planner import scan_cram_containers
        print(sum(nr for _off, _size, nr in scan_cram_containers(args.path)))
        return 0
    if isinstance(ds, BamDataset):
        for batch in ds.batches():
            import numpy as np
            idx = np.arange(len(batch))
            if region:
                # conservative vectorized pre-filter (start bound only; the
                # exact CIGAR-span overlap check runs on the decoded line)
                keep = (batch.refid == rid) & (batch.pos + 1 <= region[2])
                idx = idx[keep]
            for i in idx:
                line = batch.to_sam_line(int(i))
                if region and not _overlaps_region(SamRecord.from_line(line),
                                                   region):
                    continue
                if args.count:
                    n += 1
                else:
                    sys.stdout.write(line + "\n")
    else:
        for rec in ds.records():
            if region and not _overlaps_region(rec, region):
                continue
            if args.count:
                n += 1
            else:
                sys.stdout.write(rec.to_line() + "\n")
    if args.count:
        print(n)
    return 0


def _view_vcf(args) -> int:
    from hadoop_bam_tpu.api.vcf_dataset import open_vcf
    ds = open_vcf(args.path)
    if args.header_only:
        sys.stdout.write(ds.header.to_text())
        return 0
    region = None
    if args.region:
        from hadoop_bam_tpu.split.intervals import resolve_interval
        iv = resolve_interval(args.region, ds.header.contigs)
        region = (iv.rname, iv.start, iv.end)
    n = 0
    if not args.count and not args.no_header:
        sys.stdout.write(ds.header.to_text())
    for rec in ds.records():
        if region and (rec.chrom != region[0]
                       or not (region[1] <= rec.pos <= region[2])):
            continue
        if args.count:
            n += 1
        else:
            sys.stdout.write(rec.to_line() + "\n")
    if args.count:
        print(n)
    return 0


# ---------------------------------------------------------------------------
# index
# ---------------------------------------------------------------------------

def cmd_index(args) -> int:
    from hadoop_bam_tpu.split.splitting_index import write_splitting_index
    for path in args.paths:
        if args.flavor == "bai":
            from hadoop_bam_tpu.split.bai import write_bai
            out = write_bai(path)
        elif args.flavor == "tbi":
            from hadoop_bam_tpu.split.tabix import write_tabix
            out = write_tabix(path)
        else:
            out = write_splitting_index(path, granularity=args.granularity,
                                        flavor=args.flavor)
        print(f"wrote {out}")
    return 0


# ---------------------------------------------------------------------------
# cat
# ---------------------------------------------------------------------------

def cmd_cat(args) -> int:
    """Concatenate BAMs sharing a header (reference CLI `cat`): header from
    the first input, record bytes streamed through, one EOF terminator."""
    from hadoop_bam_tpu.formats.bamio import BamWriter, read_bam_header
    from hadoop_bam_tpu.api.dataset import open_bam

    header, _ = read_bam_header(args.inputs[0])
    for path in args.inputs[1:]:
        other, _ = read_bam_header(path)
        if (other.ref_names != header.ref_names
                or other.ref_lengths != header.ref_lengths):
            print(f"error: {path} has a different reference dictionary than "
                  f"{args.inputs[0]}; refusing to concatenate", file=sys.stderr)
            return 1
    with BamWriter(args.output, header) as w:
        for path in args.inputs:
            ds = open_bam(path)
            for batch in ds.batches():
                for i in range(len(batch)):
                    w.write_record_bytes(batch.record_bytes(i))
    print(f"wrote {args.output} ({w.records_written} records)")
    return 0


# ---------------------------------------------------------------------------
# observability plumbing shared by the device verbs
# ---------------------------------------------------------------------------

def _start_obs(args) -> None:
    """--trace FILE: turn on the span trace ring before the verb runs."""
    if getattr(args, "trace", None):
        from hadoop_bam_tpu.obs import enable_tracing
        enable_tracing()


def _finish_obs(args, metrics=None) -> None:
    """Write the --trace Chrome trace file and/or the --metrics-json
    snapshot after the verb's work is done."""
    if getattr(args, "trace", None):
        from hadoop_bam_tpu.obs import disable_tracing
        rec = disable_tracing()
        if rec is not None:
            try:
                pid = (sys.modules["jax"].process_index()
                       if "jax" in sys.modules else 0)
            except Exception:  # noqa: BLE001 — labeling only
                pid = 0
            rec.save(args.trace, process_index=pid)
            print(f"wrote trace {args.trace} ({len(rec.events())} spans, "
                  f"{rec.dropped} dropped) — load in chrome://tracing or "
                  f"https://ui.perfetto.dev", file=sys.stderr)
    if getattr(args, "metrics_json", None):
        from hadoop_bam_tpu.obs import save_metrics_json
        if metrics is None:
            from hadoop_bam_tpu.utils.metrics import current_metrics
            metrics = current_metrics()
        save_metrics_json(metrics, args.metrics_json)
        print(f"wrote metrics snapshot {args.metrics_json} "
              f"(render/export it with `hbam metrics`)", file=sys.stderr)


def _add_obs_flags(sub) -> None:
    sub.add_argument("--trace", metavar="FILE", default=None,
                     help="record stage spans (all pipeline stages, all "
                          "pool threads) and write a Chrome trace-event "
                          "JSON file loadable in chrome://tracing / "
                          "Perfetto")
    sub.add_argument("--metrics-json", metavar="FILE", default=None,
                     help="write the run's full metrics snapshot "
                          "(counters, timers, walls, histogram buckets) "
                          "as JSON for `hbam metrics`")


# ---------------------------------------------------------------------------
# summarize
# ---------------------------------------------------------------------------

def cmd_summarize(args) -> int:
    from hadoop_bam_tpu.ops.flagstat import format_flagstat
    from hadoop_bam_tpu.parallel.distributed import distributed_flagstat
    _start_obs(args)
    # plan-once + per-host shares + one allgather under jax.distributed;
    # identical to flagstat_file in a single-process run
    stats = distributed_flagstat(args.path)
    sys.stdout.write(format_flagstat(stats))
    merged = None
    from hadoop_bam_tpu.parallel.distributed import (
        merge_metrics, process_count,
    )
    if args.metrics or args.metrics_json or process_count() > 1:
        # mesh-wide merge: under jax.distributed every host reports the
        # same job-level counters/histograms; single-process this is a
        # plain copy of the local state.  Multi-host runs enter the
        # merge UNCONDITIONALLY: it is a collective, and gating it on
        # per-host CLI flags would deadlock the mesh if the flags ever
        # diverged across hosts (the CL2xx lockstep rule, applied here)
        merged = merge_metrics()
    if args.metrics:
        print("\n-- pipeline metrics (mesh-merged) --", file=sys.stderr)
        print(merged.render(), file=sys.stderr)
    _finish_obs(args, metrics=merged)
    return 0


# ---------------------------------------------------------------------------
# seq-stats / vcf-stats (device payload paths; no reference-CLI analog —
# the closest is `summarize`, which these extend to payload columns)
# ---------------------------------------------------------------------------

_COVERAGE_TILE = 1 << 24        # bases per coverage_file call


def cmd_coverage(args) -> int:
    import contextlib

    import numpy as np

    from hadoop_bam_tpu.formats.bamio import read_bam_header
    from hadoop_bam_tpu.split.intervals import Interval, resolve_interval

    header, _ = read_bam_header(args.input)
    region = resolve_interval(args.region, header.ref_names)
    if region.rname not in header.ref_names:
        raise ValueError(f"region reference {region.rname!r} not in header")
    ref_len = header.ref_lengths[header.ref_names.index(region.rname)]
    start, end = region.start, min(region.end, ref_len)
    if end < start:
        raise ValueError(f"empty region {region}")

    # a bare contig name means the whole reference — tile it through
    # fixed-size windows so device memory stays bounded and the jit
    # caches one window shape.  Without a .bai sidecar every tile must
    # stream the whole file, so say so.
    from hadoop_bam_tpu.parallel.distributed import distributed_coverage
    from hadoop_bam_tpu.split.bai import load_bai_for
    n_tiles = (end - start) // _COVERAGE_TILE + 1
    if n_tiles > 1 and load_bai_for(args.input) is None:
        print(f"note: {n_tiles} tiles with no genomic index sidecar — "
              f"every tile streams the whole file; run "
              f"'hbam index --flavor bai' first for region-pruned reads",
              file=sys.stderr)
    total = covered = max_depth = 0
    depth_sum = 0
    bg_tmp = args.bedgraph + ".tmp" if args.bedgraph else None
    try:
        with (open(bg_tmp, "w") if bg_tmp
              else contextlib.nullcontext()) as bg:
            pending = None               # (start0, end0, depth) run buffer
            for lo in range(start, end + 1, _COVERAGE_TILE):
                hi = min(lo + _COVERAGE_TILE - 1, end)
                # plan-once/per-host-shares/one-allgather under
                # jax.distributed; plain single-process coverage_file
                # otherwise
                depth = distributed_coverage(args.input,
                                             Interval(region.rname, lo, hi),
                                             header=header,
                                             max_cigar=args.max_cigar)
                total += depth.size
                covered += int((depth > 0).sum())
                depth_sum += int(depth.sum(dtype=np.int64))
                if depth.size:
                    max_depth = max(max_depth, int(depth.max()))
                if bg is not None:
                    # run-length encode, merging runs across tile
                    # boundaries (0-based half-open [bedGraph])
                    edges = np.flatnonzero(np.diff(depth)) + 1
                    starts = np.concatenate([[0], edges])
                    ends = np.concatenate([edges, [depth.size]])
                    base = lo - 1
                    for s, e in zip(starts, ends):
                        d = int(depth[s])
                        if not d:
                            continue
                        if pending and pending[1] == base + s \
                                and pending[2] == d:
                            pending = (pending[0], base + e, d)
                        else:
                            if pending:
                                bg.write(f"{region.rname}\t{pending[0]}"
                                         f"\t{pending[1]}\t{pending[2]}\n")
                            pending = (base + s, base + e, d)
            if bg is not None and pending:
                bg.write(f"{region.rname}\t{pending[0]}\t{pending[1]}"
                         f"\t{pending[2]}\n")
    except BaseException:
        # never leave a truncated-but-plausible bedGraph behind
        if bg_tmp and os.path.exists(bg_tmp):
            os.unlink(bg_tmp)
        raise
    if bg_tmp:
        os.replace(bg_tmp, args.bedgraph)

    print(f"region\t{region.rname}:{start}-{end}")
    print(f"bases\t{total}")
    print(f"covered\t{covered}")
    print(f"mean_depth\t{depth_sum / total if total else 0.0:.4f}")
    print(f"max_depth\t{max_depth}")
    if args.bedgraph:
        print(f"wrote {args.bedgraph}")
    return 0


def cmd_seq_stats(args) -> int:
    from hadoop_bam_tpu.parallel.distributed import (
        distributed_cram_seq_stats, distributed_fastq_seq_stats,
        distributed_seq_stats,
    )
    from hadoop_bam_tpu.parallel.pipeline import (
        CRAM_EXTS, TEXT_READ_EXTS, PayloadGeometry,
    )
    geometry = PayloadGeometry(max_len=args.max_len)
    if args.path.lower().endswith(TEXT_READ_EXTS):
        stats = distributed_fastq_seq_stats(args.path, geometry=geometry)
    elif args.path.lower().endswith(CRAM_EXTS):
        import dataclasses

        from hadoop_bam_tpu.config import DEFAULT_CONFIG
        cfg = DEFAULT_CONFIG
        if getattr(args, "reference", None):
            cfg = dataclasses.replace(
                cfg, cram_reference_source_path=args.reference)
        stats = distributed_cram_seq_stats(args.path, config=cfg,
                                           geometry=geometry)
    else:
        stats = distributed_seq_stats(args.path, geometry=geometry)
    print(f"reads\t{stats['n_reads']}")
    print(f"mean_gc\t{stats['mean_gc']:.6f}")
    print(f"mean_qual\t{stats['mean_qual']:.3f}")
    names = ["=", "A", "C", "M", "G", "R", "S", "V",
             "T", "W", "Y", "H", "K", "D", "B", "N"]
    hist = stats["base_hist"]
    total = max(float(hist.sum()), 1.0)
    for code, name in enumerate(names):
        if hist[code]:
            print(f"base_{name}\t{int(hist[code])}\t{hist[code]/total:.4f}")
    return 0


def cmd_explain(args) -> int:
    """Compile the plan for an op and print the IR + routing decision:
    source, spans summary, op DAG, sink, digest, the selected decode
    plane, and the reason each rejected plane/mode failed its gate
    (plan/executor.select_plane — the same single predicate the
    drivers consume)."""
    import dataclasses as _dc
    import json as _json

    from hadoop_bam_tpu.config import DEFAULT_CONFIG
    from hadoop_bam_tpu.plan import builders
    from hadoop_bam_tpu.plan.executor import select_plane

    # flag -> config-field forwarding, value-filtered (no gate
    # conditionals here: PL101 applies to this module too)
    overrides = {
        "inflate_backend": args.inflate_backend,
        "bam_intervals": args.intervals,
        "skip_bad_spans": True if args.skip_bad_spans else None,
        "use_fused_decode": False if args.no_fused else None,
    }
    overrides = {k: v for k, v in overrides.items() if v is not None}
    cfg = _dc.replace(DEFAULT_CONFIG, **overrides) if overrides \
        else DEFAULT_CONFIG

    if args.op == "flagstat":
        plan = builders.flagstat_plan(args.path, cfg)
    elif args.op == "seq-stats":
        plan = builders.seq_stats_plan(args.path, cfg)
    elif args.op == "vcf-stats":
        # cfg matters here: the backend decides whether the BCF device
        # unpack op joins the DAG (and therefore the digest)
        plan = builders.variant_stats_plan(args.path, cfg)
    elif args.op == "cohort":
        plan = builders.cohort_plan(args.path, cfg)
    elif args.op == "mkdup":
        plan = builders.mkdup_plan(args.path, args.path + ".mkdup.bam",
                                   cfg)
    elif args.op == "serve-tile":
        if args.region:
            # the realistic shape: resolve the region through the index
            # and explain the FIRST coalesced chunk's tile build
            from hadoop_bam_tpu.query.engine import QueryEngine
            engine = QueryEngine(config=cfg)
            meta = engine._file_meta(args.path)
            _iv, ranges = engine._resolve(meta, args.region)
            chunks = engine._coalesce(ranges, meta.kind)
            s, e = chunks[0] if chunks else (0, 0)
            plan = builders.serve_tile_plan(args.path, meta.kind, s, e)
        else:
            plan = builders.serve_tile_plan(args.path)
    else:  # query
        if not args.region:
            raise SystemExit("explain query needs --region")
        from hadoop_bam_tpu.query.engine import QueryEngine
        engine = QueryEngine(config=cfg)
        meta = engine._file_meta(args.path)
        _iv, ranges = engine._resolve(meta, args.region)
        chunks = engine._coalesce(ranges, meta.kind)
        plan = builders.query_region_plan(args.path, meta.kind,
                                          args.region, chunks)
    # the gate sees parsed intervals at run time; a set-but-unparsed
    # config string is the same gate signal for explain purposes
    intervals = () if cfg.bam_intervals else None
    decision = select_plane(plan.source, plan.ops, cfg,
                            intervals=intervals)
    if args.json:
        print(_json.dumps({"plan": plan.to_doc(),
                           "digest": plan.digest(),
                           "decision": decision.to_doc()},
                          indent=1, sort_keys=True))
        return 0
    for line in plan.render():
        print(line)
    print(f"plane   {decision.plane} (backend={decision.backend}, "
          f"host_backend={decision.host_backend}, "
          f"fused={'on' if decision.use_fused else 'off'}, "
          f"stream_fused={'on' if decision.stream_fused else 'off'})")
    if decision.rejected:
        print("rejected:")
        for p, reason in decision.rejected:
            print(f"  {p:13s} {reason}")
    return 0


def cmd_vcf_stats(args) -> int:
    from hadoop_bam_tpu.parallel.distributed import (
        distributed_variant_stats,
    )
    stats = distributed_variant_stats(args.path)
    print(f"variants\t{stats['n_variants']}")
    print(f"snps\t{stats['n_snp']}")
    print(f"pass\t{stats['n_pass']}")
    print(f"mean_af\t{stats['mean_af']:.6f}")
    for i, cr in enumerate(stats["sample_callrate"]):
        print(f"callrate_{i}\t{cr:.4f}")
    return 0


# ---------------------------------------------------------------------------
# sort
# ---------------------------------------------------------------------------

def _write_config(args):
    """Write-path knobs shared by the sort verbs -> an HBamConfig."""
    import dataclasses

    from hadoop_bam_tpu.config import DEFAULT_CONFIG
    overrides = {}
    if getattr(args, "compress_level", None) is not None:
        # validate at the argv boundary: an out-of-range level would
        # otherwise surface as a raw zlib.error from a pool worker —
        # and inconsistently, since the native backend accepts levels
        # zlib rejects
        if not 0 <= args.compress_level <= 9:
            raise SystemExit(
                f"--compress-level must be in 0-9, "
                f"got {args.compress_level}")
        overrides["write_compress_level"] = args.compress_level
    if getattr(args, "no_write_index", False):
        overrides["write_index_kinds"] = "none"
    return dataclasses.replace(DEFAULT_CONFIG, **overrides) \
        if overrides else DEFAULT_CONFIG


def _journal_arg(args, default_path: str) -> Optional[str]:
    """Resolve a ``--journal [PATH]`` flag: absent -> None, bare flag ->
    the job's default sibling journal, explicit value -> that path."""
    j = getattr(args, "journal", None)
    if j is None:
        return None
    return default_path if j == "" else j


def cmd_sort(args) -> int:
    if args.run_records is not None and args.run_records <= 0:
        raise SystemExit("--run-records must be positive")
    cfg = _write_config(args)
    journal = None
    if getattr(args, "journal", None) is not None:
        if not args.mesh:
            raise SystemExit("--journal requires --mesh (the spill-merge "
                             "sort is not journaled; its runs are "
                             "process-local temps)")
        from hadoop_bam_tpu.jobs import journal_path_for
        journal = _journal_arg(args, journal_path_for(args.output))
    if args.mesh:
        if args.by_name:
            raise SystemExit(
                "--mesh supports coordinate sort only (queryname keys "
                "have no fixed-width device representation); drop -n")
        from hadoop_bam_tpu.parallel.mesh_sort import sort_bam_mesh
        # --run-records under --mesh selects the multi-round SPILL
        # exchange: device memory bounded by ~that many records per
        # device per round (the MR shuffle's spill).  Output rides the
        # write/ subsystem: pooled deflate + co-written index sidecars
        n = sort_bam_mesh(args.input, args.output, exchange=args.exchange,
                          round_records=args.run_records, config=cfg,
                          journal_path=journal)
        mode = "mesh spill" if args.run_records is not None else "mesh"
        extra = f", journal {journal}" if journal else ""
        print(f"wrote {args.output} ({n} records, coordinate, {mode}"
              f"{extra})")
        return 0
    if args.exchange is not None:
        raise SystemExit("--exchange only applies to --mesh")
    from hadoop_bam_tpu.utils.sort import sort_bam

    n = sort_bam(args.input, args.output, by_name=args.by_name,
                 config=cfg,
                 run_records=args.run_records
                 if args.run_records is not None else 1_000_000)
    so = "queryname" if args.by_name else "coordinate"
    print(f"wrote {args.output} ({n} records, {so})")
    return 0


# ---------------------------------------------------------------------------
# fixmate
# ---------------------------------------------------------------------------

def cmd_fixmate(args) -> int:
    from hadoop_bam_tpu.utils.fixmate import fixmate_bam

    n = fixmate_bam(args.input, args.output, config=_write_config(args))
    print(f"wrote {args.output} ({n} records)")
    return 0


# ---------------------------------------------------------------------------
# mkdup
# ---------------------------------------------------------------------------

def cmd_mkdup(args) -> int:
    """The fused preprocessing pipeline: read -> mesh sort exchange ->
    duplicate marking -> flag-patched indexed write, one pass, driven
    through the plan IR (`hbam explain mkdup` shows the compiled
    plan)."""
    if args.run_records is not None and args.run_records <= 0:
        raise SystemExit("--run-records must be positive")
    cfg = _write_config(args)
    journal = None
    if getattr(args, "journal", None) is not None:
        from hadoop_bam_tpu.jobs import journal_path_for
        journal = _journal_arg(args, journal_path_for(args.output))
    from hadoop_bam_tpu.plan import builders
    from hadoop_bam_tpu.plan.executor import execute

    plan = builders.mkdup_plan(args.input, args.output, cfg,
                               remove_duplicates=args.remove_duplicates,
                               library_from=args.library_from)
    n = execute(plan, config=cfg, round_records=args.run_records,
                journal_path=journal)
    what = "removed" if args.remove_duplicates else "marked"
    extra = f", journal {journal}" if journal else ""
    print(f"wrote {args.output} ({n} records, duplicates {what}, "
          f"coordinate, fused mesh{extra})")
    return 0


def _alen(r) -> int:
    """Alignment span on the reference from the CIGAR (M/D/N/=/X)."""
    import re
    if r.cigar in ("*", ""):
        return len(r.seq) if r.seq != "*" else 0
    return sum(int(n) for n, op in re.findall(r"(\d+)([MIDNSHP=X])", r.cigar)
               if op in "MDN=X")


# ---------------------------------------------------------------------------
# query
# ---------------------------------------------------------------------------

def cmd_query(args) -> int:
    """Batched random-access region serving (query/engine.py): resolve
    every region through the file's genomic index (.bai/.csi for BAM,
    .tbi for BGZF VCF and BCF, container coordinates for CRAM), decode
    the union of needed chunks once, and filter on the mesh."""
    import dataclasses

    from hadoop_bam_tpu.config import DEFAULT_CONFIG
    from hadoop_bam_tpu.query import QueryEngine, QueryRequest

    from hadoop_bam_tpu.utils.metrics import METRICS

    cfg = DEFAULT_CONFIG
    if args.deadline is not None:
        cfg = dataclasses.replace(cfg, query_deadline_s=args.deadline)
    _start_obs(args)
    engine = QueryEngine(config=cfg)
    reqs = [QueryRequest(args.path, region) for region in args.regions]
    results = engine.query_records(reqs)
    for res in results:
        if args.count:
            print(f"{res.request.region}\t{len(res.records)}")
        else:
            for rec in res.records:
                print(rec.to_line())
    if args.metrics:
        stats = engine.stats()
        print("-- query cache --", file=sys.stderr)
        for k in sorted(stats):
            print(f"{k}\t{stats[k]}", file=sys.stderr)
        lat = METRICS.hist_summary("query.latency_s")
        if lat:
            print(f"latency_s\tp50={lat['p50']:.4g} p95={lat['p95']:.4g} "
                  f"p99={lat['p99']:.4g} n={lat['count']}",
                  file=sys.stderr)
    _finish_obs(args)
    return 0


# ---------------------------------------------------------------------------
# serve
# ---------------------------------------------------------------------------

def cmd_serve(args) -> int:
    """Long-running multi-tenant region serving (serve/loop.py): JSONL
    requests over stdin/stdout (default) or TCP (--port), served from a
    device-resident decoded-tile cache above the host chunk LRU, with
    per-tenant admission quotas, priority classes, and predictive
    prefetch."""
    import dataclasses
    import json as _json

    from hadoop_bam_tpu.config import DEFAULT_CONFIG
    from hadoop_bam_tpu.serve import ServeLoop, make_tcp_server, serve_stdio

    cfg = DEFAULT_CONFIG
    overrides = {}
    if args.deadline is not None:
        overrides["query_deadline_s"] = args.deadline
    if args.tile_cache_bytes is not None:
        overrides["serve_tile_cache_bytes"] = args.tile_cache_bytes
    if args.no_prefetch:
        overrides["serve_prefetch"] = False
    if getattr(args, "breaker_cooldown", None) is not None:
        overrides["breaker_cooldown_s"] = args.breaker_cooldown
    if getattr(args, "flight_dir", None):
        overrides["flight_dump_dir"] = args.flight_dir
    if getattr(args, "replica_id", None):
        overrides["serve_replica_id"] = args.replica_id
    if getattr(args, "peers", None):
        overrides["serve_peers"] = args.peers
    if getattr(args, "replication", None) is not None:
        overrides["fleet_replication"] = args.replication
    if overrides:
        cfg = dataclasses.replace(cfg, **overrides)
    if cfg.serve_peers and not cfg.serve_replica_id:
        print("error: --peers requires --replica-id (this replica's own "
              "name in the peer set)", file=sys.stderr)
        return 2
    if cfg.serve_peers and args.port is None:
        print("error: --peers requires --port (peer fetch rides the TCP "
              "transport)", file=sys.stderr)
        return 2
    _start_obs(args)
    n = 0
    with ServeLoop(config=cfg) as loop:
        for path in args.warm or ():
            # warm metadata + index up front so the first client query
            # doesn't pay the header walk
            loop.engine._file_meta(path)
        if args.port is not None:
            server = make_tcp_server(loop, host=args.host, port=args.port)
            host, port = server.server_address[:2]
            print(f"serving on {host}:{port} (JSONL; ^C stops)",
                  file=sys.stderr)
            if loop.fleet is not None:
                print(f"fleet replica={loop.fleet.replica_id} "
                      f"replication={loop.fleet.replication} "
                      f"peers={','.join(sorted(loop.fleet.peers)) or '-'}",
                      file=sys.stderr)
            try:
                server.serve_forever()
            except KeyboardInterrupt:
                pass
            finally:
                server.shutdown()
                server.server_close()
        else:
            n = serve_stdio(loop)
        if args.metrics:
            print("-- serve stats --", file=sys.stderr)
            for section, stats in sorted(loop.stats().items()):
                print(f"{section}\t{stats}", file=sys.stderr)
        # the degrade-and-heal surface, always reported at shutdown:
        # breaker/ladder state is exactly what an operator needs when a
        # server that kept serving was quietly demoted or shedding
        # (clients get the same document live via {"op": "health"})
        print("-- serve health --", file=sys.stderr)
        print(_json.dumps(loop.health(), default=str), file=sys.stderr)
    _finish_obs(args)
    if args.port is None:
        print(f"served {n} request(s)", file=sys.stderr)
    return 0


# ---------------------------------------------------------------------------
# metrics (snapshot render / export)
# ---------------------------------------------------------------------------

def cmd_metrics(args) -> int:
    """Render or re-export a metrics snapshot written by
    ``--metrics-json`` (or by bench.py): human text, Prometheus text
    exposition, or passthrough JSON.  Multiple snapshots merge with the
    same semantics as the mesh-wide allgather (counter sums, histogram
    bucket merges, wall maxima)."""
    from hadoop_bam_tpu.obs import (
        load_metrics_json, prometheus_text, render_metrics,
    )
    from hadoop_bam_tpu.utils.metrics import Metrics

    merged = Metrics()
    for path in args.files:
        merged.merge_dict(load_metrics_json(path))
    d = merged.to_dict()
    if args.format == "prometheus":
        sys.stdout.write(prometheus_text(d))
    elif args.format == "json":
        import json
        json.dump(d, sys.stdout, indent=1, sort_keys=True)
        sys.stdout.write("\n")
    else:
        print(render_metrics(d))
    return 0


# ---------------------------------------------------------------------------
# lint
# ---------------------------------------------------------------------------

def cmd_lint(args) -> int:
    """Repo-native static analysis (hbam-lint): trace safety, collective
    lockstep, error taxonomy, binary-layout contracts.  Non-zero exit on
    unsuppressed findings — the CI contract."""
    from hadoop_bam_tpu.analysis.core import lint_main
    fwd: List[str] = []
    if args.root:
        fwd += ["--root", args.root]
    for only in args.only or ():
        fwd += ["--only", only]
    if args.baseline:
        fwd += ["--baseline", args.baseline]
    if args.no_baseline:
        fwd.append("--no-baseline")
    if args.update_baseline:
        fwd.append("--update-baseline")
    if args.show_suppressed:
        fwd.append("--show-suppressed")
    if args.format != "text":
        fwd += ["--format", args.format]
    if args.no_cache:
        fwd.append("--no-cache")
    return lint_main(fwd)


# ---------------------------------------------------------------------------
# vcf-sort
# ---------------------------------------------------------------------------

def cmd_vcf_sort(args) -> int:
    from hadoop_bam_tpu.utils.sort import sort_vcf

    if args.run_records <= 0:
        raise SystemExit("--run-records must be positive")
    n = sort_vcf(args.input, args.output, config=_write_config(args),
                 run_records=args.run_records)
    print(f"wrote {args.output} ({n} records)")
    return 0


# ---------------------------------------------------------------------------
# cohort
# ---------------------------------------------------------------------------

def cmd_cohort(args) -> int:
    """Cohort variant plane (cohort/): join the manifest's single-sample
    VCF/BCF inputs on position into one [variants, samples] mesh tensor
    and run the GWAS drivers (allele frequency, call rate, HWE; the
    score test with --pheno).  --region restricts the report to one
    slice; --tsv writes the full per-variant table."""
    import numpy as np

    from hadoop_bam_tpu.cohort import GWAS_COLUMNS, CohortDataset

    _start_obs(args)
    journal = None
    if getattr(args, "journal", None) is not None:
        from hadoop_bam_tpu.jobs import JOURNAL_SUFFIX
        journal = _journal_arg(args, args.manifest + JOURNAL_SUFFIX)
    ds = CohortDataset(args.manifest, journal_path=journal)
    pheno = None
    if args.pheno:
        # one float per manifest sample, in manifest order; 'nan' (or
        # any non-float token) = missing phenotype
        vals = []
        with open(args.pheno) as f:
            for line in f:
                line = line.strip()
                if not line or line.startswith("#"):
                    continue
                try:
                    vals.append(float(line.split()[-1]))
                except ValueError:
                    vals.append(float("nan"))
        pheno = np.asarray(vals, np.float32)
    res = ds.gwas(phenotype=pheno)
    mask = np.ones(res["n_variants"], bool)
    if args.region:
        from hadoop_bam_tpu.split.intervals import parse_interval
        iv = parse_interval(args.region)
        rid = ds.contig_index(iv.rname)
        if rid < 0:
            raise SystemExit(f"contig {iv.rname!r} is in no sample header")
        mask = ((res["chrom"] == rid) & (res["pos"] >= iv.start)
                & (res["pos"] <= iv.end))
    n = int(mask.sum())
    print(f"samples\t{ds.n_samples}")
    print(f"variants\t{n}")
    print(f"quarantined\t{len(res['quarantined'])}")
    for sid in sorted(res["quarantined"]):
        print(f"quarantined_sample\t{sid}", file=sys.stderr)
    with np.errstate(invalid="ignore"):
        for col in GWAS_COLUMNS:
            v = res[col][mask]
            if col == "score_chi2" and pheno is None:
                continue
            if v.size and not np.all(np.isnan(v)):
                print(f"mean_{col}\t{np.nanmean(v):.6f}")
            else:
                print(f"mean_{col}\tnan")
    if args.tsv:
        cols = [c for c in GWAS_COLUMNS
                if not (c == "score_chi2" and pheno is None)]
        with open(args.tsv, "w") as f:
            f.write("\t".join(["chrom", "pos", "n_allele"] + cols) + "\n")
            rows = np.flatnonzero(mask)
            for r in rows:
                name = (ds.contigs[int(res["chrom"][r])]
                        if 0 <= int(res["chrom"][r]) < len(ds.contigs)
                        else str(int(res["chrom"][r])))
                f.write("\t".join(
                    [name, str(int(res["pos"][r])),
                     str(int(res["n_allele"][r]))]
                    + [f"{float(res[c][r]):.6g}" for c in cols]) + "\n")
        print(f"wrote {args.tsv} ({n} variants)", file=sys.stderr)
    _finish_obs(args)
    return 0


# ---------------------------------------------------------------------------
# resume / jobs (crash-safe job layer, jobs/)
# ---------------------------------------------------------------------------

def cmd_resume(args) -> int:
    """Resume (or verify) the job a journal describes: re-invokes the
    journaled pipeline, which replays the journal, verifies every
    recorded artifact, skips the completed units, and re-runs only the
    remainder.  Identity/fingerprint/plan mismatches refuse loudly
    (PlanError) rather than publish a silently-wrong output."""
    from hadoop_bam_tpu.jobs import resume_job
    from hadoop_bam_tpu.utils.metrics import METRICS

    _start_obs(args)
    out = resume_job(args.journal)
    for k in sorted(out):
        v = out[k]
        if v is not None:
            print(f"{k}\t{v}")
    for c in ("jobs.rounds_skipped", "jobs.spans_skipped",
              "jobs.shards_skipped", "jobs.chunks_replayed",
              "jobs.jobs_skipped", "jobs.stale_runs_swept",
              "jobs.stale_chunks_swept", "write.stale_temps_swept"):
        n = METRICS.counters.get(c, 0)
        if n:
            print(f"{c}\t{n}")
    _finish_obs(args)
    return 0


def cmd_jobs(args) -> int:
    """List job journals in a directory: kind, status (done / resumable
    / fresh / corrupt), committed units, output.  ``--json`` emits one
    machine-readable object per journal (trace_id, resume grain, units
    skipped/total) — the SAME document ``hbam top`` renders, so
    external schedulers and the live view share one parser
    (``jobs.runner.job_info_doc``)."""
    import json as _json

    from hadoop_bam_tpu.jobs import job_info_doc, job_status, list_jobs

    infos = [job_status(p) for p in args.journals] if args.journals \
        else list_jobs(args.dir)
    if getattr(args, "json", False):
        for i in infos:
            print(_json.dumps(job_info_doc(i), sort_keys=True))
        return 0
    if not infos:
        print(f"no *.hbam-journal files in {args.dir}")
        return 0
    for i in infos:
        detail = f"\t[{i.detail}]" if i.detail else ""
        print(f"{i.path}\t{i.kind}\t{i.status}\tunits={i.units}"
              f"\t{i.output or '-'}{detail}")
    return 0


# ---------------------------------------------------------------------------
# top (live ops view over a running `hbam serve`)
# ---------------------------------------------------------------------------

def _top_fetch(host: str, port: int, timeout: float = 10.0):
    """One poll of a live serve process: the health document and the
    metrics/SLO snapshot, over the JSONL TCP transport."""
    import json as _json
    import socket

    with socket.create_connection((host, port), timeout=timeout) as s:
        f = s.makefile("rw", encoding="utf-8", newline="\n")
        f.write(_json.dumps({"op": "health", "id": 1}) + "\n")
        f.write(_json.dumps({"op": "metrics", "id": 2}) + "\n")
        f.flush()
        docs = {}
        for _ in range(2):
            line = f.readline()
            if not line:
                break
            d = _json.loads(line)
            docs[d.get("id")] = d
    return (docs.get(1, {}).get("health", {}), docs.get(2, {}))


def _hist_summary(hists: dict, key: str) -> Optional[dict]:
    from hadoop_bam_tpu.obs import Histogram
    h = hists.get(key)
    if not isinstance(h, dict) or "buckets" not in h:
        return None
    return Histogram.from_dict(h).summary()


def _render_top(health: dict, mdoc: dict, prev_counters: Optional[dict],
                interval: float, jobs_dir: Optional[str]) -> str:
    """One `hbam top` frame as text: per-tenant q/s + latency
    percentiles, cache hit rates, pool occupancy, breaker/SLO state,
    and active-job resume progress."""
    metrics = mdoc.get("metrics", {}) or {}
    counters = {k: int(v)
                for k, v in dict(metrics.get("counters", {})).items()}
    hists = dict(metrics.get("histograms", {}))
    lines: List[str] = []
    tiles = health.get("tiles", {}) or {}
    pool = health.get("pool", {}) or {}
    lines.append(
        f"status={health.get('status', '?')} "
        f"queued={health.get('queued', '?')} "
        f"fault_pressure={health.get('fault_pressure', 0)} "
        f"open_breakers={health.get('open_breakers', 0)}")
    lines.append(
        f"pool: workers={pool.get('workers', '?')} "
        f"live={pool.get('threads_live', '?')} "
        f"queued={pool.get('queued_tasks', 0)} "
        f"bg={pool.get('bg_running', 0)}/{pool.get('bg_queued', 0)}")
    th = int(tiles.get("hits", 0))
    tm = int(tiles.get("misses", 0))
    ch = counters.get("query.cache_hits", 0)
    cm = counters.get("query.cache_misses", 0)
    lines.append(
        f"caches: tile_hit_rate="
        f"{th / (th + tm):.2f}" if (th + tm) else
        "caches: tile_hit_rate=-")
    lines[-1] += (f" chunk_hit_rate={ch / (ch + cm):.2f}"
                  if (ch + cm) else " chunk_hit_rate=-")
    for name, s in sorted((mdoc.get("slo") or {}).items()):
        burn = " ".join(f"{w}={v}" for w, v in sorted(s.items()))
        lines.append(f"slo {name}: {burn}")
    fl = health.get("flight", {}) or {}
    if fl:
        lines.append(f"flight: dumps={fl.get('dumps_written', 0)} "
                     f"last={fl.get('last_dump') or '-'}")
    # per-tenant table from the serve.requests.<tenant> counters and
    # serve.latency_s.<tenant> histograms the serve loop mirrors into
    # its process-global metrics
    _prefix = "serve.requests."
    tenants = sorted(k[len(_prefix):] for k in counters
                     if k.startswith(_prefix))
    tbreak = health.get("tenant_breakers", {}) or {}
    if tenants:
        lines.append(f"{'tenant':<16}{'q/s':>8}{'p50ms':>9}{'p99ms':>9}"
                     f"{'reqs':>8}  breaker")
        for t in tenants:
            reqs = counters.get(f"serve.requests.{t}", 0)
            if prev_counters is not None and interval > 0:
                d = reqs - prev_counters.get(f"serve.requests.{t}", 0)
                qps = f"{d / interval:.1f}"
            else:
                qps = "-"
            s = _hist_summary(hists, f"serve.latency_s.{t}")
            p50 = f"{s['p50'] * 1e3:.1f}" if s else "-"
            p99 = f"{s['p99'] * 1e3:.1f}" if s else "-"
            br = (tbreak.get(t) or {}).get("state", "closed")
            lines.append(f"{t:<16}{qps:>8}{p50:>9}{p99:>9}"
                         f"{reqs:>8}  {br}")
    else:
        lines.append("tenants: (no requests served yet)")
    if jobs_dir:
        from hadoop_bam_tpu.jobs import job_info_doc, list_jobs
        rows = [job_info_doc(i) for i in list_jobs(jobs_dir)]
        active = [r for r in rows if r["status"] != "done"]
        lines.append(f"jobs in {jobs_dir}: {len(rows)} journal(s), "
                     f"{len(active)} not done")
        for r in rows:
            lines.append(
                f"  {r['path']} {r['kind']} {r['status']} "
                f"grain={r['resume_grain']} "
                f"units={r['units_skipped']}/{r['units_total']} "
                f"trace={r['trace_id'] or '-'}")
    return "\n".join(lines)


def _parse_endpoints(spec: str) -> List[Tuple[str, int]]:
    out: List[Tuple[str, int]] = []
    for entry in (spec or "").split(","):
        entry = entry.strip()
        if not entry:
            continue
        host, _, port = entry.rpartition(":")
        if not host or not port.isdigit():
            raise ValueError(f"bad endpoint {entry!r} — want HOST:PORT")
        out.append((host, int(port)))
    if not out:
        raise ValueError("--endpoints needs at least one HOST:PORT")
    return out


def _fleet_snapshot(endpoints, timeout: float) -> List[dict]:
    """Poll every fleet endpoint once; an unreachable replica becomes a
    DOWN row, never a failed frame (the whole point of a fleet view is
    seeing who is missing)."""
    snaps = []
    for host, port in endpoints:
        ep = f"{host}:{port}"
        try:
            health, mdoc = _top_fetch(host, port, timeout=timeout)
        except (OSError, ValueError) as e:
            snaps.append({"endpoint": ep, "ok": False, "err": str(e)})
            continue
        snaps.append({"endpoint": ep, "ok": True,
                      "health": health, "mdoc": mdoc})
    return snaps


def _render_fleet_top(snaps: List[dict], prev: dict,
                      interval: float) -> str:
    """The ``hbam top --endpoints`` frame: one row per replica
    (q/s, p50/p99 across tenants, tile hit rate, peer-breaker states,
    degraded flag) plus fleet-wide aggregates."""
    from hadoop_bam_tpu.obs import Histogram

    lines: List[str] = []
    lines.append(f"{'replica':<12}{'endpoint':<22}{'q/s':>7}{'p50ms':>8}"
                 f"{'p99ms':>8}{'tile%':>7}{'peers':>12}  flags")
    up = 0
    tot_qps = 0.0
    tot_th = tot_tm = 0
    tot_fetch_ok = tot_served = tot_local = 0
    for snap in snaps:
        ep = snap["endpoint"]
        if not snap["ok"]:
            lines.append(f"{'-':<12}{ep:<22}{'-':>7}{'-':>8}{'-':>8}"
                         f"{'-':>7}{'-':>12}  DOWN ({snap['err']})")
            continue
        up += 1
        health, mdoc = snap["health"], snap["mdoc"]
        fleet = health.get("fleet") or {}
        rid = str(fleet.get("replica_id") or "-")
        metrics = mdoc.get("metrics", {}) or {}
        counters = {k: int(v)
                    for k, v in dict(metrics.get("counters", {})).items()}
        hists = dict(metrics.get("histograms", {}))
        reqs = sum(v for k, v in counters.items()
                   if k.startswith("serve.requests."))
        pc = prev.get(ep)
        if pc is not None and interval > 0:
            preqs = sum(v for k, v in pc.items()
                        if k.startswith("serve.requests."))
            qv = max(0, reqs - preqs) / interval
            tot_qps += qv
            qps = f"{qv:.1f}"
        else:
            qps = "-"
        merged = Histogram.merged(
            Histogram.from_dict(h) for k, h in hists.items()
            if k.startswith("serve.latency_s.")
            and isinstance(h, dict) and "buckets" in h)
        if merged.count:
            p50 = f"{merged.percentile(50) * 1e3:.1f}"
            p99 = f"{merged.percentile(99) * 1e3:.1f}"
        else:
            p50 = p99 = "-"
        tiles = health.get("tiles", {}) or {}
        th, tm = int(tiles.get("hits", 0)), int(tiles.get("misses", 0))
        tot_th += th
        tot_tm += tm
        tile = f"{100.0 * th / (th + tm):.0f}" if (th + tm) else "-"
        brk = {}
        for st in (d.get("state", "closed") for d in
                   dict(fleet.get("peer_breakers") or {}).values()):
            brk[st] = brk.get(st, 0) + 1
        peers = ",".join(f"{n}{s[:1].upper()}"
                         for s, n in sorted(brk.items())) or "-"
        flags = []
        if fleet.get("degraded"):
            flags.append("DEGRADED")
        if health.get("status") not in (None, "ok"):
            flags.append(str(health.get("status")))
        tot_fetch_ok += int(fleet.get("peer_fetch_ok", 0))
        tot_served += int(fleet.get("chunks_served", 0))
        tot_local += int(fleet.get("local_decodes", 0))
        lines.append(f"{rid:<12}{ep:<22}{qps:>7}{p50:>8}{p99:>8}"
                     f"{tile:>7}{peers:>12}  {' '.join(flags) or '-'}")
        snap["counters"] = counters
    agg_tile = (f"{100.0 * tot_th / (tot_th + tot_tm):.0f}%"
                if (tot_th + tot_tm) else "-")
    denom = tot_fetch_ok + tot_local
    xr = f"{tot_fetch_ok / denom:.2f}" if denom else "-"
    lines.append(
        f"fleet: up={up}/{len(snaps)} q/s={tot_qps:.1f} "
        f"tile_hit={agg_tile} peer_fetches={tot_fetch_ok} "
        f"chunks_served_for_peers={tot_served} "
        f"cross_replica_tile_rate={xr}")
    return "\n".join(lines)


def cmd_top(args) -> int:
    """Live introspection of a running ``hbam serve --port`` process:
    polls the ``{"op": "health"}`` / ``{"op": "metrics"}`` transport
    surfaces and renders per-tenant q/s, latency percentiles, cache hit
    rates, pool occupancy, breaker + SLO burn state, and (with
    ``--jobs-dir``) journaled-job resume progress.  With
    ``--endpoints HOST:PORT,...`` it becomes the FLEET view: one row
    per replica plus fleet-wide aggregates, DOWN rows for unreachable
    replicas."""
    import time as _time

    if getattr(args, "endpoints", None):
        try:
            endpoints = _parse_endpoints(args.endpoints)
        except ValueError as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
        iterations = 1 if args.once else int(args.iterations)
        prev: dict = {}
        i = 0
        try:
            while True:
                i += 1
                snaps = _fleet_snapshot(endpoints, timeout=args.timeout)
                frame = _render_fleet_top(snaps, prev,
                                          float(args.interval))
                print(f"-- hbam top (fleet, poll {i}"
                      f"{'' if not iterations else f'/{iterations}'}"
                      f") --")
                print(frame, flush=True)
                prev = {s["endpoint"]: s.get("counters", {})
                        for s in snaps if s["ok"]}
                if iterations and i >= iterations:
                    return 0
                _time.sleep(max(0.1, float(args.interval)))
        except KeyboardInterrupt:
            return 0
    if args.port is None:
        print("error: --port (single server) or --endpoints (fleet) "
              "is required", file=sys.stderr)
        return 2
    iterations = 1 if args.once else int(args.iterations)
    prev_counters = None
    i = 0
    try:
        while True:
            i += 1
            try:
                health, mdoc = _top_fetch(args.host, args.port,
                                          timeout=args.timeout)
            except (OSError, ValueError) as e:
                print(f"error: cannot poll {args.host}:{args.port}: "
                      f"{e}", file=sys.stderr)
                return 1
            frame = _render_top(health, mdoc, prev_counters,
                                float(args.interval), args.jobs_dir)
            hdr = (f"-- hbam top {args.host}:{args.port} "
                   f"(poll {i}"
                   f"{'' if not iterations else f'/{iterations}'}) --")
            print(hdr)
            print(frame, flush=True)
            prev_counters = {
                k: int(v) for k, v in dict(
                    (mdoc.get("metrics", {}) or {})
                    .get("counters", {})).items()}
            if iterations and i >= iterations:
                return 0
            _time.sleep(max(0.1, float(args.interval)))
    except KeyboardInterrupt:
        # ^C is the documented way out of the default forever loop
        return 0


def cmd_fleet(args) -> int:
    """One replica's view of the serving fleet: the ``{"op": "fleet"}``
    transport surface — membership states (alive/suspect/evicted),
    per-peer breaker states, hedge soft deadline, peer-fetch/serve
    counters, degraded flag."""
    import json as _json
    import socket

    try:
        with socket.create_connection((args.host, args.port),
                                      timeout=args.timeout) as s:
            f = s.makefile("rw", encoding="utf-8", newline="\n")
            f.write(_json.dumps({"op": "fleet", "id": 1}) + "\n")
            f.flush()
            doc = _json.loads(f.readline() or "{}")
    except (OSError, ValueError) as e:
        print(f"error: cannot poll {args.host}:{args.port}: {e}",
              file=sys.stderr)
        return 1
    fleet = doc.get("fleet")
    if fleet is None:
        print(f"{args.host}:{args.port}: not a fleet replica "
              f"(started without --peers/--replica-id)")
        return 1
    if args.json:
        print(_json.dumps(fleet, sort_keys=True, default=str))
        return 0
    print(f"replica={fleet.get('replica_id')} "
          f"replication={fleet.get('replication')} "
          f"degraded={fleet.get('degraded')}")
    peers = dict((fleet.get("membership") or {}).get("peers") or {})
    for pid in sorted(peers):
        st = peers[pid] if isinstance(peers[pid], str) else \
            peers[pid].get("state", "?")
        brk = (dict(fleet.get("peer_breakers") or {}).get(pid)
               or {}).get("state", "-")
        print(f"  {pid:<16}{st:<10}breaker={brk}")
    soft = fleet.get("hedge_soft_deadline_s")
    print(f"hedge_soft_deadline_s={soft if soft is not None else '-'} "
          f"peer_fetch_ok={fleet.get('peer_fetch_ok', 0)} "
          f"peer_fetch_failed={fleet.get('peer_fetch_failed', 0)} "
          f"chunks_served={fleet.get('chunks_served', 0)} "
          f"hedges={fleet.get('hedges', 0)}/"
          f"{fleet.get('hedge_wins', 0)} wins "
          f"degraded_serves={fleet.get('degraded_serves', 0)}")
    return 0


# ---------------------------------------------------------------------------
# frontend
# ---------------------------------------------------------------------------

def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="hadoop_bam_tpu",
        description="TPU-native splittable genomics I/O — CLI verbs "
                    "(reference parity: cat, index, sort, summarize, view, "
                    "fixmate, vcf-sort)")
    sub = p.add_subparsers(dest="verb", required=True)

    v = sub.add_parser("view", help="print records as SAM/VCF text")
    v.add_argument("path")
    v.add_argument("region", nargs="?", default=None,
                   help="chr[:start-end] filter")
    v.add_argument("-H", "--header-only", action="store_true")
    v.add_argument("-c", "--count", action="store_true")
    v.add_argument("--no-header", action="store_true")
    v.set_defaults(fn=cmd_view, uses_device=False)

    i = sub.add_parser("index", help="build splitting index sidecar(s)")
    i.add_argument("paths", nargs="+")
    i.add_argument("-g", "--granularity", type=int, default=4096)
    i.add_argument("--flavor",
                   choices=["splitting-bai", "sbi", "bai", "tbi"],
                   default="splitting-bai",
                   help="bai = genomic BAI for BAM; tbi = tabix for BGZF "
                        "VCF (both need coordinate-sorted input and "
                        "enable interval queries/trimming)")
    i.set_defaults(fn=cmd_index, uses_device=False)

    c = sub.add_parser("cat", help="concatenate same-header BAMs")
    c.add_argument("output")
    c.add_argument("inputs", nargs="+")
    c.set_defaults(fn=cmd_cat, uses_device=False)

    s = sub.add_parser("summarize", help="distributed flagstat")
    s.add_argument("path")
    s.add_argument("--metrics", action="store_true",
                   help="dump mesh-merged pipeline counters/timers/"
                        "histograms to stderr")
    _add_obs_flags(s)
    s.set_defaults(fn=cmd_summarize, uses_device=True)

    sq = sub.add_parser("seq-stats",
                        help="GC/quality/base stats via the Pallas "
                             "payload kernel")
    sq.add_argument("path")
    sq.add_argument("--max-len", type=int, default=160)
    sq.add_argument("--reference",
                    help="FASTA reference for reference-compressed CRAM "
                         "(the hadoopbam.cram.reference-source-path "
                         "analog)")
    sq.set_defaults(fn=cmd_seq_stats, uses_device=True)

    vst = sub.add_parser("vcf-stats",
                         help="variant counts, allele freq, call rates "
                              "on the mesh")
    vst.add_argument("path")
    vst.set_defaults(fn=cmd_vcf_stats, uses_device=True)

    so = sub.add_parser("sort", help="sort a BAM (external spill-merge)")
    so.add_argument("input")
    so.add_argument("output")
    so.add_argument("-n", "--by-name", action="store_true")
    so.add_argument("--run-records", type=int, default=None,
                    help="memory bound in records: per in-memory sort run "
                         "(spill-merge mode, default 1000000), or per "
                         "device per exchange round (--mesh: engages the "
                         "multi-round spill shuffle)")
    so.add_argument("--mesh", action="store_true",
                    help="bucketed sort over the device mesh (device key "
                         "extraction + all_to_all exchange; coordinate "
                         "order only; without --run-records the input "
                         "must fit host/device memory)")
    so.add_argument("--exchange", choices=("index", "bytes"), default=None,
                    help="mesh shuffle flavor: 'index' (keys only ride the "
                         "all_to_all; single-host) or 'bytes' (record bytes "
                         "ride it; required and default under "
                         "jax.distributed multi-host runs)")
    so.add_argument("--compress-level", type=int, default=None,
                    metavar="0-9",
                    help="BGZF deflate level for the output (default "
                         "config write_compress_level = 6; the "
                         "hbam.write-compress-level key)")
    so.add_argument("--no-write-index", action="store_true",
                    help="skip the BAI + splitting-index sidecars the "
                         "write path co-writes with coordinate-sorted "
                         "output (-n output is never indexed)")
    so.add_argument("--journal", nargs="?", const="", default=None,
                    metavar="PATH",
                    help="crash-safe run (--mesh only): record job "
                         "identity + per-round spill commits to an "
                         "fsync'd journal (default PATH: "
                         "<output>.hbam-journal) so a killed run "
                         "resumes via `hbam resume` — spill mode "
                         "(--run-records) resumes at round grain, "
                         "resident modes at job grain")
    so.set_defaults(fn=cmd_sort, uses_device=False)

    cov = sub.add_parser("coverage",
                         help="per-base aligned depth over a region "
                              "(device cigar pileup)")
    cov.add_argument("input")
    cov.add_argument("region", help='samtools-style region, e.g. '
                                    '"chr20:1,000-2,000"')
    cov.add_argument("--max-cigar", type=int, default=64,
                     help="cigar ops per record tile (loud error if "
                          "exceeded)")
    cov.add_argument("--bedgraph", metavar="PATH",
                     help="write non-zero depth runs as bedGraph")
    cov.set_defaults(fn=cmd_coverage, uses_device=True)

    f = sub.add_parser("fixmate", help="fill mate fields on name-grouped BAM")
    f.add_argument("input")
    f.add_argument("output")
    f.add_argument("--compress-level", type=int, default=None,
                   metavar="0-9",
                   help="BGZF deflate level for the output (default "
                        "config write_compress_level = 6)")
    f.add_argument("--no-write-index", action="store_true",
                   help="skip the index sidecars the write path "
                        "co-writes (name-grouped output is rarely "
                        "coordinate-compatible; the sidecars are only "
                        "meaningful when it is)")
    f.set_defaults(fn=cmd_fixmate, uses_device=False)

    md = sub.add_parser(
        "mkdup",
        help="mark (or remove) duplicates, fused: read -> mesh sort "
             "exchange -> on-device signature markdup -> flag-patched "
             "indexed write, one pass over the records")
    md.add_argument("input")
    md.add_argument("output")
    md.add_argument("--remove-duplicates", action="store_true",
                    help="drop duplicate records instead of setting "
                         "their 0x400 flag")
    md.add_argument("--library-from", choices=("none", "rg"),
                    default="none",
                    help="library component of the duplicate signature: "
                         "'none' (one anonymous library) or 'rg' (join "
                         "each record's RG:Z tag to its @RG LB header "
                         "library)")
    md.add_argument("--run-records", type=int, default=None,
                    help="records per device per exchange round (the "
                         "spill shuffle's memory bound; default "
                         "1000000)")
    md.add_argument("--compress-level", type=int, default=None,
                    metavar="0-9",
                    help="BGZF deflate level for the output (default "
                         "config write_compress_level = 6)")
    md.add_argument("--no-write-index", action="store_true",
                    help="skip the BAI + splitting-index sidecars the "
                         "write path co-writes with the coordinate-"
                         "sorted output")
    md.add_argument("--journal", nargs="?", const="", default=None,
                    metavar="PATH",
                    help="crash-safe run: record per-round spills, the "
                         "duplicate bitmap, and per-shard writes to an "
                         "fsync'd journal (default PATH: "
                         "<output>.hbam-journal) so a killed run "
                         "resumes via `hbam resume` at stage grain")
    md.set_defaults(fn=cmd_mkdup, uses_device=True)

    q = sub.add_parser("query",
                       help="batched random-access region queries via the "
                            "genomic index (.bai/.csi, .tbi, CRAM "
                            "containers); device interval predicate + "
                            "chunk cache")
    q.add_argument("path")
    q.add_argument("regions", nargs="+",
                   help='samtools-style regions, e.g. "chr20:1,000-2,000"')
    q.add_argument("-c", "--count", action="store_true",
                   help="print per-region match counts instead of records")
    q.add_argument("--deadline", type=float, default=None,
                   help="per-batch deadline in seconds (blown deadlines "
                        "raise the retryable TransientIOError)")
    q.add_argument("--metrics", action="store_true",
                   help="dump chunk-cache hit/miss stats and latency "
                        "percentiles to stderr")
    _add_obs_flags(q)
    q.set_defaults(fn=cmd_query, uses_device=True)

    sv = sub.add_parser("serve",
                        help="long-running multi-tenant region server: "
                             "JSONL requests on stdin (or --port TCP), "
                             "device-resident tile cache, per-tenant "
                             "quotas + priority classes, predictive "
                             "prefetch")
    sv.add_argument("--port", type=int, default=None,
                    help="listen on TCP PORT (0 = ephemeral) instead of "
                         "stdin/stdout JSONL")
    sv.add_argument("--host", default="127.0.0.1",
                    help="bind address for --port (default 127.0.0.1)")
    sv.add_argument("--deadline", type=float, default=None,
                    help="default per-request deadline in seconds, "
                         "anchored at enqueue (admission wait counts)")
    sv.add_argument("--tile-cache-bytes", type=int, default=None,
                    help="device-resident decoded-tile LRU budget "
                         "(default config.serve_tile_cache_bytes)")
    sv.add_argument("--no-prefetch", action="store_true",
                    help="disable predictive adjacent-window prefetch")
    sv.add_argument("--warm", metavar="PATH", action="append",
                    help="pre-resolve header+index of PATH at startup; "
                         "repeatable")
    sv.add_argument("--metrics", action="store_true",
                    help="dump tile/chunk/prefetch/tenant stats to "
                         "stderr at shutdown")
    sv.add_argument("--breaker-cooldown", type=float, default=None,
                    help="seconds an OPEN breaker (tenant / decode "
                         "plane / quarantine) waits before its "
                         "half-open re-probe (default "
                         "config.breaker_cooldown_s)")
    sv.add_argument("--flight-dir", metavar="DIR", default=None,
                    help="write flight-recorder incident dumps "
                         "(breaker trips, plane demotions, deadline "
                         "misses, serve errors) as redacted JSON here, "
                         "rotation-capped (config.flight_dump_cap); "
                         "without it the always-on ring is memory-only "
                         "and still served via {\"op\": \"health\"}")
    sv.add_argument("--replica-id", default=None, metavar="ID",
                    help="this replica's name in the fleet peer set "
                         "(enables fleet mode with --peers)")
    sv.add_argument("--peers", default=None,
                    metavar="ID=HOST:PORT,...",
                    help="static fleet roster (every replica, including "
                         "this one): rendezvous-hashed tile ownership, "
                         "heartbeat membership, hedged peer-fetch of "
                         "decoded tiles over the same TCP transport")
    sv.add_argument("--replication", type=int, default=None,
                    help="tile ownership replication factor R "
                         "(default config.fleet_replication)")
    _add_obs_flags(sv)
    sv.set_defaults(fn=cmd_serve, uses_device=True)

    mt = sub.add_parser("metrics",
                        help="render/merge metrics snapshots written by "
                             "--metrics-json (text, Prometheus "
                             "exposition, or JSON)")
    mt.add_argument("files", nargs="+",
                    help="snapshot JSON file(s); several merge like the "
                         "mesh-wide allgather")
    mt.add_argument("--format", choices=("text", "prometheus", "json"),
                    default="text")
    mt.set_defaults(fn=cmd_metrics, uses_device=False)

    ex = sub.add_parser(
        "explain",
        help="compile an op's plan IR and print it with the decode-"
             "plane decision (which plane, and why each rejected "
             "plane failed its gate)")
    ex.add_argument("op", choices=["flagstat", "seq-stats", "vcf-stats",
                                   "query", "cohort", "serve-tile",
                                   "mkdup"])
    ex.add_argument("path", help="input file (BAM/VCF/BCF) or cohort "
                                 "manifest JSON")
    ex.add_argument("--region", default=None,
                    help="region for `explain query`/`explain "
                         "serve-tile` (resolved through the file's "
                         "genomic index into pinned chunks)")
    ex.add_argument("--intervals", default=None,
                    help="explain with hadoopbam.bam.intervals set "
                         "(gates the device plane and fused streaming)")
    ex.add_argument("--inflate-backend", default=None,
                    choices=["auto", "native", "zlib", "device"],
                    help="explain under this backend instead of the "
                         "config default")
    ex.add_argument("--skip-bad-spans", action="store_true",
                    help="explain with quarantine-and-skip on")
    ex.add_argument("--no-fused", action="store_true",
                    help="explain with the fused decode knob off")
    ex.add_argument("--json", action="store_true",
                    help="emit {plan, digest, decision} as JSON")
    ex.set_defaults(fn=cmd_explain, uses_device=True)

    ln = sub.add_parser("lint",
                        help="static analysis: trace safety (TS1xx), "
                             "collective lockstep (CL2xx), error taxonomy "
                             "(ET3xx), layout contracts (LC4xx), "
                             "observability discipline (OB6xx), serving "
                             "cache bounds (SV8xx), write-path atomicity "
                             "(WR10x), thread-safety/lock order "
                             "(TH1xx/LK2xx); exits non-zero on "
                             "unsuppressed findings")
    ln.add_argument("--root", default=None,
                    help="package directory to analyze")
    ln.add_argument("--only", action="append", metavar="ANALYZER",
                    help="run one analyzer (trace_safety, lockstep, "
                         "taxonomy, layout, feedpath, querycache, obs, "
                         "decodepath, servebounds, threadsafety, "
                         "writepath); repeatable")
    ln.add_argument("--baseline", default=None,
                    help="baseline file (default analysis/baseline.json)")
    ln.add_argument("--no-baseline", action="store_true")
    ln.add_argument("--update-baseline", action="store_true",
                    help="accept all current findings into the baseline")
    ln.add_argument("--show-suppressed", action="store_true")
    ln.add_argument("--format", choices=("text", "json", "sarif"),
                    default="text",
                    help="findings output format (json/sarif for CI "
                         "annotation; text stays byte-stable)")
    ln.add_argument("--no-cache", action="store_true",
                    help="ignore the lint findings cache")
    ln.set_defaults(fn=cmd_lint, uses_device=False)

    ch = sub.add_parser(
        "cohort",
        help="join a cohort manifest of single-sample VCF/BCF files on "
             "position and run the GWAS mesh drivers")
    ch.add_argument("manifest",
                    help='manifest JSON ({"samples": [{"id", "path"}, ...]}'
                         " or a bare path list)")
    ch.add_argument("--region", default=None,
                    help="report one chr[:start-end] slice of the joined "
                         "tensor instead of the whole cohort")
    ch.add_argument("--pheno", default=None, metavar="FILE",
                    help="phenotype file (one float per manifest sample, "
                         "manifest order; nan = missing) — enables the "
                         "score-test association column")
    ch.add_argument("--tsv", default=None, metavar="FILE",
                    help="write the per-variant stats table")
    ch.add_argument("--journal", nargs="?", const="", default=None,
                    metavar="PATH",
                    help="crash-safe join: persist every joined chunk + "
                         "an fsync'd journal (default PATH: "
                         "<manifest>.hbam-journal); a killed join "
                         "resumes via `hbam resume`, replaying the "
                         "committed chunks instead of re-joining them")
    _add_obs_flags(ch)
    ch.set_defaults(fn=cmd_cohort, uses_device=True)

    rs = sub.add_parser(
        "resume",
        help="resume (or verify) a journaled job after a crash")
    rs.add_argument("journal", help="the job's .hbam-journal file")
    _add_obs_flags(rs)
    rs.set_defaults(fn=cmd_resume, uses_device=True)

    jb = sub.add_parser(
        "jobs", help="list job journals (kind, status, committed units)")
    jb.add_argument("dir", nargs="?", default=".",
                    help="directory to scan for *.hbam-journal files")
    jb.add_argument("--journal", dest="journals", action="append",
                    default=None, metavar="PATH",
                    help="inspect specific journal file(s) instead of "
                         "scanning a directory")
    jb.add_argument("--json", action="store_true",
                    help="one machine-readable JSON object per journal "
                         "(trace_id, resume_grain, units skipped/total) "
                         "— the parser `hbam top` and external "
                         "schedulers share")
    jb.set_defaults(fn=cmd_jobs, uses_device=False)

    tp = sub.add_parser(
        "top",
        help="live ops view of a running `hbam serve --port` process: "
             "per-tenant q/s + p50/p99, cache hit rates, pool "
             "occupancy, breaker + SLO burn state, job resume progress")
    tp.add_argument("--host", default="127.0.0.1")
    tp.add_argument("--port", type=int, default=None,
                    help="the serve process's TCP port")
    tp.add_argument("--endpoints", default=None,
                    metavar="HOST:PORT,...",
                    help="fleet view: poll N replicas and render one "
                         "row each (q/s, p50/p99, tile hit rate, peer "
                         "breaker states, degraded flag) plus "
                         "fleet-wide aggregates; DOWN rows for "
                         "unreachable replicas")
    tp.add_argument("--interval", type=float, default=2.0,
                    help="seconds between polls (default 2)")
    tp.add_argument("--iterations", type=int, default=0,
                    help="stop after N polls (0 = until ^C)")
    tp.add_argument("--once", action="store_true",
                    help="poll exactly once and exit (scripting shape)")
    tp.add_argument("--timeout", type=float, default=10.0,
                    help="per-poll socket timeout")
    tp.add_argument("--jobs-dir", default=None, metavar="DIR",
                    help="also render *.hbam-journal resume progress "
                         "from DIR (the `hbam jobs --json` document)")
    tp.set_defaults(fn=cmd_top, uses_device=False)

    fl = sub.add_parser(
        "fleet",
        help="one replica's fleet view: membership states, per-peer "
             "breaker states, hedge soft deadline, peer-fetch counters")
    fl.add_argument("--host", default="127.0.0.1")
    fl.add_argument("--port", type=int, required=True,
                    help="any fleet replica's TCP port")
    fl.add_argument("--timeout", type=float, default=10.0)
    fl.add_argument("--json", action="store_true",
                    help="emit the raw fleet states document")
    fl.set_defaults(fn=cmd_fleet, uses_device=False)

    vs = sub.add_parser("vcf-sort", help="sort a VCF/BCF by (contig, pos) "
                                         "(external spill-merge)")
    vs.add_argument("input")
    vs.add_argument("output")
    vs.add_argument("--run-records", type=int, default=1_000_000)
    vs.add_argument("--compress-level", type=int, default=None,
                    metavar="0-9",
                    help="BGZF deflate level for compressed output")
    vs.add_argument("--no-write-index", action="store_true",
                    help="skip the .tbi sidecar co-written with sorted "
                         "BCF output")
    vs.set_defaults(fn=cmd_vcf_sort, uses_device=False)
    return p


def _resilient_backend() -> None:
    """Survive a stale JAX_PLATFORMS pin.

    The environment may pin JAX_PLATFORMS to a plugin name (e.g. a
    tunneled-TPU plugin) that this process's plugin registration does
    not expose under that name — an intermittent race observed with the
    axon plugin, which sometimes registers as plain 'tpu'.  bench.py
    already probes around this; the CLI gets the cheap version: if the
    pinned platform cannot initialize, clear the pin and let jax choose
    (real TPU when registered, CPU otherwise) instead of crashing."""
    import os

    if not os.environ.get("JAX_PLATFORMS"):
        return
    try:
        import jax

        jax.devices()
    except RuntimeError:
        os.environ.pop("JAX_PLATFORMS", None)
        try:
            import jax

            jax.config.update("jax_platforms", None)
            jax.devices()
        except RuntimeError as e:
            print(f"warning: JAX backend init failed ({e}); downstream "
                  f"device steps will fail", file=sys.stderr)


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    # device verbs only: pure-IO verbs must not pay jax import/backend
    # init (or grab the accelerator) at startup
    if getattr(args, "uses_device", False) or getattr(args, "mesh", False):
        _resilient_backend()
    # one TraceContext per CLI invocation: the verb is an entry point,
    # and every span / journal line / flight-ring entry the verb
    # produces carries this trace id (obs/context.py)
    from hadoop_bam_tpu.obs.context import trace_context
    with trace_context(op=f"cli.{getattr(args, 'verb', '?')}"):
        try:
            return args.fn(args)
        except (ValueError, OSError) as e:
            # covers the classified taxonomy too: PlanError is a
            # ValueError, TransientIOError (shed load / blown deadline)
            # an OSError
            from hadoop_bam_tpu.obs import flight
            flight.recorder().dump(
                f"cli_error:{getattr(args, 'verb', '?')}", error=str(e))
            print(f"error: {e}", file=sys.stderr)
            return 1


if __name__ == "__main__":
    sys.exit(main())
