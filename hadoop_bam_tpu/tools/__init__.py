"""CLI tools — the rebuild of the reference command-line frontend.

Upstream <= 6.x shipped ``fi.tkk.ics.hadoop.bam.cli`` (Frontend + plugin
verbs: cat, index, sort, summarize, view, fixmate, vcf-sort — SURVEY.md
section 2.7; upstream 7.0.0 removed it).  We keep the verb set: each verb is
both a user tool and a benchmark driver for the decode pipeline.
"""
