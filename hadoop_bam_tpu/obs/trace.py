"""Structured span tracing: a bounded ring of begin/end events.

The reference exposed only Hadoop task counters and stderr warnings
(PAPER.md section 5); ``utils/metrics.py`` rebuilt the counters.  This
module adds the missing half — WHERE the time went, per thread, as
spans: every pipeline stage (plan / fetch / inflate / host_decode /
staging pack / dispatch / kernel / combine, and the query engine's
resolve / fetch / filter) records a ``(name, t0, dur, thread, args)``
event through ``Metrics.span``, and the whole run exports as ONE
Chrome trace-event JSON file loadable in ``chrome://tracing`` /
Perfetto — pool threads, the staging packer and the dispatch thread
side by side on a real timeline, which is the waterfall view the
rapidgzip and SAGe papers (PAPERS.md) credit their pipeline wins to.

Design constraints, in order:

- **Disabled is (near) free.**  Tracing is off by default; the only
  always-on cost is one module-global read per span.  The bench's
  ``obs_overhead_pct`` row pins the whole instrumentation layer (spans
  + histograms, tracing disabled) under 2% of flagstat throughput.
- **Enabled is bounded.**  Events land in a preallocated ring of
  ``capacity`` slots (config ``trace_ring_events``); once full, the
  OLDEST events are overwritten and ``dropped`` counts them — an
  always-on recorder can never grow without bound.
- **Thread-safe by construction.**  One lock per recorded event; the
  event payload is a plain tuple built outside the lock.

``jax.profiler`` interop: when tracing is enabled and jax is already
imported, spans are ALSO wrapped in ``jax.profiler.TraceAnnotation``
so they show up inside TPU profiler traces; when jax is absent or not
yet imported, spans degrade to ring events alone (no import is ever
triggered from the hot path).
"""
from __future__ import annotations

import json
import os
import threading
import time
from typing import Dict, List, Optional, Tuple

# event tuple layout: (name, ts_s, dur_s, tid, thread_name, args_or_None)
_Event = Tuple[str, float, float, int, str, Optional[dict]]


class TraceRecorder:
    """Bounded ring buffer of completed spans + instant events."""

    def __init__(self, capacity: int = 65536):
        self.capacity = max(16, int(capacity))
        self._buf: List[Optional[_Event]] = [None] * self.capacity
        self._next = 0          # monotonically increasing write cursor
        self.dropped = 0        # events overwritten after the ring filled
        self._lock = threading.Lock()
        self._t0 = time.perf_counter()   # trace epoch (ts are relative)
        # TraceAnnotation class, resolved once at enable time iff jax is
        # already imported — never triggers a jax import itself
        self._annotation = _resolve_jax_annotation()

    # -- recording -----------------------------------------------------------

    def complete(self, name: str, t0: float, dur: float,
                 args: Optional[dict] = None) -> None:
        """Record one finished span (perf_counter begin + duration)."""
        t = threading.current_thread()
        ev = (name, t0 - self._t0, dur, t.ident or 0, t.name, args)
        with self._lock:
            i = self._next
            if i >= self.capacity:   # overwriting the oldest event
                self.dropped += 1
            self._buf[i % self.capacity] = ev
            self._next = i + 1

    def instant(self, name: str, args: Optional[dict] = None) -> None:
        """Record a zero-duration marker event."""
        self.complete(name, time.perf_counter(), 0.0, args)

    def annotation(self, name: str):
        """A ``jax.profiler.TraceAnnotation`` for ``name`` when jax is
        importable and already imported; None otherwise."""
        return self._annotation(name) if self._annotation else None

    # -- export --------------------------------------------------------------

    def events(self) -> List[_Event]:
        """Events in record order (oldest surviving first)."""
        with self._lock:
            n, cap = self._next, self.capacity
            if n <= cap:
                return [e for e in self._buf[:n] if e is not None]
            start = n % cap
            return [e for e in self._buf[start:] + self._buf[:start]
                    if e is not None]

    def chrome_trace(self, process_label: Optional[str] = None,
                     process_index: int = 0) -> Dict[str, object]:
        """The trace as a Chrome trace-event JSON document
        (https://docs.google.com/document/d/1CvAClvFfyA5R-PhYUmn5OOQtYMH4h6I0nSsKchNAySU):
        ``ph: "X"`` complete events with microsecond timestamps, plus
        metadata events naming the process and each thread.  Loadable
        directly in ``chrome://tracing`` and Perfetto."""
        pid = int(process_index)
        events: List[dict] = [{
            "ph": "M", "pid": pid, "tid": 0, "name": "process_name",
            "args": {"name": process_label
                     or f"hbam host {pid} (pid {os.getpid()})"},
        }]
        seen_tids = {}
        for name, ts, dur, tid, tname, args in self.events():
            if tid not in seen_tids:
                seen_tids[tid] = tname
                events.append({"ph": "M", "pid": pid, "tid": tid,
                               "name": "thread_name",
                               "args": {"name": tname}})
            ev = {"ph": "X", "pid": pid, "tid": tid, "name": name,
                  "ts": round(ts * 1e6, 3), "dur": round(dur * 1e6, 3),
                  "cat": name.split(".", 1)[0]}
            if args:
                ev["args"] = args
            events.append(ev)
        doc: Dict[str, object] = {"traceEvents": events,
                                  "displayTimeUnit": "ms"}
        if self.dropped:
            doc["otherData"] = {"dropped_events": self.dropped}
        return doc

    def save(self, path: str, process_label: Optional[str] = None,
             process_index: int = 0) -> str:
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(self.chrome_trace(process_label, process_index), f)
        os.replace(tmp, path)
        return path


def _resolve_jax_annotation():
    """jax.profiler.TraceAnnotation iff jax is ALREADY imported (a
    minimal install without jax, or a pure-IO CLI verb that never
    touched jax, must not pay the import here)."""
    import sys
    if "jax" not in sys.modules:
        return None
    try:
        from jax.profiler import TraceAnnotation
        return TraceAnnotation
    except Exception:  # noqa: BLE001 — tracing must never break a run
        return None


# ---------------------------------------------------------------------------
# the process-wide active recorder (None = tracing disabled, the default)
# ---------------------------------------------------------------------------

_ACTIVE: Optional[TraceRecorder] = None
_ACTIVE_LOCK = threading.Lock()


def enable_tracing(capacity: Optional[int] = None) -> TraceRecorder:
    """Install (and return) the process-wide recorder.  Idempotent: an
    already-active recorder is returned unchanged unless ``capacity``
    asks for a different ring size."""
    global _ACTIVE
    with _ACTIVE_LOCK:
        if _ACTIVE is None or (capacity is not None
                               and _ACTIVE.capacity != int(capacity)):
            _ACTIVE = TraceRecorder(capacity or 65536)
        return _ACTIVE


def disable_tracing() -> Optional[TraceRecorder]:
    """Uninstall and return the recorder (so a caller can still export)."""
    global _ACTIVE
    with _ACTIVE_LOCK:
        rec, _ACTIVE = _ACTIVE, None
        return rec


def install_recorder(rec: Optional[TraceRecorder]
                     ) -> Optional[TraceRecorder]:
    """Swap the active recorder in (None = disable), returning the
    previous one — the suspend/resume primitive for code that must not
    pollute a live trace (the bench's overhead row measures the
    tracing-DISABLED cost and would otherwise wrap the ring with its
    own 12 flagstat runs)."""
    global _ACTIVE
    with _ACTIVE_LOCK:
        prev, _ACTIVE = _ACTIVE, rec
        return prev


def active_recorder() -> Optional[TraceRecorder]:
    """The hot-path read ``Metrics.span`` does per span: one global."""
    return _ACTIVE
