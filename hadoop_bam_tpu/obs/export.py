"""Metric exporters: Prometheus text exposition + snapshot JSON files.

Two consumer shapes (the reference had neither — Hadoop counters died
with the job):

- **Prometheus text exposition** (``prometheus_text``): counters as
  ``_total`` counters, timers as seconds+calls counter pairs, wall
  spans as gauges, histograms as native Prometheus histograms with
  cumulative ``le`` buckets derived from the log-bucket grid — a
  ``hbam serve`` scrape endpoint (ROADMAP item 2) can return this
  string verbatim.
- **Snapshot JSON** (``save_metrics_json`` / ``load_metrics_json``):
  the full mergeable ``Metrics.to_dict`` state on disk, so a run's
  numbers survive the process and ``hbam metrics FILE`` can re-render
  or re-export them later (and snapshots from several hosts/runs merge
  with ``Metrics.merge_dict`` — bucket merge is associative).
"""
from __future__ import annotations

import json
import os
import re
from typing import Dict, Optional

from hadoop_bam_tpu.obs.hist import Histogram

_NAME_RE = re.compile(r"[^a-zA-Z0-9_]")


def _prom_name(prefix: str, name: str, suffix: str = "") -> str:
    return f"{prefix}_{_NAME_RE.sub('_', name)}{suffix}"


def _fmt(v: float) -> str:
    return repr(round(float(v), 9))


def prometheus_text(metrics_or_dict, prefix: str = "hbam",
                    labels: Optional[Dict[str, str]] = None) -> str:
    """Render a ``Metrics`` instance (or its ``to_dict`` payload) in the
    Prometheus text exposition format (version 0.0.4)."""
    d = metrics_or_dict if isinstance(metrics_or_dict, dict) \
        else metrics_or_dict.to_dict()
    lab = ""
    if labels:
        lab = "{" + ",".join(f'{k}="{v}"'
                             for k, v in sorted(labels.items())) + "}"
    lines = []
    for k in sorted(d.get("counters", {})):
        n = _prom_name(prefix, k, "_total")
        lines += [f"# TYPE {n} counter",
                  f"{n}{lab} {int(d['counters'][k])}"]
    timer_calls = d.get("timer_calls", {})
    for k in sorted(d.get("timers", {})):
        n = _prom_name(prefix, k, "_seconds_total")
        lines += [f"# TYPE {n} counter",
                  f"{n}{lab} {_fmt(d['timers'][k])}"]
        c = _prom_name(prefix, k, "_calls_total")
        lines += [f"# TYPE {c} counter",
                  f"{c}{lab} {int(timer_calls.get(k, 0))}"]
    for k in sorted(d.get("wall_timers", {})):
        n = _prom_name(prefix, k, "_seconds")
        lines += [f"# TYPE {n} gauge",
                  f"{n}{lab} {_fmt(d['wall_timers'][k])}"]
    for k in sorted(d.get("histograms", {})):
        h = d["histograms"][k]
        if not isinstance(h, dict) or "buckets" not in h:
            continue           # a summary snapshot, not mergeable state
        n = _prom_name(prefix, k)
        lines.append(f"# TYPE {n} histogram")
        cum = 0
        for idx in sorted(int(i) for i in h["buckets"]):
            cum += int(h["buckets"][str(idx)])
            _, upper = Histogram.bucket_bounds(idx)
            le = f'le="{_fmt(upper)}"'
            sep = "," if labels else ""
            inner = (lab[1:-1] + sep + le) if labels else le
            lines.append(f"{n}_bucket{{{inner}}} {cum}")
        inf = 'le="+Inf"'
        inner = (lab[1:-1] + "," + inf) if labels else inf
        lines.append(f"{n}_bucket{{{inner}}} {int(h.get('count', cum))}")
        lines.append(f"{n}_sum{lab} {_fmt(h.get('total', 0.0))}")
        lines.append(f"{n}_count{lab} {int(h.get('count', cum))}")
    return "\n".join(lines) + "\n"


def save_metrics_json(metrics_or_dict, path: str) -> str:
    """Write the full mergeable snapshot (``Metrics.to_dict``) to disk."""
    d = metrics_or_dict if isinstance(metrics_or_dict, dict) \
        else metrics_or_dict.to_dict()
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(d, f, indent=1, sort_keys=True)
        f.write("\n")
    os.replace(tmp, path)
    return path


def load_metrics_json(path: str) -> Dict[str, object]:
    with open(path, "r", encoding="utf-8") as f:
        return json.load(f)


def render_metrics(d: Dict[str, object]) -> str:
    """Human-readable text of a snapshot dict (``Metrics.render``)."""
    from hadoop_bam_tpu.utils.metrics import Metrics
    return Metrics.from_dict(d).render()
