"""Declarative SLOs with multi-window burn-rate accounting.

A deadline knob says what ONE request was promised; an SLO says what
the SERVICE promised over time — "99% of requests under 250ms" — and
the operationally useful signal is the BURN RATE: how fast the error
budget (the allowed 1%) is being spent.  One rate over one window is
either too twitchy (pages on a blip) or too slow (a real regression
burns for an hour unseen); the standard fix is multi-window alerting —
a short "fast" window that reacts in minutes paired with a long "slow"
window that confirms sustained burn — and that is what this module
computes, fed entirely from the log-bucketed histograms and counters
the obs layer already records (``obs/hist.py``; no second measurement
path).

Mechanics: the engine periodically snapshots each objective's
(total, bad) event totals — for a latency objective, "bad" is the
histogram mass in buckets strictly above the threshold's bucket; for an
error-rate objective, a (bad counter, total counter) pair.  The burn
rate over a window is::

    burn = (bad_in_window / events_in_window) / (1 - target)

i.e. 1.0 means the budget is being spent exactly at the rate that
exhausts it by the period's end; 14.4 over a 5-minute window is the
classic "2% of a 30-day budget in one hour" page.  Windows with fewer
than ``min_events`` events report 0.0 — a cold tenant's first slow
request must not page anyone.

Consumers: Prometheus series (``prometheus_lines``: one
``hbam_slo_burn_rate{slo=...,window=...}`` gauge per objective/window),
the serve health document, ``hbam top``, and — closing the loop —
``serve/tenancy.py`` sheds BATCH-priority admissions for a tenant whose
fast window is burning (interactive traffic keeps flowing; backfill is
the load that can wait).

Clock is injectable (the ``utils/resilient.py`` convention) so tests
drive the regression-flips-fast-before-slow contract without real time.
"""
from __future__ import annotations

import dataclasses
import threading
import time
from collections import OrderedDict, deque
from typing import Callable, Dict, List, Optional, Tuple

from hadoop_bam_tpu.obs.hist import Histogram


@dataclasses.dataclass(frozen=True)
class SloObjective:
    """One declared objective.

    ``kind="latency"``: ``source`` names a Metrics histogram; an event
    is bad when it landed in a bucket strictly above ``threshold_s``'s.
    ``kind="errors"``: ``source`` names the TOTAL counter and
    ``bad_source`` the error counter.
    """

    name: str                        # "latency/<tenant>" etc.
    source: str                      # histogram or total-counter key
    target: float = 0.99             # promised good fraction
    kind: str = "latency"            # "latency" | "errors"
    threshold_s: float = 1.0         # latency objective bound
    bad_source: str = ""             # errors kind: the error counter


@dataclasses.dataclass(frozen=True)
class BurnWindow:
    label: str
    seconds: float
    threshold: float                 # burn rate at/above which it flips


# the classic fast/slow pairing: a fast page window and a slow
# confirmation window (thresholds from the 30d-budget alerting table)
DEFAULT_WINDOWS = (BurnWindow("fast", 300.0, 14.4),
                   BurnWindow("slow", 3600.0, 3.0))

_MAX_OBJECTIVES = 256            # LRU bound (arbitrary tenant strings)


class SloEngine:
    """Objectives + snapshot history + burn computation (module doc)."""

    def __init__(self, windows: Tuple[BurnWindow, ...] = DEFAULT_WINDOWS,
                 clock: Callable[[], float] = time.monotonic,
                 tick_s: float = 10.0, min_events: int = 64):
        self.windows = tuple(windows)
        self.tick_s = max(0.0, float(tick_s))
        self.min_events = max(1, int(min_events))
        self._clock = clock
        self._lock = threading.Lock()
        self._objectives: "OrderedDict[str, SloObjective]" = OrderedDict()
        # snapshot history: (t, {objective: (total, bad)}); bounded so a
        # long-lived server cannot grow it (the SV801 discipline) — the
        # slow window at the tick cadence needs far fewer than this
        self._snaps: deque = deque(maxlen=4096)
        self._last_tick: Optional[float] = None

    # -- objectives ----------------------------------------------------------

    def add(self, obj: SloObjective) -> SloObjective:
        """Install (or refresh) one objective; LRU-bounded so per-tenant
        objectives over arbitrary tenant strings cannot grow forever."""
        with self._lock:
            if obj.name in self._objectives:
                self._objectives.move_to_end(obj.name)
            else:
                while len(self._objectives) >= _MAX_OBJECTIVES:
                    self._objectives.popitem(last=False)
            self._objectives[obj.name] = obj
            return obj

    def ensure_latency(self, name: str, hist: str, threshold_s: float,
                       target: float) -> SloObjective:
        """Idempotent per-tenant install: an existing objective of this
        name is kept (and LRU-refreshed), not re-declared."""
        with self._lock:
            obj = self._objectives.get(name)
            if obj is not None:
                self._objectives.move_to_end(name)
                return obj
        return self.add(SloObjective(name=name, source=hist,
                                     threshold_s=float(threshold_s),
                                     target=float(target)))

    def objectives(self) -> List[SloObjective]:
        with self._lock:
            return list(self._objectives.values())

    # -- totals from the live metrics ----------------------------------------

    @staticmethod
    def _latency_totals(d: Dict, obj: SloObjective) -> Tuple[int, int]:
        h = dict(d.get("histograms", {})).get(obj.source)
        if not isinstance(h, dict) or "buckets" not in h:
            return 0, 0
        cutoff = Histogram.bucket_index(obj.threshold_s)
        total = 0
        bad = 0
        for idx, n in dict(h["buckets"]).items():
            total += int(n)
            if int(idx) > cutoff:
                bad += int(n)
        return total, bad

    def _totals(self, metrics_dict: Dict,
                objs: Optional[List[SloObjective]] = None
                ) -> Dict[str, Tuple[int, int]]:
        out: Dict[str, Tuple[int, int]] = {}
        counters = dict(metrics_dict.get("counters", {}))
        for obj in (self.objectives() if objs is None else objs):
            if obj.kind == "errors":
                out[obj.name] = (int(counters.get(obj.source, 0)),
                                 int(counters.get(obj.bad_source, 0)))
            else:
                out[obj.name] = self._latency_totals(metrics_dict, obj)
        return out

    @staticmethod
    def _metrics_dict(metrics=None,
                      objs: Optional[List[SloObjective]] = None) -> Dict:
        if isinstance(metrics, dict):
            return metrics
        if metrics is None:
            from hadoop_bam_tpu.utils.metrics import base_metrics
            metrics = base_metrics()
        if objs is not None and hasattr(metrics, "hist_dict"):
            # targeted extraction — the admission-path shape: copy only
            # the named objectives' sources instead of serializing the
            # whole instance (to_dict under the Metrics lock is O(all
            # keys) and would run per batch admission)
            counters: Dict[str, int] = {}
            hists: Dict[str, object] = {}
            for obj in objs:
                if obj.kind == "errors":
                    counters[obj.source] = metrics.get(obj.source)
                    counters[obj.bad_source] = metrics.get(
                        obj.bad_source)
                else:
                    hists[obj.source] = metrics.hist_dict(obj.source)
            return {"counters": counters, "histograms": hists}
        return metrics.to_dict()

    # -- ticking + burn ------------------------------------------------------

    def tick(self, metrics=None, now: Optional[float] = None,
             force: bool = False) -> bool:
        """Snapshot the objectives' totals (rate-limited to one per
        ``tick_s`` unless forced).  Callers sprinkle this on request
        completion paths — it is the whole scheduling model, no thread."""
        now = self._clock() if now is None else now
        with self._lock:
            if not force and self._last_tick is not None \
                    and now - self._last_tick < self.tick_s:
                return False
            self._last_tick = now
        objs = self.objectives()
        totals = self._totals(self._metrics_dict(metrics, objs), objs)
        with self._lock:
            self._snaps.append((now, totals))
        return True

    def _baseline(self, name: str, now: float, window_s: float
                  ) -> Optional[Tuple[int, int]]:
        """The snapshot totals at (or just before) the window start —
        newest snapshot old enough to cover the window; the oldest
        available when history is shorter than the window."""
        with self._lock:
            snaps = list(self._snaps)
        best = None
        for t, totals in snaps:
            if name not in totals:
                continue
            if t <= now - window_s:
                best = totals[name]       # newest one old enough wins
            elif best is None:
                return totals[name]       # history shorter than window
        return best

    def burn_rates(self, metrics=None, now: Optional[float] = None,
                   names: Optional[List[str]] = None
                   ) -> Dict[str, Dict[str, float]]:
        """{objective: {window_label: burn}} against the live totals.
        ``names`` restricts the computation (the admission-path shape:
        one tenant's objective, not every objective's histogram)."""
        now = self._clock() if now is None else now
        objs = self.objectives() if names is None else \
            [o for o in self.objectives() if o.name in set(names)]
        live = self._totals(self._metrics_dict(metrics, objs), objs)
        out: Dict[str, Dict[str, float]] = {}
        for obj in objs:
            total, bad = live.get(obj.name, (0, 0))
            budget = max(1e-9, 1.0 - float(obj.target))
            rates: Dict[str, float] = {}
            for w in self.windows:
                base = self._baseline(obj.name, now, w.seconds)
                b_total, b_bad = base if base is not None else (0, 0)
                d_total = total - b_total
                d_bad = bad - b_bad
                if d_total < self.min_events or d_total <= 0:
                    rates[w.label] = 0.0
                else:
                    rates[w.label] = round(
                        (d_bad / d_total) / budget, 4)
            out[obj.name] = rates
        return out

    def burning(self, name: str, metrics=None,
                now: Optional[float] = None) -> Optional[str]:
        """The label of the first window (fast first) whose burn rate
        is at/over its threshold for ``name``; None when healthy or the
        objective is unknown."""
        with self._lock:
            if name not in self._objectives:
                return None
        rates = self.burn_rates(metrics, now=now, names=[name]).get(name)
        if not rates:
            return None
        for w in self.windows:
            if rates.get(w.label, 0.0) >= w.threshold:
                return w.label
        return None

    # -- export --------------------------------------------------------------

    def prometheus_lines(self, metrics=None,
                         now: Optional[float] = None) -> List[str]:
        """``hbam_slo_burn_rate{slo="...",window="..."}`` gauge series
        (appended to the ``prometheus_text`` exposition by the serve
        metrics op and ``hbam top``)."""
        rates = self.burn_rates(metrics, now=now)
        if not rates:
            return []
        lines = ["# TYPE hbam_slo_burn_rate gauge"]
        for name in sorted(rates):
            for w in self.windows:
                lines.append(
                    f'hbam_slo_burn_rate{{slo="{name}",'
                    f'window="{w.label}"}} {rates[name][w.label]}')
        return lines

    def summary(self, metrics=None,
                now: Optional[float] = None) -> Dict[str, object]:
        """Health-surface view: burn rates plus which window (if any)
        is burning per objective."""
        rates = self.burn_rates(metrics, now=now)
        out: Dict[str, object] = {}
        for name, r in rates.items():
            burning = None
            for w in self.windows:
                if r.get(w.label, 0.0) >= w.threshold:
                    burning = w.label
                    break
            out[name] = {"burn": r, "burning": burning}
        return out
