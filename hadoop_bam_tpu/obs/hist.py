"""Log-bucketed, mergeable latency/size histograms with percentiles.

Flat counters and union-wall timers (utils/metrics.py) answer "how much
work" and "how long did the stage occupy the wall"; a serving system
also needs DISTRIBUTIONS — the p99 a deadline contract is written
against is invisible to both.  This histogram is built for exactly the
three properties the mesh needs:

- **log-bucketed**: bucket boundaries are powers of ``2**(1/4)``
  (~19% relative width), so nine decades of latency (ns to minutes) or
  size (bytes to TB) fit in a small sparse dict with bounded relative
  quantile error;
- **mergeable**: two histograms over the same bucket grid merge by
  bucket-count addition — associative and commutative, so per-host
  histograms allgather and merge into one mesh-wide distribution in any
  order (``tests/test_obs.py`` pins associativity);
- **cheap to record**: one ``math.frexp``-free log, one dict increment,
  no allocation on the hot path.

Quantiles are read as the geometric midpoint of the bucket holding the
rank, which bounds the error at half a bucket (~10%) — plenty for p50/
p95/p99 reporting, and exact min/max ride along for the tails.
"""
from __future__ import annotations

import math
from typing import Dict, Iterable, Optional

# 2**(1/4) bucket growth: index = round(4 * log2(value))
_LOG2_SCALE = 4.0
# values at or below this clamp into the bottom bucket (1 ns / 1 byte
# grain is far below anything the pipeline measures)
_MIN_VALUE = 1e-9


class Histogram:
    """Sparse log-bucketed histogram of positive values."""

    __slots__ = ("buckets", "count", "total", "min", "max")

    def __init__(self) -> None:
        self.buckets: Dict[int, int] = {}
        self.count = 0
        self.total = 0.0
        self.min: Optional[float] = None
        self.max: Optional[float] = None

    @staticmethod
    def bucket_index(value: float) -> int:
        v = max(float(value), _MIN_VALUE)
        return int(round(_LOG2_SCALE * math.log2(v)))

    @staticmethod
    def bucket_bounds(index: int) -> "tuple[float, float]":
        """(lower, upper) value bounds of one bucket index."""
        half = 0.5 / _LOG2_SCALE
        return (2.0 ** (index / _LOG2_SCALE - half),
                2.0 ** (index / _LOG2_SCALE + half))

    def record(self, value: float, n: int = 1) -> None:
        i = self.bucket_index(value)
        self.buckets[i] = self.buckets.get(i, 0) + n
        self.count += n
        self.total += float(value) * n
        v = float(value)
        self.min = v if self.min is None else min(self.min, v)
        self.max = v if self.max is None else max(self.max, v)

    # -- reading -------------------------------------------------------------

    def percentile(self, p: float) -> float:
        """Value at percentile ``p`` (0..100): the geometric midpoint of
        the bucket containing that rank; 0.0 on an empty histogram.  The
        exact observed min/max clamp the extremes so p0/p100 never report
        outside the recorded range."""
        if not self.count:
            return 0.0
        rank = max(1, math.ceil(self.count * min(max(p, 0.0), 100.0)
                                / 100.0))
        seen = 0
        for i in sorted(self.buckets):
            seen += self.buckets[i]
            if seen >= rank:
                mid = 2.0 ** (i / _LOG2_SCALE)
                lo = self.min if self.min is not None else mid
                hi = self.max if self.max is not None else mid
                return min(max(mid, lo), hi)
        return self.max or 0.0

    @property
    def mean(self) -> float:
        return self.total / self.count if self.count else 0.0

    def summary(self) -> Dict[str, float]:
        """The reporting tuple every consumer wants: count/mean/p50/p95/
        p99/max."""
        return {"count": self.count, "mean": self.mean,
                "p50": self.percentile(50), "p95": self.percentile(95),
                "p99": self.percentile(99), "max": self.max or 0.0}

    # -- merging / serialization --------------------------------------------

    def merge(self, other: "Histogram") -> "Histogram":
        """In-place bucket-count merge (associative + commutative — the
        property the mesh-wide allgather reduction depends on)."""
        for i, n in other.buckets.items():
            self.buckets[i] = self.buckets.get(i, 0) + n
        self.count += other.count
        self.total += other.total
        for attr, pick in (("min", min), ("max", max)):
            a, b = getattr(self, attr), getattr(other, attr)
            setattr(self, attr, b if a is None else
                    (a if b is None else pick(a, b)))
        return self

    @classmethod
    def merged(cls, parts: Iterable["Histogram"]) -> "Histogram":
        out = cls()
        for h in parts:
            out.merge(h)
        return out

    def to_dict(self) -> Dict[str, object]:
        return {"buckets": {str(i): n for i, n in
                            sorted(self.buckets.items())},
                "count": self.count, "total": self.total,
                "min": self.min, "max": self.max}

    @classmethod
    def from_dict(cls, d: Dict[str, object]) -> "Histogram":
        h = cls()
        h.buckets = {int(i): int(n)
                     for i, n in dict(d.get("buckets", {})).items()}
        h.count = int(d.get("count", 0))
        h.total = float(d.get("total", 0.0))
        h.min = None if d.get("min") is None else float(d["min"])
        h.max = None if d.get("max") is None else float(d["max"])
        return h
