"""TraceContext: the causal request identity every span and journal
line can carry.

The PR-6 obs layer answered "where did the wall go" per PROCESS; a
multi-tenant serve tier with breakers and shedding, journaled jobs, and
a cohort plane also needs "why was THIS request slow" — which requires
attributing spans to a request, not a process.  A ``TraceContext`` is
minted at every entry point (one serve transport line, one ``hbam`` CLI
verb, one job start, one query batch) and rides a ``contextvars``
variable, so every propagation seam the codebase already has — the
shared decode pool (``utils.pools.submit`` copies the submitter's
context), the staging packer thread, the serve dispatcher (jobs run
under the submitter's contextvars snapshot), prefetch background tasks
— carries it for free:

- ``Metrics.span`` stamps the trace id (and, when tracing is enabled,
  a span id + parent span id) onto every trace-ring event, so the
  Chrome-trace export reconstructs ONE causally-linked tree per request
  across threads;
- the flight recorder (``obs/flight.py``) records the trace id on every
  span completion, so a breaker-trip dump names the request that
  tripped it;
- ``jobs.JobJournal`` stamps the trace id on every journal line, so
  ``hbam jobs --json`` reports which invocation wrote a journal.

Minting is cheap (8 random bytes + one contextvar set) and therefore
UNCONDITIONAL at entry points — a trace id exists whether or not the
trace ring is recording.  Span ids are only allocated while tracing is
enabled (``obs.trace.enable_tracing``), keeping the disabled span path
near-free (the ``obs_overhead_pct`` bench bar).
"""
from __future__ import annotations

import contextlib
import contextvars
import dataclasses
import itertools
import os
from typing import Iterator, Optional, Tuple

# root span id of a freshly-minted trace: events whose parent is
# _ROOT_SPAN are the top of the request's tree
_ROOT_SPAN = 0


@dataclasses.dataclass(frozen=True)
class TraceContext:
    """One request/job identity: immutable, cheap to fork per span."""

    trace_id: str                       # 16 hex chars, process-unique++
    span_id: int = _ROOT_SPAN           # innermost ACTIVE span's id
    op: str = ""                        # entry point ("serve.request",
    #                                     "cli.sort", "job.cohort_join")
    tenant: Optional[str] = None
    deadline_s: Optional[float] = None


_CURRENT: "contextvars.ContextVar[Optional[TraceContext]]" = \
    contextvars.ContextVar("hbam_trace_ctx", default=None)

# span ids are process-wide (CPython's itertools.count.__next__ is
# atomic — the same idiom ServeLoop uses for its dispatch sequence)
_SPAN_IDS = itertools.count(1)


def new_trace_id() -> str:
    return os.urandom(8).hex()


# the fleet identity of THIS process (serve/fleet.py sets it once at
# replica construction): stamped on every span event so a Chrome-trace
# or flight dump assembled from N replicas attributes each span to the
# process that did the work.  None outside a fleet — spans stay as they
# were, zero overhead beyond one global read.
_REPLICA_ID: Optional[str] = None


def set_replica_id(replica_id: Optional[str]) -> None:
    global _REPLICA_ID
    _REPLICA_ID = str(replica_id) if replica_id is not None else None


def replica_id() -> Optional[str]:
    return _REPLICA_ID


def current_trace() -> Optional[TraceContext]:
    """The active TraceContext, or None outside any entry point."""
    return _CURRENT.get()


def current_trace_id() -> Optional[str]:
    ctx = _CURRENT.get()
    return ctx.trace_id if ctx is not None else None


@contextlib.contextmanager
def trace_context(op: str = "", tenant: Optional[str] = None,
                  deadline_s: Optional[float] = None,
                  trace_id: Optional[str] = None
                  ) -> Iterator[TraceContext]:
    """Mint a NEW root TraceContext for the block — the entry-point
    primitive.  Pass ``trace_id`` to adopt a caller-supplied id (a
    client header, a journal's recorded trace)."""
    ctx = TraceContext(trace_id=trace_id or new_trace_id(), op=op,
                       tenant=tenant, deadline_s=deadline_s)
    tok = _CURRENT.set(ctx)
    try:
        yield ctx
    finally:
        _CURRENT.reset(tok)


@contextlib.contextmanager
def ensure_trace(op: str = "", tenant: Optional[str] = None,
                 deadline_s: Optional[float] = None
                 ) -> Iterator[TraceContext]:
    """Library entry points use this instead of ``trace_context``: when
    an outer entry point (a CLI verb, a transport line) already minted a
    trace, join it; otherwise mint one — so a direct library caller
    still gets end-to-end ids without double-minting under the CLI."""
    cur = _CURRENT.get()
    if cur is not None:
        yield cur
        return
    with trace_context(op=op, tenant=tenant,
                       deadline_s=deadline_s) as ctx:
        yield ctx


def begin_span() -> Optional[Tuple["contextvars.Token", str, int, int]]:
    """Allocate a child span under the current trace and make it the
    active parent: returns ``(reset_token, trace_id, span_id,
    parent_span_id)``, or None when no trace is active.  Only called
    while tracing is ENABLED (``Metrics.span``); the token must be
    handed back to ``end_span`` in the same context."""
    cur = _CURRENT.get()
    if cur is None:
        return None
    sid = next(_SPAN_IDS)
    tok = _CURRENT.set(dataclasses.replace(cur, span_id=sid))
    return tok, cur.trace_id, sid, cur.span_id


def end_span(token: "contextvars.Token") -> None:
    _CURRENT.reset(token)
