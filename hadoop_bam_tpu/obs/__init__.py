"""hbam-trace: the observability layer — spans, histograms, exporters.

Three pieces, threaded through every pipeline stage via
``utils/metrics.py``:

- ``trace.py``   ring-buffer-bounded structured span recording with
  Chrome trace-event JSON export (``chrome://tracing`` / Perfetto) and
  ``jax.profiler`` layering — ``enable_tracing()`` turns it on,
  ``Metrics.span`` feeds it;
- ``hist.py``    log-bucketed mergeable latency/size histograms with
  p50/p95/p99 extraction — ``Metrics.observe`` feeds them, and their
  bucket merge is associative so per-host histograms allgather into
  one mesh-wide distribution (``parallel/distributed.merge_metrics``);
- ``export.py``  Prometheus text exposition + snapshot JSON files —
  the ``hbam metrics`` CLI surface.

The causal/ops additions (PR 14):

- ``context.py`` ``TraceContext`` — a request/job identity minted at
  every entry point and propagated across the pool, packer, dispatcher
  and prefetch seams via contextvars; spans and journal lines carry
  its trace_id;
- ``flight.py``  always-on bounded flight recorder — recent span
  completions + breaker/ladder transitions, auto-dumped (redacted,
  rotation-capped) on breaker trips, demotions, deadline misses and
  unhandled serve errors;
- ``slo.py``     declarative latency/error-rate SLOs with multi-window
  burn-rate accounting fed from the log-bucketed histograms, exported
  as Prometheus gauges and consulted by serve admission.

Run-scoped isolation lives in ``utils.metrics.MetricsContext`` (the
contextvar-scoped instance the ``METRICS`` proxy resolves to).
"""
from hadoop_bam_tpu.obs.hist import Histogram  # noqa: F401
from hadoop_bam_tpu.obs.trace import (  # noqa: F401
    TraceRecorder, active_recorder, disable_tracing, enable_tracing,
    install_recorder,
)
from hadoop_bam_tpu.obs.export import (  # noqa: F401
    load_metrics_json, prometheus_text, render_metrics, save_metrics_json,
)
from hadoop_bam_tpu.obs.context import (  # noqa: F401
    TraceContext, current_trace, current_trace_id, ensure_trace,
    new_trace_id, trace_context,
)
from hadoop_bam_tpu.obs.slo import (  # noqa: F401
    BurnWindow, SloEngine, SloObjective,
)
from hadoop_bam_tpu.obs import flight  # noqa: F401
