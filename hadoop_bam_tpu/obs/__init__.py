"""hbam-trace: the observability layer — spans, histograms, exporters.

Three pieces, threaded through every pipeline stage via
``utils/metrics.py``:

- ``trace.py``   ring-buffer-bounded structured span recording with
  Chrome trace-event JSON export (``chrome://tracing`` / Perfetto) and
  ``jax.profiler`` layering — ``enable_tracing()`` turns it on,
  ``Metrics.span`` feeds it;
- ``hist.py``    log-bucketed mergeable latency/size histograms with
  p50/p95/p99 extraction — ``Metrics.observe`` feeds them, and their
  bucket merge is associative so per-host histograms allgather into
  one mesh-wide distribution (``parallel/distributed.merge_metrics``);
- ``export.py``  Prometheus text exposition + snapshot JSON files —
  the ``hbam metrics`` CLI surface.

Run-scoped isolation lives in ``utils.metrics.MetricsContext`` (the
contextvar-scoped instance the ``METRICS`` proxy resolves to).
"""
from hadoop_bam_tpu.obs.hist import Histogram  # noqa: F401
from hadoop_bam_tpu.obs.trace import (  # noqa: F401
    TraceRecorder, active_recorder, disable_tracing, enable_tracing,
    install_recorder,
)
from hadoop_bam_tpu.obs.export import (  # noqa: F401
    load_metrics_json, prometheus_text, render_metrics, save_metrics_json,
)
