"""Flight recorder: an always-on bounded ring of what JUST happened.

The trace ring (``obs/trace.py``) is opt-in and sized for whole-run
export; production incidents need the opposite shape — a small,
ALWAYS-recording ring whose contents are dumped automatically at the
moment something goes wrong, like an aircraft FDR.  The recorder keeps:

- the last N span completions (name, duration, thread, trace_id,
  trimmed args) fed by ``Metrics.span`` / ``Metrics.add_wall``;
- the last M policy transitions (breaker state flips, decode-plane
  demotions, deadline misses) fed by ``resilience/`` and the query
  scheduler;
- counter snapshots, delta'd against the previous dump, so a dump shows
  what moved since the system was last healthy.

Dumps trigger automatically on: ``CircuitBreaker`` OPEN (including the
quarantine circuit's force-open), a decode-plane demotion, a deadline
miss, and an unhandled serve/CLI error.  They land as redacted JSON in
a rotation-capped directory (config ``flight_dump_dir`` — None keeps
the ring memory-only, which is the default outside ``hbam serve``), and
the latest ring state is also attached to the serve transport's
``{"op": "health"}`` document, so a degraded server hands its recent
history to whoever asks.

Redaction: arg values are stringified and truncated, and values of
keys that look like credentials are dropped — dumps are written for
operators and may leave the machine.

Cost discipline: recording is one ``deque.append`` of a prebuilt tuple
(``maxlen`` deques drop the oldest atomically; no lock on the record
path), so the always-on ring stays inside the ``obs_overhead_pct``
bench bar.  All dump I/O failures are swallowed — the recorder must
never turn an incident into a second incident.
"""
from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Dict, Optional

from hadoop_bam_tpu.obs.context import current_trace_id

_SECRET_MARKERS = ("secret", "token", "password", "credential", "apikey")
_REDACT_MAX_STR = 160

# span entry: (wall_ts, name, dur_s, thread_name, trace_id, args_or_None)
# transition entry: (wall_ts, kind, name, state, trace_id)


def redact_value(v) -> object:
    """Dump-safe rendering of one arg value: scalars pass through,
    everything else is stringified and truncated."""
    if isinstance(v, (int, float, bool)) or v is None:
        return v
    s = v if isinstance(v, str) else repr(v)
    if len(s) > _REDACT_MAX_STR:
        s = s[:_REDACT_MAX_STR] + f"...(+{len(s) - _REDACT_MAX_STR})"
    return s


def redact_args(args: Optional[dict]) -> Optional[dict]:
    if not args:
        return None
    out = {}
    for k, v in args.items():
        ks = str(k)
        if any(m in ks.lower() for m in _SECRET_MARKERS):
            out[ks] = "[redacted]"
        else:
            out[ks] = redact_value(v)
    return out


class FlightRecorder:
    """The bounded always-on ring (module docstring)."""

    def __init__(self, capacity: int = 512, transitions: int = 128):
        self._spans: deque = deque(maxlen=max(16, int(capacity)))
        self._transitions: deque = deque(maxlen=max(16, int(transitions)))
        self._lock = threading.Lock()         # dump/configure only
        self._dump_dir: Optional[str] = None
        self._dump_cap = 16
        self._last_counters: Dict[str, int] = {}
        self.dumps_written = 0
        self.dump_errors = 0
        self.last_dump_path: Optional[str] = None

    # -- recording (lock-free hot path) --------------------------------------

    def record_span(self, name: str, dur: float,
                    args: Optional[dict] = None,
                    trace_id: Optional[str] = None) -> None:
        if trace_id is None:
            trace_id = current_trace_id()
        self._spans.append((time.time(), name, dur,
                            threading.current_thread().name, trace_id,
                            args))

    def record_transition(self, kind: str, name: str, state: str,
                          trace_id: Optional[str] = None) -> None:
        if trace_id is None:
            trace_id = current_trace_id()
        self._transitions.append((time.time(), kind, name, state,
                                  trace_id))

    # -- configuration --------------------------------------------------------

    def configure(self, dump_dir: Optional[str] = "__keep__",
                  dump_cap: Optional[int] = None) -> None:
        """Set the dump directory (None disables disk dumps) and/or the
        rotation cap.  Called by ``hbam serve`` startup from config; the
        sentinel default leaves the directory unchanged."""
        with self._lock:
            if dump_dir != "__keep__":
                self._dump_dir = dump_dir
            if dump_cap is not None:
                self._dump_cap = max(1, int(dump_cap))

    @property
    def dump_dir(self) -> Optional[str]:
        return self._dump_dir

    # -- reading / dumping ----------------------------------------------------

    def snapshot(self, reason: str = "",
                 error: Optional[str] = None) -> Dict[str, object]:
        """The redacted ring state as one JSON-able document.  Counters
        come from the PROCESS-GLOBAL metrics, not the current context:
        incident dumps fire on serving threads that may be running under
        a client's isolated MetricsContext, and the ops question is
        "what moved in the process", not in one request's view."""
        from hadoop_bam_tpu.utils.metrics import base_metrics

        spans = [{"ts": round(ts, 6), "name": n, "dur_s": round(d, 6),
                  "thread": t, "trace": tid,
                  "args": redact_args(a)}
                 for ts, n, d, t, tid, a in list(self._spans)]
        transitions = [{"ts": round(ts, 6), "kind": k, "name": n,
                        "state": s, "trace": tid}
                       for ts, k, n, s, tid in list(self._transitions)]
        counters = dict(base_metrics().snapshot()["counters"])
        with self._lock:
            delta = {k: v - self._last_counters.get(k, 0)
                     for k, v in counters.items()
                     if v != self._last_counters.get(k, 0)}
        doc: Dict[str, object] = {
            "reason": reason,
            "ts": round(time.time(), 6),
            "trace": current_trace_id(),
            "transitions": transitions,
            "spans": spans,
            "counters": counters,
            "counters_delta_since_last_dump": delta,
        }
        if error is not None:
            doc["error"] = redact_value(error)
        return doc

    def stats(self) -> Dict[str, object]:
        """The health-surface summary (cheap; no span payloads)."""
        recent = [{"kind": k, "name": n, "state": s, "trace": tid}
                  for _ts, k, n, s, tid in list(self._transitions)[-8:]]
        return {"spans_buffered": len(self._spans),
                "transitions_buffered": len(self._transitions),
                "dumps_written": self.dumps_written,
                "last_dump": self.last_dump_path,
                "recent_transitions": recent}

    def dump(self, reason: str,
             error: Optional[str] = None) -> Optional[str]:
        """Write one snapshot to the dump directory (rotation-capped);
        returns the path, or None when disk dumping is disabled.  Never
        raises — an incident dump must not become a second incident."""
        if self._dump_dir is None:
            return None
        try:
            doc = self.snapshot(reason=reason, error=error)
            with self._lock:
                os.makedirs(self._dump_dir, exist_ok=True)
                name = (f"flight-{int(time.time() * 1000):013d}-"
                        f"{self.dumps_written:04d}-"
                        f"{_safe_reason(reason)}.json")
                path = os.path.join(self._dump_dir, name)
                tmp = path + ".tmp"
                with open(tmp, "w", encoding="utf-8") as f:
                    json.dump(doc, f, indent=1, sort_keys=True)
                os.replace(tmp, path)
                self.dumps_written += 1
                self.last_dump_path = path
                self._last_counters = dict(doc["counters"])
                self._rotate_locked()
        except Exception:  # noqa: BLE001 — never break the caller
            self.dump_errors += 1
            return None
        from hadoop_bam_tpu.utils.metrics import METRICS
        METRICS.count("obs.flight_dumps")
        return path

    def _rotate_locked(self) -> None:
        """Keep at most ``_dump_cap`` dump files (oldest removed first;
        the sortable name encodes the write time)."""
        try:
            names = sorted(n for n in os.listdir(self._dump_dir)
                           if n.startswith("flight-")
                           and n.endswith(".json"))
        except OSError:
            return
        for name in names[:max(0, len(names) - self._dump_cap)]:
            try:
                os.unlink(os.path.join(self._dump_dir, name))
            except OSError:
                pass


def _safe_reason(reason: str) -> str:
    return "".join(c if c.isalnum() or c in "-_" else "_"
                   for c in str(reason))[:48] or "dump"


_RECORDER = FlightRecorder()


def recorder() -> FlightRecorder:
    """The process-wide flight recorder (always recording)."""
    return _RECORDER


def reset(capacity: int = 512, transitions: int = 128) -> FlightRecorder:
    """Install a pristine recorder (tests): fresh rings, disk dumps off."""
    global _RECORDER
    _RECORDER = FlightRecorder(capacity=capacity, transitions=transitions)
    return _RECORDER
