"""Typed configuration — the rebuild of Hadoop-BAM's string-keyed Configuration.

The reference's entire "flag system" is Hadoop ``Configuration`` string keys
scattered over the classes that consume them (SURVEY.md section 5):

- ``hadoopbam.anysam.trust-exts``              (hb/AnySAMInputFormat.java)
- ``hadoopbam.vcf.trust-exts``                 (hb/VCFInputFormat.java)
- ``hadoopbam.samheaderreader.validation-stringency`` (hb/util/SAMHeaderReader.java)
- ``hadoopbam.cram.reference-source-path``     (hb/CRAMInputFormat.java)
- ``hadoopbam.vcf.output-format``              (hb/VCFOutputFormat.java)
- ``hbam.fastq-input.base-quality-encoding``, ``...filter-failed-qc``
                                               (hb/FormatConstants.java)
- ``hadoopbam.bam.intervals``                  (hb/BAMInputFormat.java, 7.7+)

Here they become one typed dataclass with the same semantic knobs, plus the
TPU-specific knobs (backend selection, mesh shape, batch geometry).  A
``from_dict`` constructor accepts the reference's string keys verbatim so
Hadoop-BAM users can carry configs over unchanged.
"""
from __future__ import annotations

import dataclasses
import enum
from typing import Mapping, Optional, Sequence, Tuple


class ValidationStringency(enum.Enum):
    """Mirror of htsjdk ValidationStringency as consumed by
    hb/util/SAMHeaderReader.java: governs malformed-record handling."""

    STRICT = "STRICT"     # raise on malformed records
    LENIENT = "LENIENT"   # warn and skip
    SILENT = "SILENT"     # skip silently

    @classmethod
    def parse(cls, s: "str | ValidationStringency | None") -> "ValidationStringency":
        if s is None:
            return cls.SILENT
        if isinstance(s, cls):
            return s
        return cls[str(s).upper()]


class BaseQualityEncoding(enum.Enum):
    """FASTQ/QSEQ base-quality encodings (hb/FormatConstants.java).
    Offsets are [SPEC]: Sanger = Phred+33, Illumina(1.3-1.7) = Phred+64."""

    SANGER = 33
    ILLUMINA = 64

    @classmethod
    def parse(cls, s: "str | BaseQualityEncoding | None", default: "BaseQualityEncoding"):
        if s is None:
            return default
        if isinstance(s, cls):
            return s
        return cls[str(s).upper()]


# Mapping from the reference's Hadoop Configuration keys to dataclass fields.
_HADOOP_KEY_MAP = {
    "hadoopbam.anysam.trust-exts": "trust_exts",
    "hadoopbam.vcf.trust-exts": "vcf_trust_exts",
    "hadoopbam.samheaderreader.validation-stringency": "validation_stringency",
    "hadoopbam.cram.reference-source-path": "cram_reference_source_path",
    "hadoopbam.vcf.output-format": "vcf_output_format",
    "hadoopbam.bam.intervals": "bam_intervals",
    "hadoopbam.bam.keep-paired-reads-together": "keep_paired_reads_together",
    "hbam.fastq-input.base-quality-encoding": "fastq_base_quality_encoding",
    "hbam.fastq-input.filter-failed-qc": "fastq_filter_failed_qc",
    "hbam.qseq-input.base-quality-encoding": "qseq_base_quality_encoding",
    "hbam.qseq-input.filter-failed-qc": "qseq_filter_failed_qc",
    "hadoop-bam.backend": "backend",
    # failure-policy knobs (no reference analog: Hadoop relied on
    # mapreduce.map.maxattempts; these are the span-grain equivalents)
    "hbam.span-retries": "span_retries",
    "hbam.skip-bad-spans": "skip_bad_spans",
    "hbam.max-bad-span-fraction": "max_bad_span_fraction",
    "hbam.debug-keep-spill": "debug_keep_spill",
    # host->device feed knobs (parallel/staging.py; no reference analog —
    # Hadoop's record-ahead buffering was not configurable)
    "hbam.feed-ring-slots": "feed_ring_slots",
    "hbam.feed-dispatch-depth": "feed_dispatch_depth",
    "hbam.decode-pool-workers": "decode_pool_workers",
    # fused host decode knobs (ops/inflate.py FusedSpanDecode; the
    # reference's analog was per-block zlib-over-JNI with no fusion)
    "hbam.use-fused-decode": "use_fused_decode",
    "hbam.decode-chunk-blocks": "decode_chunk_blocks",
    # decode-plane selection (ops/inflate_device.py + the pipeline
    # token-feed path; no reference analog — the JNI inflate had exactly
    # one implementation)
    "hbam.inflate-backend": "inflate_backend",
    # region-query serving knobs (query/; no reference analog — Hadoop-BAM
    # only ever trimmed scan plans with intervals, it never served them)
    "hbam.query-cache-bytes": "query_cache_bytes",
    "hbam.query-chunk-bytes": "query_chunk_bytes",
    "hbam.query-tile-records": "query_tile_records",
    "hbam.query-max-in-flight": "query_max_in_flight",
    "hbam.query-queue-depth": "query_queue_depth",
    "hbam.query-deadline-s": "query_deadline_s",
    # write-path knobs (write/; the reference's OutputFormats had only
    # the Hadoop codec's mapreduce.output.* compression settings)
    "hbam.write-compress-level": "write_compress_level",
    "hbam.write-parallel-workers": "write_parallel_workers",
    "hbam.write-index-kinds": "write_index_kinds",
    # serving knobs (serve/; no reference analog — Hadoop-BAM never ran
    # as a resident service)
    "hbam.serve-tile-cache-bytes": "serve_tile_cache_bytes",
    "hbam.serve-tile-records": "serve_tile_records",
    "hbam.serve-prefetch": "serve_prefetch",
    "hbam.serve-prefetch-depth": "serve_prefetch_depth",
    "hbam.serve-recent-regions": "serve_recent_regions",
    "hbam.serve-tenant-max-in-flight": "serve_tenant_max_in_flight",
    "hbam.serve-tenant-queue-depth": "serve_tenant_queue_depth",
    "hbam.serve-max-tenants": "serve_max_tenants",
    "hbam.serve-ring-slots": "serve_ring_slots",
    # fleet knobs (serve/fleet.py + serve/membership.py; no reference
    # analog — Hadoop-BAM had no serving tier to replicate)
    "hbam.serve-replica-id": "serve_replica_id",
    "hbam.serve-peers": "serve_peers",
    "hbam.fleet-replication": "fleet_replication",
    "hbam.fleet-heartbeat-s": "fleet_heartbeat_s",
    "hbam.fleet-suspicion-s": "fleet_suspicion_s",
    "hbam.fleet-eviction-s": "fleet_eviction_s",
    "hbam.fleet-peer-timeout-s": "fleet_peer_timeout_s",
    "hbam.fleet-hedge-min-s": "fleet_hedge_min_s",
    # resilience knobs (resilience/; no reference analog — Hadoop's only
    # adaptive behavior was task re-execution)
    "hbam.adaptive-planes": "adaptive_planes",
    "hbam.breaker-failure-threshold": "breaker_failure_threshold",
    "hbam.breaker-window-s": "breaker_window_s",
    "hbam.breaker-cooldown-s": "breaker_cooldown_s",
    "hbam.breaker-half-open-probes": "breaker_half_open_probes",
    "hbam.serve-shed-retry-after-s": "serve_shed_retry_after_s",
    "hbam.serve-prefetch-pause-pressure": "serve_prefetch_pause_pressure",
    "hbam.chaos-seed": "chaos_seed",
    # crash-safe job knobs (jobs/; the reference's analog was MapReduce
    # task re-execution + speculative execution, configured via
    # mapreduce.map.maxattempts / mapreduce.map.speculative)
    "hbam.pool-task-timeout-s": "pool_task_timeout_s",
    "hbam.speculative-decode": "speculative_decode",
    "hbam.straggler-multiplier": "straggler_multiplier",
    "hbam.straggler-min-s": "straggler_min_s",
    "hbam.collective-timeout-s": "collective_timeout_s",
    "hbam.journal-fsync": "journal_fsync",
    # cohort variant plane knobs (cohort/; no reference analog — Hadoop-BAM
    # never joined inputs, it only split them)
    "hbam.cohort-chunk-sites": "cohort_chunk_sites",
    "hbam.cohort-quarantine-inputs": "cohort_quarantine_inputs",
    "hbam.cohort-max-quarantine-fraction": "cohort_max_quarantine_fraction",
    "hbam.serve-cohort-manifests": "serve_cohort_manifests",
    # live-ops plane knobs (obs/flight.py, obs/slo.py; no reference
    # analog — Hadoop counters died with the job and nothing watched
    # them while it ran)
    "hbam.flight-dump-dir": "flight_dump_dir",
    "hbam.flight-dump-cap": "flight_dump_cap",
    "hbam.slo-latency-s": "slo_latency_s",
    "hbam.slo-target": "slo_target",
    "hbam.slo-tick-s": "slo_tick_s",
    "hbam.slo-min-events": "slo_min_events",
    "hbam.slo-shed-batch": "slo_shed_batch",
}


@dataclasses.dataclass
class HBamConfig:
    # --- format dispatch (hb/AnySAMInputFormat.java, hb/VCFInputFormat.java) ---
    trust_exts: bool = True          # skip magic sniffing when extension is known
    vcf_trust_exts: bool = True

    # --- decode behavior ---
    validation_stringency: ValidationStringency = ValidationStringency.SILENT
    cram_reference_source_path: Optional[str] = None

    # --- output ---
    vcf_output_format: str = "VCF"   # "VCF" | "BCF" (hb/VCFOutputFormat.java)
    write_header: bool = True        # per-shard header (KeyIgnoring*RecordWriter)
    write_terminator: bool = True    # BGZF EOF block on close
    # write path (write/): BGZF deflate level for EVERY producing path
    # (parallel writer, serial writers, shard parts, sort outputs);
    # htsjdk's BlockCompressedOutputStream default is 5, zlib's is 6 —
    # 6 kept for byte-compatibility with this repo's existing fixtures
    write_compress_level: int = 6
    write_parallel_workers: Optional[int] = None  # in-flight deflate
    #                                  bound for ParallelBGZFWriter;
    #                                  None = shared decode pool size,
    #                                  0 = serial in-line deflate
    write_index_kinds: str = "auto"  # sidecars co-written with outputs:
    #                                  "auto" (BAM: bai+sbi, BCF: tbi),
    #                                  "none", or a comma list
    # (3, 1) writes rANS Nx16 blocks.  EXPERIMENTAL: the Nx16 transform
    # metadata layouts are pinned by golden-byte tests against this repo's
    # own encoder only — no htslib cross-validation was possible in-image
    # (SURVEY.md section 0), so 3.1 output may not interop with samtools.
    cram_version: Tuple[int, int] = (3, 0)

    # --- FASTQ / QSEQ (hb/FormatConstants.java) ---
    fastq_base_quality_encoding: BaseQualityEncoding = BaseQualityEncoding.SANGER
    fastq_filter_failed_qc: bool = False
    qseq_base_quality_encoding: BaseQualityEncoding = BaseQualityEncoding.ILLUMINA
    qseq_filter_failed_qc: bool = False

    # --- interval filtering (hb/BAMInputFormat.java upstream 7.7+) ---
    # "chr20:1-100000,chr21" style; None = no filtering.
    bam_intervals: Optional[str] = None
    # keep both reads of a pair in the same span when the BAM is
    # queryname-grouped (hb/BAMInputFormat.java upstream 7.9+):
    keep_paired_reads_together: bool = False

    # --- failure policy (SURVEY.md section 5: spans are idempotent retry
    # units, the MapReduce task-retry analog — but retries are CLASSIFIED:
    # only transient I/O faults are re-attempted; corruption fails fast;
    # plan errors are never retried or skipped.  utils/errors.py owns the
    # taxonomy, utils/resilient.py the backoff/quarantine machinery.) ---
    span_retries: int = 2            # TRANSIENT re-decode attempts per span
    skip_bad_spans: bool = False     # after the policy: True = quarantine +
    #                                  skip (ticks pipeline.bad_spans and the
    #                                  manifest), False = raise
    max_bad_span_fraction: float = 1.0  # circuit breaker: abort once the
    #                                  quarantined fraction of planned spans
    #                                  exceeds this (1.0 = never trips)
    retry_backoff_base_s: float = 0.05  # first transient-retry delay
    retry_backoff_max_s: float = 2.0    # backoff ceiling
    io_read_retries: int = 0         # >0: wrap file sources in
    #                                  RetryingByteSource with this budget
    io_read_deadline_s: Optional[float] = None  # per-pread deadline
    check_crc: bool = False          # verify BGZF CRC32 footers on inflate

    # --- resilience (resilience/: adaptive degrade-and-heal; rides on
    # top of the failure policy above) ---
    adaptive_planes: bool = True     # decode-backend demotion ladder:
    #                                  oracle-confirmed plane-local
    #                                  faults demote device -> native ->
    #                                  zlib mid-run (byte-identical) and
    #                                  heal back via half-open probes;
    #                                  False = static plane selection
    breaker_failure_threshold: float = 3.0  # decayed failures within
    #                                  breaker_window_s that OPEN a
    #                                  fault domain's circuit
    breaker_window_s: float = 30.0   # failure-rate decay window
    breaker_cooldown_s: float = 5.0  # OPEN -> HALF_OPEN delay; also the
    #                                  retry_after hint open circuits
    #                                  report
    breaker_half_open_probes: int = 1  # concurrent probes HALF_OPEN
    #                                  admits before re-deciding
    serve_shed_retry_after_s: float = 0.1  # retry_after hint on
    #                                  admission-queue sheds (breaker
    #                                  sheds report their cooldown
    #                                  remainder instead)
    serve_prefetch_pause_pressure: float = 3.0  # registry-wide decayed
    #                                  failure count above which serve
    #                                  prefetch auto-pauses (speculative
    #                                  decode is the wrong spend under
    #                                  fault pressure)
    chaos_seed: Optional[int] = None  # seed for deterministic chaos
    #                                  schedules (tests/bench/soak);
    #                                  None = chaos only via explicit
    #                                  install_chaos / fault_points_on

    # --- crash-safe jobs (jobs/: durable journals, straggler defense;
    # the MapReduce analogs were task re-execution + speculative
    # execution) ---
    pool_task_timeout_s: Optional[float] = None  # hard per-future decode
    #                                  deadline on ACTIVE wait: queue
    #                                  time on a backlogged-but-healthy
    #                                  pool is excused up to an 8x grace
    #                                  (so a deep queue never false-
    #                                  fires, but a FULLY-wedged pool
    #                                  where nothing dequeues still
    #                                  surfaces); an overrunning task is
    #                                  abandoned and re-submitted once
    #                                  per span_retries budget, then
    #                                  raises TransientIOError — a
    #                                  wedged worker can no longer hang
    #                                  the consumer forever.  None = off
    speculative_decode: bool = True  # race a second copy of a span
    #                                  decode that outlives the job's
    #                                  soft deadline (first result wins,
    #                                  loser discarded); needs >= 16
    #                                  completed units before any
    #                                  deadline exists, so tiny runs
    #                                  never speculate
    straggler_multiplier: float = 4.0  # soft deadline = p95 of the
    #                                  decaying per-job unit-latency
    #                                  histogram x this
    straggler_min_s: float = 0.5     # soft-deadline floor: decode storms
    #                                  of sub-ms units must not
    #                                  speculate on scheduler jitter
    collective_timeout_s: Optional[float] = None  # multi-host loss
    #                                  detection: broadcast/allgather
    #                                  barriers outliving this surface
    #                                  TransientIOError (one dead host
    #                                  fails the collective fast) instead
    #                                  of blocking forever.  None = wait
    journal_fsync: bool = True       # fsync the job journal after every
    #                                  record (the durability the resume
    #                                  contract is written against);
    #                                  False trades crash-safety of the
    #                                  LAST unit for test speed

    # --- cohort variant plane (cohort/: k-way position join of
    # single-sample VCF/BCF inputs into [variants, samples] mesh tiles) ---
    cohort_chunk_sites: int = 1024   # joined sites per host column chunk
    #                                  handed to the feed pipeline (bounds
    #                                  host memory: k streams buffer one
    #                                  record each + one chunk of columns)
    cohort_quarantine_inputs: bool = True  # a sample file that faults
    #                                  mid-join (corrupt bytes, exhausted
    #                                  transient retries) is QUARANTINED:
    #                                  its column goes sentinel (-1/NaN)
    #                                  from the fault onward and the join
    #                                  completes; False = raise.  PLAN
    #                                  errors (bad paths/params) always
    #                                  raise either way
    cohort_max_quarantine_fraction: float = 0.5  # abort the build once
    #                                  more than this fraction of samples
    #                                  quarantined — a cohort that lost
    #                                  half its columns is not a result
    serve_cohort_manifests: int = 8  # cohort manifests kept resident in
    #                                  the serve tier before LRU eviction

    # --- live ops plane (obs/flight.py flight recorder + obs/slo.py
    # SLO burn accounting; `hbam top` reads both off the serve
    # transport) ---
    flight_dump_dir: Optional[str] = None  # where breaker-trip /
    #                                  demotion / deadline-miss /
    #                                  serve-error flight snapshots land
    #                                  (redacted JSON); None = the
    #                                  always-on ring stays memory-only
    #                                  (still served via {"op":"health"})
    flight_dump_cap: int = 16        # rotation cap on dump files kept
    slo_latency_s: float = 1.0       # per-tenant latency objective: a
    #                                  request slower than this spends
    #                                  error budget
    slo_target: float = 0.99         # promised good fraction
    slo_tick_s: float = 10.0         # burn-window snapshot cadence
    slo_min_events: int = 64         # window events below which burn
    #                                  reads 0 (a cold tenant's first
    #                                  slow request must not page)
    slo_shed_batch: bool = True      # shed batch-priority admissions
    #                                  for a tenant whose FAST burn
    #                                  window is alight (interactive
    #                                  traffic keeps flowing)

    # --- debug ---
    debug_keep_spill: bool = False   # keep mesh-sort .mesh-spill run dirs
    #                                  for post-mortem instead of removing
    #                                  them in the sort's finally

    # --- split planning ---
    split_size: int = 128 * 1024 * 1024   # analog of HDFS block size splits
    splitting_index_granularity: int = 4096  # records per splitting-bai sample
    use_splitting_index: bool = True      # snap splits via sidecar when present

    # --- host->device feed (parallel/staging.py) ---
    feed_ring_slots: int = 2         # preallocated group buffers in the
    #                                  staging ring (2 = one being packed
    #                                  while one is in dispatch; more buys
    #                                  slack at n_dev*cap*row_bytes each)
    feed_dispatch_depth: int = 2     # groups in flight past the packer
    #                                  (2 = double buffering: device_put k
    #                                  overlaps host repack of k+1)
    decode_pool_workers: Optional[int] = None  # shared decode pool size;
    #                                  None = min(32, max(4, 4*cpus)).
    #                                  First driver call in the process
    #                                  sizes the pool (utils/pools.py)
    use_fused_decode: bool = True    # single-pass native inflate+walk+pack
    #                                  (+CRC fold) per span, chunk-streamed
    #                                  into the staging ring; falls back to
    #                                  the two-pass oracle path when the
    #                                  native library is unavailable
    decode_chunk_blocks: int = 32    # BGZF blocks per fused decode chunk
    #                                  (~2 MiB inflated: big enough to
    #                                  amortize the walk handoff, small
    #                                  enough to stay cache-resident and
    #                                  stream tiles before the span tail
    #                                  inflates)
    inflate_backend: str = "auto"    # decode-plane selection:
    #                                  "auto"   = probe once per process
    #                                             and pick fused-native
    #                                             vs the device plane
    #                                             (resolve_inflate_backend)
    #                                  "native" = host C++ inflate
    #                                             (+ fused single-pass)
    #                                  "zlib"   = Python zlib (portable;
    #                                             disables the fused path)
    #                                  "device" = token-feed device decode
    #                                             plane (host Huffman
    #                                             tokenize + on-mesh LZ77
    #                                             resolve/walk/unpack) on
    #                                             drivers that support it

    # --- region-query serving (query/) ---
    query_cache_bytes: int = 256 << 20  # decoded-chunk LRU byte budget
    query_chunk_bytes: int = 1 << 20    # max compressed bytes coalesced
    #                                     into one cacheable chunk
    query_tile_records: int = 8192      # rows per device per predicate
    #                                     dispatch (FeedPipeline cap)
    query_max_in_flight: int = 8        # admission: concurrent queries
    query_queue_depth: int = 32         # admission: bounded wait queue;
    #                                     overflow sheds load with
    #                                     TransientIOError
    query_deadline_s: Optional[float] = None  # per-request wall budget;
    #                                     blown deadlines raise
    #                                     TransientIOError (retryable);
    #                                     anchored at ENQUEUE, so
    #                                     admission wait counts

    # --- serving (serve/: hbam serve / ServeLoop) ---
    serve_tile_cache_bytes: int = 512 << 20  # device-resident decoded-
    #                                     tile LRU budget (tier above the
    #                                     host chunk LRU; a hit skips
    #                                     fetch+inflate+host_decode)
    serve_tile_records: int = 4096      # rows per device per cached tile
    serve_prefetch: bool = True         # predictive adjacent-chunk
    #                                     prefetch at background pool
    #                                     priority
    serve_prefetch_depth: int = 2       # adjacent region windows
    #                                     prefetched per served query
    serve_recent_regions: int = 16      # per-file recency window driving
    #                                     prefetch dedup
    serve_tenant_max_in_flight: int = 4  # per-tenant admission quota
    serve_tenant_queue_depth: int = 16  # per-tenant bounded wait queue;
    #                                     overflow sheds with
    #                                     TransientIOError
    serve_max_tenants: int = 64         # idle tenant schedulers kept
    #                                     before LRU eviction
    serve_ring_slots: int = 3           # staging-ring slots for the tile
    #                                     builder (>= 3: one filling plus
    #                                     pinned-in-transfer slack)

    # --- serving fleet (serve/fleet.py, serve/membership.py) ---
    serve_replica_id: Optional[str] = None  # this process's fleet member
    #                                     id; None = not fleet-joined
    serve_peers: str = ""               # "id=host:port,..." peer list;
    #                                     empty = single-replica serving
    fleet_replication: int = 2          # R: rendezvous owners per tile
    #                                     key (self counts when ranked)
    fleet_heartbeat_s: float = 0.25     # peer heartbeat cadence
    fleet_suspicion_s: float = 1.5      # no heartbeat for this long ->
    #                                     SUSPECT (ownership unchanged)
    fleet_eviction_s: float = 5.0       # suspect for this long ->
    #                                     EVICTED from the member set
    #                                     (ownership re-ranks)
    fleet_peer_timeout_s: float = 2.0   # per-peer-call socket cap; the
    #                                     request's enqueue-anchored
    #                                     deadline still binds below it
    fleet_hedge_min_s: float = 0.05     # hedged peer-fetch soft-deadline
    #                                     floor (p95 * straggler_multiplier,
    #                                     never below this)

    # --- TPU backend ---
    backend: str = "tpu"                  # "tpu" | "cpu" (host NumPy decode)
    blocks_per_batch: int = 512           # BGZF blocks per device batch
    records_capacity_per_block: int = 2048  # SoA capacity per 64KiB block
    mesh_shape: Optional[Tuple[int, ...]] = None  # None = all local devices, 1D
    mesh_axis_names: Sequence[str] = ("data",)
    use_native: bool = True               # C++ batched inflate when available

    @classmethod
    def from_dict(cls, conf: Mapping[str, object]) -> "HBamConfig":
        """Build from a Hadoop-style string-keyed dict (reference key names)."""
        kwargs = {}
        for key, value in conf.items():
            field = _HADOOP_KEY_MAP.get(key, key)
            kwargs[field] = value
        return cls(**_coerce(kwargs))


def _coerce(kwargs: dict) -> dict:
    out = dict(kwargs)
    if "validation_stringency" in out:
        out["validation_stringency"] = ValidationStringency.parse(
            out["validation_stringency"])
    for k, default in (
        ("fastq_base_quality_encoding", BaseQualityEncoding.SANGER),
        ("qseq_base_quality_encoding", BaseQualityEncoding.ILLUMINA),
    ):
        if k in out:
            out[k] = BaseQualityEncoding.parse(out[k], default)
    for k in ("trust_exts", "vcf_trust_exts", "fastq_filter_failed_qc",
              "qseq_filter_failed_qc", "write_header", "write_terminator",
              "use_splitting_index", "use_native", "use_fused_decode",
              "keep_paired_reads_together", "skip_bad_spans",
              "debug_keep_spill", "serve_prefetch", "adaptive_planes",
              "cohort_quarantine_inputs", "speculative_decode",
              "journal_fsync", "slo_shed_batch"):
        if k in out and isinstance(out[k], str):
            out[k] = out[k].lower() in ("1", "true", "yes")
    for k in ("max_bad_span_fraction", "retry_backoff_base_s",
              "retry_backoff_max_s", "io_read_deadline_s",
              "query_deadline_s", "breaker_failure_threshold",
              "breaker_window_s", "breaker_cooldown_s",
              "serve_shed_retry_after_s",
              "serve_prefetch_pause_pressure",
              "cohort_max_quarantine_fraction", "pool_task_timeout_s",
              "straggler_multiplier", "straggler_min_s",
              "collective_timeout_s", "slo_latency_s", "slo_target",
              "slo_tick_s", "fleet_heartbeat_s", "fleet_suspicion_s",
              "fleet_eviction_s", "fleet_peer_timeout_s",
              "fleet_hedge_min_s"):
        if k in out and isinstance(out[k], str):
            out[k] = float(out[k])
    for k in ("span_retries", "io_read_retries", "feed_ring_slots",
              "feed_dispatch_depth", "decode_pool_workers",
              "decode_chunk_blocks",
              "write_compress_level", "write_parallel_workers",
              "query_cache_bytes", "query_chunk_bytes",
              "query_tile_records", "query_max_in_flight",
              "query_queue_depth",
              "serve_tile_cache_bytes", "serve_tile_records",
              "serve_prefetch_depth", "serve_recent_regions",
              "serve_tenant_max_in_flight", "serve_tenant_queue_depth",
              "serve_max_tenants", "serve_ring_slots",
              "breaker_half_open_probes", "chaos_seed",
              "cohort_chunk_sites", "serve_cohort_manifests",
              "flight_dump_cap", "slo_min_events",
              "fleet_replication"):
        if k in out and isinstance(out[k], str):
            out[k] = int(out[k])
    return out


DEFAULT_CONFIG = HBamConfig()


# ---------------------------------------------------------------------------
# Decode-plane selection.  ``inflate_backend="auto"`` resolves ONCE per
# process: the probe (ops/inflate_device.probe_device_plane) times the
# host Huffman tokenize stage against the device LZ77 resolve and picks
# the device plane only when its pipelined wall (max of the two
# overlapped stages) beats host inflate — which can never happen when
# the "device" is the host CPU running XLA, so the CPU backend resolves
# straight to "native" without paying the probe's jit compile.  Drivers
# without a device plane treat "device" as "native" (each driver
# documents its planes; flagstat is the token-feed pilot).
# ---------------------------------------------------------------------------

INFLATE_BACKENDS = ("auto", "native", "zlib", "device")

# Decode planes, fastest first — the vocabulary plan/executor.select_plane
# (the ONE plane-gating predicate; planroute lint PL101 keeps gates out of
# every other package) decides over, and the rung order the resilience
# DemotionLadder demotes along.  "fused" is a MODE of the native plane
# (the single-pass sweep), not a plane of its own.
DECODE_PLANES = ("device", "native", "zlib")

_PLANE_CACHE: dict = {}


def resolve_inflate_backend(config: "HBamConfig | None") -> str:
    """Resolve a config's ``inflate_backend`` to a concrete plane name
    ("native" | "zlib" | "device").  "auto" probes once per process.

    This is only the STARTING rung, and only one input of the decision:
    per-plan routing (which plane a given op DAG actually runs on, given
    intervals / skip_bad_spans / fused availability) is decided in
    ``plan.executor.select_plane``, the single predicate table every
    driver consults.  With ``config.adaptive_planes`` the
    drivers run the resolved plane through a ``resilience.DemotionLadder``
    — oracle-confirmed plane-local faults demote it mid-run and a
    half-open probe revisits the faster plane after the breaker
    cooldown, so the once-per-process probe is no longer the last word
    on plane selection."""
    backend = getattr(config, "inflate_backend", "auto") \
        if config is not None else "auto"
    if backend not in INFLATE_BACKENDS:
        # PLAN class: a bad plane name is run configuration, not data —
        # never retried, never quarantined (utils/errors classifies
        # PlanError by type; imported lazily to keep this module light)
        from hadoop_bam_tpu.utils.errors import PlanError
        raise PlanError(
            f"unknown inflate backend {backend!r}; "
            f"expected one of {INFLATE_BACKENDS}")
    if backend != "auto":
        return backend
    if "auto" not in _PLANE_CACHE:
        _PLANE_CACHE["auto"] = _probe_auto_plane()
    return _PLANE_CACHE["auto"]


def _probe_auto_plane() -> str:
    try:
        from hadoop_bam_tpu.ops.inflate_device import probe_device_plane
        probe = probe_device_plane()
        return "device" if probe.get("device_wins") else "native"
    except Exception:  # noqa: BLE001 — selection must never fail a run
        return "native"
