"""Batched device rANS 4x8 decode (CRAM block method 4).

The TPU mapping of the reference stack's entropy decoder (SURVEY.md
section 2.8 row 5: htsjdk/htslib rANS reached through CRAM decode).
An rANS stream is serial *within* a block — the four interleaved 32-bit
states share one renormalization byte stream, so each step's byte
consumption depends on all previous steps — but blocks are independent.
The device decode therefore vectorizes ACROSS blocks: a ``lax.scan`` over
output steps whose body decodes 4 states x B blocks of lanes on the VPU,
with table lookups as batched gathers.

Per step and state: ``m = x & 0xFFF; s = slot2sym[m];
x' = freq[s] * (x >> 12) + m - cum[s]``, then at most two 8-bit
renormalization reads (``x >= freq >= 1`` after a step gives
``x' >= 2^11``, and two byte loads reach ``>= 2^27 > 2^23``) [SPEC
CRAMcodecs rANS].  Order-0 interleaves states over positions
(state j owns positions 4k + j); order-1 gives each state one quarter of
the output with per-context tables keyed on the previous byte.

Host side (table parsing, padding, batch assembly) reuses
formats/cram_codecs.py — the same tables drive the NumPy, native C++,
and device decoders, so parity tests pin all three to each other.

Backend selection: ``rans_decode_batch(payloads, backend=...)`` with
"host" (native C++/NumPy per stream — the throughput default),
"device" (this module), or "auto" (host; the honest measurement in
BASELINE.md shows where each wins).
"""
from __future__ import annotations

import functools
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from hadoop_bam_tpu.formats.cram_codecs import (
    RANS_LOW, RANS_ORDER_0, RANS_ORDER_1, RansError, TF_SHIFT, TOTFREQ,
    normalize_truncation, rans4x8_decode, read_order0_tables,
    read_order1_tables,
)

_MASK = TOTFREQ - 1


def _round_pow2(x: int, lo: int = 1) -> int:
    n = lo
    while n < x:
        n <<= 1
    return n


# ---------------------------------------------------------------------------
# Device kernels (jnp + lax.scan; vectorized over the block axis)
# ---------------------------------------------------------------------------

@functools.partial(jax.jit, static_argnames=("steps",))
def _decode0_batch(data, states0, ptr0, freqs, cums, slot2sym, n_out,
                   steps: int):
    """Order-0 batch: data [B, L] u8 (padded), states0 [B, 4] u32,
    ptr0 [B] i32, freqs/cums [B, 256] u32, slot2sym [B, 4096] u8,
    n_out [B] i32 -> [B, 4 * steps] u8 (positions past n_out are junk)."""
    def gather(tbl, idx):
        return jnp.take_along_axis(tbl, idx[:, None].astype(jnp.int32),
                                   axis=1)[:, 0]

    def body(carry, step):
        states, ptr = carry
        outs = []
        for j in range(4):
            x = states[:, j]
            active = (4 * step + j) < n_out
            m = x & jnp.uint32(_MASK)
            sym = gather(slot2sym, m).astype(jnp.uint32)
            f = gather(freqs, sym)
            c = gather(cums, sym)
            x2 = f * (x >> TF_SHIFT) + m - c
            for _ in range(2):  # renorm: at most two byte reads
                need = x2 < jnp.uint32(RANS_LOW)
                byte = gather(data, ptr).astype(jnp.uint32)
                x2 = jnp.where(need, (x2 << 8) | byte, x2)
                ptr = ptr + jnp.where(active & need, 1, 0)
            states = states.at[:, j].set(jnp.where(active, x2, x))
            outs.append(sym.astype(jnp.uint8))
        return (states, ptr), jnp.stack(outs, axis=1)   # [B, 4]

    (fstates, fptr), ys = jax.lax.scan(body, (states0, ptr0),
                                       jnp.arange(steps, dtype=jnp.int32))
    return (jnp.transpose(ys, (1, 0, 2)).reshape(ys.shape[1], -1),
            fstates, fptr)


@functools.partial(jax.jit, static_argnames=("steps",))
def _decode1_batch(data, states0, ptr0, freqs, cums, slot2sym, q, rem,
                   steps: int):
    """Order-1 batch: freqs/cums [B, 256*256] u32 (ctx-major), slot2sym
    [B, 256*4096] u8, q/rem [B] i32 -> [B, 4, steps] u8 (state-major;
    state j holds quarter j, state 3 also the tail remainder)."""
    def gather(tbl, idx):
        return jnp.take_along_axis(tbl, idx[:, None].astype(jnp.int32),
                                   axis=1)[:, 0]

    def body(carry, step):
        states, ptr, ctxs = carry
        outs = []
        for j in range(4):
            x = states[:, j]
            lens_j = q + (rem if j == 3 else 0)
            active = step < lens_j
            m = x & jnp.uint32(_MASK)
            ctx = ctxs[:, j]
            sym = gather(slot2sym,
                         ctx * TOTFREQ + m.astype(jnp.int32)
                         ).astype(jnp.uint32)
            f = gather(freqs, ctx * 256 + sym.astype(jnp.int32))
            c = gather(cums, ctx * 256 + sym.astype(jnp.int32))
            x2 = f * (x >> TF_SHIFT) + m - c
            for _ in range(2):
                need = x2 < jnp.uint32(RANS_LOW)
                byte = gather(data, ptr).astype(jnp.uint32)
                x2 = jnp.where(need, (x2 << 8) | byte, x2)
                ptr = ptr + jnp.where(active & need, 1, 0)
            states = states.at[:, j].set(jnp.where(active, x2, x))
            ctxs = ctxs.at[:, j].set(
                jnp.where(active, sym.astype(jnp.int32), ctx))
            outs.append(sym.astype(jnp.uint8))
        return (states, ptr, ctxs), jnp.stack(outs, axis=1)

    ctxs0 = jnp.zeros_like(states0, dtype=jnp.int32)
    (fstates, fptr, _), ys = jax.lax.scan(body, (states0, ptr0, ctxs0),
                                          jnp.arange(steps, dtype=jnp.int32))
    return jnp.transpose(ys, (1, 2, 0)), fstates, fptr  # [B, 4, steps]


# ---------------------------------------------------------------------------
# Host batch assembly
# ---------------------------------------------------------------------------

def _parse_header(payload: bytes) -> Tuple[int, int, int]:
    if len(payload) < 9:
        raise RansError("rANS stream shorter than its 9-byte prefix")
    order = payload[0]
    comp_size = int.from_bytes(payload[1:5], "little")
    out_size = int.from_bytes(payload[5:9], "little")
    if len(payload) < 9 + comp_size:
        raise RansError("truncated rANS stream")
    return order, comp_size, out_size


def _pad_batch(blocks: Sequence[Tuple[np.ndarray, np.ndarray, int, int]],
               b_cap: int):
    """(body u8, states u32[4], body_pos, out_size) list -> padded arrays.

    Shapes round up (B to b_cap, lengths to pow2) so jit caches stay
    small across batches."""
    B = len(blocks)
    max_body = _round_pow2(max(b.size for b, *_ in blocks) + 8, 64)
    data = np.zeros((b_cap, max_body), dtype=np.uint8)
    states = np.zeros((b_cap, 4), dtype=np.uint32)
    ptr = np.zeros(b_cap, dtype=np.int32)
    n_out = np.zeros(b_cap, dtype=np.int32)
    # dummy rows keep states >= RANS_LOW so the renorm loop never loops
    states[:, :] = RANS_LOW
    for i, (body, st, pos, osz) in enumerate(blocks):
        data[i, :body.size] = body
        states[i] = st
        ptr[i] = pos
        n_out[i] = osz
    return data, states, ptr, n_out, B


def _check_final(fstates: np.ndarray, fptr: np.ndarray, chunk) -> None:
    """Integrity check after a batched device decode.

    The encoder initializes every state to RANS_LOW, so a well-formed
    stream decodes back to exactly RANS_LOW with the shared byte pointer
    landing on the end of the renorm bytes.  A corrupt/truncated payload
    (whose out-of-range gathers clamp silently under JAX semantics) fails
    one of the two — raise instead of returning garbage, matching the
    host decoder's error behavior.  ``chunk`` is the [(payload index,
    block)] list so errors name the batch-level payload, not the
    chunk-local row."""
    for k, (i, (body, _st, _pos, _osz)) in enumerate(chunk):
        if fptr[k] != body.size or (fstates[k] != RANS_LOW).any():
            raise RansError(
                f"device rANS decode integrity failure on payload {i}: "
                f"consumed {int(fptr[k])}/{body.size} renorm bytes, "
                f"final states {fstates[k].tolist()} (want all "
                f"{RANS_LOW}) — corrupt or truncated stream")


def rans_decode_batch_device(payloads: Sequence[bytes]) -> List[bytes]:
    """Decode many rANS 4x8 streams on the default JAX device, batched.

    Parity oracle: formats/cram_codecs.rans4x8_decode per stream."""
    results: List[Optional[bytes]] = [None] * len(payloads)
    o0: List[Tuple[int, tuple]] = []    # (payload idx, parsed block)
    o1: List[Tuple[int, tuple]] = []
    tables0: List[Tuple[np.ndarray, np.ndarray]] = []
    tables1: List[Tuple[np.ndarray, np.ndarray, np.ndarray]] = []

    for i, p in enumerate(payloads):
        order, comp_size, out_size = _parse_header(p)
        if out_size == 0:
            results[i] = b""
            continue
        body = np.frombuffer(p, dtype=np.uint8, count=comp_size, offset=9)
        with normalize_truncation(f"rANS (payload {i})"):
            if order == RANS_ORDER_0:
                freqs, cum, slot2sym, pos = read_order0_tables(p, 9)
                tables0.append((freqs, cum[:256], slot2sym))
            elif order == RANS_ORDER_1:
                freqs, cums, slot2sym, pos = read_order1_tables(p, 9)
                tables1.append((freqs, cums[:, :256], slot2sym))
            else:
                raise RansError(f"unknown rANS order {order}")
            if len(p) < pos + 16:
                raise RansError("truncated rANS stream (state words)")
            st = np.frombuffer(p[pos:pos + 16], dtype="<u4").copy()
            (o0 if order == RANS_ORDER_0 else o1).append(
                (i, (body[pos - 9 + 16:], st, 0, out_size)))

    # --- order-0: vectorize across up to 256 blocks per dispatch
    CH0 = 256
    for lo in range(0, len(o0), CH0):
        chunk = o0[lo:lo + CH0]
        tabs = tables0[lo:lo + CH0]
        b_cap = _round_pow2(len(chunk), 8)
        data, states, ptr, n_out, B = _pad_batch(
            [blk for _, blk in chunk], b_cap)
        freqs = np.zeros((b_cap, 256), dtype=np.uint32)
        cums = np.zeros((b_cap, 256), dtype=np.uint32)
        slot = np.zeros((b_cap, TOTFREQ), dtype=np.uint8)
        for k, (f, c, s) in enumerate(tabs):
            freqs[k], cums[k], slot[k] = f, c, s
        freqs[B:, :] = 1  # dummy rows: nonzero freq keeps states sane
        steps = _round_pow2((int(n_out.max()) + 3) // 4)
        out, fstates, fptr = _decode0_batch(
            jnp.asarray(data), jnp.asarray(states), jnp.asarray(ptr),
            jnp.asarray(freqs), jnp.asarray(cums), jnp.asarray(slot),
            jnp.asarray(n_out), steps)
        out = np.asarray(out)
        _check_final(np.asarray(fstates), np.asarray(fptr), chunk)
        for k, (i, (_b, _s, _p, osz)) in enumerate(chunk):
            results[i] = out[k, :osz].tobytes()

    # --- order-1: larger tables, smaller chunks
    CH1 = 16
    for lo in range(0, len(o1), CH1):
        chunk = o1[lo:lo + CH1]
        tabs = tables1[lo:lo + CH1]
        b_cap = _round_pow2(len(chunk), 4)
        data, states, ptr, n_out, B = _pad_batch(
            [blk for _, blk in chunk], b_cap)
        freqs = np.zeros((b_cap, 256 * 256), dtype=np.uint32)
        cums = np.zeros((b_cap, 256 * 256), dtype=np.uint32)
        slot = np.zeros((b_cap, 256 * TOTFREQ), dtype=np.uint8)
        for k, (f, c, s) in enumerate(tabs):
            freqs[k] = f.reshape(-1)
            cums[k] = c.reshape(-1)
            slot[k] = s.reshape(-1)
        freqs[B:, :] = 1
        q = n_out >> 2
        rem = n_out - 3 * q - q
        steps = _round_pow2(int((q + rem).max()))
        out, fstates, fptr = _decode1_batch(
            jnp.asarray(data), jnp.asarray(states), jnp.asarray(ptr),
            jnp.asarray(freqs), jnp.asarray(cums), jnp.asarray(slot),
            jnp.asarray(q), jnp.asarray(rem), steps)    # [B, 4, steps]
        out = np.asarray(out)
        _check_final(np.asarray(fstates), np.asarray(fptr), chunk)
        for k, (i, (_b, _s, _p, osz)) in enumerate(chunk):
            qq, rr = osz >> 2, osz - 4 * (osz >> 2)
            parts = [out[k, 0, :qq], out[k, 1, :qq], out[k, 2, :qq],
                     out[k, 3, :qq + rr]]
            results[i] = np.concatenate(parts).tobytes()

    return results  # type: ignore[return-value]


def rans_decode_batch(payloads: Sequence[bytes],
                      backend: str = "auto") -> List[bytes]:
    """Decode a batch of rANS 4x8 streams.

    backend="host": native C++/NumPy, stream at a time (default under
    "auto" — single-stream latency wins on the host; see BASELINE.md for
    the measured device/host crossover).  backend="device": the batched
    VPU decode above."""
    if backend == "device":
        return rans_decode_batch_device(payloads)
    return [rans4x8_decode(p) for p in payloads]
