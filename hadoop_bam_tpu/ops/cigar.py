"""Device CIGAR geometry: ragged cigar unpack, reference spans, coverage.

The reference computes alignment geometry per record on the CPU (htsjdk
``SAMRecord.getAlignmentEnd`` walking the cigar; SURVEY.md section 7
kernel (b) maps it to a device kernel).  Here the ragged cigar arrays
become fixed-shape [N, max_cigar] u32 tiles (zero-padded — a zero word
is a 0-length M op, which every reduction ignores), and geometry falls
out of masked row reductions:

- ``reference_span_from_tiles``: bases consumed on the reference
  (M/D/N/=/X), parity with the host ``BamBatch.reference_span``;
- ``window_coverage_from_tiles``: exact per-base aligned-base depth
  (M/=/X ops only — deletions and ref-skips do not add depth) over a
  genomic window, as a diff-array scatter + cumsum — the segment-ops
  formulation of pileup that keeps the VPU busy instead of a per-read
  host loop.

Coordinates stay int32: BAM positions and windows are < 2^31 [SPEC].
"""
from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp

from hadoop_bam_tpu.ops.unpack_bam import PREFIX

# op codes [SPEC]: M I D N S H P = X
_REF_CONSUMING = (0, 2, 3, 7, 8)     # M D N = X
_ALIGNED = (0, 7, 8)                 # M = X  (bases that add depth)


def _is_in(op: jnp.ndarray, codes: Tuple[int, ...]) -> jnp.ndarray:
    m = op == codes[0]
    for c in codes[1:]:
        m = m | (op == c)
    return m


@functools.partial(jax.jit, static_argnames=("max_cigar",))
def unpack_cigar_tiles(data: jnp.ndarray, offsets: jnp.ndarray,
                       l_read_name: jnp.ndarray, n_cigar: jnp.ndarray,
                       max_cigar: int) -> jnp.ndarray:
    """Gather each record's cigar words into a [N, max_cigar] uint32 tile.

    ``data`` is the inflated span bytes; per record the cigar begins at
    ``offset + PREFIX + l_read_name`` [SPEC record layout].  Ops beyond
    ``n_cigar`` (and rows whose cigar would read past the buffer) are 0.

    CONTRACT: records with ``n_cigar > max_cigar`` are silently truncated
    here (no raising inside jit) and every downstream geometry value for
    them is wrong — callers must validate ``n_cigar.max() <= max_cigar``
    on the host first, as coverage_file does before dispatch.
    """
    if data.shape[0] < 4:   # shapes are static under jit: plain Python
        # a buffer shorter than one cigar word can hold no ops, and the
        # clip below would get a negative upper bound (min > max is
        # implementation-defined); no record is valid either way
        return jnp.zeros((offsets.shape[0], max_cigar), jnp.uint32)
    start = offsets + PREFIX + l_read_name
    j = jnp.arange(max_cigar, dtype=jnp.int32)
    base = start[:, None] + 4 * j[None, :]
    base = jnp.clip(base, 0, jnp.int32(data.shape[0] - 4))
    w = (data[base].astype(jnp.uint32)
         | (data[base + 1].astype(jnp.uint32) << 8)
         | (data[base + 2].astype(jnp.uint32) << 16)
         | (data[base + 3].astype(jnp.uint32) << 24))
    valid = j[None, :] < n_cigar[:, None]
    return jnp.where(valid, w, jnp.uint32(0))


def reference_span_from_tiles(tiles: jnp.ndarray, n_cigar: jnp.ndarray,
                              l_seq: jnp.ndarray) -> jnp.ndarray:
    """Reference bases consumed per record; '*'-cigar records fall back to
    l_seq (host parity: formats/bam.py::BamBatch.reference_span)."""
    op = (tiles & 0xF).astype(jnp.int32)
    ln = (tiles >> 4).astype(jnp.int32)
    span = jnp.sum(jnp.where(_is_in(op, _REF_CONSUMING), ln, 0), axis=1)
    return jnp.where(n_cigar > 0, span, jnp.maximum(l_seq, 0))


@functools.partial(jax.jit, static_argnames=("window",))
def window_coverage_from_tiles(tiles: jnp.ndarray,
                               pos: jnp.ndarray, refid: jnp.ndarray,
                               flag: jnp.ndarray, row_valid: jnp.ndarray,
                               target_refid: jnp.ndarray,
                               win_start: jnp.ndarray,
                               window: int) -> jnp.ndarray:
    """Exact per-base depth of aligned bases over [win_start, win_start +
    window) of one reference sequence.

    Depth counts M/=/X op bases of mapped records on the target
    reference; D/N ops advance the reference cursor without adding
    depth; unmapped records (FLAG 0x4) and padded rows contribute
    nothing.  Ops past each record's n_cigar need no mask: tile padding
    is zero words = 0-length M ops, provably net-zero in the diff array.
    Returns int32 [window].
    """
    op = (tiles & 0xF).astype(jnp.int32)
    ln = (tiles >> 4).astype(jnp.int32)
    adv = jnp.where(_is_in(op, _REF_CONSUMING), ln, 0)
    op_start = pos[:, None] + jnp.cumsum(adv, axis=1) - adv

    keep = (_is_in(op, _ALIGNED)
            & row_valid[:, None]
            & ((flag[:, None] & 4) == 0)
            & (refid[:, None] == target_refid))
    s = jnp.clip(op_start - win_start, 0, window)
    e = jnp.clip(op_start + ln - win_start, 0, window)
    s = jnp.where(keep, s, 0)
    e = jnp.where(keep, e, 0)                 # zero-length: no-op
    one = keep.astype(jnp.int32)
    diff = jnp.zeros(window + 1, jnp.int32)
    diff = diff.at[s.ravel()].add(one.ravel())
    diff = diff.at[e.ravel()].add(-one.ravel())
    return jnp.cumsum(diff[:window])
