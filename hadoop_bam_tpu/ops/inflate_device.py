"""Device DEFLATE: LZ77 back-reference resolution on the accelerator.

The reference hot loop inflates every 64 KiB BGZF block through zlib's JNI
(SURVEY.md section 3.2); the inflate CPU cost splits into two very
different halves:

1. **Huffman symbol decode** — a bit-serial, data-dependent branch cascade
   with no intra-stream parallelism.  This stays on the host
   (native/hbam_native.cpp::hbam_deflate_tokenize_batch, threaded across
   blocks), emitting fixed-width u32 LZ77 tokens:
   bit31 set -> copy (bits 16-24 length, bits 0-15 distance-1),
   bit31 clear -> literal byte.
2. **LZ77 copy resolution** — embarrassingly parallel across blocks AND,
   via pointer doubling, log-depth parallel across bytes.  This is the
   device half below.

Kernel shape (pure jnp/lax — batched gathers on the VPU, no scalar loops):

- token lengths -> exclusive cumsum gives each token's output start;
- scatter-add marks at starts, cumsum -> per-byte token id;
- per byte: ``src[p] = p - dist`` for copy bytes, ``src[p] = p`` (fixed
  point) for literals — an acyclic pointer forest rooted at literals;
- pointer doubling ``src = src[src]`` inside ``lax.while_loop`` until
  converged (<= ceil(log2(chain depth)) rounds; overlapping RLE-style
  copies are the deep-chain worst case), then one gather from the
  scattered literal bytes.

Measurement discipline (BASELINE.md "Device DEFLATE"): the host tokenize
stage, the on-chip resolve (jitted, inputs device-resident, excludes the
H2D link), and the end-to-end span inflate are timed separately so the
conclusion transfers to non-tunneled hardware.
"""
from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from hadoop_bam_tpu.formats import bgzf
from hadoop_bam_tpu.ops.rans import _round_pow2
from hadoop_bam_tpu.utils import native

# BGZF caps a block's inflated size at 64 KiB [SPEC SAMv1 4.1]
BGZF_MAX_ISIZE = 1 << 16


@functools.partial(jax.jit, static_argnames=("P",))
def resolve_tokens(tokens: jax.Array, n_tokens: jax.Array, P: int
                   ) -> jax.Array:
    """Resolve LZ77 tokens to inflated bytes: [B, T] u32 + [B] i32 -> [B, P] u8.

    Positions past each block's output length hold junk; the caller slices
    by out_lens.  P must be >= every block's inflated size."""
    B, T = tokens.shape
    is_copy = (tokens >> 31).astype(jnp.int32)
    tok_len = jnp.where(is_copy == 1,
                        ((tokens >> 16) & 0x1FF).astype(jnp.int32), 1)
    tid = jnp.arange(T, dtype=jnp.int32)[None, :]
    valid = tid < n_tokens[:, None]
    tok_len = jnp.where(valid, tok_len, 0)
    starts = jnp.cumsum(tok_len, axis=1) - tok_len          # exclusive

    # per-byte token id: scatter 1 at each token start (zero-length pads
    # land in a sacrificial extra column), cumsum, -1
    scat = jnp.where((tok_len > 0) & valid, starts, P)
    marks = jnp.zeros((B, P + 1), jnp.int32).at[
        jnp.arange(B, dtype=jnp.int32)[:, None], scat].add(1)
    tok_of_byte = jnp.cumsum(marks[:, :P], axis=1) - 1
    tok_of_byte = jnp.clip(tok_of_byte, 0, T - 1)

    w = jnp.take_along_axis(tokens, tok_of_byte, axis=1)    # token per byte
    pos = jnp.arange(P, dtype=jnp.int32)[None, :]
    byte_is_copy = (w >> 31).astype(jnp.int32)
    dist = (w & 0xFFFF).astype(jnp.int32) + 1
    src = jnp.where(byte_is_copy == 1, pos - dist, pos)
    src = jnp.clip(src, 0, P - 1)   # tokenizer guarantees dist <= position
    lit = jnp.where(byte_is_copy == 1, 0, w & 0xFF).astype(jnp.uint8)

    # pointer doubling until every byte points at its literal root; the
    # forest is acyclic (src[p] < p for copies) so this terminates in
    # <= ceil(log2(P)) rounds, far fewer for typical shallow chains
    def cond(c):
        return c[1]

    def body(c):
        s, _ = c
        s2 = jnp.take_along_axis(s, s, axis=1)
        return s2, jnp.any(s2 != s)

    src, _ = jax.lax.while_loop(cond, body, (src, jnp.bool_(True)))
    return jnp.take_along_axis(lit, src, axis=1)


def inflate_span_device(raw: bytes, table: Optional[dict] = None,
                        chunk: int = 64, n_threads: int = 0
                        ) -> Tuple[np.ndarray, np.ndarray]:
    """Inflate a BGZF span with host Huffman tokenize + device LZ77 resolve.

    Same contract as ops.inflate.inflate_span: returns (contiguous
    inflated bytes, per-block starting offsets)."""
    from hadoop_bam_tpu.ops.inflate import block_table
    if table is None:
        table = block_table(raw)
    if not native.available():
        # PLAN class: selecting the device backend without the native
        # library is a configuration fault — classify_error must not
        # treat it as transient (old RuntimeError fell through to the
        # generic CORRUPT bucket; retrying could never heal it either)
        from hadoop_bam_tpu.utils.errors import PlanError
        raise PlanError(
            "device inflate needs the native tokenizer "
            "(hbam_deflate_tokenize_batch); native library unavailable")
    isize = table["isize"]
    n = isize.size
    ubase = np.zeros(n + 1, dtype=np.int64)
    np.cumsum(isize, out=ubase[1:])
    dst = np.empty(int(ubase[-1]), dtype=np.uint8)
    src = np.frombuffer(raw, dtype=np.uint8)

    for lo in range(0, n, chunk):
        hi = min(lo + chunk, n)
        sub_isize = isize[lo:hi]
        stride = max(16, int(sub_isize.max())) if hi > lo else 16
        tokens, n_tokens, out_lens = native.deflate_tokenize_batch(
            src, table["cdata_off"][lo:hi], table["cdata_len"][lo:hi],
            stride, n_threads)
        if not np.array_equal(out_lens, sub_isize):
            bad = int(np.nonzero(out_lens != sub_isize)[0][0])
            raise bgzf.BGZFError(
                f"ISIZE mismatch in block {lo + bad}: tokenized "
                f"{int(out_lens[bad])}, footer says {int(sub_isize[bad])}")
        P = _round_pow2(stride, 256)
        b_cap = _round_pow2(hi - lo, 8)
        # pad the token axis to P too, so (B, T, P) are all canonical and
        # heterogeneous chunks reuse one jit cache entry
        tok_pad = np.zeros((b_cap, P), dtype=np.uint32)
        tok_pad[: hi - lo, : tokens.shape[1]] = tokens
        nt_pad = np.zeros(b_cap, dtype=np.int32)
        nt_pad[: hi - lo] = n_tokens
        out = np.asarray(resolve_tokens(
            jnp.asarray(tok_pad), jnp.asarray(nt_pad), P))
        for k in range(hi - lo):
            i = lo + k
            dst[int(ubase[i]):int(ubase[i + 1])] = out[k, : int(isize[i])]
    return dst, ubase[:-1]
