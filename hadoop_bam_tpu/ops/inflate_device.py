"""Device DEFLATE: LZ77 back-reference resolution on the accelerator.

The reference hot loop inflates every 64 KiB BGZF block through zlib's JNI
(SURVEY.md section 3.2); the inflate CPU cost splits into two very
different halves:

1. **Huffman symbol decode** — a bit-serial, data-dependent branch cascade
   with no intra-stream parallelism.  This stays on the host
   (native/hbam_native.cpp::hbam_deflate_tokenize_batch, threaded across
   blocks), emitting fixed-width u32 LZ77 tokens:
   bit31 set -> copy (bits 16-24 length, bits 0-15 distance-1),
   bit31 clear -> literal byte.
2. **LZ77 copy resolution** — embarrassingly parallel across blocks AND,
   via pointer doubling, log-depth parallel across bytes.  This is the
   device half below.

Kernel shape (pure jnp/lax — batched gathers on the VPU, no scalar loops):

- token lengths -> exclusive cumsum gives each token's output start;
- scatter-add marks at starts, cumsum -> per-byte token id;
- per byte: ``src[p] = p - dist`` for copy bytes, ``src[p] = p`` (fixed
  point) for literals — an acyclic pointer forest rooted at literals;
- pointer doubling ``src = src[src]`` inside ``lax.while_loop`` until
  converged (<= ceil(log2(chain depth)) rounds; overlapping RLE-style
  copies are the deep-chain worst case), then one gather from the
  scattered literal bytes.

On top of the per-block resolve, this module provides the two fusions the
device decode plane (parallel/pipeline.py token-feed path) runs through:

- ``resolve_tokens_packed`` — resolve + one device-side slice/pack into a
  contiguous span buffer (replaces the old per-block host copy loop:
  ONE host sync per chunk instead of one per block);
- ``resolve_walk_fields`` — resolve + pack + an on-device record walk
  (the block_size chain traversed by the same pointer-doubling trick:
  log-depth scatter/gather rounds instead of a serial host walk) + the
  ``ops/unpack_bam.FIXED_FIELDS`` gather, so the resolved bytes NEVER
  leave the device on the stats paths: flagstat/coverage predicates read
  the columns straight from the device-resident inflated buffer.

Shape discipline: ``(B, T, P)`` are canonicalized — ``T == P`` and ``P``
clamped to the small pow2 ``P_LADDER`` — so heterogeneous chunks share
one jit cache entry per ladder rung (the compile-count test in
tests/test_inflate_device.py pins this).

Measurement discipline (BASELINE.md "Device DEFLATE"): the host tokenize
stage, the on-chip resolve (jitted, inputs device-resident, excludes the
H2D link), and the end-to-end span inflate are timed separately so the
conclusion transfers to non-tunneled hardware.
"""
from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from hadoop_bam_tpu.formats import bgzf
from hadoop_bam_tpu.ops.rans import _round_pow2
from hadoop_bam_tpu.ops.unpack_bam import PREFIX, unpack_fixed_fields_tile
from hadoop_bam_tpu.resilience import chaos
from hadoop_bam_tpu.utils import native

# BGZF caps a block's inflated size at 64 KiB [SPEC SAMv1 4.1]
BGZF_MAX_ISIZE = 1 << 16

# The canonical per-block width ladder: P (inflated bytes per block, ==
# the token-axis pad T) snaps UP to one of these, so a run over spans
# whose max ISIZE wanders (mixed BAM/BCF/tabix block sizes, short final
# blocks) compiles each kernel at most len(P_LADDER) times instead of
# once per distinct pow2 (the jit-cache churn the round-11 issue calls
# out).  Three rungs: tiny index/EOF blocks, mid-size text blocks, and
# full 64 KiB BAM blocks.
P_LADDER = (1 << 10, 1 << 13, 1 << 16)


def ladder_pow2(x: int) -> int:
    """Snap a per-block byte width up to the canonical P_LADDER rung."""
    for p in P_LADDER:
        if x <= p:
            return p
    raise bgzf.BGZFError(
        f"block inflated size {x} exceeds the BGZF 64 KiB cap")


def records_cap(B: int, P: int) -> int:
    """Static record capacity for a [B, P] chunk's device walk: the
    minimum on-wire BAM record is 36 bytes (4-byte block_size + 32-byte
    fixed core), so B*P//32 rounded to a pow2 can never be exceeded by
    well-formed data — an overflow IS corruption (same taxonomy as the
    fused native path's capacity fault)."""
    return _round_pow2(max(16, (B * P) // 32), 16)


@functools.partial(jax.jit, static_argnames=("P",))
def resolve_tokens(tokens: jax.Array, n_tokens: jax.Array, P: int
                   ) -> jax.Array:
    """Resolve LZ77 tokens to inflated bytes: [B, T] u32 + [B] i32 -> [B, P] u8.

    Positions past each block's output length hold junk; the caller slices
    by out_lens.  P must be >= every block's inflated size."""
    B, T = tokens.shape
    is_copy = (tokens >> 31).astype(jnp.int32)
    tok_len = jnp.where(is_copy == 1,
                        ((tokens >> 16) & 0x1FF).astype(jnp.int32), 1)
    tid = jnp.arange(T, dtype=jnp.int32)[None, :]
    valid = tid < n_tokens[:, None]
    tok_len = jnp.where(valid, tok_len, 0)
    starts = jnp.cumsum(tok_len, axis=1) - tok_len          # exclusive

    # per-byte token id: scatter 1 at each token start (zero-length pads
    # land in a sacrificial extra column), cumsum, -1
    scat = jnp.where((tok_len > 0) & valid, starts, P)
    marks = jnp.zeros((B, P + 1), jnp.int32).at[
        jnp.arange(B, dtype=jnp.int32)[:, None], scat].add(1)
    tok_of_byte = jnp.cumsum(marks[:, :P], axis=1) - 1
    tok_of_byte = jnp.clip(tok_of_byte, 0, T - 1)

    w = jnp.take_along_axis(tokens, tok_of_byte, axis=1)    # token per byte
    pos = jnp.arange(P, dtype=jnp.int32)[None, :]
    byte_is_copy = (w >> 31).astype(jnp.int32)
    dist = (w & 0xFFFF).astype(jnp.int32) + 1
    src = jnp.where(byte_is_copy == 1, pos - dist, pos)
    src = jnp.clip(src, 0, P - 1)   # tokenizer guarantees dist <= position
    lit = jnp.where(byte_is_copy == 1, 0, w & 0xFF).astype(jnp.uint8)

    # pointer doubling until every byte points at its literal root; the
    # forest is acyclic (src[p] < p for copies) so this terminates in
    # <= ceil(log2(P)) rounds, far fewer for typical shallow chains
    def cond(c):
        return c[1]

    def body(c):
        s, _ = c
        s2 = jnp.take_along_axis(s, s, axis=1)
        return s2, jnp.any(s2 != s)

    src, _ = jax.lax.while_loop(cond, body, (src, jnp.bool_(True)))
    return jnp.take_along_axis(lit, src, axis=1)


def _pack_contiguous(blk_bytes: jax.Array, isize: jax.Array
                     ) -> Tuple[jax.Array, jax.Array]:
    """[B, P] per-block bytes + [B] isize -> ([B*P] contiguous buffer,
    total) — the device-side slice/pack that replaced the per-block host
    copy loop.  Bytes past ``total`` are zero."""
    B, P = blk_bytes.shape
    iz = jnp.minimum(jnp.maximum(isize.astype(jnp.int32), 0), P)
    ubase = jnp.concatenate([jnp.zeros(1, jnp.int32), jnp.cumsum(iz)])
    total = ubase[B]
    L = B * P
    q = jnp.arange(L, dtype=jnp.int32)
    # block of output byte q: last block whose start is <= q (repeated
    # boundaries from empty blocks resolve to the owning block)
    blk = jnp.searchsorted(ubase[1:], q, side="right").astype(jnp.int32)
    blk = jnp.minimum(blk, B - 1)
    off = jnp.clip(q - ubase[blk], 0, P - 1)
    out = blk_bytes.reshape(-1)[blk * P + off]
    return jnp.where(q < total, out, jnp.uint8(0)), total


@jax.jit
def resolve_tokens_packed(tokens: jax.Array, n_tokens: jax.Array,
                          isize: jax.Array) -> jax.Array:
    """Resolve a token chunk and pack it contiguous on device:
    [B, P] u32 + [B] i32 + [B] i32 -> [B*P] u8 (junk past sum(isize) is
    zeroed).  ONE host copy per chunk replaces the per-block loop."""
    B, P = tokens.shape
    blk_bytes = resolve_tokens(tokens, n_tokens, P)
    buf, _ = _pack_contiguous(blk_bytes, isize)
    return buf


def _walk_records_device(buf: jax.Array, total: jax.Array,
                         start: jax.Array, stop: jax.Array, R: int
                         ) -> Tuple[jax.Array, jax.Array, jax.Array,
                                    jax.Array]:
    """On-device BAM record walk over a contiguous inflated buffer.

    The record chain (``offset[i+1] = offset[i] + 4 + block_size[i]``) is
    a linked list rooted at ``start``; instead of a serial host walk, the
    successor array is built for EVERY byte position and the reachable
    set is computed by pointer doubling — ``ceil(log2(n_records))``
    gather+scatter rounds, each fully parallel (the same log-depth trick
    the LZ77 resolve uses).

    Returns (offsets [R] i32 — record starts owned by [start, stop),
    n_all i32 — the UNCLAMPED owned count (> R flags a capacity fault),
    tail i32 — the first incomplete record's offset (== the walked end
    when every record completed), bad i32 — 1 when a reached record has
    an absurd block_size (< 32) with its size field fully readable: the
    malformed-chain corruption the host walkers raise on)."""
    L = buf.shape[0]
    pos = jnp.arange(L, dtype=jnp.int32)
    bufp = jnp.concatenate([buf, jnp.zeros(4, jnp.uint8)]).astype(jnp.uint32)
    bs = (bufp[:L] | (bufp[1:L + 1] << 8) | (bufp[2:L + 2] << 16)
          | (bufp[3:L + 3] << 24)).astype(jnp.int32)
    has_size = pos + 4 <= total
    # bs > L can only be a record cut at the buffer end (the host path
    # extends past and completes it — the driver's tail fixup does the
    # same), never followed on device; negative/absurd bs at a reached
    # position with a readable size field is corruption
    bs_ok = has_size & (bs >= 32) & (bs <= L)
    rec_end = pos + 4 + jnp.where(bs_ok, bs, 0)
    complete = bs_ok & (rec_end <= total)
    SINK = L
    nxt = jnp.where(complete, jnp.minimum(rec_end, L), SINK)
    jumps = jnp.concatenate([nxt, jnp.array([SINK], jnp.int32)])
    marks = jnp.zeros(L + 1, jnp.int32).at[jnp.minimum(start, L)].set(1)

    def cond(c):
        return c[2]

    def body(c):
        m, j, _ = c
        prop = jnp.zeros_like(m).at[j].max(m)
        m2 = jnp.maximum(m, prop)
        return m2, j[j], jnp.any(m2 != m)

    marks, _, _ = jax.lax.while_loop(cond, body,
                                     (marks, jumps, jnp.bool_(True)))
    started = marks[:L] == 1
    term = started & ~complete
    bad = jnp.any(term & has_size & (bs < 32)).astype(jnp.int32)
    tail = jnp.min(jnp.where(term, pos, total))
    kept = started & complete & (pos < stop)
    n_all = jnp.sum(kept.astype(jnp.int32))
    rank = jnp.cumsum(kept.astype(jnp.int32)) - 1
    tgt = jnp.where(kept & (rank < R), rank, R)   # R = sacrificial sink
    offs = jnp.zeros(R + 1, jnp.int32).at[tgt].max(pos)[:R]
    return offs, n_all, tail, bad


@jax.jit
def resolve_walk_fields(tokens: jax.Array, n_tokens: jax.Array,
                        isize: jax.Array, start: jax.Array,
                        stop: jax.Array):
    """The fused device decode step: resolve + contiguous pack + record
    walk + FIXED_FIELDS gather, all on device — the resolved bytes never
    leave the accelerator.

    Inputs: one chunk's [B, P] u32 tokens (T == P canonical pad), [B] i32
    token counts and per-block ISIZEs, and the chunk's record-walk window
    ``[start, stop)`` in inflated-buffer coordinates.

    Returns (cols, valid, n_all, tail, bad): ``cols`` is the
    ops/unpack_bam fixed-field column dict of the owned records (rows
    past ``valid`` hold junk gathered at offset 0 — the standard padding
    convention), ``n_all`` the unclamped owned-record count, ``tail`` the
    first incomplete record's offset, ``bad`` the malformed-chain flag.
    Static shape per (B, P) ladder rung; R derives from them."""
    B, P = tokens.shape
    R = records_cap(B, P)
    blk_bytes = resolve_tokens(tokens, n_tokens, P)
    buf, total = _pack_contiguous(blk_bytes, isize)
    offs, n_all, tail, bad = _walk_records_device(buf, total, start, stop, R)
    L = B * P
    idx = jnp.clip(
        offs[:, None] + jnp.arange(PREFIX, dtype=jnp.int32)[None, :],
        0, L - 1)
    tile = buf[idx]
    cols = unpack_fixed_fields_tile(tile)
    valid = jnp.arange(R, dtype=jnp.int32) < jnp.minimum(n_all, R)
    return cols, valid, n_all, tail, bad


# Per-record CIGAR word capacity of the serve-tile device walk.  Reads
# with more ops than this (ultra-long split alignments) make the whole
# chunk fall back to the host build — flagged via ``over``, never
# silently truncated, because end1 derived from a truncated CIGAR would
# be WRONG (a value fault, not a capacity fault).  64 ops covers >99.9%
# of real short/long-read alignments while keeping the gather tile
# [R, 64, 4] bytes.
DEVICE_TILE_CIGAR_CAP = 64


@functools.partial(jax.jit,
                   static_argnames=("max_len", "seq_stride", "qual_stride"))
def resolve_walk_payload(tokens: jax.Array, n_tokens: jax.Array,
                         isize: jax.Array, start: jax.Array,
                         stop: jax.Array, max_len: int, seq_stride: int,
                         qual_stride: int):
    """Device decode step for the variable-length payload family:
    resolve + pack + record walk + FIXED_FIELDS gather + segmented
    seq/qual extraction — the inflated bytes never leave the device.

    The variable-length sections are flattened by the same trick the
    record walk uses: the walk's pointer-doubling offsets give each
    record's start, the fixed columns give the per-record seq offset
    (``PREFIX + l_read_name + 4*n_cigar``), and one segmented gather per
    stream lifts the packed 4-bit bases and quals into the padded
    ``[R, stride]`` tiles ops/seq_pallas consumes (same stride/truncation
    convention as the host packer ``decode_span_payload_host``).

    Returns (cols, seq, qual, valid, n_all, tail, bad); ``bad`` also
    folds in the payload-bounds fault the host walker raises as
    ``ValueError("malformed BAM record chain")`` — a record whose seq or
    qual section overruns its own block_size."""
    B, P = tokens.shape
    R = records_cap(B, P)
    blk_bytes = resolve_tokens(tokens, n_tokens, P)
    buf, total = _pack_contiguous(blk_bytes, isize)
    offs, n_all, tail, bad = _walk_records_device(buf, total, start, stop, R)
    L = B * P
    idx = jnp.clip(
        offs[:, None] + jnp.arange(PREFIX, dtype=jnp.int32)[None, :],
        0, L - 1)
    cols = unpack_fixed_fields_tile(buf[idx])
    valid = jnp.arange(R, dtype=jnp.int32) < jnp.minimum(n_all, R)
    l_seq = cols["l_seq"]
    seq_off = offs + PREFIX + cols["l_read_name"] + 4 * cols["n_cigar"]
    nb = (jnp.maximum(l_seq, 0) + 1) // 2
    pay_bad = valid & (
        (l_seq < 0)
        | ((seq_off - offs) + nb + jnp.maximum(l_seq, 0)
           > 4 + cols["block_size"]))
    bad = jnp.maximum(bad, jnp.any(pay_bad).astype(jnp.int32))
    use = jnp.where(valid, jnp.clip(l_seq, 0, max_len), 0)
    half = (use + 1) // 2
    js = jnp.arange(seq_stride, dtype=jnp.int32)[None, :]
    seq = jnp.where(
        js < half[:, None],
        buf[jnp.clip(seq_off[:, None] + js, 0, L - 1)], jnp.uint8(0))
    jq = jnp.arange(qual_stride, dtype=jnp.int32)[None, :]
    qual = jnp.where(
        jq < use[:, None],
        buf[jnp.clip(seq_off[:, None] + nb[:, None] + jq, 0, L - 1)],
        jnp.uint8(0))
    return cols, seq, qual, valid, n_all, tail, bad


@functools.partial(jax.jit, static_argnames=("cigar_cap",))
def resolve_walk_intervals(tokens: jax.Array, n_tokens: jax.Array,
                           isize: jax.Array, start: jax.Array,
                           stop: jax.Array,
                           cigar_cap: int = DEVICE_TILE_CIGAR_CAP):
    """Device decode step for the serve-tile family: resolve + pack +
    record walk + the (rid, pos1, end1) interval columns the tile filter
    consumes, with end1 derived from an on-device CIGAR walk.

    Mirrors the host chunk decode (query/engine._decode_bam_chunk +
    formats/bam.BamBatch.reference_span): reference span sums the op
    lengths of M/D/N/=/X ops; '*'-CIGAR records fall back to l_seq;
    pos1/end1 are 1-based and clamped to int32 max.  Records with more
    than ``cigar_cap`` CIGAR ops raise the ``over`` flag — the driver
    falls back to the host build for the whole chunk rather than serve a
    wrong end1.

    Returns (rid, pos1, end1, n_all, tail, bad, over); rows past the
    owned count hold the tile pad values (rid -1, pos1/end1 0)."""
    B, P = tokens.shape
    R = records_cap(B, P)
    blk_bytes = resolve_tokens(tokens, n_tokens, P)
    buf, total = _pack_contiguous(blk_bytes, isize)
    offs, n_all, tail, bad = _walk_records_device(buf, total, start, stop, R)
    L = B * P
    idx = jnp.clip(
        offs[:, None] + jnp.arange(PREFIX, dtype=jnp.int32)[None, :],
        0, L - 1)
    cols = unpack_fixed_fields_tile(buf[idx])
    valid = jnp.arange(R, dtype=jnp.int32) < jnp.minimum(n_all, R)
    n_cigar = cols["n_cigar"]
    l_seq = cols["l_seq"]
    over = jnp.any(valid & (n_cigar > cigar_cap)).astype(jnp.int32)
    cig_off = offs + PREFIX + cols["l_read_name"]
    k = jnp.arange(cigar_cap, dtype=jnp.int32)[None, :]
    widx = cig_off[:, None] + 4 * k
    b0 = buf[jnp.clip(widx, 0, L - 1)].astype(jnp.uint32)
    b1 = buf[jnp.clip(widx + 1, 0, L - 1)].astype(jnp.uint32)
    b2 = buf[jnp.clip(widx + 2, 0, L - 1)].astype(jnp.uint32)
    b3 = buf[jnp.clip(widx + 3, 0, L - 1)].astype(jnp.uint32)
    word = b0 | (b1 << 8) | (b2 << 16) | (b3 << 24)
    op = (word & 0xF).astype(jnp.int32)
    oplen = (word >> 4).astype(jnp.int32)
    consumes = ((op == 0) | (op == 2) | (op == 3) | (op == 7) | (op == 8))
    act = k < jnp.minimum(n_cigar, cigar_cap)[:, None]
    cig_span = jnp.sum(jnp.where(act & consumes, oplen, 0), axis=1)
    ref_span = jnp.where(n_cigar > 0, cig_span, jnp.maximum(l_seq, 0))
    imax = jnp.int32(2**31 - 1)
    pos1 = jnp.minimum(cols["pos"], imax - 1) + 1
    end1 = pos1 + jnp.minimum(jnp.maximum(ref_span, 1) - 1, imax - pos1)
    rid = jnp.where(valid, cols["refid"], -1)
    pos1 = jnp.where(valid, pos1, 0)
    end1 = jnp.where(valid, end1, 0)
    return rid, pos1, end1, n_all, tail, bad, over


@jax.jit
def variant_prefix_device(buf: jax.Array, starts: jax.Array):
    """BCF fixed-prefix gather riding a resolved-bytes device buffer:
    [L] u8 + [R] i32 record starts -> (chrom [R] i32, pos [R] i32,
    1-based).  The same little-endian assembly formats/bcf_columns
    applies to bytes 8..32 of each record (the 24-byte core after the
    two length words); rows whose start is a pad (< 0) gather at 0 and
    are masked by the caller's valid count."""
    L = buf.shape[0]
    idx = jnp.clip(
        starts[:, None] + jnp.arange(8, 32, dtype=jnp.int32)[None, :],
        0, L - 1)
    tile = buf[idx].astype(jnp.uint32)

    def _i32(o):
        return (tile[:, o] | (tile[:, o + 1] << 8) | (tile[:, o + 2] << 16)
                | (tile[:, o + 3] << 24)).astype(jnp.int32)

    return _i32(0), _i32(4) + 1


@functools.partial(jax.jit,
                   static_argnames=("width", "count", "n_sample"))
def variant_gt_dosage_device(buf: jax.Array, gt_off: jax.Array,
                             width: int, count: int, n_sample: int):
    """Grouped GT gather -> per-sample ALT dosage for one (int width,
    ploidy, n_sample) combo, on device: [L] u8 buffer + [R2] i32 GT data
    offsets -> [R2, n_sample] i8 dosage.

    Byte-for-byte the formats/bcf_columns._decode_columns GT semantics:
    little-endian sign-extended ints, END_OF_VECTOR sentinel trims
    ploidy, any MISSING allele (or allele value 0) makes the call
    missing (-1), otherwise dosage = count of ALT alleles, saturated at
    127.  One jit entry per combo — combos are a property of the file's
    FORMAT layout, stable across spans."""
    L = buf.shape[0]
    R2 = gt_off.shape[0]
    nbytes = width * count * n_sample
    idx = jnp.clip(
        gt_off[:, None] + jnp.arange(nbytes, dtype=jnp.int32)[None, :],
        0, L - 1)
    raw = buf[idx].astype(jnp.uint32).reshape(R2, n_sample, count, width)
    shifts = (jnp.arange(width, dtype=jnp.uint32) * 8)[None, None, None, :]
    w = jnp.sum(raw << shifts, axis=-1, dtype=jnp.uint32)
    if width < 4:
        sbit = jnp.uint32(1 << (8 * width - 1))
        w = w & jnp.uint32((1 << (8 * width)) - 1)
        g = (w ^ sbit).astype(jnp.int32) - sbit.astype(jnp.int32)
    else:
        g = w.astype(jnp.int32)
    missing_val = -(1 << (8 * width - 1))
    present = g != (missing_val + 1)          # END_OF_VECTOR sentinel
    miss = present & (((g >> 1) == 0) | (g == missing_val))
    alt = present & (((g >> 1) - 1) > 0)
    d = jnp.where(
        jnp.any(present, axis=2) & ~jnp.any(miss, axis=2),
        jnp.sum(alt.astype(jnp.int32), axis=2), -1)
    return jnp.minimum(d, 127).astype(jnp.int8)


def inflate_span_device(raw: bytes, table: Optional[dict] = None,
                        chunk: int = 64, n_threads: int = 0,
                        check_crc: bool = False
                        ) -> Tuple[np.ndarray, np.ndarray]:
    """Inflate a BGZF span with host Huffman tokenize + device LZ77 resolve.

    Same contract as ops.inflate.inflate_span: returns (contiguous
    inflated bytes, per-block starting offsets).  ``check_crc`` verifies
    every block's BGZF CRC32 footer against a CRC folded into the native
    tokenize pass (no separate host inflate sweep), raising the same
    ``BGZFError`` the host paths raise."""
    from hadoop_bam_tpu.ops.inflate import block_table, footer_crcs
    if table is None:
        table = block_table(raw)
    if not native.available():
        # PLAN class: selecting the device backend without the native
        # library is a configuration fault — classify_error must not
        # treat it as transient (old RuntimeError fell through to the
        # generic CORRUPT bucket; retrying could never heal it either)
        from hadoop_bam_tpu.utils.errors import PlanError
        raise PlanError(
            "device inflate needs the native tokenizer "
            "(hbam_deflate_tokenize_batch); native library unavailable")
    isize = table["isize"]
    n = isize.size
    ubase = np.zeros(n + 1, dtype=np.int64)
    np.cumsum(isize, out=ubase[1:])
    dst = np.empty(int(ubase[-1]), dtype=np.uint8)
    src = np.frombuffer(raw, dtype=np.uint8)
    expect = footer_crcs(src, table) if check_crc else None

    for lo in range(0, n, chunk):
        # chaos point at the library-level device step: injected faults
        # here hit the same plane boundary the pipeline's dispatch-level
        # device.step point covers, for callers that use this entry
        # directly (chunk index rides along for schedule targeting)
        chaos.fire("device.step", chunk_lo=lo)
        hi = min(lo + chunk, n)
        sub_isize = isize[lo:hi]
        # canonical (B, T, P): P snaps to the ladder (not the chunk's own
        # pow2 — mixed spans then share one jit entry per rung), the
        # token axis pads to P, B to a pow2 row count
        P = ladder_pow2(max(16, int(sub_isize.max())) if hi > lo else 16)
        b_cap = _round_pow2(hi - lo, 8)
        try:
            out = native.deflate_tokenize_batch(
                src, table["cdata_off"][lo:hi], table["cdata_len"][lo:hi],
                P, n_threads, with_crc=check_crc)
        except ValueError as e:
            # same class as the host backends: bad DEFLATE bytes are a
            # BGZF-level corruption whichever plane finds them
            raise bgzf.BGZFError(str(e)) from e
        tokens, n_tokens, out_lens = out[:3]
        if not np.array_equal(out_lens, sub_isize):
            bad = int(np.nonzero(out_lens != sub_isize)[0][0])
            raise bgzf.BGZFError(
                f"ISIZE mismatch in block {lo + bad}: tokenized "
                f"{int(out_lens[bad])}, footer says {int(sub_isize[bad])}")
        if check_crc:
            mism = np.nonzero(out[3] != expect[lo:hi])[0]
            if mism.size:
                raise bgzf.BGZFError(
                    f"CRC32 mismatch in block(s) "
                    f"{(mism[:8] + lo).tolist()}")
        if b_cap != hi - lo:
            tokens = np.vstack(
                [tokens, np.zeros((b_cap - (hi - lo), P), np.uint32)])
            n_tokens = np.concatenate(
                [n_tokens, np.zeros(b_cap - (hi - lo), np.int32)])
        iz_pad = np.zeros(b_cap, dtype=np.int32)
        iz_pad[: hi - lo] = sub_isize
        # device-side slice/pack: the resolve output comes back as ONE
        # contiguous chunk buffer (a single host copy per chunk) instead
        # of the old per-block copy loop
        out_bytes = np.asarray(resolve_tokens_packed(
            jnp.asarray(tokens), jnp.asarray(n_tokens),
            jnp.asarray(iz_pad)))
        dst[int(ubase[lo]):int(ubase[hi])] = \
            out_bytes[: int(ubase[hi] - ubase[lo])]
    return dst, ubase[:-1]


# ---------------------------------------------------------------------------
# Plane selection probe (config.resolve_inflate_backend's "auto" input)
# ---------------------------------------------------------------------------

def probe_device_plane(payload_bytes: int = 1 << 16,
                       force: bool = False) -> dict:
    """Measure once whether the device decode plane can beat fused-native
    host inflate on THIS process's default device.

    The plane's steady-state wall is ``max(tokenize, resolve)`` (the two
    stages overlap); fused-native pays the full host inflate.  The probe
    times both halves on one synthetic 64 KiB block and reports the
    decision.  On the CPU backend the answer is forced to host (the
    device plane cannot beat host inflate when the "device" IS the host
    CPU running XLA) unless ``force`` — which tests use to exercise the
    probe mechanics."""
    import time
    import zlib

    out = {"device_wins": False, "tokenize_s": None, "resolve_s": None,
           "inflate_s": None,
           "backend": jax.default_backend()}
    if not native.available():
        return out
    if jax.default_backend() == "cpu" and not force:
        return out
    rng = np.random.RandomState(0)
    data = rng.choice(np.frombuffer(b"ACGT", np.uint8),
                      size=payload_bytes).tobytes()
    co = zlib.compressobj(6, zlib.DEFLATED, -15)
    comp = co.compress(data) + co.flush()
    src = np.frombuffer(comp, np.uint8)
    off = np.array([0], np.int64)
    ln = np.array([len(comp)], np.int32)
    P = ladder_pow2(len(data))

    from hadoop_bam_tpu.utils.metrics import METRICS

    def timeit(fn, label, reps=3):
        fn()                      # warmup (jit compile / page-in)
        best = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            fn()
            best = min(best, time.perf_counter() - t0)
        # probe measurements feed the metrics layer, so the once-per-
        # process plane decision is visible in traces and snapshots
        METRICS.observe(f"pipeline.plane_probe_{label}", best)
        return best

    toks, nt, _ = native.deflate_tokenize_batch(src, off, ln, P, 1)
    toks_d = jnp.asarray(toks)
    nt_d = jnp.asarray(nt)
    out["tokenize_s"] = timeit(
        lambda: native.deflate_tokenize_batch(src, off, ln, P, 1),
        "tokenize_s")
    out["resolve_s"] = timeit(
        lambda: resolve_tokens(toks_d, nt_d, P).block_until_ready(),
        "resolve_s")
    # the host baseline must be the plane the device actually competes
    # with: the NATIVE batched inflate (libdeflate when built in, ~2x
    # Python zlib) — benchmarking zlib here would systematically
    # overestimate host cost and mis-pick the device plane
    dst = np.empty(len(data), dtype=np.uint8)
    dst_off = np.zeros(1, np.int64)
    isz = np.array([len(data)], np.int32)
    out["inflate_s"] = timeit(
        lambda: native.inflate_batch(src, off, ln, dst, dst_off, isz, 1),
        "inflate_s")
    out["device_wins"] = (max(out["tokenize_s"], out["resolve_s"])
                          < out["inflate_s"])
    METRICS.count("pipeline.plane_probe_device_wins",
                  int(out["device_wins"]))
    return out
