"""Device kernels (JAX/jnp + Pallas): the compute the reference hid in JNI.

In Hadoop-BAM, the per-record hot loop (SURVEY.md section 3.2) bottoms out in
htsjdk ``BAMRecordCodec.decode`` and zlib-over-JNI inflate.  Here that work is
reshaped for the TPU:

- record *boundary discovery* stays on the host (serial block_size chaining;
  C++ native path) — it is O(records) pointer-walking, not FLOPs;
- record *field unpack* becomes a fixed-shape batched gather on device
  (unpack_bam.py), emitting SoA columns;
- sequence/quality decode, flagstat-style reductions, and tokenization are
  vectorized device ops;
- BGZF inflate is dispatched (inflate.py): host zlib, native C++
  multithreaded, or the experimental on-device path.

All jittable entry points take static shapes (capacity + count scalars) so XLA
traces once and the same compiled step serves every span batch.
"""
from hadoop_bam_tpu.ops.unpack_bam import unpack_fixed_fields, FIXED_FIELDS  # noqa: F401
from hadoop_bam_tpu.ops.flagstat import flagstat_from_columns, FLAGSTAT_FIELDS  # noqa: F401
