"""Batched BAM record field unpack: bytes + offsets -> SoA columns, on device.

The device-side replacement for htsjdk ``BAMRecordCodec.decode``'s per-record
field parse (the hot loop of hb/BAMRecordReader.java, SURVEY.md section 3.2).
Input is the inflated span bytes (uint8, padded to a static capacity) and the
record start offsets (int32, padded); output is one int32 column per fixed
field [SPEC record layout, formats/bam.py docstring].

Two implementations with identical semantics:

- ``unpack_fixed_fields``: pure jnp.  The single gather
  ``data[offsets[:, None] + arange(36)]`` pulls each record's fixed 36-byte
  prefix into an [N, 36] tile; field extraction is then fused elementwise
  arithmetic.  XLA lowers this well on TPU and it is the default.
- ``unpack_fixed_fields_pallas``: Pallas kernel tiling the offset vector, with
  the span bytes resident in VMEM; useful when fusing unpack with downstream
  per-record compute in one kernel.

Padding convention: offsets[i] for i >= n_records MUST point at valid bytes
(use 0); consumers mask with ``valid = arange(N) < n_records``.
"""
from __future__ import annotations

import functools
from typing import Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np

# column name -> (byte offset in record, byte width, signed)
FIXED_FIELDS: Dict[str, Tuple[int, int, bool]] = {
    "block_size": (0, 4, True),
    "refid": (4, 4, True),
    "pos": (8, 4, True),
    "l_read_name": (12, 1, False),
    "mapq": (13, 1, False),
    "bin": (14, 2, False),
    "n_cigar": (16, 2, False),
    "flag": (18, 2, False),
    "l_seq": (20, 4, True),
    "mate_refid": (24, 4, True),
    "mate_pos": (28, 4, True),
    "tlen": (32, 4, True),
}

PREFIX = 36

ALL_FIELDS: Tuple[str, ...] = tuple(FIXED_FIELDS)

# Pushdown projection for flagstat: only the columns the reduction reads
# cross the host->device link (11 bytes/record instead of 36).
FLAGSTAT_PROJECTION: Tuple[str, ...] = ("flag", "refid", "mate_refid", "mapq")


def projection_row_bytes(fields: Tuple[str, ...]) -> int:
    return sum(FIXED_FIELDS[name][1] for name in fields)


def projection_ranges(fields: Tuple[str, ...]) -> "list[tuple[int, int]]":
    """(src_offset, length) copy ranges for the host row packer, with
    adjacent source ranges merged (the full-field projection collapses to a
    single 36-byte memcpy)."""
    ranges: list[tuple[int, int]] = []
    for name in fields:
        off, width, _ = FIXED_FIELDS[name]
        if ranges and ranges[-1][0] + ranges[-1][1] == off:
            ranges[-1] = (ranges[-1][0], ranges[-1][1] + width)
        else:
            ranges.append((off, width))
    return ranges


def unpack_projected_tile(tile: jnp.ndarray, fields: Tuple[str, ...]
                          ) -> Dict[str, jnp.ndarray]:
    """tile: [N, row_bytes] uint8, rows packed per ``fields`` order ->
    dict of int32 columns (fused elementwise, no gather)."""
    t = tile.astype(jnp.uint32)
    out: Dict[str, jnp.ndarray] = {}
    off = 0
    for name in fields:
        _, width, _signed = FIXED_FIELDS[name]
        acc = t[:, off]
        for k in range(1, width):
            acc = acc | (t[:, off + k] << (8 * k))
        out[name] = acc.astype(jnp.int32)
        off += width
    return out


def unpack_fixed_fields_tile(tile: jnp.ndarray) -> Dict[str, jnp.ndarray]:
    """tile: [N, 36] uint8 -> dict of int32 columns (fused elementwise).

    The dense-tile entry point: when the host packs each record's 36-byte
    fixed prefix contiguously (the columnar transfer layout — ~20x fewer
    bytes over the interconnect than shipping whole inflated spans), field
    extraction is pure strided slicing, no gather at all.  The fixed prefix
    is exactly the all-fields projection: FIXED_FIELDS covers bytes 0..35
    contiguously in declaration order."""
    return unpack_projected_tile(tile, ALL_FIELDS)


@jax.jit
def unpack_fixed_fields(data: jnp.ndarray, offsets: jnp.ndarray
                        ) -> Dict[str, jnp.ndarray]:
    """data: uint8 [D]; offsets: int32 [N] (padded with safe offsets).
    Returns dict of int32 [N] columns for every fixed field."""
    idx = offsets[:, None] + jnp.arange(PREFIX, dtype=offsets.dtype)[None, :]
    tile = data[idx]  # [N, 36] uint8 gather
    return unpack_fixed_fields_tile(tile)


def unpack_fixed_fields_pallas(data: jnp.ndarray, offsets: jnp.ndarray,
                               block_n: int = 1024) -> Dict[str, jnp.ndarray]:
    """Pallas variant: grid over offset tiles; span bytes stay in ANY/HBM and
    each tile gathers through dynamic indexing.

    Note: on TPU, arbitrary-offset gathers inside a kernel serialize through
    scalar loads, so this variant mainly exists as the fusion point for
    later kernels (unpack + filter + reduce in one pass); the jnp gather above
    is the throughput path today."""
    from jax.experimental import pallas as pl

    n = offsets.shape[0]
    assert n % block_n == 0, "pad offsets to a multiple of block_n"

    def kernel(data_ref, offs_ref, *out_refs):
        offs = offs_ref[:]  # [block_n]
        idx = offs[:, None] + jax.lax.broadcasted_iota(
            jnp.int32, (block_n, PREFIX), 1)
        tile = data_ref[idx]
        cols = unpack_fixed_fields_tile(tile)
        for ref, name in zip(out_refs, FIXED_FIELDS):
            ref[:] = cols[name]

    out_shapes = tuple(jax.ShapeDtypeStruct((n,), jnp.int32)
                       for _ in FIXED_FIELDS)
    outs = pl.pallas_call(
        kernel,
        grid=(n // block_n,),
        in_specs=[
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec((block_n,), lambda i: (i,)),
        ],
        out_specs=tuple(pl.BlockSpec((block_n,), lambda i: (i,))
                        for _ in FIXED_FIELDS),
        out_shape=out_shapes,
        interpret=jax.default_backend() == "cpu",
    )(data, offsets)
    return dict(zip(FIXED_FIELDS, outs))


@jax.jit
def gather_record_windows(data: jnp.ndarray, offsets: jnp.ndarray,
                          window: int) -> jnp.ndarray:
    """Gather a fixed-size byte window per record (for payload-stage kernels:
    names, cigar, seq).  Returns uint8 [N, window]."""
    idx = offsets[:, None] + jnp.arange(window, dtype=offsets.dtype)[None, :]
    idx = jnp.minimum(idx, data.shape[0] - 1)
    return data[idx]


def pad_offsets(offsets: np.ndarray, capacity: int) -> Tuple[np.ndarray, int]:
    """Host helper: pad an offsets vector to ``capacity`` with zeros."""
    n = int(offsets.size)
    if n > capacity:
        raise ValueError(f"{n} records exceed capacity {capacity}")
    out = np.zeros(capacity, dtype=np.int32)
    out[:n] = offsets
    return out, n


def pad_data(data: np.ndarray, capacity: int) -> np.ndarray:
    """Host helper: pad span bytes to ``capacity`` (static shape for jit)."""
    if data.size > capacity:
        raise ValueError(f"{data.size} bytes exceed capacity {capacity}")
    out = np.zeros(capacity, dtype=np.uint8)
    out[:data.size] = data
    return out
