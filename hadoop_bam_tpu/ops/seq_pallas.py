"""Pallas TPU kernels over packed sequence/quality payload tiles.

The device side of the tensor-batch feed: the host packs each read's 4-bit
encoded bases (2/byte [SPEC section 4.2.3 seq encoding]) and quality bytes
into fixed-stride tiles (native hbam_walk_bam_payload); these kernels unpack
and reduce them entirely in VMEM — one pass, no [N, L] base matrix ever
materialised in HBM for the stats path.

In the reference universe this work does not exist as device compute at all:
per-base access went through htsjdk ``SAMRecord.getReadBases()`` on the JVM
heap (hb/SAMRecordWritable.java consumers).  Here it is the framework's
showcase of intra-record parallelism: VPU lanes process 2 bases/byte across
a whole record tile per grid step.

Nibble convention [SPEC]: the FIRST base of a pair sits in the HIGH nibble.
Codes: 0='=', 1=A, 2=C, 4=G, 8=T, 15=N (4-bit IUPAC subset).
"""
from __future__ import annotations

import functools
from typing import Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

N_CODES = 16

# GC bases: C=2, G=4 (canonical); S (C|G ambiguity) = 6 also counts as GC.
_GC_CODES = (2, 4, 6)


def _interpret() -> bool:
    # any non-TPU target takes the non-Mosaic path (plain-XLA twin, or
    # the Pallas interpreter under force_pallas)
    return jax.default_backend() != "tpu"


def _is_gc(c):
    """GC membership as an explicit compare-or chain — shared by the
    kernel and its plain-XLA twin so the code set cannot drift
    (jnp.isin does not lower inside Pallas)."""
    m = c == _GC_CODES[0]
    for code in _GC_CODES[1:]:
        m = m | (c == code)
    return m


def _seq_stats_kernel(seq_ref, qual_ref, len_ref,
                      gc_ref, mq_ref, hist_ref):
    """One record tile: [TN, SB] packed bases + [TN, QB] quals + [TN, 1]
    lengths -> per-record GC fraction and mean quality, plus a global
    base-code histogram accumulated across the (sequential) TPU grid."""
    i = pl.program_id(0)
    # widen before bit ops: Mosaic cannot legalize shifts on i8 vectors
    seq = seq_ref[:].astype(jnp.int32)
    ln = len_ref[:]                                   # [TN, 1] int32
    hi = seq >> 4                                     # base 2j
    lo = seq & 0xF                                    # base 2j + 1
    jidx = jax.lax.broadcasted_iota(jnp.int32, seq.shape, 1)
    hi_valid = (2 * jidx) < ln
    lo_valid = (2 * jidx + 1) < ln

    denom = jnp.maximum(ln[:, 0], 1).astype(jnp.float32)
    gc_hi = _is_gc(hi) & hi_valid
    gc_lo = _is_gc(lo) & lo_valid
    gc = (gc_hi.sum(axis=1) + gc_lo.sum(axis=1)).astype(jnp.float32)
    gc_ref[:] = (gc / denom)[:, None]

    # Mosaic has no direct u8 -> f32 cast; widen to i32 first
    qual = qual_ref[:].astype(jnp.int32).astype(jnp.float32)
    qidx = jax.lax.broadcasted_iota(jnp.int32, qual.shape, 1)
    qmask = (qidx < ln).astype(jnp.float32)
    mq_ref[:] = ((qual * qmask).sum(axis=1) / denom)[:, None]

    counts = []
    for code in range(N_CODES):
        c = ((hi == code) & hi_valid).sum() + ((lo == code) & lo_valid).sum()
        counts.append(c)
    # i32, not f32: float accumulation loses integer precision past 2^24
    # (one 150bp x 112k-read tile already exceeds 16.7M bases)
    hist = jnp.stack(counts).astype(jnp.int32)[None, :]  # [1, 16]

    @pl.when(i == 0)
    def _init():
        hist_ref[:] = jnp.zeros_like(hist_ref)

    hist_ref[:] += hist


def _seq_stats_jnp(seq_tile: jnp.ndarray, qual_tile: jnp.ndarray,
                   lengths: jnp.ndarray) -> Dict[str, jnp.ndarray]:
    """Plain-XLA twin of _seq_stats_kernel — same math, no Pallas.

    On non-TPU platforms the Pallas interpreter executes the kernel
    block-by-block in Python (~2 s per 64k-read tile on one CPU core,
    the dominant cost of the CPU FASTQ/seq-stats rows and the bench
    scaling children); XLA:CPU compiles this version natively.  The TPU
    path keeps the fused kernel (bases never materialize in HBM)."""
    seq = seq_tile.astype(jnp.int32)
    ln = lengths[:, None]
    hi = seq >> 4
    lo = seq & 0xF
    jidx = jnp.arange(seq.shape[1], dtype=jnp.int32)[None, :]
    hi_valid = (2 * jidx) < ln
    lo_valid = (2 * jidx + 1) < ln

    denom = jnp.maximum(lengths, 1).astype(jnp.float32)
    gc = ((_is_gc(hi) & hi_valid).sum(axis=1)
          + (_is_gc(lo) & lo_valid).sum(axis=1)).astype(jnp.float32)
    qual = qual_tile.astype(jnp.int32).astype(jnp.float32)
    qidx = jnp.arange(qual_tile.shape[1], dtype=jnp.int32)[None, :]
    qmask = (qidx < ln).astype(jnp.float32)
    mq = (qual * qmask).sum(axis=1) / denom
    # scatter-add histogram: two passes over the tile instead of the
    # kernel's 16 per-code masked sums (XLA:CPU doesn't fuse those away)
    hist = (jnp.zeros(N_CODES, jnp.int32)
            .at[hi.ravel()].add(hi_valid.ravel().astype(jnp.int32))
            .at[lo.ravel()].add(lo_valid.ravel().astype(jnp.int32)))
    return {"gc": gc / denom, "mean_qual": mq, "base_hist": hist}


@functools.partial(jax.jit,
                   static_argnames=("block_n", "interpret",
                                    "force_pallas"))
def seq_qual_stats(seq_tile: jnp.ndarray, qual_tile: jnp.ndarray,
                   lengths: jnp.ndarray, block_n: int = 256,
                   interpret: bool | None = None,
                   force_pallas: bool = False
                   ) -> Dict[str, jnp.ndarray]:
    """Fused per-read stats over packed payload tiles.

    seq_tile: [N, SB] uint8, 2 bases/byte; qual_tile: [N, QB] uint8;
    lengths: [N] int32 (0 for padding rows — they contribute nothing).
    N must be a multiple of block_n.  Returns {"gc": [N] f32,
    "mean_qual": [N] f32, "base_hist": [16] i32}.

    ``interpret``: the computation targets a non-TPU device.  None =
    infer from the default backend — pass it explicitly when placing
    the computation on devices that are not the default backend (e.g. a
    virtual CPU mesh under a TPU-default process).  Non-TPU targets use
    the plain-XLA twin (_seq_stats_jnp) instead of the Pallas
    interpreter; ``force_pallas`` keeps the kernel itself testable on
    CPU via the interpreter.
    """
    n = seq_tile.shape[0]
    assert n % block_n == 0, (n, block_n)
    grid = n // block_n
    if interpret is None:
        interpret = _interpret()
    if interpret and not force_pallas:
        return _seq_stats_jnp(seq_tile, qual_tile, lengths)
    gc, mq, hist = pl.pallas_call(
        _seq_stats_kernel,
        grid=(grid,),
        in_specs=[
            pl.BlockSpec((block_n, seq_tile.shape[1]), lambda i: (i, 0)),
            pl.BlockSpec((block_n, qual_tile.shape[1]), lambda i: (i, 0)),
            pl.BlockSpec((block_n, 1), lambda i: (i, 0)),
        ],
        out_specs=(
            pl.BlockSpec((block_n, 1), lambda i: (i, 0)),
            pl.BlockSpec((block_n, 1), lambda i: (i, 0)),
            pl.BlockSpec((1, N_CODES), lambda i: (0, 0)),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((n, 1), jnp.float32),
            jax.ShapeDtypeStruct((n, 1), jnp.float32),
            jax.ShapeDtypeStruct((1, N_CODES), jnp.int32),
        ),
        interpret=interpret,
    )(seq_tile, qual_tile, lengths[:, None])
    return {"gc": gc[:, 0], "mean_qual": mq[:, 0], "base_hist": hist[0]}


@functools.partial(jax.jit, static_argnames=("max_len",))
def unpack_bases(seq_tile: jnp.ndarray, max_len: int | None = None
                 ) -> jnp.ndarray:
    """[N, SB] packed nibbles -> [N, 2*SB] base codes (uint8), high nibble
    first [SPEC].  Plain XLA — interleave is a reshape, and downstream
    one-hot/embedding fuses with it; the Pallas path is for fused stats."""
    hi = seq_tile >> 4
    lo = seq_tile & 0xF
    codes = jnp.stack([hi, lo], axis=-1).reshape(seq_tile.shape[0], -1)
    if max_len is not None:
        codes = codes[:, :max_len]
    return codes


# host-side reference implementations (test oracles, NumPy)

def seq_qual_stats_host(seq_tile: np.ndarray, qual_tile: np.ndarray,
                        lengths: np.ndarray) -> Dict[str, np.ndarray]:
    n = seq_tile.shape[0]
    gc = np.zeros(n, dtype=np.float32)
    mq = np.zeros(n, dtype=np.float32)
    hist = np.zeros(N_CODES, dtype=np.int64)
    for i in range(n):
        ln = int(lengths[i])
        packed = seq_tile[i]
        codes = np.empty(packed.size * 2, dtype=np.uint8)
        codes[0::2] = packed >> 4
        codes[1::2] = packed & 0xF
        codes = codes[:ln]
        denom = max(ln, 1)
        gc[i] = float(np.isin(codes, _GC_CODES).sum()) / denom
        mq[i] = float(qual_tile[i, :ln].astype(np.float64).sum()) / denom
        for c in codes:
            hist[c] += 1
    return {"gc": gc, "mean_qual": mq, "base_hist": hist}
