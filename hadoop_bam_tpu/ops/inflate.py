"""Batched BGZF inflate dispatch: the framework's replacement for the
per-block zlib-over-JNI inflate in the reference hot loop (SURVEY.md 3.2).

Paths, in preference order:

- ``native``: C++ multithreaded zlib over all blocks of a span at once
  (native/hbam_native.cpp) — the production host path feeding device batches.
- ``zlib``: Python zlib per block (portable fallback, still batched at the
  span level).
- ``device``: two-stage device DEFLATE (ops/inflate_device.py) — host
  Huffman tokenize (native, threaded) + on-device LZ77 copy resolution by
  pointer doubling.  Measured, not default: the Huffman stage dominates
  inflate cost and is bit-serial, so the host stage bounds throughput; see
  BASELINE.md "Device DEFLATE" for the numbers.

All paths share one contract: given the raw compressed span bytes and the
parsed block table, produce a contiguous inflated buffer + per-block inflated
offsets.
"""
from __future__ import annotations

import zlib
from typing import Iterator, List, Optional, Sequence, Tuple

import numpy as np

from hadoop_bam_tpu.formats import bgzf
from hadoop_bam_tpu.utils import native


def block_table(raw: bytes, offset: int = 0) -> dict:
    """Parse consecutive BGZF block headers into a columnar table."""
    coffs, cdata_off, cdata_len, isize = [], [], [], []
    p = offset
    n = len(raw)
    while p < n:
        info = bgzf.parse_block_header(raw, p)
        coffs.append(info.coffset)
        cdata_off.append(info.cdata_offset)
        cdata_len.append(info.cdata_size)
        isize.append(info.isize)
        p = info.next_coffset
    return {
        "coffset": np.asarray(coffs, dtype=np.int64),
        "cdata_off": np.asarray(cdata_off, dtype=np.int64),
        "cdata_len": np.asarray(cdata_len, dtype=np.int32),
        "isize": np.asarray(isize, dtype=np.int32),
    }


def inflate_span(raw: bytes, table: Optional[dict] = None,
                 backend: str = "auto", n_threads: int = 0
                 ) -> Tuple[np.ndarray, np.ndarray]:
    """Inflate all blocks of a compressed span.

    Returns (data, ubase): ``data`` is the contiguous inflated bytes of the
    span; ``ubase[i]`` is each block's starting offset within ``data`` (the
    map from (block, in-block offset) to buffer offset — i.e. from virtual
    offsets to positions).
    """
    if table is None:
        table = block_table(raw)
    if backend == "device":
        from hadoop_bam_tpu.ops.inflate_device import inflate_span_device
        return inflate_span_device(raw, table, n_threads=n_threads)
    isize = table["isize"]
    ubase = np.zeros(isize.size + 1, dtype=np.int64)
    np.cumsum(isize, out=ubase[1:])
    total = int(ubase[-1])
    dst = np.empty(total, dtype=np.uint8)
    src = np.frombuffer(raw, dtype=np.uint8)

    if backend == "auto":
        backend = "native" if native.available() else "zlib"
    if backend == "native":
        try:
            native.inflate_batch(src, table["cdata_off"],
                                 table["cdata_len"], dst, ubase[:-1],
                                 isize, n_threads)
        except ValueError as e:
            # same class as the zlib backend and the fused path: bad
            # DEFLATE bytes are a BGZF-level corruption either way
            raise bgzf.BGZFError(str(e)) from e
    elif backend == "zlib":
        mv = memoryview(raw)
        for i in range(isize.size):
            o, l = int(table["cdata_off"][i]), int(table["cdata_len"][i])
            try:
                # decompress straight off the memoryview slice — the old
                # bytes(mv[...]) copy doubled this backend's allocation
                # traffic (one copy per block before zlib even ran)
                out = zlib.decompress(mv[o:o + l], wbits=-15)
            except zlib.error as e:
                # classified at the policy boundary: bad DEFLATE bytes are
                # deterministic corruption, not a retryable read fault
                raise bgzf.BGZFError(
                    f"corrupt DEFLATE payload in block {i}: {e}") from e
            if len(out) != int(isize[i]):
                raise bgzf.BGZFError(f"ISIZE mismatch in block {i}")
            dst[int(ubase[i]):int(ubase[i + 1])] = np.frombuffer(out, np.uint8)
    else:
        # PLAN class (still a ValueError): a bad backend name is run
        # configuration, not data — never retried, never quarantined
        from hadoop_bam_tpu.utils.errors import PlanError
        raise PlanError(f"unknown inflate backend {backend!r}")
    return dst, ubase[:-1]


def footer_crcs(src: np.ndarray, table: dict) -> np.ndarray:
    """Each block's expected CRC32, read from the BGZF footers (the CRC
    sits 8 bytes before each block end)."""
    foot = table["cdata_off"] + table["cdata_len"]
    return (src[foot].astype(np.uint32)
            | (src[foot + 1].astype(np.uint32) << 8)
            | (src[foot + 2].astype(np.uint32) << 16)
            | (src[foot + 3].astype(np.uint32) << 24))


def verify_crcs(raw: bytes, table: dict, data: np.ndarray,
                ubase: np.ndarray, n_threads: int = 0) -> None:
    """Validate every block's CRC32 footer against the inflated bytes
    (native batched CRC when available)."""
    n = table["isize"].size
    src = np.frombuffer(raw, dtype=np.uint8)
    expect = footer_crcs(src, table)
    if native.available():
        import ctypes
        lib = native.load()
        got = np.empty(n, dtype=np.uint32)
        lib.hbam_crc32_batch(
            data.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            ubase.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            table["isize"].ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            n, got.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
            n_threads if n_threads > 0 else 0 or 1)
    else:
        got = np.empty(n, dtype=np.uint32)
        for i in range(n):
            s, e = int(ubase[i]), int(ubase[i]) + int(table["isize"][i])
            got[i] = zlib.crc32(data[s:e].tobytes()) & 0xFFFFFFFF
    bad = np.nonzero(got != expect)[0]
    if bad.size:
        raise bgzf.BGZFError(f"CRC32 mismatch in block(s) {bad[:8].tolist()}")


def walk_records(data: np.ndarray, start: int = 0,
                 cap: Optional[int] = None) -> Tuple[np.ndarray, int]:
    """Record-boundary walk over inflated bytes: native when available,
    NumPy/Python otherwise.  Returns (offsets, tail_offset) where tail_offset
    is the first incomplete record's offset (== len when exact)."""
    if cap is None:
        # min on-wire record = 4-byte block_size + 32-byte fixed core (the
        # native walker accepts any bs >= 32), so count can never exceed //36
        cap = max(16, data.size // 36)
    if native.available():
        return native.walk_bam_records(np.ascontiguousarray(data), start, cap)
    from hadoop_bam_tpu.formats.bam import walk_record_offsets
    # walk straight over the array's buffer — the old data.tobytes() here
    # duplicated the whole inflated span per walk (DP701's founding case)
    offs = walk_record_offsets(np.ascontiguousarray(data), start=start)
    tail = int(offs[-1] + 4 + int.from_bytes(
        data[int(offs[-1]):int(offs[-1]) + 4].tobytes(), "little", signed=True)
        ) if offs.size else start
    return offs, tail


# ---------------------------------------------------------------------------
# Fused single-pass span decode (native/hbam_native.cpp hbam_fused_*)
# ---------------------------------------------------------------------------

def fused_available() -> bool:
    """True when the native fused decode entry points are loadable."""
    return native.available() and native.fused_available()


def _raise_fused_error(rc: int, index: int) -> None:
    """Map a fused-decode rc to the same exception CLASS the two-pass
    path raises for the identical corruption (the fuzz tests pin this):
    BGZF-level faults -> BGZFError, record-chain faults -> the CORRUPT
    taxonomy (the two-pass walkers' bare ValueError classifies the same
    way through classify_error)."""
    from hadoop_bam_tpu.utils.errors import CorruptDataError

    kind = -rc
    if kind == 1:
        raise bgzf.BGZFError(f"corrupt DEFLATE payload in block {index}")
    if kind == 2:
        raise bgzf.BGZFError(f"ISIZE mismatch in block {index}")
    if kind == 3:
        raise bgzf.BGZFError(f"CRC32 mismatch in block(s) [{index}]")
    if kind == 5:
        raise CorruptDataError(
            f"record count exceeds capacity at offset {index}")
    raise CorruptDataError("malformed BAM record chain")


class FusedSpanDecode:
    """One span's fused native inflate + walk + pack (+ CRC fold) job.

    Wraps ``utils/native.FusedJob`` with the span-level geometry: builds
    the inflated-offset table, sizes the packed outputs for the worst
    case, and exposes the decode as a stream of completed row chunks::

        dec = FusedSpanDecode(raw, table, start=s, stop=e, mode="rows",
                              sel=ranges, row_stride=w, check_crc=True)
        for lo, hi in dec.chunks():
            consume(dec.rows[lo:hi])          # packed while cache-hot
        n, tail = dec.finish()

    ``chunks()`` yields ``[row_lo, row_hi)`` ranges the moment the native
    walk publishes them — downstream tile packing starts before the
    span's tail blocks are even inflated.  After ``finish()``:
    ``data`` holds the fully inflated span, ``offsets[:n]`` the record
    starts, and the mode-specific outputs (``rows`` / ``prefix``+
    ``seq``+``qual``) their packed tiles.  Corruption raises the same
    ``BGZFError``/``ValueError`` the two-pass path raises; closing the
    stream early (generator abandoned) joins the native workers.

    Modes: ``"offsets"`` (walk only — callers packing variable-length
    series themselves), ``"rows"`` (fixed-prefix ``sel`` ranges packed
    into ``row_stride``-byte rows), ``"payload"`` (prefix/seq/qual tiles,
    ``hbam_walk_bam_payload`` layout)."""

    def __init__(self, raw: bytes, table: Optional[dict] = None, *,
                 start: int = 0, stop: Optional[int] = None,
                 mode: str = "offsets",
                 sel: Optional[Sequence[Tuple[int, int]]] = None,
                 row_stride: int = 0, max_len: int = 0, seq_stride: int = 0,
                 qual_stride: int = 0, check_crc: bool = False,
                 chunk_blocks: int = 32, n_threads: int = 0):
        if table is None:
            table = block_table(raw)
        isize = table["isize"]
        ubase = np.zeros(isize.size + 1, dtype=np.int64)
        np.cumsum(isize, out=ubase[1:])
        total = int(ubase[-1])
        self.data = np.empty(total, dtype=np.uint8)
        self.ubase = ubase[:-1]
        self.stop = total if stop is None else min(int(stop), total)
        self.rows = self.prefix = self.seq = self.qual = None
        src = np.frombuffer(raw, dtype=np.uint8)
        expect = footer_crcs(src, table) if check_crc else None
        cap = max(16, (self.stop - start) // 36 + 1)
        self.offsets = np.empty(cap, dtype=np.int64)
        mode_id = {"offsets": native.FUSED_OFFSETS,
                   "rows": native.FUSED_ROWS,
                   "payload": native.FUSED_PAYLOAD}[mode]
        sel_off = sel_len = out_rows = out_seq = out_qual = None
        if mode == "rows":
            sel_off = np.asarray([o for o, _ in sel], dtype=np.int32)
            sel_len = np.asarray([l for _, l in sel], dtype=np.int32)
            self.rows = out_rows = np.empty((cap, row_stride),
                                            dtype=np.uint8)
        elif mode == "payload":
            # zeroed like the two-pass wrappers: the C side writes only
            # each row's payload bytes, padding stays zero
            self.prefix = out_rows = np.zeros((cap, 36), dtype=np.uint8)
            self.seq = out_seq = np.zeros((cap, seq_stride), dtype=np.uint8)
            self.qual = out_qual = np.zeros((cap, qual_stride),
                                            dtype=np.uint8)
        self.n_blocks = int(isize.size)
        if self.n_blocks == 0:
            self._job = None
            self.n_rows, self.tail = 0, int(start)
            return
        self._job = native.FusedJob(
            src, table["cdata_off"], table["cdata_len"], isize, expect,
            self.data, self.ubase, start, self.stop, mode_id, sel_off,
            sel_len, row_stride, out_rows, out_seq, out_qual, max_len,
            seq_stride, qual_stride, self.offsets, chunk_blocks, n_threads)
        self.n_rows: Optional[int] = None
        self.tail: Optional[int] = None

    def chunks(self) -> "Iterator[Tuple[int, int]]":
        """Yield ``(row_lo, row_hi)`` as the native walk completes them;
        raises on corruption.  Always drives the job to completion unless
        the generator is closed early (which cancels + joins)."""
        if self._job is None:
            return
        try:
            while True:
                c = self._job.next_chunk()
                if c is None:
                    if self._job.rc < 0:
                        _raise_fused_error(self._job.rc,
                                           self._job.err_index)
                    return
                yield c
        finally:
            # abandoned mid-stream (early generator close): join workers
            # so no native thread outlives its span's buffers
            if self.n_rows is None:
                self.finish(check=False)

    def finish(self, check: bool = True) -> Tuple[int, int]:
        """Join the job; returns (n_rows, tail).  ``check=False`` skips
        raising (the cancellation path)."""
        if self._job is not None:
            rc = self._job.finish()
            self.n_rows, self.tail = self._job.n_rows, self._job.tail
            idx = self._job.err_index
            self._job = None
            if check and rc < 0:
                _raise_fused_error(rc, idx)
        return self.n_rows, self.tail

    @property
    def err_index(self) -> int:
        return -1 if self._job is None else self._job.err_index

    def run(self) -> Tuple[int, int]:
        """Non-streamed convenience: drain every chunk, then finish."""
        for _ in self.chunks():
            pass
        return self.finish()
