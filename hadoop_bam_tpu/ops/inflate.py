"""Batched BGZF inflate dispatch: the framework's replacement for the
per-block zlib-over-JNI inflate in the reference hot loop (SURVEY.md 3.2).

Paths, in preference order:

- ``native``: C++ multithreaded zlib over all blocks of a span at once
  (native/hbam_native.cpp) — the production host path feeding device batches.
- ``zlib``: Python zlib per block (portable fallback, still batched at the
  span level).
- ``device``: two-stage device DEFLATE (ops/inflate_device.py) — host
  Huffman tokenize (native, threaded) + on-device LZ77 copy resolution by
  pointer doubling.  Measured, not default: the Huffman stage dominates
  inflate cost and is bit-serial, so the host stage bounds throughput; see
  BASELINE.md "Device DEFLATE" for the numbers.

All paths share one contract: given the raw compressed span bytes and the
parsed block table, produce a contiguous inflated buffer + per-block inflated
offsets.
"""
from __future__ import annotations

import zlib
from typing import List, Optional, Sequence, Tuple

import numpy as np

from hadoop_bam_tpu.formats import bgzf
from hadoop_bam_tpu.utils import native


def block_table(raw: bytes, offset: int = 0) -> dict:
    """Parse consecutive BGZF block headers into a columnar table."""
    coffs, cdata_off, cdata_len, isize = [], [], [], []
    p = offset
    n = len(raw)
    while p < n:
        info = bgzf.parse_block_header(raw, p)
        coffs.append(info.coffset)
        cdata_off.append(info.cdata_offset)
        cdata_len.append(info.cdata_size)
        isize.append(info.isize)
        p = info.next_coffset
    return {
        "coffset": np.asarray(coffs, dtype=np.int64),
        "cdata_off": np.asarray(cdata_off, dtype=np.int64),
        "cdata_len": np.asarray(cdata_len, dtype=np.int32),
        "isize": np.asarray(isize, dtype=np.int32),
    }


def inflate_span(raw: bytes, table: Optional[dict] = None,
                 backend: str = "auto", n_threads: int = 0
                 ) -> Tuple[np.ndarray, np.ndarray]:
    """Inflate all blocks of a compressed span.

    Returns (data, ubase): ``data`` is the contiguous inflated bytes of the
    span; ``ubase[i]`` is each block's starting offset within ``data`` (the
    map from (block, in-block offset) to buffer offset — i.e. from virtual
    offsets to positions).
    """
    if table is None:
        table = block_table(raw)
    if backend == "device":
        from hadoop_bam_tpu.ops.inflate_device import inflate_span_device
        return inflate_span_device(raw, table, n_threads=n_threads)
    isize = table["isize"]
    ubase = np.zeros(isize.size + 1, dtype=np.int64)
    np.cumsum(isize, out=ubase[1:])
    total = int(ubase[-1])
    dst = np.empty(total, dtype=np.uint8)
    src = np.frombuffer(raw, dtype=np.uint8)

    if backend == "auto":
        backend = "native" if native.available() else "zlib"
    if backend == "native":
        native.inflate_batch(src, table["cdata_off"], table["cdata_len"],
                             dst, ubase[:-1], isize, n_threads)
    elif backend == "zlib":
        mv = memoryview(raw)
        for i in range(isize.size):
            o, l = int(table["cdata_off"][i]), int(table["cdata_len"][i])
            try:
                out = zlib.decompress(bytes(mv[o:o + l]), wbits=-15)
            except zlib.error as e:
                # classified at the policy boundary: bad DEFLATE bytes are
                # deterministic corruption, not a retryable read fault
                raise bgzf.BGZFError(
                    f"corrupt DEFLATE payload in block {i}: {e}") from e
            if len(out) != int(isize[i]):
                raise bgzf.BGZFError(f"ISIZE mismatch in block {i}")
            dst[int(ubase[i]):int(ubase[i + 1])] = np.frombuffer(out, np.uint8)
    else:
        # PLAN class (still a ValueError): a bad backend name is run
        # configuration, not data — never retried, never quarantined
        from hadoop_bam_tpu.utils.errors import PlanError
        raise PlanError(f"unknown inflate backend {backend!r}")
    return dst, ubase[:-1]


def verify_crcs(raw: bytes, table: dict, data: np.ndarray,
                ubase: np.ndarray, n_threads: int = 0) -> None:
    """Validate every block's CRC32 footer against the inflated bytes
    (native batched CRC when available)."""
    n = table["isize"].size
    src = np.frombuffer(raw, dtype=np.uint8)
    # footer CRC sits 8 bytes before each block end
    foot = table["cdata_off"] + table["cdata_len"]
    expect = (src[foot].astype(np.uint32)
              | (src[foot + 1].astype(np.uint32) << 8)
              | (src[foot + 2].astype(np.uint32) << 16)
              | (src[foot + 3].astype(np.uint32) << 24))
    if native.available():
        import ctypes
        lib = native.load()
        got = np.empty(n, dtype=np.uint32)
        lib.hbam_crc32_batch(
            data.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            ubase.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            table["isize"].ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            n, got.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
            n_threads if n_threads > 0 else 0 or 1)
    else:
        got = np.empty(n, dtype=np.uint32)
        for i in range(n):
            s, e = int(ubase[i]), int(ubase[i]) + int(table["isize"][i])
            got[i] = zlib.crc32(data[s:e].tobytes()) & 0xFFFFFFFF
    bad = np.nonzero(got != expect)[0]
    if bad.size:
        raise bgzf.BGZFError(f"CRC32 mismatch in block(s) {bad[:8].tolist()}")


def walk_records(data: np.ndarray, start: int = 0,
                 cap: Optional[int] = None) -> Tuple[np.ndarray, int]:
    """Record-boundary walk over inflated bytes: native when available,
    NumPy/Python otherwise.  Returns (offsets, tail_offset) where tail_offset
    is the first incomplete record's offset (== len when exact)."""
    if cap is None:
        # min on-wire record = 4-byte block_size + 32-byte fixed core (the
        # native walker accepts any bs >= 32), so count can never exceed //36
        cap = max(16, data.size // 36)
    if native.available():
        return native.walk_bam_records(np.ascontiguousarray(data), start, cap)
    from hadoop_bam_tpu.formats.bam import walk_record_offsets
    offs = walk_record_offsets(data.tobytes(), start=start)
    tail = int(offs[-1] + 4 + int.from_bytes(
        data[int(offs[-1]):int(offs[-1]) + 4].tobytes(), "little", signed=True)
        ) if offs.size else start
    return offs, tail
