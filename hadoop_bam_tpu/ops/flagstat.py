"""Flagstat: samtools-style flag summary as a device reduction.

The "model" of this framework's minimum end-to-end slice (SURVEY.md section 7):
decode a BAM span on device, reduce flag columns to counters.  Equivalent
functionality in the reference universe is the CLI ``summarize`` plugin
[VER?]; counts follow the samtools flagstat definitions over the FLAG field
[SPEC section 1.4].

All counters are jnp sums over masked boolean columns — embarrassingly
fusable, and on a mesh they finish with one ``psum`` over the data axis
(hadoop_bam_tpu/parallel/pipeline.py).
"""
from __future__ import annotations

from typing import Dict

import jax
import jax.numpy as jnp

from hadoop_bam_tpu.formats.bam import (
    FDUP, FMUNMAP, FPAIRED, FPROPER_PAIR, FQCFAIL, FREAD1, FREAD2, FREVERSE,
    FSECONDARY, FSUPPLEMENTARY, FUNMAP,
)

FLAGSTAT_FIELDS = (
    "total", "primary", "secondary", "supplementary", "duplicates",
    "primary_duplicates", "mapped", "primary_mapped", "paired", "read1",
    "read2", "properly_paired", "with_itself_and_mate_mapped", "singletons",
    "mate_on_different_chr", "mate_on_different_chr_mapq5",
)


@jax.jit
def flagstat_from_columns(cols: Dict[str, jnp.ndarray], valid: jnp.ndarray
                          ) -> Dict[str, jnp.ndarray]:
    """cols: output of ops.unpack_bam.unpack_fixed_fields; valid: bool [N].
    Returns a dict of int32 scalar counters (a pytree, psum-able);
    per-batch counts fit int32, cross-batch accumulation is host-side Python."""
    flag = cols["flag"]
    refid = cols["refid"]
    mate_refid = cols["mate_refid"]
    mapq = cols["mapq"]

    def has(bit):
        return (flag & bit) != 0

    v = valid
    secondary = has(FSECONDARY)
    supplementary = has(FSUPPLEMENTARY)
    primary = ~secondary & ~supplementary
    mapped = ~has(FUNMAP)
    paired = has(FPAIRED)
    mate_mapped = ~has(FMUNMAP)
    both = paired & mapped & mate_mapped
    diff_chr = both & (mate_refid != refid) & (refid >= 0) & (mate_refid >= 0)

    def count(mask):
        return jnp.sum(jnp.where(v & mask, 1, 0), dtype=jnp.int32)

    return {
        "total": count(jnp.ones_like(flag, dtype=bool)),
        "primary": count(primary),
        "secondary": count(secondary),
        "supplementary": count(supplementary),
        "duplicates": count(has(FDUP)),
        "primary_duplicates": count(primary & has(FDUP)),
        "mapped": count(mapped),
        "primary_mapped": count(primary & mapped),
        "paired": count(paired),
        "read1": count(paired & has(FREAD1)),
        "read2": count(paired & has(FREAD2)),
        "properly_paired": count(paired & has(FPROPER_PAIR) & mapped),
        "with_itself_and_mate_mapped": count(both),
        "singletons": count(paired & mapped & ~mate_mapped),
        "mate_on_different_chr": count(diff_chr),
        "mate_on_different_chr_mapq5": count(diff_chr & (mapq >= 5)),
    }


def format_flagstat(stats: Dict[str, int]) -> str:
    """samtools-flagstat-style text rendering (host side)."""
    g = {k: int(v) for k, v in stats.items()}
    lines = [
        f"{g['total']} + 0 in total (QC-passed reads + QC-failed reads)",
        f"{g['primary']} + 0 primary",
        f"{g['secondary']} + 0 secondary",
        f"{g['supplementary']} + 0 supplementary",
        f"{g['duplicates']} + 0 duplicates",
        f"{g['primary_duplicates']} + 0 primary duplicates",
        f"{g['mapped']} + 0 mapped",
        f"{g['primary_mapped']} + 0 primary mapped",
        f"{g['paired']} + 0 paired in sequencing",
        f"{g['read1']} + 0 read1",
        f"{g['read2']} + 0 read2",
        f"{g['properly_paired']} + 0 properly paired",
        f"{g['with_itself_and_mate_mapped']} + 0 with itself and mate mapped",
        f"{g['singletons']} + 0 singletons",
        f"{g['mate_on_different_chr']} + 0 with mate mapped to a different chr",
        f"{g['mate_on_different_chr_mapq5']} + 0 with mate mapped to a different chr (mapQ>=5)",
    ]
    return "\n".join(lines)


def flagstat_from_batch(batch, stats=None) -> Dict[str, int]:
    """Host (NumPy) flagstat over one BamBatch — the same counters as the
    jitted column path, for contexts that already hold a decoded batch
    (e.g. interval-filtered datasets).  Accumulates into ``stats``."""
    import numpy as np

    flag = batch.flag.astype(np.int64)
    refid = batch.refid
    mate_refid = batch.mate_refid
    mapq = batch.mapq

    def has(bit):
        return (flag & bit) != 0

    secondary = has(FSECONDARY)
    supplementary = has(FSUPPLEMENTARY)
    primary = ~secondary & ~supplementary
    mapped = ~has(FUNMAP)
    paired = has(FPAIRED)
    mate_mapped = ~has(FMUNMAP)
    both = paired & mapped & mate_mapped
    diff_chr = both & (mate_refid != refid) & (refid >= 0) & (mate_refid >= 0)
    out = {
        "total": flag.size,
        "primary": int(primary.sum()),
        "secondary": int(secondary.sum()),
        "supplementary": int(supplementary.sum()),
        "duplicates": int(has(FDUP).sum()),
        "primary_duplicates": int((primary & has(FDUP)).sum()),
        "mapped": int(mapped.sum()),
        "primary_mapped": int((primary & mapped).sum()),
        "paired": int(paired.sum()),
        "read1": int((paired & has(FREAD1)).sum()),
        "read2": int((paired & has(FREAD2)).sum()),
        "properly_paired": int((paired & has(FPROPER_PAIR) & mapped).sum()),
        "with_itself_and_mate_mapped": int(both.sum()),
        "singletons": int((paired & mapped & ~mate_mapped).sum()),
        "mate_on_different_chr": int(diff_chr.sum()),
        "mate_on_different_chr_mapq5": int((diff_chr & (mapq >= 5)).sum()),
    }
    if stats is not None:
        for k, v in out.items():
            stats[k] = stats.get(k, 0) + v
        return stats
    return out
