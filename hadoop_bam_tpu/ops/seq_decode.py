"""Sequence/quality payload decode on device.

Device-side replacement for htsjdk's per-record seq/qual string decode:
the 4-bit packed bases [SPEC "=ACMGRSVTWYHKDBN"] of a whole batch are
unpacked into an [N, L] uint8 matrix by one gather + nibble select, and
qualities by one gather + offset — the shapes downstream TPU compute wants
(one row per read, fixed length, masked).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from hadoop_bam_tpu.formats.bam import SEQ_NIBBLE

_NIBBLE_LUT = np.frombuffer(SEQ_NIBBLE.encode(), dtype=np.uint8)


@functools.partial(jax.jit, static_argnames=("max_len",))
def decode_seq(data: jnp.ndarray, seq_offsets: jnp.ndarray,
               l_seq: jnp.ndarray, max_len: int) -> jnp.ndarray:
    """data u8 [D]; seq_offsets/l_seq i32 [N] -> ASCII bases u8 [N, max_len],
    zero beyond each read's length."""
    pos = jnp.arange(max_len, dtype=jnp.int32)[None, :]          # [1, L]
    byte_idx = seq_offsets[:, None] + pos // 2                   # [N, L]
    byte_idx = jnp.minimum(byte_idx, data.shape[0] - 1)
    packed = data[byte_idx]                                      # [N, L]
    nibble = jnp.where(pos % 2 == 0, packed >> 4, packed & 0xF)
    lut = jnp.asarray(_NIBBLE_LUT)
    ascii_ = lut[nibble]
    mask = pos < l_seq[:, None]
    return jnp.where(mask, ascii_, 0).astype(jnp.uint8)


@functools.partial(jax.jit, static_argnames=("max_len", "ascii_offset"))
def decode_qual(data: jnp.ndarray, qual_offsets: jnp.ndarray,
                l_seq: jnp.ndarray, max_len: int,
                ascii_offset: int = 33) -> jnp.ndarray:
    """Phred qualities as ASCII (offset +33 by default); 0 beyond length."""
    pos = jnp.arange(max_len, dtype=jnp.int32)[None, :]
    idx = jnp.minimum(qual_offsets[:, None] + pos, data.shape[0] - 1)
    q = data[idx]
    mask = (pos < l_seq[:, None]) & (q != 0xFF)
    return jnp.where(mask, q + ascii_offset, 0).astype(jnp.uint8)


@jax.jit
def base_composition(seq_ascii: jnp.ndarray) -> jnp.ndarray:
    """Count A/C/G/T/N/other over an [N, L] ASCII base matrix -> int32 [6]."""
    flat = seq_ascii.reshape(-1)
    live = flat != 0
    counts = []
    for ch in b"ACGTN":
        counts.append(jnp.sum(jnp.where(live & (flat == ch), 1, 0),
                              dtype=jnp.int32))
    total = jnp.sum(jnp.where(live, 1, 0), dtype=jnp.int32)
    counts.append(total - sum(counts))
    return jnp.stack(counts)


@jax.jit
def mean_base_quality(qual_ascii: jnp.ndarray, ascii_offset: int = 33
                      ) -> jnp.ndarray:
    """Mean Phred score over valid bases of an [N, L] ASCII quality matrix."""
    live = qual_ascii != 0
    q = jnp.where(live, qual_ascii.astype(jnp.int32) - ascii_offset, 0)
    n = jnp.maximum(jnp.sum(jnp.where(live, 1, 0)), 1)
    return jnp.sum(q) / n
