"""Datasets: the InputFormat/RecordReader surface, iterator-shaped.

Where the reference exposed ``InputFormat<K, V>`` + ``RecordReader`` pairs
(hb/AnySAMInputFormat.java, hb/BAMInputFormat.java, hb/SAMInputFormat.java,
SURVEY.md section 2.3), this framework exposes datasets: ``open_bam(path)``
resolves the container (dispatch.py), reads the header, plans record-aligned
spans, and iterates SoA batches — host batches (``BamBatch``) or device-fed
mesh steps (parallel/pipeline.py).

Checkpoint/resume (SURVEY.md section 5): the iterator's position is just
(plan, next span index) — ``state_dict()`` / ``load_state_dict()`` make any
consumer resumable, the moral equivalent of the splitting-bai cursor idea.
"""
from __future__ import annotations

import dataclasses
from typing import Dict, Iterator, List, Optional

import numpy as np

from hadoop_bam_tpu.config import DEFAULT_CONFIG, HBamConfig, ValidationStringency
from hadoop_bam_tpu.api.dispatch import SAMContainer, sniff_sam_container
from hadoop_bam_tpu.formats.bam import BamBatch, SAMHeader
from hadoop_bam_tpu.formats.bamio import read_bam_header
from hadoop_bam_tpu.formats.sam import SamRecord, read_sam_text
from hadoop_bam_tpu.split.planners import (
    plan_bam_spans, plan_text_spans, read_bam_span, read_text_span,
)
from hadoop_bam_tpu.split.spans import FileByteSpan, FileVirtualSpan
from hadoop_bam_tpu.utils.seekable import as_byte_source


def _check_replan(ds, num_spans) -> None:
    """Guard against silently reusing a plan built with a different
    num_spans (same contract as read_datasets._SpannedDataset.spans)."""
    cached = getattr(ds, "_plan_num_spans", None)
    if getattr(ds, "_plan", None) is not None and num_spans is not None \
            and num_spans != cached:
        raise ValueError(
            f"span plan already built with num_spans={cached}; "
            "open a new dataset to re-plan")


class BamDataset:
    """Record-aligned access to one BAM file (hb/BAMInputFormat +
    hb/BAMRecordReader in dataset clothes)."""

    def __init__(self, path: str, config: HBamConfig = DEFAULT_CONFIG):
        self.path = path
        self.config = config
        self.header, self.first_voffset = read_bam_header(path)
        self._plan: Optional[List[FileVirtualSpan]] = None
        self._next_span = 0
        self._intervals = None

    def spans(self, num_spans: Optional[int] = None) -> List[FileVirtualSpan]:
        _check_replan(self, num_spans)
        if self._plan is None:
            from hadoop_bam_tpu.split.planners import (
                plan_spans_maybe_intervals,
            )
            self._plan = plan_spans_maybe_intervals(
                self.path, self.header, self.config, num_spans=num_spans)
            self._plan_num_spans = num_spans
        return self._plan

    def read_span(self, span: FileVirtualSpan) -> BamBatch:
        batch = read_bam_span(self.path, span, header=self.header)
        if self.config.bam_intervals:
            from hadoop_bam_tpu.split.intervals import (
                filter_batch, parse_intervals,
            )
            if self._intervals is None:
                self._intervals = parse_intervals(self.config.bam_intervals,
                                                  self.header.ref_names)
            batch = filter_batch(batch, self._intervals, self.header)
        return batch

    def batches(self, num_spans: Optional[int] = None) -> Iterator[BamBatch]:
        """Yield one SoA batch per span, resumable via state_dict();
        a fresh call after exhaustion restarts from the beginning."""
        plan = self.spans(num_spans)
        if self._next_span >= len(plan):
            self._next_span = 0
        while self._next_span < len(plan):
            span = plan[self._next_span]
            batch = self.read_span(span)
            self._next_span += 1  # before yield: state = batches delivered
            yield batch

    def records(self, num_spans: Optional[int] = None) -> Iterator[SamRecord]:
        """Per-record view (tests/CLI; the batch path is the fast path)."""
        for batch in self.batches(num_spans):
            for i in range(len(batch)):
                yield SamRecord.from_line(batch.to_sam_line(i))

    # -- checkpoint / resume --
    def state_dict(self) -> Dict:
        return {
            "path": self.path,
            "plan": [s.to_dict() for s in (self._plan or [])],
            "next_span": self._next_span,
        }

    def load_state_dict(self, state: Dict) -> None:
        assert state["path"] == self.path
        self._plan = [FileVirtualSpan.from_dict(d) for d in state["plan"]] \
            or None
        self._next_span = int(state["next_span"])

    def tensor_batches(self, mesh=None, geometry=None,
                       num_spans: Optional[int] = None) -> Iterator[Dict]:
        """Yield device-resident tensor batches for mesh consumers — the
        ML-feed surface this framework exists for.  Each batch is a dict of
        arrays sharded over the mesh's data axis:

        - ``seq_packed`` [n_dev, cap, seq_stride] uint8 — 4-bit bases,
          2/byte, high nibble first [SPEC]; unpack on device with
          ops.seq_pallas.unpack_bases (or feed packed straight into a
          Pallas kernel)
        - ``qual`` [n_dev, cap, qual_stride] uint8
        - ``prefix`` [n_dev, cap, 36] uint8 — fixed columns; decode with
          ops.unpack_bam.unpack_fixed_fields_tile
        - ``n_records`` [n_dev] int32 — valid rows per shard

        ``cap`` is geometry.tile_records for every full batch; the FINAL
        batch of a run may arrive with fewer rows (shrunk to the
        smallest dispatch bucket that holds its records) — size consumer
        buffers from the batch's own shape, not the geometry.
        Consumers that preallocate by ``tile_records`` can opt out with
        ``PayloadGeometry(fixed_shape=True)``: the final batch then pads
        to ``tile_records`` instead of shrinking (every batch shares one
        shape, at the cost of padding transfer on the last batch).
        """
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        from hadoop_bam_tpu.parallel.mesh import make_mesh
        from hadoop_bam_tpu.parallel.pipeline import (
            PayloadGeometry, iter_payload_tile_groups,
        )

        if mesh is None:
            mesh = make_mesh()
        if geometry is None:
            geometry = PayloadGeometry()
        n_dev = int(np.prod(mesh.devices.shape))
        sharding = NamedSharding(mesh, P("data"))
        spans = self.spans(num_spans)

        def emit(arrays, counts):
            # the device dict doubles as the ring slot's in-flight
            # transfer handle (staging.FeedPipeline.stream contract)
            return {
                "prefix": jax.device_put(arrays[0], sharding),
                "seq_packed": jax.device_put(arrays[1], sharding),
                "qual": jax.device_put(arrays[2], sharding),
                "n_records": jax.device_put(counts, sharding),
            }

        yield from iter_payload_tile_groups(
            self.path, spans, geometry, n_dev, self.config,
            header=self.header, emit_fn=emit)

    def query(self, region: str) -> Iterator[SamRecord]:
        """Random access via a ``.bai``/``.csi`` sidecar: yields records
        overlapping the samtools-style region, reading only the index's
        chunk ranges (build with ``hbam index --flavor bai``).  Falls back
        to a full scan + filter when no genomic index exists."""
        from hadoop_bam_tpu.split.bai import load_bai_for, plan_interval_spans
        from hadoop_bam_tpu.split.intervals import (
            batch_overlap_mask, parse_intervals,
        )

        intervals = parse_intervals(region, self.header.ref_names)
        spans = plan_interval_spans(self.path, intervals, self.header)
        if spans is None:
            spans = self.spans()
        for span in spans:
            batch = read_bam_span(self.path, span, header=self.header)
            mask = batch_overlap_mask(batch, intervals, self.header)
            idx = np.nonzero(mask)[0]
            for i in idx:
                yield SamRecord.from_line(batch.to_sam_line(int(i)))

    def seq_stats(self, mesh=None, geometry=None) -> Dict:
        """Distributed GC / quality / base-composition stats via the fused
        Pallas payload kernel (parallel/pipeline.seq_stats_file).  Honors
        bam_intervals (rows filter host-side before tiling)."""
        from hadoop_bam_tpu.parallel.pipeline import seq_stats_file
        return seq_stats_file(self.path, mesh=mesh, config=self.config,
                              geometry=geometry, header=self.header)

    def flagstat(self, mesh=None) -> Dict[str, int]:
        """Distributed flagstat; honors bam_intervals via the mesh path's
        host-side row filter."""
        from hadoop_bam_tpu.parallel.pipeline import flagstat_file
        return flagstat_file(self.path, mesh=mesh, config=self.config,
                             header=self.header)


class SamDataset:
    """Plain-text SAM (hb/SAMInputFormat + hb/SAMRecordReader): line-split
    text; header read separately since mid-file spans never see it."""

    def __init__(self, path: str, config: HBamConfig = DEFAULT_CONFIG):
        self.path = path
        self.config = config
        self.header = self._read_header()
        self._next_span = 0

    def _read_header(self) -> SAMHeader:
        src = as_byte_source(self.path)
        try:
            chunks = []
            off = 0
            while True:
                got = src.pread(off, 1 << 16)
                if not got:
                    break
                chunks.append(got)
                off += len(got)
                # stop once a non-@ line has started
                text = b"".join(chunks)
                lines = text.split(b"\n")
                if any(l and not l.startswith(b"@") for l in lines[:-1]):
                    break
            text = b"".join(chunks)
            header_lines = []
            for line in text.split(b"\n"):
                if line.startswith(b"@"):
                    header_lines.append(line.decode() + "\n")
                elif line:
                    break
            return SAMHeader.from_sam_text("".join(header_lines))
        finally:
            src.close()

    def spans(self, num_spans: Optional[int] = None) -> List[FileByteSpan]:
        return plan_text_spans(self.path, num_spans=num_spans,
                               span_bytes=None if num_spans
                               else self.config.split_size)

    def read_span(self, span: FileByteSpan) -> List[SamRecord]:
        text = read_text_span(self.path, span).decode()
        out = []
        for line in text.splitlines():
            if not line or line.startswith("@"):
                continue
            try:
                out.append(SamRecord.from_line(line))
            except Exception:
                if self.config.validation_stringency is ValidationStringency.STRICT:
                    raise
        return out

    def records(self, num_spans: Optional[int] = None) -> Iterator[SamRecord]:
        for span in self.spans(num_spans):
            yield from self.read_span(span)

    def flagstat(self, mesh=None) -> Dict[str, int]:
        """Host-side flagstat (text SAM has no columnar device path);
        same counter definitions as the BAM mesh path."""
        return _flagstat_records(self.records())


def _flagstat_records(records) -> Dict[str, int]:
    """samtools-flagstat counters over an iterator of SamRecords — the
    uniform fallback for datasets without a device decode path."""
    import numpy as np

    from hadoop_bam_tpu.formats.bam import BamBatch
    from hadoop_bam_tpu.ops.flagstat import FLAGSTAT_FIELDS, flagstat_from_batch

    stats = {k: 0 for k in FLAGSTAT_FIELDS}

    class _Cols:
        pass

    flags, refids, mrefids, mapqs = [], [], [], []
    names: Dict[str, int] = {}
    for r in records:
        flags.append(r.flag)
        refids.append(-1 if r.rname == "*"
                      else names.setdefault(r.rname, len(names)))
        if r.rnext == "*":
            mrefids.append(-1)
        elif r.rnext == "=":
            mrefids.append(refids[-1])
        else:
            mrefids.append(names.setdefault(r.rnext, len(names)))
        mapqs.append(r.mapq)
    batch = _Cols()
    batch.flag = np.asarray(flags, dtype=np.int64)
    batch.refid = np.asarray(refids, dtype=np.int64)
    batch.mate_refid = np.asarray(mrefids, dtype=np.int64)
    batch.mapq = np.asarray(mapqs, dtype=np.int64)
    return flagstat_from_batch(batch, stats)


def open_bam(path: str, config: HBamConfig = DEFAULT_CONFIG) -> BamDataset:
    return BamDataset(path, config)


def open_sam(path: str, config: HBamConfig = DEFAULT_CONFIG) -> SamDataset:
    return SamDataset(path, config)


def open_any_sam(path: str, config: HBamConfig = DEFAULT_CONFIG):
    """hb/AnySAMInputFormat: resolve the container, return the dataset."""
    fmt = sniff_sam_container(path, config)
    if fmt is SAMContainer.BAM:
        return BamDataset(path, config)
    if fmt is SAMContainer.SAM:
        return SamDataset(path, config)
    if fmt is SAMContainer.CRAM:
        from hadoop_bam_tpu.api.cram_dataset import CramDataset
        return CramDataset(path, config)
    raise ValueError(f"unsupported container {fmt}")
