"""``api.query_regions`` — the tensor-batch face of the query engine.

Where ``BamDataset.tensor_batches`` streams a whole file, this streams
the union of a BATCH of region queries: the engine resolves every
region through the genomic indexes, decodes each needed chunk once
(LRU-cached across calls), and yields device groups whose ``keep`` mask
was computed by the interval-overlap predicate on the mesh.
"""
from __future__ import annotations

from typing import Dict, Iterator, Optional, Sequence, Tuple, Union

from hadoop_bam_tpu.config import DEFAULT_CONFIG, HBamConfig
from hadoop_bam_tpu.query.engine import QueryEngine, QueryRequest

RequestLike = Union[QueryRequest, Tuple[str, str]]


def query_regions(requests: "Sequence[RequestLike] | RequestLike",
                  regions: Optional[Sequence[str]] = None,
                  *, config: HBamConfig = DEFAULT_CONFIG,
                  engine: Optional[QueryEngine] = None,
                  mesh=None,
                  deadline_s: Optional[float] = None) -> Iterator[Dict]:
    """Serve a batch of region queries as sharded device tensor batches.

    Two calling shapes::

        query_regions([("a.bam", "chr1:1-5000"), ("b.bam", "chr2")])
        query_regions("a.bam", ["chr1:1-5000", "chr2:100-200"])

    Yields ``{rid, pos, end, req, keep, n_records}`` groups —
    ``[n_dev, cap]`` int32 columns sharded over the mesh's data axis,
    ``keep`` the mesh-computed boolean overlap mask, ``req`` mapping each
    row back to its request index.  Pass a long-lived ``engine`` to reuse
    its chunk cache across calls (the warm serving path); otherwise a
    fresh engine (and cold cache) is built per call.
    """
    if isinstance(requests, (str, bytes)):
        if regions is None:
            raise TypeError(
                "query_regions(path, regions): regions list required")
        batch = [QueryRequest(str(requests), r) for r in regions]
    else:
        batch = [r if isinstance(r, QueryRequest) else QueryRequest(*r)
                 for r in requests]
    if engine is None:
        engine = QueryEngine(config=config, mesh=mesh)
    yield from engine.tensor_batches(batch, deadline_s=deadline_s)
