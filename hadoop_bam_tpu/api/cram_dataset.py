"""CramDataset: record-aligned access to one CRAM file.

The dataset face of hb/CRAMInputFormat.java + hb/CRAMRecordReader.java
(SURVEY.md section 2.3, [VER? 7.1+]): spans align to container boundaries,
each span decodes independently, and the reference source is resolved from
config (``cram_reference_source_path`` — the analog of
``hadoopbam.cram.reference-source-path``).
"""
from __future__ import annotations

from typing import Dict, Iterator, List, Optional

from hadoop_bam_tpu.config import DEFAULT_CONFIG, HBamConfig
from hadoop_bam_tpu.formats.bam import SAMHeader
from hadoop_bam_tpu.formats.cram_decode import (
    FastaReferenceSource, ReferenceSource,
)
from hadoop_bam_tpu.formats.cramio import read_cram_header
from hadoop_bam_tpu.formats.sam import SamRecord
from hadoop_bam_tpu.split.cram_planner import plan_cram_spans, read_cram_span
from hadoop_bam_tpu.split.spans import FileByteSpan


class CramDataset:
    def __init__(self, path: str, config: HBamConfig = DEFAULT_CONFIG):
        self.path = path
        self.config = config
        self.header, self._first_container = read_cram_header(path)
        self._plan: Optional[List[FileByteSpan]] = None
        self._next_span = 0
        self._ref_source: Optional[ReferenceSource] = None
        if config.cram_reference_source_path:
            self._ref_source = FastaReferenceSource(
                config.cram_reference_source_path)

    def spans(self, num_spans: Optional[int] = None) -> List[FileByteSpan]:
        from hadoop_bam_tpu.api.dataset import _check_replan
        _check_replan(self, num_spans)
        if self._plan is None:
            self._plan = plan_cram_spans(self.path, num_spans=num_spans,
                                         config=self.config)
            self._plan_num_spans = num_spans
        return self._plan

    def read_span(self, span: FileByteSpan) -> List[SamRecord]:
        return read_cram_span(self.path, span, header=self.header,
                              ref_source=self._ref_source)

    def records(self, num_spans: Optional[int] = None) -> Iterator[SamRecord]:
        plan = self.spans(num_spans)
        if self._next_span >= len(plan):
            self._next_span = 0
        while self._next_span < len(plan):
            span = plan[self._next_span]
            recs = self.read_span(span)
            self._next_span += 1
            yield from recs

    def tensor_batches(self, mesh=None, geometry=None,
                       num_spans: Optional[int] = None,
                       spans: Optional[List[FileByteSpan]] = None,
                       quarantine=None,
                       ) -> Iterator[Dict]:
        """Device-resident read batches (same layout as
        FastqDataset.tensor_batches) decoded from CRAM containers.

        Columnar fast path: spans decode straight to columns
        (read_cram_span_columns — the vectorized slice decoder, no
        CramRecord objects) whose seq/qual runs pack directly into
        tiles; slices outside the vectorizable layout fall back to the
        record decoder with identical output."""
        from hadoop_bam_tpu.api.read_datasets import (
            ragged_to_payload_tiles,
        )
        from hadoop_bam_tpu.parallel.pipeline import (
            stream_read_tensor_batches,
        )
        from hadoop_bam_tpu.split.cram_planner import (
            read_cram_span_columns,
        )

        def tiles(span, geom):
            cols = read_cram_span_columns(self.path, span,
                                          header=self.header,
                                          ref_source=self._ref_source)
            # qual_lens gate == the CF_QUAL_STORED gate in _to_sam:
            # without stored quals the column is already empty
            return ragged_to_payload_tiles(
                cols["seq_cat"], cols["seq_lens"], cols["qual_cat"],
                cols["qual_lens"], geom.seq_stride, geom.qual_stride,
                geom.max_len, qual_offset=0)

        yield from stream_read_tensor_batches(
            self.spans(num_spans) if spans is None else spans, None,
            self.config, mesh, geometry, tiles_fn=tiles,
            quarantine=quarantine, fmt="cram")

    def flagstat(self, mesh=None) -> Dict[str, int]:
        """Host-side flagstat over decoded CRAM records (same counters as
        the BAM mesh path)."""
        from hadoop_bam_tpu.api.dataset import _flagstat_records
        return _flagstat_records(self.records())

    # -- checkpoint / resume (same contract as BamDataset) --
    def state_dict(self) -> Dict:
        return {"path": self.path,
                "plan": [s.to_dict() for s in (self._plan or [])],
                "next_span": self._next_span}

    def load_state_dict(self, state: Dict) -> None:
        assert state["path"] == self.path
        self._plan = [FileByteSpan.from_dict(d) for d in state["plan"]] \
            or None
        self._next_span = int(state["next_span"])


def open_cram(path: str, config: HBamConfig = DEFAULT_CONFIG) -> CramDataset:
    return CramDataset(path, config)
