"""User surface: datasets, format dispatch, writers.

The rebuild of the reference's L4/L5 adapter layer (SURVEY.md section 1) —
InputFormats become datasets yielding SoA batches; OutputFormats become shard
writers + mergers; AnySAM/VCF format sniffing becomes ``sniff_*`` dispatch.
"""
from hadoop_bam_tpu.api.dispatch import (  # noqa: F401
    SAMContainer, VCFContainer, sniff_sam_container, sniff_vcf_container,
)
from hadoop_bam_tpu.api.dataset import (  # noqa: F401
    open_bam, open_sam, open_any_sam, BamDataset, SamDataset,
)
from hadoop_bam_tpu.api.cram_dataset import CramDataset, open_cram  # noqa: F401
from hadoop_bam_tpu.api.vcf_dataset import VcfDataset, open_vcf  # noqa: F401
from hadoop_bam_tpu.api.read_datasets import (  # noqa: F401
    FastaDataset, FastqDataset, QseqDataset, open_fasta, open_fastq,
    open_qseq,
)
from hadoop_bam_tpu.api.query import query_regions  # noqa: F401
from hadoop_bam_tpu.cohort import (  # noqa: F401
    CohortDataset, CohortManifest, cohort_gwas, open_cohort,
)
