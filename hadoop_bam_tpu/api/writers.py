"""Shard writers: the OutputFormat layer.

Rebuild of hb/KeyIgnoringAnySAMOutputFormat.java / KeyIgnoringBAMOutputFormat
/ KeyIgnoringSAMOutputFormat and hb/BAMRecordWriter.java (SURVEY.md section
2.4).  Semantics preserved:

- "KeyIgnoring": writers consume records (values) only; span keys are
  irrelevant on output.
- the header is supplied up front (the reference routed it through a
  config-pointed file because OutputFormats were constructed reflectively;
  we just pass the object);
- per-shard header and BGZF terminator are optional so shards can be
  concatenated into one legal file by the merger (utils/mergers.py).
"""
from __future__ import annotations

import os
from typing import Iterable, Optional, Union

from hadoop_bam_tpu.config import DEFAULT_CONFIG, HBamConfig
from hadoop_bam_tpu.api.dispatch import SAMContainer
from hadoop_bam_tpu.formats.bam import SAMHeader
from hadoop_bam_tpu.formats.bamio import BamWriter
from hadoop_bam_tpu.formats.bcfio import BcfWriter
from hadoop_bam_tpu.formats.sam import SamRecord
from hadoop_bam_tpu.formats.vcf import VCFHeader, VcfRecord


class BamShardWriter(BamWriter):
    """BAM shard writer with reference OutputFormat knobs from config."""

    def __init__(self, sink, header: SAMHeader,
                 config: HBamConfig = DEFAULT_CONFIG, **kw):
        kw.setdefault("write_header", config.write_header)
        kw.setdefault("write_eof", config.write_terminator)
        kw.setdefault("level", config.write_compress_level)
        super().__init__(sink, header, **kw)


class SamShardWriter:
    """Text SAM shard writer (hb/KeyIgnoringSAMRecordWriter.java)."""

    def __init__(self, sink, header: SAMHeader,
                 config: HBamConfig = DEFAULT_CONFIG,
                 write_header: Optional[bool] = None):
        self._own = False
        if isinstance(sink, (str, os.PathLike)):
            sink = open(sink, "w")
            self._own = True
        self._sink = sink
        self.header = header
        if config.write_header if write_header is None else write_header:
            self._sink.write(header.to_sam_text())
        self.records_written = 0

    def write_sam_record(self, rec: SamRecord) -> None:
        self._sink.write(rec.to_line() + "\n")
        self.records_written += 1

    def close(self) -> None:
        if self._own:
            self._sink.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def open_any_sam_writer(path: str, header: SAMHeader,
                        container: Optional[SAMContainer] = None,
                        config: HBamConfig = DEFAULT_CONFIG):
    """hb/AnySAMOutputFormat: pick the writer from extension/config."""
    if container is None:
        ext = os.path.splitext(path)[1].lower()
        container = {".bam": SAMContainer.BAM, ".sam": SAMContainer.SAM,
                     ".cram": SAMContainer.CRAM}.get(ext, SAMContainer.BAM)
    if container is SAMContainer.BAM:
        return BamShardWriter(path, header, config)
    if container is SAMContainer.SAM:
        return SamShardWriter(path, header, config)
    if container is SAMContainer.CRAM:
        return CramShardWriter(path, header, config)
    raise ValueError(f"no writer for container {container}")


class CramShardWriter:
    """CRAM shard writer (hb/KeyIgnoringCRAMOutputFormat.java /
    hb/KeyIgnoringCRAMRecordWriter.java, [VER? 7.3+]): reference-free CRAM
    3.0 containers (formats/cram_encode.py); headerless / terminator-less
    shards concatenate via utils/mergers.merge_cram_shards."""

    def __init__(self, sink, header: SAMHeader,
                 config: HBamConfig = DEFAULT_CONFIG, **kw):
        from hadoop_bam_tpu.formats.cramio import CramWriter
        kw.setdefault("write_header", config.write_header)
        kw.setdefault("write_eof", config.write_terminator)
        kw.setdefault("version", tuple(config.cram_version))
        self._w = CramWriter(sink, header, **kw)
        self.header = header
        self.records_written = 0

    def write_sam_record(self, rec: SamRecord) -> None:
        self._w.write_record(rec)
        self.records_written += 1

    def close(self) -> None:
        self._w.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class VcfShardWriter:
    """Text VCF shard writer, optionally BGZF-compressed
    (hb/KeyIgnoringVCFRecordWriter.java)."""

    def __init__(self, sink, header: "VCFHeader",
                 config: HBamConfig = DEFAULT_CONFIG,
                 write_header: Optional[bool] = None,
                 compress: bool = False, level: Optional[int] = None):
        from hadoop_bam_tpu.formats import bgzf
        if level is None:
            level = config.write_compress_level
        self._own = False
        if isinstance(sink, (str, os.PathLike)):
            sink = open(sink, "wb")
            self._own = True
        self._raw_sink = sink
        if compress:
            self._bgzf = bgzf.BGZFWriter(sink, level=level,
                                         write_eof=config.write_terminator)
        else:
            self._bgzf = None
        self.header = header
        self.records_written = 0
        if config.write_header if write_header is None else write_header:
            self._write(header.to_text().encode())

    def _write(self, data: bytes) -> None:
        (self._bgzf or self._raw_sink).write(data)

    def write_record(self, rec: "VcfRecord") -> None:
        self._write((rec.to_line() + "\n").encode())
        self.records_written += 1

    def close(self) -> None:
        if self._bgzf is not None:
            self._bgzf.close()
        if self._own:
            self._raw_sink.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class BcfShardWriter(BcfWriter):
    """BCF shard writer with reference OutputFormat knobs from config
    (hb/BCFRecordWriter)."""

    def __init__(self, sink, header: "VCFHeader",
                 config: HBamConfig = DEFAULT_CONFIG, **kw):
        kw.setdefault("write_header", config.write_header)
        kw.setdefault("write_eof", config.write_terminator)
        kw.setdefault("level", config.write_compress_level)
        super().__init__(sink, header, **kw)


def open_vcf_writer(path: str, header: "VCFHeader",
                    config: HBamConfig = DEFAULT_CONFIG):
    """hb/VCFOutputFormat: pick VCF vs BCF per extension, falling back to the
    ``vcf_output_format`` config knob (``hadoopbam.vcf.output-format``)."""
    lower = path.lower()
    if lower.endswith(".bcf") or (not lower.endswith((".vcf", ".vcf.gz"))
                                  and config.vcf_output_format.upper() == "BCF"):
        return BcfShardWriter(path, header, config)
    return VcfShardWriter(path, header, config,
                          compress=lower.endswith((".vcf.gz", ".vcf.bgz")))


class FastqShardWriter:
    """4-line FASTQ emitter (hb/FastqOutputFormat.java); optional BGZF
    compression mirrors the reference's optional Hadoop codec; qualities are
    emitted in the configured base-quality encoding."""

    def __init__(self, sink, config: HBamConfig = DEFAULT_CONFIG,
                 compress: bool = False, level: Optional[int] = None):
        from hadoop_bam_tpu.formats import bgzf
        if level is None:
            level = config.write_compress_level
        self._encoding = config.fastq_base_quality_encoding
        self._own = False
        if isinstance(sink, (str, os.PathLike)):
            sink = open(sink, "wb")
            self._own = True
        self._raw_sink = sink
        self._bgzf = bgzf.BGZFWriter(sink, level=level) if compress else None
        self.records_written = 0

    def write_record(self, frag) -> None:
        from hadoop_bam_tpu.config import BaseQualityEncoding
        from hadoop_bam_tpu.formats.fastq import convert_quality
        text = frag.to_fastq()
        if self._encoding is not BaseQualityEncoding.SANGER:
            q = convert_quality(frag.quality, BaseQualityEncoding.SANGER,
                                self._encoding)
            text = f"@{frag.name}\n{frag.sequence}\n+\n{q}\n"
        (self._bgzf or self._raw_sink).write(text.encode())
        self.records_written += 1

    def close(self) -> None:
        if self._bgzf is not None:
            self._bgzf.close()
        if self._own:
            self._raw_sink.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class QseqShardWriter:
    """Tab-line qseq emitter (hb/QseqOutputFormat.java)."""

    def __init__(self, sink, config: HBamConfig = DEFAULT_CONFIG):
        self._encoding = config.qseq_base_quality_encoding
        self._own = False
        if isinstance(sink, (str, os.PathLike)):
            sink = open(sink, "w")
            self._own = True
        self._sink = sink
        self.records_written = 0

    def write_record(self, frag) -> None:
        from hadoop_bam_tpu.formats.qseq import format_qseq_line
        self._sink.write(format_qseq_line(frag, self._encoding) + "\n")
        self.records_written += 1

    def close(self) -> None:
        if self._own:
            self._sink.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def write_records(path: str, header: SAMHeader,
                  records: Iterable[Union[SamRecord, bytes]],
                  config: HBamConfig = DEFAULT_CONFIG) -> int:
    """One-shot convenience: write a full SAM/BAM file."""
    w = open_any_sam_writer(path, header, config=config)
    with w:
        for r in records:
            if isinstance(r, (bytes, bytearray)) and isinstance(w, BamShardWriter):
                w.write_record_bytes(bytes(r))
            else:
                w.write_sam_record(r)
        return w.records_written
