"""Variant datasets: the VCF/BCF InputFormat surface, iterator-shaped.

Rebuild of hb/VCFInputFormat.java + hb/VCFRecordReader.java +
hb/BCFRecordReader.java (SURVEY.md section 2.3): ``open_vcf(path)`` resolves
the container (text VCF, BGZF VCF, BCF — api/dispatch.py), reads the header
once (hb/util/VCFHeaderReader.java did this per task; we cache it), plans
spans, and yields records or SoA ``VariantBatch``es per span.
"""
from __future__ import annotations

from typing import Dict, Iterator, List, Optional, Union

import numpy as np

from hadoop_bam_tpu.config import DEFAULT_CONFIG, HBamConfig, ValidationStringency
from hadoop_bam_tpu.api.dispatch import VCFContainer, sniff_vcf_container
from hadoop_bam_tpu.formats import bgzf
from hadoop_bam_tpu.formats.bcfio import read_bcf_header
from hadoop_bam_tpu.formats.vcf import (
    VCFHeader, VariantBatch, VcfRecord, read_vcf_header_text,
)
from hadoop_bam_tpu.split.planners import plan_text_spans, read_text_span
from hadoop_bam_tpu.split.spans import FileByteSpan, FileVirtualSpan
from hadoop_bam_tpu.split.vcf_planners import (
    plan_bcf_spans, plan_bgzf_text_spans, read_bcf_span, read_bgzf_text_span,
)
from hadoop_bam_tpu.utils.seekable import as_byte_source

Span = Union[FileByteSpan, FileVirtualSpan]


class VcfDataset:
    """Record-aligned access to one VCF/BCF file in any container."""

    def __init__(self, path: str, config: HBamConfig = DEFAULT_CONFIG,
                 container: Optional[VCFContainer] = None):
        self.path = path
        self.config = config
        self.container = container or sniff_vcf_container(path, config)
        self._is_bgzf_bcf = False
        self.header = self._read_header()
        self._plan: Optional[List[Span]] = None
        self._next_span = 0

    # -- header (hb/util/VCFHeaderReader.java) -------------------------------
    def _read_header(self) -> VCFHeader:
        src = as_byte_source(self.path)
        try:
            if self.container is VCFContainer.VCF:
                header, _ = read_vcf_header_text(src.pread)
                return header
            if self.container is VCFContainer.VCF_BGZF:
                r = bgzf.BGZFReader(src)

                def read_chunk(off: int, size: int) -> bytes:
                    r.seek_voffset(0)
                    r.read(off)  # positions are tiny (header-sized)
                    return r.read(size)
                header, _ = read_vcf_header_text(read_chunk)
                return header
            if self.container is VCFContainer.VCF_GZIP:
                import gzip
                text = gzip.decompress(src.pread(0, src.size))

                def read_chunk(off: int, size: int) -> bytes:
                    return text[off:off + size]
                header, _ = read_vcf_header_text(read_chunk)
                return header
            header, _, self._is_bgzf_bcf = read_bcf_header(src)
            return header
        finally:
            src.close()

    # -- planning (hb/VCFInputFormat.getSplits) ------------------------------
    def spans(self, num_spans: Optional[int] = None) -> List[Span]:
        from hadoop_bam_tpu.api.dataset import _check_replan
        _check_replan(self, num_spans)
        if self._plan is None:
            self._plan_num_spans = num_spans
            if self.container is VCFContainer.VCF:
                self._plan = plan_text_spans(
                    self.path, num_spans=num_spans,
                    span_bytes=None if num_spans else self.config.split_size)
            elif self.container is VCFContainer.VCF_BGZF:
                self._plan = plan_bgzf_text_spans(
                    self.path, num_spans=num_spans, config=self.config)
            elif self.container is VCFContainer.VCF_GZIP:
                # plain gzip is not splittable: one whole-file span
                # (hb/util/BGZFEnhancedGzipCodec fallback)
                src = as_byte_source(self.path)
                try:
                    self._plan = [FileByteSpan(self.path, 0, src.size)]
                finally:
                    src.close()
            else:
                self._plan = plan_bcf_spans(
                    self.path, num_spans=num_spans, config=self.config,
                    header=self.header)
        return self._plan

    def read_span_text(self, span: Span) -> Optional[bytes]:
        """Raw text bytes of a span (None for the binary BCF container) —
        the input of the fast column tokenizer
        (parallel/variant_pipeline.pack_variant_tiles_from_text)."""
        if self.container is VCFContainer.BCF:
            return None
        if self.container is VCFContainer.VCF_BGZF:
            return read_bgzf_text_span(self.path, span)
        if self.container is VCFContainer.VCF_GZIP:
            import gzip
            with open(self.path, "rb") as f:
                return gzip.decompress(f.read())
        return read_text_span(self.path, span)

    # -- span read (hb/VCFRecordReader / hb/BCFRecordReader) -----------------
    def read_span(self, span: Span) -> List[VcfRecord]:
        if self.container is VCFContainer.BCF:
            return read_bcf_span(self.path, span, header=self.header,
                                 is_bgzf=self._is_bgzf_bcf)
        if self.container is VCFContainer.VCF_BGZF:
            text = read_bgzf_text_span(self.path, span)
        elif self.container is VCFContainer.VCF_GZIP:
            import gzip
            with open(self.path, "rb") as f:
                text = gzip.decompress(f.read())
        else:
            text = read_text_span(self.path, span)
        out: List[VcfRecord] = []
        for line in text.decode().splitlines():
            if not line or line.startswith("#"):
                continue
            try:
                out.append(VcfRecord.from_line(line))
            except Exception:
                if (self.config.validation_stringency
                        is ValidationStringency.STRICT):
                    raise
        return out

    def records(self, num_spans: Optional[int] = None) -> Iterator[VcfRecord]:
        plan = self.spans(num_spans)
        if self._next_span >= len(plan):
            self._next_span = 0
        while self._next_span < len(plan):
            span = plan[self._next_span]
            recs = self.read_span(span)
            self._next_span += 1
            yield from recs

    def batches(self, num_spans: Optional[int] = None
                ) -> Iterator[VariantBatch]:
        plan = self.spans(num_spans)
        if self._next_span >= len(plan):
            self._next_span = 0
        while self._next_span < len(plan):
            span = plan[self._next_span]
            recs = self.read_span(span)
            self._next_span += 1
            yield VariantBatch(recs, self.header)

    def tensor_batches(self, mesh=None, geometry=None,
                       num_spans: Optional[int] = None) -> Iterator[Dict]:
        """Yield device-resident variant tensor batches sharded over the
        mesh's data axis: ``chrom``/``pos`` int32 [n_dev, cap], ``flags``
        uint8 (bit0 PASS, bit1 SNP), ``dosage`` int8 [n_dev, cap, S_pad]
        (ALT-allele dosage, -1 missing), ``n_records`` int32 [n_dev].

        Padding rows (beyond each shard's ``n_records``) carry the
        missing-value sentinels UNIFORMLY: dosage -1, qual NaN, other
        columns 0.  (Before the staging-ring feed, shards of the final
        group that received no spans were zero-filled — dosage 0 read
        as a hom-ref call; mask by ``n_records`` either way.)"""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        from hadoop_bam_tpu.parallel.mesh import make_mesh
        from hadoop_bam_tpu.parallel.pipeline import _iter_windowed
        from hadoop_bam_tpu.parallel.variant_pipeline import (
            VariantGeometry, pack_variant_tiles, variant_feed,
        )
        from hadoop_bam_tpu.utils.pools import decode_pool, decode_pool_size

        if mesh is None:
            mesh = make_mesh()
        if geometry is None:
            geometry = VariantGeometry(n_samples=self.header.n_samples)
        n_dev = int(np.prod(mesh.devices.shape))
        cap = geometry.tile_records
        sharding = NamedSharding(mesh, P("data"))
        spans = self.spans(num_spans)
        pool = decode_pool(self.config)

        def decode(span):
            if self.container is VCFContainer.BCF:
                # columnar fast path: no VcfRecord objects
                # (formats/bcf_columns.py, record-scan fallback)
                from hadoop_bam_tpu.parallel.variant_pipeline import (
                    bcf_span_stat_columns,
                )
                return bcf_span_stat_columns(
                    self.path, span, self.header, geometry,
                    self._is_bgzf_bcf)
            return pack_variant_tiles(
                VariantBatch(self.read_span(span), self.header),
                geometry)

        stream = _iter_windowed(pool, spans, decode,
                                2 * decode_pool_size(self.config),
                                config=self.config)
        # variant_feed peeks the first span's dict for the schema (same
        # genericity as the old serial tiler); fixed_shape keeps the
        # historical contract that every variant tensor batch carries
        # full tile_records rows
        keys, fp, tuples = variant_feed(stream, n_dev, cap, self.config,
                                        fixed_shape=True, fmt="vcf")
        if fp is None:
            return

        def emit(arrays, counts) -> Dict:
            # the device dict doubles as the slot's in-flight handle
            out = {k: jax.device_put(a, sharding)
                   for k, a in zip(keys, arrays)}
            out["n_records"] = jax.device_put(counts, sharding)
            return out

        yield from fp.stream(tuples, emit)

    def variant_stats(self, mesh=None, geometry=None) -> Dict:
        """Distributed variant/SNP/PASS counts, mean ALT allele frequency,
        and per-sample call rates (parallel/variant_pipeline.py)."""
        from hadoop_bam_tpu.parallel.variant_pipeline import (
            variant_stats_file,
        )
        return variant_stats_file(self.path, mesh=mesh, config=self.config,
                                  header=self.header)

    def query(self, region: str) -> Iterator[VcfRecord]:
        """Random access via a ``.tbi`` sidecar (BGZF VCF): yields records
        overlapping the samtools-style region (``chr``, ``chr:start-end``)
        reading only the index's chunk ranges — build the sidecar with
        split.tabix.write_tabix or ``hbam index --flavor tbi``."""
        from hadoop_bam_tpu.split.intervals import parse_interval
        from hadoop_bam_tpu.split.tabix import TBI_SUFFIX, load_tabix_for
        from hadoop_bam_tpu.utils.seekable import as_byte_source

        if self.container is not VCFContainer.VCF_BGZF:
            raise ValueError("query() needs a BGZF-compressed VCF "
                             "(.vcf.gz); plain text/gzip cannot be "
                             "random-accessed")
        idx = load_tabix_for(self.path)
        if idx is None:
            raise FileNotFoundError(
                f"{self.path}{TBI_SUFFIX} not found — build it with "
                "split.tabix.write_tabix")
        iv = parse_interval(region)
        ranges = idx.query(iv.rname, iv.start - 1, iv.end)
        src = as_byte_source(self.path)
        try:
            r = bgzf.BGZFReader(src)
            for v0, v1 in ranges:
                r.seek_voffset(v0)
                text = r.read_to_voffset(v1)
                for line in text.split(b"\n"):
                    if not line or line[:1] == b"#":
                        continue
                    try:
                        rec = VcfRecord.from_line(line.decode())
                    except Exception:
                        if (self.config.validation_stringency
                                is ValidationStringency.STRICT):
                            raise
                        continue
                    if rec.chrom != iv.rname:
                        continue
                    if rec.pos <= iv.end and rec.pos + rec.rlen - 1 >= iv.start:
                        yield rec
        finally:
            src.close()

    # -- checkpoint / resume (SURVEY.md section 5) ---------------------------
    def state_dict(self) -> Dict:
        return {
            "path": self.path,
            "container": self.container.value,
            "plan": [s.to_dict() for s in (self._plan or [])],
            "next_span": self._next_span,
        }

    def load_state_dict(self, state: Dict) -> None:
        assert state["path"] == self.path
        cls = (FileVirtualSpan if self.container is VCFContainer.BCF
               else FileByteSpan)
        self._plan = [cls.from_dict(d) for d in state["plan"]] or None
        self._next_span = int(state["next_span"])


def open_vcf(path: str, config: HBamConfig = DEFAULT_CONFIG) -> VcfDataset:
    """hb/VCFInputFormat: resolve VCF/BCF container, return the dataset."""
    return VcfDataset(path, config)
