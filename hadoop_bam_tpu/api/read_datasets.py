"""Read datasets: the FASTQ/QSEQ/FASTA InputFormat surface, iterator-shaped.

Rebuild of hb/FastqInputFormat.java, hb/QseqInputFormat.java,
hb/FastaInputFormat.java (SURVEY.md section 2.3) in dataset clothes, plus a
padded-array bridge that feeds device pipelines the same way BamBatch does.
"""
from __future__ import annotations

from typing import Dict, Iterator, List, Optional, Tuple

import numpy as np

from hadoop_bam_tpu.config import DEFAULT_CONFIG, HBamConfig
from hadoop_bam_tpu.formats.fasta import ReferenceFragment, parse_fasta
from hadoop_bam_tpu.formats.fastq import SequencedFragment, parse_fastq
from hadoop_bam_tpu.formats.qseq import parse_qseq
from hadoop_bam_tpu.split.planners import plan_text_spans, read_text_span
from hadoop_bam_tpu.split.read_planners import (
    plan_fasta_spans, read_fasta_span, read_fastq_span,
)
from hadoop_bam_tpu.split.spans import FileByteSpan
from hadoop_bam_tpu.utils.seekable import scoped_byte_source


class _SpannedDataset:
    """Shared span bookkeeping + checkpoint/resume."""

    def __init__(self, path: str, config: HBamConfig):
        self.path = path
        self.config = config
        self._plan: Optional[List[FileByteSpan]] = None
        self._plan_num_spans: Optional[int] = None
        self._next_span = 0

    def read_span(self, span: FileByteSpan) -> List:
        raise NotImplementedError

    def _iter_spans(self, num_spans: Optional[int]) -> Iterator:
        """Span-granular resumable iteration (state = spans delivered).
        A fresh call after exhaustion restarts from the beginning; a call
        after load_state_dict resumes mid-plan."""
        plan = self.spans(num_spans)
        if self._next_span >= len(plan):
            self._next_span = 0
        while self._next_span < len(plan):
            recs = self.read_span(plan[self._next_span])
            self._next_span += 1
            yield from recs

    def _is_compressed(self) -> bool:
        """gzip/BGZF input?  Compressed text reads as ONE span over the
        inflated stream — the reference's behavior for non-splittable
        Hadoop codecs."""
        cached = getattr(self, "_compressed", None)
        if cached is None:
            with scoped_byte_source(self.path) as src:
                cached = src.pread(0, 2) == b"\x1f\x8b"
            self._compressed = cached
        return cached

    def _plan_spans(self, num_spans: Optional[int]) -> List[FileByteSpan]:
        if self._is_compressed():
            with scoped_byte_source(self.path) as src:
                return [FileByteSpan(self.path, 0, src.size)]
        return plan_text_spans(self.path, num_spans=num_spans,
                               span_bytes=None if num_spans
                               else self.config.split_size)

    def _span_text(self, span: FileByteSpan, reader) -> bytes:
        """Span text via ``reader(path, span)``, decompressing the whole
        file for the single compressed-input span."""
        if span.start == 0 and self._is_compressed():
            import gzip
            with open(self.path, "rb") as f:
                return gzip.decompress(f.read())
        return reader(self.path, span)

    def spans(self, num_spans: Optional[int] = None) -> List[FileByteSpan]:
        if self._plan is not None and num_spans is not None \
                and num_spans != self._plan_num_spans:
            raise ValueError(
                f"span plan already built with num_spans="
                f"{self._plan_num_spans}; open a new dataset to re-plan")
        if self._plan is None:
            self._plan = self._plan_spans(num_spans)
            self._plan_num_spans = num_spans
        return self._plan

    def state_dict(self) -> Dict:
        return {"path": self.path,
                "plan": [s.to_dict() for s in (self._plan or [])],
                "next_span": self._next_span}

    def load_state_dict(self, state: Dict) -> None:
        assert state["path"] == self.path
        self._plan = [FileByteSpan.from_dict(d) for d in state["plan"]] or None
        self._next_span = int(state["next_span"])


class FastqDataset(_SpannedDataset):
    """Splittable FASTQ: record-quadruple alignment at every span
    boundary; compressed inputs read as one span (base class)."""

    def read_span_text(self, span: FileByteSpan) -> bytes:
        """Raw record-aligned text of a span (whole file when gzipped) —
        the input to both the object parse and the vectorized tile path."""
        return self._span_text(span, read_fastq_span)

    def read_span(self, span: FileByteSpan) -> List[SequencedFragment]:
        return parse_fastq(self.read_span_text(span),
                           encoding=self.config.fastq_base_quality_encoding,
                           filter_failed_qc=self.config.fastq_filter_failed_qc)

    def records(self, num_spans: Optional[int] = None
                ) -> Iterator[SequencedFragment]:
        return self._iter_spans(num_spans)

    def tensor_batches(self, mesh=None, geometry=None,
                       num_spans: Optional[int] = None) -> Iterator[Dict]:
        """Device-resident read batches sharded over the mesh's data axis:
        ``seq_packed`` uint8 [n_dev, cap, seq_stride] (BAM 4-bit nibble
        codes, same alphabet as BamDataset.tensor_batches), ``qual`` uint8,
        ``lengths`` int32 [n_dev, cap], ``n_records`` int32 [n_dev].
        The FINAL batch may arrive with fewer rows than
        geometry.tile_records (shrunk to the smallest dispatch bucket) —
        size consumer buffers from each batch's own shape."""
        from hadoop_bam_tpu.parallel.pipeline import (
            stream_read_tensor_batches,
        )
        yield from stream_read_tensor_batches(
            self.spans(num_spans), self.read_span, self.config, mesh,
            geometry, fmt="fastq")


class QseqDataset(_SpannedDataset):
    """Illumina qseq: one record per line."""

    def read_span_text(self, span: FileByteSpan) -> bytes:
        return self._span_text(span, read_text_span)

    def read_span(self, span: FileByteSpan) -> List[SequencedFragment]:
        return parse_qseq(self.read_span_text(span),
                          encoding=self.config.qseq_base_quality_encoding,
                          filter_failed_qc=self.config.qseq_filter_failed_qc)

    def records(self, num_spans: Optional[int] = None
                ) -> Iterator[SequencedFragment]:
        return self._iter_spans(num_spans)

    def tensor_batches(self, mesh=None, geometry=None,
                       num_spans: Optional[int] = None) -> Iterator[Dict]:
        """Same device batch layout as FastqDataset.tensor_batches."""
        from hadoop_bam_tpu.parallel.pipeline import (
            stream_read_tensor_batches,
        )
        yield from stream_read_tensor_batches(
            self.spans(num_spans), self.read_span, self.config, mesh,
            geometry, fmt="qseq")


class FastaDataset(_SpannedDataset):
    """Reference FASTA: spans hold whole contigs (snapped to '>')."""

    def _plan_spans(self, num_spans: Optional[int]) -> List[FileByteSpan]:
        return plan_fasta_spans(self.path, num_spans=num_spans,
                                config=self.config)

    def read_span(self, span: FileByteSpan) -> List[ReferenceFragment]:
        return parse_fasta(read_fasta_span(self.path, span))

    def fragments(self, num_spans: Optional[int] = None
                  ) -> Iterator[ReferenceFragment]:
        return self._iter_spans(num_spans)

    def window_tensor_batches(self, window: int = 1024, stride: int = 0,
                              mesh=None, geometry=None,
                              num_spans: Optional[int] = None
                              ) -> Iterator[Dict]:
        """Reference windows as device tensors: each contig is cut into
        ``window``-base pieces every ``stride`` bases (default stride =
        window, i.e. non-overlapping) and packed into the same 4-bit
        nibble tiles as the read feeds — the reference-context input for
        models that consume (read, reference) pairs.  Yields the
        FastqDataset.tensor_batches layout."""
        from hadoop_bam_tpu.parallel.pipeline import (
            PayloadGeometry, stream_read_tensor_batches,
        )

        stride = stride or window
        if geometry is None:
            geometry = PayloadGeometry(max_len=window)

        def read_windows(span) -> List[SequencedFragment]:
            out: List[SequencedFragment] = []
            # contig-order reassembly: fragments of one contig arrive in
            # position order within a span (spans snap to '>')
            per_contig: Dict[str, List[ReferenceFragment]] = {}
            for frag in self.read_span(span):
                per_contig.setdefault(frag.contig, []).append(frag)
            for contig, frags in per_contig.items():
                seq = "".join(f.sequence for f in frags)
                n = len(seq)
                if not n:
                    continue
                if n <= window:
                    out.append(SequencedFragment(sequence=seq, quality=""))
                    continue
                last = n - window
                starts = list(range(0, last + 1, stride))
                if starts[-1] != last:
                    starts.append(last)  # flush a final full window
                for off in starts:
                    out.append(SequencedFragment(
                        sequence=seq[off:off + window], quality=""))
            return out

        yield from stream_read_tensor_batches(
            self.spans(num_spans), read_windows, self.config, mesh,
            geometry, fmt="fasta")


def open_fastq(path: str, config: HBamConfig = DEFAULT_CONFIG) -> FastqDataset:
    return FastqDataset(path, config)


def open_qseq(path: str, config: HBamConfig = DEFAULT_CONFIG) -> QseqDataset:
    return QseqDataset(path, config)


def open_fasta(path: str, config: HBamConfig = DEFAULT_CONFIG) -> FastaDataset:
    return FastaDataset(path, config)


# ---------------------------------------------------------------------------
# device bridge: fragments -> fixed-shape arrays
# ---------------------------------------------------------------------------

# Unknown/ambiguity characters (IUPAC codes, gaps) map to N (4), never to a
# confident base; 5 is reserved for padding.
_BASE_CODE = np.full(256, 4, dtype=np.uint8)
for i, c in enumerate("ACGT"):
    _BASE_CODE[ord(c)] = i
    _BASE_CODE[ord(c.lower())] = i


# ASCII -> BAM 4-bit base codes [SPEC]: the same nibble alphabet the BAM
# payload tiles use, so one Pallas kernel (ops/seq_pallas.py) serves every
# read format.  Unknown characters map to N (15).
_NIBBLE_CODE = np.full(256, 15, dtype=np.uint8)
for _c, _code in (("=", 0), ("A", 1), ("C", 2), ("M", 3), ("G", 4),
                  ("R", 5), ("S", 6), ("V", 7), ("T", 8), ("W", 9),
                  ("Y", 10), ("H", 11), ("K", 12), ("D", 13), ("B", 14),
                  ("N", 15)):
    _NIBBLE_CODE[ord(_c)] = _code
    _NIBBLE_CODE[ord(_c.lower())] = _code


def _scan_lines(buf: np.ndarray) -> Tuple[np.ndarray, np.ndarray, bool]:
    """Newline scan -> CRLF-safe (starts, ends, synthesized_last) line
    table.  A final line without a terminating newline still counts as a
    line; ``synthesized_last`` marks it so callers can drop only THAT
    line when it is empty (a real empty line must be kept or rejected by
    format-specific rules)."""
    nl = np.flatnonzero(buf == 0x0A)
    synthesized_last = nl.size == 0 or nl[-1] != buf.size - 1
    if synthesized_last:
        nl = np.append(nl, buf.size)
    starts = np.empty(nl.size, dtype=np.int64)
    starts[0] = 0
    starts[1:] = nl[:-1] + 1
    ends = nl.copy()
    has_cr = (ends > starts) & (buf[np.minimum(ends - 1, buf.size - 1)]
                                == 0x0D)
    ends = ends - has_cr
    return starts, ends, synthesized_last


def _pack_seq_qual_tiles(buf: np.ndarray, seq_starts: np.ndarray,
                         qual_starts: np.ndarray, lengths: np.ndarray,
                         seq_stride: int, qual_stride: int,
                         qual_offset: int,
                         guard_lens: Optional[np.ndarray] = None
                         ) -> Tuple[np.ndarray, np.ndarray]:
    """Gather per-record SEQ/QUAL runs into payload tiles: nibble-code +
    pair-pack the bases, re-base the qualities with the wrong-encoding
    guard (shared by the FASTQ and QSEQ grid tokenizers — their behavior
    must stay byte-identical, so this is one function).

    ``guard_lens`` is the UNTRUNCATED quality-field length per record:
    the object parsers (convert_quality) validate the whole string, not
    just the max_len prefix the tiles keep, so the guard must too."""
    from hadoop_bam_tpu.formats.fastq import FastqError

    n = lengths.size
    seq = np.zeros((n, seq_stride), dtype=np.uint8)
    qual = np.zeros((n, qual_stride), dtype=np.uint8)
    if qual_offset != 33 and n and guard_lens is not None             and guard_lens.size:
        Lg = int(guard_lens.max())
        if Lg:
            colg = np.arange(Lg, dtype=np.int64)[None, :]
            maskg = colg < guard_lens[:, None]
            gg = np.minimum(qual_starts[:, None] + colg, buf.size - 1)
            vals = buf[gg].astype(np.int16) - qual_offset
            # mirror convert_quality: re-based ASCII must stay printable,
            # i.e. Phred in [0, 93], over the FULL field
            bad = maskg & ((vals < 0) | (vals > 93))
            if bad.any():
                raise FastqError(
                    "quality out of range after re-encoding — wrong "
                    "base-quality-encoding config?")
    L = int(lengths.max()) if n else 0
    if not L:
        return seq, qual
    L_even = L + (L & 1)
    col = np.arange(L_even, dtype=np.int64)[None, :]
    mask = col < lengths[:, None]
    g = np.minimum(seq_starts[:, None] + col, buf.size - 1)
    codes = np.where(mask, _NIBBLE_CODE[buf[g]], 0).astype(np.uint8)
    packed = (codes[:, 0::2] << 4) | codes[:, 1::2]
    ks = min(packed.shape[1], seq_stride)
    seq[:, :ks] = packed[:, :ks]

    gq = np.minimum(qual_starts[:, None] + col[:, :L], buf.size - 1)
    q = np.where(mask[:, :L], buf[gq].astype(np.int16) - qual_offset, 0)
    kq = min(L, qual_stride)
    qual[:, :kq] = np.clip(q, 0, 255).astype(np.uint8)[:, :kq]
    return seq, qual


def fastq_text_to_payload_tiles(text: bytes, seq_stride: int,
                                qual_stride: int, max_len: int,
                                qual_offset: int = 33
                                ) -> Tuple[np.ndarray, np.ndarray,
                                           np.ndarray]:
    """Vectorized FASTQ span -> payload tiles, no per-read Python objects.

    The stats drivers only need (packed bases, qualities, lengths); going
    through parse_fastq costs a SequencedFragment (with run-metadata name
    parsing) per read and dominates the FASTQ pipeline wall clock.  This
    path tokenizes the whole span with NumPy: newline scan -> line table ->
    4-line record grid -> one clamped gather per payload matrix.

    Validation matches parse_fastq's strictness where cheap (4n lines,
    '@'/'+' leads, SEQ/QUAL length equality); it raises the same FastqError.
    """
    from hadoop_bam_tpu.formats.fastq import FastqError

    buf = np.frombuffer(text, dtype=np.uint8)
    if buf.size == 0:
        return (np.zeros((0, seq_stride), np.uint8),
                np.zeros((0, qual_stride), np.uint8),
                np.zeros((0,), np.int32))
    starts, ends, synthesized_last = _scan_lines(buf)
    # drop only the synthesized final line when empty — a real
    # zero-length final line (legal zero-length read) must be kept
    if synthesized_last and starts[-1] >= ends[-1]:
        starts, ends = starts[:-1], ends[:-1]
    if starts.size % 4:
        raise FastqError(f"FASTQ span has {starts.size} lines (not 4n)")
    n = starts.size // 4
    if n == 0:
        return (np.zeros((0, seq_stride), np.uint8),
                np.zeros((0, qual_stride), np.uint8),
                np.zeros((0,), np.int32))
    s4 = starts.reshape(n, 4)
    e4 = ends.reshape(n, 4)
    if not (buf[s4[:, 0]] == ord("@")).all() \
            or not (buf[s4[:, 2]] == ord("+")).all():
        bad = int(np.flatnonzero((buf[s4[:, 0]] != ord("@"))
                                 | (buf[s4[:, 2]] != ord("+")))[0])
        raise FastqError(f"malformed FASTQ record at line {bad * 4}")
    seq_len = e4[:, 1] - s4[:, 1]
    if not (seq_len == e4[:, 3] - s4[:, 3]).all():
        raise FastqError("SEQ/QUAL length mismatch")
    lengths = np.minimum(seq_len, max_len).astype(np.int32)
    seq, qual = _pack_seq_qual_tiles(buf, s4[:, 1], s4[:, 3], lengths,
                                     seq_stride, qual_stride, qual_offset,
                                     guard_lens=seq_len)
    return seq, qual, lengths


def qseq_text_to_payload_tiles(text: bytes, seq_stride: int,
                               qual_stride: int, max_len: int,
                               qual_offset: int = 64
                               ) -> Tuple[np.ndarray, np.ndarray,
                                          np.ndarray]:
    """Vectorized QSEQ span -> payload tiles (the 11-tab-field twin of
    fastq_text_to_payload_tiles): newline/tab grid -> one gather each for
    the SEQ (field 8; '.' reads as N via the nibble table) and QUAL
    (field 9, Illumina +64 by default) columns.  Validation matches
    parse_qseq: exactly 11 fields, SEQ/QUAL equal length, loud
    wrong-encoding guard."""
    from hadoop_bam_tpu.formats.fastq import FastqError

    buf = np.frombuffer(text, dtype=np.uint8)
    empty = (np.zeros((0, seq_stride), np.uint8),
             np.zeros((0, qual_stride), np.uint8),
             np.zeros((0,), np.int32))
    if buf.size == 0:
        return empty
    starts, ends, _synth = _scan_lines(buf)
    keep = ends > starts                    # parse_qseq skips empty lines
    starts, ends = starts[keep], ends[keep]
    n = starts.size
    if n == 0:
        return empty

    tabs = np.flatnonzero(buf == 0x09)
    t0 = np.searchsorted(tabs, starts)
    t1 = np.searchsorted(tabs, ends)
    ntab = t1 - t0
    if not (ntab == 10).all():
        bad = int(np.flatnonzero(ntab != 10)[0])
        raise FastqError(f"qseq line has {int(ntab[bad]) + 1} fields, "
                         f"need 11")
    k = np.arange(10, dtype=np.int64)[None, :]
    tabm = tabs[t0[:, None] + k]
    fs = np.concatenate([starts[:, None], tabm + 1], axis=1)
    fe = np.concatenate([tabm, ends[:, None]], axis=1)
    seq_len = fe[:, 8] - fs[:, 8]
    qual_len = fe[:, 9] - fs[:, 9]
    if not (seq_len == qual_len).all():
        raise FastqError("qseq SEQ/QUAL length mismatch")
    lengths = np.minimum(seq_len, max_len).astype(np.int32)
    seq, qual = _pack_seq_qual_tiles(buf, fs[:, 8], fs[:, 9], lengths,
                                     seq_stride, qual_stride, qual_offset,
                                     guard_lens=seq_len)
    return seq, qual, lengths


def ragged_to_payload_tiles(seq_cat: bytes, seq_lens: np.ndarray,
                            qual_cat: bytes, qual_lens: np.ndarray,
                            seq_stride: int, qual_stride: int,
                            max_len: int, qual_offset: int = 0
                            ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Concatenated ragged sequences/qualities -> payload tiles, fully
    vectorized (the packing half of fastq_text_to_payload_tiles, for
    producers that already hold decoded bytes — e.g. CRAM records).

    ``qual_cat`` holds per-record quality runs of ``qual_lens`` bytes;
    ``qual_offset`` is subtracted (0 when the bytes are already raw
    Phred, 33 for printable ASCII).  Records with no quality simply have
    qual_lens 0 — their tile rows stay zero."""
    n = seq_lens.size
    seq = np.zeros((n, seq_stride), dtype=np.uint8)
    qual = np.zeros((n, qual_stride), dtype=np.uint8)
    lengths = np.minimum(seq_lens, max_len).astype(np.int32)
    if n == 0:
        return seq, qual, lengths
    sbuf = np.frombuffer(seq_cat, dtype=np.uint8)
    qbuf = np.frombuffer(qual_cat, dtype=np.uint8)
    s0 = np.cumsum(seq_lens, dtype=np.int64) - seq_lens
    q0 = np.cumsum(qual_lens, dtype=np.int64) - qual_lens

    L = int(lengths.max())
    if L:
        # uniform read length (the overwhelmingly common case): the
        # concatenated buffer IS the (n, len) matrix — reshape instead
        # of building per-row gather/mask matrices
        if int(seq_lens.min()) == int(seq_lens.max()):
            rl0 = int(seq_lens[0])
            mat = sbuf[:n * rl0].reshape(n, rl0)[:, :L]
            codes = _NIBBLE_CODE[mat]
            if L & 1:
                codes = np.concatenate(
                    [codes, np.zeros((n, 1), np.uint8)], axis=1)
        else:
            L_even = L + (L & 1)
            col = np.arange(L_even, dtype=np.int64)[None, :]
            mask = col < lengths[:, None]
            g = np.minimum(s0[:, None] + col, max(sbuf.size - 1, 0))
            codes = np.where(mask, _NIBBLE_CODE[sbuf[g]], 0
                             ).astype(np.uint8)
        packed = (codes[:, 0::2] << 4) | codes[:, 1::2]
        ks = min(packed.shape[1], seq_stride)
        seq[:, :ks] = packed[:, :ks]

    qlen = np.minimum(qual_lens, max_len).astype(np.int64)
    Lq = int(qlen.max(initial=0))
    if Lq and qbuf.size:
        kq = min(Lq, qual_stride)
        if int(qual_lens.min()) == int(qual_lens.max()):
            ql0 = int(qual_lens[0])
            mat = qbuf[:n * ql0].reshape(n, ql0)[:, :kq]
            if qual_offset:
                qual[:, :kq] = np.clip(
                    mat.astype(np.int16) - qual_offset, 0, 255
                ).astype(np.uint8)
            else:
                qual[:, :kq] = mat
        else:
            colq = np.arange(Lq, dtype=np.int64)[None, :]
            maskq = colq < qlen[:, None]
            gq = np.minimum(q0[:, None] + colq, qbuf.size - 1)
            vals = np.where(maskq, qbuf[gq].astype(np.int16)
                            - qual_offset, 0)
            qual[:, :kq] = np.clip(vals, 0, 255).astype(np.uint8)[:, :kq]
    return seq, qual, lengths


def fragments_to_payload_tiles(frags: List[SequencedFragment],
                               seq_stride: int, qual_stride: int,
                               max_len: int
                               ) -> Tuple[np.ndarray, np.ndarray,
                                          np.ndarray]:
    """Pack reads into the BAM-payload tile layout (4-bit bases, 2/byte,
    high nibble first; Phred quality bytes) — the FASTQ/QSEQ entry into
    the device payload path.  Returns (seq [n, seq_stride] uint8,
    qual [n, qual_stride] uint8, lengths [n] int32)."""
    n = len(frags)
    seq = np.zeros((n, seq_stride), dtype=np.uint8)
    qual = np.zeros((n, qual_stride), dtype=np.uint8)
    lengths = np.zeros(n, dtype=np.int32)
    for i, f in enumerate(frags):
        l = min(len(f.sequence), max_len)
        lengths[i] = l
        raw = np.frombuffer(f.sequence[:l].encode("latin-1"), np.uint8)
        codes = _NIBBLE_CODE[raw]
        if l % 2:
            codes = np.concatenate([codes, np.zeros(1, np.uint8)])
        packed = (codes[0::2] << 4) | codes[1::2]
        seq[i, :packed.size] = packed
        q = np.frombuffer(f.quality[:l].encode("latin-1"), np.uint8)
        qual[i, :q.size] = q - 33  # quality may be absent (FASTA windows)
    return seq, qual, lengths


def fragments_to_arrays(frags: List[SequencedFragment], max_len: int
                        ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Pad/truncate reads into fixed shapes for the device:
    (bases [n, max_len] uint8 codes A0 C1 G2 T3 N4 pad5,
     quals [n, max_len] uint8 Phred values, lengths [n] int32)."""
    n = len(frags)
    bases = np.full((n, max_len), 5, dtype=np.uint8)
    quals = np.zeros((n, max_len), dtype=np.uint8)
    lengths = np.zeros(n, dtype=np.int32)
    for i, f in enumerate(frags):
        l = min(len(f.sequence), max_len)
        lengths[i] = l
        seq = np.frombuffer(f.sequence[:l].encode("latin-1"), dtype=np.uint8)
        bases[i, :l] = _BASE_CODE[seq]
        q = np.frombuffer(f.quality[:l].encode("latin-1"), dtype=np.uint8)
        quals[i, :l] = q - 33
    return bases, quals, lengths
