"""Format dispatch: extension + magic-byte sniffing.

Rebuild of hb/SAMFormat.java (enum SAM/BAM/CRAM with ``inferFromFilePath`` /
``inferFromData``), hb/VCFFormat.java (VCF/BCF), and the per-path resolution
+ trust-exts semantics of hb/AnySAMInputFormat.java / hb/VCFInputFormat.java.

Magics [SPEC]: BAM = BGZF block whose inflated payload starts "BAM\\1";
CRAM = "CRAM"; BCF = "BCF" (optionally inside BGZF); text VCF starts
"##fileformat="; otherwise SAM (text with @header or alignment lines).
"""
from __future__ import annotations

import enum
import os
from typing import Dict, Optional

from hadoop_bam_tpu.config import DEFAULT_CONFIG, HBamConfig
from hadoop_bam_tpu.formats import bgzf
from hadoop_bam_tpu.utils.seekable import as_byte_source


class SAMContainer(enum.Enum):
    SAM = "sam"
    BAM = "bam"
    CRAM = "cram"


class VCFContainer(enum.Enum):
    VCF = "vcf"       # plain text
    VCF_BGZF = "vcf.gz"
    # plain-gzip (non-BGZF) .vcf.gz: readable but NOT splittable — decoded
    # as one whole-file span, the hb/util/BGZFEnhancedGzipCodec.java
    # fallback behavior
    VCF_GZIP = "vcf.gz(plain)"
    BCF = "bcf"


_SAM_EXT = {".sam": SAMContainer.SAM, ".bam": SAMContainer.BAM,
            ".cram": SAMContainer.CRAM}
_VCF_EXT = {".vcf": VCFContainer.VCF, ".bcf": VCFContainer.BCF}

# per-path sniff cache, as in hb/AnySAMInputFormat (formatMap)
_sam_cache: Dict[str, SAMContainer] = {}
_vcf_cache: Dict[str, VCFContainer] = {}


def sniff_sam_container(path: str, config: HBamConfig = DEFAULT_CONFIG,
                        data: Optional[bytes] = None) -> SAMContainer:
    """Resolve SAM/BAM/CRAM for a path (extension first when trusted, magic
    bytes otherwise) — hb/AnySAMInputFormat.getFormat semantics."""
    if path in _sam_cache:
        return _sam_cache[path]
    ext = os.path.splitext(path)[1].lower()
    if config.trust_exts and ext in _SAM_EXT:
        fmt = _SAM_EXT[ext]
    else:
        fmt = _sniff_sam_data(path, data)
    _sam_cache[path] = fmt
    return fmt


def _sniff_sam_data(path: str, data: Optional[bytes]) -> SAMContainer:
    head = data if data is not None else _read_head(path)
    if head[:4] == b"CRAM":
        return SAMContainer.CRAM
    if bgzf.is_bgzf(head):
        try:
            payload = bgzf.inflate_block(head)
        except bgzf.BGZFError:
            payload = b""
        if payload[:4] == b"BAM\x01":
            return SAMContainer.BAM
    return SAMContainer.SAM


def sniff_vcf_container(path: str, config: HBamConfig = DEFAULT_CONFIG,
                        data: Optional[bytes] = None) -> VCFContainer:
    """Resolve VCF / VCF-in-BGZF / BCF — hb/VCFFormat + VCFInputFormat."""
    if path in _vcf_cache:
        return _vcf_cache[path]
    lower = path.lower()
    if config.vcf_trust_exts:
        if lower.endswith((".vcf.gz", ".vcf.bgz", ".vcf.bgzf")):
            # the extension promises VCF-in-gzip; BGZF vs plain gzip decides
            # splittability and must be checked against the bytes
            # (hb/util/BGZFEnhancedGzipCodec.java)
            head = data if data is not None else _read_head(path)
            fmt = VCFContainer.VCF_BGZF if bgzf.is_bgzf(head) \
                else VCFContainer.VCF_GZIP
        elif lower.endswith(".bcf"):
            fmt = VCFContainer.BCF
        elif lower.endswith(".vcf"):
            fmt = VCFContainer.VCF
        else:
            fmt = _sniff_vcf_data(path, data)
    else:
        fmt = _sniff_vcf_data(path, data)
    _vcf_cache[path] = fmt
    return fmt


def _sniff_vcf_data(path: str, data: Optional[bytes]) -> VCFContainer:
    head = data if data is not None else _read_head(path)
    if head[:3] == b"BCF":
        return VCFContainer.BCF
    if bgzf.is_bgzf(head):
        try:
            payload = bgzf.inflate_block(head)
        except bgzf.BGZFError:
            payload = b""
        if payload[:3] == b"BCF":
            return VCFContainer.BCF
        return VCFContainer.VCF_BGZF
    if head[:2] == b"\x1f\x8b":
        return VCFContainer.VCF_GZIP
    if head[:13] == b"##fileformat=":
        return VCFContainer.VCF
    raise ValueError(f"cannot determine VCF container of {path!r}")


def _read_head(path: str) -> bytes:
    src = as_byte_source(path)
    try:
        return src.pread(0, bgzf.MAX_BLOCK_SIZE)
    finally:
        src.close()


def clear_sniff_caches() -> None:
    _sam_cache.clear()
    _vcf_cache.clear()
