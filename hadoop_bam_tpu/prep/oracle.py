"""Serial host markdup oracle — the semantics the mesh path must match.

Duplicate marking here is the Picard-style *ends signature* computed
from each record's own bytes, with two documented simplifications (both
mirrored exactly by the device kernel, PARITY.md markdup row):

- the mate key is the raw ``(next_refID, next_pos, mate-reverse)``
  triple, not the mate's MC-derived unclipped end (no tag round trip);
- best-of-duplicate selection is per END (each record scored by its own
  sum of base qualities >= 15), not per pair-sum.

Signature of an ELIGIBLE record (mapped, primary — ``flag & 0x904 ==
0``): ``(refid, unclipped 5' position, library, orientation/pair-class
bits, mate key)``.  The unclipped 5' position extends the mapped
position through the leading (forward strand) or trailing (reverse
strand) soft/hard clips, so trimmed copies of the same molecule still
collide.  Within a signature group the winner is the highest score,
ties broken by the LOWEST global input index — deterministic across
any shard count or round size.  Every record (eligible or not) gets
its duplicate flag (0x400) cleared and re-derived; losers are flagged,
or dropped under ``remove_duplicates``.  Output is coordinate-sorted
(the mesh pipeline's order) through ``write_bam_records``, sidecars
included.

Raw-record offsets (block_size-prefixed, see utils/fixmate.py):

    0:4 block_size | 4:8 refID | 8:12 pos | 12 l_read_name | 13 mapq
    | 14:16 bin | 16:18 n_cigar_op | 18:20 flag | 20:24 l_seq
    | 24:28 next_refID | 28:32 next_pos | 32:36 tlen
    | 36+ read_name NUL | cigar u32[n_cigar] | seq (l_seq+1)//2
    | qual l_seq | aux
"""
from __future__ import annotations

import re
from typing import Dict, Iterator, List, Optional, Tuple

import numpy as np

from hadoop_bam_tpu.config import DEFAULT_CONFIG, HBamConfig
from hadoop_bam_tpu.utils.errors import CorruptDataError, PlanError

_CLIP_OPS = frozenset((4, 5))            # S, H [SPEC cigar ops]
_REF_CONSUME = frozenset((0, 2, 3, 7, 8))   # M D N = X
_U32 = 0xFFFFFFFF

# sum-of-base-qualities floor (Picard's DuplicateScoringStrategy):
# qualities below this never count toward the winner score
SCORE_MIN_QUAL = 15

LIBRARY_MODES = ("none", "rg")


def _u16(rec, off: int) -> int:
    return int.from_bytes(rec[off:off + 2], "little")


def _i32(rec, off: int) -> int:
    return int.from_bytes(rec[off:off + 4], "little", signed=True)


def _cigar_walk(rec) -> Tuple[int, int, int]:
    """(leading_clip, trailing_clip, ref_len) from the packed CIGAR.

    Leading = the maximal S/H prefix, trailing = the maximal S/H suffix
    (an all-clip CIGAR counts its total on both sides — the device
    kernel's masked prefix/suffix products do the same); ref_len falls
    back to l_seq for CIGAR-less records (the '*' convention,
    utils/fixmate.py::_alen)."""
    n_cigar = _u16(rec, 16)
    if n_cigar == 0:
        return 0, 0, _i32(rec, 20)
    off = 36 + rec[12]
    ops = []
    for k in range(n_cigar):
        v = int.from_bytes(rec[off + 4 * k:off + 4 * k + 4], "little")
        ops.append((v & 0xF, v >> 4))
    lead = 0
    for op, ln in ops:
        if op not in _CLIP_OPS:
            break
        lead += ln
    trail = 0
    for op, ln in reversed(ops):
        if op not in _CLIP_OPS:
            break
        trail += ln
    ref_len = sum(ln for op, ln in ops if op in _REF_CONSUME)
    return lead, trail, ref_len


def record_score(rec) -> int:
    """Sum of base qualities >= SCORE_MIN_QUAL — the best-of-duplicate
    selection key.  Missing-quality bytes (0xFF) count at face value on
    both paths, keeping the mesh/oracle contract exact."""
    l_read_name = rec[12]
    n_cigar = _u16(rec, 16)
    l_seq = _i32(rec, 20)
    qual_off = 36 + l_read_name + 4 * n_cigar + (l_seq + 1) // 2
    if qual_off + l_seq > len(rec):
        raise CorruptDataError(
            f"record qual array ([{qual_off}:{qual_off + l_seq}]) "
            f"overruns the {len(rec)}-byte record")
    return sum(q for q in rec[qual_off:qual_off + l_seq]
               if q >= SCORE_MIN_QUAL)


def record_signature(rec, lib: int) -> Optional[Tuple[int, int, int,
                                                      int, int]]:
    """The 5-tuple duplicate signature of one record, or None when the
    record is ineligible (unmapped / secondary / supplementary).

    ``(k0, k1, k2, k3, k4)`` — exactly the five uint32 key columns the
    device kernel sorts on (prep/markdup.py), so the two definitions
    cannot diverge silently:

    - k0: refid;
    - k1: unclipped 5' position + 1 in uint32 wraparound (the sort-key
      convention, parallel/mesh_sort.py::_keys_of);
    - k2: ``lib << 3 | mate_reverse << 2 | orientation << 1 |
      pair_class``;
    - k3/k4: mate key ``(next_refID + 1, next_pos + 1)`` as uint32,
      zero for fragments (pair_class 0: unpaired, or mate unmapped).
    """
    flag = _u16(rec, 18)
    if flag & 0x904:                 # unmapped/secondary/supplementary
        return None
    pos = _i32(rec, 8)
    lead, trail, ref_len = _cigar_walk(rec)
    orient = (flag >> 4) & 1
    if orient:
        upos = pos + ref_len - 1 + trail
    else:
        upos = pos - lead
    pair = 1 if (flag & 0x1) and not (flag & 0x8) else 0
    mate_rev = ((flag >> 5) & 1) if pair else 0
    k3 = ((_i32(rec, 24) + 1) & _U32) if pair else 0
    k4 = ((_i32(rec, 28) + 1) & _U32) if pair else 0
    k0 = _i32(rec, 4) & _U32
    k1 = (upos + 1) & _U32
    k2 = ((lib << 3) | (mate_rev << 2) | (orient << 1) | pair) & _U32
    return (k0, k1, k2, k3, k4)


# ---------------------------------------------------------------------------
# library resolution (--library-from)
# ---------------------------------------------------------------------------

def library_map(header, mode: str) -> Optional[Dict[bytes, int]]:
    """RG id -> small integer library id, or None when ``mode`` is
    "none" (every record in one anonymous library 0).

    Libraries are the sorted unique ``@RG LB:`` values, numbered from
    1; read groups without LB — and records without an RG tag — fall
    into library 0.  Sorting makes the numbering a pure function of the
    header, so the mesh and oracle paths (and any shard order) agree."""
    if mode == "none":
        return None
    if mode != "rg":
        raise PlanError(f"unknown library mode {mode!r}; expected one "
                        f"of {LIBRARY_MODES}")
    rg_lb: Dict[bytes, bytes] = {}
    for line in header.text.splitlines():
        if not line.startswith("@RG"):
            continue
        m_id = re.search(r"\tID:([^\t\n]+)", line)
        m_lb = re.search(r"\tLB:([^\t\n]+)", line)
        if m_id and m_lb:
            rg_lb[m_id.group(1).encode()] = m_lb.group(1).encode()
    libs = {lb: i + 1 for i, lb in enumerate(sorted(set(rg_lb.values())))}
    return {rg: libs[lb] for rg, lb in rg_lb.items()}


def _aux_rg(rec) -> Optional[bytes]:
    """The RG:Z tag value from a record's aux block, or None."""
    l_read_name = rec[12]
    n_cigar = _u16(rec, 16)
    l_seq = _i32(rec, 20)
    off = 36 + l_read_name + 4 * n_cigar + (l_seq + 1) // 2 + l_seq
    end = len(rec)
    while off + 3 <= end:
        tag = bytes(rec[off:off + 2])
        typ = rec[off + 2]
        off += 3
        if typ in (0x5A, 0x48):                       # Z, H
            nul = rec.find(b"\x00", off) if isinstance(rec, bytes) \
                else bytes(rec).find(b"\x00", off)
            if nul < 0:
                raise CorruptDataError(
                    f"unterminated {chr(typ)}-type aux tag "
                    f"{tag!r} in record")
            if tag == b"RG" and typ == 0x5A:
                return bytes(rec[off:nul])
            off = nul + 1
        elif typ == 0x42:                             # B: array
            if off + 5 > end:
                raise CorruptDataError("truncated B-type aux tag")
            sub = rec[off]
            count = int.from_bytes(rec[off + 1:off + 5], "little")
            size = {0x63: 1, 0x43: 1, 0x73: 2, 0x53: 2,
                    0x69: 4, 0x49: 4, 0x66: 4}.get(sub)
            if size is None:
                raise CorruptDataError(
                    f"unknown B-array subtype {sub:#x} in aux block")
            off += 5 + size * count
        else:
            size = {0x41: 1, 0x63: 1, 0x43: 1, 0x73: 2, 0x53: 2,
                    0x69: 4, 0x49: 4, 0x66: 4}.get(typ)
            if size is None:
                raise CorruptDataError(
                    f"unknown aux tag type {typ:#x} in record")
            off += size
    return None


def library_column(data: np.ndarray, offs: np.ndarray,
                   lens: np.ndarray,
                   rg_to_lib: Optional[Dict[bytes, int]]) -> np.ndarray:
    """Per-record uint32 library ids for a decoded span — the host-side
    column the fused pipeline ships alongside the row tile (library
    identity lives in a text tag + header join; everything positional
    in the signature is computed on device)."""
    n = int(offs.size)
    out = np.zeros(n, np.uint32)
    if rg_to_lib is None or not n:
        return out
    mv = data.tobytes()
    base = offs.astype(np.int64)
    for i in range(n):
        rec = mv[int(base[i]):int(base[i] + lens[i])]
        rg = _aux_rg(rec)
        if rg is not None:
            out[i] = rg_to_lib.get(rg, 0)
    return out


# ---------------------------------------------------------------------------
# the oracle pipeline
# ---------------------------------------------------------------------------

def select_duplicates(sigs: List[Optional[Tuple]],
                      scores: List[int]) -> np.ndarray:
    """The best-of-duplicate selection, host reference: one uint8 dup
    bit per input record.  Winner per signature group = max score, ties
    to the lowest global input index; ineligible records (signature
    None) never participate."""
    groups: Dict[Tuple, List[int]] = {}
    for gidx, sig in enumerate(sigs):
        if sig is not None:
            groups.setdefault(sig, []).append(gidx)
    dup = np.zeros(len(sigs), np.uint8)
    for members in groups.values():
        if len(members) < 2:
            continue
        winner = min(members, key=lambda g: (-scores[g], g))
        for g in members:
            if g != winner:
                dup[g] = 1
    return dup


def patch_flag(rec: bytes, dup: bool) -> bytes:
    """Clear-and-rederive the duplicate flag (0x400) in a raw record."""
    flag = int.from_bytes(rec[18:20], "little")
    nf = (flag & ~0x400) | (0x400 if dup else 0)
    if nf == flag:
        return rec
    return rec[:18] + nf.to_bytes(2, "little") + rec[20:]


def markdup_bam_oracle(input_path: str, output_path: str, *,
                       config: HBamConfig = DEFAULT_CONFIG,
                       remove_duplicates: bool = False,
                       library_from: str = "none") -> int:
    """Mark (or remove) duplicates serially: decode every record, build
    signatures/scores, select winners, coordinate-sort, patch flags
    during the write.  Returns the record count written.

    Holds the whole file's records in memory — this is the VALIDATION
    oracle the fused mesh pipeline is byte-compared against, not the
    scalable path (``prep.pipeline.markdup_bam_mesh`` is)."""
    from hadoop_bam_tpu.api.dataset import open_bam
    from hadoop_bam_tpu.utils.sort import _sorted_header
    from hadoop_bam_tpu.write import write_bam_records

    if library_from not in LIBRARY_MODES:
        raise PlanError(f"unknown --library-from {library_from!r}; "
                        f"expected one of {LIBRARY_MODES}")
    ds = open_bam(input_path, config)
    rg_to_lib = library_map(ds.header, library_from)
    recs: List[bytes] = []
    sigs: List[Optional[Tuple]] = []
    scores: List[int] = []
    for batch in ds.batches():
        for i in range(len(batch)):
            rec = batch.record_bytes(i)
            lib = 0
            if rg_to_lib is not None:
                rg = _aux_rg(rec)
                lib = rg_to_lib.get(rg, 0) if rg is not None else 0
            recs.append(rec)
            sigs.append(record_signature(rec, lib))
            scores.append(record_score(rec))
    dup = select_duplicates(sigs, scores)

    # coordinate order with the input index as the tie key — exactly
    # the mesh exchange's (hi, lo, gidx) sort
    def key(gidx: int) -> Tuple[int, int, int]:
        rec = recs[gidx]
        refid = _i32(rec, 4)
        hi = _U32 if refid < 0 else refid
        lo = (_i32(rec, 8) + 1) & _U32
        return (hi, lo, gidx)

    order = sorted(range(len(recs)), key=key)
    out_header = _sorted_header(ds.header, by_name=False)

    def chunks() -> Iterator[Tuple[bytes, np.ndarray]]:
        buf: List[bytes] = []
        offsets: List[int] = []
        pos = 0
        for gidx in order:
            if remove_duplicates and dup[gidx]:
                continue
            rec = patch_flag(recs[gidx], bool(dup[gidx]))
            buf.append(rec)
            offsets.append(pos)
            pos += len(rec)
            if pos >= (8 << 20):
                yield b"".join(buf), np.asarray(offsets, np.int64)
                buf, offsets, pos = [], [], 0
        if buf:
            yield b"".join(buf), np.asarray(offsets, np.int64)

    return write_bam_records(output_path, out_header, chunks(),
                             config=config).records
