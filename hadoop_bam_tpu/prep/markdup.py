"""Device markdup kernels: fused sort exchange + signature columns, and
the signature-hash duplicate exchange.

Two shard_map steps, both riding the mesh-sort machinery
(parallel/mesh_sort.py — ``_device_keys``/``_bucket_pack``/
``_send_matrices`` are imported, not re-derived, so the key conventions
cannot drift):

1. ``_make_fused_sort_markdup_step`` — the byte-exchange sort step
   EXTENDED: before the all_to_all ships the rows away, the device
   unpacks the duplicate-signature columns (unclipped 5' position via a
   masked CIGAR prefix/suffix walk, orientation/pair-class bits, mate
   key, sum-of-quals score) straight from the resident row bytes.  One
   jitted call per round does the shuffle AND the signature unpack —
   records are never re-inflated for a second pass.

2. ``_make_markdup_exchange_step`` — the duplicate grouping: signature
   columns (7 uint32s per record, never the payload) are hash-
   partitioned over the mesh so every signature group lands whole on
   one device, a multi-key ``lax.sort`` over (signature, inverted
   score, global index) clusters each group with its winner first, and
   the duplicate bit is exactly "valid and same signature as the
   previous row" — the segmented best-of-duplicate reduction.

The column definitions mirror ``prep.oracle.record_signature`` /
``record_score`` field for field; tests pin byte identity of the whole
pipeline against the oracle, which would catch any drift here.
"""
from __future__ import annotations

import numpy as np

from hadoop_bam_tpu.parallel.mesh_sort import (
    _I32_SENTINEL, _bucket_pack, _device_keys, _send_matrices,
)

_U32 = 0xFFFFFFFF
# ineligible flags: unmapped 0x4, secondary 0x100, supplementary 0x800
_INELIGIBLE_MASK = 0x904


def _le_u16(rows, col):
    import jax.numpy as jnp

    b = rows[:, col:col + 2].astype(jnp.uint32)
    return b[:, 0] | (b[:, 1] << 8)


def _le_i32(rows, col):
    import jax
    import jax.numpy as jnp

    b = rows[:, col:col + 4].astype(jnp.uint32)
    v = (b[:, 0] | (b[:, 1] << 8) | (b[:, 2] << 16) | (b[:, 3] << 24))
    return jax.lax.bitcast_convert_type(v, jnp.int32)


def host_kmax(data: np.ndarray, offs: np.ndarray) -> int:
    """Max n_cigar_op over a decoded span (host, cheap): the static
    CIGAR-walk width the fused step compiles for."""
    if not offs.size:
        return 0
    base = offs.astype(np.int64)
    n_cigar = (data[base[:, None] + np.arange(16, 18)]
               .view("<u2").ravel())
    return int(n_cigar.max())


def markdup_columns(rows, lens, valid, lib, kmax: int, stride: int):
    """(k0..k4, score, elig) uint32 signature columns from a row tile,
    on device — the single-definition twin of
    ``oracle.record_signature``/``record_score`` (docstrings there).

    ``kmax`` is the static CIGAR width (host-measured per round);
    ``lib`` the host-joined per-record library column.  Runs on the
    PRE-exchange rows, so each record's columns carry its own global
    index position implicitly (the caller pairs them with
    ``base + arange``)."""
    import jax.numpy as jnp

    R = rows.shape[0]
    flag = _le_u16(rows, 18)
    l_read_name = rows[:, 12].astype(jnp.int32)
    n_cigar = _le_u16(rows, 16).astype(jnp.int32)
    l_seq = _le_i32(rows, 20)
    refid = _le_i32(rows, 4)
    pos = _le_i32(rows, 8)
    nref = _le_i32(rows, 24)
    npos = _le_i32(rows, 28)

    elig = valid & ((flag & _INELIGIBLE_MASK) == 0)

    # --- masked CIGAR walk: leading/trailing clips + reference span ---
    cig_off = 36 + l_read_name
    if kmax > 0:
        karange = jnp.arange(kmax, dtype=jnp.int32)
        kvalid = karange[None, :] < n_cigar[:, None]
        flat = rows.ravel()
        cpos = (jnp.arange(R, dtype=jnp.int32)[:, None] * stride
                + cig_off[:, None] + 4 * karange[None, :])
        cap = R * stride - 1

        def gb(j):
            return jnp.take(flat, jnp.clip(cpos + j, 0, cap)
                            ).astype(jnp.uint32)

        v = gb(0) | (gb(1) << 8) | (gb(2) << 16) | (gb(3) << 24)
        op = v & 0xF
        ln = (v >> 4).astype(jnp.int32)
        is_clip = ((op == 4) | (op == 5)) & kvalid
        # maximal clip prefix / suffix (oracle._cigar_walk): padding
        # counts as clip on the suffix side so variable lengths don't
        # break the right-to-left product
        lead_mask = jnp.cumprod(is_clip.astype(jnp.int32), axis=1)
        clip_or_pad = (is_clip | ~kvalid).astype(jnp.int32)
        suffix = jnp.cumprod(clip_or_pad[:, ::-1], axis=1)[:, ::-1]
        lead = jnp.sum(ln * lead_mask, axis=1)
        trail = jnp.sum(ln * suffix * is_clip.astype(jnp.int32), axis=1)
        is_ref = ((op == 0) | (op == 2) | (op == 3)
                  | (op == 7) | (op == 8)) & kvalid
        ref_sum = jnp.sum(ln * is_ref.astype(jnp.int32), axis=1)
    else:
        lead = trail = ref_sum = jnp.zeros(R, jnp.int32)
    ref_len = jnp.where(n_cigar == 0, l_seq, ref_sum)

    orient = (flag >> 4) & 1
    upos = jnp.where(orient.astype(bool),
                     pos + ref_len - 1 + trail, pos - lead)

    # --- sum of base qualities >= SCORE_MIN_QUAL (oracle.record_score) ---
    qual_off = 36 + l_read_name + 4 * n_cigar + (l_seq + 1) // 2
    cols = jnp.arange(stride, dtype=jnp.int32)[None, :]
    qmask = ((cols >= qual_off[:, None])
             & (cols < (qual_off + l_seq)[:, None])
             & (rows >= 15))
    score = jnp.sum(jnp.where(qmask, rows, 0).astype(jnp.uint32),
                    axis=1)

    pair = ((flag & 0x1) != 0) & ((flag & 0x8) == 0)
    mate_rev = jnp.where(pair, (flag >> 5) & 1, 0)
    k0 = refid.astype(jnp.uint32)
    k1 = (upos + 1).astype(jnp.uint32)
    k2 = ((lib.astype(jnp.uint32) << 3) | (mate_rev << 2)
          | (orient << 1) | pair.astype(jnp.uint32))
    k3 = jnp.where(pair, (nref + 1).astype(jnp.uint32), jnp.uint32(0))
    k4 = jnp.where(pair, (npos + 1).astype(jnp.uint32), jnp.uint32(0))
    return k0, k1, k2, k3, k4, score, elig


def _make_fused_sort_markdup_step(mesh, records_cap: int, stride: int,
                                  kmax: int):
    """The byte-exchange sort step (mesh_sort._make_bytes_sort_step)
    fused with the signature-column unpack: same all_to_all shuffle and
    bucket sort, plus per-source-device (k0..k4, score, elig) columns
    computed from the rows BEFORE they ship.  Returns
    ((sorted_rows, sorted_lens, six), (k0..k4, score, elig))."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from hadoop_bam_tpu.parallel.mesh import shard_map

    n_dev = int(np.prod(mesh.devices.shape))
    R = records_cap
    N = n_dev * R

    def per_device(rows, lens, count, base, lib, bhi, blo):
        rows, lens = rows[0], lens[0]
        count, base, lib = count[0], base[0], lib[0]
        refid = _le_i32(rows, 4)
        pos = _le_i32(rows, 8)
        valid = jnp.arange(R, dtype=jnp.int32) < count
        hi, lo, gidx = _device_keys(refid, pos, valid, base, R)

        # signature columns from the resident pre-exchange rows — the
        # fusion: one pass over bytes that are already on device
        k0, k1, k2, k3, k4, score, elig = markdup_columns(
            rows, lens, valid, lib, kmax, stride)

        perm, sb, rank = _bucket_pack(hi, lo, bhi, blo, R)
        send_hi, send_lo, send_ix = _send_matrices(hi, lo, gidx, perm,
                                                   sb, rank, n_dev, R)
        send_ln = jnp.zeros((n_dev, R), jnp.int32
                            ).at[sb, rank].set(lens[perm])
        send_rows = jnp.zeros((n_dev, R, stride), jnp.uint8
                              ).at[sb, rank].set(rows[perm])

        recv_hi = jax.lax.all_to_all(send_hi, "data", 0, 0,
                                     tiled=True).ravel()
        recv_lo = jax.lax.all_to_all(send_lo, "data", 0, 0,
                                     tiled=True).ravel()
        recv_ix = jax.lax.all_to_all(send_ix, "data", 0, 0,
                                     tiled=True).ravel()
        recv_ln = jax.lax.all_to_all(send_ln, "data", 0, 0,
                                     tiled=True).ravel()
        recv_rows = jax.lax.all_to_all(send_rows, "data", 0, 0,
                                       tiled=True).reshape(N, stride)

        iota = jnp.arange(N, dtype=jnp.int32)
        _, _, six, order = jax.lax.sort(
            (recv_hi, recv_lo, recv_ix, iota), num_keys=3)
        sorted_rows = jnp.take(recv_rows, order, axis=0)
        sorted_ln = jnp.take(recv_ln, order)
        return (sorted_rows[None], sorted_ln[None], six[None],
                k0[None], k1[None], k2[None], k3[None], k4[None],
                score[None], elig.astype(jnp.uint8)[None])

    return jax.jit(shard_map(
        per_device, mesh=mesh,
        in_specs=(P("data"),) * 5 + (P(), P()),
        out_specs=(P("data"),) * 10, check_vma=False))


def _make_markdup_exchange_step(mesh, cap: int):
    """The duplicate-grouping exchange: hash-partition signature column
    tuples over the mesh, multi-key sort each device's groups with the
    winner first, emit per-record duplicate bits keyed by global index.

    Capacity is structural like the sort exchange: a source holds at
    most ``cap`` eligible records, so no (src, dst) send cell can
    overflow.  Padding cells carry the int32 gidx sentinel and all-ones
    keys; they sort last and are dropped on the host."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from hadoop_bam_tpu.parallel.mesh import shard_map

    n_dev = int(np.prod(mesh.devices.shape))
    R = cap
    N = n_dev * R

    def per_device(k0, k1, k2, k3, k4, score, gidx, count):
        k0, k1, k2, k3, k4 = k0[0], k1[0], k2[0], k3[0], k4[0]
        score, gidx, count = score[0], gidx[0], count[0]
        valid = jnp.arange(R, dtype=jnp.int32) < count

        # deterministic u32 hash mix over the 5 signature keys: equal
        # signatures land on one device regardless of mesh size, which
        # is what makes tie-breaks shard-count-invariant
        h = k0
        for k in (k1, k2, k3, k4):
            h = (h ^ k) * jnp.uint32(0x9E3779B1)
        bucket = jnp.where(valid, (h % jnp.uint32(n_dev)).astype(
            jnp.int32), 0)
        perm = jnp.argsort(bucket, stable=True)
        sb = bucket[perm]
        rank = jnp.arange(R, dtype=jnp.int32) - jnp.searchsorted(
            sb, sb, side="left").astype(jnp.int32)

        def send_u32(x):
            x = jnp.where(valid, x, jnp.uint32(_U32))
            return jnp.full((n_dev, R), _U32, jnp.uint32
                            ).at[sb, rank].set(x[perm])

        sends = [send_u32(k) for k in (k0, k1, k2, k3, k4)]
        # inverted score: ascending sort puts the HIGHEST score first
        inv = jnp.uint32(_U32) - jnp.where(valid, score, jnp.uint32(0))
        sends.append(send_u32(inv))
        gidx_s = jnp.where(valid, gidx, _I32_SENTINEL)
        send_ix = jnp.full((n_dev, R), _I32_SENTINEL, jnp.int32
                           ).at[sb, rank].set(gidx_s[perm])

        recvd = [jax.lax.all_to_all(s, "data", 0, 0, tiled=True).ravel()
                 for s in sends]
        recv_ix = jax.lax.all_to_all(send_ix, "data", 0, 0,
                                     tiled=True).ravel()

        s0, s1, s2, s3, s4, sinv, six = jax.lax.sort(
            (*recvd, recv_ix), num_keys=7)
        ok = six != _I32_SENTINEL
        prev_same = jnp.zeros(N, bool).at[1:].set(
            (s0[1:] == s0[:-1]) & (s1[1:] == s1[:-1])
            & (s2[1:] == s2[:-1]) & (s3[1:] == s3[:-1])
            & (s4[1:] == s4[:-1]) & ok[1:] & ok[:-1])
        dup = (ok & prev_same).astype(jnp.uint8)
        return six[None], dup[None]

    return jax.jit(shard_map(
        per_device, mesh=mesh,
        in_specs=(P("data"),) * 8,
        out_specs=(P("data"), P("data")), check_vma=False))
