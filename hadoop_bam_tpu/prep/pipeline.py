"""The fused preprocessing pipeline: read -> mesh sort exchange ->
markdup -> indexed write, as ONE journaled run.

Composition, not new machinery: the sort half IS the spill byte
exchange from ``parallel/mesh_sort.py`` (same planner, same bucket
boundaries protocol, same framed spill runs and per-bucket k-way
merge), extended in the SAME jitted step with the duplicate-signature
column unpack (prep/markdup.py) so the markdup keys are computed while
the record bytes are already resident on device — records never
re-inflate between stages.  The duplicate bits then ride a second,
columns-only exchange (7 uint32s per record, never the payload), and
the FLAG patch is applied per frame during the shard write, between the
spill merge and the BGZF deflate.

Journal grains (``jobs/``), one per stage:

- ``round``  — each sort round's spilled runs + its signature-column
  sidecar (size+CRC verified on resume; partial rounds swept);
- ``markdup`` — the duplicate bitmap over global record indices;
- ``shard``  — each written output part (ShardedFileWriter's protocol).

A SIGKILL at any stage boundary resumes byte-identically: completed
rounds are not re-decoded, a completed bitmap is not re-exchanged,
committed parts are not re-deflated (``jobs.rounds_skipped`` /
``jobs.markdup_skipped`` / ``jobs.shards_skipped``).

Semantics are pinned byte-for-byte against ``prep.oracle`` — see its
docstrings for the signature/score/patch contract and the documented
deviations from Picard (PARITY.md).
"""
from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np

from hadoop_bam_tpu.config import DEFAULT_CONFIG, HBamConfig
from hadoop_bam_tpu.formats.bam import SAMHeader
from hadoop_bam_tpu.utils.errors import CorruptDataError, PlanError

DEFAULT_ROUND_RECORDS = 1_000_000


def markdup_bam_mesh(input_path: str, output_path: str, *,
                     mesh=None, config: HBamConfig = DEFAULT_CONFIG,
                     header: Optional[SAMHeader] = None,
                     remove_duplicates: bool = False,
                     library_from: str = "none",
                     round_records: Optional[int] = None,
                     journal_path: Optional[str] = None) -> int:
    """Mark duplicates in ``input_path`` and write the coordinate-sorted
    result to ``output_path`` in one fused mesh pass (module docstring).
    Returns the number of records written.  Byte-identical to
    ``oracle.markdup_bam_oracle`` with the same options.

    Spilled runs, the column sidecars, the duplicate bitmap, and the
    output parts all live in ``<output>.mkdup-spill``; the directory is
    removed on success (or on failure without a journal — with one, the
    completed units ARE the resume state and must survive)."""
    import shutil

    import jax

    from hadoop_bam_tpu.parallel.mesh import make_mesh

    if jax.process_count() > 1:
        raise PlanError(
            "the fused markdup pipeline is single-process for now: the "
            "duplicate bitmap and the journal protocol assume one host; "
            "run under a single process (multi-host markdup needs the "
            "distributed journal protocol first)")
    if mesh is None:
        mesh = make_mesh()
    if round_records is None:
        round_records = DEFAULT_ROUND_RECORDS
    if int(round_records) <= 0:
        raise PlanError(f"round_records must be positive, got "
                        f"{round_records}")
    ok = False
    try:
        n = _markdup_bam_mesh_impl(
            input_path, output_path, mesh=mesh, config=config,
            header=header, remove_duplicates=bool(remove_duplicates),
            library_from=library_from, round_records=int(round_records),
            journal_path=journal_path)
        ok = True
        return n
    finally:
        keep = bool(getattr(config, "debug_keep_spill", False)) \
            or (journal_path is not None and not ok)
        if not keep:
            shutil.rmtree(output_path + ".mkdup-spill",
                          ignore_errors=True)


def _markdup_bam_mesh_impl(input_path: str, output_path: str, *, mesh,
                           config: HBamConfig,
                           header: Optional[SAMHeader],
                           remove_duplicates: bool, library_from: str,
                           round_records: int,
                           journal_path: Optional[str]) -> int:
    import os
    import shutil

    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from hadoop_bam_tpu.formats.bamio import BamWriter, read_bam_header
    from hadoop_bam_tpu.parallel.mesh_sort import (
        _I32_SENTINEL, _buckets, _frame_run, _iter_run_frames, _keys_of,
        _pack_record_rows, _record_lens, _round_up, _sample_bounds,
        check_global_index_ceiling,
    )
    from hadoop_bam_tpu.parallel.pipeline import _decode_span_core
    from hadoop_bam_tpu.prep.markdup import (
        _make_fused_sort_markdup_step, _make_markdup_exchange_step,
        host_kmax,
    )
    from hadoop_bam_tpu.prep.oracle import library_column, library_map
    from hadoop_bam_tpu.split.planners import plan_bam_spans_balanced
    from hadoop_bam_tpu.utils.metrics import METRICS
    from hadoop_bam_tpu.utils.sort import _sorted_header
    from hadoop_bam_tpu.write import (
        ShardedFileWriter, write_bam_shards_concat,
    )

    mesh_devs = list(mesh.devices.ravel())
    n_dev = len(mesh_devs)
    if header is None:
        header, _ = read_bam_header(input_path)
    rg_to_lib = library_map(header, library_from)

    jr = None
    resume = None
    if journal_path is not None:
        from hadoop_bam_tpu.jobs import journal as jj
        from hadoop_bam_tpu.jobs.runner import (
            SORT_FINGERPRINT_FIELDS, plan_journal_params,
        )
        from hadoop_bam_tpu.plan import builders
        plan_ir = builders.mkdup_plan(
            input_path, output_path, config,
            remove_duplicates=remove_duplicates,
            library_from=library_from)
        jr, resume = jj.JobJournal.resume(
            journal_path, kind="mkdup",
            inputs=[(os.path.abspath(input_path),
                     jj.file_identity_digest(input_path))],
            output=os.path.abspath(output_path),
            fingerprint=jj.config_fingerprint(config,
                                              SORT_FINGERPRINT_FIELDS),
            config_values=jj.fingerprint_values(config,
                                                SORT_FINGERPRINT_FIELDS),
            params=plan_journal_params(plan_ir, {
                "input": os.path.abspath(input_path),
                "output": os.path.abspath(output_path),
                "remove_duplicates": bool(remove_duplicates),
                "library_from": library_from,
                "round_records": int(round_records),
                "n_dev": n_dev,
            }),
            fsync=bool(getattr(config, "journal_fsync", True)))
        if resume is not None and resume.done is not None:
            d = resume.done
            if jj.verify_artifact(output_path, d.get("size", -1),
                                  d.get("crc", "")):
                METRICS.count("jobs.jobs_skipped")
                jr.close()
                return int(d.get("records", 0))

    def plan():
        from hadoop_bam_tpu.split.splitting_index import (
            SplittingIndex, build_splitting_index,
        )
        index = SplittingIndex.load_for(input_path)
        fine = max(1, round_records // 8)
        if index is None or (index.granularity or 1) > fine:
            index = build_splitting_index(input_path, granularity=fine)
        n_samples = max(1, len(index.voffsets) - 1)
        if index.total_records > 0:
            total_est = index.total_records
            check_global_index_ceiling(total_est, "fused markdup plan")
        else:
            total_est = n_samples * max(1, index.granularity)
        want = -(-total_est // max(1, round_records))
        want = _round_up(want, n_dev)
        return plan_bam_spans_balanced(input_path, want, header=header,
                                       index=index)

    spans = plan()
    n_rounds = max(1, -(-len(spans) // n_dev))

    shard_dir = output_path + ".mkdup-spill"
    resumed_rounds: dict = {}
    markdup_unit = None
    bounds_ev = None
    if jr is not None:
        pd = jj.plan_digest(spans)
        plan_ev = resume.last_event("plan") if resume is not None else None
        if plan_ev is not None and plan_ev.get("digest") != pd:
            raise PlanError(
                f"refusing to resume {journal_path}: the span plan no "
                f"longer matches the journaled run (journal digest "
                f"{plan_ev.get('digest')!r}, now {pd!r}) — the input's "
                f"splitting-index state changed; delete the journal to "
                f"start over")
        if plan_ev is None:
            jr.event("plan", digest=pd, n_spans=len(spans),
                     n_rounds=int(n_rounds))
        if resume is not None:
            bounds_ev = resume.last_event("bounds")
            for t in range(n_rounds):
                u = resume.unit("round", t)
                if u is None:
                    continue
                runs = list(u.get("runs", []))
                cols = u.get("cols")
                if (all(jj.verify_artifact(p, s, c) for _b, p, s, c
                        in runs)
                        and cols is not None
                        and jj.verify_artifact(*cols)):
                    resumed_rounds[t] = u
            mu = resume.unit("markdup", 0)
            if mu is not None and jj.verify_artifact(
                    mu.get("path", ""), mu.get("size", -1),
                    mu.get("crc", "")):
                markdup_unit = mu
            recorded = [p for u in resumed_rounds.values()
                        for _b, p, s, c in u.get("runs", [])]
            recorded += [u["cols"][0] for u in resumed_rounds.values()]
            if markdup_unit is not None:
                recorded.append(markdup_unit["path"])
            jj.sweep_unrecorded(shard_dir, recorded,
                                counter="jobs.stale_runs_swept")
            if resumed_rounds and bounds_ev is None:
                raise PlanError(
                    f"refusing to resume {journal_path}: completed "
                    f"rounds are recorded but the round-0 bucket "
                    f"boundaries are not — later rounds re-bucketed "
                    f"under fresh boundaries would break the global "
                    f"order; delete the journal to start over")
            spans_skipped = sum(
                min((t + 1) * n_dev, len(spans)) - t * n_dev
                for t in resumed_rounds)
            if resumed_rounds:
                METRICS.count("jobs.rounds_skipped", len(resumed_rounds))
                METRICS.count("jobs.spans_skipped", spans_skipped)
            jr.event("resume_plan", rounds_total=int(n_rounds),
                     rounds_skipped=len(resumed_rounds),
                     spans_skipped=int(spans_skipped))
    if not resumed_rounds and markdup_unit is None:
        shutil.rmtree(shard_dir, ignore_errors=True)
    os.makedirs(shard_dir, exist_ok=True)

    sharding = NamedSharding(mesh, P("data"))
    rep = NamedSharding(mesh, P())

    def sharded(shape, dtype, of_d):
        return jax.make_array_from_single_device_arrays(
            shape, sharding,
            [jax.device_put(np.asarray(of_d(d), dtype=dtype),
                            mesh_devs[d]) for d in range(n_dev)])

    def replicated(arr, dtype):
        arr = np.asarray(arr, dtype=dtype)
        return jax.make_array_from_single_device_arrays(
            arr.shape, rep,
            [jax.device_put(arr, mesh_devs[d]) for d in range(n_dev)])

    # ---------------- stage 1: fused sort exchange + column unpack ----
    step_cache = {}
    bhi = blo = None
    bhi_g = blo_g = None
    prefix_total = 0
    run_files: dict = {}               # bucket -> [run paths]
    col_files: List[str] = []          # per-round signature sidecars

    with METRICS.span("prep.sort_wall"):
        for t in range(n_rounds):
            if t in resumed_rounds:
                u = resumed_rounds[t]
                for b, p, _s, _c in u.get("runs", []):
                    run_files.setdefault(int(b), []).append(p)
                col_files.append(u["cols"][0])
                prefix_total += int(u.get("round_total", 0))
                continue
            decoded = {}
            counts_vec = np.zeros(n_dev, np.int64)
            max_len = 0
            kmax = 0
            his: List[np.ndarray] = []
            los: List[np.ndarray] = []
            for d in range(n_dev):
                s = t * n_dev + d
                if s >= len(spans):
                    continue
                data, offs, _v, _ = _decode_span_core(
                    input_path, spans[s], False, "auto",
                    want_voffs=False)
                lens_ = _record_lens(data, offs)
                libs = library_column(data, offs, lens_, rg_to_lib)
                decoded[d] = (data, offs, lens_, libs)
                counts_vec[d] = offs.size
                if offs.size:
                    max_len = max(max_len, int(lens_.max()))
                    kmax = max(kmax, host_kmax(data, offs))
                if t == 0:
                    h, l = _keys_of(data, offs)
                    his.append(h)
                    los.append(l)

            if bhi is None:
                if bounds_ev is not None:
                    bhi = np.asarray(bounds_ev["bhi"], np.uint32)
                    blo = np.asarray(bounds_ev["blo"], np.uint32)
                else:
                    bhi, blo = _sample_bounds(his, los, n_dev)
                    if jr is not None:
                        jr.event("bounds",
                                 bhi=[int(x) for x in bhi],
                                 blo=[int(x) for x in blo])
                bhi_g = replicated(bhi, jnp.uint32)
                blo_g = replicated(blo, jnp.uint32)

            round_total = int(counts_vec.sum())
            check_global_index_ceiling(prefix_total + round_total,
                                       "fused markdup (mid-run backstop)")
            base_vec = prefix_total + np.concatenate(
                [[0], np.cumsum(counts_vec[:-1])])
            prefix_total += round_total

            records_cap = _round_up(max(int(counts_vec.max()), 1), 1024)
            stride = 1 << max(6, int(max(max_len, 36) - 1).bit_length())
            kpow = 0 if kmax == 0 else 1 << (kmax - 1).bit_length()
            key = (records_cap, stride, kpow)
            if key not in step_cache:
                step_cache[key] = _make_fused_sort_markdup_step(
                    mesh, records_cap, stride, kpow)
            step = step_cache[key]

            _empty = (np.zeros(0, np.uint8), np.zeros(0, np.int64),
                      np.zeros(0, np.int64), np.zeros(0, np.uint32))
            packed = {}
            lib_cols = {}
            for d in range(n_dev):
                data, offs, lens_, libs = decoded.pop(d, _empty)
                packed[d] = _pack_record_rows(data, offs, lens_,
                                              records_cap, stride)
                lc = np.zeros(records_cap, np.uint32)
                lc[:libs.size] = libs
                lib_cols[d] = lc
            del decoded

            rows_g = sharded((n_dev, records_cap, stride), jnp.uint8,
                             lambda d: packed[d][0][None])
            lens_g = sharded((n_dev, records_cap), jnp.int32,
                             lambda d: packed[d][1][None])
            count_g = sharded((n_dev,), jnp.int32,
                              lambda d: np.asarray([counts_vec[d]],
                                                   np.int32))
            base_g = sharded((n_dev,), jnp.int32,
                             lambda d: np.asarray([base_vec[d]],
                                                  np.int32))
            lib_g = sharded((n_dev, records_cap), jnp.uint32,
                            lambda d: lib_cols[d][None])
            (rows_s, lens_s, six_s, k0_s, k1_s, k2_s, k3_s, k4_s,
             score_s, elig_s) = step(rows_g, lens_g, count_g, base_g,
                                     lib_g, bhi_g, blo_g)

            # spill the round's buckets as framed sorted runs (the sort
            # half, identical to mesh_sort's spill protocol)
            b_rows, b_lens, b_six = (_buckets(rows_s), _buckets(lens_s),
                                     _buckets(six_s))
            round_runs: List[Tuple[int, str]] = []
            for b in sorted(b_rows):
                keep = b_six[b] != _I32_SENTINEL
                if not bool(keep.any()):
                    continue
                rows_k = b_rows[b][keep]
                lens_k = b_lens[b][keep]
                six_k = b_six[b][keep]
                hi_k, lo_k = _keys_of(
                    np.ascontiguousarray(rows_k).ravel(),
                    np.arange(rows_k.shape[0], dtype=np.int64)
                    * rows_k.shape[1])
                path = os.path.join(shard_dir, f"b{b:05d}-r{t:05d}.run")
                with open(path, "wb") as f:
                    f.write(_frame_run(rows_k, lens_k, six_k, hi_k,
                                       lo_k))
                run_files.setdefault(b, []).append(path)
                round_runs.append((b, path))

            # spill the round's signature columns (the markdup half):
            # eligible records only — 28 bytes per record, not payload
            cols_d = {n: _buckets(a) for n, a in (
                ("k0", k0_s), ("k1", k1_s), ("k2", k2_s), ("k3", k3_s),
                ("k4", k4_s), ("score", score_s), ("elig", elig_s))}
            parts = {n: [] for n in ("k0", "k1", "k2", "k3", "k4",
                                     "score", "gidx")}
            for d in range(n_dev):
                cnt = int(counts_vec[d])
                el = cols_d["elig"][d][:cnt].astype(bool)
                for n in ("k0", "k1", "k2", "k3", "k4", "score"):
                    parts[n].append(cols_d[n][d][:cnt][el])
                parts["gidx"].append(
                    (base_vec[d] + np.arange(cnt, dtype=np.int64))[el]
                    .astype(np.int32))
            cpath = os.path.join(shard_dir, f"cols-r{t:05d}.npz")
            with open(cpath, "wb") as f:
                np.savez(f, **{n: np.concatenate(v) if v else
                               np.zeros(0, np.uint32)
                               for n, v in parts.items()})
            col_files.append(cpath)

            if jr is not None:
                jr.unit_done(
                    "round", t,
                    runs=[[b, os.path.abspath(p), *jj.file_digest(p)]
                          for b, p in round_runs],
                    cols=[os.path.abspath(cpath),
                          *jj.file_digest(cpath)],
                    round_total=int(round_total))

    total = prefix_total

    # ---------------- stage 2: duplicate-group exchange ---------------
    with METRICS.span("prep.markdup_wall"):
        if markdup_unit is not None:
            dup_bits = np.fromfile(markdup_unit["path"], np.uint8)
            if dup_bits.size != total:
                raise CorruptDataError(
                    f"journaled duplicate bitmap covers {dup_bits.size} "
                    f"records but the plan decodes {total} — the spill "
                    f"state is inconsistent; delete the journal to "
                    f"start over")
            n_dups = int(dup_bits.sum())
            METRICS.count("jobs.markdup_skipped")
        else:
            sig = {n: [] for n in ("k0", "k1", "k2", "k3", "k4",
                                   "score", "gidx")}
            for cpath in col_files:
                with np.load(cpath) as z:
                    for n in sig:
                        sig[n].append(z[n])
            sig = {n: np.concatenate(v) if v else np.zeros(0, np.uint32)
                   for n, v in sig.items()}
            m = int(sig["gidx"].size)
            dup_bits = np.zeros(total, np.uint8)
            if m:
                n_per = -(-m // n_dev)
                cap2 = _round_up(max(n_per, 1), 1024)
                step2 = _make_markdup_exchange_step(mesh, cap2)

                def slice_of(arr, d, dtype):
                    part = arr[d * n_per:min((d + 1) * n_per, m)]
                    out = np.zeros(cap2, dtype)
                    out[:part.size] = part
                    return out[None]

                args2 = [sharded((n_dev, cap2), jnp.uint32,
                                 lambda d, a=sig[n]: slice_of(
                                     a, d, np.uint32))
                         for n in ("k0", "k1", "k2", "k3", "k4",
                                   "score")]
                args2.append(sharded((n_dev, cap2), jnp.int32,
                                     lambda d: slice_of(sig["gidx"], d,
                                                        np.int32)))
                args2.append(sharded(
                    (n_dev,), jnp.int32,
                    lambda d: np.asarray(
                        [max(0, min(n_per, m - d * n_per))], np.int32)))
                six2, dup2 = step2(*args2)
                b_six, b_dup = _buckets(six2), _buckets(dup2)
                for d in range(n_dev):
                    s_arr, du = b_six[d], b_dup[d]
                    okm = s_arr != _I32_SENTINEL
                    dup_bits[s_arr[okm & (du == 1)]] = 1
            n_dups = int(dup_bits.sum())
            dpath = os.path.join(shard_dir, "dupbits.u8")
            with open(dpath, "wb") as f:
                f.write(dup_bits.tobytes())
            if jr is not None:
                jr.unit_done("markdup", 0, path=os.path.abspath(dpath),
                             size=jj.file_digest(dpath)[0],
                             crc=jj.file_digest(dpath)[1],
                             n_dups=n_dups, total=int(total))
        METRICS.count("prep.duplicates_marked", n_dups)

    # ---------------- stage 3: patched per-bucket merge + write -------
    from hadoop_bam_tpu.split.kmerge import kmerge

    out_header = _sorted_header(header, by_name=False)
    written = 0
    with METRICS.span("prep.write_wall"):
        sw = ShardedFileWriter(output_path, n_dev,
                               dir_suffix=".mkdup-spill/parts",
                               resume_state=resume)
        if resume is not None:
            sw.sweep_stale_temps()
        for b in range(n_dev):
            if jr is not None and sw.shard_committed(b):
                written += int(resume.unit("shard", b).get("records", 0))
                continue
            chunks: List[bytes] = []
            n_b = 0
            for (hi, lo, gidx), payload in kmerge(
                    (_iter_run_frames(p)
                     for p in run_files.get(b, [])),
                    key=lambda kv: kv[0]):
                dup = int(dup_bits[gidx])
                if remove_duplicates and dup:
                    continue
                flag = int.from_bytes(payload[18:20], "little")
                nf = (flag & ~0x400) | (0x400 if dup else 0)
                if nf != flag:
                    payload = (payload[:18]
                               + nf.to_bytes(2, "little")
                               + payload[20:])
                chunks.append(payload)
                n_b += 1
            # every bucket writes its part — empty included — so the
            # concatenation sees the full deterministic part set
            with sw.open_shard(b) as f:
                with BamWriter(f, out_header, write_header=False,
                               write_eof=False,
                               level=config.write_compress_level) as w:
                    w.write_raw(b"".join(chunks), n_records=n_b)
            written += n_b
            if jr is not None:
                part = sw.shard_path(b)
                size, crc = jj.file_digest(part)
                jr.unit_done("shard", b, path=os.path.abspath(part),
                             size=size, crc=crc, records=n_b)

        expected = total - (n_dups if remove_duplicates else 0)
        if written != expected:
            raise CorruptDataError(
                f"fused markdup wrote {written} of {expected} records "
                f"— output is invalid")
        sw.concatenate(
            lambda parts: write_bam_shards_concat(
                parts, output_path, out_header, config=config),
            what="fused markdup write", cleanup=False)

    if jr is not None:
        size, crc = jj.file_digest(output_path)
        jr.job_done(records=int(written), size=size, crc=crc)
        jr.close()
    return written
