"""Fused preprocessing plane: mesh duplicate marking.

The last ROADMAP vertical: the NGS preprocessing stages that already
exist as separate passes (decode planes, mesh sort exchange, the
parallel indexed writers, journaled resume) composed into ONE pass —
read -> sort exchange -> markdup -> indexed write — so records never
re-inflate between stages (sam2bam's fusion argument, PAPERS.md).

- ``oracle`` — the serial host oracle: the ONE definition of the
  duplicate signature, the best-of-duplicate score, and the flag-patch
  semantics the mesh path is byte-validated against.
- ``markdup`` — the device kernels: the fused sort-exchange +
  signature-column unpack step and the signature-hash markdup exchange.
- ``pipeline`` — the journaled fused pipeline (``hbam mkdup``), with
  per-stage resume grains: round (sort spills), markdup (the duplicate
  bitmap), shard (the indexed write's parts).
"""
from hadoop_bam_tpu.prep.oracle import markdup_bam_oracle  # noqa: F401
from hadoop_bam_tpu.prep.pipeline import markdup_bam_mesh  # noqa: F401
