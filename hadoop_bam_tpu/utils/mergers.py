"""Shard mergers + output preparation.

Rebuild of hb/util/SAMFileMerger.java, hb/util/VCFFileMerger.java and
hb/util/SAMOutputPreparer.java (SURVEY.md section 2.4): distributed jobs
write headerless, terminatorless shards in parallel; the merger writes the
header once, concatenates shard bytes (BGZF members concatenate legally
[SPEC]), and appends the 28-byte BGZF EOF terminator.  Shards that do carry
a stray terminator are tolerated (stripped), since empty BGZF members are
legal but wasteful mid-file.
"""
from __future__ import annotations

import glob
import io
import os
from typing import Iterable, List, Optional, Sequence

from hadoop_bam_tpu.config import DEFAULT_CONFIG
from hadoop_bam_tpu.formats import bgzf
from hadoop_bam_tpu.formats.bam import SAMHeader


def _level(level: Optional[int]) -> int:
    # default follows the write_compress_level knob, not a literal 6
    return DEFAULT_CONFIG.write_compress_level if level is None else level


def prepare_bam_output(sink, header: SAMHeader,
                       level: Optional[int] = None) -> None:
    """Write the initial (BGZF-compressed) BAM header bytes — the
    SAMOutputPreparer step when composing final outputs from shards."""
    w = bgzf.BGZFWriter(sink, level=_level(level), write_eof=False)
    w.write(header.to_bam_bytes())
    w.close()


def prepare_sam_output(sink, header: SAMHeader) -> None:
    sink.write(header.to_sam_text().encode())


def _strip_trailing_eof(data: bytes) -> bytes:
    while data.endswith(bgzf.EOF_BLOCK):
        data = data[:-len(bgzf.EOF_BLOCK)]
    return data


def merge_bam_shards(shard_paths: Sequence[str], out_path: str,
                     header: SAMHeader,
                     level: Optional[int] = None) -> None:
    """Header + concatenated shards + EOF terminator -> one legal BAM."""
    with open(out_path, "wb") as out:
        prepare_bam_output(out, header, level=level)
        for p in shard_paths:
            with open(p, "rb") as f:
                out.write(_strip_trailing_eof(f.read()))
        out.write(bgzf.EOF_BLOCK)


def merge_bam_shards_reblocked(shard_paths: Sequence[str], out_path: str,
                               header: SAMHeader,
                               level: Optional[int] = None) -> None:
    """Like merge_bam_shards, but re-compresses the shards into ONE
    continuous BGZF stream (header and records share the 64 KiB block
    framing) instead of concatenating shard members.  The output is
    byte-identical to writing the same records through a single
    streaming BamWriter — the property the mesh sort's multi-host path
    needs to match sort_bam exactly.  Costs one inflate+deflate pass on
    the merging host; use merge_bam_shards when member-concat framing
    is acceptable."""
    from hadoop_bam_tpu.formats.bamio import BamWriter
    from hadoop_bam_tpu.ops import inflate as inflate_ops

    with open(out_path, "wb") as out:
        with BamWriter(out, header, level=_level(level)) as w:
            for p in shard_paths:
                raw = open(p, "rb").read()
                if not raw:
                    continue
                table = inflate_ops.block_table(raw)
                data, _ = inflate_ops.inflate_span(raw, table)
                w.write_raw(data.tobytes())


def merge_sam_shards(shard_paths: Sequence[str], out_path: str,
                     header: SAMHeader) -> None:
    with open(out_path, "w") as out:
        out.write(header.to_sam_text())
        for p in shard_paths:
            with open(p) as f:
                for line in f:
                    if not line.startswith("@"):
                        out.write(line)


def merge_vcf_shards(shard_paths: Sequence[str], out_path: str,
                     header: "VCFHeader", compress: bool = False,
                     level: Optional[int] = None) -> None:
    """hb/util/VCFFileMerger.java: header once + headerless text shards; for
    BGZF output the header gets its own member(s) and shards concatenate as
    legal BGZF members, terminated by the EOF block."""
    if compress:
        with open(out_path, "wb") as out:
            w = bgzf.BGZFWriter(out, level=_level(level), write_eof=False)
            w.write(header.to_text().encode())
            w.close()
            for p in shard_paths:
                with open(p, "rb") as f:
                    out.write(_strip_trailing_eof(f.read()))
            out.write(bgzf.EOF_BLOCK)
    else:
        with open(out_path, "wb") as out:
            out.write(header.to_text().encode())
            for p in shard_paths:
                with open(p, "rb") as f:
                    for line in f:
                        if not line.startswith(b"#"):
                            out.write(line)


def merge_bcf_shards(shard_paths: Sequence[str], out_path: str,
                     header: "VCFHeader",
                     level: Optional[int] = None) -> None:
    """Header block once (BGZF member) + concatenated headerless BCF shards
    + EOF terminator -> one legal BCF."""
    from hadoop_bam_tpu.formats.bcf import encode_header
    with open(out_path, "wb") as out:
        w = bgzf.BGZFWriter(out, level=_level(level), write_eof=False)
        w.write(encode_header(header))
        w.close()
        for p in shard_paths:
            with open(p, "rb") as f:
                out.write(_strip_trailing_eof(f.read()))
        out.write(bgzf.EOF_BLOCK)


def shard_paths_in_dir(dir_path: str, pattern: str = "part-*") -> List[str]:
    """Sorted shard discovery (the reference merges MR part-r-NNNNN files)."""
    return sorted(glob.glob(os.path.join(dir_path, pattern)))


def merge_cram_shards(shard_paths: Sequence[str], out_path: str,
                      header: SAMHeader) -> None:
    """CRAM flavor of hb/util/SAMFileMerger.java: file definition + header
    container once, concatenated headerless shard containers (containers are
    self-contained, so they concatenate legally), one EOF container."""
    from hadoop_bam_tpu.formats.cram import EOF_CONTAINER, FileDefinition
    from hadoop_bam_tpu.formats.cramio import _header_container_bytes

    def _strip_cram_eof(data: bytes) -> bytes:
        while data.endswith(EOF_CONTAINER):
            data = data[:-len(EOF_CONTAINER)]
        return data

    with open(out_path, "wb") as out:
        out.write(FileDefinition().to_bytes())
        out.write(_header_container_bytes(header))
        for p in shard_paths:
            with open(p, "rb") as f:
                out.write(_strip_cram_eof(f.read()))
        out.write(EOF_CONTAINER)
