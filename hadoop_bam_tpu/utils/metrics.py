"""Stage metrics: counters, timers, histograms, spans — context-scoped.

The reference exposed only Hadoop task counters and stderr warnings
(SURVEY.md section 5); here every pipeline stage (plan/fetch/inflate/
walk/host_decode/pack/dispatch/kernel/combine, and the query engine's
resolve/fetch/filter) ticks named counters and timers, records
latency/size distributions, and emits structured spans:

- ``count`` / ``timer``       flat counters + thread-summed work seconds
- ``wall_timer``              wall-clock UNION spans (overlapping pool
                              threads merge; see the docstring below)
- ``observe``                 log-bucketed mergeable histograms
                              (``obs/hist.py``) with p50/p95/p99
- ``span``                    wall_timer + a trace-ring event when
                              tracing is enabled (``obs/trace.py``) +
                              a ``jax.profiler`` annotation when jax is
                              active — Chrome-trace exportable
- ``trace``                   timer + jax.profiler annotation (degrades
                              to a plain timer on minimal installs)

**Context scoping.**  ``METRICS`` is a PROXY: attribute access resolves
to the contextvar-scoped current ``Metrics`` instance, falling back to
the process-global default — so every historical ``METRICS.count(...)``
call site keeps working unchanged, while ``MetricsContext`` gives a
concurrent engine batch or bench row its own isolated, attributable
numbers.  ``utils/pools.submit`` and the staging packer thread carry
the context across threads (a bare ``ThreadPoolExecutor.submit`` would
silently fall back to the global).
"""
from __future__ import annotations

import contextlib
import contextvars
import threading
import time
from collections import defaultdict
from typing import Dict, Iterator, Optional

from hadoop_bam_tpu.obs import context as trace_ctx
from hadoop_bam_tpu.obs import flight as _flight
from hadoop_bam_tpu.obs.hist import Histogram
from hadoop_bam_tpu.obs.trace import active_recorder

# span-args size guard: a pathological path/region/repr string passed as
# a span attr must not bloat the trace ring or the flight recorder —
# values are truncated and the key set is capped before any recording
_SPAN_ARG_MAX_CHARS = 120
_SPAN_ARG_MAX_KEYS = 8


def trim_span_args(args: Dict[str, object]) -> Dict[str, object]:
    """Bound one span's attr payload: at most ``_SPAN_ARG_MAX_KEYS``
    keys (insertion order wins; a ``dropped_args`` count marks the cut),
    scalar values pass through, everything else is stringified and
    truncated to ``_SPAN_ARG_MAX_CHARS`` with the elided length noted."""
    out: Dict[str, object] = {}
    dropped = 0
    for k, v in args.items():
        if len(out) >= _SPAN_ARG_MAX_KEYS:
            dropped += 1
            continue
        if isinstance(v, (int, float, bool)) or v is None:
            out[k] = v
            continue
        s = v if isinstance(v, str) else repr(v)
        if len(s) > _SPAN_ARG_MAX_CHARS:
            s = (s[:_SPAN_ARG_MAX_CHARS]
                 + f"...(+{len(s) - _SPAN_ARG_MAX_CHARS})")
        out[k] = s
    if dropped:
        out["dropped_args"] = dropped
    return out


class Metrics:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.counters: Dict[str, int] = defaultdict(int)
        self.timers: Dict[str, float] = defaultdict(float)
        self.timer_calls: Dict[str, int] = defaultdict(int)
        self.wall_timers: Dict[str, float] = defaultdict(float)
        self.wall_calls: Dict[str, int] = defaultdict(int)
        self.histograms: Dict[str, Histogram] = {}
        self._wall_active: Dict[str, list] = {}
        # bumped by reset(): a wall span that straddles a reset() must
        # not account into (or corrupt) the post-reset state — the span
        # captures the epoch at entry and discards itself on mismatch
        self._epoch = 0

    def count(self, name: str, n: int = 1) -> None:
        with self._lock:
            self.counters[name] += n

    def get(self, name: str) -> int:
        """Read one counter without mutating the defaultdict (a bare
        ``counters[name]`` probe would materialize a zero entry)."""
        with self._lock:
            return self.counters.get(name, 0)

    def observe(self, name: str, value: float, n: int = 1) -> None:
        """Record ``value`` into the named log-bucketed histogram
        (latencies in seconds, sizes in bytes — the name's suffix says
        which: ``*_s`` / ``*_bytes``)."""
        with self._lock:
            h = self.histograms.get(name)
            if h is None:
                h = self.histograms[name] = Histogram()
            h.record(value, n)

    def hist_summary(self, name: str) -> Dict[str, float]:
        """count/mean/p50/p95/p99/max of one histogram ({} when absent)."""
        with self._lock:
            h = self.histograms.get(name)
            return h.summary() if h is not None else {}

    def hist_dict(self, name: str) -> Dict[str, object]:
        """One histogram's full mergeable state ({} when absent) — the
        targeted read the SLO engine's admission-path burn check uses
        instead of serializing the whole instance with ``to_dict``."""
        with self._lock:
            h = self.histograms.get(name)
            return h.to_dict() if h is not None else {}

    def discard_series(self, *names: str) -> None:
        """Remove the named series (counter/timer/wall/histogram entries
        of exactly these names) — the eviction hook for bounded
        per-tenant series in a long-lived server.  Unknown names are
        ignored."""
        with self._lock:
            for n in names:
                self.counters.pop(n, None)
                self.timers.pop(n, None)
                self.timer_calls.pop(n, None)
                self.wall_timers.pop(n, None)
                self.wall_calls.pop(n, None)
                self.histograms.pop(n, None)

    def snapshot(self) -> Dict[str, Dict[str, float]]:
        """Consistent copy of all counters/timers (one lock acquisition) —
        the hook quarantine/failure reports use to embed resilience counts
        (pipeline.bad_spans / transient_retries / corrupt_spans,
        io.read_retries, chaos.injected_faults) without racing the pool.
        Histograms are included as their p-summaries; ``to_dict`` carries
        the full mergeable buckets."""
        with self._lock:
            return {"counters": dict(self.counters),
                    "timers": dict(self.timers),
                    "timer_calls": dict(self.timer_calls),
                    "wall_timers": dict(self.wall_timers),
                    "histograms": {k: h.summary()
                                   for k, h in self.histograms.items()}}

    @contextlib.contextmanager
    def timer(self, name: str) -> Iterator[None]:
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            with self._lock:
                self.timers[name] += dt
                self.timer_calls[name] += 1

    @contextlib.contextmanager
    def wall_timer(self, name: str) -> Iterator[None]:
        """WALL-CLOCK span aggregation, distinct from ``timer``: spans of
        the same name that overlap in time (pool threads decoding
        concurrently) merge into their union, so the aggregate reports
        how long the stage occupied the wall — not thread-summed work
        seconds, which can exceed wall time and make pipeline overlap
        invisible (the bench's stage_timer_note caveat)."""
        t0 = time.perf_counter()
        with self._lock:
            epoch = self._epoch
            st = self._wall_active.setdefault(name, [0, t0])
            if st[0] == 0:
                st[1] = t0
            st[0] += 1
        try:
            yield
        finally:
            t1 = time.perf_counter()
            with self._lock:
                if self._epoch != epoch:
                    return     # reset() raced this span: discard it
                st = self._wall_active.get(name)
                if st is None:
                    return
                st[0] -= 1
                if st[0] == 0:
                    self.wall_timers[name] += t1 - st[1]
                    self.wall_calls[name] += 1

    def add_wall(self, name: str, seconds: float,
                 t0: Optional[float] = None,
                 args: Optional[dict] = None) -> None:
        """Record an externally-measured wall span (the FeedPipeline's
        packer/dispatch accounting measures its own intervals).  When
        tracing is enabled and the caller passes its ``perf_counter``
        start ``t0``, the interval also lands in the trace ring — with
        the active trace id and parent span, so externally-measured
        intervals join the request's causal tree.  Every add_wall also
        feeds the always-on flight recorder."""
        if args:
            args = trim_span_args(args)
        with self._lock:
            self.wall_timers[name] += seconds
            self.wall_calls[name] += 1
        if t0 is not None:
            rec = active_recorder()
            if rec is not None:
                ev_args = dict(args) if args else {}
                ctx = trace_ctx.current_trace()
                if ctx is not None:
                    ev_args["trace"] = ctx.trace_id
                    ev_args["psid"] = ctx.span_id
                rid = trace_ctx.replica_id()
                if rid is not None:
                    ev_args["replica"] = rid
                rec.complete(name, t0, seconds, ev_args or None)
        _flight.recorder().record_span(name, seconds, args or None)

    @contextlib.contextmanager
    def span(self, name: str, **args) -> Iterator[None]:
        """A STAGE SPAN: ``wall_timer`` aggregation plus, when tracing is
        enabled (``obs.trace.enable_tracing``), one trace-ring event per
        occurrence — name, thread, duration, the keyword ``args``
        (byte counts, record counts; size-guarded by ``trim_span_args``)
        and the active ``TraceContext``'s (trace, sid, psid) causal ids
        — and a ``jax.profiler`` TraceAnnotation when jax is active.
        Every completion ALSO lands in the always-on flight recorder
        ring (one deque append).  Tracing disabled, this is
        ``wall_timer`` plus the flight append (the bench's
        ``obs_overhead_pct`` row pins the whole cost <2%)."""
        rec = active_recorder()
        if args:
            args = trim_span_args(args)
        # child-span bookkeeping only while tracing (the causal ids are
        # for the exported tree; the flight ring needs just the trace id,
        # which it reads from the contextvar itself)
        ids = trace_ctx.begin_span() if rec is not None else None
        ann = rec.annotation(name) if rec is not None else None
        t0 = time.perf_counter()
        try:
            if ann is not None:
                with ann, self.wall_timer(name):
                    yield
            else:
                with self.wall_timer(name):
                    yield
        finally:
            dur = time.perf_counter() - t0
            if rec is not None:
                ev_args = dict(args) if args else {}
                if ids is not None:
                    tok, tid, sid, psid = ids
                    ev_args["trace"] = tid
                    ev_args["sid"] = sid
                    ev_args["psid"] = psid
                    try:
                        trace_ctx.end_span(tok)
                    except ValueError:
                        pass   # closed from another context: ids stand
                rid = trace_ctx.replica_id()
                if rid is not None:
                    # fleet processes stamp their replica on every span
                    # so a cross-replica trace attributes work correctly
                    ev_args["replica"] = rid
                rec.complete(name, t0, dur, ev_args or None)
            _flight.recorder().record_span(name, dur, args or None)

    @contextlib.contextmanager
    def trace(self, name: str) -> Iterator[None]:
        """Timer + jax.profiler annotation (shows up in TPU traces).

        The profiler import is guarded: on a minimal install without
        jax (or with a jax lacking the profiler module) this degrades
        to the plain ``timer`` instead of raising ImportError from a
        hot loop."""
        try:
            from jax.profiler import TraceAnnotation
            ann = TraceAnnotation(name)
        except Exception:  # noqa: BLE001 — profiling is optional
            ann = None
        if ann is None:
            with self.timer(name):
                yield
        else:
            with ann, self.timer(name):
                yield

    # -- mesh-wide merge (parallel/distributed.merge_metrics) ----------------

    def to_dict(self) -> Dict[str, object]:
        """Full mergeable state (histograms as buckets, not summaries) —
        the allgather payload of ``merge_metrics``."""
        with self._lock:
            return {"counters": dict(self.counters),
                    "timers": dict(self.timers),
                    "timer_calls": dict(self.timer_calls),
                    "wall_timers": dict(self.wall_timers),
                    "wall_calls": dict(self.wall_calls),
                    "histograms": {k: h.to_dict()
                                   for k, h in self.histograms.items()}}

    def merge_dict(self, d: Dict[str, object]) -> None:
        """Merge one host's ``to_dict`` payload into this instance:
        counters/timers SUM (work adds across hosts), histograms merge
        by bucket addition (associative), and wall spans take the MAX
        across hosts — each host's value is already its local union, and
        hosts run concurrently, so the mesh-wide wall is bounded by the
        slowest host, not the sum."""
        with self._lock:
            for k, v in dict(d.get("counters", {})).items():
                self.counters[k] += int(v)
            for k, v in dict(d.get("timers", {})).items():
                self.timers[k] += float(v)
            for k, v in dict(d.get("timer_calls", {})).items():
                self.timer_calls[k] += int(v)
            for k, v in dict(d.get("wall_timers", {})).items():
                self.wall_timers[k] = max(self.wall_timers[k], float(v))
            for k, v in dict(d.get("wall_calls", {})).items():
                self.wall_calls[k] = max(self.wall_calls[k], int(v))
            for k, hd in dict(d.get("histograms", {})).items():
                h = self.histograms.get(k)
                if h is None:
                    h = self.histograms[k] = Histogram()
                h.merge(Histogram.from_dict(hd))

    @classmethod
    def from_dict(cls, d: Dict[str, object]) -> "Metrics":
        m = cls()
        m.merge_dict(d)
        return m

    def render(self) -> str:
        lines = []
        for k in sorted(self.counters):
            lines.append(f"counter {k} = {self.counters[k]}")
        for k in sorted(self.timers):
            calls = self.timer_calls[k]
            tot = self.timers[k]
            lines.append(f"timer   {k} = {tot:.4f}s over {calls} calls "
                         f"({tot / max(calls, 1) * 1e3:.2f} ms/call)")
        for k in sorted(self.wall_timers):
            lines.append(f"wall    {k} = {self.wall_timers[k]:.4f}s over "
                         f"{self.wall_calls[k]} span(s)")
        for k in sorted(self.histograms):
            s = self.histograms[k].summary()
            lines.append(
                f"hist    {k} = n={s['count']} mean={s['mean']:.4g} "
                f"p50={s['p50']:.4g} p95={s['p95']:.4g} "
                f"p99={s['p99']:.4g} max={s['max']:.4g}")
        return "\n".join(lines)

    def reset(self) -> None:
        with self._lock:
            self._epoch += 1
            self.counters.clear()
            self.timers.clear()
            self.timer_calls.clear()
            self.wall_timers.clear()
            self.wall_calls.clear()
            self.histograms.clear()
            self._wall_active.clear()


class NullMetrics(Metrics):
    """Every recording surface a no-op: the bench's ``obs_overhead_pct``
    row runs flagstat under this to measure what the always-on
    instrumentation itself costs (spans, counters, histogram ticks —
    tracing disabled)."""

    def count(self, name: str, n: int = 1) -> None:
        pass

    def observe(self, name: str, value: float, n: int = 1) -> None:
        pass

    def add_wall(self, name: str, seconds: float,
                 t0: Optional[float] = None,
                 args: Optional[dict] = None) -> None:
        pass

    @contextlib.contextmanager
    def timer(self, name: str) -> Iterator[None]:
        yield

    @contextlib.contextmanager
    def wall_timer(self, name: str) -> Iterator[None]:
        yield

    @contextlib.contextmanager
    def span(self, name: str, **args) -> Iterator[None]:
        yield

    @contextlib.contextmanager
    def trace(self, name: str) -> Iterator[None]:
        yield


# ---------------------------------------------------------------------------
# context scoping: METRICS is a proxy over the contextvar-scoped instance
# ---------------------------------------------------------------------------

_BASE = Metrics()
_CURRENT: "contextvars.ContextVar[Optional[Metrics]]" = \
    contextvars.ContextVar("hbam_metrics", default=None)


def current_metrics() -> Metrics:
    """The Metrics instance this context records into: the innermost
    active ``MetricsContext``, else the process-global default."""
    m = _CURRENT.get()
    return m if m is not None else _BASE


def base_metrics() -> Metrics:
    """The process-global default instance (what ``METRICS`` resolves to
    outside any ``MetricsContext``)."""
    return _BASE


class MetricsContext:
    """Run-scoped isolation: everything recorded inside the ``with``
    block — including work handed to the shared decode pool via
    ``utils.pools.submit`` and the staging packer thread — lands in this
    context's own ``Metrics`` instead of the process global, so two
    concurrent engine batches (or bench rows) get separately
    attributable numbers::

        with MetricsContext() as m:
            engine.query_records(batch)
        print(m.hist_summary("query.latency_s"))

    Re-entrant and nestable; pass an existing instance (e.g.
    ``NullMetrics()``) to substitute rather than isolate."""

    def __init__(self, metrics: Optional[Metrics] = None):
        self.metrics = metrics if metrics is not None else Metrics()
        self._token: Optional[contextvars.Token] = None

    def __enter__(self) -> Metrics:
        self._token = _CURRENT.set(self.metrics)
        return self.metrics

    def __exit__(self, *exc) -> None:
        if self._token is not None:
            _CURRENT.reset(self._token)
            self._token = None


class _MetricsProxy:
    """Attribute access forwards to ``current_metrics()`` — the shim
    that context-scopes every historical ``METRICS.x`` call site without
    touching it."""

    __slots__ = ()

    def __getattr__(self, name: str):
        return getattr(current_metrics(), name)

    def __repr__(self) -> str:
        return f"<METRICS proxy -> {current_metrics()!r}>"


METRICS = _MetricsProxy()
