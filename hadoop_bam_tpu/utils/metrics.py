"""Stage metrics: counters + timers with a text dump.

The reference exposed only Hadoop task counters and stderr warnings
(SURVEY.md section 5); here every pipeline stage (plan/fetch/inflate/walk/
device) ticks named counters and timers, dumpable as text — and
``jax.profiler`` traces can be layered on via ``trace()``.
"""
from __future__ import annotations

import contextlib
import threading
import time
from collections import defaultdict
from typing import Dict, Iterator


class Metrics:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.counters: Dict[str, int] = defaultdict(int)
        self.timers: Dict[str, float] = defaultdict(float)
        self.timer_calls: Dict[str, int] = defaultdict(int)
        self.wall_timers: Dict[str, float] = defaultdict(float)
        self.wall_calls: Dict[str, int] = defaultdict(int)
        self._wall_active: Dict[str, list] = {}

    def count(self, name: str, n: int = 1) -> None:
        with self._lock:
            self.counters[name] += n

    def get(self, name: str) -> int:
        """Read one counter without mutating the defaultdict (a bare
        ``counters[name]`` probe would materialize a zero entry)."""
        with self._lock:
            return self.counters.get(name, 0)

    def snapshot(self) -> Dict[str, Dict[str, float]]:
        """Consistent copy of all counters/timers (one lock acquisition) —
        the hook quarantine/failure reports use to embed resilience counts
        (pipeline.bad_spans / transient_retries / corrupt_spans,
        io.read_retries, chaos.injected_faults) without racing the pool."""
        with self._lock:
            return {"counters": dict(self.counters),
                    "timers": dict(self.timers),
                    "timer_calls": dict(self.timer_calls),
                    "wall_timers": dict(self.wall_timers)}

    @contextlib.contextmanager
    def timer(self, name: str) -> Iterator[None]:
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            with self._lock:
                self.timers[name] += dt
                self.timer_calls[name] += 1

    @contextlib.contextmanager
    def wall_timer(self, name: str) -> Iterator[None]:
        """WALL-CLOCK span aggregation, distinct from ``timer``: spans of
        the same name that overlap in time (pool threads decoding
        concurrently) merge into their union, so the aggregate reports
        how long the stage occupied the wall — not thread-summed work
        seconds, which can exceed wall time and make pipeline overlap
        invisible (the bench's stage_timer_note caveat)."""
        t0 = time.perf_counter()
        with self._lock:
            st = self._wall_active.setdefault(name, [0, t0])
            if st[0] == 0:
                st[1] = t0
            st[0] += 1
        try:
            yield
        finally:
            t1 = time.perf_counter()
            with self._lock:
                st = self._wall_active.get(name)
                if st is None:      # reset() raced an active span
                    return
                st[0] -= 1
                if st[0] == 0:
                    self.wall_timers[name] += t1 - st[1]
                    self.wall_calls[name] += 1

    def add_wall(self, name: str, seconds: float) -> None:
        """Record an externally-measured wall span (the FeedPipeline's
        packer/dispatch accounting measures its own intervals)."""
        with self._lock:
            self.wall_timers[name] += seconds
            self.wall_calls[name] += 1

    @contextlib.contextmanager
    def trace(self, name: str) -> Iterator[None]:
        """Timer + jax.profiler annotation (shows up in TPU traces)."""
        import jax.profiler
        with jax.profiler.TraceAnnotation(name), self.timer(name):
            yield

    def render(self) -> str:
        lines = []
        for k in sorted(self.counters):
            lines.append(f"counter {k} = {self.counters[k]}")
        for k in sorted(self.timers):
            calls = self.timer_calls[k]
            tot = self.timers[k]
            lines.append(f"timer   {k} = {tot:.4f}s over {calls} calls "
                         f"({tot / max(calls, 1) * 1e3:.2f} ms/call)")
        for k in sorted(self.wall_timers):
            lines.append(f"wall    {k} = {self.wall_timers[k]:.4f}s over "
                         f"{self.wall_calls[k]} span(s)")
        return "\n".join(lines)

    def reset(self) -> None:
        with self._lock:
            self.counters.clear()
            self.timers.clear()
            self.timer_calls.clear()
            self.wall_timers.clear()
            self.wall_calls.clear()
            self._wall_active.clear()


METRICS = Metrics()
