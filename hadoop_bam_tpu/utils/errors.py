"""Fault taxonomy for the I/O resilience layer.

The reference leaned entirely on MapReduce task retries for fault tolerance
(SURVEY.md section 5): every failure was retried identically.  Production
streaming decompressors separate failure *classes* with different policies —
a transient read error (flaky NFS, object-store throttle, tunnel reset) may
heal on retry with backoff, while a CRC mismatch or malformed record chain
is deterministic and re-decoding it only wastes the retry budget.  This
module is the single place that distinction lives; every policy boundary
(``decode_with_retry``, ``RetryingByteSource``, ``broadcast_plan``) consults
``classify_error`` instead of growing its own isinstance ladders.

Classes deliberately multiple-inherit from the builtin they historically
surfaced as (``OSError`` / ``ValueError``) so pre-taxonomy callers catching
builtins keep working — classification is additive, not a breaking rename.
"""
from __future__ import annotations

import struct
import zlib

# error-class tags (quarantine manifest entries carry these strings)
TRANSIENT = "transient"
CORRUPT = "corrupt"
PLAN = "plan"


class HBamError(Exception):
    """Base of all classified framework errors."""


class TransientIOError(HBamError, OSError):
    """A read/communication failure that may heal on retry: flaky network
    filesystem, object-store throttling, a dropped tunnel link, an injected
    chaos fault.  The retry policy backs off and re-attempts these.

    ``retry_after_s`` is the optional server-supplied backoff hint a shed
    (admission reject, open tenant breaker, stopping serve loop) carries —
    transports forward it on the wire so clients back off for the right
    duration instead of guessing."""

    def __init__(self, *args, retry_after_s: "float | None" = None):
        super().__init__(*args)
        self.retry_after_s = retry_after_s


class CorruptDataError(HBamError, ValueError):
    """Deterministic data corruption: bad magic, CRC mismatch, malformed
    record chain, impossible field values.  Re-decoding the same bytes can
    never heal it — the policy fails fast (or quarantines the span when
    ``skip_bad_spans`` is set) without burning retries."""


class PlanError(HBamError, ValueError):
    """A planning / user-parameter error (bad interval syntax, span larger
    than the device geometry, oversized broadcast payload).  Never retried
    and never eaten by ``skip_bad_spans``: the run is misconfigured, not
    the data."""


class CircuitBreakerError(HBamError, RuntimeError):
    """Raised when the quarantined-span fraction crosses
    ``config.max_bad_span_fraction`` — or when a ``resilience`` circuit
    for the subsystem is OPEN: the run aborts (or the request sheds)
    loudly instead of silently degrading.  No longer one-way: the
    half-open machinery in ``resilience/breaker.py`` re-probes after a
    cooldown, and ``retry_after_s`` tells callers when that is."""

    def __init__(self, *args, retry_after_s: "float | None" = None):
        super().__init__(*args)
        self.retry_after_s = retry_after_s


# builtins that indicate the environment, not the bytes, failed
_TRANSIENT_BUILTINS = (TimeoutError, ConnectionError, InterruptedError,
                       BlockingIOError)
# deterministic OSErrors: retrying a missing path or a permission wall
# wastes the budget exactly like corruption would, and quarantining it
# would silently convert a path typo into an empty result — PLAN class
_PLAN_BUILTINS = (FileNotFoundError, IsADirectoryError, NotADirectoryError,
                  PermissionError)
# builtins raised by the decode stack on bad bytes
_CORRUPT_BUILTINS = (zlib.error, struct.error, ValueError, IndexError,
                     KeyError, UnicodeDecodeError, EOFError, OverflowError)


def classify_error(exc: BaseException) -> str:
    """Map an exception to its failure class: TRANSIENT / CORRUPT / PLAN.

    Explicit taxonomy classes win; builtins fall back to their usual
    meaning on the decode path (most of the OSError family = environment =
    transient, except the deterministic members like FileNotFoundError
    which are PLAN; parse/decode errors = bytes = corrupt).  Unknown
    exceptions classify as CORRUPT: retrying an unknown failure is the old
    wasteful behavior this layer exists to remove, and fail-fast is the
    safe default."""
    if isinstance(exc, PlanError):
        return PLAN
    if isinstance(exc, TransientIOError):
        return TRANSIENT
    if isinstance(exc, CorruptDataError):
        return CORRUPT
    if isinstance(exc, _TRANSIENT_BUILTINS):
        return TRANSIENT
    if isinstance(exc, _PLAN_BUILTINS):
        return PLAN
    if isinstance(exc, OSError):
        return TRANSIENT
    if isinstance(exc, _CORRUPT_BUILTINS):
        return CORRUPT
    return CORRUPT
