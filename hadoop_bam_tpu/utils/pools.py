"""The process-wide shared decode pool.

Every driver call used to spin up (and tear down) its own
``ThreadPoolExecutor`` — a per-call tax of worker-thread creation plus a
join on exit, multiplied by the number of driver invocations in a run
(the bench alone makes dozens).  Decode work is uniform across drivers
(fetch + inflate + pack a span), so one pool sized once from the host's
CPU count serves them all; ``set_decode_pool`` injects a replacement for
tests (a recording pool, a single-thread pool for determinism).

The pool is created lazily on first use.  ``config.decode_pool_workers``
overrides the size at creation time only — the first caller wins, later
configs get the existing pool (one process, one pool, by design).
"""
from __future__ import annotations

import collections
import concurrent.futures as cf
import contextvars
import os
import threading
import time
from typing import Optional, Tuple

from hadoop_bam_tpu.resilience import chaos

_LOCK = threading.Lock()
_POOL: Optional[cf.ThreadPoolExecutor] = None
_POOL_SIZE = 0


def default_pool_size(config=None) -> int:
    """Worker count for a fresh pool: config.decode_pool_workers when
    set, else the measured sweet spot of 4x CPUs in [4, 32] (decode
    threads block on I/O about as often as they inflate)."""
    n = getattr(config, "decode_pool_workers", None) if config else None
    if n:
        return max(1, int(n))
    return min(32, max(4, (os.cpu_count() or 4) * 4))


def decode_pool(config=None) -> cf.ThreadPoolExecutor:
    """The shared decode executor (created on first call, never torn
    down — idle workers cost nothing, re-creation per driver call cost
    thread spawns + a join on every invocation)."""
    global _POOL, _POOL_SIZE
    with _LOCK:
        if _POOL is None:
            _POOL_SIZE = default_pool_size(config)
            _POOL = cf.ThreadPoolExecutor(
                max_workers=_POOL_SIZE, thread_name_prefix="hbam-decode")
        return _POOL


def decode_pool_size(config=None) -> int:
    """Worker count of the shared pool (materializing it if needed) —
    what the drivers size their prefetch windows from."""
    decode_pool(config)
    return _POOL_SIZE


def _timed_task(fn, t_submit: float, args, kwargs):
    from hadoop_bam_tpu.utils.metrics import current_metrics

    m = current_metrics()
    t0 = time.perf_counter()
    # queue wait + run durations as log-bucketed histograms: the pool is
    # SHARED across drivers, so p95 task_wait is the direct saturation
    # signal (a deep wait distribution means the pool, not the device,
    # is the bottleneck) — a flat timer cannot show that
    m.observe("pool.task_wait_s", t0 - t_submit)
    # chaos point ON THE WORKER thread (pool.submit fires on the
    # submitter's): a "delay" fault here wedges a worker mid-task —
    # the exact hang shape the per-future timeout exists to surface
    chaos.fire("pool.task")
    try:
        return fn(*args, **kwargs)
    finally:
        m.observe("pool.task_run_s", time.perf_counter() - t0)


def result_with_timeout(fut: cf.Future, timeout_s: Optional[float],
                        what: str = "pool task"):
    """``fut.result()`` with a hard deadline, classified.

    A worker that never returns — an injected ``pool.task`` wedge, a
    kernel pread stuck on a dead NFS server — used to hang the consumer
    forever; the timeout converts it into ``TransientIOError`` so the
    caller's retry/breaker machinery (re-submit, quarantine, abort) gets
    to decide instead of the job just freezing.  The wedged THREAD is
    not recoverable (Python cannot kill it) — the caller abandons the
    future and the thread rejoins the pool if/when it unwedges.

    This is the standalone single-future primitive; the windowed span
    consumer (``parallel/pipeline._iter_windowed``) implements the same
    policy inline because it races speculative twins and re-submits —
    the ``pool.task_timeouts`` counter and TRANSIENT classification
    must stay in sync between the two."""
    try:
        return fut.result(timeout=timeout_s)
    except cf.TimeoutError:
        from hadoop_bam_tpu.utils.errors import TransientIOError
        from hadoop_bam_tpu.utils.metrics import METRICS
        METRICS.count("pool.task_timeouts")
        fut.cancel()
        raise TransientIOError(
            f"{what} exceeded the {timeout_s:g}s pool_task_timeout_s "
            f"deadline — worker presumed wedged, abandoning the "
            f"future") from None


def submit(pool: cf.ThreadPoolExecutor, fn, *args,
           priority: str = "fg", **kwargs) -> cf.Future:
    """Context-carrying, histogram-instrumented submit — what every
    decode-path call site uses instead of bare ``pool.submit``:

    - the submitter's ``contextvars`` context rides along, so work done
      on a pool thread records into the submitter's ``MetricsContext``
      (a bare submit silently falls back to the process-global Metrics
      and two concurrent engine batches smear into each other);
    - per-task queue-wait and run durations land in the
      ``pool.task_wait_s`` / ``pool.task_run_s`` histograms;
    - ``priority="bg"`` routes the task through the background gate:
      at most ``background_limit(pool)`` (a quarter of the workers,
      min 1) background tasks occupy the pool concurrently, so serve
      prefetch can soak idle decode capacity without ever starving
      foreground admission — excess background work queues in FIFO
      order and drains as permits free.
    """
    if priority not in ("fg", "bg"):
        from hadoop_bam_tpu.utils.errors import PlanError
        raise PlanError(f"pool priority must be 'fg' or 'bg', "
                        f"got {priority!r}")
    # chaos point: an injected submission failure surfaces HERE — on the
    # submitter's thread, classified TRANSIENT — exactly where a real
    # saturated/failing executor would (no-op unless armed)
    chaos.fire("pool.submit", priority=priority)
    ctx = contextvars.copy_context()
    t_submit = time.perf_counter()
    if priority == "fg":
        return pool.submit(ctx.run, _timed_task, fn, t_submit, args, kwargs)
    fut: cf.Future = cf.Future()
    from hadoop_bam_tpu.utils.metrics import METRICS
    METRICS.count("pool.bg_submitted")
    with _BG_LOCK:
        _BG_QUEUE.append((pool, fut, ctx, fn, t_submit, args, kwargs))
    _pump_background()
    return fut


# ---------------------------------------------------------------------------
# background priority gate (serve prefetch rides this)
# ---------------------------------------------------------------------------

_BG_LOCK = threading.Lock()
_BG_QUEUE: "collections.deque" = collections.deque()
_BG_RUNNING = [0]


def background_limit(pool: cf.ThreadPoolExecutor) -> int:
    """Concurrent background tasks allowed in ``pool``: a quarter of the
    workers (min 1), so >= 3/4 of the pool is always free the instant
    foreground decode work arrives."""
    size = int(getattr(pool, "_max_workers", 1) or 1)
    return max(1, size // 4)


def _run_background(fut: cf.Future, ctx, fn, t_submit, args, kwargs) -> None:
    if not fut.set_running_or_notify_cancel():
        return
    try:
        fut.set_result(ctx.run(_timed_task, fn, t_submit, args, kwargs))
    except BaseException as e:  # noqa: BLE001 — crosses the thread
        fut.set_exception(e)


def _pump_background() -> None:
    while True:
        with _BG_LOCK:
            if not _BG_QUEUE:
                return
            pool = _BG_QUEUE[0][0]
            if _BG_RUNNING[0] >= background_limit(pool):
                return
            item = _BG_QUEUE.popleft()
            _BG_RUNNING[0] += 1
        _pool, fut, ctx, fn, t_submit, args, kwargs = item

        def task(fut=fut, ctx=ctx, fn=fn, t_submit=t_submit, args=args,
                 kwargs=kwargs):
            try:
                _run_background(fut, ctx, fn, t_submit, args, kwargs)
            finally:
                with _BG_LOCK:
                    _BG_RUNNING[0] -= 1
                _pump_background()

        try:
            _pool.submit(task)
        except BaseException as e:  # noqa: BLE001 — pool shut down etc.
            # the permit was taken above and `task` will never run its
            # finally: give the permit back, fail the future (so waiters
            # like Prefetcher.drain never hang), and keep pumping — a
            # speculative submit must never wedge the gate or raise into
            # a foreground serve path
            with _BG_LOCK:
                _BG_RUNNING[0] -= 1
            if not fut.cancel():
                try:
                    fut.set_exception(e)
                except Exception:  # noqa: BLE001 — already resolved
                    pass


def cancel_background() -> int:
    """Cancel every QUEUED (not yet running) background task; returns the
    number cancelled.  ``ServeLoop.stop`` / ``Prefetcher`` teardown use
    this so a shutting-down server never keeps decoding regions nobody
    will ask for."""
    cancelled = 0
    with _BG_LOCK:
        while _BG_QUEUE:
            _p, fut, *_rest = _BG_QUEUE.popleft()
            if fut.cancel():
                cancelled += 1
    from hadoop_bam_tpu.utils.metrics import METRICS
    if cancelled:
        METRICS.count("pool.bg_cancelled", cancelled)
    return cancelled


def pool_stats() -> dict:
    """Occupancy snapshot of the shared decode pool for the health/
    `hbam top` surfaces: worker count, how many pool threads exist (a
    lazy executor only spawns them under load), and the background
    gate's running/queued depths.  Never materializes the pool."""
    with _LOCK:
        pool, size = _POOL, _POOL_SIZE
    with _BG_LOCK:
        bg_running, bg_queued = _BG_RUNNING[0], len(_BG_QUEUE)
    out = {"workers": size, "threads_live": 0,
           "bg_running": bg_running, "bg_queued": bg_queued}
    if pool is not None:
        out["threads_live"] = len(getattr(pool, "_threads", ()) or ())
        out["queued_tasks"] = getattr(pool, "_work_queue").qsize() \
            if hasattr(pool, "_work_queue") else 0
    return out


def set_decode_pool(pool: Optional[cf.ThreadPoolExecutor],
                    size: Optional[int] = None
                    ) -> Tuple[Optional[cf.ThreadPoolExecutor], int]:
    """Injection hook for tests: install ``pool`` (with its advertised
    ``size``) and return the previous (pool, size) for restoration.
    ``set_decode_pool(None)`` drops the override so the next
    ``decode_pool`` call creates a fresh default pool.  The caller owns
    shutdown of any pool it injects (and of a returned previous pool it
    chooses not to restore)."""
    global _POOL, _POOL_SIZE
    with _LOCK:
        prev, prev_size = _POOL, _POOL_SIZE
        _POOL = pool
        _POOL_SIZE = 0 if pool is None else int(
            size if size is not None else getattr(pool, "_max_workers", 1))
        return prev, prev_size
