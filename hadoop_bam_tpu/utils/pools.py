"""The process-wide shared decode pool.

Every driver call used to spin up (and tear down) its own
``ThreadPoolExecutor`` — a per-call tax of worker-thread creation plus a
join on exit, multiplied by the number of driver invocations in a run
(the bench alone makes dozens).  Decode work is uniform across drivers
(fetch + inflate + pack a span), so one pool sized once from the host's
CPU count serves them all; ``set_decode_pool`` injects a replacement for
tests (a recording pool, a single-thread pool for determinism).

The pool is created lazily on first use.  ``config.decode_pool_workers``
overrides the size at creation time only — the first caller wins, later
configs get the existing pool (one process, one pool, by design).
"""
from __future__ import annotations

import concurrent.futures as cf
import os
import threading
from typing import Optional, Tuple

_LOCK = threading.Lock()
_POOL: Optional[cf.ThreadPoolExecutor] = None
_POOL_SIZE = 0


def default_pool_size(config=None) -> int:
    """Worker count for a fresh pool: config.decode_pool_workers when
    set, else the measured sweet spot of 4x CPUs in [4, 32] (decode
    threads block on I/O about as often as they inflate)."""
    n = getattr(config, "decode_pool_workers", None) if config else None
    if n:
        return max(1, int(n))
    return min(32, max(4, (os.cpu_count() or 4) * 4))


def decode_pool(config=None) -> cf.ThreadPoolExecutor:
    """The shared decode executor (created on first call, never torn
    down — idle workers cost nothing, re-creation per driver call cost
    thread spawns + a join on every invocation)."""
    global _POOL, _POOL_SIZE
    with _LOCK:
        if _POOL is None:
            _POOL_SIZE = default_pool_size(config)
            _POOL = cf.ThreadPoolExecutor(
                max_workers=_POOL_SIZE, thread_name_prefix="hbam-decode")
        return _POOL


def decode_pool_size(config=None) -> int:
    """Worker count of the shared pool (materializing it if needed) —
    what the drivers size their prefetch windows from."""
    decode_pool(config)
    return _POOL_SIZE


def set_decode_pool(pool: Optional[cf.ThreadPoolExecutor],
                    size: Optional[int] = None
                    ) -> Tuple[Optional[cf.ThreadPoolExecutor], int]:
    """Injection hook for tests: install ``pool`` (with its advertised
    ``size``) and return the previous (pool, size) for restoration.
    ``set_decode_pool(None)`` drops the override so the next
    ``decode_pool`` call creates a fresh default pool.  The caller owns
    shutdown of any pool it injects (and of a returned previous pool it
    chooses not to restore)."""
    global _POOL, _POOL_SIZE
    with _LOCK:
        prev, prev_size = _POOL, _POOL_SIZE
        _POOL = pool
        _POOL_SIZE = 0 if pool is None else int(
            size if size is not None else getattr(pool, "_max_workers", 1))
        return prev, prev_size
