"""Build + load the native C++ helper library (ctypes).

The reference reached native code through java.util.zip's JNI; we compile
native/hbam_native.cpp on first use with g++ and bind via ctypes (no pybind11
in this image).  Every caller must tolerate ``load() is None`` — the NumPy /
zlib-module fallbacks keep the framework fully functional without a compiler.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

import numpy as np

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
_SRC = os.path.join(_REPO_ROOT, "native", "hbam_native.cpp")
_OUT_DIR = os.path.join(_REPO_ROOT, "native", "build")

# HBAM_NATIVE_SANITIZE=address|thread builds and loads a sanitized variant
# (the reference side got memory safety for free from the JVM; our C++ has
# threads + raw offset arithmetic, so CI exercises it under ASan/TSan —
# SURVEY.md section 5 sanitizers row).  The sanitized .so only loads when
# the runtime (libasan/libtsan) is preloaded; tests spawn a subprocess with
# LD_PRELOAD set (tests/test_native_sanitize.py).
_SANITIZE = os.environ.get("HBAM_NATIVE_SANITIZE", "")
_SO = os.path.join(
    _OUT_DIR, f"libhbam_native_{_SANITIZE}.so" if _SANITIZE
    else "libhbam_native.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False


def _compile() -> bool:
    os.makedirs(_OUT_DIR, exist_ok=True)
    base = ["g++", "-O3", "-march=native", "-shared", "-fPIC", "-pthread",
            _SRC, "-o", _SO]
    if _SANITIZE:
        base[1:1] = [f"-fsanitize={_SANITIZE}", "-fno-omit-frame-pointer",
                     "-g"]
    # Prefer libdeflate (~2x zlib inflate speed); fall back to plain zlib.
    for extra in (["-DHBAM_USE_LIBDEFLATE", "-lz", "-ldeflate"], ["-lz"]):
        try:
            subprocess.run(base + extra, check=True, capture_output=True,
                           timeout=120)
            return True
        except Exception:
            continue
    return False


def load() -> Optional[ctypes.CDLL]:
    """Load (compiling if needed) the native library; None on failure."""
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if not os.path.exists(_SRC):
            return None
        stale = (not os.path.exists(_SO)
                 or os.path.getmtime(_SO) < os.path.getmtime(_SRC))
        if stale and not _compile():
            return None
        try:
            lib = ctypes.CDLL(_SO)
        except OSError:
            return None
        i8p = ctypes.POINTER(ctypes.c_uint8)
        i64p = ctypes.POINTER(ctypes.c_int64)
        i32p = ctypes.POINTER(ctypes.c_int32)
        u32p = ctypes.POINTER(ctypes.c_uint32)
        lib.hbam_inflate_batch.restype = ctypes.c_int
        lib.hbam_inflate_batch.argtypes = [
            i8p, i64p, i32p, ctypes.c_int32, i8p, i64p, i32p, ctypes.c_int32]
        lib.hbam_walk_bam_records.restype = ctypes.c_int64
        lib.hbam_walk_bam_records.argtypes = [
            i8p, ctypes.c_int64, ctypes.c_int64, i64p, ctypes.c_int64, i64p]
        lib.hbam_walk_bam_packed.restype = ctypes.c_int64
        lib.hbam_walk_bam_packed.argtypes = [
            i8p, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64, i32p, i32p,
            ctypes.c_int32, ctypes.c_int32, i8p, i64p, ctypes.c_int64, i64p]
        lib.hbam_walk_bam_payload.restype = ctypes.c_int64
        lib.hbam_walk_bam_payload.argtypes = [
            i8p, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_int32, ctypes.c_int32, ctypes.c_int32,
            i8p, i8p, i8p, i64p, ctypes.c_int64, i64p]
        for name in ("hbam_rans0_decode", "hbam_rans1_decode"):
            fn = getattr(lib, name)
            fn.restype = ctypes.c_int
            fn.argtypes = [i8p, ctypes.c_int64, ctypes.c_int64,
                           u32p, u32p, i8p, i8p, ctypes.c_int64]
        lib.hbam_itf8_decode_batch.restype = ctypes.c_int64
        lib.hbam_itf8_decode_batch.argtypes = [
            i8p, ctypes.c_int64, ctypes.c_int64, i32p]
        lib.hbam_crc32_batch.restype = ctypes.c_int
        lib.hbam_crc32_batch.argtypes = [
            i8p, i64p, i32p, ctypes.c_int32, u32p, ctypes.c_int32]
        lib.hbam_deflate_batch.restype = ctypes.c_int
        lib.hbam_deflate_batch.argtypes = [
            i8p, i64p, i32p, ctypes.c_int32, i8p, i64p, i32p, i32p,
            ctypes.c_int32, ctypes.c_int32]
        lib.hbam_deflate_tokenize.restype = ctypes.c_int
        lib.hbam_deflate_tokenize.argtypes = [
            i8p, ctypes.c_int64, u32p, ctypes.c_int64, i64p, i64p]
        lib.hbam_deflate_tokenize_batch.restype = ctypes.c_int
        lib.hbam_deflate_tokenize_batch.argtypes = [
            i8p, i64p, i32p, ctypes.c_int32, u32p, ctypes.c_int64,
            i32p, i32p, u32p, ctypes.c_int32]
        if hasattr(lib, "hbam_fused_start"):
            lib.hbam_fused_start.restype = ctypes.c_void_p
            lib.hbam_fused_start.argtypes = [
                i8p, i64p, i32p, i32p, u32p, ctypes.c_int32,
                i8p, i64p, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
                ctypes.c_int32, i32p, i32p, ctypes.c_int32, ctypes.c_int32,
                i8p, i8p, i8p, ctypes.c_int32, ctypes.c_int32,
                ctypes.c_int32, i64p, ctypes.c_int64, ctypes.c_int32,
                ctypes.c_int32]
            lib.hbam_fused_next.restype = ctypes.c_int
            lib.hbam_fused_next.argtypes = [ctypes.c_void_p, i64p, i64p]
            lib.hbam_fused_finish.restype = ctypes.c_int
            lib.hbam_fused_finish.argtypes = [
                ctypes.c_void_p, i64p, i64p, i64p]
        _lib = lib
        return _lib


def _ptr(arr: np.ndarray, ctype):
    return arr.ctypes.data_as(ctypes.POINTER(ctype))


def inflate_batch(src: np.ndarray, cdata_off: np.ndarray,
                  cdata_len: np.ndarray, dst: np.ndarray,
                  dst_off: np.ndarray, isize: np.ndarray,
                  n_threads: int = 0) -> None:
    """Native batched inflate; raises on corrupt blocks."""
    lib = load()
    assert lib is not None
    if n_threads <= 0:
        n_threads = min(len(cdata_off), os.cpu_count() or 1)
    rc = lib.hbam_inflate_batch(
        _ptr(src, ctypes.c_uint8), _ptr(cdata_off, ctypes.c_int64),
        _ptr(cdata_len, ctypes.c_int32), len(cdata_off),
        _ptr(dst, ctypes.c_uint8), _ptr(dst_off, ctypes.c_int64),
        _ptr(isize, ctypes.c_int32), n_threads)
    if rc:
        raise ValueError(f"native inflate failed at block {rc - 1000}")


def walk_bam_records(buf: np.ndarray, start: int, cap: int
                     ) -> tuple[np.ndarray, int]:
    """Native record walk; returns (offsets, tail_offset)."""
    lib = load()
    assert lib is not None
    out = np.empty(cap, dtype=np.int64)
    tail = np.zeros(1, dtype=np.int64)
    n = lib.hbam_walk_bam_records(
        _ptr(buf, ctypes.c_uint8), buf.size, start,
        _ptr(out, ctypes.c_int64), cap, _ptr(tail, ctypes.c_int64))
    if n < 0:
        raise ValueError("malformed BAM record chain")
    if n > cap:
        raise ValueError(f"record count {n} exceeds capacity {cap}")
    return out[:n], int(tail[0])


def walk_bam_packed(buf: np.ndarray, start: int, cap: int,
                    sel: "list[tuple[int, int]]", row_stride: int,
                    stop: Optional[int] = None,
                    ) -> tuple[np.ndarray, np.ndarray, int]:
    """Native single-pass walk + columnar row pack.

    ``sel`` is a list of (src_offset, length) ranges within each record's
    fixed prefix, packed back-to-back into ``row_stride``-byte rows.  The
    walk stops at the first record starting at or past ``stop`` (records
    there belong to the next span).  ``cap`` must cover the worst case —
    (stop - start) / 36 + 1 records.
    Returns (rows[n, row_stride], offsets[n], tail_offset).
    """
    lib = load()
    assert lib is not None
    if stop is None:
        stop = buf.size
    sel_off = np.asarray([o for o, _ in sel], dtype=np.int32)
    sel_len = np.asarray([l for _, l in sel], dtype=np.int32)
    rows = np.empty((cap, row_stride), dtype=np.uint8)
    offs = np.empty(cap, dtype=np.int64)
    tail = np.zeros(1, dtype=np.int64)
    n = lib.hbam_walk_bam_packed(
        _ptr(buf, ctypes.c_uint8), buf.size, start, stop,
        _ptr(sel_off, ctypes.c_int32), _ptr(sel_len, ctypes.c_int32),
        len(sel), row_stride, _ptr(rows, ctypes.c_uint8),
        _ptr(offs, ctypes.c_int64), cap, _ptr(tail, ctypes.c_int64))
    if n < 0:
        raise ValueError("malformed BAM record chain")
    if n > cap:
        raise ValueError(f"record count {n} exceeds capacity {cap}")
    return rows[:n], offs[:n], int(tail[0])


def walk_bam_payload(buf: np.ndarray, start: int, cap: int, max_len: int,
                     seq_stride: int, qual_stride: int,
                     stop: Optional[int] = None,
                     ) -> tuple[np.ndarray, np.ndarray, np.ndarray,
                                np.ndarray, int]:
    """Native single-pass walk + prefix/seq/qual tile pack.

    Returns (prefix[n, 36], seq[n, seq_stride] 4-bit packed,
    qual[n, qual_stride], offsets[n], tail_offset).  Rows are zero-padded
    (buffers are allocated zeroed here; the C side only writes payload).
    """
    lib = load()
    assert lib is not None
    if stop is None:
        stop = buf.size
    prefix = np.zeros((cap, 36), dtype=np.uint8)
    seq = np.zeros((cap, seq_stride), dtype=np.uint8)
    qual = np.zeros((cap, qual_stride), dtype=np.uint8)
    offs = np.empty(cap, dtype=np.int64)
    tail = np.zeros(1, dtype=np.int64)
    n = lib.hbam_walk_bam_payload(
        _ptr(buf, ctypes.c_uint8), buf.size, start, stop,
        max_len, seq_stride, qual_stride,
        _ptr(prefix, ctypes.c_uint8), _ptr(seq, ctypes.c_uint8),
        _ptr(qual, ctypes.c_uint8), _ptr(offs, ctypes.c_int64), cap,
        _ptr(tail, ctypes.c_int64))
    if n < 0:
        raise ValueError("malformed BAM record chain")
    if n > cap:
        raise ValueError(f"record count {n} exceeds capacity {cap}")
    return prefix[:n], seq[:n], qual[:n], offs[:n], int(tail[0])


def deflate_raw(payload: bytes, level: int = 6) -> Optional[bytes]:
    """Compress one raw-DEFLATE stream natively (libdeflate when built in).
    Returns None when the result would not beat the stored-block limit —
    callers fall back to an uncompressed block."""
    lib = load()
    assert lib is not None
    src = np.frombuffer(payload, dtype=np.uint8)
    cap = max(len(payload) + 64, 256)
    dst = np.empty(cap, dtype=np.uint8)
    out_len = np.zeros(1, dtype=np.int32)
    rc = lib.hbam_deflate_batch(
        _ptr(src, ctypes.c_uint8),
        _ptr(np.zeros(1, np.int64), ctypes.c_int64),
        _ptr(np.asarray([len(payload)], np.int32), ctypes.c_int32), 1,
        _ptr(dst, ctypes.c_uint8),
        _ptr(np.zeros(1, np.int64), ctypes.c_int64),
        _ptr(np.asarray([cap], np.int32), ctypes.c_int32),
        _ptr(out_len, ctypes.c_int32), level, 1)
    if rc or out_len[0] <= 0:
        return None
    return dst[:int(out_len[0])].tobytes()


def rans_decode(order: int, buf: np.ndarray, ptr: int, freqs: np.ndarray,
                cum: np.ndarray, slot2sym: np.ndarray, out_size: int
                ) -> np.ndarray:
    """Native rANS 4x8 decode loop (tables parsed by the caller).
    Raises on corrupt/truncated streams."""
    lib = load()
    assert lib is not None
    out = np.empty(out_size, dtype=np.uint8)
    fn = lib.hbam_rans1_decode if order else lib.hbam_rans0_decode
    rc = fn(_ptr(buf, ctypes.c_uint8), buf.size, ptr,
            _ptr(freqs, ctypes.c_uint32), _ptr(cum, ctypes.c_uint32),
            _ptr(slot2sym, ctypes.c_uint8), _ptr(out, ctypes.c_uint8),
            out_size)
    if rc != 0:
        from hadoop_bam_tpu.formats.cram_codecs import RansError
        raise RansError(
            "corrupt rANS stream (ran out of bytes)" if rc == -1 else
            "corrupt rANS stream (final-state integrity check failed)")
    return out


def deflate_tokenize_batch(src: np.ndarray, cdata_off: np.ndarray,
                           cdata_len: np.ndarray, tok_stride: int,
                           n_threads: int = 0, with_crc: bool = False
                           ) -> tuple:
    """Huffman-decode many raw DEFLATE streams into LZ77 token arrays
    (copies unresolved) — the host half of the two-stage device inflate
    (ops/inflate_device.py).  Returns (tokens [B, tok_stride] u32,
    n_tokens [B] i32, out_lens [B] i32); with ``with_crc`` a fourth
    ``crcs [B] u32`` array rides along — the CRC32 of each block's
    inflated bytes, folded in at tokenize time (thread-local resolve
    scratch), so check_crc on the device plane needs no separate host
    inflate sweep."""
    lib = load()
    assert lib is not None
    n = len(cdata_off)
    if n_threads <= 0:
        n_threads = min(n, os.cpu_count() or 1)
    tokens = np.empty((n, tok_stride), dtype=np.uint32)
    n_tokens = np.zeros(n, dtype=np.int32)
    out_lens = np.zeros(n, dtype=np.int32)
    crcs = np.zeros(n, dtype=np.uint32) if with_crc else None
    rc = lib.hbam_deflate_tokenize_batch(
        _ptr(src, ctypes.c_uint8), _ptr(cdata_off, ctypes.c_int64),
        _ptr(cdata_len, ctypes.c_int32), n,
        _ptr(tokens, ctypes.c_uint32), tok_stride,
        _ptr(n_tokens, ctypes.c_int32), _ptr(out_lens, ctypes.c_int32),
        None if crcs is None else _ptr(crcs, ctypes.c_uint32),
        n_threads)
    if rc:
        kinds = {1: "truncated stream", 2: "malformed stream",
                 3: "token capacity exceeded (caller's tok_stride too "
                    "small)", 4: "back-reference before stream start"}
        kind = (rc - 1000) // 1000000
        block = (rc - 1000) % 1000000
        raise ValueError(
            f"deflate tokenize failed at block {block}: "
            f"{kinds.get(kind, f'error {kind}')}")
    if with_crc:
        return tokens, n_tokens, out_lens, crcs
    return tokens, n_tokens, out_lens


def itf8_decode_batch(buf: np.ndarray, count: int
                      ) -> "tuple[np.ndarray, int]":
    """Decode ``count`` ITF8 varints from ``buf`` in one native pass.

    Returns (values int32[count], bytes_consumed).  Raises ValueError on
    a truncated stream.  Callers must handle load() failure themselves
    (available() gate) — CRAM's predecode falls back to the per-record
    Python path."""
    lib = load()
    assert lib is not None
    out = np.empty(count, dtype=np.int32)
    buf = np.ascontiguousarray(buf)
    consumed = lib.hbam_itf8_decode_batch(
        _ptr(buf, ctypes.c_uint8), buf.size, count,
        _ptr(out, ctypes.c_int32))
    if consumed < 0:
        raise ValueError("ITF8 stream truncated")
    return out, int(consumed)


def fused_available() -> bool:
    """True when the loaded library exposes the fused span-decode entry
    points (a stale pre-fused .so rebuilds on the next source touch; until
    then callers fall back to the two-pass path)."""
    lib = load()
    return lib is not None and hasattr(lib, "hbam_fused_start")


# fused pack modes (must mirror HbamFusedJob::mode in hbam_native.cpp)
FUSED_OFFSETS, FUSED_ROWS, FUSED_PAYLOAD = 0, 1, 2


class FusedJob:
    """Handle over one running ``hbam_fused_*`` span decode.

    Thin lifecycle wrapper: pins every borrowed array for the job's
    lifetime, exposes the blocking chunk poll, and guarantees the native
    workers are joined exactly once (``finish``/``close``/GC).  Error
    mapping to the repo taxonomy lives in ``ops/inflate.py`` — this layer
    only reports raw (rc, err_index) pairs.  Single consumer; not
    thread-safe."""

    def __init__(self, src: np.ndarray, cdata_off: np.ndarray,
                 cdata_len: np.ndarray, isize: np.ndarray,
                 expect_crc: Optional[np.ndarray], dst: np.ndarray,
                 ubase: np.ndarray, start: int, stop: int, mode: int,
                 sel_off: Optional[np.ndarray], sel_len: Optional[np.ndarray],
                 row_stride: int, out_rows: Optional[np.ndarray],
                 out_seq: Optional[np.ndarray],
                 out_qual: Optional[np.ndarray], max_len: int,
                 seq_stride: int, qual_stride: int, out_off: np.ndarray,
                 chunk_blocks: int, n_threads: int = 0):
        lib = load()
        assert lib is not None and hasattr(lib, "hbam_fused_start")
        self._lib = lib
        n_blocks = len(cdata_off)
        if n_threads <= 0:
            n_threads = min(
                (n_blocks + chunk_blocks - 1) // max(1, chunk_blocks),
                os.cpu_count() or 1)
        # pin every borrowed buffer until finish()
        self._keep = (src, cdata_off, cdata_len, isize, expect_crc, dst,
                      ubase, sel_off, sel_len, out_rows, out_seq, out_qual,
                      out_off)
        self._h = lib.hbam_fused_start(
            _ptr(src, ctypes.c_uint8), _ptr(cdata_off, ctypes.c_int64),
            _ptr(cdata_len, ctypes.c_int32), _ptr(isize, ctypes.c_int32),
            None if expect_crc is None else _ptr(expect_crc,
                                                ctypes.c_uint32),
            n_blocks, _ptr(dst, ctypes.c_uint8), _ptr(ubase, ctypes.c_int64),
            int(dst.size), int(start), int(stop), int(mode),
            None if sel_off is None else _ptr(sel_off, ctypes.c_int32),
            None if sel_len is None else _ptr(sel_len, ctypes.c_int32),
            0 if sel_off is None else len(sel_off), int(row_stride),
            None if out_rows is None else _ptr(out_rows, ctypes.c_uint8),
            None if out_seq is None else _ptr(out_seq, ctypes.c_uint8),
            None if out_qual is None else _ptr(out_qual, ctypes.c_uint8),
            int(max_len), int(seq_stride), int(qual_stride),
            _ptr(out_off, ctypes.c_int64), int(out_off.size),
            int(chunk_blocks), int(n_threads))
        if not self._h:
            raise ValueError("fused decode rejected its arguments")
        self.rc = 0
        self.tail = int(start)
        self.n_rows = 0
        self.err_index = -1

    def next_chunk(self) -> "Optional[tuple[int, int]]":
        """Block until the next walked row range lands; (row_lo, row_hi),
        or None when the decode is complete.  On error, joins the workers
        and returns None with ``self.rc < 0`` set."""
        if self._h is None:
            return None
        lo = np.zeros(1, dtype=np.int64)
        hi = np.zeros(1, dtype=np.int64)
        rc = self._lib.hbam_fused_next(
            self._h, _ptr(lo, ctypes.c_int64), _ptr(hi, ctypes.c_int64))
        if rc == 1:
            return int(lo[0]), int(hi[0])
        if rc < 0:
            self.finish()
        return None

    def finish(self) -> int:
        """Join + free; idempotent.  Returns the final rc (0 or -kind) and
        populates ``tail``/``n_rows``/``err_index``."""
        if self._h is None:
            return self.rc
        tail = np.zeros(1, dtype=np.int64)
        n_rows = np.zeros(1, dtype=np.int64)
        err_index = np.zeros(1, dtype=np.int64)
        rc = self._lib.hbam_fused_finish(
            self._h, _ptr(tail, ctypes.c_int64),
            _ptr(n_rows, ctypes.c_int64), _ptr(err_index, ctypes.c_int64))
        self._h = None
        self.rc = int(rc)
        self.tail = int(tail[0])
        self.n_rows = int(n_rows[0])
        self.err_index = int(err_index[0])
        return self.rc

    close = finish

    def __del__(self):  # abandoned mid-stream: never leak native threads
        try:
            self.finish()
        except Exception:  # noqa: BLE001 — interpreter teardown
            pass


def available() -> bool:
    return load() is not None
