"""Resilient byte sources, fault injection, and the quarantine manifest.

Three building blocks of the fault-classified resilience layer:

- ``RetryingByteSource``: wraps any ``ByteSource`` with jittered exponential
  backoff on transient read failures plus a per-read deadline.  Clock, sleep
  and RNG are injectable (``RetryPolicy``), so tests assert exact backoff
  schedules without real sleeps.
- ``FaultInjectingByteSource``: the chaos twin — a deterministic fault
  schedule (transient errors, slow reads, truncations, bit flips) applied to
  an intact source, usable from tests and ``bench.py`` via the registry hook
  (``install_chaos``) that ``as_byte_source`` consults for path sources.
- ``QuarantineManifest``: the structured skip record ``decode_with_retry``
  fills under ``skip_bad_spans`` (file, virtual-offset range, error class,
  attempts) — replacing the old stderr print — and the circuit-breaker state
  (``max_bad_span_fraction``) that aborts a run instead of letting it
  silently degrade past a threshold.  JSON round-trip + merge support the
  multi-host reduce in parallel/distributed.py.
"""
from __future__ import annotations

import collections
import dataclasses
import json
import os
import random
import threading
import time
from typing import Callable, Dict, Iterator, List, Optional, Sequence, Tuple

from hadoop_bam_tpu.utils import seekable
from hadoop_bam_tpu.utils.errors import (
    CORRUPT, CircuitBreakerError, TRANSIENT, TransientIOError, classify_error,
)
from hadoop_bam_tpu.utils.metrics import METRICS
from hadoop_bam_tpu.utils.seekable import ByteSource


@dataclasses.dataclass
class RetryPolicy:
    """Backoff schedule + injectable time primitives.

    ``delay(attempt)`` is capped exponential with multiplicative jitter in
    ``[1 - jitter, 1]`` — jitter shrinks the delay (never extends it) so a
    deadline bound computed from the nominal schedule stays valid.  All
    time functions are injectable: tests pass a fake clock/sleep and assert
    the exact schedule; collectives pass ``jitter=0`` so every host runs an
    identical schedule and the group stays in lockstep."""

    retries: int = 3
    backoff_base_s: float = 0.05
    backoff_max_s: float = 2.0
    jitter: float = 0.5
    deadline_s: Optional[float] = None
    sleep: Callable[[float], None] = time.sleep
    clock: Callable[[], float] = time.monotonic
    rng: Optional[random.Random] = None

    def delay(self, attempt: int) -> float:
        d = min(self.backoff_max_s, self.backoff_base_s * (2.0 ** attempt))
        if self.jitter > 0.0:
            r = (self.rng or random).random()
            d *= 1.0 - self.jitter * r
        return d


def span_retry_policy(config) -> "RetryPolicy":
    """The one way to derive a span-grain RetryPolicy from config —
    decode spans, deflate workers, and shard-concat reads must agree on
    the knob names (and their fallbacks) or they silently diverge."""
    return RetryPolicy(
        retries=max(0, int(getattr(config, "span_retries", 2))),
        backoff_base_s=float(getattr(config, "retry_backoff_base_s", 0.05)),
        backoff_max_s=float(getattr(config, "retry_backoff_max_s", 2.0)))


def call_with_retry(fn: Callable[[], object], policy: RetryPolicy,
                    what: str = "operation",
                    counter: str = "resilient.retries"):
    """Run ``fn`` retrying ONLY transient-classified failures per ``policy``.

    Corrupt/plan failures raise immediately.  On exhaustion (retry budget or
    deadline) the last transient error is wrapped in ``TransientIOError``
    so callers upstream see one classified type."""
    deadline = (policy.clock() + policy.deadline_s
                if policy.deadline_s is not None else None)
    last: Optional[BaseException] = None
    attempts = 0
    for attempt in range(policy.retries + 1):
        try:
            attempts = attempt + 1
            return fn()
        except Exception as e:  # noqa: BLE001 — policy boundary
            if classify_error(e) != TRANSIENT:
                raise
            last = e
            if attempt >= policy.retries:
                break
            d = policy.delay(attempt)
            if deadline is not None and policy.clock() + d > deadline:
                break
            METRICS.count(counter)
            policy.sleep(d)
    raise TransientIOError(
        f"{what} failed after {attempts} attempt(s) "
        f"(budget {policy.retries + 1}"
        + (f", deadline {policy.deadline_s:g}s" if deadline is not None
           else "") + f"): {last}") from last


class RetryingByteSource(ByteSource):
    """Transient-retrying wrapper: ``pread`` failures classified TRANSIENT
    are re-attempted with jittered exponential backoff and an optional
    per-read deadline; corrupt/plan failures pass straight through."""

    def __init__(self, inner, policy: Optional[RetryPolicy] = None):
        self.inner = seekable.as_byte_source(inner)
        self.policy = policy or RetryPolicy()
        self.size = self.inner.size
        self.path = getattr(self.inner, "path", None)

    def pread(self, offset: int, size: int) -> bytes:
        return call_with_retry(
            lambda: self.inner.pread(offset, size), self.policy,
            what=f"pread({offset}, {size}) on {self.path or self.inner!r}",
            counter="io.read_retries")

    def close(self) -> None:
        self.inner.close()


# ---------------------------------------------------------------------------
# Chaos injection
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class FaultSpec:
    """One scheduled fault.  Matching: ``at_read`` fires on a source's
    reads from index N on (0-based — so ``at_read=0, count=2`` fails the
    first two attempts and lets the third through: the
    transient-then-success shape), ``offset_range`` on any read overlapping
    ``[lo, hi)``; with neither set the spec matches every read.  ``count``
    is the firing budget — specs are shared mutable state when one schedule
    wraps many sources (each span decode opens its own source), so the
    budget is global across them."""

    kind: str                                   # transient|slow|truncate|bitflip
    at_read: Optional[int] = None
    offset_range: Optional[Tuple[int, int]] = None
    count: int = 1
    delay_s: float = 0.01                       # slow
    truncate_to: int = 0                        # truncate: bytes kept
    xor_mask: int = 0x01                        # bitflip


_FAULT_LOCK = threading.Lock()


class SeededFaultSchedule:
    """A deterministic chaos schedule derived from ONE integer seed
    (``config.chaos_seed``) — the reproducibility contract for chaos /
    soak runs: the same seed produces the same fault timeline on every
    run, every host, regardless of thread interleaving or how many
    source instances a run opens.

    Decisions are therefore keyed on the READ'S OFFSET, not a read
    counter: ``roll(kind, offset)`` hashes ``(seed, kind, offset)`` into
    [0, 1) and fires when it lands under that kind's rate.  ``once``
    (default) gives each (kind, offset) a single firing budget shared
    across all sources on the schedule — so a transient fault at offset
    X heals when the retry re-reads X, exactly the transient-then-
    success shape, while a re-run with the same seed faults at the same
    offsets again."""

    def __init__(self, seed: int, transient_rate: float = 0.0,
                 slow_rate: float = 0.0, bitflip_rate: float = 0.0,
                 delay_s: float = 0.005, xor_mask: int = 0x01,
                 once: bool = True):
        self.seed = int(seed)
        self.rates = {"transient": float(transient_rate),
                      "slow": float(slow_rate),
                      "bitflip": float(bitflip_rate)}
        self.delay_s = float(delay_s)
        self.xor_mask = int(xor_mask)
        self.once = bool(once)
        self._fired: set = set()
        self._lock = threading.Lock()

    def roll(self, kind: str, offset: int) -> bool:
        import zlib
        h = zlib.crc32(f"{self.seed}:{kind}:{int(offset)}".encode())
        return (h / 2.0 ** 32) < self.rates.get(kind, 0.0)

    def faults_for(self, offset: int, size: int) -> List[FaultSpec]:
        """Fault specs firing on a ``pread(offset, size)`` (consumes the
        once-budget for each)."""
        hits: List[FaultSpec] = []
        for kind, rate in self.rates.items():
            if rate <= 0.0 or not self.roll(kind, offset):
                continue
            if self.once:
                with self._lock:
                    if (kind, offset) in self._fired:
                        continue
                    self._fired.add((kind, offset))
            hits.append(FaultSpec(kind, count=1, delay_s=self.delay_s,
                                  xor_mask=self.xor_mask))
        return hits


class FaultInjectingByteSource(ByteSource):
    """Deterministic chaos wrapper over an intact source.

    Faults fire by per-source read index or by offset overlap (see
    ``FaultSpec``), or by a seed-derived offset-keyed schedule
    (``SeededFaultSchedule``); injected transients raise
    ``TransientIOError`` so the retry layer treats them exactly like
    real ones.  ``injected`` counts firings by kind for assertions."""

    def __init__(self, inner, faults: Sequence[FaultSpec] = (),
                 sleep: Callable[[float], None] = time.sleep,
                 schedule: Optional[SeededFaultSchedule] = None):
        self.inner = seekable.as_byte_source(inner)
        self.faults = list(faults)
        self.schedule = schedule
        self.size = self.inner.size
        self.path = getattr(self.inner, "path", None)
        self.reads = 0
        self.injected: "collections.Counter[str]" = collections.Counter()
        self._sleep = sleep

    def pread(self, offset: int, size: int) -> bytes:
        with _FAULT_LOCK:
            idx = self.reads
            self.reads += 1
            hits: List[FaultSpec] = []
            for f in self.faults:
                if f.count <= 0:
                    continue
                if f.at_read is None and f.offset_range is None:
                    match = True
                else:
                    match = f.at_read is not None and idx >= f.at_read
                    if not match and f.offset_range is not None:
                        lo, hi = f.offset_range
                        match = offset < hi and offset + size > lo
                if match:
                    f.count -= 1
                    self.injected[f.kind] += 1
                    METRICS.count("chaos.injected_faults")
                    hits.append(f)
            if self.schedule is not None:
                for f in self.schedule.faults_for(offset, size):
                    self.injected[f.kind] += 1
                    METRICS.count("chaos.injected_faults")
                    hits.append(f)
        for f in hits:
            if f.kind == "slow":
                self._sleep(f.delay_s)
            elif f.kind == "transient":
                raise TransientIOError(
                    f"injected transient fault at pread({offset}, {size})")
        data = self.inner.pread(offset, size)
        for f in hits:
            if f.kind == "truncate":
                data = data[:f.truncate_to]
            elif f.kind == "bitflip" and data:
                lo, hi = f.offset_range or (offset, offset + len(data))
                buf = bytearray(data)
                s = max(lo - offset, 0)
                e = min(hi - offset, len(buf))
                for i in range(s, e):
                    buf[i] ^= f.xor_mask
                data = bytes(buf)
        return data

    def close(self) -> None:
        self.inner.close()


# Registry hook: install_chaos(path, ...) makes every ByteSource that
# as_byte_source() opens for that path go through a FaultInjectingByteSource
# — zero plumbing through the drivers, usable from tests and bench.py.
_CHAOS: Dict[str, Tuple[List[FaultSpec], Callable[[float], None],
                        Optional[SeededFaultSchedule]]] = {}


def install_chaos(path, faults: Sequence[FaultSpec] = (),
                  sleep: Callable[[float], None] = time.sleep,
                  schedule: Optional[SeededFaultSchedule] = None) -> None:
    _CHAOS[os.path.abspath(os.fspath(path))] = (list(faults), sleep,
                                                schedule)
    seekable._SOURCE_WRAPPER = _wrap_registered


def install_chaos_seeded(path, seed: int, *,
                         transient_rate: float = 0.0,
                         slow_rate: float = 0.0,
                         bitflip_rate: float = 0.0,
                         delay_s: float = 0.005,
                         sleep: Callable[[float], None] = time.sleep
                         ) -> SeededFaultSchedule:
    """The one-knob chaos entry: a ``SeededFaultSchedule`` derived from
    ``seed`` (``config.chaos_seed``) installed for ``path``.  Returns
    the schedule so callers can assert on / share it."""
    schedule = SeededFaultSchedule(
        seed, transient_rate=transient_rate, slow_rate=slow_rate,
        bitflip_rate=bitflip_rate, delay_s=delay_s)
    install_chaos(path, (), sleep=sleep, schedule=schedule)
    return schedule


def clear_chaos(path=None) -> None:
    if path is None:
        _CHAOS.clear()
    else:
        _CHAOS.pop(os.path.abspath(os.fspath(path)), None)
    if not _CHAOS:
        seekable._SOURCE_WRAPPER = None


class chaos_on:
    """``with chaos_on(path, faults):`` — scoped install_chaos."""

    def __init__(self, path, faults: Sequence[FaultSpec] = (),
                 sleep: Callable[[float], None] = time.sleep,
                 schedule: Optional[SeededFaultSchedule] = None):
        self._path = path
        install_chaos(path, faults, sleep, schedule=schedule)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        clear_chaos(self._path)


def _wrap_registered(src: ByteSource) -> ByteSource:
    hit = _CHAOS.get(os.path.abspath(getattr(src, "path", "") or ""))
    if hit is None:
        return src
    faults, sleep, schedule = hit
    return FaultInjectingByteSource(src, faults, sleep, schedule=schedule)


# ---------------------------------------------------------------------------
# Quarantine manifest + circuit breaker
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class QuarantineEntry:
    """One skipped span: which bytes were excluded from the run and why.
    ``span_start``/``span_end`` are packed virtual offsets for BGZF spans
    and plain byte offsets for text-format byte spans."""

    path: str
    span_start: int
    span_end: int
    error_class: str        # errors.TRANSIENT / CORRUPT
    error: str
    attempts: int
    host: int = 0

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: dict) -> "QuarantineEntry":
        return cls(str(d["path"]), int(d["span_start"]), int(d["span_end"]),
                   str(d["error_class"]), str(d["error"]),
                   int(d["attempts"]), int(d.get("host", 0)))


def _span_bounds(span) -> Tuple[str, int, int]:
    start = getattr(span, "start_voffset", None)
    if start is not None:
        return span.path, int(start), int(span.end_voffset)
    return span.path, int(span.start), int(span.end)


class QuarantineManifest:
    """Thread-safe record of every span a run skipped, plus the circuit
    breaker: once ``len(entries) / total_spans`` exceeds the config's
    ``max_bad_span_fraction``, ``check_circuit`` raises
    ``CircuitBreakerError`` and the run aborts instead of quietly returning
    an answer computed from a shrinking subset of the file."""

    def __init__(self, total_spans: Optional[int] = None):
        self._lock = threading.Lock()
        self.entries: List[QuarantineEntry] = []
        self.total_spans = total_spans

    def add(self, span, error: BaseException, error_class: str,
            attempts: int, host: int = 0) -> QuarantineEntry:
        path, s, e = _span_bounds(span)
        entry = QuarantineEntry(path, s, e, error_class,
                                f"{type(error).__name__}: {error}",
                                attempts, host)
        with self._lock:
            self.entries.append(entry)
        # no counter here: decode_with_retry's skip branch owns the single
        # pipeline.bad_spans tick for this event
        return entry

    def extend(self, entries: Sequence[QuarantineEntry]) -> None:
        with self._lock:
            self.entries.extend(entries)

    def __len__(self) -> int:
        with self._lock:
            return len(self.entries)

    def __bool__(self) -> bool:
        return len(self) > 0

    def __iter__(self) -> Iterator[QuarantineEntry]:
        with self._lock:
            return iter(list(self.entries))

    def bad_fraction(self) -> float:
        with self._lock:
            n = len(self.entries)
        if not self.total_spans:
            return 0.0
        return n / float(self.total_spans)

    def check_circuit(self, config) -> None:
        limit = float(getattr(config, "max_bad_span_fraction", 1.0))
        frac = self.bad_fraction()
        if frac > limit:
            # no longer one-way: the trip force-opens the per-file
            # quarantine circuit in the resilience registry, so future
            # runs on the same file fast-fail at the driver's
            # check_quarantine_gate while OPEN, get a half-open probe
            # after the cooldown, and heal on a clean finish
            retry_after = None
            try:
                from hadoop_bam_tpu import resilience
                for p in sorted({e.path for e in self}):
                    br = resilience.quarantine_breaker(p, config=config)
                    br.force_open()
                    retry_after = br.retry_after_s()
            except Exception:  # noqa: BLE001 — the abort must still fire
                pass
            raise CircuitBreakerError(
                f"quarantined {len(self)}/{self.total_spans} spans "
                f"({frac:.1%}) exceeds max_bad_span_fraction={limit:g} — "
                "aborting instead of degrading further",
                retry_after_s=retry_after)

    def to_dicts(self) -> List[dict]:
        with self._lock:
            return [e.to_dict() for e in self.entries]

    def to_json(self) -> str:
        return json.dumps({"total_spans": self.total_spans,
                           "entries": self.to_dicts()})

    @classmethod
    def from_dicts(cls, dicts: Sequence[dict],
                   total_spans: Optional[int] = None) -> "QuarantineManifest":
        m = cls(total_spans=total_spans)
        m.extend([QuarantineEntry.from_dict(d) for d in dicts])
        return m

    @classmethod
    def from_json(cls, payload: str) -> "QuarantineManifest":
        d = json.loads(payload)
        if isinstance(d, list):          # bare entry list (older payloads)
            return cls.from_dicts(d)
        return cls.from_dicts(d["entries"],
                              total_spans=d.get("total_spans"))

    def merged_with(self, others: Sequence["QuarantineManifest"]
                    ) -> "QuarantineManifest":
        """Union of this and other hosts' manifests, deduplicated by
        (path, range) and canonically ordered — every host computing this
        over the same inputs gets the identical entry list.  total_spans
        SUMS across the inputs (hosts hold disjoint plan slices, so the
        sum is the job-wide plan size); any unknown total makes the merged
        total unknown rather than a wrong fraction."""
        seen = set()
        entries: List[QuarantineEntry] = []
        totals: List[Optional[int]] = []
        for m in [self, *others]:
            totals.append(m.total_spans)
            for e in m:
                key = (e.path, e.span_start, e.span_end)
                if key not in seen:
                    seen.add(key)
                    entries.append(e)
        entries.sort(key=lambda e: (e.path, e.span_start, e.span_end))
        total = None if any(t is None for t in totals) else sum(totals)
        out = QuarantineManifest(total_spans=total)
        out.extend(entries)
        return out
