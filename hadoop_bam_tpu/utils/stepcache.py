"""A tiny locked, bounded build-once cache for jitted step functions.

Both the query engine's overlap predicate and the serve tier's tile
filter key one compiled step per (mesh, axis) — a process cycling
through many meshes must not grow those module caches forever (the
SV801 discipline), and the logic (lock, double-check, FIFO evict) is
identical.  One implementation, shared.
"""
from __future__ import annotations

import threading
from typing import Callable, Dict, Hashable


class BoundedStepCache:
    """``get_or_build(key, build)``: returns the cached value or builds,
    inserts (evicting oldest-inserted past ``cap``), and returns it.
    ``build`` runs OUTSIDE the lock — jit construction is slow and must
    not serialize unrelated lookups; two racing builders of the same key
    both build, first insert wins for future callers."""

    def __init__(self, cap: int = 8):
        self.cap = max(1, int(cap))
        self._lock = threading.Lock()
        self._entries: Dict[Hashable, object] = {}

    def get_or_build(self, key: Hashable, build: Callable[[], object]):
        with self._lock:
            hit = self._entries.get(key)
            if hit is not None:
                return hit
        value = build()
        with self._lock:
            existing = self._entries.get(key)
            if existing is not None:
                return existing
            while len(self._entries) >= self.cap:
                self._entries.pop(next(iter(self._entries)))
            self._entries[key] = value
        return value

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)
