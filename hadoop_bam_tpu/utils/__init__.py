"""Utility layer: seekable byte sources, header readers, mergers, metrics."""
