"""External (spill-merge) BAM sort — the MR-shuffle analog at any scale.

The reference never sorted in-library: its CLI `sort` plugin keyed records
into the MapReduce shuffle and let Hadoop's external merge sort do the work.
This module is that machinery in-process: decode spans, accumulate bounded
runs, sort each run, spill as headerless BGZF shards, then k-way merge by
key into the final file (header written once, BGZF EOF terminator last —
the same shard-concatenation contract as utils/mergers.py).

Keys follow the SAM spec orderings: coordinate = (refid with unmapped
last, pos); queryname = read-name bytes.
"""
from __future__ import annotations

import heapq
import os
import re
import tempfile
from typing import Callable, Iterator, List, Optional, Tuple

import numpy as np

from hadoop_bam_tpu.config import DEFAULT_CONFIG, HBamConfig
from hadoop_bam_tpu.formats import bgzf
from hadoop_bam_tpu.formats.bam import SAMHeader

_UNMAPPED = 1 << 40


def coordinate_key(rec: bytes) -> Tuple[int, int]:
    """(refid, pos) from raw record bytes; unmapped (refid -1) sorts last
    [SPEC coordinate order]."""
    refid = int.from_bytes(rec[4:8], "little", signed=True)
    pos = int.from_bytes(rec[8:12], "little", signed=True)
    return (_UNMAPPED if refid < 0 else refid, pos)


def name_key(rec: bytes) -> bytes:
    """Read name bytes (NUL excluded) from raw record bytes."""
    l_read_name = rec[16]
    return rec[36:36 + l_read_name - 1]


def _iter_run(path: str) -> Iterator[bytes]:
    """Stream raw record bytes from a spilled run file."""
    from hadoop_bam_tpu.formats.bamio import read_bam_header
    from hadoop_bam_tpu.utils.seekable import as_byte_source

    src = as_byte_source(path)
    try:
        _, first = read_bam_header(src)
        r = bgzf.BGZFReader(src)
        r.seek_voffset(first)
        while True:
            head = r.read(4)
            if len(head) < 4:
                return
            bs = int.from_bytes(head, "little", signed=True)
            body = r.read(bs)
            if len(body) < bs:
                raise ValueError(f"truncated run file {path}")
            yield head + body
    finally:
        src.close()


def _sorted_header(header: SAMHeader, by_name: bool) -> SAMHeader:
    so = "queryname" if by_name else "coordinate"
    text = header.text
    if "@HD" in text:
        text = re.sub(r"(@HD[^\n]*?)\tSO:\S*", r"\1", text, count=1)
        text = re.sub(r"(@HD[^\n]*)", rf"\1\tSO:{so}", text, count=1)
    else:
        text = f"@HD\tVN:1.6\tSO:{so}\n" + text
    return type(header)(text=text, ref_names=header.ref_names,
                        ref_lengths=header.ref_lengths)


def sort_vcf(input_path: str, output_path: str, *,
             config: HBamConfig = DEFAULT_CONFIG,
             run_records: int = 1_000_000,
             tmp_dir: Optional[str] = None) -> int:
    """External (contig, pos) sort for VCF/BCF — runs spill as BCF shards
    (compact binary), k-way merged into the output container chosen by the
    output extension.  Returns record count."""
    from hadoop_bam_tpu.api.vcf_dataset import open_vcf
    from hadoop_bam_tpu.api.writers import open_vcf_writer

    ds = open_vcf(input_path, config)
    header = ds.header
    contig_order = {c: i for i, c in enumerate(header.contigs)}

    def key(rec) -> Tuple[int, int]:
        return (contig_order.get(rec.chrom, 1 << 30), rec.pos)

    own_tmp = tmp_dir is None
    tmp_dir = tmp_dir or tempfile.mkdtemp(prefix="hbam_vcfsort_")
    runs: List[str] = []
    pending: List = []
    total = 0

    def spill() -> None:
        if not pending:
            return
        pending.sort(key=lambda kv: kv[0])
        run_path = os.path.join(tmp_dir, f"run-{len(runs):05d}.bcf")
        with open_vcf_writer(run_path, header) as w:
            for _k, rec in pending:
                w.write_record(rec)
        runs.append(run_path)
        pending.clear()

    try:
        for rec in ds.records():
            pending.append((key(rec), rec))
            total += 1
            if len(pending) >= run_records:
                spill()
        with open_vcf_writer(output_path, header) as w:
            if not runs:
                pending.sort(key=lambda kv: kv[0])
                for _k, rec in pending:
                    w.write_record(rec)
            else:
                spill()
                merged = heapq.merge(
                    *(((key(rec), rec)
                       for rec in open_vcf(p, config).records())
                      for p in runs),
                    key=lambda kv: kv[0])
                for _k, rec in merged:
                    w.write_record(rec)
    finally:
        if own_tmp:
            for p in runs:
                try:
                    os.unlink(p)
                except OSError:
                    pass
            try:
                os.rmdir(tmp_dir)
            except OSError:
                pass
    return total


def sort_bam(input_path: str, output_path: str, *, by_name: bool = False,
             config: HBamConfig = DEFAULT_CONFIG,
             run_records: int = 1_000_000,
             tmp_dir: Optional[str] = None) -> int:
    """Sort a BAM of any size with bounded memory; returns record count.

    Memory bound ≈ run_records × record size; spills go to ``tmp_dir``
    (a fresh temporary directory by default, removed afterwards).
    """
    from hadoop_bam_tpu.api.dataset import open_bam
    from hadoop_bam_tpu.formats.bamio import BamWriter

    key: Callable = name_key if by_name else coordinate_key
    ds = open_bam(input_path, config)
    header = _sorted_header(ds.header, by_name)

    own_tmp = tmp_dir is None
    tmp_dir = tmp_dir or tempfile.mkdtemp(prefix="hbam_sort_")
    runs: List[str] = []
    pending: List[Tuple] = []
    total = 0

    def spill() -> None:
        if not pending:
            return
        pending.sort(key=lambda kv: kv[0])
        run_path = os.path.join(tmp_dir, f"run-{len(runs):05d}.bam")
        # level 1: runs are transient, trade ratio for speed
        with BamWriter(run_path, ds.header, level=1) as w:
            for _k, rec in pending:
                w.write_record_bytes(rec)
        runs.append(run_path)
        pending.clear()

    try:
        for batch in ds.batches():
            for i in range(len(batch)):
                rec = batch.record_bytes(i)
                pending.append((key(rec), rec))
                total += 1
            if len(pending) >= run_records:
                spill()

        with BamWriter(output_path, header) as w:
            if not runs:  # everything fit in one run: sort + write directly
                pending.sort(key=lambda kv: kv[0])
                for _k, rec in pending:
                    w.write_record_bytes(rec)
            else:
                spill()
                merged = heapq.merge(
                    *(((key(rec), rec) for rec in _iter_run(p))
                      for p in runs),
                    key=lambda kv: kv[0])
                for _k, rec in merged:
                    w.write_record_bytes(rec)
    finally:
        if own_tmp:
            for p in runs:
                try:
                    os.unlink(p)
                except OSError:
                    pass
            try:
                os.rmdir(tmp_dir)
            except OSError:
                pass
    return total
