"""External (spill-merge) sorts — the MR-shuffle analog at any scale.

The reference never sorted in-library: its CLI `sort` plugin keyed records
into the MapReduce shuffle and let Hadoop's external merge sort do the work.
This module is that machinery in-process: decode, accumulate bounded runs,
sort each run, spill, then k-way merge by key into the final file.  One
shared scaffold (`_external_sort`) parameterized by (record stream, run
writer, run reader, output writer, key); BAM and VCF instantiate it.

Keys follow the SAM/VCF spec orderings: BAM coordinate = (refid with
unmapped last, pos); queryname = read-name bytes; VCF = (contig order with
undeclared contigs last, POS).
"""
from __future__ import annotations

import heapq
import os
import re
import tempfile
from typing import Callable, Iterable, Iterator, List, Optional, Tuple

from hadoop_bam_tpu.config import DEFAULT_CONFIG, HBamConfig
from hadoop_bam_tpu.formats import bgzf
from hadoop_bam_tpu.formats.bam import SAMHeader

_UNMAPPED = 1 << 40


def coordinate_key(rec: bytes) -> Tuple[int, int]:
    """(refid, pos) from raw record bytes; unmapped (refid -1) sorts last
    [SPEC coordinate order]."""
    refid = int.from_bytes(rec[4:8], "little", signed=True)
    pos = int.from_bytes(rec[8:12], "little", signed=True)
    return (_UNMAPPED if refid < 0 else refid, pos)


def name_key(rec: bytes) -> bytes:
    """Read name bytes (NUL excluded) from raw record bytes.

    Layout is block_size-prefixed: l_read_name lives at byte 12 of the raw
    record (4 block_size + 8 refid/pos) [SPEC alignment section].
    """
    l_read_name = rec[12]
    return rec[36:36 + l_read_name - 1]


def _external_sort(records: Iterable, key: Callable,
                   write_run: Callable, iter_run: Callable,
                   write_output: Callable, run_records: int,
                   tmp_dir: Optional[str], run_suffix: str) -> int:
    """Shared spill-merge scaffold.

    - ``write_run(path, sorted_records)`` spills one run;
    - ``iter_run(path)`` STREAMS a run back (bounded memory — the whole
      point; never materialize a run);
    - ``write_output(record_iter)`` writes the final sorted stream.
    Returns the record count.
    """
    own_tmp = tmp_dir is None
    tmp_dir = tmp_dir or tempfile.mkdtemp(prefix="hbam_sort_")
    runs: List[str] = []
    pending: List[Tuple] = []
    total = 0

    def spill() -> None:
        if not pending:
            return
        pending.sort(key=lambda kv: kv[0])
        run_path = os.path.join(tmp_dir, f"run-{len(runs):05d}{run_suffix}")
        write_run(run_path, (rec for _k, rec in pending))
        runs.append(run_path)
        pending.clear()

    try:
        for rec in records:
            pending.append((key(rec), rec))
            total += 1
            if len(pending) >= run_records:
                spill()
        if not runs:  # everything fit in one run: sort + write directly
            pending.sort(key=lambda kv: kv[0])
            write_output(rec for _k, rec in pending)
        else:
            spill()
            merged = heapq.merge(
                *(((key(rec), rec) for rec in iter_run(p)) for p in runs),
                key=lambda kv: kv[0])
            write_output(rec for _k, rec in merged)
    finally:
        if own_tmp:
            for p in runs:
                try:
                    os.unlink(p)
                except OSError:
                    pass
            try:
                os.rmdir(tmp_dir)
            except OSError:
                pass
    return total


# ---------------------------------------------------------------------------
# BAM
# ---------------------------------------------------------------------------

def _iter_bam_run(path: str) -> Iterator[bytes]:
    """Stream raw record bytes from a spilled BAM run file."""
    from hadoop_bam_tpu.formats.bamio import read_bam_header
    from hadoop_bam_tpu.utils.seekable import as_byte_source

    src = as_byte_source(path)
    try:
        _, first = read_bam_header(src)
        r = bgzf.BGZFReader(src)
        r.seek_voffset(first)
        while True:
            head = r.read(4)
            if len(head) < 4:
                return
            bs = int.from_bytes(head, "little", signed=True)
            body = r.read(bs)
            if len(body) < bs:
                raise ValueError(f"truncated run file {path}")
            yield head + body
    finally:
        src.close()


def _sorted_header(header: SAMHeader, by_name: bool) -> SAMHeader:
    so = "queryname" if by_name else "coordinate"
    text = header.text
    if "@HD" in text:
        text = re.sub(r"(@HD[^\n]*?)\tSO:\S*", r"\1", text, count=1)
        text = re.sub(r"(@HD[^\n]*)", rf"\1\tSO:{so}", text, count=1)
    else:
        text = f"@HD\tVN:1.6\tSO:{so}\n" + text
    return type(header)(text=text, ref_names=header.ref_names,
                        ref_lengths=header.ref_lengths)


def sort_bam(input_path: str, output_path: str, *, by_name: bool = False,
             config: HBamConfig = DEFAULT_CONFIG,
             run_records: int = 1_000_000,
             tmp_dir: Optional[str] = None) -> int:
    """Sort a BAM of any size with bounded memory; returns record count.

    Memory bound ≈ run_records × record size; spills go to ``tmp_dir``
    (a fresh temporary directory by default, removed afterwards).
    """
    from hadoop_bam_tpu.api.dataset import open_bam
    from hadoop_bam_tpu.formats.bamio import BamWriter

    ds = open_bam(input_path, config)
    out_header = _sorted_header(ds.header, by_name)

    def records() -> Iterator[bytes]:
        for batch in ds.batches():
            for i in range(len(batch)):
                yield batch.record_bytes(i)

    def write_run(path, recs) -> None:
        # level 1: runs are transient, trade ratio for speed
        with BamWriter(path, ds.header, level=1) as w:
            for rec in recs:
                w.write_record_bytes(rec)

    def write_output(recs) -> None:
        if not by_name:
            # coordinate output rides the parallel write path: pooled
            # deflate + co-written index sidecars (write_index_kinds /
            # --no-write-index), byte-identical to the serial BamWriter.
            # Queryname order keeps the plain writer — a genomic index
            # on name-sorted records would be meaningless.
            import numpy as np

            from hadoop_bam_tpu.write import write_bam_records

            def chunks():
                buf: List[bytes] = []
                offs: List[int] = []
                pos = 0
                for rec in recs:
                    buf.append(rec)
                    offs.append(pos)
                    pos += len(rec)
                    if pos >= (8 << 20):
                        yield b"".join(buf), np.asarray(offs, np.int64)
                        buf, offs, pos = [], [], 0
                if buf:
                    yield b"".join(buf), np.asarray(offs, np.int64)

            write_bam_records(output_path, out_header, chunks(),
                              config=config)
            return
        with BamWriter(output_path, out_header,
                       level=config.write_compress_level) as w:
            for rec in recs:
                w.write_record_bytes(rec)

    return _external_sort(records(), name_key if by_name else coordinate_key,
                          write_run, _iter_bam_run, write_output,
                          run_records, tmp_dir, ".bam")


# ---------------------------------------------------------------------------
# VCF / BCF
# ---------------------------------------------------------------------------

def _iter_vcf_run(path: str) -> Iterator:
    """Stream VcfRecords from a spilled text run, one line at a time."""
    from hadoop_bam_tpu.formats.vcf import VcfRecord

    with open(path, "r") as f:
        for line in f:
            line = line.rstrip("\n")
            if line and not line.startswith("#"):
                yield VcfRecord.from_line(line)


def sort_vcf(input_path: str, output_path: str, *,
             config: HBamConfig = DEFAULT_CONFIG,
             run_records: int = 1_000_000,
             tmp_dir: Optional[str] = None) -> int:
    """External (contig, pos) sort for VCF/BCF; returns record count.

    Runs spill as headerless TEXT VCF: no contig dictionary needed (a text
    VCF may legally use contigs with no ##contig line, which BCF runs
    would reject), and text streams back line-by-line, keeping the merge's
    memory bound at one record per run.  The output container follows the
    output extension and ``config`` (open_vcf_writer).
    """
    from hadoop_bam_tpu.api.vcf_dataset import open_vcf
    from hadoop_bam_tpu.api.writers import open_vcf_writer

    ds = open_vcf(input_path, config)
    header = ds.header
    contig_order = {c: i for i, c in enumerate(header.contigs)}

    def key(rec) -> Tuple[int, int]:
        return (contig_order.get(rec.chrom, 1 << 30), rec.pos)

    def write_run(path, recs) -> None:
        with open(path, "w") as f:
            for rec in recs:
                f.write(rec.to_line() + "\n")

    def write_output(recs) -> None:
        if output_path.lower().endswith(".bcf"):
            # BCF output routes through the parallel write path: pooled
            # deflate + a co-written .tbi, so the sorted output is
            # immediately region-queryable (byte-identical to BcfWriter)
            from hadoop_bam_tpu.write import write_bcf_records
            write_bcf_records(output_path, header, recs, config=config)
            return
        with open_vcf_writer(output_path, header, config=config) as w:
            for rec in recs:
                w.write_record(rec)

    return _external_sort(ds.records(), key, write_run, _iter_vcf_run,
                          write_output, run_records, tmp_dir, ".vcf")
