"""Seekable byte sources — positioned reads over files and buffers.

Rebuild of the reference's seekable-stream adapters
(hb/util/WrapSeekable.java: htsjdk SeekableStream over Hadoop
FSDataInputStream; hb/util/SeekableArrayStream.java: over byte[]): every layer
above works against one tiny interface, ``pread(offset, size) -> bytes`` plus
``size``, so local files, in-memory buffers, and (later) object-store
byte-range fetchers are interchangeable.  Positioned reads (not stateful
seeks) are the right primitive for the TPU pipeline: span fetches are
stateless and trivially parallel across threads/hosts.
"""
from __future__ import annotations

import io
import os
import threading
from typing import Callable, Optional, Union

# Resilience hook (utils/resilient.py): when chaos injection or a retry
# wrapper is registered for some path, resilient installs a wrapper here and
# every path-opened source flows through it.  None = zero-overhead fast path.
_SOURCE_WRAPPER: Optional[Callable[["ByteSource"], "ByteSource"]] = None


class ByteSource:
    """Interface: stateless positioned reads."""

    size: int

    def pread(self, offset: int, size: int) -> bytes:
        raise NotImplementedError

    def close(self) -> None:
        pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class FileByteSource(ByteSource):
    """Positioned reads over a local file via os.pread (thread-safe, no
    seek state — many fetcher threads can share one fd)."""

    def __init__(self, path: Union[str, os.PathLike]):
        self.path = os.fspath(path)
        self._fd = -1  # set first so __del__ is safe if os.open raises
        self._fd = os.open(self.path, os.O_RDONLY)
        self.size = os.fstat(self._fd).st_size

    def pread(self, offset: int, size: int) -> bytes:
        if offset >= self.size or size <= 0:
            return b""
        try:
            return os.pread(self._fd, size, offset)
        except OSError as e:
            # classify at the policy boundary: a failed positioned read is
            # an environment fault (EIO on network mounts, stale handles),
            # not data corruption — retryable upstream
            from hadoop_bam_tpu.utils.errors import TransientIOError
            raise TransientIOError(
                f"pread({offset}, {size}) failed on {self.path}: {e}"
            ) from e

    def close(self) -> None:
        if self._fd >= 0:
            os.close(self._fd)
            self._fd = -1

    def __del__(self):
        try:
            self.close()
        except OSError:
            pass


class BytesByteSource(ByteSource):
    """Over an in-memory buffer (hb/util/SeekableArrayStream.java analog);
    guessers re-scan fetched windows through this."""

    def __init__(self, data: bytes):
        self._data = data
        self.size = len(data)

    def pread(self, offset: int, size: int) -> bytes:
        return self._data[offset:offset + size]


def as_byte_source(obj) -> ByteSource:
    if isinstance(obj, ByteSource):
        return obj
    if isinstance(obj, (bytes, bytearray, memoryview)):
        return BytesByteSource(bytes(obj))
    if isinstance(obj, (str, os.PathLike)):
        src = FileByteSource(obj)
        return _SOURCE_WRAPPER(src) if _SOURCE_WRAPPER is not None else src
    raise TypeError(f"cannot make a ByteSource from {type(obj)!r}")


class scoped_byte_source:
    """``with scoped_byte_source(obj) as src``: closes ``src`` on exit only
    when this call created it (an already-open ByteSource passes through
    untouched — the caller owns its lifetime)."""

    def __init__(self, obj):
        self._owned = not isinstance(obj, ByteSource)
        self.src = as_byte_source(obj)

    def __enter__(self) -> ByteSource:
        return self.src

    def __exit__(self, *exc):
        if self._owned:
            self.src.close()
