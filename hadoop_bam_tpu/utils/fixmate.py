"""Streaming mate-info fixup (the CLI ``fixmate`` verb's engine).

The reference's fixmate was an MR job driver (SURVEY.md section 2.7,
``hb/cli`` fixmate plugin) pairing name-adjacent records.  This is the
same contract — input must be queryname-grouped, as for samtools
fixmate — executed as a single streaming pass over raw record bytes:
mate fields live at fixed offsets in the BAM wire layout [SPEC alignment
section], so each pair is patched in place with no record-object or
SAM-text materialization, and memory is bounded by one decode span plus
one pending record regardless of file size.

Raw-record offsets (block_size-prefixed, as ``BamBatch.record_bytes``
returns them — see ops/unpack_bam.py::FIXED_FIELDS):

    0:4 block_size | 4:8 refID | 8:12 pos | 12 l_read_name | 13 mapq
    | 14:16 bin | 16:18 n_cigar_op | 18:20 flag | 20:24 l_seq
    | 24:28 next_refID | 28:32 next_pos | 32:36 tlen
    | 36:36+l_read_name read_name (NUL-terminated) | cigar u32[n_cigar]
"""
from __future__ import annotations

from typing import Iterator, Optional

import numpy as np

from hadoop_bam_tpu.config import DEFAULT_CONFIG, HBamConfig

_REF_CONSUME = frozenset((0, 2, 3, 7, 8))   # M D N = X [SPEC cigar ops]


def _i32(rec, off: int) -> int:
    return int.from_bytes(rec[off:off + 4], "little", signed=True)


def _put_i32(rec: bytearray, off: int, v: int) -> None:
    rec[off:off + 4] = v.to_bytes(4, "little", signed=True)


def _u16(rec, off: int) -> int:
    return int.from_bytes(rec[off:off + 2], "little")


def _qname(rec) -> bytes:
    return bytes(rec[36:36 + rec[12] - 1])


def _alen(rec) -> int:
    """Alignment span on the reference from the packed CIGAR; falls back
    to l_seq for CIGAR-less records (the '*' CIGAR convention)."""
    n_cigar = _u16(rec, 16)
    if n_cigar == 0:
        return _i32(rec, 20)          # l_seq; 0 when seq is '*'
    off = 36 + rec[12]
    total = 0
    for k in range(n_cigar):
        v = int.from_bytes(rec[off + 4 * k:off + 4 * k + 4], "little")
        if (v & 0xF) in _REF_CONSUME:
            total += v >> 4
    return total


def fix_pair(a: bytearray, b: bytearray) -> None:
    """Patch mate refid/pos, template length, and mate flags of a
    name-matched pair, in place."""
    refid_a, refid_b = _i32(a, 4), _i32(b, 4)
    pos_a, pos_b = _i32(a, 8), _i32(b, 8)
    _put_i32(a, 24, refid_b)
    _put_i32(a, 28, pos_b)
    _put_i32(b, 24, refid_a)
    _put_i32(b, 28, pos_a)
    if refid_a == refid_b and pos_a >= 0 and pos_b >= 0:
        span = (max(pos_a + _alen(a), pos_b + _alen(b))
                - min(pos_a, pos_b))
        sign = 1 if pos_a <= pos_b else -1
        _put_i32(a, 32, sign * span)
        _put_i32(b, 32, -sign * span)
    else:
        # not computable (cross-reference or unmapped member): zero any
        # stale input tlen, as samtools fixmate does
        _put_i32(a, 32, 0)
        _put_i32(b, 32, 0)
    flag_a, flag_b = _u16(a, 18), _u16(b, 18)
    for x, xf, yf in ((a, flag_a, flag_b), (b, flag_b, flag_a)):
        nf = ((xf & ~0x28)
              | (0x8 if yf & 0x4 else 0)      # mate unmapped [SPEC 0x8]
              | (0x20 if yf & 0x10 else 0))   # mate reverse [SPEC 0x20]
        x[18:20] = nf.to_bytes(2, "little")


def fixmate_bam(input_path: str, output_path: str, *,
                config: HBamConfig = DEFAULT_CONFIG) -> int:
    """Fix mate information across a queryname-grouped BAM, streaming.

    Pairs are adjacent primary records sharing a read name whose first
    member has the paired flag (0x1) set; secondary (0x100) and
    supplementary (0x800) alignments never pair (a primary's mate is the
    other primary, not its own split alignment — samtools fixmate
    contract) and pass through untouched, as does everything unpaired.
    Returns the record count.

    Output goes through ``write_bam_records`` so the write config
    (``write_compress_level``, ``write_index_kinds``) and the co-written
    index sidecars apply, same as every other verb.  Caveat: a BAI
    sidecar is only meaningful when the queryname-grouped input happens
    to also be coordinate-compatible; ``--no-write-index`` skips it.
    """
    from hadoop_bam_tpu.api.dataset import open_bam
    from hadoop_bam_tpu.write import write_bam_records

    ds = open_bam(input_path, config)
    n = 0

    def fixed_records() -> "Iterator[bytes]":
        nonlocal n
        pending: Optional[bytearray] = None
        pending_name = b""
        for batch in ds.batches():
            for i in range(len(batch)):
                rec = bytearray(batch.record_bytes(i))
                n += 1
                if _u16(rec, 18) & 0x900:    # secondary/supplementary
                    yield bytes(rec)
                    continue
                name = _qname(rec)
                if (pending is not None and name == pending_name
                        and _u16(pending, 18) & 0x1):
                    fix_pair(pending, rec)
                    yield bytes(pending)
                    yield bytes(rec)
                    pending = None
                else:
                    if pending is not None:
                        yield bytes(pending)
                    pending, pending_name = rec, name
        if pending is not None:
            yield bytes(pending)

    def chunks():
        buf = []
        offsets = []
        pos = 0
        for rec in fixed_records():
            buf.append(rec)
            offsets.append(pos)
            pos += len(rec)
            if pos >= (8 << 20):
                yield b"".join(buf), np.asarray(offsets, np.int64)
                buf, offsets, pos = [], [], 0
        if buf:
            yield b"".join(buf), np.asarray(offsets, np.int64)

    write_bam_records(output_path, ds.header, chunks(), config=config)
    return n
