"""Record serialization for exchange between pipeline stages and hosts.

The rebuild of the reference's Writables layer (hb/SAMRecordWritable.java,
hb/VariantContextWritable.java + hb/util/VariantContextCodec.java,
SURVEY.md section 2.5): where Hadoop needed ``write()``/``readFields()`` so
records could cross the shuffle, a mesh framework needs records to cross
host boundaries (plan broadcast, resort exchanges, checkpoint sidecars).
The wire formats ARE the specs' own binary layouts — BAM record bytes
[SPEC section 4.2] and BCF2 record bytes [SPEC BCFv2] — so any spec-
compliant reader interoperates; like the reference's lazy ``readFields``,
decode defers to the columnar BamBatch machinery rather than eagerly
materializing objects.
"""
from __future__ import annotations

from typing import List, Sequence

import numpy as np

from hadoop_bam_tpu.formats.bam import BamBatch, SAMHeader
from hadoop_bam_tpu.formats.sam import SamRecord
from hadoop_bam_tpu.formats.vcf import VCFHeader, VcfRecord


def encode_sam_records(records: Sequence[SamRecord], header: SAMHeader
                       ) -> bytes:
    """SamRecords -> concatenated BAM record bytes (block_size-prefixed,
    uncompressed — the SAMRecordWritable wire form)."""
    return b"".join(rec.to_bam_bytes(header) for rec in records)


def decode_sam_records(buf: bytes, header: SAMHeader) -> List[SamRecord]:
    """Concatenated BAM record bytes -> SamRecords (via the lazy columnar
    batch, the LazyBAMRecordFactory analog: fields parse on access)."""
    data = np.frombuffer(buf, dtype=np.uint8)
    offs: List[int] = []
    p = 0
    while p + 4 <= data.size:
        bs = int.from_bytes(buf[p:p + 4], "little", signed=True)
        if bs < 32 or p + 4 + bs > data.size:
            raise ValueError(f"malformed serialized BAM record at {p}")
        offs.append(p)
        p += 4 + bs
    if p != data.size:
        raise ValueError("trailing bytes after final serialized record")
    batch = BamBatch(data, np.asarray(offs, dtype=np.int64), header=header)
    return [SamRecord.from_line(batch.to_sam_line(i))
            for i in range(len(offs))]


def encode_variants(records: Sequence[VcfRecord], header: VCFHeader) -> bytes:
    """VcfRecords -> concatenated BCF2 record bytes (the
    VariantContextWritable wire form)."""
    from hadoop_bam_tpu.formats.bcf import BCFRecordCodec
    codec = BCFRecordCodec(header)
    return b"".join(codec.encode(rec) for rec in records)


def decode_variants(buf: bytes, header: VCFHeader) -> List[VcfRecord]:
    from hadoop_bam_tpu.formats.bcf import BCFRecordCodec
    codec = BCFRecordCodec(header)
    out: List[VcfRecord] = []
    off = 0
    while off < len(buf):
        rec, off = codec.decode(buf, off)
        out.append(rec)
    return out
