"""Durable job journal — append-only, fsync'd, checksummed JSONL.

The reference inherited durability from MapReduce for free: task
re-execution over idempotent, atomically-committed splits meant a lost
worker cost one task, not the job (SURVEY.md section 5).  This rebuild's
long pipelines — multi-round mesh sorts, k-way cohort joins, multi-shard
writes — died with the process until now: a SIGKILL restarted the job
from byte zero.  The journal is the missing recovery substrate:

- **append-only JSONL**: one JSON object per line, written with an
  ``os.fsync`` after every record, so a committed line survives any
  process death (only the tail the OS never flushed can be lost);
- **checksummed lines**: every record carries a CRC32 of its canonical
  serialization — replay distinguishes "torn tail" (a half-written
  final line: expected after SIGKILL, silently dropped) from
  "corrupted middle" (bit rot / concurrent writers: ``CorruptDataError``,
  the journal is not trustworthy and resume refuses);
- **job identity**: the header line records the input files'
  identity digests (abspath, size, mtime_ns — the ``file_identity``
  convention the query cache keys on), a fingerprint of the
  output-affecting config fields, and the job parameters.  Resume
  verifies ALL of them and refuses with ``PlanError`` on any mismatch —
  resuming a sort over a rewritten input or at a different compression
  level would publish a silently-wrong file;
- **unit records**: per-unit completion (``round`` of a spill sort,
  ``shard`` of a sharded write, ``chunk`` of a cohort join) with the
  produced artifact's size + CRC32, so a restarted process verifies —
  not trusts — what finished before skipping it.

The journal never records record DATA; it records which durable
artifacts (spill runs, shard parts, chunk files) are complete and how
to verify them.  Replaying a journal is therefore cheap (KBs of JSON)
and resuming is exactly "verify artifacts, skip their work".
"""
from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import zlib
from typing import Dict, List, Optional, Sequence, Tuple

from hadoop_bam_tpu.utils.errors import CorruptDataError, PlanError
from hadoop_bam_tpu.utils.metrics import METRICS

JOURNAL_SUFFIX = ".hbam-journal"
_VERSION = 1


def journal_path_for(output_path: str) -> str:
    """The default journal location for a job publishing ``output_path``:
    a sibling file, so it lands on the same (shared) filesystem as the
    artifacts it describes."""
    return output_path + JOURNAL_SUFFIX


# ---------------------------------------------------------------------------
# identity + digests
# ---------------------------------------------------------------------------

def file_identity_digest(path: str) -> str:
    """Digest of a file's (abspath, size, mtime_ns) identity — the same
    convention the query cache and cohort manifests key on.  Cheap (one
    stat), and exactly strong enough for the resume contract: a
    rewritten/touched input refuses to resume rather than silently
    merging old rounds with new bytes."""
    from hadoop_bam_tpu.query.cache import file_identity

    ident = file_identity(path)
    return hashlib.sha256(repr(tuple(ident)).encode()).hexdigest()[:24]


def file_digest(path: str) -> Tuple[int, str]:
    """(size, crc32 hex) of a file's CONTENT — what unit verification
    uses for the artifacts themselves (spill runs, shard parts, chunk
    files, the published output).  Streamed, so verifying a resumed
    job's artifacts costs one read pass, never a decode."""
    crc = 0
    size = 0
    with open(path, "rb") as f:
        while True:
            buf = f.read(1 << 20)
            if not buf:
                break
            crc = zlib.crc32(buf, crc)
            size += len(buf)
    return size, f"{crc & 0xFFFFFFFF:08x}"


def verify_artifact(path: str, size: int, crc: str) -> bool:
    """True iff ``path`` exists with exactly the recorded size + CRC."""
    try:
        if os.path.getsize(path) != int(size):
            return False
    except OSError:
        return False
    got_size, got_crc = file_digest(path)
    return got_size == int(size) and got_crc == str(crc)


def plan_digest(spans) -> str:
    """Digest of a serialized span plan — resumes verify it so a changed
    splitting-index sidecar (which would re-cut spans under the recorded
    units) refuses instead of silently mis-joining.

    Span paths are canonicalized to abspath first: the killed run may
    have named its input relatively while ``hbam resume`` re-plans from
    the journal's absolute params, and the digest must cover span
    GEOMETRY (cuts and offsets), not path spelling — same-file identity
    is already the header's job."""
    from hadoop_bam_tpu.parallel.distributed import serialize_plan

    doc = json.loads(serialize_plan(spans, max_bytes=1 << 30).decode())
    for d in doc:
        if isinstance(d.get("path"), str):
            d["path"] = os.path.abspath(d["path"])
    blob = json.dumps(doc, sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(blob.encode()).hexdigest()[:24]


def fingerprint_values(config, fields: Sequence[str]) -> Dict:
    """The named config fields as a JSON-able dict — both the
    fingerprint's input and (recorded in the journal header) what lets
    ``hbam resume`` reconstruct the job's output-affecting config
    instead of refusing whenever the journaled run used non-default
    knobs."""
    vals = {}
    for f in sorted(fields):
        v = getattr(config, f, None)
        vals[f] = v if isinstance(v, (int, float, str, bool,
                                      type(None))) else repr(v)
    return vals


def config_fingerprint(config, fields: Sequence[str]) -> str:
    """Digest of the named config fields — the output-affecting subset a
    job's resume contract depends on.  Deliberately NOT the whole config:
    changing an observability knob must not strand a resumable journal,
    while changing the compression level must."""
    blob = json.dumps(fingerprint_values(config, fields),
                      sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(blob.encode()).hexdigest()[:24]


def _line_crc(rec: Dict) -> str:
    blob = json.dumps(rec, sort_keys=True, separators=(",", ":"))
    return f"{zlib.crc32(blob.encode()) & 0xFFFFFFFF:08x}"


# ---------------------------------------------------------------------------
# replayed state
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class JournalState:
    """What a replay reconstructs: the header, the completed units, the
    recorded events, and whether the job finished.  ``good_bytes`` is
    the byte length of the intact prefix — what a resume truncates to
    before appending, so new records never concatenate onto a torn
    final line (which would turn the next replay's 'expected crash
    shape' into mid-file corruption)."""

    header: Dict
    units: Dict[Tuple[str, str], Dict]
    events: List[Dict]
    done: Optional[Dict]
    torn_tail: bool
    lines: int
    good_bytes: int = 0

    def unit(self, kind: str, key) -> Optional[Dict]:
        return self.units.get((str(kind), str(key)))

    def last_event(self, name: str) -> Optional[Dict]:
        for rec in reversed(self.events):
            if rec.get("name") == name:
                return rec
        return None

    @property
    def kind(self) -> str:
        return str(self.header.get("kind", ""))


class JobJournal:
    """One job's append-only journal (module docstring).

    Writers hold the file open in append mode; every ``append`` is one
    ``write + flush + fsync`` so a record either fully exists on disk or
    was never acknowledged.  Records are small (unit metadata, never
    data), so the fsync cadence — once per completed UNIT, not per
    record of work — is what keeps journaling overhead under the bench
    row's <3% bar."""

    def __init__(self, path: str, *, fsync: bool = True):
        self.path = path
        self.fsync = bool(fsync)
        self._f = None
        self._seq = 0

    # -- writing -------------------------------------------------------------

    def _ensure_open(self):
        if self._f is None:
            self._f = open(self.path, "ab")
        return self._f

    def append(self, rec: Dict) -> None:
        rec = dict(rec)
        rec["seq"] = self._seq
        # every journal line carries the active trace id (obs/context):
        # `hbam jobs --json` reports which invocation wrote the journal,
        # and a resumed job's lines are attributable to the RESUMING
        # trace, not the original one
        if "trace" not in rec:
            from hadoop_bam_tpu.obs.context import current_trace_id

            tid = current_trace_id()
            if tid is not None:
                rec["trace"] = tid
        rec["c"] = _line_crc(rec)
        line = json.dumps(rec, sort_keys=True,
                          separators=(",", ":")) + "\n"
        f = self._ensure_open()
        f.write(line.encode())
        f.flush()
        if self.fsync:
            os.fsync(f.fileno())
        self._seq += 1
        METRICS.count("jobs.journal_records")

    def start(self, kind: str, *, inputs: Sequence[Tuple[str, str]],
              output: Optional[str], fingerprint: str,
              params: Optional[Dict] = None,
              config_values: Optional[Dict] = None) -> None:
        """The header record — written exactly once, first.
        ``config_values`` (the fingerprinted field values) ride along
        so ``hbam resume`` can reconstruct the job's output-affecting
        config; only the FINGERPRINT participates in matching."""
        rec = {
            "t": "job", "v": _VERSION, "kind": str(kind),
            "inputs": [[p, d] for p, d in inputs],
            "output": output, "fingerprint": str(fingerprint),
            "params": dict(params or {}),
        }
        if config_values is not None:
            rec["config"] = dict(config_values)
        self.append(rec)

    def unit_done(self, kind: str, key, **fields) -> None:
        """One unit of work committed: its durable artifact(s) exist and
        their size+CRC are recorded for verification on resume."""
        self.append({"t": "unit", "k": str(kind), "key": str(key),
                     **fields})

    def event(self, name: str, **fields) -> None:
        """A non-unit fact resume needs (bucket bounds, plan digest,
        quarantine, a resume itself)."""
        self.append({"t": "event", "name": str(name), **fields})

    def job_done(self, **fields) -> None:
        self.append({"t": "done", **fields})

    def close(self) -> None:
        if self._f is not None:
            self._f.close()
            self._f = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # -- replay --------------------------------------------------------------

    @classmethod
    def replay(cls, path: str) -> JournalState:
        """Reconstruct job state from a journal file.

        Tolerates exactly one torn record — the final line, the only one
        a crash can leave half-written under the append+fsync discipline.
        A checksum/parse failure anywhere BEFORE the final line means the
        file is not an honestly-crashed journal (bit rot, truncation in
        the middle, a concurrent writer) and raises ``CorruptDataError``:
        resuming from untrustworthy state is worse than restarting."""
        try:
            with open(path, "rb") as f:
                raw = f.read()
        except FileNotFoundError:
            raise PlanError(f"no job journal at {path}") from None
        lines = raw.split(b"\n")
        if lines and lines[-1] == b"":
            lines.pop()
        header: Optional[Dict] = None
        units: Dict[Tuple[str, str], Dict] = {}
        events: List[Dict] = []
        done: Optional[Dict] = None
        torn = False
        seq = 0
        good_bytes = 0
        for i, line in enumerate(lines):
            rec = _parse_line(line)
            if rec is None:
                if i == len(lines) - 1:
                    torn = True          # the one expected failure mode
                    break
                raise CorruptDataError(
                    f"job journal {path}: line {i + 1} of {len(lines)} "
                    f"fails its checksum — mid-file corruption, refusing "
                    f"to reconstruct state from it")
            good_bytes += len(line) + 1          # line + its newline
            seq = int(rec.get("seq", seq)) + 1
            t = rec.get("t")
            if t == "job":
                if header is not None:
                    raise CorruptDataError(
                        f"job journal {path}: duplicate header at line "
                        f"{i + 1}")
                header = rec
            elif t == "unit":
                units[(str(rec.get("k")), str(rec.get("key")))] = rec
            elif t == "event":
                events.append(rec)
            elif t == "done":
                done = rec
        if header is None:
            raise CorruptDataError(
                f"job journal {path}: no (intact) header record")
        return JournalState(header=header, units=units, events=events,
                            done=done, torn_tail=torn, lines=len(lines),
                            good_bytes=good_bytes)

    @classmethod
    def resume(cls, path: str, *, kind: str,
               inputs: Sequence[Tuple[str, str]], output: Optional[str],
               fingerprint: str, params: Optional[Dict] = None,
               config_values: Optional[Dict] = None,
               fsync: bool = True
               ) -> Tuple["JobJournal", Optional[JournalState]]:
        """Open ``path`` for a job, resuming when a matching journal
        already exists.

        Returns ``(journal, state)``: ``state`` is None for a fresh job
        (the header was just written), or the replayed state of the
        prior attempt.  A journal for a DIFFERENT job — other kind,
        other inputs (by identity digest), other config fingerprint,
        other params — refuses with ``PlanError``: the caller asked to
        resume something that no longer exists."""
        if not os.path.exists(path):
            j = cls(path, fsync=fsync)
            j.start(kind, inputs=inputs, output=output,
                    fingerprint=fingerprint, params=params,
                    config_values=config_values)
            return j, None
        state = cls.replay(path)
        _check_header(path, state.header, kind=kind, inputs=inputs,
                      output=output, fingerprint=fingerprint,
                      params=params)
        if state.torn_tail:
            # appending onto the half-written final line would weld the
            # new record into one unparseable MID-file line, turning the
            # next replay's "honest crash" into refused corruption —
            # amputate the torn fragment before the first append
            with open(path, "r+b") as f:
                f.truncate(state.good_bytes)
        j = cls(path, fsync=fsync)
        j._seq = state.lines
        METRICS.count("jobs.resumes")
        j.event("resume", prior_units=len(state.units),
                torn_tail=bool(state.torn_tail))
        return j, state


def _parse_line(line: bytes) -> Optional[Dict]:
    """Decode + checksum one journal line; None on any failure (the
    caller decides whether that position tolerates it)."""
    if not line.strip():
        return None
    try:
        rec = json.loads(line.decode())
    except (ValueError, UnicodeDecodeError):
        return None
    if not isinstance(rec, dict):
        return None
    crc = rec.pop("c", None)
    if crc is None or _line_crc(rec) != crc:
        return None
    return rec


def _check_header(path: str, header: Dict, *, kind: str,
                  inputs: Sequence[Tuple[str, str]], output: Optional[str],
                  fingerprint: str, params: Optional[Dict]) -> None:
    def refuse(what: str, want, got) -> None:
        raise PlanError(
            f"refusing to resume {path}: {what} changed since the "
            f"journal was written (journal: {got!r}, now: {want!r}) — "
            f"delete the journal to start the job over")

    if str(header.get("kind")) != str(kind):
        refuse("job kind", kind, header.get("kind"))
    if str(header.get("fingerprint")) != str(fingerprint):
        refuse("config fingerprint (an output-affecting knob)",
               fingerprint, header.get("fingerprint"))
    want_inputs = [[p, d] for p, d in inputs]
    if list(header.get("inputs", [])) != want_inputs:
        refuse("input file identity", want_inputs, header.get("inputs"))
    if header.get("output") != output:
        refuse("output path", output, header.get("output"))
    want_params = dict(params or {})
    if dict(header.get("params", {})) != want_params:
        refuse("job parameters", want_params, header.get("params"))


def sweep_unrecorded(directory: str, recorded: Sequence[str],
                     counter: str = "jobs.stale_artifacts_swept") -> int:
    """Delete files in ``directory`` that no journal unit claims — the
    partial artifacts of the unit that was in flight when the process
    died.  Returns the number removed."""
    keep = {os.path.abspath(p) for p in recorded}
    swept = 0
    try:
        names = os.listdir(directory)
    except OSError:
        return 0
    for name in names:
        p = os.path.join(directory, name)
        if os.path.abspath(p) in keep or not os.path.isfile(p):
            continue
        try:
            os.unlink(p)
            swept += 1
        except OSError:
            pass
    if swept:
        METRICS.count(counter, swept)
    return swept
