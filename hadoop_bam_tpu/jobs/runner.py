"""Job-level resume glue: fingerprints, the `hbam resume`/`hbam jobs`
entry points, and the generic job-grain idempotence wrapper.

The journal (jobs/journal.py) is mechanism; this module is policy —
which config fields participate in each job kind's resume contract, how
a journal's header maps back to the pipeline invocation that wrote it,
and what ``hbam jobs`` reports about a directory of journals.
"""
from __future__ import annotations

import dataclasses
import glob
import os
from typing import Callable, Dict, List, Optional, Sequence

from hadoop_bam_tpu.jobs import journal as jj
from hadoop_bam_tpu.obs.context import ensure_trace
from hadoop_bam_tpu.utils.errors import PlanError
from hadoop_bam_tpu.utils.metrics import METRICS

# Output-affecting config fields per job kind — the resume contract's
# fingerprint (jobs/journal.config_fingerprint).  Observability /
# scheduling knobs are deliberately absent: changing a trace flag must
# not strand a resumable journal; changing anything that alters the
# published BYTES (or the unit partitioning the journal indexes) must.
SORT_FINGERPRINT_FIELDS = (
    "write_compress_level", "write_header", "write_terminator",
    "write_index_kinds", "splitting_index_granularity",
)
COHORT_FINGERPRINT_FIELDS = (
    "cohort_chunk_sites", "cohort_quarantine_inputs",
    "cohort_max_quarantine_fraction",
)


def plan_journal_params(plan, extra: Optional[Dict] = None) -> Dict:
    """Journal params carrying a compiled plan's IR digest — the
    IR-level twin of ``journal.plan_digest(spans)``.  Where the span
    digest pins the CUT GEOMETRY of a pinned span plan, the plan digest
    pins the compiled workload itself (source identity, op DAG, the
    unit-partitioning knobs the builder folded in), so a resume whose
    plan compiles differently refuses inside ``JobJournal.resume``'s
    params match instead of silently mis-joining units."""
    out = dict(extra or {})
    out["plan_digest"] = plan.digest()
    return out


def sort_job_params(input_path: str, output_path: str, *,
                    exchange: Optional[str],
                    round_records: Optional[int],
                    n_dev: Optional[int] = None) -> Dict:
    """The spill sort's params carry ``n_dev``: round units are cut per
    device position, so resuming on a different mesh size must refuse
    (params mismatch) instead of mis-stitching rounds.  The resident
    modes omit it — their output is byte-identical at any mesh size and
    they only resume at job grain."""
    out = {"input": os.path.abspath(input_path),
           # abspath both endpoints: a job journaled with a relative
           # spelling must resume from `hbam resume` (which re-plans
           # from the journal's params) without a spurious mismatch
           "output": os.path.abspath(output_path),
           "exchange": exchange,
           "round_records": (None if round_records is None
                             else int(round_records))}
    if n_dev is not None:
        out["n_dev"] = int(n_dev)
    return out


def run_job_level(journal_path: str, *, kind: str, config,
                  inputs: Sequence[str], output: str, params: Dict,
                  run: Callable[[], int],
                  fingerprint_fields: Sequence[str] = SORT_FINGERPRINT_FIELDS
                  ) -> int:
    """Idempotence at JOB grain for pipelines whose whole run is one
    unit of work: a journal whose ``job_done`` record matches the
    (verified) output makes the re-run a no-op; anything else re-runs
    ``run()`` and commits the result.  Mismatched identity/fingerprint/
    params refuse inside ``JobJournal.resume``."""
    output = os.path.abspath(output)
    # job start is an entry point: the minted (or joined) trace id is
    # stamped onto every journal line this run writes
    with ensure_trace(op=f"job.{kind}"):
        jr, state = jj.JobJournal.resume(
            journal_path, kind=kind,
            inputs=[(os.path.abspath(p), jj.file_identity_digest(p))
                    for p in inputs],
            output=output,
            fingerprint=jj.config_fingerprint(config, fingerprint_fields),
            config_values=jj.fingerprint_values(config,
                                                fingerprint_fields),
            params=params,
            fsync=bool(getattr(config, "journal_fsync", True)))
        with jr:
            if state is not None and state.done is not None:
                d = state.done
                if jj.verify_artifact(output, d.get("size", -1),
                                      d.get("crc", "")):
                    METRICS.count("jobs.jobs_skipped")
                    return int(d.get("records", 0))
            n = int(run())
            size, crc = jj.file_digest(output)
            jr.job_done(records=n, size=size, crc=crc)
            return n


# ---------------------------------------------------------------------------
# hbam resume
# ---------------------------------------------------------------------------

def resume_job(journal_path: str, config=None) -> Dict:
    """Re-drive the job a journal describes (the ``hbam resume`` verb).

    Reads only the journal HEADER here; all verification (input
    identity, config fingerprint, plan digest, per-unit artifacts)
    happens inside the pipeline itself when it re-opens the journal —
    resume is a plain re-invocation, which is what makes it correct
    under repeated crashes (resuming a resume is the same code path).

    Returns a summary dict: kind, output, records/chunks, and the skip
    counters the resumed run recorded."""
    from hadoop_bam_tpu.config import DEFAULT_CONFIG

    config = DEFAULT_CONFIG if config is None else config
    state = jj.JobJournal.replay(journal_path)
    kind = state.kind
    with ensure_trace(op=f"job.resume.{kind}"):
        return _resume_replayed(journal_path, config, state, kind)


def _resume_replayed(journal_path: str, config, state, kind: str) -> Dict:
    params = dict(state.header.get("params", {}))
    # the header records the fingerprinted field VALUES: reconstruct the
    # job's output-affecting config on top of the caller's, so a job
    # journaled with non-default knobs (a custom compression level, a
    # different chunk size) resumes from the bare CLI instead of
    # refusing on its own fingerprint
    recorded = {k: v for k, v in dict(state.header.get("config",
                                                       {})).items()
                if hasattr(config, k)}
    if recorded:
        config = dataclasses.replace(config, **recorded)
    if kind in ("mesh_sort_spill", "mesh_sort"):
        from hadoop_bam_tpu.parallel.mesh_sort import sort_bam_mesh

        n = sort_bam_mesh(
            params["input"], params["output"],
            config=config,
            exchange=params.get("exchange"),
            round_records=params.get("round_records"),
            journal_path=journal_path)
        return {"kind": kind, "output": params["output"], "records": n}
    if kind == "mkdup":
        from hadoop_bam_tpu.prep.pipeline import markdup_bam_mesh

        n = markdup_bam_mesh(
            params["input"], params["output"],
            config=config,
            remove_duplicates=bool(params.get("remove_duplicates",
                                              False)),
            library_from=params.get("library_from", "none"),
            round_records=params.get("round_records"),
            journal_path=journal_path)
        return {"kind": kind, "output": params["output"], "records": n}
    if kind == "cohort_join":
        from hadoop_bam_tpu.cohort.dataset import open_cohort

        manifest = params.get("manifest")
        if not manifest:
            raise PlanError(
                f"journal {journal_path} records an inline-manifest "
                f"cohort job — only manifest-file cohort jobs are "
                f"resumable from the CLI; resume through the library "
                f"(CohortDataset(..., journal_path=...))")
        ds = open_cohort(manifest, config=config,
                         journal_path=journal_path)
        sites = 0
        chunks = 0
        for chunk in ds.site_chunks():
            sites += int(chunk["pos"].shape[0])
            chunks += 1
        return {"kind": kind, "output": None, "chunks": chunks,
                "sites": sites,
                "quarantined": sorted(ds.manifest.quarantined)}
    raise PlanError(
        f"journal {journal_path} records job kind {kind!r}, which has "
        f"no CLI resume driver (resumable kinds: mesh_sort_spill, "
        f"mesh_sort, mkdup, cohort_join)")


# ---------------------------------------------------------------------------
# hbam jobs
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class JobInfo:
    path: str
    kind: str
    status: str        # done | resumable | fresh | corrupt
    units: int
    output: Optional[str]
    detail: str = ""
    # machine-readable extras (`hbam jobs --json` / `hbam top`):
    trace_id: Optional[str] = None      # trace that wrote the header
    units_skipped: int = 0              # units a resume verified+skipped
    resumes: int = 0                    # resume events recorded


# the grain a resumed job skips completed work at, per journal kind —
# the `hbam jobs --json` / `hbam top` vocabulary (README crash-recovery
# table is the human-readable twin)
RESUME_GRAINS = {
    "mesh_sort_spill": "round",
    "mesh_sort": "job",
    "mkdup": "round",
    "cohort_join": "chunk",
    "shard_write": "part",
}


def resume_grain(kind: str) -> str:
    return RESUME_GRAINS.get(kind, "job")


def job_info_doc(info: JobInfo) -> Dict:
    """THE machine-readable job row — the one parser ``hbam jobs
    --json``, ``hbam top`` and external schedulers share.  Keys are a
    stable contract: path/kind/status/output, the journal-writing
    trace_id, the resume grain, and units committed/skipped."""
    return {
        "path": info.path,
        "kind": info.kind,
        "status": info.status,
        "output": info.output,
        "detail": info.detail or None,
        "trace_id": info.trace_id,
        "resume_grain": resume_grain(info.kind),
        "units_total": info.units,
        "units_skipped": info.units_skipped,
        "resumes": info.resumes,
    }


def job_status(journal_path: str) -> JobInfo:
    """One journal's summary row, never raising: a corrupt journal is a
    listable fact, not a listing failure."""
    try:
        state = jj.JobJournal.replay(journal_path)
    except Exception as e:  # noqa: BLE001 — report, don't die
        return JobInfo(path=journal_path, kind="?", status="corrupt",
                       units=0, output=None,
                       detail=f"{type(e).__name__}: {e}")
    trace_id = state.header.get("trace")
    resumes = [e for e in state.events if e.get("name") == "resume"]
    # units the LAST resume found committed = what that resume verified
    # and skipped instead of re-running
    units_skipped = int(resumes[-1].get("prior_units", 0)) \
        if resumes else 0
    if state.done is not None:
        output = state.header.get("output")
        if output is None:
            # chunk-replay jobs (cohort join) publish no single output
            # file — their artifacts are the journaled units themselves
            detail = "no published output (unit-replay job)"
        elif jj.verify_artifact(output, state.done.get("size", -1),
                                state.done.get("crc", "")):
            detail = "output verified"
        else:
            detail = "output missing/changed since job_done"
        return JobInfo(
            path=journal_path, kind=state.kind, status="done",
            units=len(state.units), output=output, detail=detail,
            trace_id=trace_id, units_skipped=units_skipped,
            resumes=len(resumes))
    status = "resumable" if state.units else "fresh"
    detail = "torn tail (expected after a crash)" if state.torn_tail \
        else ""
    return JobInfo(path=journal_path, kind=state.kind, status=status,
                   units=len(state.units),
                   output=state.header.get("output"), detail=detail,
                   trace_id=trace_id, units_skipped=units_skipped,
                   resumes=len(resumes))


def list_jobs(directory: str = ".") -> List[JobInfo]:
    """Every ``*.hbam-journal`` under ``directory`` (non-recursive),
    summarized."""
    out = []
    for p in sorted(glob.glob(os.path.join(directory,
                                           "*" + jj.JOURNAL_SUFFIX))):
        out.append(job_status(p))
    return out
