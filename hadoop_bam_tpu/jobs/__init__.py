"""Crash-safe jobs: durable journals, resumable pipelines, stragglers.

The layer every long-running pipeline inherits restartability from
(ISSUE 13): MapReduce gave the reference task re-execution over
idempotent, atomically-committed splits for free — a lost worker cost
one task.  This package rebuilds that contract for the mesh pipelines:

- ``journal`` — the durable job journal (append-only fsync'd JSONL,
  checksummed lines, torn-tail-tolerant replay) plus the identity /
  fingerprint / artifact-digest helpers the resume contract verifies;
- ``runner`` — job-kind policy: per-kind config fingerprints, the
  ``hbam resume`` / ``hbam jobs`` drivers, job-grain idempotence;
- ``speculate`` — straggler defense: the decaying per-job latency
  histogram whose p95-derived soft deadlines trigger speculative
  re-execution of slow span decodes (first result wins).

Consumers: ``parallel/mesh_sort.py`` (round-grain spill resume),
``write/sharded.py`` (shard-grain commit/skip), ``cohort/dataset.py``
(chunk-grain join resume), ``parallel/pipeline._iter_windowed`` (the
speculation + hard-timeout consumer).
"""
from hadoop_bam_tpu.jobs.journal import (     # noqa: F401
    JOURNAL_SUFFIX, JobJournal, JournalState, config_fingerprint,
    file_digest, file_identity_digest, journal_path_for, plan_digest,
    sweep_unrecorded, verify_artifact,
)
from hadoop_bam_tpu.jobs.runner import (      # noqa: F401
    COHORT_FINGERPRINT_FIELDS, JobInfo, RESUME_GRAINS,
    SORT_FINGERPRINT_FIELDS, job_info_doc, job_status, list_jobs,
    resume_grain, resume_job, run_job_level, sort_job_params,
)
from hadoop_bam_tpu.jobs.speculate import UnitLatency  # noqa: F401
