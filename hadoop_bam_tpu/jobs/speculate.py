"""Straggler defense: decaying latency tracking -> soft deadlines.

MapReduce's speculative execution re-ran the slowest tasks on spare
capacity and took whichever copy finished first; that is exactly the
right medicine for this pipeline's span decodes too (a span decode is
idempotent and side-effect-free, so racing two copies is always safe).
The open question is WHEN a unit is "slow".  A fixed timeout is wrong in
both directions — too tight for a cold page cache, uselessly loose for a
warm one — so the deadline is derived from the job's OWN latency
distribution: a decaying ``obs/hist.py`` histogram of completed unit
durations, with the soft deadline at ``p95 * straggler_multiplier``
(floored at ``straggler_min_s`` so sub-millisecond decode storms never
speculate).

Decay matters because a job's latency regime shifts mid-run (cache
warms, a fault domain demotes the decode plane): every ``decay_every``
observations the bucket counts halve, so the deadline tracks the recent
regime instead of the whole-run average.  The histogram needs
``min_samples`` completions before it issues any deadline at all — the
first units of a job carry compile/warmup noise that must not trigger a
speculation stampede.
"""
from __future__ import annotations

import threading
from typing import Optional

from hadoop_bam_tpu.obs.hist import Histogram


class UnitLatency:
    """Thread-safe decaying latency histogram with a soft-deadline read.

    One instance per job stage (each ``_iter_windowed`` drive creates
    its own), matching the ISSUE's "per-job latency histogram": a sort's
    span decodes must not inherit a cohort join's distribution."""

    def __init__(self, *, multiplier: float = 4.0, min_s: float = 0.5,
                 min_samples: int = 16, decay_every: int = 256):
        self.multiplier = float(multiplier)
        self.min_s = float(min_s)
        self.min_samples = int(min_samples)
        self.decay_every = max(2, int(decay_every))
        self.hist = Histogram()
        self._seen = 0
        self._lock = threading.Lock()

    @classmethod
    def from_config(cls, config) -> "UnitLatency":
        return cls(
            multiplier=float(getattr(config, "straggler_multiplier", 4.0)),
            min_s=float(getattr(config, "straggler_min_s", 0.5)))

    @classmethod
    def for_peer_fetch(cls, config) -> "UnitLatency":
        """The serving fleet's hedged peer-fetch tracker: same decaying
        p95 machinery, but floored at ``fleet_hedge_min_s`` (peer RTTs
        are milliseconds, not span decodes — the straggler floor would
        never hedge) and warmed after fewer samples (a fleet that just
        booted should start hedging within one zipf pass)."""
        return cls(
            multiplier=float(getattr(config, "straggler_multiplier", 4.0)),
            min_s=float(getattr(config, "fleet_hedge_min_s", 0.05)),
            min_samples=8)

    def observe(self, seconds: float) -> None:
        with self._lock:
            self.hist.record(max(float(seconds), 0.0))
            self._seen += 1
            if self._seen % self.decay_every == 0:
                self._decay()

    def _decay(self) -> None:
        # halve every bucket (dropping emptied ones) so the deadline
        # follows the RECENT latency regime; min/max stay as observed
        # extremes (they only clamp percentile reads)
        h = self.hist
        h.buckets = {i: n // 2 for i, n in h.buckets.items() if n // 2}
        h.count = sum(h.buckets.values())
        h.total /= 2.0

    def soft_deadline_s(self) -> Optional[float]:
        """Seconds a unit may run before it counts as a straggler; None
        until enough completions have been observed."""
        with self._lock:
            if self._seen < self.min_samples or not self.hist.count:
                return None
            return max(self.min_s, self.hist.percentile(95)
                       * self.multiplier)
