"""hadoop_bam_tpu — a TPU-native framework for distributed, splittable genomics I/O.

Capability parity target: trozamon/Hadoop-BAM (Java, Hadoop MapReduce adapter
around htsjdk).  Where Hadoop-BAM turns BAM/SAM/CRAM/VCF/BCF/FASTQ/QSEQ/FASTA
files into record-aligned Hadoop ``InputSplit``s feeding map tasks, this
framework turns them into record-aligned *spans* feeding a ``jax.sharding.Mesh``:
compressed BGZF blocks are batch-inflated and records are unpacked into
structure-of-arrays batches on device.

Layer map (mirrors SURVEY.md section 7):

- ``formats/``  — pure-spec codecs (BGZF, BAM, SAM, CRAM, VCF, BCF, FASTQ,
  QSEQ, FASTA); host reference implementations, NumPy-vectorized.
- ``split/``    — split planning: BGZF/BAM/BCF split guessers, splitting-bai /
  .sbi sidecar indexes, per-format planners producing ``FileVirtualSpan``s.
- ``ops/``      — device kernels (Pallas / jnp): batched record unpack to SoA,
  sequence decode, flagstat, CRC32, tokenizers.
- ``parallel/`` — mesh runtime: sharded decode pipeline (``shard_map`` over the
  data axis), multi-host planning, collectives.
- ``api/``      — user surface: ``open_bam()`` et al., format dispatch
  (AnySAM semantics), writers, mergers.
- ``tools/``    — CLI verbs (index, view, cat, summarize, ...).
- ``utils/``    — seekable byte-range readers, header readers, metrics.

Reference provenance: /root/reference was empty at survey time; behavior is
built to the public format specs (SAMv1/BGZF, VCFv4.x, BCF2, CRAM) plus the
upstream component inventory reconstructed in SURVEY.md.  Reference citations
in docstrings use upstream paths, e.g.
``src/main/java/org/seqdoop/hadoop_bam/BAMInputFormat.java`` (abbreviated
``hb/``).
"""

__version__ = "0.1.0"

from hadoop_bam_tpu.config import (  # noqa: F401
    BaseQualityEncoding, HBamConfig, ValidationStringency,
)


def __getattr__(name):
    # Lazy top-level API (keeps `import hadoop_bam_tpu` JAX-free and fast).
    _lazy = {
        "open_bam": ("hadoop_bam_tpu.api.dataset", "open_bam"),
        "open_sam": ("hadoop_bam_tpu.api.dataset", "open_sam"),
        "open_any_sam": ("hadoop_bam_tpu.api.dataset", "open_any_sam"),
        "open_cram": ("hadoop_bam_tpu.api.cram_dataset", "open_cram"),
        "open_vcf": ("hadoop_bam_tpu.api.vcf_dataset", "open_vcf"),
        "open_fastq": ("hadoop_bam_tpu.api.read_datasets", "open_fastq"),
        "open_qseq": ("hadoop_bam_tpu.api.read_datasets", "open_qseq"),
        "open_fasta": ("hadoop_bam_tpu.api.read_datasets", "open_fasta"),
    }
    if name in _lazy:
        import importlib
        mod, attr = _lazy[name]
        return getattr(importlib.import_module(mod), attr)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
