"""DP7xx — decode-path copy discipline: no full-buffer materializations
of inflated spans on the hot path.

The fused decode rework (round 10) exists because every extra sweep over
an inflated span is DRAM traffic the host CPU pays per record batch.  A
``data.tobytes()`` on a whole span silently duplicates megabytes per span
per walk (the founding case lived in ``ops/inflate.py``'s ``walk_records``
fallback), and ``np.frombuffer(...).copy()`` re-copies a buffer that was
already zero-copy.  Both patterns read as innocent one-liners and creep
back easily; this analyzer keeps them out of the modules on the inflated-
span hot path:

- DP701: a ``.tobytes()`` call whose receiver is a whole buffer (a bare
  name or attribute — NOT a sliced/indexed subscript) inside a function
  in a decode-path module.  Slices like ``data[s:e].tobytes()`` are the
  blessed idiom (bounded copies of exactly the bytes needed) and are not
  flagged.
- DP702: ``np.frombuffer(...).copy()`` in the same scope — the copy
  defeats the zero-copy view ``frombuffer`` exists to provide; if a
  mutable buffer is required, allocate once and decompress into it.

Module-level constants and test fixtures are out of scope: the rule only
fires inside function bodies of the listed hot-path modules.
"""
from __future__ import annotations

import ast
from typing import List

from hadoop_bam_tpu.analysis.astutil import last_segment
from hadoop_bam_tpu.analysis.core import Finding, Module, Project, register

# the modules every inflated byte flows through on the BAM-family hot
# path: inflate dispatch + fused decode, the device-DEFLATE experiment,
# the tile unpack layer, and the span pipeline + staging feed
SCOPE = (
    "hadoop_bam_tpu/ops/inflate.py",
    "hadoop_bam_tpu/ops/inflate_device.py",
    "hadoop_bam_tpu/ops/unpack_bam.py",
    "hadoop_bam_tpu/parallel/pipeline.py",
    "hadoop_bam_tpu/parallel/staging.py",
)


def _is_full_buffer_tobytes(node: ast.AST) -> bool:
    """``X.tobytes()`` with X a bare name/attribute (whole buffer) —
    sliced receivers (``X[a:b].tobytes()``) are the blessed idiom."""
    return (isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "tobytes"
            and not node.args and not node.keywords
            and isinstance(node.func.value, (ast.Name, ast.Attribute)))


def _is_frombuffer_copy(node: ast.AST) -> bool:
    """``np.frombuffer(...).copy()`` — any-args frombuffer, immediate
    copy."""
    if not (isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "copy"):
        return False
    inner = node.func.value
    return (isinstance(inner, ast.Call)
            and last_segment(inner.func) == "frombuffer")


def _scan_function(m: Module, fn: ast.AST, findings: List[Finding]) -> None:
    for node in ast.walk(fn):
        if _is_full_buffer_tobytes(node):
            recv = ast.unparse(node.func.value)
            findings.append(Finding(
                rule="DP701", severity="error", path=m.path,
                line=node.lineno,
                message=f"full-buffer '{recv}.tobytes()' materializes a "
                        f"whole inflated span on the decode hot path — "
                        f"walk/pack over the array's own buffer (a "
                        f"memoryview reaches every consumer), or slice "
                        f"exactly the bytes needed"))
        elif _is_frombuffer_copy(node):
            findings.append(Finding(
                rule="DP702", severity="error", path=m.path,
                line=node.lineno,
                message="'np.frombuffer(...).copy()' re-copies a buffer "
                        "frombuffer just mapped zero-copy — decompress "
                        "into a preallocated array instead of copying "
                        "the view"))


def _outermost_functions(tree: ast.Module):
    """Top-level functions and methods — NOT nested defs, whose bodies
    the enclosing scan already covers (scanning both would double-report
    every finding inside a closure)."""
    stack = list(ast.iter_child_nodes(tree))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node
        else:
            stack.extend(ast.iter_child_nodes(node))


@register("decodepath")
def analyze(project: Project) -> List[Finding]:
    findings: List[Finding] = []
    for m in project.select(SCOPE):
        for fn in _outermost_functions(m.tree):
            _scan_function(m, fn, findings)
    return findings
