"""JS1xx — crash-safe job discipline: journaled publication, idempotent
temp naming.

The jobs layer (``hadoop_bam_tpu/jobs/``) makes every long pipeline
resumable, but only as long as two invariants hold in the code that
produces durable artifacts (``write/`` and the mesh sort):

- **JS101 — publication routes through a commit helper.**  A resumable
  unit is "artifact on disk + journal record that verifies it"; a bare
  ``os.replace``/``os.rename`` sprinkled into pipeline code publishes
  an artifact the journal never learns about — a resumed run can
  neither skip it (no record to verify) nor sweep it (it looks final).
  Renames are therefore confined to the blessed publication/commit
  helpers — ``_publish`` (write/api.py's atomic data+sidecar
  publication) and ``open_shard`` (write/sharded.py's part commit,
  which appends the journal unit) — or to a function that itself
  journals the commit (calls ``unit_done``/``job_done`` alongside the
  rename, the co-location that makes a new commit helper legitimate).

- **JS102 — temp names are deterministic (job-scoped), never random.**
  Crash recovery sweeps stale temps and skips committed artifacts BY
  NAME: ``part-00007.tmp`` from a dead run is recognizably the debris
  of shard 7, and ``part-00007`` is verifiably shard 7's commit.  A
  temp name derived from ``getpid()``/``uuid4()``/``time()``/
  ``tempfile.mkstemp`` is different on every attempt — the crashed
  run's files can never be matched to units, so they leak forever and
  resume degenerates to hoping nothing collides.  Any write-mode
  ``open``/rename whose path expression references a non-deterministic
  source (or any ``tempfile`` API use) in scope is flagged.
"""
from __future__ import annotations

import ast
from typing import Iterator, List

from hadoop_bam_tpu.analysis.core import Finding, Project, register

SCOPE = ("hadoop_bam_tpu/write", "hadoop_bam_tpu/parallel/mesh_sort.py",
         "hadoop_bam_tpu/prep")

_RENAME_CALLS = {"replace", "rename", "renames", "link", "symlink"}
_BLESSED_FNS = {"_publish", "open_shard"}
_JOURNAL_COMMIT_CALLS = {"unit_done", "job_done", "commit_unit"}
_WRITE_MODES = ("w", "wb", "xb", "x", "wb+", "w+b", "ab", "a", "ab+")
_NONDETERMINISTIC = {
    "getpid", "gettid", "uuid1", "uuid4", "mktemp", "mkstemp",
    "mkdtemp", "NamedTemporaryFile", "TemporaryFile",
    "TemporaryDirectory", "token_hex", "token_bytes", "randint",
    "random", "randbytes", "urandom", "time", "time_ns", "monotonic",
    "perf_counter",
}


def _func_defs(tree: ast.AST) -> Iterator[ast.AST]:
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


def _is_rename(call: ast.Call) -> bool:
    fn = call.func
    return (isinstance(fn, ast.Attribute) and fn.attr in _RENAME_CALLS
            and isinstance(fn.value, ast.Name) and fn.value.id == "os")


def _journals_commit(fn: ast.AST) -> bool:
    for node in ast.walk(fn):
        if isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and node.func.attr in _JOURNAL_COMMIT_CALLS:
            return True
    return False


def _is_write_open(call: ast.Call) -> bool:
    fn = call.func
    if not (isinstance(fn, ast.Name) and fn.id == "open"):
        return False
    mode = None
    if len(call.args) >= 2 and isinstance(call.args[1], ast.Constant):
        mode = call.args[1].value
    for kw in call.keywords:
        if kw.arg == "mode" and isinstance(kw.value, ast.Constant):
            mode = kw.value.value
    return isinstance(mode, str) and mode in _WRITE_MODES


def _nondeterministic_names(expr: ast.AST, tainted=frozenset()
                            ) -> List[str]:
    out = []
    for sub in ast.walk(expr):
        name = None
        if isinstance(sub, ast.Attribute):
            name = sub.attr
        elif isinstance(sub, ast.Name):
            name = sub.id
            if name in tainted:
                out.append(name)
                continue
        if name in _NONDETERMINISTIC:
            out.append(name)
    return out


def _tainted_locals(fn: ast.AST) -> frozenset:
    """One-hop dataflow: local names assigned from an expression that
    references a nondeterministic source (``path = f"run-{os.getpid()}"``
    taints ``path``) — enough for the assign-then-open shape every real
    violation takes, without building a dataflow engine."""
    tainted = set()
    for node in ast.walk(fn):
        if not isinstance(node, (ast.Assign, ast.AugAssign,
                                 ast.AnnAssign)):
            continue
        value = node.value
        if value is None or not _nondeterministic_names(value,
                                                        frozenset(
                                                            tainted)):
            continue
        targets = node.targets if isinstance(node, ast.Assign) \
            else [node.target]
        for t in targets:
            if isinstance(t, ast.Name):
                tainted.add(t.id)
    return frozenset(tainted)


@register("jobsafety")
def analyze(project: Project) -> List[Finding]:
    findings: List[Finding] = []
    for m in project.select(SCOPE):
        # tempfile anywhere in scope is JS102 on its own: every
        # tempfile name is nondeterministic by construction
        for node in ast.walk(m.tree):
            if isinstance(node, (ast.Import, ast.ImportFrom)):
                names = [a.name for a in node.names]
                mod = getattr(node, "module", None)
                if "tempfile" in names or mod == "tempfile":
                    findings.append(Finding(
                        rule="JS102", severity="error", path=m.path,
                        line=node.lineno,
                        message="tempfile import in crash-safe scope: "
                                "its names are nondeterministic, so a "
                                "resumed run can neither sweep nor "
                                "verify the artifacts — build "
                                "deterministic job-scoped temp names "
                                "(e.g. <final>.tmp, part-NNNNN.tmp) "
                                "instead"))
        for fn in _func_defs(m.tree):
            blessed = fn.name in _BLESSED_FNS or _journals_commit(fn)
            tainted = _tainted_locals(fn)
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                if _is_rename(node) and not blessed:
                    findings.append(Finding(
                        rule="JS101", severity="error", path=m.path,
                        line=node.lineno,
                        message=f"side-effecting publication "
                                f"(os.{node.func.attr}) in "
                                f"{fn.name}() outside the blessed "
                                f"commit helpers "
                                f"({sorted(_BLESSED_FNS)}) and without "
                                f"a journal commit alongside it — the "
                                f"jobs layer can neither verify nor "
                                f"sweep what it publishes; route "
                                f"through write/api._publish / "
                                f"ShardedFileWriter.open_shard, or "
                                f"journal the unit in the same "
                                f"function"))
                path_args: List[ast.AST] = []
                if _is_write_open(node) and node.args:
                    path_args.append(node.args[0])
                if _is_rename(node):
                    path_args.extend(node.args)
                for arg in path_args:
                    bad = _nondeterministic_names(arg, tainted)
                    if bad:
                        findings.append(Finding(
                            rule="JS102", severity="error", path=m.path,
                            line=node.lineno,
                            message=f"non-idempotent temp naming: path "
                                    f"derives from {sorted(set(bad))} "
                                    f"— a re-run cannot recognize (or "
                                    f"sweep) the crashed attempt's "
                                    f"file; use a deterministic "
                                    f"job-scoped name"))
    return findings
