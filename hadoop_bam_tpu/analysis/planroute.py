"""PL1xx — plane-routing discipline: decode-plane gating lives in
``plan/executor.select_plane``, nowhere else.

The plan/execute refactor exists because the routing matrix (3 decode
planes x 5 driver families x {batch, query, serve, sort, write}) had
its gating conditions — ``use_fused_decode``, ``inflate_backend``,
``skip_bad_spans``, intervals — re-implemented per path; adding any
plane or workload meant touching all of them, and the copies drifted.
``select_plane`` is now the single predicate table; this analyzer keeps
it that way:

- PL101: a conditional test (``if``/``elif``, ternary, ``while``, or a
  bare boolean ``and``/``or`` expression such as a returned gate) that
  READS the plane-gating config knobs ``use_fused_decode`` or
  ``inflate_backend`` (attribute or ``getattr(cfg, "...")`` form)
  outside ``hadoop_bam_tpu/plan/``; ``skip_bad_spans`` fires only when
  combined with another gate term in the same expression — a solo
  ``if config.skip_bad_spans:`` is failure POLICY (quarantine vs
  raise, ``decode_with_retry``'s legitimate read), not plane routing.

Out of scope: the ``plan/`` package itself (the gates' one home),
``config.py`` (which defines the knobs and resolves "auto"), and this
``analysis/`` package.  Assignments and keyword arguments are never
findings — ``dataclasses.replace(cfg, use_fused_decode=False)`` and
``backend = resolve_inflate_backend(cfg)`` are how non-plan code is
SUPPOSED to interact with the knobs.
"""
from __future__ import annotations

import ast
from typing import Iterator, List, Set, Tuple

from hadoop_bam_tpu.analysis.astutil import last_segment
from hadoop_bam_tpu.analysis.core import Finding, Module, Project, register

# knobs whose read in a conditional is a finding on its own
SOLO_KNOBS = ("use_fused_decode", "inflate_backend")
# knob that is failure policy alone but a gate when combined
COMBO_KNOB = "skip_bad_spans"
# identifier fragments that mark "another gate term" for the combo rule
GATE_HINTS = ("fused", "backend", "plane", "intervals")

EXCLUDE = (
    "hadoop_bam_tpu/plan/",      # the gates' one home
    "hadoop_bam_tpu/config.py",  # defines the knobs, resolves "auto"
    "hadoop_bam_tpu/analysis/",  # this suite
)


def _knob_reads(expr: ast.AST) -> List[Tuple[str, int]]:
    """(knob, line) for every attribute/getattr read of a gate knob."""
    reads: List[Tuple[str, int]] = []
    for node in ast.walk(expr):
        if isinstance(node, ast.Attribute) \
                and node.attr in SOLO_KNOBS + (COMBO_KNOB,):
            reads.append((node.attr, node.lineno))
        elif isinstance(node, ast.Call) \
                and last_segment(node.func) == "getattr" \
                and len(node.args) >= 2 \
                and isinstance(node.args[1], ast.Constant) \
                and node.args[1].value in SOLO_KNOBS + (COMBO_KNOB,):
            reads.append((str(node.args[1].value), node.lineno))
    return reads


def _has_gate_hint(expr: ast.AST) -> bool:
    """Does the expression reference another gate term (an identifier
    mentioning fused/backend/plane/intervals) besides the knob reads
    themselves?"""
    for node in ast.walk(expr):
        ident = None
        if isinstance(node, ast.Name):
            ident = node.id
        elif isinstance(node, ast.Attribute):
            ident = node.attr
        if ident and ident not in (COMBO_KNOB,) \
                and any(h in ident.lower() for h in GATE_HINTS):
            return True
    return False


def _candidate_tests(tree: ast.Module) -> Iterator[ast.AST]:
    """Conditional-test expressions: if/elif/ternary/while tests, plus
    bare BoolOps (returned or assigned gate expressions).  BoolOps
    nested inside an already-yielded test are not re-yielded — the
    per-(knob, line) dedup in ``analyze`` covers stragglers."""
    tests: List[ast.AST] = []
    covered: Set[int] = set()
    for node in ast.walk(tree):
        if isinstance(node, (ast.If, ast.IfExp, ast.While)):
            tests.append(node.test)
            covered.update(id(n) for n in ast.walk(node.test))
    for node in ast.walk(tree):
        if isinstance(node, ast.BoolOp) and id(node) not in covered:
            tests.append(node)
            covered.update(id(n) for n in ast.walk(node))
    return iter(tests)


@register("planroute")
def analyze(project: Project) -> List[Finding]:
    findings: List[Finding] = []
    for m in project.modules:
        if any(m.path == p.rstrip("/") or m.path.startswith(p)
               for p in EXCLUDE):
            continue
        seen: Set[Tuple[str, int]] = set()
        for test in _candidate_tests(m.tree):
            reads = _knob_reads(test)
            if not reads:
                continue
            solo = [r for r in reads if r[0] in SOLO_KNOBS]
            combo_ok = solo or _has_gate_hint(test)
            for knob, line in reads:
                if knob == COMBO_KNOB and not combo_ok:
                    continue          # solo skip_bad_spans: policy, fine
                if (knob, line) in seen:
                    continue
                seen.add((knob, line))
                findings.append(Finding(
                    rule="PL101", severity="error", path=m.path,
                    line=line,
                    message=f"plane-gating conditional reads "
                            f"'{knob}' outside hadoop_bam_tpu/plan/ — "
                            f"the decode-plane decision belongs to "
                            f"plan.executor.select_plane; consume a "
                            f"PlaneDecision instead of re-deriving the "
                            f"gate"))
    return findings
