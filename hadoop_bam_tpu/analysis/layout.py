"""LC4xx — binary-layout contracts: hand-written offsets vs the spec table.

SAGe-style data-prep bugs concentrate in hand-addressed binary layouts:
a `+ 16` that should be `+ 18`, a two-byte read of a four-byte field, a
new struct format nobody registered.  ``analysis/layout_specs.py``
declares every fixed-struct field once; this analyzer cross-checks the
code against it.

Rules:

- LC401 unregistered struct format: a literal ``struct.pack/unpack``
  format string in ``formats/`` or ``split/`` that is not in
  ``KNOWN_FORMATS``.
- LC402 spec table self-inconsistency (field gaps/overlaps, calcsize
  mismatch) — the contract itself must be well-formed.
- LC403 offset contract violation: a hard-coded offset in a contracted
  function that does not land on a declared field (multi-byte reads
  must exactly cover contiguous field runs; single-byte reads must fall
  inside a field).
- LC404 runtime mirror drift: a runtime field table (e.g.
  ``ops/unpack_bam.FIXED_FIELDS``) disagrees with its spec row.
"""
from __future__ import annotations

import ast
import struct
from typing import Dict, List, Optional, Tuple

from hadoop_bam_tpu.analysis.astutil import collect_functions, last_segment
from hadoop_bam_tpu.analysis.core import Finding, Project, register
from hadoop_bam_tpu.analysis.layout_specs import (
    KNOWN_FORMATS, OFFSET_CONTRACTS, RUNTIME_MIRRORS, SPECS, spec_self_check,
)

SCOPE = ("hadoop_bam_tpu/formats", "hadoop_bam_tpu/split")

_STRUCT_CALLS = {"pack", "unpack", "unpack_from", "pack_into", "calcsize",
                 "iter_unpack", "Struct"}


def _struct_format(node: ast.Call) -> Optional[str]:
    """The literal format string of a struct.* call, else None."""
    f = node.func
    is_struct = (isinstance(f, ast.Attribute)
                 and isinstance(f.value, ast.Name)
                 and f.value.id == "struct" and f.attr in _STRUCT_CALLS) \
        or (isinstance(f, ast.Name) and f.id == "Struct")
    if not is_struct or not node.args:
        return None
    a0 = node.args[0]
    if isinstance(a0, ast.Constant) and isinstance(a0.value, str):
        return a0.value
    return None


def _cursor_offset(node: ast.AST, cursor: str) -> Optional[int]:
    """Byte offset relative to ``cursor`` for `cursor`, `cursor + k`
    (any association of constant additions); None when not of that shape."""
    if isinstance(node, ast.Name):
        return 0 if node.id == cursor else None
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Add):
        for a, b in ((node.left, node.right), (node.right, node.left)):
            if isinstance(b, ast.Constant) and isinstance(b.value, int):
                base = _cursor_offset(a, cursor)
                if base is not None:
                    return base + b.value
    return None


def _const_int(node: ast.AST) -> Optional[int]:
    if isinstance(node, ast.Constant) and isinstance(node.value, int) \
            and not isinstance(node.value, bool):
        return node.value
    return None


class _ContractChecker:
    def __init__(self, contract, fn_node: ast.AST, path: str):
        self.contract = contract
        self.fn = fn_node
        self.path = path
        self.findings: List[Finding] = []

    def _check_span(self, spec_name: str, base: int, offset: int,
                    width: Optional[int], node: ast.AST, what: str) -> None:
        spec = SPECS.get(spec_name)
        if spec is None:
            self.findings.append(Finding(
                rule="LC403", severity="error", path=self.path,
                line=node.lineno,
                message=f"offset contract names unknown spec "
                        f"{spec_name!r}"))
            return
        abs_off = base + offset
        if width is None:
            if spec.field_at(abs_off) is None:
                self.findings.append(Finding(
                    rule="LC403", severity="error", path=self.path,
                    line=node.lineno,
                    message=f"{what} reads byte {abs_off} of "
                            f"'{spec_name}' — not inside any declared "
                            f"field {spec.tag}"))
        elif spec.run_at(abs_off, width) is None:
            self.findings.append(Finding(
                rule="LC403", severity="error", path=self.path,
                line=node.lineno,
                message=f"{what} reads bytes [{abs_off}, "
                        f"{abs_off + width}) of '{spec_name}' — does not "
                        f"cover a whole declared field run {spec.tag}"))

    def check(self) -> List[Finding]:
        cursors = self.contract.cursors
        tiles = self.contract.tiles
        for node in ast.walk(self.fn):
            if isinstance(node, ast.Subscript):
                self._check_subscript(node, cursors, tiles)
            elif isinstance(node, ast.Call):
                self._check_unpack_from(node, cursors)
        return self.findings

    def _check_subscript(self, node: ast.Subscript, cursors, tiles) -> None:
        sl = node.slice
        # tile[:, a] / tile[:, a:b]
        if isinstance(node.value, ast.Name) and node.value.id in tiles \
                and isinstance(sl, ast.Tuple) and len(sl.elts) == 2:
            spec_name, base = tiles[node.value.id]
            col = sl.elts[1]
            if isinstance(col, ast.Slice) and col.lower is not None \
                    and col.upper is not None and col.step is None:
                a, b = _const_int(col.lower), _const_int(col.upper)
                if a is not None and b is not None and b > a:
                    self._check_span(spec_name, base, a, b - a, node,
                                     f"tile slice [{a}:{b}]")
            else:
                a = _const_int(col)
                if a is not None:
                    self._check_span(spec_name, base, a, None, node,
                                     f"tile column {a}")
            return
        # data[cur + a] / data[cur + a : cur + b]
        for cur, (spec_name, base) in cursors.items():
            if isinstance(sl, ast.Slice) and sl.step is None \
                    and sl.lower is not None and sl.upper is not None:
                a = _cursor_offset(sl.lower, cur)
                b = _cursor_offset(sl.upper, cur)
                if a is not None and b is not None and b > a:
                    self._check_span(spec_name, base, a, b - a, node,
                                     f"slice [{cur}+{a}:{cur}+{b}]")
                    return
            else:
                a = _cursor_offset(sl, cur)
                if a is not None and a > 0:
                    # bare `data[cur]` (a == 0) is a record-start peek,
                    # not a field claim
                    self._check_span(spec_name, base, a, None, node,
                                     f"byte read [{cur}+{a}]")
                    return

    def _check_unpack_from(self, node: ast.Call, cursors) -> None:
        fmt = _struct_format(node)
        f = node.func
        if fmt is None or not isinstance(f, ast.Attribute) \
                or f.attr not in ("unpack_from", "pack_into"):
            return
        if len(node.args) < 3:
            return
        try:
            width = struct.calcsize(fmt)
        except struct.error:
            return
        for cur, (spec_name, base) in cursors.items():
            off = _cursor_offset(node.args[2], cur)
            if off is not None:
                self._check_span(spec_name, base, off, width, node,
                                 f"struct.{f.attr}({fmt!r})")
                return


@register("layout")
def analyze(project: Project) -> List[Finding]:
    findings: List[Finding] = []

    # LC402: the contract table must itself be well-formed
    for spec in SPECS.values():
        for problem in spec_self_check(spec):
            findings.append(Finding(
                rule="LC402", severity="error",
                path="hadoop_bam_tpu/analysis/layout_specs.py", line=1,
                message=f"spec '{spec.name}' inconsistent: {problem}"))

    # LC401: literal struct formats must be registered
    for m in project.select(SCOPE):
        for node in ast.walk(m.tree):
            if isinstance(node, ast.Call):
                fmt = _struct_format(node)
                if fmt is not None and fmt not in KNOWN_FORMATS:
                    findings.append(Finding(
                        rule="LC401", severity="error", path=m.path,
                        line=node.lineno,
                        message=f"struct format {fmt!r} is not registered "
                                f"in analysis/layout_specs.KNOWN_FORMATS — "
                                f"declare the layout it addresses"))

    # LC403: contracted functions' hard-coded offsets
    fn_index: Dict[Tuple[str, str], ast.AST] = {}
    for m in project.modules:
        _top, every = collect_functions(m.tree, m.path)
        for fi in every:
            fn_index[(m.path, fi.qualname)] = fi.node
    for contract in OFFSET_CONTRACTS:
        fn = fn_index.get((contract.path, contract.function))
        if fn is None:
            if contract.path in project.by_path:
                findings.append(Finding(
                    rule="LC403", severity="warning", path=contract.path,
                    line=1,
                    message=f"offset contract names missing function "
                            f"'{contract.function}' — update "
                            f"analysis/layout_specs.OFFSET_CONTRACTS"))
            continue
        findings.extend(
            _ContractChecker(contract, fn, contract.path).check())

    # LC404: runtime field tables must mirror their spec
    for path, var, spec_name in RUNTIME_MIRRORS:
        m = project.by_path.get(path)
        spec = SPECS.get(spec_name)
        if m is None or spec is None:
            continue
        table = None
        line = 1
        for node in m.tree.body:
            targets = node.targets if isinstance(node, ast.Assign) else \
                [node.target] if isinstance(node, ast.AnnAssign) else []
            for t in targets:
                if isinstance(t, ast.Name) and t.id == var \
                        and getattr(node, "value", None) is not None:
                    try:
                        table = ast.literal_eval(node.value)
                        line = node.lineno
                    except ValueError:
                        pass
        if not isinstance(table, dict):
            findings.append(Finding(
                rule="LC404", severity="warning", path=path, line=line,
                message=f"runtime mirror '{var}' not found as a literal "
                        f"dict — cannot cross-check against "
                        f"'{spec_name}'"))
            continue
        declared = {f.name: (f.offset, f.width) for f in spec.fields}
        got = {}
        for name, val in table.items():
            if isinstance(val, (tuple, list)) and len(val) >= 2:
                got[name] = (int(val[0]), int(val[1]))
        if got != declared:
            drift = sorted(set(got.items()) ^ set(declared.items()))
            findings.append(Finding(
                rule="LC404", severity="error", path=path, line=line,
                message=f"runtime table '{var}' drifted from spec "
                        f"'{spec_name}': {drift}"))
    return findings
