"""PF5xx — feed-path allocation discipline: no fresh group tiles per emit.

The round-8 feed rebuild moved group-tile staging into
``parallel/staging.py``'s preallocated rings precisely because a fresh
``np.zeros((n_dev, cap, w))`` per dispatched group put an O(n_dev)
memset-plus-copy tax on every emit — the host-side cost that made the
pipeline scale *inversely* with device count (536k rec/s at 8 devices vs
1.09M at 1 in the r5-r7 bench series).  This analyzer keeps the tax from
silently regressing:

- PF501: inside ``parallel/`` (the staging module itself excluded — the
  ring is the one allowed owner of such buffers), an
  ``np.zeros``/``np.empty``/``np.full`` call allocating a >=2-D array
  whose LEADING dimension is the device count (a name like ``n_dev``),
  made inside a loop body or inside an emit/dispatch helper, is a fresh
  per-group device tile.  Route it through ``staging.StagingRing`` /
  ``FeedPipeline`` instead.

Per-device 1-D vectors (``np.zeros((n_dev,), np.int32)`` count
vectors) are deliberately NOT flagged: a 32-byte alloc per group is
noise, and the rule must not cry wolf over it.
"""
from __future__ import annotations

import ast
from typing import List, Optional

from hadoop_bam_tpu.analysis.astutil import last_segment
from hadoop_bam_tpu.analysis.core import Finding, Project, register

SCOPE = ("hadoop_bam_tpu/parallel",)
# the ring owns its buffers; allocations there are the fix, not the bug
EXEMPT = ("hadoop_bam_tpu/parallel/staging.py",)

_ALLOCATORS = {"zeros", "empty", "full"}
_DEVICE_DIM_NAMES = {"n_dev", "n_devices", "num_devices"}
_EMIT_NAMES = ("emit", "dispatch")


def _leading_device_dim(call: ast.Call) -> bool:
    """True when the allocation's shape is a >=2-element tuple whose
    first element is a device-count name."""
    if not call.args:
        return False
    shape = call.args[0]
    if not isinstance(shape, (ast.Tuple, ast.List)) or len(shape.elts) < 2:
        return False
    lead = shape.elts[0]
    if isinstance(lead, ast.Name) and lead.id in _DEVICE_DIM_NAMES:
        return True
    if isinstance(lead, ast.Attribute) and lead.attr in _DEVICE_DIM_NAMES:
        return True
    return False


def _is_group_alloc(node: ast.AST) -> Optional[ast.Call]:
    if isinstance(node, ast.Call) \
            and last_segment(node.func) in _ALLOCATORS \
            and isinstance(node.func, ast.Attribute) \
            and _leading_device_dim(node):
        return node
    return None


@register("feedpath")
def analyze(project: Project) -> List[Finding]:
    findings: List[Finding] = []
    for m in project.select(SCOPE):
        if m.path in EXEMPT:
            continue

        def visit(node: ast.AST, in_loop: bool, in_emit: bool,
                  where: str) -> None:
            for child in ast.iter_child_nodes(node):
                loop = in_loop
                emit = in_emit
                ctx = where
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                    # a fresh function scope: loop context does not
                    # carry in, but emit/dispatch naming does mark it
                    loop = False
                    emit = child.name.startswith(_EMIT_NAMES)
                    ctx = child.name
                elif isinstance(child, (ast.For, ast.AsyncFor, ast.While)):
                    loop = True
                call = _is_group_alloc(child)
                if call is not None and (loop or emit):
                    findings.append(Finding(
                        rule="PF501", severity="error", path=m.path,
                        line=call.lineno,
                        message=f"fresh device-group tile "
                                f"'{last_segment(call.func)}' allocation "
                                f"inside the per-group emit path "
                                f"('{ctx}') — group buffers must come "
                                f"from the staging ring "
                                f"(parallel/staging.py), not a per-"
                                f"dispatch np allocation (the memset tax "
                                f"scales with device count)"))
                visit(child, loop, emit, ctx)

        visit(m.tree, False, False, "<module>")
    return findings
