"""OB6xx — observability discipline: stage timing must flow through Metrics.

The obs layer (``obs/``, ``utils/metrics.py``) only works if the hot
paths actually report through it: a stage timed with a bare
``time.perf_counter()`` pair never reaches the trace ring, the
histograms, or the mesh-wide merge — it is invisible exactly where the
waterfall matters.  And the wrong PRIMITIVE is as bad as none:
``Metrics.timer`` sums thread-seconds, so a timer inside a function the
decode pool runs concurrently reports work-seconds that exceed wall
time and hide overlap (the PR-4 lesson that created ``wall_timer``).

Scope: ``parallel/``, ``query/``, ``ops/`` (the pipeline hot paths).

- OB601: a ``time.perf_counter()`` / ``time.time()`` call inside a
  function that never feeds Metrics (no ``METRICS.*`` /
  ``current_metrics`` / ``observe`` / ``add_wall`` / ``_account``
  reference anywhere in the function) is untracked stage timing.
  Measure with ``Metrics.span``/``timer``/``wall_timer``/``observe``,
  or feed the measured interval into ``Metrics.add_wall``.

- OB602: ``Metrics.timer`` used in a function handed to the shared
  decode pool (via ``_iter_windowed`` / ``pools.submit`` /
  ``pool.submit`` / ``executor.map``) without a ``wall_timer``/``span``
  alongside — pool tasks overlap, so the timer's thread-sum misreports
  the stage; use ``wall_timer``/``span`` (keeping a paired ``timer``
  for work-seconds is fine, alone it is not).

- OB603: an ENTRY-POINT function (a ``cmd_*`` CLI verb, or
  ``submit`` / ``handle_stream`` / ``run_job_level`` / ``resume_job``
  in ``serve//jobs/``) that starts work without minting or joining a
  ``TraceContext`` (``obs/context.py``).  Work started without a trace
  produces spans, journal lines and flight-ring entries that answer
  "what ran" but never "for WHOM" — the causal tree breaks at exactly
  the seam it exists to cross.  Mint with ``trace_context`` /
  ``ensure_trace`` in the function, or (CLI verbs only) centrally in
  the module's ``main`` frontend.  Scope: ``serve/``, ``jobs/``,
  ``tools/cli.py``.
"""
from __future__ import annotations

import ast
from typing import Iterator, List

from hadoop_bam_tpu.analysis.callgraph import (
    direct_calls as _direct_children_calls,
    iter_func_defs as _func_defs,
    pooled_callee_names as _pooled_callee_names,
)
from hadoop_bam_tpu.analysis.core import Finding, Project, register

SCOPE = ("hadoop_bam_tpu/parallel", "hadoop_bam_tpu/query",
         "hadoop_bam_tpu/ops")

# OB603 scope: the entry-point layers where TraceContexts are minted
ENTRY_SCOPE = ("hadoop_bam_tpu/serve", "hadoop_bam_tpu/jobs",
               "hadoop_bam_tpu/tools/cli.py")
# function names that ARE entry points (plus any cmd_* CLI verb)
_ENTRY_NAMES = {"submit", "handle_stream", "run_job_level",
                "resume_job"}
# identifiers that count as minting/joining a TraceContext
_TRACE_MINTERS = {"trace_context", "ensure_trace", "current_trace",
                  "current_trace_id", "new_trace_id", "TraceContext",
                  "begin_span"}

_CLOCK_CALLS = {"perf_counter", "time"}
# identifiers that mark a function as feeding the metrics layer
_METRICS_FEEDERS = {"metrics", "observe", "add_wall", "timer",
                    "wall_timer", "span", "current_metrics", "_account",
                    "hist_summary"}


def _is_clock_call(call: ast.Call) -> bool:
    f = call.func
    if isinstance(f, ast.Attribute) and f.attr in _CLOCK_CALLS:
        base = f.value
        return isinstance(base, ast.Name) and base.id == "time"
    # `from time import perf_counter` style
    return isinstance(f, ast.Name) and f.id == "perf_counter"


def _identifiers(node: ast.AST) -> Iterator[str]:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name):
            yield sub.id
        elif isinstance(sub, ast.Attribute):
            yield sub.attr


def _feeds_metrics(fn: ast.AST) -> bool:
    return any(i.lower() in _METRICS_FEEDERS or "metrics" in i.lower()
               for i in _identifiers(fn))


def _metrics_attr_calls(fn: ast.AST, attr: str) -> List[ast.Call]:
    """Calls of ``<something metrics-ish>.<attr>(...)`` within fn."""
    out = []
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        if not isinstance(f, ast.Attribute) or f.attr != attr:
            continue
        recv = f.value
        name = recv.id if isinstance(recv, ast.Name) else (
            recv.attr if isinstance(recv, ast.Attribute) else "")
        if "metrics" in name.lower():
            out.append(node)
    return out


def _uses_wall_primitive(fn: ast.AST) -> bool:
    for node in ast.walk(fn):
        if isinstance(node, ast.Attribute) and node.attr in ("wall_timer",
                                                             "span"):
            return True
    return False


def _references_trace(fn: ast.AST) -> bool:
    return any(i in _TRACE_MINTERS for i in _identifiers(fn))


def _is_entry_point(fn: ast.AST) -> bool:
    name = getattr(fn, "name", "")
    return name in _ENTRY_NAMES or name.startswith("cmd_")


def _module_main_mints(tree: ast.Module) -> bool:
    """True when the module has a top-level ``main`` that mints a trace
    — the CLI-frontend idiom: one mint in ``main`` covers every
    ``cmd_*`` verb it dispatches to."""
    for node in tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and node.name == "main":
            return _references_trace(node)
    return False


@register("obs")
def analyze(project: Project) -> List[Finding]:
    findings: List[Finding] = []

    # OB603: un-traced entry points in the serve/jobs/CLI layers
    for m in project.select(ENTRY_SCOPE):
        main_mints = _module_main_mints(m.tree)
        for fn in _func_defs(m.tree):
            if not _is_entry_point(fn):
                continue
            if not any(True for _ in _direct_children_calls(fn)):
                continue                  # starts no work
            if _references_trace(fn):
                continue
            if fn.name.startswith("cmd_") and main_mints:
                continue                  # minted centrally in main()
            findings.append(Finding(
                rule="OB603", severity="error", path=m.path,
                line=fn.lineno,
                message=f"entry point {fn.name}() starts work without "
                        "minting or joining a TraceContext — spans, "
                        "journal lines and flight-ring entries it "
                        "produces cannot be attributed to a request; "
                        "wrap the work in obs.context.trace_context/"
                        "ensure_trace (CLI verbs may mint once in the "
                        "module's main())"))

    for m in project.select(SCOPE):
        # OB601: raw clock stage timing that never reaches Metrics
        for fn in _func_defs(m.tree):
            if _feeds_metrics(fn):
                continue
            for call in _direct_children_calls(fn):
                if _is_clock_call(call):
                    findings.append(Finding(
                        rule="OB601", severity="error", path=m.path,
                        line=call.lineno,
                        message=f"raw {ast.unparse(call.func)}() stage "
                                "timing in a hot path that never feeds "
                                "Metrics — the interval is invisible to "
                                "spans, histograms, and the mesh-wide "
                                "merge; use Metrics.span/timer/observe "
                                "or feed the value into "
                                "Metrics.add_wall"))

        # OB602: Metrics.timer inside a pool-dispatched function without
        # a wall-clock primitive alongside
        for fn in _func_defs(m.tree):
            pooled = _pooled_callee_names(fn)
            if not pooled:
                continue
            nested = {n.name: n for n in _func_defs(fn) if n is not fn}
            for name in pooled & set(nested):
                target = nested[name]
                if _uses_wall_primitive(target):
                    continue
                for call in _metrics_attr_calls(target, "timer"):
                    findings.append(Finding(
                        rule="OB602", severity="error", path=m.path,
                        line=call.lineno,
                        message="Metrics.timer in a decode-pool task: "
                                "pool tasks overlap, so the timer's "
                                "thread-sum exceeds wall time and hides "
                                "pipeline overlap — use "
                                "Metrics.wall_timer or Metrics.span "
                                "(alone or alongside the timer)"))
    return findings
