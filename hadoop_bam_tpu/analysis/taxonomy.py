"""ET3xx — error taxonomy: classified errors at the policy boundaries.

PR 1's resilience layer (``utils/errors.py``) keys every retry /
quarantine / fail-fast decision on the error *class*:
``TransientIOError`` retries with backoff, ``CorruptDataError`` fails
fast (re-decoding corrupt bytes never heals), ``PlanError`` always
raises (a misconfigured run must not be skipped as if the data were
bad).  ``classify_error`` has builtin fallbacks, but a bare
``ValueError`` at a decode boundary classifies as CORRUPT even when the
real cause is a bad parameter — and a bare ``OSError`` classifies as
TRANSIENT even when it is deterministic.  At the policy boundaries the
class must be explicit.

Rule:

- ET301 bare builtin raise (``ValueError`` / ``OSError`` / ``IOError``
  / ``RuntimeError`` / ``Exception``) at a bgzf / bamio / inflate /
  planner policy boundary; raise a ``utils.errors`` taxonomy class (or
  a subclass like ``BGZFError``) instead.
"""
from __future__ import annotations

import ast
from typing import List

from hadoop_bam_tpu.analysis.astutil import last_segment
from hadoop_bam_tpu.analysis.core import Finding, Project, register

# the policy boundaries decode_with_retry / RetryingByteSource /
# broadcast_plan classify across (ISSUE 3 tentpole scope), extended in
# ISSUE 11 to the write-path and serve-tier boundary modules — a bare
# builtin raised there reaches clients as the WRONG wire taxonomy kind
# (transport.error_kind) or poisons the parallel writer with a class
# the retry policy misreads — and in ISSUE 12 to the cohort plane's
# boundary modules, where the class decides whether a faulting sample
# input QUARANTINES (data) or fails the build (configuration).
# ISSUE 16 adds the fleet modules, where the class also decides
# whether a peer answer feeds that peer's circuit breaker (PLAN never
# does) and what error_kind a peer sees on the wire
SCOPE = (
    "hadoop_bam_tpu/formats/bgzf.py",
    "hadoop_bam_tpu/formats/bamio.py",
    "hadoop_bam_tpu/ops/inflate.py",
    "hadoop_bam_tpu/ops/inflate_device.py",
    "hadoop_bam_tpu/split/planners.py",
    "hadoop_bam_tpu/split/vcf_planners.py",
    "hadoop_bam_tpu/split/read_planners.py",
    "hadoop_bam_tpu/split/cram_planner.py",
    "hadoop_bam_tpu/write/parallel_bgzf.py",
    "hadoop_bam_tpu/write/sharded.py",
    "hadoop_bam_tpu/write/api.py",
    "hadoop_bam_tpu/write/indexing.py",
    "hadoop_bam_tpu/serve/transport.py",
    "hadoop_bam_tpu/serve/loop.py",
    "hadoop_bam_tpu/serve/tenancy.py",
    "hadoop_bam_tpu/serve/prefetch.py",
    "hadoop_bam_tpu/serve/tiles.py",
    "hadoop_bam_tpu/serve/fleet.py",
    "hadoop_bam_tpu/serve/membership.py",
    "hadoop_bam_tpu/cohort/manifest.py",
    "hadoop_bam_tpu/cohort/join.py",
    "hadoop_bam_tpu/cohort/serving.py",
    # ISSUE 20: the fused preprocessing plane — oracle, device kernels,
    # and pipeline all classify faults for retry/quarantine policy
    "hadoop_bam_tpu/prep/oracle.py",
    "hadoop_bam_tpu/prep/markdup.py",
    "hadoop_bam_tpu/prep/pipeline.py",
)

_BARE = {
    "ValueError": "CorruptDataError (bad bytes) or PlanError (bad "
                  "parameters)",
    "OSError": "TransientIOError (environment) or PlanError "
               "(deterministic, e.g. missing path)",
    "IOError": "TransientIOError or PlanError",
    "RuntimeError": "PlanError (misconfiguration) or CorruptDataError",
    "Exception": "an explicit utils.errors taxonomy class",
}


@register("taxonomy")
def analyze(project: Project) -> List[Finding]:
    findings: List[Finding] = []
    for m in project.select(SCOPE):
        for node in ast.walk(m.tree):
            if not isinstance(node, ast.Raise) or node.exc is None:
                continue
            exc = node.exc
            name = None
            if isinstance(exc, ast.Call):
                name = last_segment(exc.func)
            elif isinstance(exc, (ast.Name, ast.Attribute)):
                name = last_segment(exc)
            if name in _BARE:
                findings.append(Finding(
                    rule="ET301", severity="error", path=m.path,
                    line=node.lineno,
                    message=f"bare '{name}' raised at a policy boundary — "
                            f"decode_with_retry cannot classify it as "
                            f"intended; use {_BARE[name]}"))
    return findings
