"""hbam-lint: repo-native static analysis (``python -m hadoop_bam_tpu lint``).

AST analyzers over correctness regimes generic linters cannot see:

- ``trace_safety`` (TS1xx) — host Python inside JAX-traced code
- ``lockstep``     (CL2xx) — collectives off the uniform control path
- ``taxonomy``     (ET3xx) — unclassified raises at policy boundaries
- ``layout``       (LC4xx) — hand-coded offsets vs the declared
  binary-layout contract table (``analysis/layout_specs.py``)
- ``feedpath``     (PF5xx) — fresh per-group device-tile allocations in
  the feed paths (group buffers belong to ``parallel/staging.py``'s
  rings; the memset tax scales with device count)
- ``decodepath``   (DP7xx) — full-buffer ``.tobytes()`` /
  ``np.frombuffer(...).copy()`` materializations of inflated spans on
  the decode hot path (every extra sweep is a DRAM pass the fused
  decode exists to remove)
- ``devicesync``   (DV9xx) — per-iteration host syncs (``np.asarray``,
  ``jax.device_get``, ``.item()``) in loops inside the device decode
  plane (each one stalls the token-feed pipeline behind the link)
- ``jobsafety``    (JS1xx) — crash-safe job discipline in ``write/`` +
  the mesh sort: publication renames outside the blessed/journaled
  commit helpers, non-idempotent (random/pid/time-derived) temp names
  that resume can neither verify nor sweep
- ``threadsafety`` (TH1xx/LK2xx) — thread-topology races and lock
  discipline on the shared interprocedural engine
  (``analysis/callgraph.py``): unguarded cross-thread writes,
  check-then-act outside a guard, lock-order cycles

Findings carry file:line, rule id and severity; ``analysis/baseline.json``
suppresses accepted legacy findings so CI fails only on regressions.
``analysis/lintcache.py`` short-circuits a full re-run when nothing in
the tree (or the analyzers) changed; ``--format json|sarif`` emits
machine-readable findings for CI annotation.
"""
from hadoop_bam_tpu.analysis.core import (  # noqa: F401
    Baseline, Finding, Project, analyzers, lint_main, run_analyzers,
)
