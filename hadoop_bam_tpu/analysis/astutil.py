"""Shared AST plumbing for the hbam-lint analyzers.

Small, dependency-free helpers: dotted-name rendering, import maps,
function collection with lexical scope chains, and call-site argument
to parameter matching.  Analyzers stay declarative; the tree-walking
mechanics live here.
"""
from __future__ import annotations

import ast
import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple


def dotted_name(node: ast.AST) -> Optional[str]:
    """'jax.lax.psum' for an Attribute/Name chain; None for anything else."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def last_segment(node: ast.AST) -> Optional[str]:
    """'psum' for jax.lax.psum / psum; None when the callee is not a
    name chain (e.g. a subscript or a call result)."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def import_aliases(tree: ast.Module) -> Dict[str, str]:
    """Local name -> imported dotted path, for both import flavors:
    ``import numpy as np`` -> {'np': 'numpy'};
    ``from jax.experimental import multihost_utils`` ->
    {'multihost_utils': 'jax.experimental.multihost_utils'}."""
    out: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                out[a.asname or a.name.split(".")[0]] = \
                    a.name if a.asname else a.name.split(".")[0]
        elif isinstance(node, ast.ImportFrom) and node.module:
            for a in node.names:
                if a.name == "*":
                    continue
                out[a.asname or a.name] = f"{node.module}.{a.name}"
    return out


@dataclasses.dataclass
class FuncInfo:
    """One function definition with its lexical position."""
    node: ast.AST                      # FunctionDef | AsyncFunctionDef
    module_path: str                   # repo-relative path
    qualname: str                      # outer.inner
    parent: Optional["FuncInfo"]
    children: Dict[str, "FuncInfo"] = dataclasses.field(default_factory=dict)

    @property
    def name(self) -> str:
        return self.node.name

    def params(self) -> List[str]:
        """Named parameters that bind values directly.  ``*args`` /
        ``**kwargs`` are excluded on purpose: they bind *containers* of
        arguments (iterating a tuple of tracers is a static unroll, not a
        data-dependent loop), so treating them as traced values would
        flood Pallas kernels' ``*out_refs`` loops with false TS103s."""
        a = self.node.args
        return [p.arg for p in a.posonlyargs + a.args + a.kwonlyargs]


def collect_functions(tree: ast.Module, module_path: str
                      ) -> Tuple[Dict[str, FuncInfo], List[FuncInfo]]:
    """(top-level name -> FuncInfo, all FuncInfos incl. nested)."""
    top: Dict[str, FuncInfo] = {}
    every: List[FuncInfo] = []

    def visit(node: ast.AST, parent: Optional[FuncInfo], prefix: str):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qn = f"{prefix}{child.name}"
                fi = FuncInfo(child, module_path, qn, parent)
                every.append(fi)
                if parent is None:
                    top[child.name] = fi
                else:
                    parent.children[child.name] = fi
                visit(child, fi, qn + ".")
            elif isinstance(child, ast.ClassDef):
                # methods live under the class qualname; lexical chain stays
                # at the enclosing function (class bodies don't close over)
                visit(child, parent, f"{prefix}{child.name}.")
            else:
                visit(child, parent, prefix)

    visit(tree, None, "")
    return top, every


def enclosing_function(every: Sequence[FuncInfo],
                       node: ast.AST) -> Optional[FuncInfo]:
    """The innermost FuncInfo whose body span contains ``node`` (by line
    range; good enough for call-site scoping)."""
    line = getattr(node, "lineno", None)
    if line is None:
        return None
    best: Optional[FuncInfo] = None
    for fi in every:
        n = fi.node
        end = getattr(n, "end_lineno", n.lineno)
        if n.lineno <= line <= end:
            if best is None or n.lineno >= best.node.lineno:
                best = fi
    return best


def resolve_name(name: str, context: Optional[FuncInfo],
                 top: Dict[str, FuncInfo]) -> Optional[FuncInfo]:
    """Lexical lookup of a bare function name from a context function:
    the context's own nested defs, then each enclosing function's, then
    the module top level."""
    scope = context
    while scope is not None:
        if name in scope.children:
            return scope.children[name]
        scope = scope.parent
    return top.get(name)


def match_args_to_params(call: ast.Call, fn: FuncInfo
                         ) -> List[Tuple[ast.AST, str]]:
    """(argument expr, parameter name) pairs for a call of ``fn``;
    *args/**kwargs forwarding is skipped (we only track simple flow)."""
    a = fn.node.args
    pos_params = [p.arg for p in a.posonlyargs + a.args]
    out: List[Tuple[ast.AST, str]] = []
    for i, arg in enumerate(call.args):
        if isinstance(arg, ast.Starred):
            break
        if i < len(pos_params):
            out.append((arg, pos_params[i]))
    kw_ok = set(pos_params) | {p.arg for p in a.kwonlyargs}
    for kw in call.keywords:
        if kw.arg and kw.arg in kw_ok:
            out.append((kw.value, kw.arg))
    return out


def const_str_tuple(node: ast.AST) -> Optional[Tuple[str, ...]]:
    """('a', 'b') for a literal str / tuple/list of str; else None."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        vals = []
        for e in node.elts:
            if not (isinstance(e, ast.Constant) and isinstance(e.value, str)):
                return None
            vals.append(e.value)
        return tuple(vals)
    return None
