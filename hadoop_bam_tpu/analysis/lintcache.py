"""Findings cache for ``hbam lint`` — skip re-parsing an unchanged tree.

The tier-1 gate runs the full analyzer suite on every test run; with 14
analyzers (several interprocedural) over ~150 modules that is pure
recomputation whenever nothing changed.  Because several analyzers are
interprocedural, per-file finding reuse would be UNSOUND — editing one
module can create or kill findings in another — so the cache is
all-or-nothing: a digest over every source file's ``(path, mtime_ns,
size)`` plus the analyzer sources themselves.  Digest match ⇒ replay
the stored findings without parsing anything; any drift ⇒ full re-run.

The cache file lives next to the current working directory by default
(``.hbam-lint-cache.json``, git-ignored — the same convention as
``.pytest_cache``) and is keyed by (root, analyzer selection), keeping
a small LRU of entries so ``--only`` runs don't evict the full-suite
entry.  Failures to read or write the cache are silently ignored:
caching must never change lint results or exit codes, only wall time.
"""
from __future__ import annotations

import hashlib
import json
import os
from typing import Dict, List, Optional, Sequence, Tuple

from hadoop_bam_tpu.analysis.core import Finding

CACHE_VERSION = 1
_MAX_ENTRIES = 8


def default_cache_path() -> str:
    return os.environ.get("HBAM_LINT_CACHE") \
        or os.path.join(os.getcwd(), ".hbam-lint-cache.json")


def _resolve_root(root: Optional[str], package: str) -> Optional[str]:
    """Mirror Project.load's root resolution exactly — the cache digest
    must cover the same tree the analyzers would parse."""
    if root is None:
        try:
            import hadoop_bam_tpu
        except ImportError:                      # pragma: no cover
            return None
        root = os.path.dirname(os.path.abspath(hadoop_bam_tpu.__file__))
    root = os.path.abspath(root)
    if os.path.basename(root) != package \
            and os.path.isdir(os.path.join(root, package)):
        root = os.path.join(root, package)
    return root


def _stat_lines(root: str) -> Optional[List[str]]:
    lines: List[str] = []
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in sorted(dirnames)
                       if d != "__pycache__" and not d.startswith(".")]
        for fn in sorted(filenames):
            if not fn.endswith(".py"):
                continue
            full = os.path.join(dirpath, fn)
            try:
                st = os.stat(full)
            except OSError:
                return None
            rel = os.path.relpath(full, root).replace(os.sep, "/")
            lines.append(f"{rel}\x00{st.st_mtime_ns}\x00{st.st_size}")
    return lines


def compute_digest(root: Optional[str],
                   only: Optional[Sequence[str]] = None,
                   package: str = "hadoop_bam_tpu") -> Optional[str]:
    """Stat-level fingerprint of (analyzed tree, analyzer sources,
    analyzer selection); None when anything cannot be statted."""
    tree_root = _resolve_root(root, package)
    if tree_root is None or not os.path.isdir(tree_root):
        return None
    h = hashlib.sha256()
    h.update(f"v{CACHE_VERSION}\x00{sorted(only or ())!r}\x00".encode())
    tree_lines = _stat_lines(tree_root)
    if tree_lines is None:
        return None
    for line in tree_lines:
        h.update(line.encode())
        h.update(b"\n")
    # analyzer sources: when --root points away from the installed
    # package, the analyzers executing here are NOT part of the walked
    # tree — fingerprint them separately so editing a rule invalidates
    h.update(b"--analyzers--\n")
    analysis_dir = os.path.dirname(os.path.abspath(__file__))
    analysis_lines = _stat_lines(analysis_dir)
    if analysis_lines is None:
        return None
    for line in analysis_lines:
        h.update(line.encode())
        h.update(b"\n")
    return h.hexdigest()


def load(path: str, digest: str
         ) -> Optional[Tuple[List[Finding], int]]:
    """(findings, module count) stored under ``digest``, or None."""
    try:
        with open(path, "r", encoding="utf-8") as f:
            doc = json.load(f)
        if doc.get("version") != CACHE_VERSION:
            return None
        entry = doc.get("entries", {}).get(digest)
        if entry is None:
            return None
        findings = [Finding(rule=str(e["rule"]),
                            severity=str(e["severity"]),
                            path=str(e["path"]), line=int(e["line"]),
                            message=str(e["message"]))
                    for e in entry["findings"]]
        return findings, int(entry["n_modules"])
    except (OSError, ValueError, KeyError, TypeError):
        return None


def store(path: str, digest: str, findings: Sequence[Finding],
          n_modules: int) -> None:
    doc: Dict[str, object] = {"version": CACHE_VERSION, "entries": {}}
    try:
        with open(path, "r", encoding="utf-8") as f:
            got = json.load(f)
        if got.get("version") == CACHE_VERSION \
                and isinstance(got.get("entries"), dict):
            doc = got
    except (OSError, ValueError):
        pass
    entries = doc["entries"]
    assert isinstance(entries, dict)
    entries.pop(digest, None)
    entries[digest] = {
        "n_modules": int(n_modules),
        "findings": [{"rule": f.rule, "severity": f.severity,
                      "path": f.path, "line": f.line,
                      "message": f.message} for f in findings],
    }
    while len(entries) > _MAX_ENTRIES:
        # dict order is insertion order: evict the oldest entry
        entries.pop(next(iter(entries)))
    try:
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(doc, f)
        os.replace(tmp, path)
    except OSError:
        pass
