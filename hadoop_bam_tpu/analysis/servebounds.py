"""SV8xx — serving-tier cache bounds: every cache must evict.

A batch pipeline can afford an unbounded memo (the process exits); a
RESIDENT server cannot — an unbounded dict cache or append-only
registry in ``query/`` or ``serve/`` is a slow memory leak that only
shows up days into a deployment.  This analyzer enforces the bound
*structurally*:

- SV801: a PERSISTENT dict-like container (module-level name or
  ``self.X`` attribute) whose name reads cache/registry-ish and that is
  INSERTED into somewhere in the module but never evicted — no
  ``pop``/``popitem``/``clear``/``del x[...]``, no re-assignment reset,
  not a ``deque(maxlen=...)`` — is an unbounded cache.
- SV802: the same for list/set-like containers that only ever
  ``append``/``add``/``extend`` (the append-only registry).

Locals inside functions are out of scope (they die with the call);
``deque(maxlen=...)`` counts as bounded at construction.  The fix is an
explicit bound: LRU ``popitem``, a cap + ``pop(next(iter(...)))``, a
``maxlen`` deque, or identity-keyed purge — see ``query/cache.py`` and
``serve/tiles.py`` for the house idioms.
"""
from __future__ import annotations

import ast
import re
from typing import Dict, Iterator, List, Optional, Set, Tuple

from hadoop_bam_tpu.analysis.core import Finding, Project, register

SCOPE = ("hadoop_bam_tpu/query", "hadoop_bam_tpu/serve")

# names that read as long-lived lookup structures; everything else is
# presumed working state (bounded by its algorithm, not by eviction)
_CACHEISH = re.compile(
    r"cache|tile|registry|recent|history|seen|memo|lru|meta\b|"
    r"tenant|session|client|prefetch|pending|inflight|in_flight",
    re.IGNORECASE)

_DICT_CTORS = {"dict", "OrderedDict", "defaultdict", "WeakValueDictionary"}
_LIST_CTORS = {"list", "set", "deque"}
_INSERT_METHODS = {"setdefault", "update", "append", "appendleft",
                   "add", "extend", "insert"}
_EVICT_METHODS = {"pop", "popitem", "clear", "popleft", "remove",
                  "discard", "move_to_end"}
# move_to_end alone is not eviction, but it only exists on OrderedDicts
# that are being LRU-managed — and every LRU manager also pops; keeping
# it in the set just avoids double-reporting a managed structure.


def _ctor_kind(value: ast.AST) -> Optional[str]:
    """'dict' / 'list' when ``value`` constructs an (unbounded)
    container; None for anything else (incl. deque(maxlen=...))."""
    if isinstance(value, ast.Dict):
        return "dict"
    if isinstance(value, (ast.List, ast.Set)):
        return "list"
    if isinstance(value, (ast.ListComp, ast.SetComp, ast.DictComp)):
        return None               # comprehensions: computed, not grown
    if isinstance(value, ast.Call):
        fn = value.func
        name = fn.id if isinstance(fn, ast.Name) else (
            fn.attr if isinstance(fn, ast.Attribute) else "")
        if name == "deque":
            for kw in value.keywords:
                if kw.arg == "maxlen":
                    return None   # bounded at construction
            return "list"
        if name in _DICT_CTORS:
            return "dict"
        if name in _LIST_CTORS:
            return "list"
    return None


def _target_name(node: ast.AST) -> Optional[Tuple[str, str]]:
    """('global', name) for module-level Names, ('attr', name) for
    ``self.X`` — the persistent-container identities this rule tracks."""
    if isinstance(node, ast.Name):
        return ("global", node.id)
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and node.value.id == "self":
        return ("attr", node.attr)
    return None


def _candidates(tree: ast.Module) -> Dict[Tuple[str, str],
                                          Tuple[str, int]]:
    """Persistent cache-ish containers: {identity: (kind, lineno)}.
    Module-level assigns plus ``self.X = <container>`` anywhere in a
    class body; re-assignment of a tracked name elsewhere is recorded
    by the ops scan as a reset (eviction), not here."""
    out: Dict[Tuple[str, str], Tuple[str, int]] = {}
    for node in tree.body:
        if isinstance(node, (ast.Assign, ast.AnnAssign)):
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            value = node.value
            if value is None:
                continue
            kind = _ctor_kind(value)
            if kind is None:
                continue
            for t in targets:
                ident = _target_name(t)
                if ident and _CACHEISH.search(ident[1]):
                    out[ident] = (kind, node.lineno)
    for cls in ast.walk(tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        for fn in cls.body:
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            for node in ast.walk(fn):
                if not isinstance(node, (ast.Assign, ast.AnnAssign)):
                    continue
                value = node.value
                if value is None:
                    continue
                kind = _ctor_kind(value)
                if kind is None:
                    continue
                targets = node.targets if isinstance(node, ast.Assign) \
                    else [node.target]
                for t in targets:
                    ident = _target_name(t)
                    if ident and ident[0] == "attr" \
                            and _CACHEISH.search(ident[1]):
                        out.setdefault(ident, (kind, node.lineno))
    return out


def _ops(tree: ast.Module, names: Set[Tuple[str, str]]
         ) -> Tuple[Set[Tuple[str, str]], Set[Tuple[str, str]],
                    Dict[Tuple[str, str], int]]:
    """(inserted, evicted, assign_counts) over the tracked identities."""
    inserted: Set[Tuple[str, str]] = set()
    evicted: Set[Tuple[str, str]] = set()
    assigns: Dict[Tuple[str, str], int] = {}

    def tracked(node: ast.AST) -> Optional[Tuple[str, str]]:
        ident = _target_name(node)
        return ident if ident in names else None

    for node in ast.walk(tree):
        if isinstance(node, (ast.Assign, ast.AnnAssign)):
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            for t in targets:
                if isinstance(t, ast.Subscript):
                    ident = tracked(t.value)
                    if ident:
                        inserted.add(ident)
                else:
                    ident = tracked(t)
                    if ident:
                        assigns[ident] = assigns.get(ident, 0) + 1
        elif isinstance(node, ast.AugAssign):
            if isinstance(node.target, ast.Subscript):
                ident = tracked(node.target.value)
                if ident:
                    inserted.add(ident)
        elif isinstance(node, ast.Delete):
            for t in node.targets:
                if isinstance(t, ast.Subscript):
                    ident = tracked(t.value)
                    if ident:
                        evicted.add(ident)
        elif isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute):
            ident = tracked(node.func.value)
            if ident:
                if node.func.attr in _EVICT_METHODS:
                    evicted.add(ident)
                elif node.func.attr in _INSERT_METHODS:
                    inserted.add(ident)
    return inserted, evicted, assigns


@register("servebounds")
def analyze(project: Project) -> List[Finding]:
    findings: List[Finding] = []
    for m in project.select(SCOPE):
        cands = _candidates(m.tree)
        if not cands:
            continue
        inserted, evicted, assigns = _ops(m.tree, set(cands))
        for ident, (kind, lineno) in sorted(cands.items(),
                                            key=lambda kv: kv[1][1]):
            if ident not in inserted or ident in evicted:
                continue
            # a second assignment is a reset (the whole container is
            # dropped and rebuilt) — bounded by that reset
            if assigns.get(ident, 0) > 1:
                continue
            scope, name = ident
            label = (f"module-level {name}" if scope == "global"
                     else f"self.{name}")
            if kind == "dict":
                findings.append(Finding(
                    rule="SV801", severity="error", path=m.path,
                    line=lineno,
                    message=f"unbounded dict cache {label}: inserted "
                            f"into but never evicted — a resident server "
                            f"leaks it; bound it with an LRU popitem/pop "
                            f"cap, a maxlen deque, or an identity-keyed "
                            f"purge (see query/cache.py, "
                            f"serve/tiles.py)"))
            else:
                findings.append(Finding(
                    rule="SV802", severity="error", path=m.path,
                    line=lineno,
                    message=f"append-only registry {label}: grows "
                            f"without removal — a resident server leaks "
                            f"it; drain it, cap it, or use "
                            f"deque(maxlen=...)"))
    return findings
