"""TS1xx — trace safety: host Python inside JAX-traced code.

Functions reachable from ``jax.jit`` / ``pallas_call`` / ``shard_map``
call sites in ``ops/`` and ``parallel/`` execute under tracing: their
array arguments are tracers, and host-side Python on a tracer either
fails at trace time or — worse — silently forces a device sync /
constant-folds per call.  Ordinary linters cannot see this because the
code is legal Python; the contract is JAX's, not the language's.

The analyzer builds the traced-call graph (roots = functions passed to
jit/shard_map/pallas_call, minus ``static_argnames``), propagates
tracer-ness through simple intra-function dataflow (assignments taint;
``.shape``/``.dtype``/``.ndim``/``.size``/``len()`` are static
extractors and neutralize), and follows calls into project functions,
tainting exactly the parameters that receive tracer arguments.

Rules:

- TS101 host sync on a traced value: ``.item()``, ``.tolist()``,
  ``int()/float()/bool()`` or ``np.asarray``-family / ``jax.device_get``
  on a tracer.
- TS102 data-dependent Python branch: ``if``/``while`` whose test
  involves a traced value (host control flow on device data).
- TS103 Python loop over a traced value: ``for x in tracer`` or
  ``range(tracer)`` — a data-dependent unroll.
- TS104 host NumPy on a traced value: any ``numpy`` call taking a
  tracer argument (silently materializes on host).
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from hadoop_bam_tpu.analysis.astutil import (
    FuncInfo, const_str_tuple, dotted_name, enclosing_function,
    last_segment, match_args_to_params, resolve_name,
)
from hadoop_bam_tpu.analysis.callgraph import (
    InterproceduralWorklist, ModuleIndex as _ModuleIndex,
)
from hadoop_bam_tpu.analysis.core import Finding, Project, register

SCOPE = ("hadoop_bam_tpu/ops", "hadoop_bam_tpu/parallel")

# attribute reads that yield static (trace-time-known) values
_STATIC_ATTRS = {"shape", "dtype", "ndim", "size", "sharding", "at"}
# calls whose result is static regardless of argument taint
_NEUTRAL_CALLS = {"len", "isinstance", "getattr", "hasattr", "type", "print"}
# receiver methods that force a host sync
_SYNC_METHODS = {"item", "tolist", "block_until_ready"}
# builtins that concretize a tracer
_CONCRETIZE = {"int", "float", "bool", "complex"}
# numpy entry points that materialize device data on host
_NUMPY_MODULES = {"numpy"}


def _is_jit_callee(node: ast.AST) -> bool:
    seg = last_segment(node)
    return seg == "jit"


def _is_trace_wrapper(node: ast.AST) -> Optional[str]:
    """'jit' / 'shard_map' / 'pallas_call' when the call target is one."""
    seg = last_segment(node)
    if seg in ("jit", "shard_map", "pallas_call"):
        return seg
    return None


def _static_argnames(call: ast.Call) -> Tuple[str, ...]:
    for kw in call.keywords:
        if kw.arg == "static_argnames":
            got = const_str_tuple(kw.value)
            if got:
                return got
    return ()


def _decorator_roots(fi: FuncInfo) -> Optional[Tuple[str, ...]]:
    """If the function is decorated as a traced root, the tuple of
    static argnames (possibly empty); else None."""
    node = fi.node
    for dec in getattr(node, "decorator_list", ()):
        if _is_jit_callee(dec):
            return ()
        if isinstance(dec, ast.Call):
            # @jax.jit(...) or @functools.partial(jax.jit, ...)
            if _is_jit_callee(dec.func):
                return _static_argnames(dec)
            if last_segment(dec.func) == "partial" and dec.args \
                    and _is_jit_callee(dec.args[0]):
                return _static_argnames(dec)
    return None


def _find_roots(idx: _ModuleIndex) -> List[Tuple[FuncInfo, Set[str]]]:
    """(function, tracer params) roots in one module: decorated jits plus
    first arguments of jit()/shard_map()/pallas_call() call sites."""
    roots: List[Tuple[FuncInfo, Set[str]]] = []

    def tracer_params(fi: FuncInfo, static: Tuple[str, ...]) -> Set[str]:
        return {p for p in fi.params() if p not in static}

    for fi in idx.every:
        static = _decorator_roots(fi)
        if static is not None:
            roots.append((fi, tracer_params(fi, static)))
    for node in ast.walk(idx.module.tree):
        if not isinstance(node, ast.Call):
            continue
        kind = _is_trace_wrapper(node.func)
        if kind is None or not node.args:
            continue
        target = node.args[0]
        if not isinstance(target, ast.Name):
            continue
        ctx = enclosing_function(idx.every, node)
        fi = resolve_name(target.id, ctx, idx.top)
        if fi is None:
            continue
        static = _static_argnames(node) if kind == "jit" else ()
        roots.append((fi, tracer_params(fi, static)))
    return roots


class _FunctionChecker:
    """Taint + rule pass over one function with a given tracer-param set."""

    def __init__(self, idx: _ModuleIndex, fi: FuncInfo, tracers: Set[str]):
        self.idx = idx
        self.fi = fi
        self.tracers = set(tracers)
        self.findings: List[Finding] = []
        self.callee_taints: Dict[Tuple[str, str], Set[str]] = {}

    # -- taint ------------------------------------------------------------
    def tainted(self, node: ast.AST) -> bool:
        if isinstance(node, ast.Name):
            return node.id in self.tracers
        if isinstance(node, ast.Attribute):
            if node.attr in _STATIC_ATTRS:
                return False
            return self.tainted(node.value)
        if isinstance(node, ast.Subscript):
            return self.tainted(node.value) or self.tainted(node.slice)
        if isinstance(node, ast.Slice):
            return any(self.tainted(x) for x in
                       (node.lower, node.upper, node.step) if x is not None)
        if isinstance(node, (ast.BinOp,)):
            return self.tainted(node.left) or self.tainted(node.right)
        if isinstance(node, ast.UnaryOp):
            return self.tainted(node.operand)
        if isinstance(node, ast.BoolOp):
            return any(self.tainted(v) for v in node.values)
        if isinstance(node, ast.Compare):
            return self.tainted(node.left) or \
                any(self.tainted(c) for c in node.comparators)
        if isinstance(node, ast.IfExp):
            return self.tainted(node.body) or self.tainted(node.orelse)
        if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
            return any(self.tainted(e) for e in node.elts)
        if isinstance(node, ast.Dict):
            return any(self.tainted(v) for v in node.values if v is not None)
        if isinstance(node, ast.Starred):
            return self.tainted(node.value)
        if isinstance(node, ast.Call):
            seg = last_segment(node.func)
            if seg in _NEUTRAL_CALLS or seg in _CONCRETIZE:
                return False
            args = list(node.args) + [k.value for k in node.keywords]
            if any(self.tainted(a) for a in args):
                return True
            # method on a traced value returns a traced value (x.sum())
            if isinstance(node.func, ast.Attribute) \
                    and node.func.attr not in _STATIC_ATTRS:
                return self.tainted(node.func.value)
            return False
        if isinstance(node, (ast.ListComp, ast.SetComp, ast.GeneratorExp)):
            return any(self.tainted(g.iter) for g in node.generators) \
                or self.tainted(node.elt)
        return False

    def _assign_target_names(self, target: ast.AST) -> List[str]:
        if isinstance(target, ast.Name):
            return [target.id]
        if isinstance(target, (ast.Tuple, ast.List)):
            out: List[str] = []
            for e in target.elts:
                out.extend(self._assign_target_names(e))
            return out
        if isinstance(target, ast.Starred):
            return self._assign_target_names(target.value)
        return []

    def propagate(self) -> None:
        """Monotone taint fixpoint over the function body (no kill set —
        conservative across loops)."""
        body = self.fi.node.body
        for _ in range(16):
            before = len(self.tracers)
            for node in ast.walk(ast.Module(body=body, type_ignores=[])):
                if isinstance(node, ast.Assign) and self.tainted(node.value):
                    for t in node.targets:
                        self.tracers.update(self._assign_target_names(t))
                elif isinstance(node, ast.AnnAssign) and node.value \
                        and self.tainted(node.value):
                    self.tracers.update(
                        self._assign_target_names(node.target))
                elif isinstance(node, ast.AugAssign) \
                        and (self.tainted(node.value)
                             or self.tainted(node.target)):
                    self.tracers.update(
                        self._assign_target_names(node.target))
                elif isinstance(node, ast.For) and self.tainted(node.iter):
                    self.tracers.update(
                        self._assign_target_names(node.target))
                elif isinstance(node, (ast.NamedExpr,)) \
                        and self.tainted(node.value):
                    self.tracers.update(
                        self._assign_target_names(node.target))
            if len(self.tracers) == before:
                break

    # -- rules ------------------------------------------------------------
    def _emit(self, rule: str, node: ast.AST, message: str) -> None:
        self.findings.append(Finding(
            rule=rule, severity="error", path=self.fi.module_path,
            line=getattr(node, "lineno", 1),
            message=f"{message} (in traced function "
                    f"'{self.fi.qualname}')"))

    def check(self) -> None:
        """Rule pass.  Deliberately walks into NESTED defs too: closures
        of a traced function usually execute at trace time (``pl.when``
        bodies, inline helpers) with the enclosing taint in scope, and
        separately-enqueued callees dedup by (path, line, rule)."""
        self.propagate()
        for node in ast.walk(self.fi.node):
            if isinstance(node, (ast.If, ast.While)) \
                    and self.tainted(node.test):
                self._emit("TS102", node,
                           "data-dependent Python branch on a traced "
                           "value; use jnp.where / lax.cond")
            elif isinstance(node, ast.For) and self.tainted(node.iter):
                self._emit("TS103", node,
                           "Python loop over a traced value; use lax "
                           "control flow or vectorize")
            elif isinstance(node, ast.Call):
                self._check_call(node)

    def _check_call(self, node: ast.Call) -> None:
        seg = last_segment(node.func)
        args = list(node.args) + [k.value for k in node.keywords]
        any_tainted = any(self.tainted(a) for a in args)
        if isinstance(node.func, ast.Attribute):
            if seg in _SYNC_METHODS and self.tainted(node.func.value):
                self._emit("TS101", node,
                           f".{seg}() forces a host sync on a traced value")
                return
            root = node.func.value
            root_name = root.id if isinstance(root, ast.Name) else None
            if root_name in self.idx.np_names and any_tainted:
                self._emit("TS104", node,
                           f"host NumPy call "
                           f"'{dotted_name(node.func) or seg}' on a traced "
                           "value; use jnp")
                return
            if dotted_name(node.func) in ("jax.device_get",) and any_tainted:
                self._emit("TS101", node,
                           "jax.device_get on a traced value inside trace")
                return
        elif isinstance(node.func, ast.Name):
            if seg in _CONCRETIZE and any(self.tainted(a)
                                          for a in node.args):
                self._emit("TS101", node,
                           f"{seg}() concretizes a traced value "
                           "(host sync / trace error)")
                return
            target = self.idx.from_imports.get(seg, "")
            if target.split(".")[0] in _NUMPY_MODULES and any_tainted:
                self._emit("TS104", node,
                           f"host NumPy call '{seg}' on a traced value")
                return
        # record project-call taint flow for the worklist
        if isinstance(node.func, ast.Name):
            ctx = enclosing_function(self.idx.every, node) or self.fi
            callee = resolve_name(node.func.id, ctx, self.idx.top)
            callee_key: Optional[Tuple[str, str]] = None
            fi = None
            if callee is not None:
                fi = callee
                callee_key = (self.idx.module.path, callee.qualname)
            else:
                target = self.idx.from_imports.get(node.func.id)
                if target:
                    callee_key = ("import", target)
            if callee_key is not None:
                params: Set[str] = set()
                if fi is not None:
                    for arg, pname in match_args_to_params(node, fi):
                        if self.tainted(arg):
                            params.add(pname)
                else:
                    # cross-module: positions of tainted args; resolved later
                    for i, arg in enumerate(node.args):
                        if self.tainted(arg):
                            params.add(f"#{i}")
                    for kw in node.keywords:
                        if kw.arg and self.tainted(kw.value):
                            params.add(kw.arg)
                if params:
                    self.callee_taints.setdefault(callee_key, set()) \
                        .update(params)


@register("trace_safety")
def analyze(project: Project) -> List[Finding]:
    indices: Dict[str, _ModuleIndex] = {}
    for m in project.select(SCOPE):
        indices[m.path] = _ModuleIndex(m, numpy_modules=_NUMPY_MODULES)

    # worklist over (module path, qualname) -> tracer-param set; the
    # generic engine owns enqueueing, import-key resolution and the
    # positional-marker (#N) -> parameter-name mapping
    wl = InterproceduralWorklist(project, indices)
    for idx in indices.values():
        for fi, params in _find_roots(idx):
            wl.add_taint((idx.module.path, fi.qualname), params)

    findings: List[Finding] = []
    # dedup WITHOUT the message: a closure statement seen both under its
    # parent's walk and its own enqueued pass reports once
    seen: Set[Tuple[str, int, str]] = set()

    def check(idx: _ModuleIndex, fi: FuncInfo,
              taints: Set[str]) -> Dict[Tuple[str, str], Set[str]]:
        checker = _FunctionChecker(idx, fi, taints)
        checker.check()
        for f in checker.findings:
            k = (f.path, f.line, f.rule)
            if k not in seen:
                seen.add(k)
                findings.append(f)
        return checker.callee_taints

    wl.run(check)
    return findings
