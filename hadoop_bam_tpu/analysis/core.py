"""hbam-lint core: findings, project model, baseline, runner, CLI.

The codebase spans three correctness regimes that generic linters cannot
see — JAX-traced code where host Python silently serializes the mesh,
multi-host collective code where a rank-conditional branch is a deadlock,
and dozens of hand-written binary-layout offsets whose only prior contract
was a comment.  Each regime gets a repo-native AST analyzer
(``analysis/trace_safety.py``, ``analysis/lockstep.py``,
``analysis/taxonomy.py``, ``analysis/layout.py``); this module is the
shared machinery: the ``Finding`` record, the parsed-``Project`` model the
analyzers consume, the checked-in ``baseline.json`` that suppresses
accepted legacy findings so CI fails only on regressions, and the
``python -m hadoop_bam_tpu lint`` frontend.

Baseline matching is deliberately line-insensitive: a finding's
fingerprint hashes (rule, path, message), so unrelated edits that shift
line numbers do not un-suppress legacy findings, while moving or copying
a violation to a new file (or changing what it says) does surface it.
"""
from __future__ import annotations

import ast
import dataclasses
import hashlib
import json
import os
from typing import Callable, Dict, Iterable, List, Optional, Sequence, Tuple

SEVERITIES = ("error", "warning")


@dataclasses.dataclass(frozen=True)
class Finding:
    """One analyzer hit: file:line, rule id, severity, human message."""
    rule: str              # e.g. "TS101"
    severity: str          # "error" | "warning"
    path: str              # repo-relative, forward slashes
    line: int              # 1-based
    message: str

    @property
    def fingerprint(self) -> str:
        """Line-insensitive identity used for baseline suppression."""
        key = f"{self.rule}\x00{self.path}\x00{self.message}"
        return hashlib.sha256(key.encode()).hexdigest()[:16]

    def render(self) -> str:
        return (f"{self.path}:{self.line}: [{self.rule}] "
                f"{self.severity}: {self.message}")

    def to_dict(self) -> Dict[str, object]:
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "severity": self.severity, "message": self.message,
                "fingerprint": self.fingerprint}


@dataclasses.dataclass(frozen=True)
class Module:
    """One parsed source file of the project under analysis."""
    path: str              # repo-relative, forward slashes
    source: str
    tree: ast.Module

    @property
    def package_parts(self) -> Tuple[str, ...]:
        """('hadoop_bam_tpu', 'ops', 'inflate') for the module path."""
        parts = self.path.replace("\\", "/").split("/")
        if parts[-1].endswith(".py"):
            parts[-1] = parts[-1][:-3]
        if parts[-1] == "__init__":
            parts = parts[:-1]
        return tuple(parts)

    @property
    def dotted(self) -> str:
        return ".".join(self.package_parts)


class Project:
    """The set of parsed modules the analyzers run over."""

    def __init__(self, modules: Sequence[Module]):
        self.modules = list(modules)
        self.by_path = {m.path: m for m in self.modules}
        self.by_dotted = {m.dotted: m for m in self.modules}

    @classmethod
    def from_sources(cls, sources: Dict[str, str]) -> "Project":
        """Build a project from {relative_path: source}; the seeded-violation
        fixture corpus in tests goes through here."""
        mods = []
        for path, src in sorted(sources.items()):
            mods.append(Module(path=path.replace("\\", "/"), source=src,
                               tree=ast.parse(src, filename=path)))
        return cls(mods)

    @classmethod
    def load(cls, root: Optional[str] = None,
             package: str = "hadoop_bam_tpu") -> "Project":
        """Parse every .py file of the installed package (or of ``root``).

        Module paths are ALWAYS rooted at ``package`` regardless of the
        on-disk directory name, so the analyzers' path-prefix scopes
        cannot silently miss everything when ``--root`` points at a
        checkout named differently; pointing ``--root`` at a repo that
        *contains* the package descends into it."""
        if root is None:
            import hadoop_bam_tpu
            root = os.path.dirname(os.path.abspath(hadoop_bam_tpu.__file__))
        root = os.path.abspath(root)
        if os.path.basename(root) != package \
                and os.path.isdir(os.path.join(root, package)):
            root = os.path.join(root, package)
        sources: Dict[str, str] = {}
        for dirpath, dirnames, filenames in os.walk(root):
            dirnames[:] = [d for d in sorted(dirnames)
                           if d != "__pycache__" and not d.startswith(".")]
            for fn in sorted(filenames):
                if not fn.endswith(".py"):
                    continue
                full = os.path.join(dirpath, fn)
                rel = os.path.join(package, os.path.relpath(full, root))
                with open(full, "r", encoding="utf-8") as f:
                    sources[rel.replace(os.sep, "/")] = f.read()
        return cls.from_sources(sources)

    def select(self, prefixes: Sequence[str]) -> List[Module]:
        """Modules whose path starts with any of the given prefixes (the
        per-analyzer scoping hook).  Prefixes match path segments, e.g.
        'hadoop_bam_tpu/ops'."""
        out = []
        for m in self.modules:
            for p in prefixes:
                p = p.rstrip("/")
                if m.path == p or m.path.startswith(p + "/") \
                        or m.path == p + ".py":
                    out.append(m)
                    break
        return out


# ---------------------------------------------------------------------------
# analyzer registry
# ---------------------------------------------------------------------------

Analyzer = Callable[[Project], List[Finding]]
_REGISTRY: Dict[str, Analyzer] = {}


def register(name: str) -> Callable[[Analyzer], Analyzer]:
    def deco(fn: Analyzer) -> Analyzer:
        _REGISTRY[name] = fn
        return fn
    return deco


def analyzers() -> Dict[str, Analyzer]:
    """Name -> analyzer map (importing the analyzer modules on demand)."""
    # import for registration side effects
    from hadoop_bam_tpu.analysis import (  # noqa: F401
        decodepath, devicesync, feedpath, jobsafety, layout, lockstep,
        obsrules, planroute, querycache, servebounds, taxonomy,
        threadsafety, trace_safety, writepath,
    )
    return dict(_REGISTRY)


def run_analyzers(project: Project,
                  only: Optional[Sequence[str]] = None) -> List[Finding]:
    findings: List[Finding] = []
    for name, fn in sorted(analyzers().items()):
        if only and name not in only:
            continue
        findings.extend(fn(project))
    findings.sort(key=lambda f: (f.path, f.line, f.rule, f.message))
    return findings


# ---------------------------------------------------------------------------
# baseline
# ---------------------------------------------------------------------------

DEFAULT_BASELINE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "baseline.json")


class Baseline:
    """Checked-in suppression list: accepted legacy findings by fingerprint.

    The stored entries keep rule/path/line/message for human review, but
    only the fingerprint participates in matching, so line drift never
    un-suppresses and never silently suppresses a *new* finding."""

    def __init__(self, entries: Sequence[Dict[str, object]] = ()):
        self.entries = [dict(e) for e in entries]
        self._fps = {str(e["fingerprint"]) for e in self.entries}

    @classmethod
    def from_findings(cls, findings: Iterable[Finding]) -> "Baseline":
        return cls([f.to_dict() for f in findings])

    @classmethod
    def load(cls, path: str = DEFAULT_BASELINE) -> "Baseline":
        if not os.path.exists(path):
            return cls()
        with open(path, "r", encoding="utf-8") as f:
            doc = json.load(f)
        return cls(doc.get("findings", []))

    def save(self, path: str = DEFAULT_BASELINE) -> None:
        doc = {
            "comment": "hbam-lint accepted-legacy findings; matching is by "
                       "fingerprint (line-insensitive). Regenerate with "
                       "`python -m hadoop_bam_tpu lint --update-baseline`.",
            "findings": sorted(
                self.entries,
                key=lambda e: (e.get("path", ""), e.get("rule", ""),
                               e.get("fingerprint", ""))),
        }
        with open(path, "w", encoding="utf-8") as f:
            json.dump(doc, f, indent=1, sort_keys=True)
            f.write("\n")

    def __len__(self) -> int:
        return len(self.entries)

    def suppresses(self, finding: Finding) -> bool:
        return finding.fingerprint in self._fps

    def apply(self, findings: Sequence[Finding]
              ) -> Tuple[List[Finding], List[Finding], List[Dict]]:
        """(unsuppressed, suppressed, stale_baseline_entries).  Stale
        entries — baselined findings the analyzers no longer report —
        signal the baseline can be burned down further."""
        unsup = [f for f in findings if not self.suppresses(f)]
        sup = [f for f in findings if self.suppresses(f)]
        live = {f.fingerprint for f in findings}
        stale = [e for e in self.entries
                 if str(e.get("fingerprint")) not in live]
        return unsup, sup, stale


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------

def lint_main(argv: Optional[Sequence[str]] = None) -> int:
    """``python -m hadoop_bam_tpu lint`` / ``hbam lint`` entry point.

    Exit 0 when every finding is baseline-suppressed; 1 when unsuppressed
    findings remain (the CI contract)."""
    import argparse

    p = argparse.ArgumentParser(
        prog="hadoop_bam_tpu lint",
        description="repo-native static analysis: trace safety (TS1xx), "
                    "collective lockstep (CL2xx), error taxonomy (ET3xx), "
                    "binary-layout contracts (LC4xx), feed-path "
                    "allocation discipline (PF5xx), query-cache key "
                    "identity (QE5xx), observability discipline (OB6xx), "
                    "decode-path copy discipline (DP7xx), serving-tier "
                    "cache bounds (SV8xx), write-path atomicity/"
                    "parallelism (WR10x), plane-routing discipline "
                    "(PL101), thread-topology races and lock ordering "
                    "(TH1xx/LK2xx)")
    p.add_argument("--root", default=None,
                   help="package directory to analyze (default: the "
                        "installed hadoop_bam_tpu package)")
    p.add_argument("--only", action="append", default=None,
                   metavar="ANALYZER",
                   help="run one analyzer (trace_safety, lockstep, "
                        "taxonomy, layout, feedpath, querycache, obs, "
                        "decodepath, servebounds, writepath, "
                        "threadsafety); repeatable")
    p.add_argument("--baseline", default=DEFAULT_BASELINE,
                   help="baseline file (default: analysis/baseline.json)")
    p.add_argument("--no-baseline", action="store_true",
                   help="report every finding, ignoring the baseline")
    p.add_argument("--update-baseline", action="store_true",
                   help="accept all current findings into the baseline "
                        "file and exit 0")
    p.add_argument("--show-suppressed", action="store_true",
                   help="also print baseline-suppressed findings")
    p.add_argument("--format", choices=("text", "json", "sarif"),
                   default="text", dest="fmt",
                   help="output format: human text (default, "
                        "byte-stable), a JSON findings document, or "
                        "SARIF 2.1.0 for CI annotation")
    p.add_argument("--no-cache", action="store_true",
                   help="always re-parse and re-analyze, ignoring the "
                        "findings cache (.hbam-lint-cache.json)")
    args = p.parse_args(argv)

    known = sorted(analyzers())
    for name in args.only or ():
        if name not in known:
            # fail CLOSED: a typo'd --only must not run zero analyzers
            # and report a green lint
            p.error(f"unknown analyzer {name!r}; choose from {known}")

    # findings cache: sound only as a whole-run short-circuit (several
    # analyzers are interprocedural), so a stat-digest of the entire
    # tree + the analyzer sources gates replay; any drift -> full run
    from hadoop_bam_tpu.analysis import lintcache
    findings: Optional[List[Finding]] = None
    n_mod = 0
    digest = None if args.no_cache \
        else lintcache.compute_digest(args.root, only=args.only)
    if digest is not None:
        cached = lintcache.load(lintcache.default_cache_path(), digest)
        if cached is not None:
            findings, n_mod = cached
    if findings is None:
        project = Project.load(root=args.root)
        if not project.modules:
            p.error(f"no Python modules found under --root {args.root!r}")
        n_mod = len(project.modules)
        findings = run_analyzers(project, only=args.only)
        if digest is not None:
            lintcache.store(lintcache.default_cache_path(), digest,
                            findings, n_mod)

    if args.update_baseline:
        Baseline.from_findings(findings).save(args.baseline)
        print(f"wrote {args.baseline} ({len(findings)} finding(s))")
        return 0

    if args.no_baseline:
        unsup, sup, stale = list(findings), [], []
    else:
        unsup, sup, stale = Baseline.load(args.baseline).apply(findings)

    if args.fmt == "json":
        doc = {"tool": "hbam-lint", "version": 1,
               "findings": [f.to_dict() for f in unsup],
               "suppressed": [f.to_dict() for f in sup]
               if args.show_suppressed else [],
               "summary": {"modules": n_mod, "findings": len(findings),
                           "suppressed": len(sup),
                           "unsuppressed": len(unsup)}}
        print(json.dumps(doc, indent=2, sort_keys=True))
        return 1 if unsup else 0
    if args.fmt == "sarif":
        print(json.dumps(_sarif_doc(unsup), indent=2, sort_keys=True))
        return 1 if unsup else 0

    for f in unsup:
        print(f.render())
    if args.show_suppressed:
        for f in sup:
            print(f"{f.render()}  [baseline-suppressed]")
    for e in stale:
        print(f"note: stale baseline entry {e.get('fingerprint')} "
              f"({e.get('rule')} {e.get('path')}) — no longer reported; "
              f"run --update-baseline to burn it down")
    print(f"hbam-lint: {n_mod} modules, {len(findings)} finding(s), "
          f"{len(sup)} suppressed, {len(unsup)} unsuppressed")
    return 1 if unsup else 0


def _sarif_doc(findings: Sequence[Finding]) -> Dict[str, object]:
    """Minimal SARIF 2.1.0 document for CI annotation surfaces."""
    rules = sorted({f.rule for f in findings})
    return {
        "$schema": "https://json.schemastore.org/sarif-2.1.0.json",
        "version": "2.1.0",
        "runs": [{
            "tool": {"driver": {
                "name": "hbam-lint",
                "rules": [{"id": r} for r in rules],
            }},
            "results": [{
                "ruleId": f.rule,
                "level": "error" if f.severity == "error" else "warning",
                "message": {"text": f.message},
                "locations": [{"physicalLocation": {
                    "artifactLocation": {"uri": f.path},
                    "region": {"startLine": f.line},
                }}],
                "partialFingerprints": {"hbamLint/v1": f.fingerprint},
            } for f in findings],
        }],
    }
